/* Batched merlin transcript challenges for sr25519 (schnorrkel) verify.
 *
 * Mirrors tendermint_tpu/crypto/sr25519.py's keccak-f[1600] / STROBE-128 /
 * merlin stack byte-for-byte (differentially tested from Python). The caller
 * precomputes the transcript prefix common to every signature -- Strobe
 * state after Transcript("SigningContext") + append_message("", "") -- and
 * this function runs the per-signature tail:
 *
 *     append_message("sign-bytes", msg)
 *     append_message("proto-name", "Schnorr-sig")
 *     append_message("sign:pk",   pub)      [32 bytes]
 *     append_message("sign:R",    sig[:32]) [32 bytes]
 *     challenge_bytes("sign:c", 64)         -> out[i*64 .. i*64+64)
 *
 * One FFI crossing per batch; ~3-4 keccak permutations per signature.
 * Reference semantics: crypto/sr25519/pubkey.go:10 (go-schnorrkel
 * VerifyBatch path computes the same per-sig challenge).
 */

#include <stdint.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

#define STROBE_R 166
#define FLAG_I 1
#define FLAG_A 2
#define FLAG_C 4
#define FLAG_M 16
#define FLAG_K 32

static const uint64_t KECCAK_RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

static const int KECCAK_ROT[5][5] = {
    {0, 36, 3, 41, 18},
    {1, 44, 10, 45, 2},
    {62, 6, 43, 15, 61},
    {28, 55, 25, 21, 56},
    {27, 20, 39, 8, 14},
};

static uint64_t rotl64(uint64_t x, int n) {
    return n == 0 ? x : (x << n) | (x >> (64 - n));
}

/* Lane layout matches the Python reference: lane (x, y) lives at state
 * bytes [8*(x + 5*y), 8*(x + 5*y) + 8), little-endian. */
static void keccak_f1600(uint8_t *state) {
    uint64_t a[5][5];
    int x, y, r;
    for (x = 0; x < 5; x++)
        for (y = 0; y < 5; y++)
            memcpy(&a[x][y], state + 8 * (x + 5 * y), 8);
    for (r = 0; r < 24; r++) {
        uint64_t c[5], d[5], b[5][5];
        for (x = 0; x < 5; x++)
            c[x] = a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4];
        for (x = 0; x < 5; x++)
            d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
        for (x = 0; x < 5; x++)
            for (y = 0; y < 5; y++)
                a[x][y] ^= d[x];
        for (x = 0; x < 5; x++)
            for (y = 0; y < 5; y++)
                b[y][(2 * x + 3 * y) % 5] = rotl64(a[x][y], KECCAK_ROT[x][y]);
        for (x = 0; x < 5; x++)
            for (y = 0; y < 5; y++)
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y]);
        a[0][0] ^= KECCAK_RC[r];
    }
    for (x = 0; x < 5; x++)
        for (y = 0; y < 5; y++)
            memcpy(state + 8 * (x + 5 * y), &a[x][y], 8);
}

typedef struct {
    uint8_t st[200];
    int pos;
    int pos_begin;
} strobe_t;

static void strobe_run_f(strobe_t *s) {
    s->st[s->pos] ^= (uint8_t)s->pos_begin;
    s->st[s->pos + 1] ^= 0x04;
    s->st[STROBE_R + 1] ^= 0x80;
    keccak_f1600(s->st);
    s->pos = 0;
    s->pos_begin = 0;
}

static void strobe_absorb(strobe_t *s, const uint8_t *d, int64_t n) {
    int64_t i;
    for (i = 0; i < n; i++) {
        s->st[s->pos++] ^= d[i];
        if (s->pos == STROBE_R)
            strobe_run_f(s);
    }
}

static void strobe_begin_op(strobe_t *s, int flags) {
    /* more=false path of the Python _begin_op (no continued ops here). */
    uint8_t hdr[2];
    hdr[0] = (uint8_t)s->pos_begin;
    hdr[1] = (uint8_t)flags;
    s->pos_begin = s->pos + 1;
    strobe_absorb(s, hdr, 2);
    if ((flags & (FLAG_C | FLAG_K)) && s->pos != 0)
        strobe_run_f(s);
}

static void strobe_meta_ad(strobe_t *s, const uint8_t *d, int64_t n) {
    strobe_begin_op(s, FLAG_M | FLAG_A);
    strobe_absorb(s, d, n);
}

static void strobe_ad(strobe_t *s, const uint8_t *d, int64_t n) {
    strobe_begin_op(s, FLAG_A);
    strobe_absorb(s, d, n);
}

static void strobe_prf(strobe_t *s, uint8_t *out, int64_t n) {
    int64_t i;
    strobe_begin_op(s, FLAG_I | FLAG_A | FLAG_C);
    for (i = 0; i < n; i++) {
        out[i] = s->st[s->pos];
        s->st[s->pos] = 0;
        s->pos++;
        if (s->pos == STROBE_R)
            strobe_run_f(s);
    }
}

static void append_message(strobe_t *s, const uint8_t *label, int64_t label_len,
                           const uint8_t *msg, int64_t msg_len) {
    uint8_t meta[64];
    memcpy(meta, label, (size_t)label_len);
    meta[label_len + 0] = (uint8_t)(msg_len & 0xFF);
    meta[label_len + 1] = (uint8_t)((msg_len >> 8) & 0xFF);
    meta[label_len + 2] = (uint8_t)((msg_len >> 16) & 0xFF);
    meta[label_len + 3] = (uint8_t)((msg_len >> 24) & 0xFF);
    strobe_meta_ad(s, meta, label_len + 4);
    strobe_ad(s, msg, msg_len);
}

/* base_state: 200 bytes; base_pos / base_pos_begin: Strobe position state of
 * the shared transcript prefix. msgs: concatenated sign-bytes; offs/lens per
 * item. pubs/rs: N x 32. out: N x 64 challenge bytes (pre-reduction mod L,
 * done vectorized on the Python side). */
void sr25519_challenge_batch(const uint8_t *base_state, int32_t base_pos,
                             int32_t base_pos_begin, const uint8_t *msgs,
                             const int64_t *offs, const int32_t *lens,
                             const uint8_t *pubs, const uint8_t *rs,
                             int64_t n, uint8_t *out) {
    static const uint8_t L_SIGN_BYTES[] = "sign-bytes";
    static const uint8_t L_PROTO[] = "proto-name";
    static const uint8_t V_PROTO[] = "Schnorr-sig";
    static const uint8_t L_PK[] = "sign:pk";
    static const uint8_t L_R[] = "sign:R";
    static const uint8_t L_C[] = "sign:c";
    int64_t i;
    for (i = 0; i < n; i++) {
        strobe_t s;
        memcpy(s.st, base_state, 200);
        s.pos = base_pos;
        s.pos_begin = base_pos_begin;
        append_message(&s, L_SIGN_BYTES, 10, msgs + offs[i], lens[i]);
        append_message(&s, L_PROTO, 10, V_PROTO, 11);
        append_message(&s, L_PK, 7, pubs + 32 * i, 32);
        append_message(&s, L_R, 6, rs + 32 * i, 32);
        {
            uint8_t meta[16];
            memcpy(meta, L_C, 6);
            meta[6] = 64;
            meta[7] = 0;
            meta[8] = 0;
            meta[9] = 0;
            strobe_meta_ad(&s, meta, 10);
            strobe_prf(&s, out + 64 * i, 64);
        }
    }
}

#ifdef __cplusplus
}
#endif
