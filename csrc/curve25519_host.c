/* Host-side curve25519 verification: serial + RLC-batch (Pippenger).
 *
 * WHY THIS EXISTS: the TPU kernel (ops/ed25519_batch) owns large batches,
 * but this host's TPU sits behind a tunnel with a ~90 ms round-trip sync
 * floor, so any flush under a few thousand signatures LOSES to a CPU.
 * This file is the CPU side of the adaptive crossover (crypto/batch.py):
 * a from-scratch C implementation of
 *
 *   - ed25519 verify with semantics byte-identical to the Python reference
 *     (crypto/ed25519.py, itself mirroring Go crypto/ed25519 — reference
 *     crypto/ed25519/ed25519.go:148): S < L, RFC 8032 A decode, accept iff
 *     encode([S]B - [h]A) == sig[:32].
 *   - sr25519 (schnorrkel) verify: ristretto255 decode (RFC 9496),
 *     [s]B - [c]A ~ R under ristretto equality (crypto/sr25519.py:354).
 *   - batch mode: random-linear-combination check
 *         [sum z_i s_i mod L]B + sum [(z_i h_i) mod 8L](-A_i) + [z_i](-R_i)
 *     evaluated with one Pippenger multi-scalar multiplication.
 *     Scalars on A_i are reduced mod 8L (not L): 8L is the group exponent,
 *     so the reduction is exact on torsion components and "each serial
 *     equation holds" => "batch sum is identity" holds UNCONDITIONALLY
 *     (the reverse fails with probability 2^-128 over the z_i).  On batch
 *     mismatch we re-verify serially, so accept/reject decisions delivered
 *     to callers are always identical to the serial path.
 *     For sr25519 the per-item residue lives in the ristretto kernel (a
 *     4-torsion subgroup), so the batch check is [8]S == identity.
 *
 * Field arithmetic: radix-2^51, unsigned __int128 products (the standard
 * public-domain representation).  NOT constant-time — verification inputs
 * are public (pubkeys, messages, signatures); no secrets are processed.
 *
 * Built by tendermint_tpu/ops/chost.py the same way chash.py builds
 * libhashbatch (content-hashed .so name, lazy g++).
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <pthread.h>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint32_t u32;
typedef uint8_t u8;

/* ------------------------------------------------------------------ */
/* SHA-512 (only for deriving batch coefficients z_i from a seed)      */
/* ------------------------------------------------------------------ */

static const u64 SHA512_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};

static inline u64 rotr64(u64 x, int n) { return (x >> n) | (x << (64 - n)); }

static void sha512_compress(u64 st[8], const u8 blk[128]) {
    u64 w[80];
    for (int i = 0; i < 16; i++) {
        w[i] = ((u64)blk[8 * i] << 56) | ((u64)blk[8 * i + 1] << 48) |
               ((u64)blk[8 * i + 2] << 40) | ((u64)blk[8 * i + 3] << 32) |
               ((u64)blk[8 * i + 4] << 24) | ((u64)blk[8 * i + 5] << 16) |
               ((u64)blk[8 * i + 6] << 8) | (u64)blk[8 * i + 7];
    }
    for (int i = 16; i < 80; i++) {
        u64 s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
        u64 s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u64 a = st[0], b = st[1], c = st[2], d = st[3];
    u64 e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 80; i++) {
        u64 S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
        u64 ch = (e & f) ^ (~e & g);
        u64 t1 = h + S1 + ch + SHA512_K[i] + w[i];
        u64 S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
        u64 maj = (a & b) ^ (a & c) ^ (b & c);
        u64 t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

/* sha512 of a short (< 112 byte) message: one padded block */
static void sha512_short(const u8 *msg, size_t len, u8 out[64]) {
    u64 st[8] = {0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
                 0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
                 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
                 0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
    u8 blk[128];
    memset(blk, 0, sizeof(blk));
    memcpy(blk, msg, len);
    blk[len] = 0x80;
    u64 bits = (u64)len * 8;
    for (int i = 0; i < 8; i++) blk[127 - i] = (u8)(bits >> (8 * i));
    sha512_compress(st, blk);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++) out[8 * i + j] = (u8)(st[i] >> (56 - 8 * j));
}

/* ------------------------------------------------------------------ */
/* fe25519: radix-2^51 field element                                   */
/* ------------------------------------------------------------------ */

typedef struct { u64 v[5]; } fe;

#define MASK51 ((1ULL << 51) - 1)

/* 2p in radix 2^51: limb0 = 2^52-38, limbs1-4 = 2^52-2 */
#define TWO_P0 0xFFFFFFFFFFFDAULL
#define TWO_P1234 0xFFFFFFFFFFFFEULL

static void fe_zero(fe *h) { memset(h, 0, sizeof(*h)); }
static void fe_one(fe *h) { fe_zero(h); h->v[0] = 1; }

static void fe_add(fe *h, const fe *f, const fe *g) {
    for (int i = 0; i < 5; i++) h->v[i] = f->v[i] + g->v[i];
}

/* h = f - g + 2p (limbwise non-negative for reduced g) */
static void fe_sub(fe *h, const fe *f, const fe *g) {
    h->v[0] = f->v[0] + TWO_P0 - g->v[0];
    for (int i = 1; i < 5; i++) h->v[i] = f->v[i] + TWO_P1234 - g->v[i];
}

static void fe_neg(fe *h, const fe *f) {
    h->v[0] = TWO_P0 - f->v[0];
    for (int i = 1; i < 5; i++) h->v[i] = TWO_P1234 - f->v[i];
}

/* one carry pass; inputs up to ~2^63 per limb are safe */
static void fe_carry(fe *h) {
    u64 c;
    c = h->v[0] >> 51; h->v[0] &= MASK51; h->v[1] += c;
    c = h->v[1] >> 51; h->v[1] &= MASK51; h->v[2] += c;
    c = h->v[2] >> 51; h->v[2] &= MASK51; h->v[3] += c;
    c = h->v[3] >> 51; h->v[3] &= MASK51; h->v[4] += c;
    c = h->v[4] >> 51; h->v[4] &= MASK51; h->v[0] += c * 19;
    c = h->v[0] >> 51; h->v[0] &= MASK51; h->v[1] += c;
}

static void fe_mul(fe *h, const fe *f, const fe *g) {
    u64 f0 = f->v[0], f1 = f->v[1], f2 = f->v[2], f3 = f->v[3], f4 = f->v[4];
    u64 g0 = g->v[0], g1 = g->v[1], g2 = g->v[2], g3 = g->v[3], g4 = g->v[4];
    u64 g1_19 = 19 * g1, g2_19 = 19 * g2, g3_19 = 19 * g3, g4_19 = 19 * g4;
    u128 h0 = (u128)f0 * g0 + (u128)f1 * g4_19 + (u128)f2 * g3_19 +
              (u128)f3 * g2_19 + (u128)f4 * g1_19;
    u128 h1 = (u128)f0 * g1 + (u128)f1 * g0 + (u128)f2 * g4_19 +
              (u128)f3 * g3_19 + (u128)f4 * g2_19;
    u128 h2 = (u128)f0 * g2 + (u128)f1 * g1 + (u128)f2 * g0 +
              (u128)f3 * g4_19 + (u128)f4 * g3_19;
    u128 h3 = (u128)f0 * g3 + (u128)f1 * g2 + (u128)f2 * g1 +
              (u128)f3 * g0 + (u128)f4 * g4_19;
    u128 h4 = (u128)f0 * g4 + (u128)f1 * g3 + (u128)f2 * g2 +
              (u128)f3 * g1 + (u128)f4 * g0;
    u64 c;
    u64 r0 = (u64)h0 & MASK51; h1 += (u64)(h0 >> 51);
    u64 r1 = (u64)h1 & MASK51; h2 += (u64)(h1 >> 51);
    u64 r2 = (u64)h2 & MASK51; h3 += (u64)(h2 >> 51);
    u64 r3 = (u64)h3 & MASK51; h4 += (u64)(h3 >> 51);
    u64 r4 = (u64)h4 & MASK51; r0 += (u64)(h4 >> 51) * 19;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    h->v[0] = r0; h->v[1] = r1; h->v[2] = r2; h->v[3] = r3; h->v[4] = r4;
}

static void fe_sq(fe *h, const fe *f) {
    u64 f0 = f->v[0], f1 = f->v[1], f2 = f->v[2], f3 = f->v[3], f4 = f->v[4];
    u64 f0_2 = 2 * f0, f1_2 = 2 * f1, f2_2 = 2 * f2, f3_2 = 2 * f3;
    u64 f3_19 = 19 * f3, f4_19 = 19 * f4;
    u128 h0 = (u128)f0 * f0 + (u128)f1_2 * f4_19 + (u128)f2_2 * f3_19;
    u128 h1 = (u128)f0_2 * f1 + (u128)f2_2 * f4_19 + (u128)f3 * f3_19;
    u128 h2 = (u128)f0_2 * f2 + (u128)f1 * f1 + (u128)f3_2 * f4_19;
    u128 h3 = (u128)f0_2 * f3 + (u128)f1_2 * f2 + (u128)f4 * f4_19;
    u128 h4 = (u128)f0_2 * f4 + (u128)f1_2 * f3 + (u128)f2 * f2;
    u64 c;
    u64 r0 = (u64)h0 & MASK51; h1 += (u64)(h0 >> 51);
    u64 r1 = (u64)h1 & MASK51; h2 += (u64)(h1 >> 51);
    u64 r2 = (u64)h2 & MASK51; h3 += (u64)(h2 >> 51);
    u64 r3 = (u64)h3 & MASK51; h4 += (u64)(h3 >> 51);
    u64 r4 = (u64)h4 & MASK51; r0 += (u64)(h4 >> 51) * 19;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    h->v[0] = r0; h->v[1] = r1; h->v[2] = r2; h->v[3] = r3; h->v[4] = r4;
}

static void fe_sqn(fe *h, const fe *f, int n) {
    fe_sq(h, f);
    for (int i = 1; i < n; i++) fe_sq(h, h);
}

/* canonical little-endian bytes (value fully reduced mod p) */
static void fe_tobytes(u8 out[32], const fe *f) {
    fe t = *f;
    fe_carry(&t);
    fe_carry(&t);
    /* now limbs < 2^51; compute t + 19, use its carry-out as "t >= p" */
    u64 q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51; /* q = 1 iff t >= p */
    t.v[0] += 19 * q;
    u64 c;
    c = t.v[0] >> 51; t.v[0] &= MASK51; t.v[1] += c;
    c = t.v[1] >> 51; t.v[1] &= MASK51; t.v[2] += c;
    c = t.v[2] >> 51; t.v[2] &= MASK51; t.v[3] += c;
    c = t.v[3] >> 51; t.v[3] &= MASK51; t.v[4] += c;
    t.v[4] &= MASK51; /* drop the 2^255 bit */
    u64 w0 = t.v[0] | (t.v[1] << 51);
    u64 w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    u64 w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    u64 w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    for (int i = 0; i < 8; i++) {
        out[i] = (u8)(w0 >> (8 * i));
        out[8 + i] = (u8)(w1 >> (8 * i));
        out[16 + i] = (u8)(w2 >> (8 * i));
        out[24 + i] = (u8)(w3 >> (8 * i));
    }
}

/* load 32 LE bytes, top bit ignored (RFC 8032 sign bit handled by caller) */
static void fe_frombytes(fe *h, const u8 in[32]) {
    u64 w0 = 0, w1 = 0, w2 = 0, w3 = 0;
    for (int i = 7; i >= 0; i--) {
        w0 = (w0 << 8) | in[i];
        w1 = (w1 << 8) | in[8 + i];
        w2 = (w2 << 8) | in[16 + i];
        w3 = (w3 << 8) | in[24 + i];
    }
    h->v[0] = w0 & MASK51;
    h->v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
    h->v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
    h->v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
    h->v[4] = (w3 >> 12) & MASK51;
}

static int fe_iszero(const fe *f) {
    u8 b[32];
    fe_tobytes(b, f);
    u8 acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

static int fe_eq(const fe *f, const fe *g) {
    u8 a[32], b[32];
    fe_tobytes(a, f);
    fe_tobytes(b, g);
    return memcmp(a, b, 32) == 0;
}

static int fe_isneg(const fe *f) {
    u8 b[32];
    fe_tobytes(b, f);
    return b[0] & 1;
}

/* z^(2^250 - 1) ladder shared by invert and pow22523 */
static void fe_pow250(fe *out, fe *z11out, const fe *z) {
    fe z2, z9, z11, t;
    fe_sq(&z2, z);              /* 2 */
    fe_sqn(&t, &z2, 2);         /* 8 */
    fe_mul(&z9, &t, z);         /* 9 */
    fe_mul(&z11, &z9, &z2);     /* 11 */
    fe_sq(&t, &z11);            /* 22 */
    fe_mul(&t, &t, &z9);        /* 2^5 - 1 */
    fe z5 = t;
    fe_sqn(&t, &z5, 5);
    fe_mul(&t, &t, &z5);        /* 2^10 - 1 */
    fe z10 = t;
    fe_sqn(&t, &z10, 10);
    fe_mul(&t, &t, &z10);       /* 2^20 - 1 */
    fe z20 = t;
    fe_sqn(&t, &z20, 20);
    fe_mul(&t, &t, &z20);       /* 2^40 - 1 */
    fe_sqn(&t, &t, 10);
    fe_mul(&t, &t, &z10);       /* 2^50 - 1 */
    fe z50 = t;
    fe_sqn(&t, &z50, 50);
    fe_mul(&t, &t, &z50);       /* 2^100 - 1 */
    fe z100 = t;
    fe_sqn(&t, &z100, 100);
    fe_mul(&t, &t, &z100);      /* 2^200 - 1 */
    fe_sqn(&t, &t, 50);
    fe_mul(&t, &t, &z50);       /* 2^250 - 1 */
    *out = t;
    if (z11out) *z11out = z11;
}

static void fe_invert(fe *out, const fe *z) {
    fe t, z11;
    fe_pow250(&t, &z11, z);
    fe_sqn(&t, &t, 5);          /* 2^255 - 32 */
    fe_mul(out, &t, &z11);      /* 2^255 - 21 = p - 2 */
}

/* z^((p-5)/8) = z^(2^252 - 3) */
static void fe_pow22523(fe *out, const fe *z) {
    fe t;
    fe_pow250(&t, NULL, z);
    fe_sqn(&t, &t, 2);          /* 2^252 - 4 */
    fe_mul(out, &t, z);         /* 2^252 - 3 */
}

/* ------------------------------------------------------------------ */
/* group: extended coordinates + niels forms                           */
/* ------------------------------------------------------------------ */

typedef struct { fe X, Y, Z, T; } ge;            /* x=X/Z y=Y/Z xy=T/Z */
typedef struct { fe ypx, ymx, t2d; } nielspt;    /* affine precomp      */
typedef struct { fe ypx, ymx, Z, t2d; } cachedpt;

static fe FE_D, FE_2D, FE_SQRT_M1, FE_INVSQRT_A_MINUS_D;
static ge GE_BASE;

static void ge_identity(ge *h) {
    fe_zero(&h->X); fe_one(&h->Y); fe_one(&h->Z); fe_zero(&h->T);
}

static int ge_is_identity(const ge *p) {
    return fe_iszero(&p->X) && fe_iszero(&p->T) && fe_eq(&p->Y, &p->Z);
}

static void ge_dbl(ge *r, const ge *p) {
    fe a, b, c, h, e, g, f, t;
    fe_sq(&a, &p->X);
    fe_sq(&b, &p->Y);
    fe_sq(&c, &p->Z);
    fe_add(&c, &c, &c); fe_carry(&c);
    fe_add(&h, &a, &b);
    fe_add(&t, &p->X, &p->Y); fe_carry(&t);
    fe_sq(&t, &t);
    fe_sub(&e, &h, &t); fe_carry(&e);
    fe_sub(&g, &a, &b); fe_carry(&g);
    fe_add(&f, &c, &g);
    fe_mul(&r->X, &e, &f);
    fe_mul(&r->Y, &g, &h);
    fe_mul(&r->Z, &f, &g);
    fe_mul(&r->T, &e, &h);
}

/* r = p + q where q is an affine niels point (Z=1); sgn=-1 adds -q */
static void ge_madd(ge *r, const ge *p, const nielspt *q, int sgn) {
    fe a, b, c, d, e, f, g, h;
    fe_sub(&a, &p->Y, &p->X); fe_carry(&a);
    fe_add(&b, &p->Y, &p->X); fe_carry(&b);
    if (sgn > 0) {
        fe_mul(&a, &a, &q->ymx);
        fe_mul(&b, &b, &q->ypx);
        fe_mul(&c, &p->T, &q->t2d);
    } else {
        fe_mul(&a, &a, &q->ypx);
        fe_mul(&b, &b, &q->ymx);
        fe neg;
        fe_neg(&neg, &q->t2d);
        fe_carry(&neg);
        fe_mul(&c, &p->T, &neg);
    }
    fe_add(&d, &p->Z, &p->Z); fe_carry(&d);
    fe_sub(&e, &b, &a); fe_carry(&e);
    fe_sub(&f, &d, &c); fe_carry(&f);
    fe_add(&g, &d, &c); fe_carry(&g);
    fe_add(&h, &b, &a); fe_carry(&h);
    fe_mul(&r->X, &e, &f);
    fe_mul(&r->Y, &g, &h);
    fe_mul(&r->Z, &f, &g);
    fe_mul(&r->T, &e, &h);
}

static void ge_add_cached(ge *r, const ge *p, const cachedpt *q) {
    fe a, b, c, d, e, f, g, h;
    fe_sub(&a, &p->Y, &p->X); fe_carry(&a);
    fe_mul(&a, &a, &q->ymx);
    fe_add(&b, &p->Y, &p->X); fe_carry(&b);
    fe_mul(&b, &b, &q->ypx);
    fe_mul(&c, &p->T, &q->t2d);
    fe_mul(&d, &p->Z, &q->Z);
    fe_add(&d, &d, &d); fe_carry(&d);
    fe_sub(&e, &b, &a); fe_carry(&e);
    fe_sub(&f, &d, &c); fe_carry(&f);
    fe_add(&g, &d, &c); fe_carry(&g);
    fe_add(&h, &b, &a); fe_carry(&h);
    fe_mul(&r->X, &e, &f);
    fe_mul(&r->Y, &g, &h);
    fe_mul(&r->Z, &f, &g);
    fe_mul(&r->T, &e, &h);
}

static void ge_to_cached(cachedpt *c, const ge *p) {
    fe_add(&c->ypx, &p->Y, &p->X); fe_carry(&c->ypx);
    fe_sub(&c->ymx, &p->Y, &p->X); fe_carry(&c->ymx);
    c->Z = p->Z;
    fe_mul(&c->t2d, &p->T, &FE_2D);
}

static void ge_add(ge *r, const ge *p, const ge *q) {
    cachedpt c;
    ge_to_cached(&c, q);
    ge_add_cached(r, p, &c);
}

/* affine (x, y) with xy=t -> niels */
static void niels_from_affine(nielspt *n, const fe *x, const fe *y) {
    fe t;
    fe_add(&n->ypx, y, x); fe_carry(&n->ypx);
    fe_sub(&n->ymx, y, x); fe_carry(&n->ymx);
    fe_mul(&t, x, y);
    fe_mul(&n->t2d, &t, &FE_2D);
}

/* normalize extended -> affine niels (one inversion) */
static void ge_to_niels(nielspt *n, const ge *p) {
    fe zi, x, y;
    fe_invert(&zi, &p->Z);
    fe_mul(&x, &p->X, &zi);
    fe_mul(&y, &p->Y, &zi);
    niels_from_affine(n, &x, &y);
}

static void ge_compress(u8 out[32], const ge *p) {
    fe zi, x, y;
    fe_invert(&zi, &p->Z);
    fe_mul(&x, &p->X, &zi);
    fe_mul(&y, &p->Y, &zi);
    fe_tobytes(out, &y);
    u8 xb[32];
    fe_tobytes(xb, &x);
    out[31] |= (xb[0] & 1) << 7;
}

/* RFC 8032 5.1.3 decode, exactly as crypto/ed25519.py _decompress.
 * Returns 1 and fills (x, y) on success, 0 on failure. */
static int ed_decompress(fe *x, fe *y, const u8 in[32]) {
    int sign = in[31] >> 7;
    /* y >= p check: load then compare canonical re-encoding */
    fe_frombytes(y, in);
    u8 chk[32];
    fe_tobytes(chk, y);
    u8 masked[32];
    memcpy(masked, in, 32);
    masked[31] &= 0x7F;
    if (memcmp(chk, masked, 32) != 0) return 0; /* non-canonical y */
    fe y2, u, v, v3, v7, t, x2;
    fe_sq(&y2, y);
    fe one;
    fe_one(&one);
    fe_sub(&u, &y2, &one); fe_carry(&u);
    fe_mul(&v, &FE_D, &y2);
    fe_add(&v, &v, &one); fe_carry(&v);
    fe_sq(&v3, &v);
    fe_mul(&v3, &v3, &v);          /* v^3 */
    fe_sq(&v7, &v3);
    fe_mul(&v7, &v7, &v);          /* v^7 */
    fe_mul(&t, &u, &v7);
    fe_pow22523(&t, &t);           /* (u v^7)^((p-5)/8) */
    fe_mul(&t, &t, &v3);
    fe_mul(x, &t, &u);             /* u v^3 (u v^7)^((p-5)/8) */
    fe_sq(&x2, x);
    fe_mul(&x2, &x2, &v);          /* v x^2 */
    fe negu;
    fe_neg(&negu, &u); fe_carry(&negu);
    if (fe_eq(&x2, &u)) {
        /* ok */
    } else if (fe_eq(&x2, &negu)) {
        fe_mul(x, x, &FE_SQRT_M1);
    } else {
        return 0;
    }
    if (fe_iszero(x)) {
        if (sign) return 0;
    }
    if (fe_isneg(x) != sign) {
        fe_neg(x, x);
        fe_carry(x);
    }
    return 1;
}

/* ristretto255 decode, exactly as crypto/sr25519.py ristretto_decode.
 * Fills extended point; returns 1 on success. */
static int ristretto_decode_c(ge *p, const u8 in[32]) {
    fe s;
    fe_frombytes(&s, in);
    u8 chk[32];
    fe_tobytes(chk, &s);
    if (memcmp(chk, in, 32) != 0) return 0;  /* >= p or high bit set */
    if (in[0] & 1) return 0;                 /* negative s */
    fe ss, u1, u2, u2s, v, t, one;
    fe_one(&one);
    fe_sq(&ss, &s);
    fe_sub(&u1, &one, &ss); fe_carry(&u1);
    fe_add(&u2, &one, &ss); fe_carry(&u2);
    fe_sq(&u2s, &u2);
    fe_mul(&v, &FE_D, &u1);
    fe_mul(&v, &v, &u1);
    fe_neg(&v, &v); fe_carry(&v);
    fe_sub(&v, &v, &u2s); fe_carry(&v);      /* -(d u1^2) - u2^2 */
    /* invsqrt = sqrt_ratio_m1(1, v * u2s) */
    fe arg;
    fe_mul(&arg, &v, &u2s);
    /* r = arg^((p-5)/8) * ... : sqrt_ratio(1, w): r = w^((p-5)/8) * w^3 *
       ... mirror python: v3=w^3? python computes with u=1: r = v3 * (v7)^(..)
       where v=arg. */
    fe a3, a7, r;
    fe_sq(&a3, &arg); fe_mul(&a3, &a3, &arg);
    fe_sq(&a7, &a3); fe_mul(&a7, &a7, &arg);
    fe_pow22523(&r, &a7);
    fe_mul(&r, &r, &a3);
    fe check;
    fe_sq(&check, &r);
    fe_mul(&check, &check, &arg);            /* arg * r^2 */
    fe negone, negi;
    fe_neg(&negone, &one); fe_carry(&negone);
    fe_mul(&negi, &negone, &FE_SQRT_M1);
    int correct = fe_eq(&check, &one);
    int flipped = fe_eq(&check, &negone);
    int flipped_i = fe_eq(&check, &negi);
    if (flipped || flipped_i) fe_mul(&r, &r, &FE_SQRT_M1);
    int was_square = correct || flipped;
    if (fe_isneg(&r)) { fe_neg(&r, &r); fe_carry(&r); }
    fe den_x, den_y, x, y, tt;
    fe_mul(&den_x, &r, &u2);
    fe_mul(&den_y, &r, &den_x);
    fe_mul(&den_y, &den_y, &v);
    fe s2;
    fe_add(&s2, &s, &s); fe_carry(&s2);
    fe_mul(&x, &s2, &den_x);
    if (fe_isneg(&x)) { fe_neg(&x, &x); fe_carry(&x); }
    fe_mul(&y, &u1, &den_y);
    fe_mul(&tt, &x, &y);
    if (!was_square || fe_isneg(&tt) || fe_iszero(&y)) return 0;
    p->X = x; p->Y = y; fe_one(&p->Z); p->T = tt;
    return 1;
}

/* ristretto equality, as crypto/sr25519.py ristretto_eq (X/Z cross-mul) */
static int ristretto_eq_c(const ge *p, const ge *q) {
    fe a, b;
    fe_mul(&a, &p->X, &q->Y);
    fe_mul(&b, &p->Y, &q->X);
    if (fe_eq(&a, &b)) return 1;
    fe_mul(&a, &p->Y, &q->Y);
    fe_mul(&b, &p->X, &q->X);
    return fe_eq(&a, &b);
}

/* ------------------------------------------------------------------ */
/* scalars: u32-limb helpers + mod-(2^k + e) folding                   */
/* ------------------------------------------------------------------ */

/* L (little-endian bytes) and the folds L = 2^252 + DELTA, 8L = 2^255+8D */
static const u8 L_BYTES[32] = {
    0xED, 0xD3, 0xF5, 0x5C, 0x1A, 0x63, 0x12, 0x58,
    0xD6, 0x9C, 0xF7, 0xA2, 0xDE, 0xF9, 0xDE, 0x14,
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0x10};

static u32 L_LIMBS[8], DELTA_LIMBS[4], L8_LIMBS[8], DELTA8_LIMBS[5];

static void bytes_to_limbs(u32 *out, const u8 *b, int nbytes, int nlimbs) {
    memset(out, 0, 4 * nlimbs);
    for (int i = 0; i < nbytes; i++) out[i / 4] |= (u32)b[i] << (8 * (i % 4));
}

static int big_bits(const u32 *a, int n) {
    for (int i = n - 1; i >= 0; i--) {
        if (a[i]) {
            int b = 32 * i;
            u32 v = a[i];
            while (v) { b++; v >>= 1; }
            return b;
        }
    }
    return 0;
}

static int big_cmp(const u32 *a, const u32 *b, int n) {
    for (int i = n - 1; i >= 0; i--) {
        if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
    }
    return 0;
}

/* r = a - b (a >= b), n limbs */
static void big_sub(u32 *r, const u32 *a, const u32 *b, int n) {
    u64 borrow = 0;
    for (int i = 0; i < n; i++) {
        u64 t = (u64)a[i] - b[i] - borrow;
        r[i] = (u32)t;
        borrow = (t >> 32) & 1;
    }
}

static void big_add(u32 *r, const u32 *a, const u32 *b, int n) {
    u64 carry = 0;
    for (int i = 0; i < n; i++) {
        u64 t = (u64)a[i] + b[i] + carry;
        r[i] = (u32)t;
        carry = t >> 32;
    }
}

/* out(an+bn limbs) = a * b */
static void big_mul(u32 *out, const u32 *a, int an, const u32 *b, int bn) {
    memset(out, 0, 4 * (an + bn));
    for (int i = 0; i < an; i++) {
        u64 carry = 0;
        for (int j = 0; j < bn; j++) {
            u64 t = (u64)a[i] * b[j] + out[i + j] + carry;
            out[i + j] = (u32)t;
            carry = t >> 32;
        }
        out[i + bn] = (u32)carry;
    }
}

#define SC_MAX 24 /* scratch limbs (768 bits) */

/* x (inout, xl limbs) mod m where m = 2^k + e; e has el limbs, m ml limbs.
 * Unsigned folding: x = hi*2^k + lo  ==>  x := lo + (m << s) - e*hi with
 * m<<s chosen >= e*hi, repeated until x < 2^(k+2), then subtract m. */
static void big_mod_fold(u32 *x, int xl, int k, const u32 *e, int el,
                         const u32 *m, int ml) {
    u32 hi[SC_MAX], p[SC_MAX], ms[SC_MAX], acc[SC_MAX];
    for (int guard = 0; guard < 12; guard++) {
        int xb = big_bits(x, xl);
        if (xb <= k + 1) break; /* final conditional subtracts finish it */
        int hb = xb - k;
        int hl = (hb + 31) / 32;
        /* hi = x >> k */
        int ks = k / 32, kb = k % 32;
        memset(hi, 0, sizeof(hi));
        for (int i = 0; i < hl; i++) {
            u32 lo_part = (ks + i < xl) ? x[ks + i] >> kb : 0;
            u32 hi_part = (kb && ks + i + 1 < xl) ? x[ks + i + 1] << (32 - kb) : 0;
            hi[i] = lo_part | hi_part;
        }
        /* lo = x mod 2^k */
        for (int i = ks + 1; i < xl; i++) x[i] = 0;
        if (ks < xl) x[ks] &= (kb ? ((1u << kb) - 1) : 0xFFFFFFFFu);
        if (kb == 0 && ks < xl) x[ks] = 0;
        /* p = e * hi */
        int eb = big_bits(e, el);
        memset(p, 0, sizeof(p));
        big_mul(p, e, el, hi, hl);
        int pl = el + hl;
        int pb = eb + hb; /* upper bound on bits of p */
        /* ms = m << s with s making m<<s >= 2^pb > p */
        int s = pb - (k + 1) + 1;
        if (s < 0) s = 0;
        memset(ms, 0, sizeof(ms));
        int ss = s / 32, sb = s % 32;
        for (int i = ml - 1; i >= 0; i--) {
            ms[i + ss] |= m[i] << sb;
            if (sb && i + ss + 1 < SC_MAX) ms[i + ss + 1] |= m[i] >> (32 - sb);
        }
        int msl = ml + ss + 1;
        if (msl > SC_MAX) msl = SC_MAX;
        /* x = lo + ms - p */
        memset(acc, 0, sizeof(acc));
        memcpy(acc, x, 4 * xl);
        big_add(acc, acc, ms, SC_MAX);
        big_sub(acc, acc, p, SC_MAX);
        (void)pl;
        memcpy(x, acc, 4 * xl);
    }
    /* final: subtract m while x >= m (bounded) */
    u32 mm[SC_MAX];
    memset(mm, 0, sizeof(mm));
    memcpy(mm, m, 4 * ml);
    for (int guard = 0; guard < 8; guard++) {
        if (big_cmp(x, mm, xl > SC_MAX ? SC_MAX : xl) < 0) break;
        big_sub(x, x, mm, xl);
    }
}

/* scalar (LE bytes, sl limbs worth) fits and is < L ? */
static int sc_is_lt_l(const u8 s[32]) {
    for (int i = 31; i >= 0; i--) {
        if (s[i] != L_BYTES[i]) return s[i] < L_BYTES[i];
    }
    return 0; /* equal -> not less */
}

/* ------------------------------------------------------------------ */
/* recodings                                                           */
/* ------------------------------------------------------------------ */

/* signed fixed-window digits, w bits, from a 32-byte scalar (value < 2^256).
 * digits in [-2^(w-1), 2^(w-1)]; ndig = ceil(256/w)+1 covers the carry. */
static void recode_signed(const u8 sc[32], int w, int16_t *dig, int ndig) {
    int carry = 0;
    int half = 1 << (w - 1);
    u32 wmask = (1u << w) - 1;
    for (int j = 0; j < ndig; j++) {
        int bitpos = j * w;
        int byte = bitpos >> 3, off = bitpos & 7;
        u32 raw = 0;
        if (byte < 32) raw |= sc[byte];
        if (byte + 1 < 32) raw |= (u32)sc[byte + 1] << 8;
        if (byte + 2 < 32) raw |= (u32)sc[byte + 2] << 16;
        int d = (int)((raw >> off) & wmask) + carry;
        carry = 0;
        if (d > half) { d -= (1 << w); carry = 1; }
        dig[j] = (int16_t)d;
    }
}

/* wNAF with window w: digits odd in (-2^w, 2^w); returns length */
static int wnaf(int8_t *out, const u8 sc[32], int w) {
    /* copy scalar into u32 limbs we can shift */
    u32 x[9];
    bytes_to_limbs(x, sc, 32, 9);
    int len = 0;
    int bits = big_bits(x, 9);
    int pos = 0;
    memset(out, 0, 257);
    while (pos <= bits) {
        if (!((x[pos / 32] >> (pos % 32)) & 1)) { pos++; continue; }
        /* take w+1 bits at pos */
        int byte = pos / 32, off = pos % 32;
        u64 window = (u64)x[byte] >> off;
        if (byte + 1 < 9) window |= (u64)x[byte + 1] << (32 - off);
        int d = (int)(window & ((1u << (w + 1)) - 1));
        if (d > (1 << w)) d -= (1 << (w + 1));
        out[pos] = (int8_t)d;
        /* subtract d*2^pos from x */
        if (d > 0) {
            u64 borrow = 0;
            u64 sub = (u64)d << off;
            for (int i = byte; i < 9 && (sub || borrow); i++) {
                u64 t = (u64)x[i] - (sub & 0xFFFFFFFFu) - borrow;
                x[i] = (u32)t;
                borrow = (t >> 32) & 1;
                sub >>= 32;
            }
        } else {
            u64 carry = 0;
            u64 add = (u64)(-d) << off;
            for (int i = byte; i < 9 && (add || carry); i++) {
                u64 t = (u64)x[i] + (add & 0xFFFFFFFFu) + carry;
                x[i] = (u32)t;
                carry = t >> 32;
                add >>= 32;
            }
        }
        if (pos + 1 > len) len = pos + 1;
        pos += w;
        bits = big_bits(x, 9);
    }
    return len ? len : 1;
}

/* ------------------------------------------------------------------ */
/* init: constants + fixed-base tables                                 */
/* ------------------------------------------------------------------ */

#define BTAB_W 7
#define BTAB_N (1 << (BTAB_W - 1)) /* 64 odd multiples of B */
static nielspt B_TAB[BTAB_N];
static nielspt B_NIELS; /* B itself, for Pippenger */

static pthread_once_t INIT_ONCE = PTHREAD_ONCE_INIT;

static void fe_from_small(fe *h, u64 v) { fe_zero(h); h->v[0] = v; }

static void init_tables(void) {
    /* d = -121665 * inv(121666) mod p */
    fe n121665, n121666, inv;
    fe_from_small(&n121665, 121665);
    fe_from_small(&n121666, 121666);
    fe_invert(&inv, &n121666);
    fe_mul(&FE_D, &n121665, &inv);
    fe_neg(&FE_D, &FE_D);
    fe_carry(&FE_D);
    fe_add(&FE_2D, &FE_D, &FE_D);
    fe_carry(&FE_2D);
    /* sqrt(-1) = 2^((p-1)/4); exponent 2^253 - 5 LE bytes */
    u8 exp[32];
    memset(exp, 0xFF, 32);
    exp[0] = 0xFB;
    exp[31] = 0x1F;
    fe two, acc;
    fe_from_small(&two, 2);
    fe_one(&acc);
    for (int i = 255; i >= 0; i--) {
        fe_sq(&acc, &acc);
        if ((exp[i / 8] >> (i % 8)) & 1) fe_mul(&acc, &acc, &two);
    }
    FE_SQRT_M1 = acc;
    /* base point: y = 4/5, sign 0 */
    fe four, five, y;
    fe_from_small(&four, 4);
    fe_from_small(&five, 5);
    fe_invert(&inv, &five);
    fe_mul(&y, &four, &inv);
    u8 yb[32];
    fe_tobytes(yb, &y);
    fe bx, by;
    ed_decompress(&bx, &by, yb);
    GE_BASE.X = bx; GE_BASE.Y = by;
    fe_one(&GE_BASE.Z);
    fe_mul(&GE_BASE.T, &bx, &by);
    /* invsqrt(a - d) = sqrt_ratio_m1(1, -1 - d) for ristretto encode
       (not currently exported, kept for parity/selftest use) */
    fe amd, one;
    fe_one(&one);
    fe_neg(&amd, &FE_D);
    fe_carry(&amd);
    fe_sub(&amd, &amd, &one);
    fe_carry(&amd);
    fe a3, a7, r;
    fe_sq(&a3, &amd); fe_mul(&a3, &a3, &amd);
    fe_sq(&a7, &a3); fe_mul(&a7, &a7, &amd);
    fe_pow22523(&r, &a7);
    fe_mul(&r, &r, &a3);
    fe chk;
    fe_sq(&chk, &r);
    fe_mul(&chk, &chk, &amd);
    fe negone;
    fe_neg(&negone, &one); fe_carry(&negone);
    if (fe_eq(&chk, &negone)) fe_mul(&r, &r, &FE_SQRT_M1);
    if (fe_isneg(&r)) { fe_neg(&r, &r); fe_carry(&r); }
    FE_INVSQRT_A_MINUS_D = r;
    /* scalar-field constants */
    bytes_to_limbs(L_LIMBS, L_BYTES, 32, 8);
    bytes_to_limbs(DELTA_LIMBS, L_BYTES, 16, 4);
    /* 8L and 8*DELTA via limb shifts */
    u64 carry = 0;
    for (int i = 0; i < 8; i++) {
        u64 t = ((u64)L_LIMBS[i] << 3) | carry;
        L8_LIMBS[i] = (u32)t;
        carry = t >> 32;
    }
    carry = 0;
    for (int i = 0; i < 4; i++) {
        u64 t = ((u64)DELTA_LIMBS[i] << 3) | carry;
        DELTA8_LIMBS[i] = (u32)t;
        carry = t >> 32;
    }
    DELTA8_LIMBS[4] = (u32)carry;
    /* odd multiples of B as affine niels (init-time inversions are fine) */
    ge cur = GE_BASE, b2;
    ge_dbl(&b2, &GE_BASE);
    for (int i = 0; i < BTAB_N; i++) {
        ge_to_niels(&B_TAB[i], &cur);
        ge next;
        ge_add(&next, &cur, &b2);
        cur = next;
    }
    ge_to_niels(&B_NIELS, &GE_BASE);
}

/* ------------------------------------------------------------------ */
/* pubkey decompress cache (A points repeat every height)              */
/* ------------------------------------------------------------------ */

#define ACACHE_SLOTS 16384 /* power of two; ~3 MB */
typedef struct {
    u8 key[32];
    u8 state; /* 0 empty, 1 valid point, 2 known-bad key */
    nielspt neg_niels; /* niels of -A (verification always uses -A) */
    fe x, y;           /* affine A */
} acache_entry;

static acache_entry *ACACHE;
static pthread_mutex_t ACACHE_MU = PTHREAD_MUTEX_INITIALIZER;

static u64 fnv1a(const u8 *k, int n) {
    u64 h = 1469598103934665603ULL;
    for (int i = 0; i < n; i++) { h ^= k[i]; h *= 1099511628211ULL; }
    return h;
}

static acache_entry *RCACHE; /* same shape, ristretto-decoded sr25519 keys */

/* decompress A (cached); returns 1 ok (fills affine -A niels + affine A),
 * 0 bad key.  kind 0 = ed25519 RFC 8032 decode, 1 = ristretto255 decode
 * (validator keys repeat every height for both types). */
static int acache_get_kind(const u8 pub[32], nielspt *neg_niels, fe *ax,
                           fe *ay, int kind) {
    pthread_mutex_lock(&ACACHE_MU);
    acache_entry **cachep = kind ? &RCACHE : &ACACHE;
    if (!*cachep) *cachep = (acache_entry *)calloc(ACACHE_SLOTS, sizeof(acache_entry));
    acache_entry *CACHE = *cachep;
    u64 slot = fnv1a(pub, 32) & (ACACHE_SLOTS - 1);
    acache_entry *e = &CACHE[slot];
    if (e->state && memcmp(e->key, pub, 32) == 0) {
        int ok = e->state == 1;
        if (ok) {
            if (neg_niels) *neg_niels = e->neg_niels;
            if (ax) *ax = e->x;
            if (ay) *ay = e->y;
        }
        pthread_mutex_unlock(&ACACHE_MU);
        return ok;
    }
    pthread_mutex_unlock(&ACACHE_MU);
    fe x, y;
    int ok;
    if (kind) {
        ge A;
        ok = ristretto_decode_c(&A, pub);
        x = A.X;
        y = A.Y;
    } else {
        ok = ed_decompress(&x, &y, pub);
    }
    acache_entry ne;
    memset(&ne, 0, sizeof(ne));
    memcpy(ne.key, pub, 32);
    if (ok) {
        ne.state = 1;
        ne.x = x;
        ne.y = y;
        fe nx;
        fe_neg(&nx, &x);
        fe_carry(&nx);
        niels_from_affine(&ne.neg_niels, &nx, &y);
        if (neg_niels) *neg_niels = ne.neg_niels;
        if (ax) *ax = x;
        if (ay) *ay = y;
    } else {
        ne.state = 2;
    }
    pthread_mutex_lock(&ACACHE_MU);
    CACHE[slot] = ne; /* lossy overwrite on collision */
    pthread_mutex_unlock(&ACACHE_MU);
    return ok;
}

static int acache_get(const u8 pub[32], nielspt *neg_niels, fe *ax, fe *ay) {
    return acache_get_kind(pub, neg_niels, ax, ay, 0);
}

/* ------------------------------------------------------------------ */
/* serial verify                                                       */
/* ------------------------------------------------------------------ */

/* Straus: acc = [s]B + [h](-A); shared doublings, wNAF(7) on B table,
 * wNAF(5) on a per-call table of 16 odd multiples of -A. */
static void straus_sb_ha(ge *acc, const fe *ax, const fe *ay,
                         const u8 s[32], const u8 h[32]) {
    /* odd multiples of -A as cached points: T[k] = (2k+1)(-A) */
    cachedpt atab[16];
    ge a0, a2;
    fe nx;
    fe_neg(&nx, ax);
    fe_carry(&nx);
    a0.X = nx;
    a0.Y = *ay;
    fe_one(&a0.Z);
    fe_mul(&a0.T, &nx, ay);
    ge_dbl(&a2, &a0);
    ge_to_cached(&atab[0], &a0);
    for (int k = 1; k < 16; k++) {
        /* (2k+1)(-A) = (2k-1)(-A) + 2(-A) */
        ge tmp;
        ge_add_cached(&tmp, &a2, &atab[k - 1]);
        ge_to_cached(&atab[k], &tmp);
    }
    int8_t sd[257], hd[257];
    int sl = wnaf(sd, s, BTAB_W);
    int hl = wnaf(hd, h, 5);
    int top = sl > hl ? sl : hl;
    ge_identity(acc);
    for (int j = top - 1; j >= 0; j--) {
        ge_dbl(acc, acc);
        int ds = sd[j], dh = hd[j];
        if (ds > 0) ge_madd(acc, acc, &B_TAB[ds >> 1], 1);
        else if (ds < 0) ge_madd(acc, acc, &B_TAB[(-ds) >> 1], -1);
        if (dh > 0) ge_add_cached(acc, acc, &atab[dh >> 1]);
        else if (dh < 0) {
            /* negate cached: swap ypx/ymx, negate t2d */
            cachedpt c = atab[(-dh) >> 1];
            cachedpt nc;
            nc.ypx = c.ymx;
            nc.ymx = c.ypx;
            nc.Z = c.Z;
            fe_neg(&nc.t2d, &c.t2d);
            fe_carry(&nc.t2d);
            ge_add_cached(acc, acc, &nc);
        }
    }
}

/* one ed25519 serial verify; h32 = SHA512(R||A||M) mod L (LE) */
static int ed_verify_one(const u8 pub[32], const u8 h32[32], const u8 s32[32],
                         const u8 r32[32]) {
    if (!sc_is_lt_l(s32)) return 0;
    fe ax, ay;
    if (!acache_get(pub, NULL, &ax, &ay)) return 0;
    ge acc;
    straus_sb_ha(&acc, &ax, &ay, s32, h32);
    u8 enc[32];
    ge_compress(enc, &acc);
    return memcmp(enc, r32, 32) == 0;
}

/* one sr25519 serial verify; c32 = challenge mod L; s32 = sig[32:] with the
 * schnorrkel marker bit already stripped by the caller */
static int sr_verify_one(const u8 pub[32], const u8 c32[32], const u8 s32[32],
                         const u8 r32[32]) {
    if (!sc_is_lt_l(s32)) return 0;
    fe ax, ay;
    ge R;
    if (!acache_get_kind(pub, NULL, &ax, &ay, 1)) return 0;
    if (!ristretto_decode_c(&R, r32)) return 0;
    /* Q = [s]B + [c](-A); accept iff Q ~ R (ristretto coset equality) */
    ge acc;
    straus_sb_ha(&acc, &ax, &ay, s32, c32);
    return ristretto_eq_c(&acc, &R);
}

/* ------------------------------------------------------------------ */
/* Pippenger multi-scalar multiplication                               */
/* ------------------------------------------------------------------ */

typedef struct {
    const nielspt *pt; /* affine niels of the (already negated) point */
    u8 sc[32];         /* scalar, LE */
} msm_term;

static int msm_window_for(long n) {
    if (n < 12) return 4;
    if (n < 48) return 5;
    if (n < 160) return 6;
    if (n < 640) return 7;
    if (n < 4000) return 8;
    return 9;
}

/* acc = sum of terms; scratch must hold 2^(w-1) buckets */
static void msm_run(ge *acc, const msm_term *terms, long n) {
    int w = msm_window_for(n);
    int nb = 1 << (w - 1);
    int ndig = (256 + w - 1) / w + 1;
    int16_t *digs = (int16_t *)malloc((size_t)n * ndig * sizeof(int16_t));
    ge *buckets = (ge *)malloc((size_t)nb * sizeof(ge));
    u8 *used = (u8 *)malloc((size_t)nb);
    for (long i = 0; i < n; i++)
        recode_signed(terms[i].sc, w, digs + i * ndig, ndig);
    ge_identity(acc);
    for (int win = ndig - 1; win >= 0; win--) {
        if (win != ndig - 1)
            for (int k = 0; k < w; k++) ge_dbl(acc, acc);
        memset(used, 0, (size_t)nb);
        for (long i = 0; i < n; i++) {
            int d = digs[i * ndig + win];
            if (!d) continue;
            int idx = (d > 0 ? d : -d) - 1;
            if (!used[idx]) {
                ge_identity(&buckets[idx]);
                used[idx] = 1;
            }
            ge_madd(&buckets[idx], &buckets[idx], terms[i].pt, d > 0 ? 1 : -1);
        }
        /* merge: sum_k (k+1)*bucket[k] via running sums */
        ge run, wsum;
        ge_identity(&run);
        ge_identity(&wsum);
        int any = 0;
        for (int k = nb - 1; k >= 0; k--) {
            if (used[k]) {
                ge_add(&run, &run, &buckets[k]);
                any = 1;
            }
            if (any) ge_add(&wsum, &wsum, &run);
        }
        if (any) ge_add(acc, acc, &wsum);
    }
    free(digs);
    free(buckets);
    free(used);
}

/* ------------------------------------------------------------------ */
/* batch entries                                                       */
/* ------------------------------------------------------------------ */

/* derive n 128-bit coefficients from seed; z[i] full 16 bytes, nonzero */
static void derive_z(const u8 seed[32], long n, u8 *z /* 16n */) {
    u8 buf[40], dig[64];
    memcpy(buf, seed, 32);
    for (long blk = 0; blk * 4 < n; blk++) {
        for (int i = 0; i < 8; i++) buf[32 + i] = (u8)((u64)blk >> (8 * i));
        sha512_short(buf, 40, dig);
        for (int j = 0; j < 4 && blk * 4 + j < n; j++) {
            memcpy(z + (blk * 4 + j) * 16, dig + 16 * j, 16);
            /* force nonzero (an all-zero z would drop the item's equation) */
            int nz = 0;
            for (int b = 0; b < 16; b++) nz |= z[(blk * 4 + j) * 16 + b];
            if (!nz) z[(blk * 4 + j) * 16] = 1;
        }
    }
}

/* shared RLC core.  kind 0 = ed25519 (exact identity), 1 = sr25519
 * ([8]S == identity).  ax/ay and rx/ry carry the already-decoded affine
 * A_i and R_i from the caller's precheck pass (decode once, use twice).
 * Returns 1 if the batch equation holds. */
static int rlc_check(long n, const fe *ax, const fe *ay, const fe *rx,
                     const fe *ry, const u8 *h32, const u8 *s32,
                     const u8 seed[32], int kind,
                     const u8 *item_ok /* per-item prechecks */) {
    /* terms: for each valid item: -A_i with (z_i h_i mod 8L), -R_i with z_i;
     * plus B with sum z_i s_i mod L. */
    u8 *z = (u8 *)malloc((size_t)n * 16);
    derive_z(seed, n, z);
    nielspt *neg_r = (nielspt *)malloc((size_t)n * sizeof(nielspt));
    nielspt *neg_a = (nielspt *)malloc((size_t)n * sizeof(nielspt));
    msm_term *terms = (msm_term *)malloc((size_t)(2 * n + 1) * sizeof(msm_term));
    long nt = 0;
    /* sum z_i s_i accumulator (u64 limbs over u32 values) */
    u64 accsum[13];
    memset(accsum, 0, sizeof(accsum));
    int any = 0;
    for (long i = 0; i < n; i++) {
        if (!item_ok[i]) continue;
        any = 1;
        fe neg;
        fe_neg(&neg, &rx[i]);
        fe_carry(&neg);
        niels_from_affine(&neg_r[i], &neg, &ry[i]);
        fe_neg(&neg, &ax[i]);
        fe_carry(&neg);
        niels_from_affine(&neg_a[i], &neg, &ay[i]);
        /* scalars */
        u32 zl[4], hl_[8], prod[12], red[SC_MAX];
        bytes_to_limbs(zl, z + 16 * i, 16, 4);
        bytes_to_limbs(hl_, h32 + 32 * i, 32, 8);
        big_mul(prod, zl, 4, hl_, 8);
        memset(red, 0, sizeof(red));
        memcpy(red, prod, 4 * 12);
        big_mod_fold(red, SC_MAX, 255, DELTA8_LIMBS, 5, L8_LIMBS, 8);
        msm_term *t = &terms[nt++];
        t->pt = &neg_a[i];
        for (int b = 0; b < 32; b++) t->sc[b] = (u8)(red[b / 4] >> (8 * (b % 4)));
        t = &terms[nt++];
        t->pt = &neg_r[i];
        memset(t->sc, 0, 32);
        memcpy(t->sc, z + 16 * i, 16);
        /* accsum += z_i * s_i */
        u32 sl_[8], prod2[12];
        bytes_to_limbs(sl_, s32 + 32 * i, 32, 8);
        big_mul(prod2, zl, 4, sl_, 8);
        for (int b = 0; b < 12; b++) accsum[b] += prod2[b];
    }
    int result = 1;
    if (any) {
        /* normalize accsum -> u32 limbs, reduce mod L */
        u32 sum[SC_MAX];
        memset(sum, 0, sizeof(sum));
        u64 carry = 0;
        for (int b = 0; b < 13; b++) {
            u64 t = accsum[b] + carry;
            sum[b] = (u32)t;
            carry = t >> 32;
        }
        sum[13] = (u32)carry;
        big_mod_fold(sum, SC_MAX, 252, DELTA_LIMBS, 4, L_LIMBS, 8);
        msm_term *t = &terms[nt++];
        t->pt = &B_NIELS;
        for (int b = 0; b < 32; b++) t->sc[b] = (u8)(sum[b / 4] >> (8 * (b % 4)));
        ge S;
        msm_run(&S, terms, nt);
        if (kind == 1) {
            ge_dbl(&S, &S);
            ge_dbl(&S, &S);
            ge_dbl(&S, &S);
        }
        result = ge_is_identity(&S);
    }
    free(z);
    free(neg_r);
    free(neg_a);
    free(terms);
    return result;
}

/* mode: 0 serial, 1 RLC (serial fallback on mismatch), 2 auto */
void ed25519h_verify(long n, const u8 *pubs, const u8 *h32, const u8 *s32,
                     const u8 *r32, const u8 *valid, const u8 *seed32,
                     int mode, u8 *out) {
    pthread_once(&INIT_ONCE, init_tables);
    if (n <= 0) return;
    u8 *item_ok = (u8 *)malloc((size_t)n);
    fe *ax = (fe *)malloc((size_t)n * 4 * sizeof(fe));
    fe *ay = ax + n, *rx = ax + 2 * n, *ry = ax + 3 * n;
    for (long i = 0; i < n; i++) {
        int ok = valid[i] && sc_is_lt_l(s32 + 32 * i);
        if (ok) ok = acache_get(pubs + 32 * i, NULL, &ax[i], &ay[i]);
        /* serial never decodes R (byte compare), but an R outside the
         * canonical-point set can never equal a compress() output, so
         * "R decodes" is exactly "serial could possibly accept". */
        if (ok) ok = ed_decompress(&rx[i], &ry[i], r32 + 32 * i);
        item_ok[i] = (u8)ok;
    }
    int use_batch = (mode == 1) || (mode == 2 && n >= 8);
    if (use_batch &&
        rlc_check(n, ax, ay, rx, ry, h32, s32, seed32, 0, item_ok)) {
        for (long i = 0; i < n; i++) out[i] = item_ok[i];
    } else {
        for (long i = 0; i < n; i++)
            out[i] = item_ok[i] &&
                     ed_verify_one(pubs + 32 * i, h32 + 32 * i, s32 + 32 * i,
                                   r32 + 32 * i);
    }
    free(item_ok);
    free(ax);
}

void sr25519h_verify(long n, const u8 *pubs, const u8 *c32, const u8 *s32,
                     const u8 *r32, const u8 *valid, const u8 *seed32,
                     int mode, u8 *out) {
    pthread_once(&INIT_ONCE, init_tables);
    if (n <= 0) return;
    u8 *item_ok = (u8 *)malloc((size_t)n);
    fe *ax = (fe *)malloc((size_t)n * 4 * sizeof(fe));
    fe *ay = ax + n, *rx = ax + 2 * n, *ry = ax + 3 * n;
    for (long i = 0; i < n; i++) {
        int ok = valid[i] && sc_is_lt_l(s32 + 32 * i);
        if (ok) ok = acache_get_kind(pubs + 32 * i, NULL, &ax[i], &ay[i], 1);
        if (ok) {
            ge R;
            ok = ristretto_decode_c(&R, r32 + 32 * i);
            if (ok) { rx[i] = R.X; ry[i] = R.Y; }
        }
        item_ok[i] = (u8)ok;
    }
    int use_batch = (mode == 1) || (mode == 2 && n >= 8);
    if (use_batch &&
        rlc_check(n, ax, ay, rx, ry, c32, s32, seed32, 1, item_ok)) {
        for (long i = 0; i < n; i++) out[i] = item_ok[i];
    } else {
        for (long i = 0; i < n; i++)
            out[i] = item_ok[i] &&
                     sr_verify_one(pubs + 32 * i, c32 + 32 * i, s32 + 32 * i,
                                   r32 + 32 * i);
    }
    free(item_ok);
    free(ax);
}

/* sanity: returns 1 when the base point round-trips through compress */
int ed25519h_selftest(void) {
    pthread_once(&INIT_ONCE, init_tables);
    u8 enc[32];
    ge_compress(enc, &GE_BASE);
    fe x, y;
    if (!ed_decompress(&x, &y, enc)) return 0;
    return fe_eq(&x, &GE_BASE.X) && fe_eq(&y, &GE_BASE.Y);
}
