"""North-star benchmark: 10k-validator commit verification (20k ed25519 sigs).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value = p50 wall-clock milliseconds to decide 20,480 ed25519 signatures
(batched TPU kernel, end-to-end including host preparation, steady-state:
validator pubkey decompression cache warm - validator sets persist across
heights, so steady-state is the operating regime).

vs_baseline = speedup vs the reference's serial CPU anchor for the same batch
(Go x/crypto ed25519 ~ 70-100us/sig/core => 85us * N; BASELINE.md crypto row).
"""

from __future__ import annotations

import json
import os
import statistics
import time

N_SIGS = int(os.environ.get("BENCH_N_SIGS", 20480))
ITERS = int(os.environ.get("BENCH_ITERS", 5))
BASELINE_US_PER_SIG = 85.0


def main() -> None:
    from tendermint_tpu.crypto import ed25519 as ref
    from tendermint_tpu.ops import ed25519_batch

    # Synthetic commit: unique validators, canonical-vote-sized messages.
    n_vals = N_SIGS // 2
    t0 = time.monotonic()
    items = []
    privs = []
    for i in range(n_vals):
        seed = i.to_bytes(4, "big") * 8
        privs.append(ref.gen_priv_key(seed))
    for r in range(2):
        for i in range(n_vals):
            msg = (
                b"\x08\x02\x11" + (12345).to_bytes(8, "little")
                + b"\x19" + r.to_bytes(8, "little")
                + b"\x22\x48" + bytes(72) + b"bench-chain"
                + i.to_bytes(4, "big")
            )
            items.append((privs[i].pub_key().data, msg, ref.sign(privs[i].data, msg)))
    gen_s = time.monotonic() - t0

    # Warmup: compiles the kernel and warms the pubkey decompression cache.
    t0 = time.monotonic()
    out = ed25519_batch.verify_batch(items)
    warm_s = time.monotonic() - t0
    assert out.all(), "benchmark signatures must all verify"

    times = []
    for _ in range(ITERS):
        t0 = time.monotonic()
        out = ed25519_batch.verify_batch(items)
        times.append(time.monotonic() - t0)
    assert out.all()

    p50_ms = statistics.median(times) * 1000.0
    baseline_ms = BASELINE_US_PER_SIG * len(items) / 1000.0
    result = {
        "metric": "ed25519_commit_verify_%d_sigs_p50" % len(items),
        "value": round(p50_ms, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / p50_ms, 2),
    }
    print(json.dumps(result))
    # Diagnostics on stderr-like side channel: keep stdout to the ONE line.
    import sys

    print(
        f"# gen={gen_s:.1f}s warmup={warm_s:.1f}s iters={['%.1f' % (t*1e3) for t in times]}ms"
        f" baseline={baseline_ms:.0f}ms",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
