"""North-star benchmark: 10k-validator commit verification (20k ed25519 sigs).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value = p50 wall-clock milliseconds to decide 20,480 ed25519 signatures
(batched TPU kernel, end-to-end including host preparation and the result
readback, steady-state: validator pubkey comb tables device-resident --
validator sets persist across heights, so steady-state is the operating
regime).

vs_baseline = speedup vs the reference's serial CPU anchor for the same batch
(Go x/crypto ed25519 ~ 70-100us/sig/core => 85us * N; BASELINE.md crypto row).

Diagnostics on stderr decompose the number: this environment reaches the TPU
through a tunnel whose result-fetch latency is ~100 ms regardless of payload
(measured by `sync_floor`: a trivial 1-element op round trip), so the e2e
p50 = tunnel floor + host prep + true device time. `pipelined` measures
marginal throughput with K batches in flight, which removes the fixed floor
and is the number that scales with validator count.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

N_SIGS = int(os.environ.get("BENCH_N_SIGS", 20480))
ITERS = int(os.environ.get("BENCH_ITERS", 5))
ROUNDS = int(os.environ.get("BENCH_ROUNDS", 3))
BASELINE_US_PER_SIG = 85.0


def _measure(fn, iters):
    times = []
    for _ in range(iters):
        t0 = time.monotonic()
        fn()
        times.append(time.monotonic() - t0)
    return times


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tendermint_tpu.crypto import ed25519 as ref
    from tendermint_tpu.ops import ed25519_batch

    # Synthetic commit: unique validators, canonical-vote-sized messages.
    n_vals = N_SIGS // 2
    t0 = time.monotonic()
    items = []
    privs = []
    for i in range(n_vals):
        seed = i.to_bytes(4, "big") * 8
        privs.append(ref.gen_priv_key(seed))
    for r in range(2):
        for i in range(n_vals):
            msg = (
                b"\x08\x02\x11" + (12345).to_bytes(8, "little")
                + b"\x19" + r.to_bytes(8, "little")
                + b"\x22\x48" + bytes(72) + b"bench-chain"
                + i.to_bytes(4, "big")
            )
            items.append((privs[i].pub_key().data, msg, ref.sign(privs[i].data, msg)))
    gen_s = time.monotonic() - t0

    # Warmup: compiles the kernel and builds the device-resident tables.
    t0 = time.monotonic()
    out = ed25519_batch.verify_batch(items)
    warm_s = time.monotonic() - t0
    assert out.all(), "benchmark signatures must all verify"

    # Sync-latency floor of this host<->device link (trivial op + readback).
    tiny = jax.jit(lambda a: a * 2)
    np.asarray(tiny(jnp.ones((1,), jnp.int32)))
    floor_ms = statistics.median(
        _measure(lambda: np.asarray(tiny(jnp.ones((1,), jnp.int32))), 5)) * 1e3

    # 3 independent measurement rounds: the recorded value is the median of
    # round p50s; the spread across rounds is reported so a >1.5x variance
    # can never go unnoticed again (round-2 lesson).
    round_p50s = []
    all_iters = []
    for _ in range(ROUNDS):
        times = _measure(lambda: ed25519_batch.verify_batch(items), ITERS)
        round_p50s.append(statistics.median(times) * 1000.0)
        all_iters.append([round(t * 1e3, 1) for t in times])
    assert ed25519_batch.verify_batch(items).all()
    p50_ms = statistics.median(round_p50s)
    spread = max(round_p50s) / min(round_p50s)

    # Marginal cost per signature with the fixed sync floor removed:
    # p50(2N batch) - p50(N batch) over N extra signatures.
    double = items + items
    ed25519_batch.verify_batch(double)  # warm the 2N keyset + shapes
    t2 = statistics.median(
        _measure(lambda: ed25519_batch.verify_batch(double), max(ITERS - 2, 3))) * 1e3
    marginal_us_per_sig = max((t2 - p50_ms), 0.001) * 1e3 / len(items)

    baseline_ms = BASELINE_US_PER_SIG * len(items) / 1000.0
    result = {
        "metric": "ed25519_commit_verify_%d_sigs_p50" % len(items),
        "value": round(p50_ms, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / p50_ms, 2),
    }
    print(json.dumps(result))
    print(
        f"# gen={gen_s:.1f}s warmup={warm_s:.1f}s rounds_p50={[round(p,1) for p in round_p50s]}ms"
        f" spread={spread:.2f}x iters={all_iters}"
        f" sync_floor={floor_ms:.1f}ms (fixed host<->device round-trip latency of"
        f" this link, paid once per decision)"
        f" marginal={marginal_us_per_sig:.2f}us/sig p50_2N={t2:.1f}ms"
        f" ({1.0/marginal_us_per_sig:.2f}M sigs/s marginal)"
        f" baseline={baseline_ms:.0f}ms",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
