"""Standing benchmark suite: all five BASELINE configs + the north-star
20,480-sig commit verify.

Prints ONE JSON line on stdout:

    {"metric", "value", "unit", "vs_baseline", "configs": {...}}

where value/vs_baseline are the headline 20,480-sig commit p50 (ms) and
`configs` carries one entry per BASELINE.json config. Diagnostics and the
per-config table go to stderr (the artifact model is the reference's
docs/qa/v034/README.md standing QA tables).

Measurement discipline (the 1-core host + tunneled TPU make naive medians
meaningless — any concurrent process poisons a round):

 * A fixed CPU spin is timed before every round; a round whose spin is
   >1.3x the best spin observed is CONTENDED and retried (up to 2 extras).
 * The recorded statistic is the median of round p50s when the spread
   across rounds is <=1.3x, else the MIN (min-of-rounds is the honest
   quiet-host number; medians of poisoned rounds measure the contention,
   not the code).
 * The sync floor (a trivial 1-element op round trip, ~100 ms on this
   tunnel) and host-prep decomposition are printed so the fixed
   environment latency is never conflated with marginal throughput.

vs_baseline = speedup vs the reference's serial CPU anchor for the same
work (Go x/crypto ed25519 / go-schnorrkel ~= 85 us/sig/core; BASELINE.md
crypto row).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

N_SIGS = int(os.environ.get("BENCH_N_SIGS", 20480))
ITERS = int(os.environ.get("BENCH_ITERS", 5))
ROUNDS = int(os.environ.get("BENCH_ROUNDS", 3))
MAX_RETRY_ROUNDS = int(os.environ.get("BENCH_MAX_RETRY", 2))
N_RANGE_HEADERS = int(os.environ.get("BENCH_RANGE_HEADERS", 10000))
BASELINE_US_PER_SIG = 85.0
SPREAD_LIMIT = 1.3

BENCH_CHAIN = "bench-chain"


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _spin_ms() -> float:
    """Fixed CPU workload -> elapsed ms; inflation == host contention.
    Shared with the e2e runner's load-scaled progress waits."""
    from tendermint_tpu.e2e.runner import _spin_ms as probe

    return probe()


def _measure(fn, iters):
    times = []
    for _ in range(iters):
        t0 = time.monotonic()
        fn()
        times.append(time.monotonic() - t0)
    return times


class Rounds:
    """Contention-aware repeated measurement of one benchmark closure."""

    def __init__(self):
        self.best_spin = min(_spin_ms() for _ in range(3))

    def run(self, fn, iters=ITERS, rounds=ROUNDS, warmup_rounds=0,
            report=None, pre_round=None):
        """report="min" always records min-of-rounds (the honest quiet-host
        number for configs whose long iterations make contended rounds
        likely); default is the headline policy (median, min under spread).
        warmup_rounds: full measured-and-discarded rounds before recording
        (settles page cache/allocator/JIT state beyond the single
        throwaway call). pre_round: hook run OUTSIDE the timed region before
        every round (e.g. gc.collect, so a generational collection triggered
        by accumulated garbage cannot land inside a timed iteration)."""
        fn()  # throwaway: settle allocator/page-cache state after generation
        for _ in range(warmup_rounds):
            _measure(fn, iters)
        p50s, spins, retries = [], [], 0
        while len(p50s) < rounds:
            if pre_round is not None:
                pre_round()
            # Spin BEFORE and AFTER: contention that starts mid-round would
            # otherwise slip past a leading-only check.
            spin_a = _spin_ms()
            self.best_spin = min(self.best_spin, spin_a)
            times = _measure(fn, iters)
            spin_b = _spin_ms()
            self.best_spin = min(self.best_spin, spin_b)
            spin = max(spin_a, spin_b)
            p50 = statistics.median(times) * 1e3
            if (spin > SPREAD_LIMIT * self.best_spin
                    and retries < MAX_RETRY_ROUNDS):
                retries += 1
                _log(f"#   contended round discarded (spin {spin:.1f}ms vs "
                     f"best {self.best_spin:.1f}ms), retrying")
                continue
            p50s.append(p50)
            spins.append(round(spin, 1))
        spread = max(p50s) / min(p50s)
        if report == "min" or spread > SPREAD_LIMIT:
            value = min(p50s)
        else:
            value = statistics.median(p50s)
        return value, dict(rounds_ms=[round(p, 1) for p in p50s],
                           spread=round(spread, 2), spins_ms=spins,
                           retries=retries)


# --------------------------------------------------------------------------
# Phase attribution (docs/OBSERVABILITY.md): where does a decision's wall
# time go — host prep, queue wait, device compute, readback, replay?
# --------------------------------------------------------------------------


def _phase_attribution(items, p50_ms: float) -> dict:
    """One instrumented pass of the headline workload, split into the
    canonical verify phases (utils/trace.py CANONICAL_SPANS). Run OUTSIDE
    the timed rounds: the extra `block_until_ready` sync that separates
    device compute from readback would perturb the p50 (bench-level code
    may call it — the tmlint device-sync-choke-point rule scopes to
    tendermint_tpu/). TMTPU_TRACE_XPROF=<dir> additionally wraps the pass
    in jax.profiler traces for TensorBoard/xprof."""
    import contextlib

    import jax

    from tendermint_tpu.ops import ed25519_batch
    from tendermint_tpu.utils import trace as tmtrace

    xprof = os.environ.get("TMTPU_TRACE_XPROF")
    with contextlib.ExitStack() as stack:
        if xprof:
            stack.enter_context(tmtrace.jax_profile(xprof))
        t0 = time.monotonic()
        dev, finish = ed25519_batch.dispatch_batch(items)
        t1 = time.monotonic()
        if dev is not None:
            jax.block_until_ready(dev)
        t2 = time.monotonic()
        fetched = jax.device_get(dev) if dev is not None else None
        t3 = time.monotonic()
        out = finish(fetched)
        t4 = time.monotonic()
    assert all(bool(b) for b in out)
    phases_us = {
        "host_prep": (t1 - t0) * 1e6,
        "queue": 0.0,  # sync pass: resolve follows dispatch immediately
        "device": (t2 - t1) * 1e6,
        "readback": (t3 - t2) * 1e6,
        "replay": (t4 - t3) * 1e6,
    }
    wall_us = (t4 - t0) * 1e6
    total_us = sum(phases_us.values())
    p50_us = p50_ms * 1e3
    # the coverage number is vs the INDEPENDENTLY measured p50 (the timed
    # rounds), never vs this pass's own wall — the phases are consecutive
    # deltas of that wall, so a self-ratio would be identically 100%
    return {
        "phases_us": {k: round(v, 1) for k, v in phases_us.items()},
        "pct_of_p50": {k: round(100.0 * v / p50_us, 1)
                       for k, v in phases_us.items()},
        "wall_ms": round(wall_us / 1e3, 2),
        "attributed_pct_of_p50": round(100.0 * total_us / p50_us, 1),
    }


def _span_phases_us(agg: dict) -> dict:
    """Tracer aggregation -> canonical phase table (us). The device phase
    is folded into readback on the production spans (the host blocks in
    _device_get until the kernel finishes); the bench headline pass above
    separates them with an explicit sync."""
    def us(name):
        return agg.get(name, {}).get("total_s", 0.0) * 1e6

    return {"host_prep": round(us("verify.host_prep"), 1),
            "queue": round(us("verify.queue"), 1),
            "device": 0.0,
            "readback": round(us("verify.readback"), 1),
            "replay": round(us("verify.replay"), 1)}


# --------------------------------------------------------------------------
# Workload generators
# --------------------------------------------------------------------------


def _gen_flat_commit(n_sigs: int):
    """Synthetic n_sigs/2-validator commit (prevote+precommit rounds),
    unique keys, canonical-vote-sized messages."""
    from tendermint_tpu.crypto import ed25519 as ref

    n_vals = n_sigs // 2
    privs = [ref.gen_priv_key(i.to_bytes(4, "big") * 8) for i in range(n_vals)]
    items = []
    for r in range(2):
        for i in range(n_vals):
            msg = (b"\x08\x02\x11" + (12345).to_bytes(8, "little")
                   + b"\x19" + r.to_bytes(8, "little")
                   + b"\x22\x48" + bytes(72) + b"bench-chain" + i.to_bytes(4, "big"))
            items.append((privs[i].pub_key().data, msg, ref.sign(privs[i].data, msg)))
    return items


def _mk_valset(n_ed: int, n_sr: int = 0, power: int = 10):
    from tendermint_tpu.crypto import ed25519, sr25519
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet

    privs = [ed25519.gen_priv_key((i + 1).to_bytes(4, "big") * 8)
             for i in range(n_ed)]
    privs += [sr25519.gen_priv_key((i + 1).to_bytes(4, "big"))
              for i in range(n_sr)]
    vals = ValidatorSet([Validator.new(p.pub_key(), power) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    privs = [by_addr[v.address] for v in vals.validators]
    return privs, vals


def _sign_commit_bid(bid, height, ts, vals, privs, chain_id=BENCH_CHAIN):
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote

    sigs = []
    for i, (priv, val) in enumerate(zip(privs, vals.validators)):
        vote = Vote(type=PRECOMMIT_TYPE, height=height, round=1,
                    block_id=bid, timestamp=ts,
                    validator_address=val.address, validator_index=i)
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address, ts,
                              priv.sign(vote.sign_bytes(chain_id))))
    return Commit(height=height, round=1, block_id=bid, signatures=sigs)


def _sign_commit(header, vals, privs, chain_id=BENCH_CHAIN):
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.ttime import Time

    bid = BlockID(hash=header.hash(),
                  part_set_header=PartSetHeader(total=1, hash=b"\xcd" * 32))
    return _sign_commit_bid(bid, header.height, Time(header.time.seconds, 0),
                            vals, privs, chain_id)


def _gen_light_chain(n_headers: int, n_vals: int):
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.light_block import LightBlock, SignedHeader
    from tendermint_tpu.types.ttime import Time

    privs, vals = _mk_valset(n_vals)
    out = []
    last_bid = BlockID()
    t0 = 1_700_000_000
    for h in range(1, n_headers + 1):
        header = Header(
            chain_id=BENCH_CHAIN, height=h, time=Time(t0 + 10 * h, 0),
            last_block_id=last_bid,
            validators_hash=vals.hash(), next_validators_hash=vals.hash(),
            proposer_address=vals.validators[0].address,
        )
        commit = _sign_commit(header, vals, privs)
        out.append(LightBlock(signed_header=SignedHeader(header, commit),
                              validator_set=vals.copy()))
        last_bid = commit.block_id
    return out


# --------------------------------------------------------------------------
# Configs
# --------------------------------------------------------------------------


def config_batch64(rr, items64):
    """BASELINE config 1: 64-sig batch latency (kernel MIN_BUCKET)."""
    from tendermint_tpu.ops import ed25519_batch

    assert ed25519_batch.verify_batch(items64).all()
    value, detail = rr.run(lambda: ed25519_batch.verify_batch(items64))
    base = BASELINE_US_PER_SIG * 64 / 1000.0
    return dict(metric="batch64_p50_ms", value=round(value, 2), unit="ms",
                vs_baseline=round(base / value, 2), **detail)


def config_commit150(rr):
    """BASELINE config 2: 150-validator commit (Cosmos-Hub-4 scale) through
    the production ValidatorSet.verify_commit path."""
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.ttime import Time

    privs, vals = _mk_valset(150)
    header = Header(chain_id=BENCH_CHAIN, height=5, time=Time(1_700_000_050, 0),
                    last_block_id=BlockID(), validators_hash=vals.hash(),
                    next_validators_hash=vals.hash(),
                    proposer_address=vals.validators[0].address)
    commit = _sign_commit(header, vals, privs)

    def run():
        vals.verify_commit(BENCH_CHAIN, commit.block_id, 5, commit)

    run()
    value, detail = rr.run(run)
    base = BASELINE_US_PER_SIG * 150 / 1000.0
    return dict(metric="commit150_verify_p50_ms", value=round(value, 2),
                unit="ms", vs_baseline=round(base / value, 2), **detail)


def config_range_verify(rr):
    """BASELINE config 3: sequential header-range sync, one batched flush
    (light/range_verify.py) over N_RANGE_HEADERS headers."""
    from tendermint_tpu.light.range_verify import verify_header_range
    from tendermint_tpu.types.ttime import Time

    t0 = time.monotonic()
    chain = _gen_light_chain(N_RANGE_HEADERS, 1)
    gen_s = time.monotonic() - t0
    trusted = chain[0]
    rest = chain[1:]
    now = Time(1_700_000_000 + 10 * (N_RANGE_HEADERS + 2), 0)

    def run():
        # Trusting period spans the whole generated range (the reference
        # default for light sync is weeks; the 10s header cadence here
        # covers ~28h for 10k headers).
        verify_header_range(trusted, rest, 14 * 86400.0, now)

    # Stability (BENCH r05 spread 2.06x vs <=1.13x elsewhere): the same
    # discipline as the headline config -- full ITERS so one GC/contention
    # spike cannot poison a round's median (with iters=2 the "median" was a
    # mean of two), full ROUNDS behind the contended-round retry, plus one
    # measured-and-discarded warmup round to settle page cache + keyset
    # state, gc.collect between rounds (10k LightBlocks of garbage otherwise
    # trip gen-2 collections mid-iteration), and min-of-rounds as the
    # recorded quiet-host number.
    import gc

    value, detail = rr.run(run, iters=ITERS, rounds=ROUNDS,
                           warmup_rounds=1, report="min",
                           pre_round=gc.collect)
    n = len(rest)
    base = BASELINE_US_PER_SIG * n / 1000.0  # 1 sig/header serial anchor
    return dict(metric=f"range_verify_{n}_headers_p50_ms",
                value=round(value, 1), unit="ms",
                vs_baseline=round(base / value, 2),
                us_per_header=round(value * 1e3 / n, 2),
                gen_s=round(gen_s, 1), report="min", **detail)


def config_mixed_commit(rr):
    """BASELINE config 4 (fast-sync replay at 1000 validators, mixed
    ed25519/sr25519): per-block commit-verify cost through the production
    verify_commit path with a 700/300 ed25519/sr25519 set."""
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.ttime import Time

    t0 = time.monotonic()
    privs, vals = _mk_valset(700, 300)
    header = Header(chain_id=BENCH_CHAIN, height=9, time=Time(1_700_000_090, 0),
                    last_block_id=BlockID(), validators_hash=vals.hash(),
                    next_validators_hash=vals.hash(),
                    proposer_address=vals.validators[0].address)
    commit = _sign_commit(header, vals, privs)
    gen_s = time.monotonic() - t0

    def run():
        vals.verify_commit(BENCH_CHAIN, commit.block_id, 9, commit)

    run()  # warm (compiles the sr25519 kernel bucket on first ever run)
    value, detail = rr.run(run, iters=max(3, ITERS - 2))
    base = BASELINE_US_PER_SIG * 1000 / 1000.0
    return dict(metric="mixed_commit_1000v_700ed_300sr_p50_ms",
                value=round(value, 1), unit="ms",
                vs_baseline=round(base / value, 2),
                blocks_per_s=round(1000.0 / value, 1),
                gen_s=round(gen_s, 1), **detail)


def config_fastsync(rr):
    """BASELINE config 4 proper: fast-sync replay of mixed ed25519/sr25519
    blocks @ 1000 validators through the verify-ahead pipeline
    (blockchain/pipeline.py, driven by the shared headless replay harness
    in blockchain/replay.py), reporting blocks_per_s at depth 1 (the old
    serial loop's behavior) vs the default depth. Both depths must accept
    the same blocks and converge to the same app hash."""
    from tendermint_tpu.blockchain import pipeline as bpipe
    from tendermint_tpu.blockchain.replay import ReplayCtx, make_chain

    n_blocks = int(os.environ.get("BENCH_FASTSYNC_BLOCKS", 8))
    t0 = time.monotonic()
    privs, vals = _mk_valset(700, 300)
    # n_blocks+1 pooled blocks -> n_blocks appliable heights
    blocks = make_chain(BENCH_CHAIN, n_blocks + 1, vals, privs)
    gen_s = time.monotonic() - t0

    def run_depth(depth):
        prev = os.environ.get("TM_TPU_VERIFY_AHEAD")
        os.environ["TM_TPU_VERIFY_AHEAD"] = str(depth)
        try:
            ctx = ReplayCtx(vals, BENCH_CHAIN)
            for i, b in enumerate(blocks):
                ctx.pool.add_block("pA" if i % 2 == 0 else "pB", b)
            pipe = bpipe.VerifyAheadPipeline()
            while pipe.process_next(ctx):
                pass
            assert not ctx.punished and len(ctx.applied) == n_blocks, (
                ctx.punished, ctx.applied)
            return ctx
        finally:
            if prev is None:
                os.environ.pop("TM_TPU_VERIFY_AHEAD", None)
            else:
                os.environ["TM_TPU_VERIFY_AHEAD"] = prev

    depth_default = bpipe.DEFAULT_DEPTH
    # Correctness gate (also warms kernels/keysets for both shapes):
    # identical acceptance + app hash at depth 1 and default depth.
    ctx1, ctxd = run_depth(1), run_depth(depth_default)
    assert ctx1.applied == ctxd.applied and ctx1.app_hash == ctxd.app_hash

    v1, _ = rr.run(lambda: run_depth(1), iters=2, rounds=2, report="min")
    vd, detail = rr.run(lambda: run_depth(depth_default), iters=2, rounds=2,
                        report="min")
    bps1 = n_blocks / (v1 / 1e3)
    bpsd = n_blocks / (vd / 1e3)
    # serial CPU anchor: one core verifying the +2/3 light prefix per block
    prefix_sigs = len(vals.commit_light_prefix(
        blocks[1].last_commit, vals.total_voting_power() * 2 // 3))
    base_bps = 1e3 / (BASELINE_US_PER_SIG * prefix_sigs / 1000.0)
    return dict(metric=f"fastsync_1000v_mixed_{n_blocks}_blocks_per_s",
                value=round(bpsd, 1), unit="blocks/s",
                vs_baseline=round(bpsd / base_bps, 2),
                depth1_blocks_per_s=round(bps1, 1),
                speedup_vs_depth1=round(bpsd / bps1, 2),
                depth=depth_default, prefix_sigs=prefix_sigs,
                gen_s=round(gen_s, 1), **detail)


def config_sr25519(rr):
    """VERDICT r4 item 3: a standalone sr25519 number. Pure sr25519
    1000-validator commit through the production verify_commit path
    (reference verifies these serially via go-schnorrkel,
    crypto/sr25519/pubkey.go:10)."""
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.ttime import Time

    t0 = time.monotonic()
    privs, vals = _mk_valset(0, 1000)
    header = Header(chain_id=BENCH_CHAIN, height=11, time=Time(1_700_000_110, 0),
                    last_block_id=BlockID(), validators_hash=vals.hash(),
                    next_validators_hash=vals.hash(),
                    proposer_address=vals.validators[0].address)
    commit = _sign_commit(header, vals, privs)
    gen_s = time.monotonic() - t0

    def run():
        vals.verify_commit(BENCH_CHAIN, commit.block_id, 11, commit)

    run()
    value, detail = rr.run(run, iters=max(3, ITERS - 2))
    base = BASELINE_US_PER_SIG * 1000 / 1000.0
    return dict(metric="sr25519_1000v_commit_p50_ms", value=round(value, 1),
                unit="ms", vs_baseline=round(base / value, 2),
                us_per_sig=round(value, 1),
                gen_s=round(gen_s, 1), **detail)


def config_sharded(rr, items):
    """The multi-device story (ISSUE 4 tentpole): the production
    BatchVerifier registry at the headline 20,480-sig shape, sharded over
    the ("dp",) mesh vs pinned single-device (TM_TPU_SHARD=0), reporting
    MARGINAL us/sig for both (p50(N) - p50(N/4) over the extra sigs, the
    same fixed-floor removal the headline uses). On one device the sharded
    route never engages and this config just records that fact."""
    import jax

    from tendermint_tpu.crypto import batch as cbatch
    from tendermint_tpu.parallel import batch_shard

    ndev = len(jax.devices())
    if ndev < 2 or not batch_shard.shard_enabled():
        return dict(metric="sharded_marginal_us_per_sig", value=None,
                    unit="us/sig", devices=ndev,
                    skipped="single device: sharded route never engages")

    from tendermint_tpu.crypto import ed25519 as ed

    pubs = {}

    def registry_verify(subset):
        verifier = cbatch.create_batch_verifier("ed25519")
        for pub, msg, sig in subset:
            pk = pubs.get(pub)
            if pk is None:
                pk = pubs[pub] = ed.PubKey(pub)
            verifier.add(pk, msg, sig)
        ok_all, bitmap = verifier.dispatch().resolve()
        assert ok_all
        return bitmap

    quarter = items[: len(items) // 4]
    extra = len(items) - len(quarter)

    def marginal(env):
        prev = os.environ.get("TM_TPU_SHARD")
        if env is None:
            os.environ.pop("TM_TPU_SHARD", None)
        else:
            os.environ["TM_TPU_SHARD"] = env
        try:
            registry_verify(items)  # warm this route's executables/keysets
            full, detail = rr.run(lambda: registry_verify(items),
                                  iters=2, rounds=2, report="min")
            quart, _ = rr.run(lambda: registry_verify(quarter),
                              iters=2, rounds=2, report="min")
            return max(full - quart, 0.001) * 1e3 / extra, full, detail
        finally:
            if prev is None:
                os.environ.pop("TM_TPU_SHARD", None)
            else:
                os.environ["TM_TPU_SHARD"] = prev

    sharded_us, sharded_ms, detail = marginal(None)
    single_us, single_ms, _ = marginal("0")
    return dict(metric="sharded_marginal_us_per_sig",
                value=round(sharded_us, 2), unit="us/sig",
                vs_baseline=round(BASELINE_US_PER_SIG / sharded_us, 2),
                single_device_marginal_us=round(single_us, 2),
                speedup_vs_single=round(single_us / sharded_us, 2),
                sharded_p50_ms=round(sharded_ms, 1),
                single_p50_ms=round(single_ms, 1),
                devices=ndev, **detail)


def config_addvote(rr):
    """BASELINE config 5: the addVote hot loop — gossiped votes at a
    1024-validator height drained through VoteSet.add_votes (one batched
    flush + in-order side effects)."""
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.types.vote import PREVOTE_TYPE, Vote
    from tendermint_tpu.types.vote_set import VoteSet

    privs, vals = _mk_valset(1024)
    bid = BlockID(hash=b"\x11" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32))
    votes = []
    for i, p in enumerate(privs):
        v = Vote(type=PREVOTE_TYPE, height=1, round=0, block_id=bid,
                 timestamp=Time(1_700_001_000, 0),
                 validator_address=vals.validators[i].address,
                 validator_index=i)
        v.signature = p.sign(v.sign_bytes(BENCH_CHAIN))
        votes.append(v)

    def run():
        vs = VoteSet(BENCH_CHAIN, 1, 0, PREVOTE_TYPE, vals)
        results = vs.add_votes(votes)
        assert all(a for a, _ in results)

    # The drain metric must keep measuring VERIFICATION: with the global
    # sigcache on, iteration 2+ would re-deliver already-verified triples
    # and time SHA-256 lookups instead of the kernel (incomparable with the
    # pre-cache trajectory). Pin the cache off for the headline number, then
    # record the cache-hit drain rate separately -- that IS the gossip
    # re-delivery speedup the cache exists for.
    from tendermint_tpu.crypto import sigcache

    from tendermint_tpu.utils import trace as tmtrace

    prev = os.environ.get("TM_TPU_SIGCACHE")
    os.environ["TM_TPU_SIGCACHE"] = "0"
    try:
        run()
        value, detail = rr.run(run, iters=max(3, ITERS - 2))
        # Phase attribution: one instrumented drain through the PRODUCTION
        # dispatch()/resolve() spans; whatever the phases don't cover is
        # the serial vote-apply replay (side effects, maj23 bookkeeping).
        tr = tmtrace.Tracer(name="bench-addvote", cap=65536, enabled=True)
        try:
            with tr.activate():
                t0 = time.monotonic()
                run()
                drain_wall_us = (time.monotonic() - t0) * 1e6
        finally:
            # a mid-drain failure must not pin the process-global ENABLED
            # flag (every later config would silently pay the traced path)
            tr.disable()
        phases_us = _span_phases_us(tr.summarize())
        p50_us = value * 1e3
        attribution = {
            "phases_us": phases_us,
            "pct_of_p50": {k: round(100.0 * v / p50_us, 1)
                           for k, v in phases_us.items()},
            "apply_us": round(max(drain_wall_us - sum(phases_us.values()),
                                  0.0), 1),
            "wall_ms": round(drain_wall_us / 1e3, 1),
        }
        # Tracing tax (ISSUE 10 bench hygiene): the SAME drain with the
        # flight recorder enabled vs disabled, both measured back to back
        # under the IDENTICAL policy (iters/rounds/min) — comparing the
        # headline median against a traced min would systematically
        # underestimate the tax. Recorded so a future PR cannot silently
        # make tracing expensive.
        ovh_iters, ovh_rounds = max(3, ITERS - 2), 2
        base_value, _ = rr.run(run, iters=ovh_iters, rounds=ovh_rounds,
                               report="min")
        tr2 = tmtrace.Tracer(name="bench-addvote-ovh", cap=65536,
                             enabled=True)
        try:
            with tr2.activate():
                traced_value, _ = rr.run(run, iters=ovh_iters,
                                         rounds=ovh_rounds, report="min")
        finally:
            tr2.disable()
        trace_overhead_pct = round(
            100.0 * (traced_value - base_value) / base_value, 2)
    finally:
        if prev is None:
            os.environ.pop("TM_TPU_SIGCACHE", None)
        else:
            os.environ["TM_TPU_SIGCACHE"] = prev
    sigcache.reset()
    run()  # populates the cache
    cached_ms, _ = rr.run(run, iters=max(3, ITERS - 2), rounds=2,
                          report="min")
    sigcache.reset()
    votes_per_s = len(votes) / (value / 1e3)
    base = BASELINE_US_PER_SIG * len(votes) / 1000.0
    return dict(metric="addvote_1024v_drain_p50_ms", value=round(value, 1),
                unit="ms", vs_baseline=round(base / value, 2),
                votes_per_s=int(votes_per_s),
                sigcache_hit_p50_ms=round(cached_ms, 1),
                sigcache_hit_votes_per_s=int(len(votes) / (cached_ms / 1e3)),
                phase_attribution=attribution,
                trace_overhead_pct=trace_overhead_pct,
                **detail)


def config_concurrent_verify(rr):
    """ISSUE 11 acceptance: M simultaneous verify paths — the consensus
    vote drain, the fast-sync commit-verify primitive, and light range
    verification — hammering the device CONCURRENTLY, with the
    continuous-batching verify service on vs off (TMTPU_VERIFY_SERVICE=0).

    The service's whole claim is that N concurrent callers share kernel
    launches (one sync floor, not N), so the reported numbers are the
    aggregate decisions/s of the storm, each path's per-decision p50, the
    service's coalescing stats, and the flight-recorder phase attribution
    per path for BOTH sides — the win must show up as the per-decision
    readback/host_prep share shrinking, not just a better total."""
    import threading

    from tendermint_tpu.crypto import sigcache, verify_service
    from tendermint_tpu.light.range_verify import verify_header_range
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.types.vote import PREVOTE_TYPE, Vote
    from tendermint_tpu.types.vote_set import VoteSet
    from tendermint_tpu.utils import trace as tmtrace

    iters_per_path = int(os.environ.get("BENCH_CONCURRENT_ITERS", 4))
    t0 = time.monotonic()
    # drain path: 512-validator prevote pile through VoteSet.add_votes
    d_privs, d_vals = _mk_valset(512)
    d_bid = BlockID(hash=b"\x31" * 32,
                    part_set_header=PartSetHeader(total=1, hash=b"\x32" * 32))
    d_votes = []
    for i, p in enumerate(d_privs):
        v = Vote(type=PREVOTE_TYPE, height=1, round=0, block_id=d_bid,
                 timestamp=Time(1_700_002_000, 0),
                 validator_address=d_vals.validators[i].address,
                 validator_index=i)
        v.signature = p.sign(v.sign_bytes(BENCH_CHAIN))
        d_votes.append(v)
    # fastsync path: 512-validator commit through verify_commit_light
    f_privs, f_vals = _mk_valset(512, power=7)
    f_header = Header(chain_id=BENCH_CHAIN, height=13,
                      time=Time(1_700_002_100, 0), last_block_id=BlockID(),
                      validators_hash=f_vals.hash(),
                      next_validators_hash=f_vals.hash(),
                      proposer_address=f_vals.validators[0].address)
    f_commit = _sign_commit(f_header, f_vals, f_privs)
    # range path: light header chain (BASELINE config 3 shape, small)
    r_headers = int(os.environ.get("BENCH_CONCURRENT_RANGE_HEADERS", 192))
    r_chain = _gen_light_chain(r_headers, 4)
    r_trusted, r_rest = r_chain[0], r_chain[1:]
    r_now = Time(1_700_000_000 + 10 * (r_headers + 2), 0)
    gen_s = time.monotonic() - t0

    def drain_decision():
        vs = VoteSet(BENCH_CHAIN, 1, 0, PREVOTE_TYPE, d_vals)
        results = vs.add_votes(d_votes)
        assert all(a for a, _ in results)

    def fastsync_decision():
        f_vals.verify_commit_light(BENCH_CHAIN, f_commit.block_id, 13,
                                   f_commit)

    def range_decision():
        verify_header_range(r_trusted, r_rest, 14 * 86400.0, r_now)

    paths = (("drain", drain_decision), ("fastsync", fastsync_decision),
             ("range", range_decision))

    def storm(collect=None):
        """One concurrent pass: every path runs iters_per_path decisions on
        its own thread. collect[path] <- per-decision wall times."""
        barrier = threading.Barrier(len(paths))
        errors = []

        def worker(name, fn, tracer):
            try:
                if tracer is not None:
                    stack = tracer.activate()
                    stack.__enter__()
                barrier.wait()
                for _ in range(iters_per_path):
                    t = time.monotonic()
                    fn()
                    if collect is not None:
                        collect[name].append(time.monotonic() - t)
                if tracer is not None:
                    stack.__exit__(None, None, None)
            except Exception as e:  # noqa: BLE001 - surfaced after join
                errors.append((name, e))

        tracers = {name: (tmtrace.Tracer(name=f"bench-cv-{name}", cap=65536,
                                         enabled=True)
                          if collect is not None else None)
                   for name, _ in paths}
        threads = [threading.Thread(target=worker, args=(n, f, tracers[n]))
                   for n, f in paths]
        t = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.monotonic() - t
        for tr in tracers.values():
            if tr is not None:
                tr.disable()
        if errors:
            raise RuntimeError(f"concurrent_verify path failed: {errors}")
        return wall, tracers

    def measure(service_on):
        prev = os.environ.get("TMTPU_VERIFY_SERVICE")
        os.environ["TMTPU_VERIFY_SERVICE"] = "1" if service_on else "0"
        verify_service.reset()
        try:
            storm()  # warm kernels/keysets for this routing
            walls = []
            collect = {n: [] for n, _ in paths}
            tracers = None
            for _ in range(2):
                w, trs = storm(collect=collect)
                walls.append(w)
                tracers = trs
            svc = verify_service.get()
            phases = {n: _span_phases_us(tracers[n].summarize())
                      for n, _ in paths}
            return dict(
                wall_s=min(walls),
                agg_decisions_per_s=(len(paths) * iters_per_path * 2
                                     / sum(walls)),
                per_path_p50_ms={n: round(statistics.median(ts) * 1e3, 1)
                                 for n, ts in collect.items()},
                # per-decision phases: `tracers` holds the LAST storm's
                # fresh Tracer objects, so totals cover iters_per_path
                # decisions (NOT both storms)
                phase_attribution={
                    n: {k: round(v / iters_per_path, 1)
                        for k, v in phases[n].items()}
                    for n, _ in paths},
                service=dict(launches=svc.launches, requests=svc.requests,
                             max_coalesced=svc.max_coalesced,
                             fallbacks=svc.fallbacks),
            )
        finally:
            if prev is None:
                os.environ.pop("TMTPU_VERIFY_SERVICE", None)
            else:
                os.environ["TMTPU_VERIFY_SERVICE"] = prev
            verify_service.reset()

    prev_sc = os.environ.get("TM_TPU_SIGCACHE")
    os.environ["TM_TPU_SIGCACHE"] = "0"  # keep every decision VERIFYING
    try:
        on = measure(True)
        off = measure(False)
    finally:
        if prev_sc is None:
            os.environ.pop("TM_TPU_SIGCACHE", None)
        else:
            os.environ["TM_TPU_SIGCACHE"] = prev_sc
        sigcache.reset()
    speedup = on["agg_decisions_per_s"] / max(off["agg_decisions_per_s"],
                                              1e-9)
    return dict(metric="concurrent_verify_3path_agg_decisions_per_s",
                value=round(on["agg_decisions_per_s"], 2),
                unit="decisions/s",
                vs_baseline=round(speedup, 2),
                speedup_vs_service_off=round(speedup, 2),
                service_off_decisions_per_s=round(
                    off["agg_decisions_per_s"], 2),
                per_path_p50_ms_on=on["per_path_p50_ms"],
                per_path_p50_ms_off=off["per_path_p50_ms"],
                phase_attribution_on=on["phase_attribution"],
                phase_attribution_off=off["phase_attribution"],
                service_stats=on["service"],
                iters_per_path=iters_per_path, gen_s=round(gen_s, 1))


def config_light_serve(rr):
    """ISSUE 20 acceptance: gateway light-serving throughput. C concurrent
    clients chase the tip of a signed header chain through ONE shared
    LightGateway (verified-answer cache + single-flight coalescing: ~H
    verifications total) vs the SAME workload where every client runs its
    own light client and verifies everything itself (serial: C*H
    verifications). Reports aggregate queries/s, p99 serve latency, the
    coalesced-vs-serial speedup, and the verify-service on/off delta.
    Sigcache is pinned OFF so the serial baseline actually re-verifies."""
    import threading

    from tendermint_tpu.crypto import sigcache, verify_service
    from tendermint_tpu.light.client import Client, TrustOptions
    from tendermint_tpu.light.gateway import LightGateway
    from tendermint_tpu.light.provider import MockProvider
    from tendermint_tpu.light.store import DBStore
    from tendermint_tpu.store.db import MemDB
    from tendermint_tpu.types.ttime import Time

    n_headers = int(os.environ.get("BENCH_LIGHT_HEADERS", 32))
    n_clients = int(os.environ.get("BENCH_LIGHT_CLIENTS", 8))
    t0 = time.monotonic()
    chain = _gen_light_chain(n_headers, 16)
    gen_s = time.monotonic() - t0
    lbs = {lb.height: lb for lb in chain}
    now = Time(1_700_000_000 + 10 * (n_headers + 2), 0)
    period_s = 14 * 86400.0
    opts = TrustOptions(period_s=period_s, height=1, hash=chain[0].hash())

    def crowd(worker):
        """C threads running `worker(client_index, latencies)`; returns
        (wall_s, all latencies)."""
        lat: list[list[float]] = [[] for _ in range(n_clients)]
        errors: list = []

        def run(c):
            try:
                worker(c, lat[c])
            except Exception as e:  # noqa: BLE001 - surfaced after join
                errors.append(e)

        threads = [threading.Thread(target=run, args=(c,))
                   for c in range(n_clients)]
        t = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.monotonic() - t
        if errors:
            raise RuntimeError(f"light_serve worker failed: {errors}")
        return wall, [x for per in lat for x in per]

    def pass_gateway():
        gw = LightGateway(BENCH_CHAIN, opts,
                          [MockProvider(BENCH_CHAIN, lbs) for _ in range(3)],
                          DBStore(MemDB(), BENCH_CHAIN),
                          sleep=lambda s: None)

        def worker(c, out):
            for h in range(2, n_headers + 1):
                t = time.monotonic()
                lb, _verdict = gw.serve_light_block(h, now=now)
                out.append(time.monotonic() - t)
                assert lb.height == h

        return crowd(worker)

    def pass_serial():
        def worker(c, out):
            client = Client(BENCH_CHAIN, opts,
                            MockProvider(BENCH_CHAIN, lbs), [],
                            DBStore(MemDB(), BENCH_CHAIN))
            for h in range(2, n_headers + 1):
                t = time.monotonic()
                lb = client.verify_light_block_at_height(h, now)
                out.append(time.monotonic() - t)
                assert lb.height == h

        return crowd(worker)

    n_queries = n_clients * (n_headers - 1)

    def measure(mode_pass, service_on):
        prev = os.environ.get("TMTPU_VERIFY_SERVICE")
        os.environ["TMTPU_VERIFY_SERVICE"] = "1" if service_on else "0"
        verify_service.reset()
        try:
            mode_pass()  # warm kernels/keysets for this routing
            walls, lat = [], []
            for _ in range(2):
                w, ls = mode_pass()
                walls.append(w)
                lat = ls
            svc = verify_service.get()
            lat.sort()
            return dict(
                wall_s=min(walls),
                queries_per_s=n_queries / min(walls),
                p50_ms=round(lat[len(lat) // 2] * 1e3, 2),
                p99_ms=round(lat[min(int(len(lat) * 0.99),
                                     len(lat) - 1)] * 1e3, 2),
                launches=svc.launches, requests=svc.requests,
            )
        finally:
            if prev is None:
                os.environ.pop("TMTPU_VERIFY_SERVICE", None)
            else:
                os.environ["TMTPU_VERIFY_SERVICE"] = prev
            verify_service.reset()

    prev_sc = os.environ.get("TM_TPU_SIGCACHE")
    os.environ["TM_TPU_SIGCACHE"] = "0"
    try:
        gw_on = measure(pass_gateway, True)
        gw_off = measure(pass_gateway, False)
        serial = measure(pass_serial, True)
    finally:
        if prev_sc is None:
            os.environ.pop("TM_TPU_SIGCACHE", None)
        else:
            os.environ["TM_TPU_SIGCACHE"] = prev_sc
        sigcache.reset()
    speedup = gw_on["queries_per_s"] / max(serial["queries_per_s"], 1e-9)
    return dict(metric=f"light_serve_{n_clients}c_queries_per_s",
                value=round(gw_on["queries_per_s"], 1), unit="queries/s",
                vs_baseline=round(speedup, 2),
                speedup_vs_serial=round(speedup, 2),
                serial_queries_per_s=round(serial["queries_per_s"], 1),
                p99_serve_ms=gw_on["p99_ms"],
                p99_serve_ms_serial=serial["p99_ms"],
                service_off_queries_per_s=round(gw_off["queries_per_s"], 1),
                service_stats=dict(launches=gw_on["launches"],
                                   requests=gw_on["requests"],
                                   launches_serial=serial["launches"]),
                clients=n_clients, headers=n_headers, gen_s=round(gen_s, 1))


def config_mempool_ingest(rr):
    """ISSUE 12 acceptance: sustained front-door txs/s and p99 admission
    latency, micro-batched coalescer vs the TMTPU_INGEST=0 serial baseline,
    against a SOCKET ABCI app — each serial CheckTx pays a real round trip
    (the cost the batched RequestCheckTxBatch amortizes), exactly the shape
    of a production out-of-process app. Batch-rich load: N submitter
    threads hammering ingest_tx concurrently."""
    import threading

    from tendermint_tpu.abci import types as abci_types
    from tendermint_tpu.abci.client import ABCISocketClient
    from tendermint_tpu.abci.server import ABCIServer
    from tendermint_tpu.mempool.mempool import Mempool

    import hashlib

    n_threads = int(os.environ.get("BENCH_INGEST_THREADS", 16))
    n_txs = int(os.environ.get("BENCH_INGEST_TXS", 6000))
    per_thread = n_txs // n_threads

    class PricedApp(abci_types.Application):
        """A state-bearing app with realistic per-CALL admission cost: every
        CheckTx call opens a state context (modeled as hashing the app's
        state blob — real apps branch the store and build a gas meter per
        call), then prices each tx. Its NATIVE check_tx_batch opens ONE
        context per batch — exactly the amortization the batched ABCI seam
        exists to unlock (docs/INGEST.md)."""

        STATE = b"\x5a" * (256 * 1024)

        def _open_context(self) -> None:
            hashlib.sha256(self.STATE).digest()

        def _price(self, tx: bytes) -> abci_types.ResponseCheckTx:
            # priority from the tx tail: the v1 lanes stay exercised
            return abci_types.ResponseCheckTx(
                code=0, gas_wanted=1, priority=tx[-1] if tx else 0)

        def check_tx(self, req):
            self._open_context()
            return self._price(req.tx)

        def check_tx_batch(self, req):
            self._open_context()
            return abci_types.ResponseCheckTxBatch(
                responses=[self._price(tx) for tx in req.txs])

    server = ABCIServer(PricedApp(), "tcp://127.0.0.1:0")
    server.start()

    def measure(batched: bool) -> dict:
        prev = os.environ.get("TMTPU_INGEST")
        os.environ["TMTPU_INGEST"] = "1" if batched else "0"
        app = ABCISocketClient(server.addr)
        mp = Mempool(app, version="v1", max_txs=2 * n_txs,
                     cache_size=4 * n_txs)
        lat: list[list[float]] = [[] for _ in range(n_threads)]
        errors = []

        def worker(t):
            try:
                for i in range(per_thread):
                    tx = b"ingest-%d-%d=" % (t, i) + bytes([(t + i) % 251 + 1])
                    t0 = time.monotonic()
                    res = mp.ingest_tx(tx)
                    lat[t].append(time.monotonic() - t0)
                    assert res.is_ok()
            except Exception as e:  # noqa: BLE001 - surfaced after join
                errors.append((t, e))

        try:
            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(n_threads)]
            t0 = time.monotonic()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.monotonic() - t0
            if errors:
                raise RuntimeError(f"mempool_ingest worker failed: {errors}")
            alllat = sorted(x for ts in lat for x in ts)
            co = mp._ingest
            return dict(
                txs_per_s=len(alllat) / wall,
                p50_ms=alllat[len(alllat) // 2] * 1e3,
                p99_ms=alllat[int(0.99 * (len(alllat) - 1))] * 1e3,
                batches=co.batches, coalesced_txs=co.coalesced_txs,
                max_coalesced=co.max_coalesced)
        finally:
            app.close()
            if prev is None:
                os.environ.pop("TMTPU_INGEST", None)
            else:
                os.environ["TMTPU_INGEST"] = prev

    try:
        measure(True)  # warm sockets/allocator for both routings
        on = measure(True)
        off = measure(False)
    finally:
        server.stop()
    speedup = on["txs_per_s"] / max(off["txs_per_s"], 1e-9)
    return dict(metric="mempool_ingest_sustained_txs_per_s",
                value=round(on["txs_per_s"], 1),
                unit="txs/s",
                vs_baseline=round(speedup, 2),
                speedup_vs_serial=round(speedup, 2),
                serial_txs_per_s=round(off["txs_per_s"], 1),
                p99_admission_ms_batched=round(on["p99_ms"], 2),
                p99_admission_ms_serial=round(off["p99_ms"], 2),
                p50_admission_ms_batched=round(on["p50_ms"], 2),
                ingest_stats=dict(batches=on["batches"],
                                  coalesced_txs=on["coalesced_txs"],
                                  max_coalesced=on["max_coalesced"]),
                threads=n_threads, txs=n_txs)


def config_chain_throughput(rr):
    """ISSUE 17: end-to-end chain throughput (blocks/s) at 1000 mixed
    validators with FULL blocks, replayed through the verify-ahead
    pipeline against a socket-backed kvstore app — the batched execution
    plane (DeliverTxBatch: one ABCI wire round trip per
    TMTPU_DELIVER_MAX_BATCH chunk) vs TMTPU_DELIVER=0 (one round trip per
    tx, the old serial loop). Both modes must converge to the same replay
    app hash; the serial run is the config's own baseline
    (speedup_vs_serial)."""
    from tendermint_tpu.abci.client import ABCISocketClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.abci.server import ABCIServer
    from tendermint_tpu.blockchain import pipeline as bpipe
    from tendermint_tpu.blockchain.replay import ReplayCtx, make_chain

    n_blocks = int(os.environ.get("BENCH_CHAIN_BLOCKS", 6))
    txs_per_block = int(os.environ.get("BENCH_CHAIN_TXS", 512))
    t0 = time.monotonic()
    privs, vals = _mk_valset(700, 300)
    blocks = make_chain(
        BENCH_CHAIN, n_blocks + 1, vals, privs,
        txs_for=lambda h: [b"c%d-%d=%d" % (h, i, (h * 131 + i) % 9973)
                           for i in range(txs_per_block)])
    gen_s = time.monotonic() - t0

    def run(batched: bool) -> bytes:
        """One full replay: fresh app + socket per run so both modes
        apply the identical chain from genesis state."""
        prev = os.environ.get("TMTPU_DELIVER")
        os.environ["TMTPU_DELIVER"] = "1" if batched else "0"
        server = ABCIServer(KVStoreApplication(), "tcp://127.0.0.1:0")
        server.start()
        cli = None
        try:
            cli = ABCISocketClient(server.addr)
            ctx = ReplayCtx(vals, BENCH_CHAIN, app=cli)
            for i, b in enumerate(blocks):
                ctx.pool.add_block("pA" if i % 2 == 0 else "pB", b)
            pipe = bpipe.VerifyAheadPipeline()
            while pipe.process_next(ctx):
                pass
            assert not ctx.punished and len(ctx.applied) == n_blocks, (
                ctx.punished, ctx.applied)
            return ctx.app_hash
        finally:
            if cli is not None:
                cli.close()
            server.stop()
            if prev is None:
                os.environ.pop("TMTPU_DELIVER", None)
            else:
                os.environ["TMTPU_DELIVER"] = prev

    # Correctness gate (also warms kernels/keysets/allocator for both
    # modes): identical replay app hash batched vs serial.
    hb, hs = run(True), run(False)
    assert hb == hs, "batched replay app hash != serial"

    vb, detail = rr.run(lambda: run(True), iters=2, rounds=2, report="min")
    vs, _ = rr.run(lambda: run(False), iters=2, rounds=2, report="min")
    bps_b = n_blocks / (vb / 1e3)
    bps_s = n_blocks / (vs / 1e3)
    # serial CPU anchor: one core verifying the block's +2/3 light prefix
    # PLUS one socket round trip per tx (measured by the serial mode) —
    # vs_baseline for this config IS the speedup over that serial loop.
    speedup = bps_b / max(bps_s, 1e-9)
    return dict(metric=f"chain_throughput_1000v_{txs_per_block}tx_blocks_per_s",
                value=round(bps_b, 2), unit="blocks/s",
                vs_baseline=round(speedup, 2),
                speedup_vs_serial=round(speedup, 2),
                serial_blocks_per_s=round(bps_s, 2),
                txs_per_block=txs_per_block, n_blocks=n_blocks,
                gen_s=round(gen_s, 1), **detail)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tendermint_tpu.ops import ed25519_batch

    _log(f"# backend={jax.default_backend()} devices={len(jax.devices())} "
         f"loadavg={os.getloadavg()}")

    # Measure the host/kernel crossover BEFORE timing anything: the adaptive
    # routing (VERDICT r4 item 1a) is part of what the bench measures.
    cross = ed25519_batch.calibrate_host_crossover()
    cal = ed25519_batch._HOST_CAL
    _log(f"# crossover={cross} sigs (floor={cal['floor_ms']}ms host_rlc="
         f"{None if cal['host_us'] is None else round(cal['host_us'], 1)}us/sig)")

    t0 = time.monotonic()
    items = _gen_flat_commit(N_SIGS)
    gen_s = time.monotonic() - t0

    t0 = time.monotonic()
    out = ed25519_batch.verify_batch(items)
    warm_s = time.monotonic() - t0
    assert out.all(), "benchmark signatures must all verify"

    # Sync-latency floor of this host<->device link (trivial op + readback).
    tiny = jax.jit(lambda a: a * 2)
    np.asarray(tiny(jnp.ones((1,), jnp.int32)))
    floor_ms = min(
        _measure(lambda: np.asarray(tiny(jnp.ones((1,), jnp.int32))), 7)) * 1e3

    rr = Rounds()

    # Headline: the north-star 20,480-sig commit.
    headline, hdetail = rr.run(lambda: ed25519_batch.verify_batch(items))

    # Phase attribution (ISSUE 10): a separate instrumented pass so the
    # extra device sync never lands inside a timed round. This is the
    # measured target the ROADMAP-1 continuous-batching work shrinks.
    attribution = _phase_attribution(items, headline)

    # Marginal cost with the fixed floor removed: (p50(N) - p50(N/4)) over
    # the extra signatures, both min-of-rounds. A quarter batch rides the
    # same sync floor, so the difference is pure per-signature cost.
    quarter = items[: len(items) // 4]
    ed25519_batch.verify_batch(quarter)  # build the subset keyset once
    tq, _ = rr.run(lambda: ed25519_batch.verify_batch(quarter),
                   iters=max(ITERS - 2, 3), rounds=2)
    marginal_us = max(headline - tq, 0.001) * 1e3 / (len(items) - len(quarter))

    # Host-prep decomposition (what still fights the 1 core per call).
    ks, key_idx, pub_ok = ed25519_batch.get_keyset([it[0] for it in items])
    pub_ok = pub_ok & ks.valid[key_idx]
    tprep = min(_measure(
        lambda: ed25519_batch.prepare_scalars(items, pub_ok, windows=False,
                                              reduce=False), 3)) * 1e3

    configs = {}
    for name, fn, args in (
        ("batch64", config_batch64, (rr, items[:64])),
        ("commit150", config_commit150, (rr,)),
        ("range_verify", config_range_verify, (rr,)),
        ("mixed_commit", config_mixed_commit, (rr,)),
        ("fastsync", config_fastsync, (rr,)),
        ("sr25519", config_sr25519, (rr,)),
        ("addvote", config_addvote, (rr,)),
        ("concurrent_verify", config_concurrent_verify, (rr,)),
        ("light_serve", config_light_serve, (rr,)),
        ("mempool_ingest", config_mempool_ingest, (rr,)),
        ("chain_throughput", config_chain_throughput, (rr,)),
        ("sharded", config_sharded, (rr, items)),
    ):
        try:
            configs[name] = fn(*args)
            _log(f"# {name}: {json.dumps(configs[name])}")
        except Exception as e:  # noqa: BLE001 - one config must not kill the run
            configs[name] = dict(error=str(e))
            _log(f"# {name}: FAILED {e}")

    baseline_ms = BASELINE_US_PER_SIG * len(items) / 1000.0
    result = {
        "metric": "ed25519_commit_verify_%d_sigs_p50" % len(items),
        "value": round(headline, 3),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / headline, 2),
        "sync_floor_ms": round(floor_ms, 1),
        "marginal_us_per_sig": round(marginal_us, 2),
        "host_prep_ms": round(tprep, 1),
        "spread": hdetail["spread"],
        "phase_attribution": attribution,
        "configs": {k: {kk: vv for kk, vv in v.items()
                        if kk in ("metric", "value", "unit", "vs_baseline",
                                  "spread", "error", "depth1_blocks_per_s",
                                  "speedup_vs_depth1", "skipped", "devices",
                                  "single_device_marginal_us",
                                  "speedup_vs_single", "phase_attribution",
                                  "trace_overhead_pct",
                                  "speedup_vs_service_off",
                                  "service_off_decisions_per_s",
                                  "per_path_p50_ms_on",
                                  "per_path_p50_ms_off",
                                  "phase_attribution_on",
                                  "phase_attribution_off",
                                  "service_stats",
                                  "speedup_vs_serial",
                                  "serial_queries_per_s",
                                  "p99_serve_ms",
                                  "p99_serve_ms_serial",
                                  "service_off_queries_per_s",
                                  "serial_txs_per_s",
                                  "serial_blocks_per_s",
                                  "txs_per_block",
                                  "p99_admission_ms_batched",
                                  "p99_admission_ms_serial",
                                  "p50_admission_ms_batched",
                                  "ingest_stats")}
                    for k, v in configs.items()},
    }
    print(json.dumps(result))
    _log(f"# headline: rounds={hdetail['rounds_ms']}ms "
         f"spread={hdetail['spread']}x spins={hdetail['spins_ms']}ms "
         f"retries={hdetail['retries']}")
    _log(f"# phase_attribution: {json.dumps(attribution)}")
    _log(f"# gen={gen_s:.1f}s warmup={warm_s:.1f}s sync_floor={floor_ms:.1f}ms "
         f"(fixed host<->device round-trip of this link, paid once per "
         f"decision) host_prep={tprep:.1f}ms "
         f"({tprep * 1e3 / len(items):.2f}us/sig; SHA-512 in C + byte "
         f"packing; mod-L + windows now on device) "
         f"marginal={marginal_us:.2f}us/sig p50_quarter={tq:.1f}ms "
         f"({1.0 / marginal_us:.2f}M sigs/s marginal) "
         f"baseline={baseline_ms:.0f}ms")


if __name__ == "__main__":
    main()
