"""secp256k1 + sr25519 key types: spec vectors for every layer of the
from-scratch stacks (keccak/SHA3 cross-check, merlin transcript vector,
ristretto255 RFC 9496 vectors), sign/verify round-trips, registry routing,
and mixed-key commit verification (BASELINE config 4 shape)."""

import hashlib

import pytest

from tendermint_tpu.crypto import ed25519, keys, secp256k1, sr25519


# --- keccak-f1600 cross-checked via SHA3-256 against hashlib ----------------

def _sha3_256(data: bytes) -> bytes:
    rate = 136
    st = bytearray(200)
    padded = bytearray(data)
    padded.append(0x06)
    while len(padded) % rate != 0:
        padded.append(0)
    padded[-1] |= 0x80
    for off in range(0, len(padded), rate):
        for i in range(rate):
            st[i] ^= padded[off + i]
        sr25519.keccak_f1600(st)
    return bytes(st[:32])


def test_keccak_f1600_against_hashlib_sha3():
    for msg in (b"", b"abc", b"x" * 135, b"y" * 136, b"z" * 1000):
        assert _sha3_256(msg) == hashlib.sha3_256(msg).digest()


# --- merlin transcript (vector from merlin's own test suite) ----------------

def test_merlin_transcript_vector():
    t = sr25519.Transcript(b"test protocol")
    t.append_message(b"some label", b"some data")
    cb = t.challenge_bytes(b"challenge", 32)
    assert cb.hex() == \
        "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"


# --- ristretto255 (RFC 9496 appendix A vectors) ------------------------------

RISTRETTO_BASE_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
]

BAD_RISTRETTO = [
    # non-canonical field element
    "ecffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    # negative field element
    "0100000000000000000000000000000000000000000000000000000000000080",
    # non-square x^2
    "26948d35ca62e643e26a83177332e6b6afeb9d08e4268b650f1f5bbd8d81d371",
]


def test_ristretto_base_multiples():
    acc = (0, 1, 1, 0)  # identity in extended coords
    base = ed25519.BASE
    for i, want in enumerate(RISTRETTO_BASE_MULTIPLES):
        got = sr25519.ristretto_encode(acc)
        assert got.hex() == want, f"multiple {i}"
        # decode round-trips to an equal point
        dec = sr25519.ristretto_decode(got)
        assert dec is not None and sr25519.ristretto_eq(dec, acc)
        acc = sr25519._pt_add(acc, base)


def test_ristretto_bad_encodings_rejected():
    for bad in BAD_RISTRETTO:
        assert sr25519.ristretto_decode(bytes.fromhex(bad)) is None


# --- sr25519 sign/verify ------------------------------------------------------

def test_sr25519_sign_verify_roundtrip():
    priv = sr25519.gen_priv_key(b"sr-test-seed")
    pub = priv.pub_key()
    assert len(pub.bytes()) == 32 and len(pub.address()) == 20
    msg = b"the quick brown fox"
    sig = priv.sign(msg)
    assert len(sig) == 64 and sig[63] & 128
    assert pub.verify_signature(msg, sig)
    # randomized signing: two signatures differ, both verify
    sig2 = priv.sign(msg)
    assert sig2 != sig and pub.verify_signature(msg, sig2)
    # tamper rejection
    assert not pub.verify_signature(msg + b"!", sig)
    bad = sig[:-1] + bytes([sig[-1] ^ 1])
    assert not pub.verify_signature(msg, bad)
    assert not pub.verify_signature(msg, sig[:63])
    # unmarked signature rejected (schnorrkel marker bit)
    unmarked = sig[:63] + bytes([sig[63] & 127])
    assert not pub.verify_signature(msg, unmarked)
    # wrong key rejected
    other = sr25519.gen_priv_key(b"other").pub_key()
    assert not other.verify_signature(msg, sig)


def test_sr25519_deterministic_with_seeded_rng():
    mini = hashlib.sha256(b"det").digest()
    s1 = sr25519.sign(mini, b"m", rng_seed=b"\x00" * 32)
    s2 = sr25519.sign(mini, b"m", rng_seed=b"\x00" * 32)
    assert s1 == s2
    assert sr25519.verify(sr25519.pubkey_from_mini(mini), b"m", s1)


# --- secp256k1 ---------------------------------------------------------------

def test_secp256k1_sign_verify_roundtrip():
    priv = secp256k1.gen_priv_key(b"secp-test-seed")
    pub = priv.pub_key()
    assert len(pub.bytes()) == 33 and pub.bytes()[0] in (2, 3)
    assert len(pub.address()) == 20
    msg = b"pay to the order of"
    sig = priv.sign(msg)
    assert len(sig) == 64
    assert pub.verify_signature(msg, sig)
    # deterministic RFC 6979: same msg -> same sig
    assert priv.sign(msg) == sig
    # low-S enforced: the complement is rejected
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    assert s <= secp256k1.HALF_N
    high = r.to_bytes(32, "big") + (secp256k1.N - s).to_bytes(32, "big")
    assert not pub.verify_signature(msg, high)
    assert not pub.verify_signature(msg + b"!", sig)
    assert not pub.verify_signature(msg, sig[:-1] + bytes([sig[-1] ^ 1]))


def test_secp256k1_known_curve_identity():
    # n*G = infinity; (n-1)*G = -G
    assert secp256k1._to_affine(secp256k1._jac_mul(secp256k1.N, secp256k1._G)) is None
    m = secp256k1._to_affine(secp256k1._jac_mul(secp256k1.N - 1, secp256k1._G))
    assert m == (secp256k1.GX, secp256k1.P - secp256k1.GY)


# --- registry + mixed batch verification -------------------------------------

def test_registry_roundtrip_all_types():
    for mod, name in ((ed25519, "ed25519"), (sr25519, "sr25519"),
                      (secp256k1, "secp256k1")):
        priv = mod.gen_priv_key(b"registry-seed-0123456789abcdef##")
        pub = keys.pubkey_from_type_bytes(name, priv.pub_key().bytes())
        assert pub.type == name
        sig = priv.sign(b"reg")
        assert pub.verify_signature(b"reg", sig)
        priv2 = keys.privkey_from_type_bytes(name, priv.bytes())
        assert priv2.pub_key().bytes() == pub.bytes()


def test_mixed_batch_verifier_routes_by_type():
    """BASELINE config 4 shape: a commit with mixed ed25519/sr25519/secp256k1
    signers batches the ed25519 majority and scalar-verifies the rest, with
    order-preserving results."""
    from tendermint_tpu.crypto import batch as crypto_batch

    items = []
    expect = []
    for i in range(30):
        if i % 5 == 3:
            priv = sr25519.gen_priv_key(bytes([i]) * 8)
        elif i % 5 == 4:
            priv = secp256k1.gen_priv_key(bytes([i]) * 8)
        else:
            priv = ed25519.gen_priv_key(bytes([i + 1]) * 32)
        msg = b"mixed%d" % i
        sig = priv.sign(msg)
        if i % 7 == 0:
            sig = sig[:-2] + bytes([sig[-2] ^ 1]) + sig[-1:]
            expect.append(False)
        else:
            expect.append(True)
        items.append((priv.pub_key(), msg, sig))

    v = crypto_batch.create_batch_verifier()
    for pub, msg, sig in items:
        v.add(pub, msg, sig)
    all_ok, bitmap = v.verify()
    assert bitmap == expect
    assert all_ok == all(expect)


def test_mixed_key_validator_set_commit_verify():
    """BASELINE config 4 shape at the types layer: a validator set mixing
    ed25519 (batched) and secp256k1 (scalar fallback) keys verifies commits
    through the MixedBatchVerifier with exact accept/reject attribution.
    (sr25519 is sign-layer only: the v0.34 PublicKey proto has no sr25519
    field -- reference proto/tendermint/crypto/keys.proto:13-16.)"""
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import (
        ErrWrongSignature,
        ValidatorSet,
    )
    from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote

    chain_id = "mixed-chain"
    pairs = []
    for i in range(6):
        if i % 3 == 2:
            priv = secp256k1.gen_priv_key(bytes([i + 1]) * 32)
        else:
            priv = ed25519.gen_priv_key(bytes([i + 1]) * 32)
        pairs.append((priv, Validator.new(priv.pub_key(), 10)))
    vs = ValidatorSet([v for _, v in pairs])
    by_addr = {v.address: p for p, v in pairs}
    privs = [by_addr[v.address] for v in vs.validators]

    # wire round-trip keeps both key types
    vs2 = ValidatorSet.unmarshal(vs.marshal())
    assert [v.pub_key.type for v in vs2.validators] == \
        [v.pub_key.type for v in vs.validators]

    bid = BlockID(hash=b"\xa1" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\xb1" * 32))
    sigs = []
    for i, (priv, val) in enumerate(zip(privs, vs.validators)):
        ts = Time(1700000500 + i, 0)
        vote = Vote(type=PRECOMMIT_TYPE, height=9, round=0, block_id=bid,
                    timestamp=ts, validator_address=val.address,
                    validator_index=i)
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address, ts,
                              priv.sign(vote.sign_bytes(chain_id))))
    commit = Commit(height=9, round=0, block_id=bid, signatures=sigs)
    vs.verify_commit(chain_id, bid, 9, commit)
    vs.verify_commit_light(chain_id, bid, 9, commit)
    vs.verify_commit_light_trusting(chain_id, commit, (1, 3))

    # corrupt a secp256k1 signature: exact index attribution survives mixing
    secp_idx = next(i for i, v in enumerate(vs.validators)
                    if v.pub_key.type == "secp256k1")
    bad = sigs[secp_idx].signature
    sigs[secp_idx] = CommitSig(BLOCK_ID_FLAG_COMMIT,
                               vs.validators[secp_idx].address,
                               sigs[secp_idx].timestamp,
                               bad[:-1] + bytes([bad[-1] ^ 1]))
    commit2 = Commit(height=9, round=0, block_id=bid, signatures=sigs)
    import pytest
    with pytest.raises(ErrWrongSignature) as ei:
        vs.verify_commit(chain_id, bid, 9, commit2)
    assert ei.value.index == secp_idx
