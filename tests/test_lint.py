"""tmlint (tools/tmlint) + the runtime lock-order witness
(utils/lockwitness.py): the static-analysis gate itself.

Three layers:

1. **The tier-1 gate**: the whole tree must lint clean — zero
   non-baselined findings from >= 8 active rules, in seconds (pure AST,
   no jax import). This is what turns every one-off review catch the
   rules encode into a permanently enforced invariant.
2. **Analyzer self-tests**: for each rule, fixture snippets that MUST
   trigger and MUST NOT trigger it; pragma + baseline handling; two runs
   produce byte-identical output.
3. **Witness unit tests**: the instrumented Lock/RLock records real
   acquisition-order cycles (two threads, opposite order), stays quiet on
   reentrant RLocks and Condition.wait, and bounds its own bookkeeping.
   (The two in-process mesh scenarios run under the witness in
   test_nemesis.py / test_overload.py.)
"""

import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.tmlint import checks  # noqa: E402,F401
from tools.tmlint import core  # noqa: E402
from tendermint_tpu.utils import lockwitness  # noqa: E402

pytestmark = pytest.mark.quick

# Knob-like tokens for fixtures are spliced so the repo-wide parity scan
# of THIS file's string constants never sees a fake knob.
_PFX = "TM_TPU_"
_CPFX = "TMTPU_"


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _project(tmp_path, files: dict, side: dict | None = None):
    for rel, content in {**files, **(side or {})}.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    roots = sorted({rel.split("/")[0] for rel in files})
    return core.Project(str(tmp_path),
                        core.collect_files(str(tmp_path), roots))


def _run(tmp_path, files, rules, side=None):
    return core.run_rules(_project(tmp_path, files, side), rules)


def _rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# 1. the tier-1 gate
# ---------------------------------------------------------------------------


def test_rule_registry_has_the_contracted_set():
    assert len(core.RULES) >= 9
    assert set(core.RULES) >= {
        "lock-held-call", "lock-order", "device-sync-choke-point",
        "thread-crash-surface", "daemon-or-joined", "metrics-discipline",
        "fault-site-registry", "trace-span-discipline", "config-knob-parity",
    }


def test_whole_tree_lints_clean_fast():
    """THE gate: zero non-baselined findings over the default scan set.
    A new finding means either fix the code or (rarely, with a review
    reason) pragma/baseline it — never ignore it."""
    t0 = time.monotonic()
    project = core.Project(
        REPO, core.collect_files(REPO, core.DEFAULT_PATHS))
    findings = core.run_rules(project)
    elapsed = time.monotonic() - t0
    new, baselined = core.split_baselined(findings, core.load_baseline())
    assert not new, (
        "tmlint found new violations (fix them, or pragma/baseline with "
        "a reason):\n" + "\n".join(f.render() for f in new))
    # the baseline is a grandfather list, not a dumping ground
    assert len(baselined) <= 10, (
        f"baseline has grown to {len(baselined)} entries — fix some")
    # pure-AST speed: the gate must stay ~free inside the tier-1 budget
    assert elapsed < 30, f"lint pass took {elapsed:.1f}s (budget blown)"


def test_cli_acceptance_command_exits_zero():
    """The documented invocation (docs/LINT.md, docs/QA.md):
    `python -m tools.tmlint tendermint_tpu tests` — subprocess-level so
    the CLI wiring itself is pinned, and timed (<~10 s acceptance)."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.tmlint", "tendermint_tpu", "tests"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, (
        f"lint CLI failed ({elapsed:.1f}s):\n{proc.stdout}\n{proc.stderr}")
    assert elapsed < 60, f"CLI lint took {elapsed:.1f}s"


def test_two_runs_identical_output():
    """Determinism: rules iterate sorted structures only, so two fresh
    scans of the same tree render byte-identically."""
    def one():
        project = core.Project(
            REPO, core.collect_files(REPO, ["tendermint_tpu"]))
        return [f.render() for f in core.run_rules(project)]

    assert one() == one()


# ---------------------------------------------------------------------------
# 2. per-rule fixtures: must-trigger / must-not-trigger
# ---------------------------------------------------------------------------


def test_lock_held_call_triggers_and_not(tmp_path):
    files = {"tendermint_tpu/m.py": (
        "import threading\n"
        "import time\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._mtx = threading.Lock()\n"
        "    def bad(self):\n"
        "        with self._mtx:\n"
        "            time.sleep(1)\n"
        "    def good(self):\n"
        "        with self._mtx:\n"
        "            x = 1\n"
        "        time.sleep(0)\n"
        "        return x\n"
        "    def cb_bad(self, on_ban):\n"
        "        with self._mtx:\n"
        "            on_ban('p')\n"
    )}
    fs = _run(tmp_path, files, ["lock-held-call"])
    lines = sorted(f.line for f in fs)
    assert lines == [8, 16], [f.render() for f in fs]


def test_lock_order_cycle_and_self_deadlock(tmp_path):
    files = {"tendermint_tpu/m.py": (
        "import threading\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._amtx = threading.Lock()\n"
        "    def one(self, b):\n"
        "        with self._amtx:\n"
        "            b.btake()\n"
        "    def atake(self):\n"
        "        with self._amtx:\n"
        "            pass\n"
        "    def re(self):\n"
        "        with self._amtx:\n"
        "            self.atake()\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._bmtx = threading.Lock()\n"
        "    def btake(self):\n"
        "        with self._bmtx:\n"
        "            pass\n"
        "    def two(self, a):\n"
        "        with self._bmtx:\n"
        "            a.atake()\n"
    )}
    fs = _run(tmp_path, files, ["lock-order"])
    msgs = [f.message for f in fs]
    assert any("cycle" in m and "m.A._amtx" in m and "m.B._bmtx" in m
               for m in msgs), msgs
    assert any("non-reentrant" in m for m in msgs), msgs
    # RLock re-acquire via self-call is NOT a self-deadlock
    files2 = {"tendermint_tpu/m.py": files["tendermint_tpu/m.py"].replace(
        "threading.Lock()", "threading.RLock()")}
    fs2 = _run(tmp_path / "b", files2, ["lock-order"])
    assert not any("non-reentrant" in f.message for f in fs2)


def test_device_sync_choke_point_scoping(tmp_path):
    bad = {"tendermint_tpu/consensus/x.py":
           "import jax\n\ndef f(d):\n    return jax.device_get(d)\n"}
    ok_ops = {"tendermint_tpu/ops/k.py":
              "import jax\n\ndef f(d):\n    return jax.device_get(d)\n"}
    choke = {"tendermint_tpu/crypto/batch.py": (
        "import jax\n"
        "def _device_get(tree):\n"
        "    return jax.device_get(tree)\n"
        "def leak(tree):\n"
        "    return jax.device_get(tree)\n"
    )}
    assert _rules_of(_run(tmp_path / "a", bad, ["device-sync-choke-point"]))
    assert not _run(tmp_path / "b", ok_ops, ["device-sync-choke-point"])
    fs = _run(tmp_path / "c", choke, ["device-sync-choke-point"])
    assert [f.line for f in fs] == [5], [f.render() for f in fs]


def test_thread_crash_surface_and_daemon_rules(tmp_path):
    files = {"tendermint_tpu/m.py": (
        "import threading\n"
        "def naked():\n"
        "    x = 1\n"
        "def shielded():\n"
        "    try:\n"
        "        x = 1\n"
        "    except Exception:\n"
        "        pass\n"
        "def loop_shielded():\n"
        "    while True:\n"
        "        try:\n"
        "            x = 1\n"
        "        except Exception:\n"
        "            pass\n"
        "def spawn_all():\n"
        "    threading.Thread(target=naked).start()\n"
        "    threading.Thread(target=shielded, daemon=True).start()\n"
        "    threading.Thread(target=loop_shielded, daemon=True).start()\n"
        "    t = threading.Thread(target=shielded)\n"
        "    t.daemon = True\n"
        "    t.start()\n"
        "class S:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n"
        "    def stop(self):\n"
        "        self._t.join()\n"
        "    def _run(self):\n"
        "        try:\n"
        "            pass\n"
        "        except Exception:\n"
        "            pass\n"
    )}
    crash = _run(tmp_path, files, ["thread-crash-surface"])
    assert [f.line for f in crash] == [16], [f.render() for f in crash]
    daemon = _run(tmp_path, files, ["daemon-or-joined"])
    # line 16: naked() spawn is fire-and-forget without daemon; the
    # S._t thread is joined in stop() so only line 16 flags
    assert [f.line for f in daemon] == [16], [f.render() for f in daemon]


def test_metrics_discipline_fixture(tmp_path):
    files = {"tendermint_tpu/m.py": (
        "class M:\n"
        "    def __init__(self, r):\n"
        "        self.good = r.counter('s', 'a', '', labels=('x',))\n"
        "        self.bad = r.counter('s', 'b', '', labels=('x',))\n"
        "        self.plain = r.counter('s', 'c', '')\n"
        "        self.removed = r.gauge('s', 'd', '', labels=('p',))\n"
        "        self.good.add(0.0, x='k')\n"
        "    def gone(self, p):\n"
        "        self.removed.remove(p=p)\n"
    )}
    fs = _run(tmp_path, files, ["metrics-discipline"])
    assert [f.line for f in fs] == [4], [f.render() for f in fs]


_FAULTS_FIXTURE = (
    "CANONICAL_SITES: dict = {\n"
    "    'wal.write': 'x',\n"
    "    'p2p.send': 'y',\n"
    "}\n"
    "def fire(site):\n"
    "    pass\n"
)


def test_fault_site_registry_fixture(tmp_path):
    files = {
        "tendermint_tpu/utils/faults.py": _FAULTS_FIXTURE,
        "tendermint_tpu/m.py": (
            "from tendermint_tpu.utils import faults\n"
            "def f():\n"
            "    faults.fire('wal.write')\n"
            "    faults.fire('p2p.made_up')\n"
        ),
    }
    side = {"docs/FAULTS.md": "`wal.write` and `p2p.send` exist; "
                              "`p2p.stale_doc_site` does not\n"}
    fs = _run(tmp_path, files, ["fault-site-registry"], side)
    msgs = sorted(f.message for f in fs)
    assert len(fs) == 2, [f.render() for f in fs]
    assert "p2p.made_up" in msgs[0] or "p2p.made_up" in msgs[1]
    assert any("stale_doc_site" in m for m in msgs)


_TRACE_FIXTURE = (
    "CANONICAL_SPANS = {\n"
    "    'consensus.commit': 'entered commit',\n"
    "    'verify.readback': 'blocking D2H fetch',\n"
    "}\n"
)


def test_trace_span_discipline_fixture(tmp_path):
    """must-trigger: an undeclared span literal, an undocumented
    canonical span, a stale doc token; must-not: a declared+documented
    span, a non-dotted literal (peerscore offences etc.), a foreign
    namespace in the doc."""
    files = {
        "tendermint_tpu/utils/trace.py": _TRACE_FIXTURE,
        "tendermint_tpu/m.py": (
            "def f(tr, board):\n"
            "    tr.mark('consensus.commit')\n"
            "    with tr.span('verify.made_up'):\n"
            "        pass\n"
            "    tr.record('verify.queue_typo', 1.0)\n"
            "    board.record('peerid', 'invalid_signature')\n"
        ),
    }
    side = {"docs/OBSERVABILITY.md": (
        "`consensus.commit` is documented; `verify.stale_doc_span` is "
        "stale; `other.namespace` is foreign\n")}
    fs = _run(tmp_path, files, ["trace-span-discipline"], side)
    msgs = sorted(f.message for f in fs)
    assert len(fs) == 4, [f.render() for f in fs]
    assert any("verify.made_up" in m for m in msgs)
    assert any("verify.queue_typo" in m for m in msgs)
    assert any("verify.readback" in m and "not documented" in m
               for m in msgs)
    assert any("stale_doc_span" in m for m in msgs)
    assert not any("invalid_signature" in m or "other.namespace" in m
                   for m in msgs)


def test_config_knob_parity_fixture(tmp_path):
    undoc = _PFX + "FIXTURE_UNDOC"
    ghost = _CPFX + "FIXTURE_GHOST"
    documented = _PFX + "FIXTURE_OK"
    files = {"tendermint_tpu/m.py": (
        "import os\n"
        f"A = os.environ.get('{documented}')\n"
        f"B = os.environ.get('{undoc}')\n"
    )}
    side = {"docs/CONFIG.md": f"| `{documented}` | ok |\n| `{ghost}` | gone |\n"}
    fs = _run(tmp_path, files, ["config-knob-parity"], side)
    assert len(fs) == 2, [f.render() for f in fs]
    assert any(undoc in f.message and f.path.endswith("m.py") for f in fs)
    assert any(ghost in f.message and f.path.endswith("CONFIG.md")
               for f in fs)


def test_knob_parity_stale_doc_needs_full_default_scope(tmp_path):
    """A subset scan (e.g. `tmlint tendermint_tpu tests`) cannot see a
    knob read only in bench.py, so the doc->code 'stale doc' direction
    must stay quiet there — and still fire on a full-scope scan."""
    knob = _PFX + "BENCH_ONLY"
    for rel, content in {
        "tendermint_tpu/m.py": "x = 1\n",
        "bench.py": f"import os\nB = os.environ.get('{knob}')\n",
        "docs/CONFIG.md": f"| `{knob}` | bench knob |\n",
    }.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    sub = core.Project(str(tmp_path),
                       core.collect_files(str(tmp_path), ["tendermint_tpu"]))
    assert not core.run_rules(sub, ["config-knob-parity"])
    full = core.Project(
        str(tmp_path),
        core.collect_files(str(tmp_path), ["tendermint_tpu", "bench.py"]))
    # full scope sees the bench.py read, so parity holds cleanly too
    assert not core.run_rules(full, ["config-knob-parity"])
    # ...and a genuinely stale doc entry IS reported at full scope
    (tmp_path / "bench.py").write_text("x = 1\n")
    full2 = core.Project(
        str(tmp_path),
        core.collect_files(str(tmp_path), ["tendermint_tpu", "bench.py"]))
    fs = core.run_rules(full2, ["config-knob-parity"])
    assert any("stale doc" in f.message for f in fs), [f.render() for f in fs]


def test_pragma_inside_string_literal_is_inert(tmp_path):
    """Only real comments are pragmas: a pragma-shaped STRING (a fixture,
    a doc snippet) must not register a suppression."""
    files = {"tendermint_tpu/m.py": (
        "import threading\n"
        "import time\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._mtx = threading.Lock()\n"
        "    def bad(self):\n"
        "        with self._mtx:\n"
        "            x = '# tmlint: disable-file=lock-held-call'\n"
        "            time.sleep(1)\n"
        "            return x\n"
    )}
    fs = _run(tmp_path, files, ["lock-held-call"])
    assert [f.line for f in fs] == [9], [f.render() for f in fs]


def test_parse_error_is_a_finding(tmp_path):
    fs = _run(tmp_path, {"tendermint_tpu/m.py": "def broken(:\n"},
              ["lock-held-call"])
    assert _rules_of(fs) == {"parse-error"}


# ---------------------------------------------------------------------------
# pragmas + baseline
# ---------------------------------------------------------------------------


def test_pragma_suppresses_line_and_file(tmp_path):
    base = (
        "import threading\n"
        "import time\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._mtx = threading.Lock()\n"
        "    def bad(self):\n"
        "        with self._mtx:\n"
        "            time.sleep(1){pragma}\n"
    )
    hot = {"tendermint_tpu/m.py": base.format(pragma="")}
    cold = {"tendermint_tpu/m.py": base.format(
        pragma="  # tmlint: disable=lock-held-call")}
    wrong = {"tendermint_tpu/m.py": base.format(
        pragma="  # tmlint: disable=lock-order")}
    filewide = {"tendermint_tpu/m.py":
                "# tmlint: disable-file=lock-held-call\n"
                + base.format(pragma="")}
    assert _run(tmp_path / "a", hot, ["lock-held-call"])
    assert not _run(tmp_path / "b", cold, ["lock-held-call"])
    assert _run(tmp_path / "c", wrong, ["lock-held-call"])
    assert not _run(tmp_path / "d", filewide, ["lock-held-call"])


def test_pragma_on_line_above(tmp_path):
    files = {"tendermint_tpu/m.py": (
        "import threading\n"
        "import time\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._mtx = threading.Lock()\n"
        "    def bad(self):\n"
        "        with self._mtx:\n"
        "            # tmlint: disable=lock-held-call\n"
        "            time.sleep(1)\n"
    )}
    assert not _run(tmp_path, files, ["lock-held-call"])


def test_baseline_roundtrip(tmp_path):
    files = {"tendermint_tpu/m.py": (
        "import threading\n"
        "import time\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._mtx = threading.Lock()\n"
        "    def bad(self):\n"
        "        with self._mtx:\n"
        "            time.sleep(1)\n"
    )}
    fs = _run(tmp_path, files, ["lock-held-call"])
    assert fs
    bl = tmp_path / "baseline.txt"
    core.write_baseline(fs, str(bl))
    entries = core.load_baseline(str(bl))
    new, old = core.split_baselined(fs, entries)
    assert not new and len(old) == len(fs)
    # line drift does NOT invalidate a baseline entry (no line numbers in
    # the identity), a different message does
    moved = [core.Finding(f.path, f.line + 7, f.rule, f.message) for f in fs]
    new, old = core.split_baselined(moved, entries)
    assert not new
    other = [core.Finding(f.path, f.line, f.rule, f.message + "!") for f in fs]
    new, old = core.split_baselined(other, entries)
    assert len(new) == len(fs)


def test_unknown_rule_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown rule"):
        _run(tmp_path, {"tendermint_tpu/m.py": "x = 1\n"}, ["no-such-rule"])


# ---------------------------------------------------------------------------
# 3. lock-order witness units
# ---------------------------------------------------------------------------


@pytest.fixture
def own_witness():
    """Isolate these units from a session-wide TMTPU_LOCKWITNESS=1 sweep:
    swap in a fresh Witness (the deliberately planted cycle below must
    never poison the session graph or trip pytest_sessionfinish), then
    restore the session witness and re-arm the sweep."""
    saved = lockwitness.WITNESS
    sweep_active = saved.enabled
    lockwitness.uninstall()
    lockwitness.WITNESS = lockwitness.Witness()
    try:
        yield
    finally:
        lockwitness.uninstall()
        lockwitness.WITNESS = saved
        if sweep_active:
            lockwitness.install()


def test_witness_detects_opposite_order_cycle(own_witness):
    with lockwitness.witness(assert_on_exit=False) as w:
        a = threading.Lock()
        b = threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t2 = threading.Thread(target=ba)
        t1.start(); t1.join()
        t2.start(); t2.join()
    cycles = w.cycles()
    assert cycles, f"no cycle found; edges={sorted(w.edges)}"
    with pytest.raises(AssertionError, match="lock-order cycle"):
        w.assert_acyclic()


def test_witness_consistent_order_is_acyclic(own_witness):
    with lockwitness.witness() as w:
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    assert w.acquires >= 6 and not w.cycles()


def test_witness_reentrant_rlock_not_a_cycle(own_witness):
    with lockwitness.witness() as w:
        r = threading.RLock()
        with r:
            with r:  # same instance: reentrancy, not ordering
                pass
    assert not w.cycles()


def test_witness_same_site_different_instances_is_flagged(own_witness):
    """Two locks born at the same line (per-peer locks) nested = the
    two-peers-in-opposite-order hazard; recorded as a site self-edge."""
    with lockwitness.witness(assert_on_exit=False) as w:
        locks = [threading.Lock() for _ in range(2)]  # one creation site
        with locks[0]:
            with locks[1]:
                pass
    assert w.cycles(), sorted(w.edges)


def test_witness_condition_wait_releases_held_entry(own_witness):
    """Condition.wait fully releases the RLock: the witness stack must
    drop it (a waiter does NOT hold the lock) and restore on wake."""
    with lockwitness.witness() as w:
        cond = threading.Condition()
        other = threading.Lock()
        done = []

        def waiter():
            with cond:
                cond.wait(timeout=5)
                done.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        # if wait() leaked a held entry, this nested take under `other`
        # would record cond->other AND other->cond edges across threads
        with other:
            with cond:
                cond.notify()
        t.join(timeout=5)
        assert done
    assert not w.cycles()


def test_witness_overhead_bookkeeping_bounded(own_witness):
    with lockwitness.witness() as w:
        locks = [threading.Lock() for _ in range(4)]
        for _ in range(200):
            for lk in locks:
                with lk:
                    pass
    assert not w.truncated
    assert w.max_depth <= 2
    assert w.acquires >= 800


def test_witness_uninstall_restores_factories(own_witness):
    before = threading.Lock
    with lockwitness.witness():
        assert threading.Lock is not before
    assert threading.Lock is lockwitness._REAL_LOCK
    assert threading.RLock is lockwitness._REAL_RLOCK
