"""LightGateway: verified-answer cache, single-flight coalescing, provider
retry/backoff/hedging, scoreboard demotion/eviction, witness rotation, and
typed degradation verdicts. Detector thread-safety regressions ride here
too (shared-Client concurrency)."""

import threading
import time
from types import SimpleNamespace

import pytest
from test_light import (
    CHAIN_ID,
    TRUST_PERIOD,
    _mk_header,
    _mk_keys,
    _sign_commit,
    gen_chain,
    t,
)

from tendermint_tpu.light.client import Client, TrustOptions
from tendermint_tpu.light.detector import detect_divergence
from tendermint_tpu.light.gateway import (
    ErrGatewayDegraded,
    GatewayConfig,
    LightGateway,
    VERDICT_CACHED,
    VERDICT_COALESCED,
    VERDICT_FRESH,
    VERDICT_STALE,
)
from tendermint_tpu.light.provider import ErrNoResponse, MockProvider
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.store.envelope import CorruptedStoreError
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def keys():
    return _mk_keys(4)


@pytest.fixture(scope="module")
def chain(keys):
    privs, vs = keys
    return gen_chain(12, privs, vs)


def _mock(chain):
    return MockProvider(CHAIN_ID, {lb.height: lb for lb in chain})


def _opts(chain):
    return TrustOptions(period_s=TRUST_PERIOD, height=1, hash=chain[0].hash())


def _gateway(chain, n=3, now=None, **kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("providers", [_mock(chain) for _ in range(n)])
    return LightGateway(CHAIN_ID, _opts(chain), kw.pop("providers"),
                        DBStore(MemDB(), CHAIN_ID), **kw)


class FlakyProvider(MockProvider):
    """Fails the first `fail_n` light_block calls with ErrNoResponse."""

    def __init__(self, chain_id, lbs, fail_n):
        super().__init__(chain_id, lbs)
        self.fail_n = fail_n
        self.calls = 0

    def light_block(self, height):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise ErrNoResponse("flaky")
        return super().light_block(height)


class SlowProvider(MockProvider):
    def __init__(self, chain_id, lbs, delay_s, skip_first=1):
        super().__init__(chain_id, lbs)
        self.delay_s = delay_s
        self.calls = 0
        self.skip_first = skip_first  # let client init go through fast

    def light_block(self, height):
        self.calls += 1
        if self.calls > self.skip_first:
            time.sleep(self.delay_s)
        return super().light_block(height)


# --- verified-answer plane ---------------------------------------------------


def test_serves_verified_and_caches(chain):
    gw = _gateway(chain)
    lb, verdict = gw.serve_light_block(8, now=t(100))
    assert verdict == VERDICT_FRESH
    assert lb.hash() == chain[7].hash()
    lb2, verdict2 = gw.serve_light_block(8, now=t(100))
    assert verdict2 == VERDICT_CACHED
    assert lb2.hash() == chain[7].hash()
    assert gw.cache_hits == 1 and gw.queries == 2


def test_cache_is_bounded(chain):
    cfg = GatewayConfig()
    cfg.cache_cap = 2
    gw = _gateway(chain, config=cfg)
    for h in (3, 5, 7, 9):
        gw.serve_light_block(h, now=t(100))
    assert len(gw._cache) <= 2


def test_concurrent_clients_coalesce(chain):
    gw = _gateway(chain)
    results = []
    errs = []
    barrier = threading.Barrier(8)

    def client():
        try:
            barrier.wait(timeout=10)
            results.append(gw.serve_light_block(10, now=t(120)))
        except Exception as e:  # noqa: BLE001 - surfaced via assert
            errs.append(e)

    threads = [threading.Thread(target=client) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errs
    assert len(results) == 8
    assert all(lb.hash() == chain[9].hash() for lb, _ in results)
    fresh = [v for _, v in results if v == VERDICT_FRESH]
    shared = [v for _, v in results
              if v in (VERDICT_COALESCED, VERDICT_CACHED)]
    assert len(fresh) == 1
    assert len(shared) == 7


# --- provider resilience -----------------------------------------------------


def test_retry_with_backoff_rides_out_transient_failures(chain):
    flaky = FlakyProvider(CHAIN_ID, {lb.height: lb for lb in chain}, 0)
    gw = _gateway(chain, providers=[flaky, _mock(chain), _mock(chain)])
    flaky.fail_n = flaky.calls + 2  # next two primary fetches fail
    lb, verdict = gw.serve_light_block(6, now=t(100))
    assert verdict == VERDICT_FRESH
    assert lb.hash() == chain[5].hash()
    assert gw.retries >= 1
    assert gw.scoreboard._board.score("p0") > 0  # no_response offenses


def test_fault_site_failures_retry_and_score(chain):
    gw = _gateway(chain)
    faults.configure(["light.gateway.fetch:raise@1"], seed=7)
    lb, verdict = gw.serve_light_block(4, now=t(100))
    assert verdict == VERDICT_FRESH
    assert lb.hash() == chain[3].hash()
    assert gw.retries >= 1


def test_hedged_secondary_beats_slow_primary(chain):
    cfg = GatewayConfig()
    cfg.hedge_s = 0.05
    cfg.n_witnesses = 2
    slow = SlowProvider(CHAIN_ID, {lb.height: lb for lb in chain}, 0.5)
    providers = [slow, _mock(chain), _mock(chain), _mock(chain)]
    gw = LightGateway(CHAIN_ID, _opts(chain), providers,
                      DBStore(MemDB(), CHAIN_ID), config=cfg,
                      sleep=lambda s: None)
    t0 = time.monotonic()
    lb, verdict = gw.serve_light_block(9, now=t(120))
    assert verdict == VERDICT_FRESH
    assert lb.hash() == chain[8].hash()
    assert gw.hedges >= 1
    assert gw.scoreboard._board.score("p0") > 0  # slow offense recorded
    assert time.monotonic() - t0 < 3.0


def test_lying_primary_evicted_and_recovers(keys, chain):
    privs, vs = keys
    fake = gen_chain(12, privs, vs, step_s=20)  # same anchor keys, forked times
    primary = MockProvider(
        CHAIN_ID, {1: chain[0], **{lb.height: lb for lb in fake[1:]}})
    witnesses = [_mock(chain), _mock(chain)]
    gw = LightGateway(CHAIN_ID, _opts(chain), [primary] + witnesses,
                      DBStore(MemDB(), CHAIN_ID), sleep=lambda s: None)
    lb, verdict = gw.serve_light_block(7, now=t(300))
    # honest answer, lying primary permanently evicted
    assert lb.hash() == chain[6].hash()
    assert gw.scoreboard.evicted("p0")
    assert gw.scoreboard.evictions == 1
    assert gw.rebuilds == 1
    assert gw.client.primary.name != "p0"
    assert gw.all_divergences()
    # evidence was reported to the (honest) witness provider
    assert any(w.evidences for w in witnesses)
    d = gw.describe()
    assert "p0" in d["providers"]["evicted"]


def test_witness_rotation_on_no_witnesses(chain):
    cfg = GatewayConfig()
    cfg.n_witnesses = 1
    dead_witness = MockProvider(CHAIN_ID, {1: chain[0]})
    providers = [_mock(chain), dead_witness, _mock(chain), _mock(chain)]
    gw = LightGateway(CHAIN_ID, _opts(chain), providers,
                      DBStore(MemDB(), CHAIN_ID), config=cfg,
                      sleep=lambda s: None)
    dead_witness._lbs.clear()  # witness goes dark after anchor check
    # first serve: detector drops the dead witness (list now empty)
    gw.serve_light_block(5, now=t(100))
    # second serve: ErrNoWitnesses -> a spare rotates into the witness set
    lb, verdict = gw.serve_light_block(7, now=t(100))
    assert verdict == VERDICT_FRESH
    assert lb.hash() == chain[6].hash()
    assert gw.rotations >= 1
    assert gw.client.witnesses


def test_anchor_lying_witness_evicted_at_construction(keys, chain):
    # a witness that contradicts the TRUST ANCHOR fails Client.__init__
    # (compare_first_header_with_witnesses); the gateway must evict it and
    # rebuild around the rest instead of dying
    privs, vs = keys
    fake = gen_chain(12, privs, vs, step_s=20)
    liar = MockProvider(CHAIN_ID, {lb.height: lb for lb in fake})
    gw = LightGateway(CHAIN_ID, _opts(chain),
                      [_mock(chain), liar, _mock(chain)],
                      DBStore(MemDB(), CHAIN_ID), sleep=lambda s: None)
    assert gw.scoreboard.evicted("p1")
    lb, verdict = gw.serve_light_block(6, now=t(100))
    assert verdict == VERDICT_FRESH
    assert lb.hash() == chain[5].hash()


def test_dead_witness_demoted_not_evicted(chain):
    dead = MockProvider(CHAIN_ID, {1: chain[0]})
    gw = LightGateway(CHAIN_ID, _opts(chain),
                      [_mock(chain), dead, _mock(chain)],
                      DBStore(MemDB(), CHAIN_ID), sleep=lambda s: None)
    dead._lbs.clear()  # goes dark after the anchor check
    lb, _ = gw.serve_light_block(5, now=t(100))
    assert lb.hash() == chain[4].hash()
    # unresponsiveness is demotion material, never a permanent eviction
    assert not gw.scoreboard.evicted("p1")
    assert gw.scoreboard._board.score("p1") > 0


def test_unsubstantiated_lying_witness_evicted(chain):
    # a witness serving a divergent header it CANNOT substantiate (signed
    # by foreign keys) is lying: detector drops it, hook evicts it
    privs_x, vs_x = _mk_keys(4, seed=5)
    fake = gen_chain(12, privs_x, vs_x, step_s=20)
    liar = MockProvider(
        CHAIN_ID, {1: chain[0], **{lb.height: lb for lb in fake[1:]}})
    gw = LightGateway(CHAIN_ID, _opts(chain),
                      [_mock(chain), liar, _mock(chain)],
                      DBStore(MemDB(), CHAIN_ID), sleep=lambda s: None)
    lb, _ = gw.serve_light_block(6, now=t(100))
    assert lb.hash() == chain[5].hash()
    assert gw.scoreboard.evicted("p1")
    assert gw.client.primary.name == "p0"  # honest primary untouched


# --- typed degradation -------------------------------------------------------


def test_degraded_refuses_unknown_height_when_providers_dead(chain):
    providers = [_mock(chain) for _ in range(3)]
    gw = _gateway(chain, providers=providers)
    gw.serve_light_block(5, now=t(100))
    for p in providers:
        p._lbs.clear()
    with pytest.raises(Exception) as ei:
        gw.serve_light_block(11, now=t(150))
    assert not isinstance(ei.value, AssertionError)
    assert gw.refused >= 1
    # but the already-verified height still serves (cache)
    lb, verdict = gw.serve_light_block(5, now=t(150))
    assert lb.hash() == chain[4].hash()


def test_serve_latest_degrades_to_stale_within_trust_period(chain):
    providers = [_mock(chain) for _ in range(3)]
    gw = _gateway(chain, providers=providers)
    gw.serve_light_block(8, now=t(100))
    for p in providers:
        p._lbs.clear()  # provider outage
    lb, verdict = gw.serve_latest(now=t(200))
    assert verdict == VERDICT_STALE
    assert lb.hash() == chain[7].hash()
    assert gw.stale_served == 1


def test_serve_latest_refuses_outside_trust_period(chain):
    providers = [_mock(chain) for _ in range(3)]
    gw = _gateway(chain, providers=providers)
    gw.serve_light_block(8, now=t(100))
    for p in providers:
        p._lbs.clear()
    with pytest.raises(ErrGatewayDegraded):
        gw.serve_latest(now=t(int(TRUST_PERIOD) + 1000))
    assert gw.refused >= 1


# --- tx plane: refuse-and-repair, never serve-corrupt ------------------------


class _QuarantinedIndexer:
    def get(self, raw):
        raise CorruptedStoreError("txindex", b"tx/" + raw, "crc mismatch")


def test_tx_query_refuses_quarantined_row(chain):
    gw = _gateway(chain)
    gw.node = SimpleNamespace(tx_indexer=_QuarantinedIndexer(),
                              block_store=None)
    with pytest.raises(ErrGatewayDegraded, match="quarantined"):
        gw.serve_tx(b"\x01" * 32, now=t(100))
    assert gw.refused == 1


def test_tx_query_without_node_refuses(chain):
    gw = _gateway(chain)
    with pytest.raises(ErrGatewayDegraded):
        gw.serve_tx(b"\x01" * 32)


# --- detector thread-safety (shared Client) ----------------------------------


def _client_with_lying_witness(keys, chain):
    privs, vs = keys
    fake = gen_chain(12, privs, vs, step_s=20)
    primary = _mock(chain)
    liar = MockProvider(
        CHAIN_ID, {1: chain[0], **{lb.height: lb for lb in fake[1:]}})
    honest = _mock(chain)
    client = Client(CHAIN_ID, _opts(chain), primary, [liar, honest],
                    DBStore(MemDB(), CHAIN_ID))
    return client, liar, honest


def test_concurrent_detect_divergence_single_remove_single_record(keys, chain):
    client, liar, honest = _client_with_lying_witness(keys, chain)
    target = chain[6]
    client.verify_light_block  # warm attr
    # verify through primary only first (no detection) by seeding the store
    client.trusted_store.save_light_block(chain[2])
    client.latest_trusted = chain[2]

    unexpected = []
    conflicts = []
    barrier = threading.Barrier(2)

    def hammer():
        try:
            barrier.wait(timeout=10)
            detect_divergence(client, target, t(300))
        except Exception as e:  # noqa: BLE001
            if type(e).__name__ == "ErrConflictingHeaders":
                conflicts.append(e)
            else:
                unexpected.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not unexpected
    # the lying witness was removed exactly once, the honest one kept
    assert all(w is not liar for w in client.witnesses)
    assert any(w is honest for w in client.witnesses)
    # exactly one Divergence recorded despite two racing detections
    assert len(client.divergences) == 1


def test_remove_witness_out_of_range_is_tolerated(keys, chain):
    client, _, _ = _client_with_lying_witness(keys, chain)
    n = len(client.witnesses)
    client.remove_witness(99)
    assert len(client.witnesses) == n


def test_remove_witnesses_by_identity_never_double_removes(keys, chain):
    client, liar, honest = _client_with_lying_witness(keys, chain)
    client.remove_witnesses([liar, liar, liar])
    assert len(client.witnesses) == 1
    assert client.witnesses[0] is honest


class _MutatingProvider(MockProvider):
    """On the first pivot fetch, mutates the client's witness list from
    another thread (regression: witness-list mutation during an in-flight
    _verify_skipping must not crash or double-remove)."""

    def __init__(self, chain_id, lbs, client_ref, victim_ref):
        super().__init__(chain_id, lbs)
        self.client_ref = client_ref
        self.victim_ref = victim_ref
        self.mutated = False

    def light_block(self, height):
        if not self.mutated and self.client_ref() is not None:
            self.mutated = True
            client, victim = self.client_ref(), self.victim_ref()
            th = threading.Thread(
                target=client.remove_witnesses, args=([victim, victim],))
            th.start()
            th.join(timeout=10)
        return super().light_block(height)


def test_witness_mutation_during_inflight_verify_skipping(keys):
    # Chain with a validator-set rotation at h4 so skipping 1 -> 6 is forced
    # to bisect (fetching pivots from the source provider mid-flight).
    privsA, vsA = keys
    privsB, vsB = _mk_keys(4, seed=9)
    lbs = []
    last_bid = None
    spec = [(1, vsA, privsA, vsA), (2, vsA, privsA, vsA), (3, vsA, privsA, vsB),
            (4, vsB, privsB, vsB), (5, vsB, privsB, vsB), (6, vsB, privsB, vsB)]
    for h, vals, privs, next_vals in spec:
        header = _mk_header(h, h * 10, vals, next_vals, last_bid)
        commit = _sign_commit(header, vals, privs)
        lbs.append(LightBlock(signed_header=SignedHeader(header, commit),
                              validator_set=vals.copy()))
        last_bid = commit.block_id
    by_h = {lb.height: lb for lb in lbs}

    holder = {}
    source = _MutatingProvider(CHAIN_ID, by_h,
                               lambda: holder.get("client"),
                               lambda: holder.get("victim"))
    w1 = MockProvider(CHAIN_ID, by_h)
    w2 = MockProvider(CHAIN_ID, by_h)
    client = Client(
        CHAIN_ID, TrustOptions(period_s=TRUST_PERIOD, height=1,
                               hash=lbs[0].hash()),
        source, [w1, w2], DBStore(MemDB(), CHAIN_ID))
    holder["client"] = client
    holder["victim"] = w1

    verified = client._verify_skipping(source, lbs[0], lbs[5], t(100),
                                       save=False)
    assert source.mutated
    assert verified  # bisection actually happened
    # w1 removed exactly once; w2 untouched
    assert all(w is not w1 for w in client.witnesses)
    assert any(w is w2 for w in client.witnesses)
    assert len(client.witnesses) == 1
