"""ABCI socket transport: wire codec round-trips, client/server over TCP,
exception propagation, proxy multiplexer, and a full consensus node running
with its app behind a socket (reference: abci/client/socket_client.go,
abci/server/socket_server.go, proxy/multi_app_conn.go)."""

import os
import time

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import wire
from tendermint_tpu.abci.client import ABCISocketClient
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.abci.proxy import local_app_conns, new_app_conns
from tendermint_tpu.abci.server import ABCIServer
from tendermint_tpu.abci.wire import ABCIRemoteError


def _roundtrip_req(kind, req):
    return wire.decode_request(wire.encode_request(kind, req))


def _roundtrip_resp(kind, resp):
    return wire.decode_response(wire.encode_response(kind, resp))


def test_wire_request_roundtrips():
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.params import ConsensusParams

    k, r = _roundtrip_req("info", abci.RequestInfo("0.34.24", 11, 8))
    assert k == "info" and r.block_version == 11 and r.p2p_version == 8

    k, r = _roundtrip_req("init_chain", abci.RequestInitChain(
        time_seconds=1700000000, time_nanos=42, chain_id="wire-chain",
        consensus_params=ConsensusParams(),
        validators=[abci.ValidatorUpdate("ed25519", b"\x01" * 32, 7)],
        app_state_bytes=b"{}", initial_height=5))
    assert k == "init_chain" and r.chain_id == "wire-chain"
    assert r.validators[0].power == 7 and r.initial_height == 5
    assert r.time_seconds == 1700000000 and r.time_nanos == 42

    hdr = Header(chain_id="wire-chain", height=3,
                 validators_hash=b"\x02" * 32, next_validators_hash=b"\x03" * 32,
                 proposer_address=b"\x04" * 20)
    k, r = _roundtrip_req("begin_block", abci.RequestBeginBlock(
        hash=b"\x05" * 32, header=hdr,
        last_commit_info=abci.LastCommitInfo(round=2, votes=[
            abci.VoteInfo(abci.ABCIValidator(b"\x06" * 20, 10), True),
            abci.VoteInfo(abci.ABCIValidator(b"\x07" * 20, 20), False)]),
        byzantine_validators=[abci.ABCIEvidence(
            type=abci.EVIDENCE_TYPE_DUPLICATE_VOTE,
            validator=abci.ABCIValidator(b"\x08" * 20, 30),
            height=2, time_seconds=1700000001, total_voting_power=60)]))
    assert k == "begin_block" and r.header.height == 3
    assert r.last_commit_info.round == 2
    assert [v.signed_last_block for v in r.last_commit_info.votes] == [True, False]
    assert r.byzantine_validators[0].validator.power == 30

    k, r = _roundtrip_req("check_tx", abci.RequestCheckTx(
        tx=b"x=1", type=abci.CHECK_TX_TYPE_RECHECK))
    assert k == "check_tx" and r.type == abci.CHECK_TX_TYPE_RECHECK

    k, r = _roundtrip_req("apply_snapshot_chunk", abci.RequestApplySnapshotChunk(
        index=3, chunk=b"\x09" * 100, sender="peerX"))
    assert k == "apply_snapshot_chunk" and r.index == 3 and r.sender == "peerX"

    assert _roundtrip_req(wire.ECHO, "hello") == (wire.ECHO, "hello")
    assert _roundtrip_req(wire.FLUSH, None) == (wire.FLUSH, None)
    assert _roundtrip_req(wire.COMMIT, None) == (wire.COMMIT, None)


def test_wire_response_roundtrips():
    k, r = _roundtrip_resp("info", abci.ResponseInfo(
        data="{}", version="1", app_version=9, last_block_height=77,
        last_block_app_hash=b"\x0a" * 8))
    assert k == "info" and r.last_block_height == 77 and r.app_version == 9

    k, r = _roundtrip_resp("check_tx", abci.ResponseCheckTx(
        code=1, log="bad", gas_wanted=5, priority=-3, sender="s"))
    assert k == "check_tx" and r.code == 1 and r.priority == -3

    k, r = _roundtrip_resp("deliver_tx", abci.ResponseDeliverTx(
        code=0, data=b"ok", events=[abci.Event(type="app", attributes=[
            abci.EventAttribute(key=b"k", value=b"v", index=True)])]))
    assert k == "deliver_tx" and r.events[0].attributes[0].key == b"k"

    k, r = _roundtrip_resp("end_block", abci.ResponseEndBlock(
        validator_updates=[abci.ValidatorUpdate("ed25519", b"\x0b" * 32, 0)]))
    assert k == "end_block" and r.validator_updates[0].power == 0

    k, r = _roundtrip_resp(wire.COMMIT, abci.ResponseCommit(
        data=b"\x0c" * 8, retain_height=11))
    assert k == wire.COMMIT and r.retain_height == 11

    k, r = _roundtrip_resp("apply_snapshot_chunk", abci.ResponseApplySnapshotChunk(
        result=abci.APPLY_CHUNK_RETRY, refetch_chunks=[1, 4],
        reject_senders=["bad"]))
    assert r.refetch_chunks == [1, 4] and r.reject_senders == ["bad"]

    with pytest.raises(ABCIRemoteError, match="boom"):
        wire.decode_response(wire.encode_response("", error="boom"))


def test_socket_client_server_roundtrip(tmp_path):
    app = KVStoreApplication()
    server = ABCIServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        client = ABCISocketClient(server.addr)
        assert client.echo("ping") == "ping"
        client.flush()
        info = client.info(abci.RequestInfo())
        assert info.last_block_height == 0

        assert client.check_tx(abci.RequestCheckTx(tx=b"a=1")).code == 0
        client.begin_block(abci.RequestBeginBlock())
        assert client.deliver_tx(abci.RequestDeliverTx(tx=b"a=1")).code == 0
        client.end_block(abci.RequestEndBlock(height=1))
        commit = client.commit()
        assert commit.data == app.app_hash and app.height == 1

        q = client.query(abci.RequestQuery(path="", data=b"a"))
        assert q.value == b"1"

        # second client on the same server (proxy-style)
        client2 = ABCISocketClient(server.addr)
        assert client2.info(abci.RequestInfo()).last_block_height == 1
        client.close()
        client2.close()
    finally:
        server.stop()


def test_socket_server_exception_propagates():
    class BoomApp(abci.Application):
        def query(self, req):
            raise RuntimeError("kaboom")

    server = ABCIServer(BoomApp(), "tcp://127.0.0.1:0")
    server.start()
    try:
        client = ABCISocketClient(server.addr)
        with pytest.raises(ABCIRemoteError, match="kaboom"):
            client.query(abci.RequestQuery(data=b"x"))
        # connection still usable afterwards
        assert client.echo("still-alive") == "still-alive"
        client.close()
    finally:
        server.stop()


def test_unix_socket_transport(tmp_path):
    app = KVStoreApplication()
    sock = str(tmp_path / "abci.sock")
    server = ABCIServer(app, f"unix://{sock}")
    server.start()
    try:
        conns = new_app_conns(f"unix://{sock}")
        assert conns.query.info(abci.RequestInfo()).last_block_height == 0
        conns.mempool.check_tx(abci.RequestCheckTx(tx=b"u=1"))
        conns.stop()
    finally:
        server.stop()


def test_local_app_conns_share_one_mutex():
    app = KVStoreApplication()
    conns = local_app_conns(app)
    conns.consensus.begin_block(abci.RequestBeginBlock())
    conns.consensus.deliver_tx(abci.RequestDeliverTx(tx=b"m=1"))
    conns.consensus.end_block(abci.RequestEndBlock(height=1))
    conns.consensus.commit()
    assert conns.query.info(abci.RequestInfo()).last_block_height == 1
    assert conns.mempool.check_tx(abci.RequestCheckTx(tx=b"n=2")).code == 0


def test_consensus_with_app_behind_socket(tmp_path):
    """The VERDICT criterion: the consensus harness runs with the app
    out-of-process behind a socket (reference: proxy/multi_app_conn.go:21)."""
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import MockPV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    app = KVStoreApplication()
    server = ABCIServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        priv = ed25519.gen_priv_key(b"\x71" * 32)
        genesis = GenesisDoc(
            chain_id="socket-chain", genesis_time=Time(1700003000, 0),
            validators=[GenesisValidator(b"", priv.pub_key(), 10)],
        )
        cfg = test_config()
        cfg.set_root(str(tmp_path / "node"))
        os.makedirs(cfg.base.root_dir, exist_ok=True)
        cfg.base.fast_sync_mode = False
        cfg.base.proxy_app = server.addr  # <- the app is REMOTE
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.consensus.wal_path = ""
        node = Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                    node_key=NodeKey(ed25519.gen_priv_key(b"\x72" * 32)))
        node.start()
        try:
            node.mempool.check_tx(b"sockettx=42")
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and app.height < 3:
                time.sleep(0.1)
            assert app.height >= 3
            assert node.block_store.height >= 3
            # the tx crossed the socket and landed in the remote app
            assert app.db.get(b"kv:sockettx") == b"42"
        finally:
            node.stop()
    finally:
        server.stop()


def test_abci_grpc_transport_roundtrip():
    """ABCI over gRPC: every method crosses the channel (reference:
    abci/client/grpc_client.go, abci/server/grpc_server.go)."""
    from tendermint_tpu.abci.grpc_transport import ABCIGrpcClient, ABCIGrpcServer

    app = KVStoreApplication(snapshot_interval=1)
    server = ABCIGrpcServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        client = ABCIGrpcClient(server.addr)
        assert client.echo("grpc-ping") == "grpc-ping"
        client.flush()
        assert client.info(abci.RequestInfo()).last_block_height == 0
        assert client.check_tx(abci.RequestCheckTx(tx=b"g=1")).code == 0
        client.begin_block(abci.RequestBeginBlock())
        assert client.deliver_tx(abci.RequestDeliverTx(tx=b"g=1")).code == 0
        client.end_block(abci.RequestEndBlock(height=1))
        commit = client.commit()
        assert commit.data == app.app_hash
        assert client.query(abci.RequestQuery(path="", data=b"g")).value == b"1"
        snaps = client.list_snapshots(abci.RequestListSnapshots()).snapshots
        assert snaps and snaps[0].height == 1
        chunk = client.load_snapshot_chunk(abci.RequestLoadSnapshotChunk(
            height=1, format=1, chunk=0)).chunk
        assert chunk
        client.close()
    finally:
        server.stop()


def test_consensus_with_app_behind_grpc(tmp_path):
    """A node commits blocks with the app remote over gRPC (proxy_app =
    grpc://...)."""
    from tendermint_tpu.abci.grpc_transport import ABCIGrpcServer
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import MockPV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    app = KVStoreApplication()
    server = ABCIGrpcServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        priv = ed25519.gen_priv_key(b"\x75" * 32)
        genesis = GenesisDoc(
            chain_id="grpc-chain", genesis_time=Time(1700003000, 0),
            validators=[GenesisValidator(b"", priv.pub_key(), 10)],
        )
        cfg = test_config()
        cfg.set_root(str(tmp_path / "node"))
        os.makedirs(cfg.base.root_dir, exist_ok=True)
        cfg.base.fast_sync_mode = False
        cfg.base.proxy_app = "grpc://" + server.addr.split("://", 1)[1]
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.consensus.wal_path = ""
        node = Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                    node_key=NodeKey(ed25519.gen_priv_key(b"\x76" * 32)))
        node.start()
        try:
            node.mempool.check_tx(b"grpctx=1")
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and app.height < 3:
                time.sleep(0.1)
            assert app.height >= 3
            assert app.db.get(b"kv:grpctx") == b"1"
        finally:
            node.stop()
    finally:
        server.stop()
