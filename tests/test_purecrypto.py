"""RFC test vectors for the pure-Python SecretConnection crypto fallback."""

import pytest

from tendermint_tpu.crypto import purecrypto as pc


def test_x25519_rfc7748_vector_1():
    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
    )
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
    )
    out = bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
    )
    assert pc.x25519(k, u) == out


def test_x25519_rfc7748_vector_2():
    k = bytes.fromhex(
        "4b66e9d4d1b4673c5ad22691957d6af5c11b6421e0ea01d42ca4169e7918ba0d"
    )
    u = bytes.fromhex(
        "e5210f12786811d3f4b7959d0538ae2c31dbe7106fc03c3efc4cd549c715a493"
    )
    out = bytes.fromhex(
        "95cbde9476e8907d7aade45cb4b873f88b595a68799fa152e6f8f7647aac7957"
    )
    assert pc.x25519(k, u) == out


def test_x25519_dh_agreement_rfc7748_section_6_1():
    a = bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    )
    b = bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    )
    pub_a = pc.X25519PrivateKey(a).public_key().public_bytes_raw()
    pub_b = pc.X25519PrivateKey(b).public_key().public_bytes_raw()
    assert pub_a == bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    assert pub_b == bytes.fromhex(
        "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
    )
    shared = bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    )
    ka = pc.X25519PrivateKey(a).exchange(pc.X25519PublicKey(pub_b))
    kb = pc.X25519PrivateKey(b).exchange(pc.X25519PublicKey(pub_a))
    assert ka == kb == shared


def test_chacha20_rfc8439_keystream_block():
    key = bytes(range(32))
    nonce = bytes.fromhex("000000090000004a00000000")
    block = pc.chacha20_xor(key, 1, nonce, b"\x00" * 64)
    assert block == bytes.fromhex(
        "10f1e7e4d13b5915500fdd1fa32071c4c7d1f4c733c068030422aa9ac3d46c4e"
        "d2826446079faa0914c2d705d98b02a2b5129cd1de164eb9cbd083e8a2503c4e"
    )


def test_poly1305_rfc8439_vector():
    key = bytes.fromhex(
        "85d6be7857556d337f4452fe42d506a80103808afb0db2fd4abff6af4149f51b"
    )
    msg = b"Cryptographic Forum Research Group"
    assert pc.poly1305_mac(key, msg) == bytes.fromhex(
        "a8061dc1305136c6c22b8baf0c0127a9"
    )


def test_aead_rfc8439_vector():
    key = bytes.fromhex(
        "808182838485868788898a8b8c8d8e8f909192939495969798999a9b9c9d9e9f"
    )
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    aead = pc.ChaCha20Poly1305(key)
    sealed = aead.encrypt(nonce, plaintext, aad)
    assert sealed[-16:] == bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
    assert sealed[:32] == bytes.fromhex(
        "d31a8d34648e60db7b86afbc53ef7ec2a4aded51296e08fea9e2b5a736ee62d6"
    )
    assert aead.decrypt(nonce, sealed, aad) == plaintext


def test_aead_rejects_tampering():
    aead = pc.ChaCha20Poly1305(b"\x01" * 32)
    sealed = bytearray(aead.encrypt(b"\x00" * 12, b"payload", None))
    sealed[0] ^= 0xFF
    with pytest.raises(pc.InvalidTag):
        aead.decrypt(b"\x00" * 12, bytes(sealed), None)


def test_secret_connection_uses_fallback_cleanly():
    # The import seam in p2p/secret_connection.py must resolve whether or
    # not `cryptography` is installed.
    from tendermint_tpu.p2p import secret_connection as sc

    assert hasattr(sc, "ChaCha20Poly1305")
    assert hasattr(sc, "X25519PrivateKey")
