"""Soak harness (tendermint_tpu/e2e/soak.py, docs/SOAK.md): schedule
grammar determinism, the continuous safety/liveness auditor, the repro
line, and a short driven soak.

Quick tier: grammar/auditor/repro units plus a bounded 4-node mini-soak
(one partition round + a joiner + a power change under tx load). The
longer seeded soaks carry the `soak` marker, which conftest always folds
into `slow` — tier-1 never runs them.
"""

import time

import pytest

from test_nemesis import _wait, repro  # noqa: F401 (shared harness)

from tendermint_tpu.e2e import fabric, soak
from tendermint_tpu.utils import faults, nemesis

SEED = 2026


@pytest.fixture(autouse=True)
def _clean_chaos():
    faults.configure([], seed=SEED)
    nemesis.clear()
    yield
    nemesis.clear()
    nemesis.PLANE.on_heal.clear()
    faults.clear()


# ---------------------------------------------------------------------------
# Schedule grammar (quick)
# ---------------------------------------------------------------------------


def test_action_grammar_roundtrip():
    for entry in ("@3:partition~2:4|rest", "@5.5:linkfault~2:*>3:drop%0.5",
                  "@8:flood~1.5:1>0", "@10:join", "@10:join_statesync",
                  "@12:power:5:30", "@14:restart:2", "@16:leave:6",
                  "@18:evidence:3", "@20:lightcrowd~8:16", "@22:lightcrowd:4"):
        a = soak.SoakAction.parse(entry)
        assert a.describe() == entry
    a = soak.SoakAction.parse("@3:partition~1.5:0/1|2/3")
    assert (a.at_s, a.kind, a.arg, a.dur_s) == (3.0, "partition", "0/1|2/3", 1.5)
    # the duration rides on the KIND segment: a link-fault arg that itself
    # contains `~` (nemesis delay grammar) must survive intact
    a = soak.SoakAction.parse("@8:linkfault:*>3:delay~0.05")
    assert (a.kind, a.arg, a.dur_s) == ("linkfault", "*>3:delay~0.05", 0.0)
    for bad in ("partition~2", "@x:join", "@3:frobnicate", ""):
        with pytest.raises(ValueError):
            soak.SoakAction.parse(bad)


def test_schedule_generation_deterministic_and_parseable():
    s1 = soak.SoakSchedule.generate(7, 30.0, 8)
    s2 = soak.SoakSchedule.generate(7, 30.0, 8)
    assert s1.describe() == s2.describe()
    assert s1.describe() != soak.SoakSchedule.generate(8, 30.0, 8).describe()
    # the printed schedule IS the schedule: parse -> describe is identity
    assert soak.SoakSchedule.parse(s1.describe()).describe() == s1.describe()
    assert s1.actions, "generated schedule is empty"
    assert all(0 < a.at_s < 30.0 for a in s1.actions)
    # statesync actions appear only when the cluster can serve them
    kinds = {a.kind for a in soak.SoakSchedule.generate(7, 120.0, 8).actions}
    assert "join_statesync" not in kinds


def test_repro_line_is_single_line_and_complete():
    line = soak.repro_line(7, 50, "k-regular:6", 30.0, "@3:join;@5:power:50:10")
    assert "\n" not in line
    for token in ("TMTPU_SOAK_REPRO:", "TMTPU_FAULT_SEED=", "TMTPU_SOAK_SEED=7",
                  "TMTPU_SOAK_NODES=50", "TMTPU_SOAK_TOPOLOGY=k-regular:6",
                  "TMTPU_SOAK_DURATION_S=30",
                  "TMTPU_SOAK_SCHEDULE='@3:join;@5:power:50:10'"):
        assert token in line, (token, line)
    # a statesync-enabled run must carry the cluster-shape knob too:
    # replaying a join_statesync schedule without it would misconfigure
    # the cluster and report bogus violations
    assert "TMTPU_SOAK_STATESYNC" not in line
    line2 = soak.repro_line(7, 8, "full", 30.0, "@3:join_statesync",
                            statesync=True)
    assert "TMTPU_SOAK_STATESYNC=1" in line2 and "\n" not in line2


# ---------------------------------------------------------------------------
# Continuous auditor (quick) — stub cluster, no real nodes
# ---------------------------------------------------------------------------


class _StubNode:
    def __init__(self):
        self.height = 0


class _StubFN:
    def __init__(self):
        self.node = _StubNode()

    @property
    def height(self):
        return self.node.height


class _StubCluster:
    """The auditor's surface: .nodes {idx: .node/.height} + block_hash."""

    def __init__(self, n):
        self.nodes = {i: _StubFN() for i in range(n)}
        self.hashes: dict[tuple[int, int], bytes] = {}

    def commit(self, idx, h, digest: bytes):
        self.hashes[(idx, h)] = digest
        self.nodes[idx].node.height = max(self.nodes[idx].node.height, h)

    def block_hash(self, i, h):
        return self.hashes.get((i, h))


def test_auditor_detects_fork_incrementally():
    c = _StubCluster(3)
    a = soak.ContinuousAuditor(c, liveness_budget_s=999)
    for i in range(3):
        c.commit(i, 1, b"\x01" * 32)
    a.sweep()
    assert not a.violations and a.heights_audited == 1
    # node 2 commits a DIFFERENT block at height 2: a fork, caught on the
    # next sweep even though heights 3+ keep agreeing afterwards
    c.commit(0, 2, b"\x02" * 32)
    c.commit(1, 2, b"\x02" * 32)
    c.commit(2, 2, b"\xbb" * 32)
    a.sweep()
    assert len(a.violations) == 1 and a.violations[0].kind == "fork"
    assert "height 2" in a.violations[0].detail
    c.commit(0, 3, b"\x03" * 32)
    c.commit(2, 3, b"\x03" * 32)
    a.sweep()
    assert len(a.violations) == 1  # no double-reporting of old heights


def test_auditor_reverifies_restarted_node_prefix():
    c = _StubCluster(2)
    a = soak.ContinuousAuditor(c, liveness_budget_s=999)
    c.commit(0, 1, b"\x01" * 32)
    c.commit(1, 1, b"\x01" * 32)
    a.sweep()
    assert not a.violations
    # node 1 restarts (new node object) and resyncs a FORKED height 1
    c.nodes[1].node = _StubNode()
    c.commit(1, 1, b"\xee" * 32)
    a.sweep()
    assert [v.kind for v in a.violations] == ["fork"]


def test_auditor_liveness_bound_and_expected_stalls():
    c = _StubCluster(2)
    a = soak.ContinuousAuditor(c, liveness_budget_s=0.15)
    c.commit(0, 1, b"\x01" * 32)
    a._t0 = a._last_advance = time.monotonic()
    a.sweep()
    assert not a.violations
    # an EXPECTED stall (quorum-cutting partition window) never trips
    a.expect_stall(True)
    time.sleep(0.3)
    a.sweep()
    assert not a.violations
    # cleared with a short grace: the bound re-arms and then trips ONCE
    a.expect_stall(False, grace_s=0.05)
    time.sleep(0.4)
    a.sweep()
    a.sweep()
    assert [v.kind for v in a.violations] == ["liveness"]
    # progress resets the episode: a later stall reports again
    c.commit(0, 2, b"\x02" * 32)
    a.sweep()
    time.sleep(0.3)
    a.sweep()
    assert [v.kind for v in a.violations] == ["liveness", "liveness"]


def test_liveness_violation_names_lagging_nodes_last_phase():
    """ISSUE 10 satellite 4: a stalled node's violation line names the
    last phase it completed (read from its flight recorder); nodes
    without an enabled tracer degrade to `last_phase=?`."""
    from tendermint_tpu.utils import trace

    c = _StubCluster(3)
    a = soak.ContinuousAuditor(c, liveness_budget_s=0.1)
    # node 0 leads; node 1 carries a tracer mid-precommit; node 2 has none
    c.commit(0, 3, b"\x01" * 32)
    tr = trace.Tracer("stub-lag", enabled=True)
    try:
        tr.mark("consensus.precommit", height=4, round=0)
        c.nodes[1].node.tracer = tr
        a._t0 = a._last_advance = time.monotonic()
        a.sweep()
        assert not a.violations
        time.sleep(0.25)
        a.sweep()
        assert [v.kind for v in a.violations] == ["liveness"]
        detail = a.violations[0].detail
        assert "lagging:" in detail, detail
        assert "node 1@h0 last_phase=consensus.precommit(h4)" in detail, detail
        assert "node 2@h0 last_phase=?" in detail, detail
    finally:
        tr.disable()


# ---------------------------------------------------------------------------
# Driven soaks
# ---------------------------------------------------------------------------


def test_mini_soak_explicit_schedule(tmp_path):
    """The quick-tier soak smoke: 4 nodes under tx load run an explicit
    composed schedule — minority partition (heal), a fast-sync joiner, and
    a voting-power promotion of that joiner — with the continuous auditor
    attached; zero violations and the joiner ends up in the validator set."""
    schedule = "@2:partition~1.5:3|rest;@5:join;@7:power:4:10"
    with repro("mini soak", schedule):
        report = soak.run_soak(
            str(tmp_path), seed=SEED, nodes=4, duration_s=12.0,
            topology="full", schedule_spec=schedule, liveness_budget_s=60.0)
        assert report.ok, f"violations: {report.violations}\n{report.repro}"
        assert report.actions_fired == 3
        assert report.txs_submitted > 0
        assert max(report.heights.values()) >= 3
        assert 4 in report.heights, "joiner never became part of the cluster"
        assert report.heights_audited >= 3
        # the repro line replays this exact run
        assert f"TMTPU_SOAK_SCHEDULE='{schedule}'" in report.repro


def test_lightcrowd_soak_acceptance(tmp_path):
    """ISSUE 20 acceptance: 16 gateway light clients ride a soak that
    composes a live posterior-corruption lunatic with a minority
    partition, a node restart and store bitrot. The crowd's gateway
    anchors at the earliest in-trust-period header (height 2, where the
    future lunatic still held 30/70 >= 1/3) with the lunatic in its
    witness pool; the first query into the forged window provokes a
    SUBSTANTIATED divergence — evidence lands in an honest node's pool
    and converges cluster-wide, the lying provider is permanently
    evicted from the gateway, and every VERIFIED answer the crowd ever
    received matches the honest chain (zero wrong-answer violations:
    the gateway refuses rather than lies, docs/LIGHT.md)."""
    cluster = fabric.Cluster(str(tmp_path), 5, powers=[30, 10, 10, 10, 10],
                             topology="full", trace=True)
    cluster.start()
    try:
        # honest warm-up past the forged window, then demote the future
        # lunatic so live byzantine power stays < 1/3 when it turns (the
        # attack is staged by POSTERIOR CORRUPTION of heights 3-4, where
        # the key held 30/70)
        assert cluster.wait_min_height(3, 90.0), cluster.heights()
        cluster.promote(0, 10)
        assert _wait(lambda: cluster.validator_power(0) == 10, 60.0), (
            cluster.validator_powers())

        schedule = soak.SoakSchedule.parse(
            "@0.5:byz:0:lunatic~3-4;@1.5:lightcrowd:16;"
            "@4:partition~1.5:4|rest;@6:restart:3;@8:bitrot:2:block")
        driver = soak.SoakDriver(cluster, schedule, SEED, duration_s=12.0,
                                 liveness_budget_s=60.0)
        report = driver.run()
        assert report.ok, f"violations: {report.violations}\n{report.repro}"
        assert report.byzantine == [0]

        # the crowd served real traffic and every verified answer was
        # audited against cluster agreement
        assert report.light["queries"] > 0, report.light
        assert report.light["served"] > 0, report.light
        assert report.light["answers_audited"] >= 1, report.light
        stats = driver._crowds[0].stats()
        assert stats["verdicts"].get("fresh", 0) > 0, stats
        # the lunatic is permanently evicted from the gateway's pool (the
        # honest first primary may fall as documented collateral of
        # detector symmetry, but serving converges to honest providers)
        assert "node0" in stats["gateway"]["evicted"], stats["gateway"]
        assert stats["gateway"]["rebuilds"] >= 1, stats["gateway"]

        # the substantiated divergence produced LightClientAttackEvidence
        # that converges onto every honest node's chain
        from tendermint_tpu.types.evidence import LightClientAttackEvidence

        def _has_attack_ev(idx):
            node = cluster.nodes[idx].node
            for h in range(1, node.block_store.height + 1):
                block = node.block_store.load_block(h)
                for ev in (block.evidence if block else ()):
                    if isinstance(ev, LightClientAttackEvidence):
                        return True
            return False

        def all_converged():
            driver.auditor.sweep()  # keep the evidence ledger advancing
            tracked = driver.auditor._ev_first
            converged = driver.auditor._ev_converged
            return (all(_has_attack_ev(i) for i in (1, 2, 3, 4))
                    and tracked and set(tracked) <= converged)

        assert _wait(all_converged, 120.0), {
            i: _has_attack_ev(i) for i in (1, 2, 3, 4)}
        assert not driver.auditor.violations, driver.auditor.violations
    finally:
        cluster.stop()


@pytest.mark.soak
def test_generated_soak_long(tmp_path):
    """A seeded GENERATED schedule on 8 nodes for ~45 s: partitions, link
    faults, churn, restarts, equivocation — composed against sustained tx
    load, audited continuously. The soak-marker tier: nightly material,
    never tier-1."""
    report = soak.run_soak(str(tmp_path), seed=11, nodes=8,
                           duration_s=45.0, topology="k-regular:4",
                           liveness_budget_s=90.0)
    assert report.ok, f"violations: {report.violations}\n{report.repro}"
    assert report.actions_fired >= 3
    assert max(report.heights.values()) >= 5


# ---------------------------------------------------------------------------
# Crash-storm plane (quick): grammar, durable generation/repro, and the
# clock-skew auditor invariants on stub data (tests/test_crash.py and
# tests/test_campaign.py drive the real fabrics).
# ---------------------------------------------------------------------------


def test_action_grammar_roundtrip_crash_and_skew():
    for entry in ("@36:crash~3:2", "@37:crash~3:4:torn", "@39:crash~-1:5",
                  "@42:crashstorm~3:2", "@45:skew~5:3:120", "@48:skew:3:-45"):
        a = soak.SoakAction.parse(entry)
        assert a.describe() == entry, entry


def test_generate_durable_weights_crash_kinds():
    s = soak.SoakSchedule.generate(1, 300.0, 8, durable=True)
    kinds = {a.kind for a in s.actions}
    assert kinds & {"crash", "crashstorm"}, sorted(kinds)
    # generated crashes always reboot: the never-reboot form (~-1) is for
    # hand-written quorum-cut scenarios, not random schedules
    assert all(a.dur_s > 0 for a in s.actions
               if a.kind in ("crash", "crashstorm"))
    # volatile clusters have nothing to reboot from -> no crash kinds
    kinds = {a.kind for a in soak.SoakSchedule.generate(7, 300.0, 8).actions}
    assert not kinds & {"crash", "crashstorm"}, sorted(kinds)


def test_repro_line_durable_token():
    line = soak.repro_line(7, 4, "full", 30.0, "@6:crash~-1:1", durable=True)
    assert "TMTPU_SOAK_DURABLE=1" in line and "\n" not in line
    assert "TMTPU_SOAK_DURABLE" not in soak.repro_line(
        7, 4, "full", 30.0, "@3:join")


class _TimedStubCluster(_StubCluster):
    def __init__(self, n):
        super().__init__(n)
        self.times: dict[int, float] = {}

    def block_time(self, i, h):
        return self.times.get(h)


def test_auditor_bft_time_strict_monotonicity():
    c = _TimedStubCluster(2)
    a = soak.ContinuousAuditor(c, liveness_budget_s=999)
    for h, t in ((1, 10.0), (2, 11.0), (3, 12.0)):
        c.times[h] = t
        for i in range(2):
            c.commit(i, h, bytes([h]) * 32)
    a.sweep()
    assert not a.violations
    # height 4's header time goes BACKWARD: flagged exactly once
    c.times[4] = 11.5
    for i in range(2):
        c.commit(i, 4, b"\x04" * 32)
    a.sweep()
    a.sweep()
    assert [v.kind for v in a.violations] == ["bft-time"]
    assert "height 4" in a.violations[0].detail


def test_auditor_false_expiry_from_pool_log():
    c = _StubCluster(2)
    a = soak.ContinuousAuditor(c, liveness_budget_s=999)

    class _Pool:
        expired_log = []

    for i in range(2):
        c.commit(i, 1, b"\x01" * 32)
    c.nodes[1].node.evidence_pool = _Pool()
    _Pool.expired_log.append(
        {"height": 90, "age_blocks": 110, "max_age_num_blocks": 100})
    a.sweep()
    assert not a.violations, "dual-bound expiry must pass"
    # a time-only expiry (height bound NOT exceeded) is the skew bug
    _Pool.expired_log.append(
        {"height": 150, "age_blocks": 50, "max_age_num_blocks": 100})
    a.sweep()
    a.sweep()  # seen-count: no double report of scanned entries
    assert [v.kind for v in a.violations] == ["false-expiry"]
    assert "node 1" in a.violations[0].detail
    _Pool.expired_log = []
