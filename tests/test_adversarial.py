"""Adversarial tests: byzantine double-prevote in a 4-node net, reactor
invalid-message fuzzing, and evil handshakes (reference:
consensus/byzantine_test.go, test/maverick/consensus/misbehavior.go:16,
p2p/conn/evil_secret_connection_test.go)."""

import os
import socket
import time

from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.consensus.misbehavior import double_prevote
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Transport
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time


def _wait(cond, timeout, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _mk_net(tmp_path, n):
    privs = [ed25519.gen_priv_key(bytes([40 + i]) * 32) for i in range(n)]
    genesis = GenesisDoc(
        chain_id="adv-chain", genesis_time=Time(1700002000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    nodes = []
    for i in range(n):
        cfg = make_test_config()
        cfg.set_root(str(tmp_path / f"n{i}"))
        os.makedirs(cfg.base.root_dir, exist_ok=True)
        cfg.base.fast_sync_mode = False
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.pex = False
        cfg.rpc.laddr = ""
        cfg.consensus.wal_path = ""
        nodes.append(Node(cfg, genesis=genesis, priv_validator=MockPV(privs[i]),
                          node_key=NodeKey(ed25519.gen_priv_key(bytes([80 + i]) * 32))))
    return nodes


def _connect_all(nodes):
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            b.switch.dial_peer(a.p2p_addr())


def test_byzantine_double_prevote_net_still_commits(tmp_path):
    """One of four validators equivocates every prevote; the net must keep
    committing (byz power 1/4 < 1/3) and honest nodes must capture
    DuplicateVoteEvidence (reference: consensus/byzantine_test.go)."""
    nodes = _mk_net(tmp_path, 4)
    byz, honest = nodes[0], nodes[1:]
    byz.consensus.misbehaviors["prevote"] = double_prevote(byz.switch)
    for n in nodes:
        n.start()
    try:
        _connect_all(nodes)
        assert _wait(lambda: all(n.block_store.height >= 3 for n in honest), 90), (
            [n.block_store.height for n in nodes])
        # chain identity across honest nodes
        h1 = [n.block_store.load_block(2).hash() for n in honest]
        assert len(set(h1)) == 1

        # equivocation detected somewhere: evidence pool or committed block
        def evidence_seen():
            for n in honest:
                if any(isinstance(e, DuplicateVoteEvidence)
                       for e in n.evidence_pool.pending_evidence(1 << 20)[0]):
                    return True
                for h in range(1, n.block_store.height + 1):
                    b = n.block_store.load_block(h)
                    if b and any(isinstance(e, DuplicateVoteEvidence)
                                 for e in b.evidence):
                        return True
            return False
        assert _wait(evidence_seen, 60)
    finally:
        for n in nodes:
            n.stop()


def test_reactor_invalid_message_fuzzing(tmp_path):
    """A handshaked peer spraying garbage on every channel must never kill
    the node: the peer is dropped or ignored and consensus keeps going."""
    nodes = _mk_net(tmp_path, 2)
    for n in nodes:
        n.start()
    try:
        _connect_all(nodes)
        assert _wait(lambda: nodes[0].block_store.height >= 2, 60)

        # evil client: real transport handshake, then garbage everywhere
        evil_key = NodeKey(ed25519.gen_priv_key(b"\x66" * 32))
        info = NodeInfo(node_id=evil_key.id(), network="adv-chain",
                        moniker="evil")
        info.channels = bytes([0x00, 0x20, 0x21, 0x22, 0x23, 0x30, 0x38,
                               0x40, 0x60, 0x61])
        transport = Transport(evil_key, info)
        conn, peer_info, _ = transport.dial(nodes[0].p2p_addr())
        from tendermint_tpu.p2p.connection import ChannelDescriptor, MConnection

        got = []
        mconn = MConnection(
            conn,
            [ChannelDescriptor(c, priority=1) for c in info.channels],
            on_receive=lambda ch, msg: got.append((ch, msg)),
            on_error=lambda e: got.append(("err", e)),
        )
        mconn.start()
        import random

        rng = random.Random(1)
        for ch in info.channels:
            for _ in range(10):
                junk = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 200)))
                if not mconn.send(ch, junk):
                    break
            time.sleep(0.02)
        time.sleep(1.0)
        mconn.stop()

        # the node survived and still commits
        h = nodes[0].block_store.height
        assert _wait(lambda: nodes[0].block_store.height >= h + 2, 60)
        for name, t in [(x.name, x) for x in __import__("threading").enumerate()]:
            assert "consensus" not in name or t.is_alive()
    finally:
        for n in nodes:
            n.stop()


def test_evil_handshake_garbage_and_slam(tmp_path):
    """Raw-socket garbage during the secret handshake + connect/slam loops
    must not crash the accept path (reference:
    p2p/conn/evil_secret_connection_test.go)."""
    nodes = _mk_net(tmp_path, 2)
    for n in nodes:
        n.start()
    try:
        _connect_all(nodes)
        addr = nodes[0].transport.node_info.listen_addr.split("://", 1)[1]
        host, port = addr.rsplit(":", 1)
        for payload in (b"", b"\x00" * 64, b"\xff" * 1024, b"GET / HTTP/1.1\r\n\r\n",
                        os.urandom(333)):
            try:
                s = socket.create_connection((host, int(port)), timeout=2)
                if payload:
                    s.sendall(payload)
                time.sleep(0.05)
                s.close()
            except OSError:
                pass
        # half-open: connect and vanish without closing politely
        socks = []
        for _ in range(5):
            try:
                socks.append(socket.create_connection((host, int(port)), timeout=2))
            except OSError:
                pass
        h = nodes[0].block_store.height
        assert _wait(lambda: nodes[0].block_store.height >= h + 2, 60)
        for s in socks:
            s.close()
    finally:
        for n in nodes:
            n.stop()
