"""The fast-sync verify-ahead pipeline (blockchain/pipeline.py): in-order
resolve, speculative-work discard, two-peer punishment, and convergence to
the depth-1 app hash — with and without device-failure injection inside the
pipeline (the ISSUE 2 acceptance matrix)."""

import types as pytypes

import pytest

from tendermint_tpu.blockchain.replay import ReplayCtx, make_chain
from tendermint_tpu.blockchain import pipeline as bpipe
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.types.block import Block, Commit, CommitSig
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote

CHAIN_ID = "pipe-chain"
N_BLOCKS = 10  # pool holds 10 blocks -> 9 appliable heights


def _mk_vals(n):
    privs = [ed25519.gen_priv_key((i + 1).to_bytes(2, "big") * 16)
             for i in range(n)]
    vals = ValidatorSet([Validator.new(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return [by_addr[v.address] for v in vals.validators], vals




def _tampered_copy(block):
    """Deep copy with the first LastCommit signature corrupted (inside the
    +2/3 serial stopping prefix, so resolve raises ErrWrongSignature)."""
    bad = Block.unmarshal(block.marshal())
    sig = bytearray(bad.last_commit.signatures[0].signature)
    sig[0] ^= 0xFF
    bad.last_commit.signatures[0].signature = bytes(sig)
    return bad


@pytest.fixture()
def chain():
    privs, vals = _mk_vals(4)
    return vals, make_chain(CHAIN_ID, N_BLOCKS, vals, privs)


def _reference_run(vals, blocks, monkeypatch):
    """Depth-1 (serial-behavior) run over a pristine pool: the convergence
    oracle every pipeline scenario must match."""
    monkeypatch.setenv("TM_TPU_VERIFY_AHEAD", "1")
    ctx = ReplayCtx(vals, CHAIN_ID)
    for b in blocks:
        ctx.pool.add_block("good", b)
    pipe = bpipe.VerifyAheadPipeline()
    while pipe.process_next(ctx):
        pass
    assert ctx.applied == list(range(1, N_BLOCKS)) and not ctx.punished
    return ctx


def _bad_commit_scenario(vals, blocks, monkeypatch):
    """Depth-4 pipeline over a pool where block 5 (sent by bad2) carries a
    corrupted LastCommit for block 4 (sent by bad1): heights 1..3 resolve
    in order, height 4's resolve fails mid-pipeline."""
    monkeypatch.setenv("TM_TPU_VERIFY_AHEAD", "4")
    ctx = ReplayCtx(vals, CHAIN_ID)
    for b in blocks:
        h = b.header.height
        peer = {4: "bad1", 5: "bad2"}.get(h, "good")
        ctx.pool.add_block(peer, _tampered_copy(b) if h == 5 else b)
    pipe = bpipe.VerifyAheadPipeline()
    while pipe.process_next(ctx):
        pass
    # In-order resolve up to the failure; all speculation discarded.
    assert ctx.applied == [1, 2, 3]
    assert len(pipe) == 0
    # BOTH senders punished (the bad LastCommit rides in the SECOND block),
    # and their blocks were dropped for re-request — exactly the serial path.
    assert ctx.punished == ["bad1", "bad2"]
    assert ctx.pool.peek_block(4) is None and ctx.pool.peek_block(5) is None
    assert ctx.pool.height == 4
    # "Re-requested" blocks arrive clean from a good peer: the pipeline
    # converges.
    ctx.pool.add_block("good", blocks[3])
    ctx.pool.add_block("good", blocks[4])
    while pipe.process_next(ctx):
        pass
    assert ctx.applied == list(range(1, N_BLOCKS))
    return ctx


def test_mid_pipeline_bad_commit_matches_serial(chain, monkeypatch):
    vals, blocks = chain
    ref = _reference_run(vals, blocks, monkeypatch)
    ctx = _bad_commit_scenario(vals, blocks, monkeypatch)
    assert ctx.app_hash == ref.app_hash


def test_mid_pipeline_bad_commit_with_device_fault(chain, monkeypatch):
    """TMTPU_FAULTS device failure INSIDE the pipeline: the injected raise
    at the speculative dispatch degrades through the circuit breaker to the
    host path within the same call — decisions, punishments, and the final
    app hash are byte-identical to the fault-free pipeline and the serial
    path."""
    from tendermint_tpu.ops import ed25519_batch
    from tendermint_tpu.utils import faults

    ref = _reference_run(*chain, monkeypatch)
    vals, blocks = chain
    # Route flushes at the device (crossover 0 pins the device path, the
    # verify-ahead force_device heuristic then applies) and make the FIRST
    # speculative dispatch die; the breaker keeps later flushes on host.
    # A huge cooldown keeps the background re-probe from touching the
    # device (and compiling kernels) during the test.
    monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "0")
    monkeypatch.setenv("TM_TPU_BREAKER_COOLDOWN_S", "3600")
    faults.configure(["ops.ed25519.device:raise@1"], seed=7)
    try:
        ctx = _bad_commit_scenario(vals, blocks, monkeypatch)
    finally:
        faults.clear()
        ed25519_batch.BREAKER.reset()
    assert ed25519_batch.BREAKER.failures >= 1  # the fault really fired
    assert ctx.app_hash == ref.app_hash


def test_depth_env_clamped(monkeypatch):
    monkeypatch.setenv("TM_TPU_VERIFY_AHEAD", "0")
    assert bpipe.verify_ahead_depth() == 1
    monkeypatch.setenv("TM_TPU_VERIFY_AHEAD", "junk")
    assert bpipe.verify_ahead_depth() == bpipe.DEFAULT_DEPTH
    monkeypatch.delenv("TM_TPU_VERIFY_AHEAD")
    assert bpipe.verify_ahead_depth() == bpipe.DEFAULT_DEPTH


def test_real_reactor_end_to_end_depths_agree(monkeypatch):
    """The REAL v0 reactor glue (no sockets): a chain built by a source
    BlockExecutor is replayed through BlockchainReactor._try_sync with a
    real executor + stores, at depth 1 and depth 4. Both must apply every
    block and land on the source's app hash."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    from tendermint_tpu.mempool.mempool import Mempool
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.state.state import make_genesis_state
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.store.db import MemDB
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    privs = [ed25519.gen_priv_key(bytes([80 + i]) * 32) for i in range(2)]
    gd = GenesisDoc(chain_id="pipe-e2e", genesis_time=Time(1700000000, 0),
                    validators=[GenesisValidator(b"", p.pub_key(), 10)
                                for p in privs])
    gd.validate_and_complete()
    by_addr = {p.pub_key().address(): p for p in privs}

    def commit_for(state, block):
        bid = BlockID(hash=block.hash(),
                      part_set_header=PartSet.from_data(block.marshal()).header())
        sigs = []
        for i, val in enumerate(state.validators.validators):
            v = Vote(type=PRECOMMIT_TYPE, height=block.header.height, round=0,
                     block_id=bid, timestamp=block.header.time.add_ns(1_000_000),
                     validator_address=val.address, validator_index=i)
            sig = by_addr[val.address].sign(v.sign_bytes(state.chain_id))
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address,
                                  v.timestamp, sig))
        return bid, Commit(height=block.header.height, round=0, block_id=bid,
                           signatures=sigs)

    # Source chain: 8 blocks through a real executor.
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    ss = StateStore(MemDB())
    ss.save(state)
    bx = BlockExecutor(ss, app, mempool=Mempool(app))
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    blocks = []
    block_time = Time(1700000010, 0)
    for h in range(1, 9):
        block = bx.create_proposal_block(
            h, state, last_commit, state.validators.get_proposer().address,
            block_time=block_time)
        bid, commit = commit_for(state, block)
        state, _ = bx.apply_block(state, bid, block)
        last_commit = commit
        # validation pins h+1's time to the weighted median of h's commit
        # timestamps (block time + 1 ms, per commit_for)
        block_time = block.header.time.add_ns(1_000_000)
        blocks.append(block)

    results = {}
    for depth in (1, 4):
        monkeypatch.setenv("TM_TPU_VERIFY_AHEAD", str(depth))
        rstate = make_genesis_state(gd)
        rapp = KVStoreApplication()
        rss = StateStore(MemDB())
        rss.save(rstate)
        rbx = BlockExecutor(rss, rapp, mempool=Mempool(rapp))
        rbs = BlockStore(MemDB())
        reactor = BlockchainReactor(rstate, rbx, rbs, fast_sync=True)
        for b in blocks:
            reactor.pool.add_block("p", b)
        applied = 0
        while reactor._try_sync():
            applied += 1
        # 8 pooled blocks -> 7 appliable heights (the last needs a successor)
        assert applied == 7 and rbs.height == 7
        assert reactor.state.last_block_height == 7
        results[depth] = reactor.state.app_hash
        assert rbs.load_block(7).hash() == blocks[6].hash()
    assert results[1] == results[4]


def test_validator_set_change_discards_speculation(chain, monkeypatch):
    """An apply that changes the validator-set hash must invalidate
    speculative dispatches made against the old set: the pipeline discards
    them, re-dispatches against the new set, and converges — decisions
    can't drift from serial. (The power bump keeps sort order, so the old
    commits still verify under the new set; what changes is the hash the
    guard watches.)"""
    vals, blocks = chain
    ref = _reference_run(vals, blocks, monkeypatch)
    monkeypatch.setenv("TM_TPU_VERIFY_AHEAD", "4")
    ctx = ReplayCtx(vals, CHAIN_ID)
    for b in blocks:
        ctx.pool.add_block("good", b)
    real_exec = ctx.block_exec

    class _RotatingExec:
        def apply_block(self, state, block_id, block):
            state, rh = real_exec.apply_block(state, block_id, block)
            if block.header.height == 2:
                rotated = state.validators.copy()
                rotated.update_with_change_set(
                    [Validator.new(rotated.validators[0].pub_key, 20)])
                state = pytypes.SimpleNamespace(validators=rotated,
                                                chain_id=CHAIN_ID)
            return state, rh

    ctx.block_exec = _RotatingExec()
    pipe = bpipe.VerifyAheadPipeline()
    discards = {"n": 0}
    orig_discard = pipe.discard

    def spy_discard():
        discards["n"] += 1
        orig_discard()

    pipe.discard = spy_discard
    while pipe.process_next(ctx):
        pass
    assert discards["n"] >= 1, "stale-valset speculation was never discarded"
    assert ctx.applied == list(range(1, N_BLOCKS)) and not ctx.punished
    assert ctx.app_hash == ref.app_hash
