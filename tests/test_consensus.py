"""In-process multi-validator consensus harness (the reference's
consensus/common_test.go pattern): N full consensus state machines in one
process, wired by direct message delivery instead of TCP, driving real blocks
through real ABCI apps. Plus WAL crash-recovery checks."""

import os
import tempfile
import time

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.consensus.state_machine import (
    BlockPartMessage,
    ConsensusState,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time


class Node:
    def __init__(self, genesis, pv, cfg, wal_dir=None):
        self.app = KVStoreApplication()
        self.state_store = StateStore(MemDB())
        self.block_store = BlockStore(MemDB())
        self.mempool = Mempool(self.app)
        state = make_genesis_state(genesis)
        self.state_store.save(state)
        self.block_exec = BlockExecutor(
            self.state_store, self.app, mempool=self.mempool,
            block_store=self.block_store,
        )
        wal = WAL(wal_dir) if wal_dir else None
        self.cs = ConsensusState(
            cfg.consensus, state, self.block_exec, self.block_store,
            mempool=self.mempool, priv_validator=pv, wal=wal,
        )


def make_net(n, wal_base=None):
    privs = [ed25519.gen_priv_key(bytes([50 + i]) * 32) for i in range(n)]
    pvs = [MockPV(p) for p in privs]
    genesis = GenesisDoc(
        chain_id="harness-chain",
        genesis_time=Time(1700001000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    cfg = make_test_config()
    nodes = [
        Node(genesis, pvs[i], cfg,
             wal_dir=os.path.join(wal_base, f"wal{i}") if wal_base else None)
        for i in range(n)
    ]

    # the in-memory "switch": deliver every internally-generated message to
    # every other node as if gossiped
    def wire(i):
        def bcast(msg):
            for j, other in enumerate(nodes):
                if j == i:
                    continue
                if isinstance(msg, VoteMessage):
                    other.cs.add_vote(msg.vote.copy(), peer_id=f"peer{i}")
                elif isinstance(msg, ProposalMessage):
                    other.cs.set_proposal(msg.proposal, peer_id=f"peer{i}")
                elif isinstance(msg, BlockPartMessage):
                    other.cs.add_proposal_block_part(
                        msg.height, msg.round, msg.part, peer_id=f"peer{i}")
        nodes[i].cs.broadcast = bcast

    for i in range(n):
        wire(i)
    return nodes


def wait_height(nodes, h, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(n.block_store.height >= h for n in nodes):
            return True
        time.sleep(0.05)
    return False


def test_single_validator_chain():
    nodes = make_net(1)
    nodes[0].mempool.check_tx(b"solo=1")
    for n in nodes:
        n.cs.start()
    try:
        assert wait_height(nodes, 3, timeout=30), (
            f"heights: {[n.block_store.height for n in nodes]}"
        )
        b1 = nodes[0].block_store.load_block(1)
        assert b1 is not None
    finally:
        for n in nodes:
            n.cs.stop()


def test_four_validator_net_commits_blocks():
    nodes = make_net(4)
    nodes[0].mempool.check_tx(b"a=1")
    nodes[1].mempool.check_tx(b"b=2")
    for n in nodes:
        n.cs.start()
    try:
        assert wait_height(nodes, 3, timeout=60), (
            f"heights: {[n.block_store.height for n in nodes]}"
        )
        # all nodes committed identical blocks
        for h in range(1, 4):
            hashes = {n.block_store.load_block(h).hash() for n in nodes}
            assert len(hashes) == 1, f"fork at height {h}!"
        # applied state trails the block store by at most the in-flight block
        st = nodes[0].state_store.load()
        assert st.last_block_height >= 2
    finally:
        for n in nodes:
            n.cs.stop()


def test_net_progresses_with_one_node_down():
    """3 of 4 validators (>2/3) must still make progress."""
    nodes = make_net(4)
    for n in nodes[:3]:
        n.cs.start()
    try:
        assert wait_height(nodes[:3], 2, timeout=60), (
            f"heights: {[n.block_store.height for n in nodes[:3]]}"
        )
    finally:
        for n in nodes[:3]:
            n.cs.stop()


def test_wal_written_and_replayable():
    with tempfile.TemporaryDirectory() as d:
        nodes = make_net(1, wal_base=d)
        for n in nodes:
            n.cs.start()
        try:
            assert wait_height(nodes, 2, timeout=30)
        finally:
            for n in nodes:
                n.cs.stop()
        # WAL contains EndHeight markers for committed heights
        wal = WAL(os.path.join(d, "wal0"))
        from tendermint_tpu.consensus.wal import EndHeightMessage

        heights = [tm.msg.height for tm, _ in wal.iter_messages()
                   if isinstance(tm.msg, EndHeightMessage)]
        assert 1 in heights and 2 in heights
