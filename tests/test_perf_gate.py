"""Perf regression gate for the consensus-path verify flushes (VERDICT r3
weak #4/#8): verify_commit and verify_commit_light at 256 and 1024
validators must stay BATCHED — exactly one kernel dispatch per call, the
scalar fallback never taken — and complete within a generous wall-clock
ceiling so a silent fall-back to serial verification (the reference's
per-signature loop, types/validator_set.go:719) cannot land unnoticed.

Flush counting is the hard gate; the wall-clock ceilings are sanity bounds
chosen loose enough for the noisy 1-core CI host."""

import time

import pytest

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote

CHAIN_ID = "perf-gate-chain"
WALL_CEILING_S = {256: 20.0, 1024: 40.0}


def _signed_commit(vals, privs, height, round_, bid, ts):
    """One precommit per validator over the canonical sign bytes — the
    single commit builder every gate in this module uses."""
    sigs = []
    for i, (p, v) in enumerate(zip(privs, vals.validators)):
        vote = Vote(type=PRECOMMIT_TYPE, height=height, round=round_,
                    block_id=bid, timestamp=ts, validator_address=v.address,
                    validator_index=i)
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, v.address, ts,
                              p.sign(vote.sign_bytes(CHAIN_ID))))
    return Commit(height=height, round=round_, block_id=bid, signatures=sigs)


def _mk_vals(n):
    privs = [ed25519.gen_priv_key((i + 1).to_bytes(2, "big") * 16)
             for i in range(n)]
    vals = ValidatorSet([Validator.new(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return [by_addr[v.address] for v in vals.validators], vals


def _commit(n):
    privs, vals = _mk_vals(n)
    bid = BlockID(hash=b"\x42" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x43" * 32))
    return vals, _signed_commit(vals, privs, 3, 0, bid, Time(1_700_000_500, 0))


class _FlushCounter:
    """Counts kernel dispatches vs scalar fallbacks through the verifier."""

    def __init__(self, monkeypatch):
        self.kernel = 0
        self.scalar = 0
        orig = cbatch._KernelBatchVerifier.dispatch
        counter = self

        def counted(vself, force_device=False):
            small = len(vself._items) < cbatch.batch_min(
                vself._batch_min_default)
            if small and not force_device:
                counter.scalar += 1
            else:
                counter.kernel += 1
            return orig(vself, force_device=force_device)

        monkeypatch.setattr(cbatch._KernelBatchVerifier, "dispatch", counted)


@pytest.mark.parametrize("n_vals", [256, 1024])
def test_verify_commit_stays_batched(n_vals, monkeypatch):
    vals, commit = _commit(n_vals)
    # warm BOTH call shapes outside the gate (first-ever XLA compile of a
    # new padded shape is O(minutes) and must not count against the ceiling)
    vals.verify_commit(CHAIN_ID, commit.block_id, 3, commit)
    vals.verify_commit_light(CHAIN_ID, commit.block_id, 3, commit)

    fc = _FlushCounter(monkeypatch)
    t0 = time.monotonic()
    vals.verify_commit(CHAIN_ID, commit.block_id, 3, commit)
    full_s = time.monotonic() - t0
    assert fc.kernel == 1, f"verify_commit used {fc.kernel} kernel flushes"
    assert fc.scalar == 0, "verify_commit fell back to the scalar loop"

    t0 = time.monotonic()
    vals.verify_commit_light(CHAIN_ID, commit.block_id, 3, commit)
    light_s = time.monotonic() - t0
    assert fc.kernel == 2, "verify_commit_light did not flush exactly once"
    assert fc.scalar == 0

    ceiling = WALL_CEILING_S[n_vals]
    assert full_s < ceiling, f"verify_commit {full_s:.1f}s > {ceiling}s"
    assert light_s < ceiling, f"verify_commit_light {light_s:.1f}s > {ceiling}s"


@pytest.mark.quick
def test_verify_ahead_batches_blocking_fetches(monkeypatch):
    """The verify-ahead pipeline gate (no wall clock, no kernels): over the
    same chain, a depth-4 pipeline must issue NO MORE blocking device
    fetches than depth 1 — the whole point of verify-ahead is amortizing
    the per-fetch sync floor across in-flight decisions. Kernel dispatch is
    stubbed with a sentinel "device" output (the scalar result computed
    eagerly), and the fetch-spy counts crypto_batch._device_get calls, the
    one choke point every blocking readback passes through."""
    from tendermint_tpu.blockchain.replay import ReplayCtx, make_chain
    from tendermint_tpu.blockchain import pipeline as bpipe

    n_blocks = 8
    privs, vals = _mk_vals(4)
    blocks = make_chain(CHAIN_ID, n_blocks + 1, vals, privs)

    def fake_dispatch(self, force_device=False):
        items, self._items = self._items, []
        out = [ed25519.verify(p, m, s) for (p, m, s) in items]
        return cbatch.PendingVerify(
            [object()], lambda _f, _r=(all(out), out): _r)

    fetches = {"n": 0}

    def counting_get(tree):
        fetches["n"] += 1
        return tree  # sentinel "device" outputs need no real transfer

    monkeypatch.setattr(cbatch._KernelBatchVerifier, "dispatch", fake_dispatch)
    monkeypatch.setattr(cbatch, "_device_get", counting_get)

    def run_depth(depth):
        monkeypatch.setenv("TM_TPU_VERIFY_AHEAD", str(depth))
        ctx = ReplayCtx(vals, CHAIN_ID)
        for b in blocks:
            ctx.pool.add_block("p", b)
        pipe = bpipe.VerifyAheadPipeline()
        fetches["n"] = 0
        applied = 0
        while pipe.process_next(ctx):
            applied += 1
        assert applied == n_blocks
        return fetches["n"]

    depth1 = run_depth(1)
    depth4 = run_depth(4)
    assert depth1 == n_blocks, f"depth-1 issued {depth1} fetches, expected one per block"
    # strictly fewer (which also satisfies the <= acceptance bound)
    assert depth4 < depth1, (
        f"depth-4 pipeline did not batch readbacks: {depth4} fetches vs "
        f"depth-1's {depth1}")


@pytest.mark.quick
def test_sharded_registry_bitmap_matches_single_device(monkeypatch):
    """ISSUE 4 acceptance gate, quick tier: on the multi-device CPU mesh the
    REGISTRY-level dispatch (crypto/batch.create_batch_verifier -- the exact
    object verify_commit_async, fast-sync, the vote drain, and range_verify
    construct) must shard and return a bitmap identical to TM_TPU_SHARD=0
    single-device for the same batch, valid + tampered lanes, for ed25519,
    sr25519, and the mixed router.

    Small tiles keep the one-time XLA compiles bounded on the CI host: the
    sharded path dispatches in fixed ndev*JNP_TILE chunks, so shrinking
    JNP_TILE shrinks the compiled chunk without changing the routing."""
    import jax

    if jax.local_device_count() < 2:
        pytest.skip("needs the multi-device CPU mesh")
    from tendermint_tpu.crypto import sr25519
    from tendermint_tpu.ops import ed25519_batch as edb
    from tendermint_tpu.parallel import batch_shard

    monkeypatch.setattr(edb, "JNP_TILE", 16)
    monkeypatch.setenv("TM_TPU_SHARD_MIN", "16")
    monkeypatch.setenv("TM_TPU_BATCH_MIN", "1")

    def ed_item(i, tamper=False):
        p = ed25519.gen_priv_key(bytes([i % 61 + 1]) * 32)
        m = b"gate-ed-%d" % i
        s = p.sign(m)
        if tamper:
            s = s[:-1] + bytes([s[-1] ^ 1])
        return (p.pub_key(), m, s)

    def sr_item(i, tamper=False):
        p = sr25519.gen_priv_key(bytes([i % 13 + 1]) * 32)
        m = b"gate-sr-%d" % i
        s = p.sign(m)
        if tamper:
            s = s[:-2] + bytes([s[-2] ^ 1]) + s[-1:]
        return (p.pub_key(), m, s)

    ed_items = [ed_item(i, tamper=i in (3, 20)) for i in range(44)]
    sr_items = [sr_item(i, tamper=i == 5) for i in range(20)]
    # Mixed: interleave so the router's order restoration is exercised.
    mixed, want_mixed = [], []
    for i in range(36):
        if i % 2 == 0:
            mixed.append(ed_item(i, tamper=i == 8))
            want_mixed.append(i != 8)
        else:
            mixed.append(sr_item(i, tamper=i == 11))
            want_mixed.append(i != 11)

    def registry(key_type, items):
        v = cbatch.create_batch_verifier(key_type)
        for pk, m, s in items:
            v.add(pk, m, s)
        return v.dispatch().resolve()

    cases = [("ed25519", ed_items, [i not in (3, 20) for i in range(44)]),
             ("sr25519", sr_items, [i != 5 for i in range(20)]),
             (None, mixed, want_mixed)]
    for key_type, items, want in cases:
        monkeypatch.delenv("TM_TPU_SHARD", raising=False)
        assert batch_shard.should_shard(len(items))
        all_ok_sh, sharded = registry(key_type, items)
        monkeypatch.setenv("TM_TPU_SHARD", "0")
        all_ok_si, single = registry(key_type, items)
        monkeypatch.delenv("TM_TPU_SHARD", raising=False)
        assert sharded == single, f"{key_type}: sharded != single-device"
        assert sharded == want, f"{key_type}: bitmap != scalar ground truth"
        assert all_ok_sh == all_ok_si == all(want)


def test_range_verify_one_flush_and_no_scalar_header_hashing(monkeypatch):
    """BASELINE config 3's shape must not silently regress: the whole range
    verifies in EXACTLY one kernel flush, and header hashing goes through
    the batched merkle forest (precompute fills every cache; the scalar
    fallback inside Header.hash must not run for range members)."""
    from tendermint_tpu.light.range_verify import verify_header_range
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.light_block import LightBlock, SignedHeader

    n_headers = 65
    privs, vals = _mk_vals(1)
    chain = []
    last_bid = BlockID()
    for h in range(1, n_headers + 1):
        header = Header(chain_id=CHAIN_ID, height=h, time=Time(1_700_000_000 + 10 * h, 0),
                        last_block_id=last_bid, validators_hash=vals.hash(),
                        next_validators_hash=vals.hash(),
                        proposer_address=vals.validators[0].address)
        bid = BlockID(hash=header.hash(),
                      part_set_header=PartSetHeader(total=1, hash=b"\x44" * 32))
        commit = _signed_commit(vals, privs, h, 1, bid,
                                Time(header.time.seconds, 0))
        chain.append(LightBlock(signed_header=SignedHeader(header, commit),
                                validator_set=vals.copy()))
        last_bid = bid

    trusted, rest = chain[0], chain[1:]
    now = Time(1_700_000_000 + 10 * (n_headers + 2), 0)
    verify_header_range(trusted, rest, 14 * 86400.0, now)  # warm/compile
    for lb in rest:
        lb.signed_header.header._hash_cache = None

    from tendermint_tpu.crypto import merkle

    def no_scalar_header_hash(items):
        if len(items) == 14:
            raise AssertionError(
                "scalar header hash ran inside range verify; the batched "
                "forest (precompute_header_hashes) must cover the range")
        return orig_hash(items)

    orig_hash = merkle.hash_from_byte_slices
    fc = _FlushCounter(monkeypatch)
    monkeypatch.setattr(merkle, "hash_from_byte_slices", no_scalar_header_hash)
    try:
        verify_header_range(trusted, rest, 14 * 86400.0, now)
    finally:
        monkeypatch.setattr(merkle, "hash_from_byte_slices", orig_hash)
    assert fc.kernel == 1, (
        f"range verify used {fc.kernel} kernel flushes, expected 1")
    assert fc.scalar == 0, "range verify fell back to the scalar loop"
