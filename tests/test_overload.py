"""Overload-resilience plane (docs/OVERLOAD.md): peer misbehavior scoring
with escalating disconnect/ban sanctions, ingress rate limiting (recv-side
flow control + per-channel message ceilings), priority load shedding, the
broadcast_tx admission gate, and the nemesis `flood` action.

Quick tier: scoreboard/ban-lifecycle units (simulated clock), shed-queue
and rate-limiter units, the recv-throttle regression, mempool-flood
scoring (gossip/recv threads survive a full mempool), ban refusal at the
dial AND accept seams, the RPC admission gate, and a 2-node in-process
flood smoke — a flooding low-power validator is banned while the majority
keeps committing.

Slow tier: the 4-node mesh scenario from the acceptance criteria — one
peer floods invalid-signature votes (nemesis flood action) + oversized
txs; the flooder is banned on the honest nodes (metric increments, redial
refused, post-ban traffic never reaches the drain) and the honest 3/4
keep committing. Failures print the TMTPU_* repro line.
"""

import os
import queue as _stdqueue
import socket as _socket
import threading
import time
import urllib.request

import pytest

from test_nemesis import (  # the in-process socketpair mesh helpers
    _PlainConn,
    _link,
    _stop_all,
    _wait,
    repro,
)

from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.utils import faults, lockwitness, nemesis, peerscore

SEED = 2027
VOTE_CH = 0x22


@pytest.fixture(autouse=True)
def _clean_chaos():
    faults.configure([], seed=SEED)
    nemesis.clear()
    yield
    nemesis.clear()
    nemesis.PLANE.on_heal.clear()
    faults.clear()


def _board(clock, **kw):
    defaults = dict(halflife_s=100.0, disconnect_score=20.0, ban_score=40.0,
                    ban_duration_s=10.0, ban_max_duration_s=35.0)
    defaults.update(kw)
    return peerscore.PeerScoreBoard(peerscore.ScoreConfig(**defaults),
                                    clock=clock)


# ---------------------------------------------------------------------------
# Scoreboard units (simulated time)
# ---------------------------------------------------------------------------


def test_score_decay_over_simulated_time():
    t = [0.0]
    b = _board(lambda: t[0])
    b.record("p1", "invalid_signature")  # 8 points
    assert b.score("p1") == pytest.approx(8.0)
    t[0] = 100.0  # one half-life
    assert b.score("p1") == pytest.approx(4.0)
    t[0] = 300.0  # three half-lives
    assert b.score("p1") == pytest.approx(1.0)
    # an unknown offense scores 1 point; unattributed reports score no one
    assert b.record("p2", "???") == peerscore.SANCTION_NONE
    assert b.score("p2") == pytest.approx(1.0)
    assert b.record("", "invalid_signature") == peerscore.SANCTION_NONE
    # fully-decayed entries are pruned from the books (anti-DoS hygiene)
    t[0] = 5000.0
    assert b.snapshot()["scores"] == {}


def test_disconnect_fires_at_and_above_threshold():
    t = [0.0]
    b = _board(lambda: t[0])
    hits = []
    b.on_disconnect.append(lambda pid, reason: hits.append(pid))
    b.record("p1", "bad_message")  # 10 < 20: no sanction yet
    assert not hits
    assert b.record("p1", "bad_message") == peerscore.SANCTION_DISCONNECT
    assert hits == ["p1"]
    # EVERY further offense above the threshold re-fires: a redialing
    # peer pacing its score inside [disconnect, ban) must not misbehave
    # sanction-free
    assert b.record("p1", "checktx_reject") == peerscore.SANCTION_DISCONNECT
    assert hits == ["p1", "p1"]


def test_ban_expiry_and_reoffense_backoff():
    t = [0.0]
    b = _board(lambda: t[0])
    banned = []
    b.on_ban.append(lambda pid, until: banned.append((pid, until)))
    for _ in range(4):  # 4 x 10 crosses ban_score 40
        b.record("p1", "bad_message")
    assert b.is_banned("p1") and banned and banned[0][1] == pytest.approx(10.0)
    assert b.score("p1") == 0.0  # ban resets the score
    t[0] = 9.9
    assert b.is_banned("p1")
    t[0] = 10.1  # expiry is lazy but exact
    assert not b.is_banned("p1")
    # re-offense: duration doubles (10 -> 20)
    for _ in range(4):
        b.record("p1", "bad_message")
    assert b.is_banned("p1") and banned[1][1] == pytest.approx(t[0] + 20.0)
    t[0] += 20.1
    # third offense: 40 would exceed the cap -> clamped at 35
    for _ in range(4):
        b.record("p1", "bad_message")
    assert banned[2][1] == pytest.approx(t[0] + 35.0)
    d = b.describe()
    assert d["ban_counts"]["p1"] == 3 and d["bans_total"] == 3
    assert d["offenses"]["p1:bad_message"] == 12


def test_describe_and_snapshot_shapes():
    t = [0.0]
    b = _board(lambda: t[0])
    b.record("px", "invalid_signature")
    b.count_shed("vote")
    b.count_rate_limited("px", "0x22")
    d = b.describe()
    assert d["scores"]["px"] == pytest.approx(8.0)
    assert d["shed"] == {"vote": 1} and d["rate_limited"] == {"px:0x22": 1}
    assert d["config"]["ban_score"] == 40.0
    s = b.snapshot()
    assert s["bans_total"] == 0 and s["rate_limited"] == {("px", "0x22"): 1}


def test_honest_overload_rates_never_sanction():
    """The review-hardened tuning: offenses an HONEST peer emits
    continuously while WE are overloaded (full mempool, app rejects)
    must never cross the default disconnect threshold at honest gossip
    rates — equilibrium = points * rate * halflife/ln2."""
    t = [0.0]
    b = peerscore.PeerScoreBoard(clock=lambda: t[0])  # default config
    # 10 tx/s into a full/rejecting mempool for 10 simulated minutes
    for i in range(6000):
        t[0] = i * 0.1
        off = "mempool_full" if i % 2 else "checktx_reject"
        assert b.record("honest01", off) == peerscore.SANCTION_NONE
    assert b.score("honest01") < b.config.disconnect_score
    # ...while a 500/s flood of the same offense still bans in seconds
    t2 = [0.0]
    b2 = peerscore.PeerScoreBoard(clock=lambda: t2[0])
    sanction = None
    for i in range(10000):
        t2[0] = i * 0.002
        sanction = b2.record("flooder", "mempool_full")
        if sanction == peerscore.SANCTION_BAN:
            break
    assert sanction == peerscore.SANCTION_BAN and t2[0] < 15.0


# ---------------------------------------------------------------------------
# Shed queue + rate limiter units
# ---------------------------------------------------------------------------


def test_shed_queue_priorities_and_fifo():
    shed = []
    q = peerscore.ShedQueue(maxsize=3, on_shed=shed.append)
    assert q.put("s0", priority=peerscore.PRIO_STALE, channel="vote")
    assert q.put("f0", priority=peerscore.PRIO_FUTURE, channel="block_part")
    assert q.put("l0", priority=peerscore.PRIO_LIVE, channel="vote")
    # full: a live arrival evicts the oldest lowest class (the stale one)
    assert q.put("l1", priority=peerscore.PRIO_LIVE, channel="vote")
    # full of future+live: another stale arrival sheds itself
    assert not q.put("s1", priority=peerscore.PRIO_STALE, channel="vote")
    # equal-lowest arrival (future vs future) sheds the arrival, not the queue
    assert not q.put("f1", priority=peerscore.PRIO_FUTURE, channel="block_part")
    # control items are always admitted, even over capacity
    q.put(None)
    assert q.qsize() == 4
    # admitted items drain in arrival order
    assert [q.get_nowait() for _ in range(4)] == ["f0", "l0", "l1", None]
    with pytest.raises(_stdqueue.Empty):
        q.get_nowait()
    assert q.shed_counts == {"vote": 2, "block_part": 1}
    assert shed == ["vote", "vote", "block_part"]


def test_shed_queue_get_timeout_and_unbounded():
    q = peerscore.ShedQueue(maxsize=0)  # unbounded: never sheds
    for i in range(50):
        assert q.put(i, priority=peerscore.PRIO_STALE, channel="vote")
    assert q.qsize() == 50 and not q.shed_counts
    q2 = peerscore.ShedQueue(maxsize=10)
    t0 = time.monotonic()
    with pytest.raises(_stdqueue.Empty):
        q2.get(timeout=0.05)
    assert time.monotonic() - t0 >= 0.04


def test_rate_spec_and_token_bucket():
    rates = peerscore.parse_rate_spec("0x22:5, 0x30:100")
    assert rates == {0x22: 5.0, 0x30: 100.0}
    for bad in ("0x22", "0x22:0", "0x22:-1"):
        with pytest.raises(ValueError):
            peerscore.parse_rate_spec(bad)
    t = [0.0]
    rl = peerscore.ChannelRateLimiter({1: 5.0}, clock=lambda: t[0])
    assert sum(rl.allow(1) for _ in range(20)) == 5  # the 1s burst
    t[0] = 0.4  # 2 tokens refill
    assert sum(rl.allow(1) for _ in range(20)) == 2
    assert all(rl.allow(9) for _ in range(100))  # unconfigured: unlimited
    # fractional rates must accumulate to a deliverable token, not
    # silently blackhole the channel (burst cap is >= one message)
    rl2 = peerscore.ChannelRateLimiter({2: 0.5}, clock=lambda: t[0])
    assert rl2.allow(2) and not rl2.allow(2)
    t[0] += 2.0  # 0.5/s * 2s = 1 token
    assert rl2.allow(2) and not rl2.allow(2)


# ---------------------------------------------------------------------------
# MConnection: recv throttle regression + per-channel ceilings
# ---------------------------------------------------------------------------


def _mconn_pair(recv_rate=5_120_000, msg_rates=None, on_rate_limited=None):
    from tendermint_tpu.p2p.connection import ChannelDescriptor, MConnection

    sa, sb = _socket.socketpair()
    received = []
    a = MConnection(_PlainConn(sa), [ChannelDescriptor(id=1)],
                    on_receive=lambda *x: None, local_id="aaaa",
                    remote_id="bbbb")
    b = MConnection(_PlainConn(sb), [ChannelDescriptor(id=1)],
                    on_receive=lambda ch, msg: received.append((ch, msg)),
                    local_id="bbbb", remote_id="aaaa", recv_rate=recv_rate,
                    msg_rates=msg_rates, on_rate_limited=on_rate_limited)
    a.start()
    b.start()
    return a, b, received


def test_recv_rate_throttles_a_fast_sender():
    """ISSUE 5 satellite 1: recv_monitor.limit is actually wired — a
    sender pushing ~64 KB against a 64 KB/s recv_rate must be held to
    roughly the configured rate (was: recv_monitor constructed but
    limit() never called; the flood arrived as fast as TCP allowed)."""
    payload = os.urandom(8 * 1024)
    a, b, received = _mconn_pair(recv_rate=64_000)
    try:
        t0 = time.monotonic()
        for _ in range(8):
            assert a.send(1, payload)
        assert _wait(lambda: len(received) == 8, 15, 0.01), \
            f"only {len(received)}/8 messages arrived"
        elapsed = time.monotonic() - t0
        # ~65 KB of frames at 64 KB/s ≈ 1s; the monitor's first sample
        # window grants a head start, so assert a generous lower bound
        # (unthrottled, the same transfer completes in < 50 ms)
        assert elapsed > 0.4, f"recv side not throttled: {elapsed:.3f}s"
        assert received[0][1] == payload
    finally:
        a.stop()
        b.stop()


def test_per_channel_message_ceiling_scores_not_processes():
    limited = []
    a, b, received = _mconn_pair(msg_rates={1: 3.0},
                                 on_rate_limited=limited.append)
    try:
        for i in range(12):
            assert a.send(1, b"m%d" % i)
        _wait(lambda: len(received) + len(limited) >= 12, 10, 0.01)
        # the 1s burst admits ~3 (+ trickle refill); the rest are reported
        # to the scoring callback instead of the reactor
        assert 3 <= len(received) <= 6, received
        assert len(limited) >= 6 and set(limited) == {1}
        # admitted messages kept arrival order
        assert [m for _, m in received] == [b"m%d" % i
                                            for i in range(len(received))]
    finally:
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# Mempool gossip scoring (satellite 2)
# ---------------------------------------------------------------------------


class _FakeSwitchWithBoard:
    def __init__(self, clock=time.monotonic):
        self.scoreboard = peerscore.PeerScoreBoard(clock=clock)


class _FakePeer:
    def __init__(self, pid):
        self.id = pid


def test_full_mempool_scores_peer_and_never_kills_gossip_thread():
    from tendermint_tpu.abci.types import Application
    from tendermint_tpu.mempool.mempool import Mempool
    from tendermint_tpu.mempool.reactor import MempoolReactor, msg_txs

    mp = Mempool(Application(), max_txs=1, max_tx_bytes=64)
    r = MempoolReactor(mp, broadcast=False)
    r.switch = _FakeSwitchWithBoard()
    board = r.switch.scoreboard
    peer = _FakePeer("flooder01")
    r.receive(0x30, peer, msg_txs([b"tx-one"]))  # fills the pool
    assert mp.size() == 1 and board.score("flooder01") == 0.0
    # a flood into the full pool: scored, swallowed, thread alive
    for i in range(30):
        r.receive(0x30, peer, msg_txs([b"tx-flood-%d" % i]))
    assert board.score("flooder01") > 0
    assert board.describe()["offenses"]["flooder01:mempool_full"] == 30
    # oversized tx: its own (heavier) offense
    r.receive(0x30, peer, msg_txs([b"x" * 100]))
    assert board.describe()["offenses"]["flooder01:tx_too_large"] == 1
    # an app blowing up mid-CheckTx must not propagate into the recv
    # thread — and must NOT score the peer (it is OUR failure; scoring it
    # would ban every honest gossiper during an ABCI app outage)
    mp.flush()  # make room so the tx reaches the app at all
    before = board.score("flooder01")

    def boom(req):
        raise RuntimeError("app crashed")
    mp.app.check_tx = boom
    r.receive(0x30, peer, msg_txs([b"tx-late"]))
    assert board.score("flooder01") <= before
    assert "flooder01:checktx_reject" not in board.describe()["offenses"]


# ---------------------------------------------------------------------------
# Ban enforcement seams: dial side, accept side, reconnect loop
# ---------------------------------------------------------------------------


def test_dial_refused_for_banned_peer_without_touching_transport():
    from tendermint_tpu.p2p import switch as sw
    from tendermint_tpu.p2p.node_info import NodeInfo

    nk = NodeKey(ed25519.gen_priv_key(b"\x61" * 32))
    t = sw.Transport(nk, NodeInfo(node_id=nk.id(), network="x", moniker="m"))
    s = sw.Switch(t)
    dialed = []

    def fake_dial(addr):
        dialed.append(addr)
        raise OSError("stub transport")

    t.dial = fake_dial
    s.scoreboard.ban("badpeer")
    assert s.dial_peer("badpeer@127.0.0.1:1") is None
    assert not dialed  # refused BEFORE the transport opened a socket
    s.scoreboard.unban("badpeer")
    assert s.dial_peer("badpeer@127.0.0.1:1") is None  # stub dial fails
    assert dialed  # ...but the transport was consulted once unbanned


def test_reconnect_pass_skips_banned_persistent_peer():
    from tendermint_tpu.p2p import switch as sw

    t = [0.0]
    s = sw.Switch.__new__(sw.Switch)
    s.peers = {}
    s.logger = None
    s.scoreboard = _board(lambda: t[0], ban_duration_s=10.0)
    s._persistent_addrs = ["peerX@127.0.0.1:1"]
    s._reconnect_attempts = {}
    s._reconnect_next_try = {}
    dials = []
    s.dial_peer = lambda addr, persistent=False: dials.append(addr) or None
    s.scoreboard.ban("peerX")
    s._reconnect_pass(s._reconnect_attempts, s._reconnect_next_try)
    assert not dials and not s._reconnect_attempts  # no backoff burned
    t[0] = 10.1  # ban expired: retried immediately on the next pass
    s._reconnect_pass(s._reconnect_attempts, s._reconnect_next_try)
    assert dials == ["peerX@127.0.0.1:1"]


def test_transport_upgrade_seam_checks_bans_and_scores_evil_handshake():
    from tendermint_tpu.p2p import switch as sw
    from tendermint_tpu.p2p.node_info import NodeInfo

    nk = NodeKey(ed25519.gen_priv_key(b"\x62" * 32))
    t = sw.Transport(nk, NodeInfo(node_id=nk.id(), network="x", moniker="m"))
    s = sw.Switch(t)
    # the switch wires both hooks at construction (bound methods compare
    # by ==, not identity)
    assert t.ban_checker == s.scoreboard.is_banned
    s.scoreboard.ban("bannedX")
    assert t.ban_checker("bannedX") and not t.ban_checker("cleanY")
    t.on_evil_handshake("liar-authenticated-id")
    # real-clock board: allow for decay between record and read (a loaded
    # test box can stall seconds between the two)
    pts = peerscore.OFFENSE_POINTS["evil_handshake"]
    assert 0.5 * pts < s.scoreboard.score("liar-authenticated-id") <= pts


# ---------------------------------------------------------------------------
# Consensus drain attribution (the batched bitmap seam)
# ---------------------------------------------------------------------------


def test_vote_drain_bitmap_attributes_invalid_lanes_to_peers():
    from tendermint_tpu.consensus.state_machine import ConsensusState, MsgInfo

    cs = ConsensusState.__new__(ConsensusState)
    cs.logger = None
    cs.scoreboard = peerscore.PeerScoreBoard()
    applied = []
    cs._try_add_vote = lambda vote, peer_id, verified=False: applied.append(
        (peer_id, verified)) or True

    class _VM:
        vote = object()

    msgs = [MsgInfo(_VM(), "honest01"), MsgInfo(_VM(), "forger02"),
            MsgInfo(_VM(), "honest03")]
    cs._apply_vote_results(msgs, {0: True, 1: False, 2: True})
    # the FAILED lane scored its delivering peer; verified lanes did not
    # (real-clock board: allow for decay between record and read)
    pts = peerscore.OFFENSE_POINTS["invalid_signature"]
    assert 0.5 * pts < cs.scoreboard.score("forger02") <= pts
    assert cs.scoreboard.score("honest01") == 0.0
    assert [p for p, _ in applied] == ["honest01", "honest03"]


def test_serial_vote_path_scores_typed_invalid_signature():
    from tendermint_tpu.consensus.state_machine import (
        ConsensusState,
        MsgInfo,
        VoteMessage,
    )
    from tendermint_tpu.types.vote import ErrVoteInvalidSignature

    cs = ConsensusState.__new__(ConsensusState)
    cs.logger = None
    cs.scoreboard = peerscore.PeerScoreBoard()

    def raise_invalid(vote, peer_id, verified=False):
        raise ErrVoteInvalidSignature("invalid signature")

    cs._try_add_vote = raise_invalid
    cs._handle_msg(MsgInfo(VoteMessage(object()), "forger02"))  # must not raise
    assert cs.scoreboard.score("forger02") > 0


# ---------------------------------------------------------------------------
# RPC: admission gate + unsafe_peers route
# ---------------------------------------------------------------------------


class _RpcCfg:
    class rpc:
        unsafe = True
        max_broadcast_tx_inflight = 1


class _RpcEnv:
    def __init__(self, node):
        self.node = node


def test_broadcast_tx_admission_gate_typed_overload():
    from tendermint_tpu.rpc import core as rpc_core

    gate_open = threading.Event()
    entered = threading.Event()

    class _MP:
        def check_tx(self, raw):
            entered.set()
            gate_open.wait(5)

            class _Res:
                code, data, log, codespace = 0, b"", "", ""
            return _Res()

    class _Node:
        config = _RpcCfg()
        mempool = _MP()
        switch = None

    import base64 as _b64mod

    def tx(s):
        return _b64mod.b64encode(s).decode()

    env = _RpcEnv(_Node())
    results = []
    th = threading.Thread(
        target=lambda: results.append(
            rpc_core.broadcast_tx_sync(env, tx(b"a"))),
        daemon=True)
    th.start()
    assert entered.wait(5)
    # slot 1 is held inside CheckTx: the second request is refused with the
    # TYPED overload error, not queued
    with pytest.raises(rpc_core.ErrOverloaded, match="overloaded"):
        rpc_core.broadcast_tx_sync(env, tx(b"b"))
    gate_open.set()
    th.join(5)
    assert results and results[0]["code"] == 0
    # the slot was released: the next call passes
    gate_open.set()
    assert rpc_core.broadcast_tx_sync(env, tx(b"c"))["code"] == 0
    # limit 0 disables the gate entirely
    env.node.config.rpc.max_broadcast_tx_inflight = 0
    env.node._rpc_tx_gate = None
    assert rpc_core.broadcast_tx_sync(env, tx(b"d"))["code"] == 0


def test_unsafe_peers_route_view_and_manual_ban():
    from tendermint_tpu.rpc import core as rpc_core

    class _Switch:
        scoreboard = peerscore.PeerScoreBoard()

    class _Node:
        config = _RpcCfg()
        switch = _Switch()

    env = _RpcEnv(_Node())
    env.node.switch.scoreboard.record("p1", "invalid_signature")
    out = rpc_core.unsafe_peers(env)
    assert 4.0 < out["scores"]["p1"] <= 8.0  # real clock: decay tolerated
    out = rpc_core.unsafe_peers(env, ban="p9", duration=60)
    assert "p9" in out["banned"] and out["bans_total"] == 1
    out = rpc_core.unsafe_peers(env, unban="p9")
    assert "p9" not in out["banned"]
    with pytest.raises(ValueError):
        rpc_core.unsafe_peers(env, ban="")
    env.node.config.rpc.unsafe = False
    try:
        with pytest.raises(ValueError, match="unsafe"):
            rpc_core.unsafe_peers(env)
    finally:
        env.node.config.rpc.unsafe = True


# ---------------------------------------------------------------------------
# Nemesis flood action units
# ---------------------------------------------------------------------------


def test_flood_grammar_and_site_scoping():
    r = nemesis.LinkRule.parse("aa>*:flood~4")
    assert r.action == "flood" and r.param == 4.0
    nemesis.add_link(r)
    assert nemesis.outcome("p2p.send", "aa1", "zz1") == "flood"
    # send-side only: the receiving end of the same plane must not
    # re-amplify the corrupted copies
    assert nemesis.outcome("p2p.recv", "zz1", "aa1") == "pass"
    with pytest.raises(faults.FaultError):
        nemesis.outcome("p2p.dial", "aa1", "zz1")
    assert any(l.startswith("aa>*:flood") for l in
               nemesis.PLANE.describe()["links"])


def test_flood_payloads_seeded_and_corrupting():
    faults.configure([], seed=123)
    nemesis.add_link("aa>bb:flood~6")
    msg = bytes(range(200))
    p1 = nemesis.PLANE.flood_payloads("aa1", "bb1", VOTE_CH, msg)
    assert len(p1) == 6
    # even copies: same length, one byte flipped near the tail; odd
    # copies: padded (the unparseable/oversized class)
    for i, c in enumerate(p1):
        assert c != msg
        if i % 2 == 0:
            assert len(c) == len(msg)
            diff = [j for j in range(len(msg)) if c[j] != msg[j]]
            assert len(diff) == 1 and diff[0] >= len(msg) - 24
        else:
            assert len(c) == len(msg) + nemesis.FLOOD_PAD_BYTES
            assert c[:len(msg)] == msg
    # deterministic replay from the seed
    nemesis.PLANE.reset_counters()
    assert nemesis.PLANE.flood_payloads("aa1", "bb1", VOTE_CH, msg) == p1
    # a different seed produces a different schedule
    faults.configure([], seed=124)
    nemesis.PLANE.reset_counters()
    assert nemesis.PLANE.flood_payloads("aa1", "bb1", VOTE_CH, msg) != p1


# ---------------------------------------------------------------------------
# In-process flood scenarios
# ---------------------------------------------------------------------------


def _mk_weighted_genesis(powers):
    privs = [ed25519.gen_priv_key(bytes([80 + i]) * 32)
             for i in range(len(powers))]
    genesis = GenesisDoc(
        chain_id="overload-chain",
        genesis_time=Time(1700004000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), w)
                    for p, w in zip(privs, powers)],
    )
    return genesis, privs


def _mk_node(tmp_path, i, genesis, priv, metrics=False, tweak=None):
    from tendermint_tpu.node.node import Node

    cfg = make_test_config()
    cfg.set_root(str(tmp_path / f"node{i}"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = ""  # peered via socketpairs (no `cryptography` dep)
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = os.path.join(cfg.base.root_dir, "cs.wal")
    if metrics:
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
    if tweak is not None:
        tweak(cfg, i)
    node_key = NodeKey(ed25519.gen_priv_key(bytes([140 + i]) * 32))
    return Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=node_key)


def _relink_until(a, b, stop, timeout=60):
    """Keep relinking a<->b (the redial-and-repeat loop a real flooder
    runs) until ``stop()`` or the link is REFUSED (ban). Returns True if
    a refusal was observed."""
    from tendermint_tpu.p2p.switch import P2PError

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if stop():
            return True
        bid = b.node_key.id()
        if bid not in a.switch.peers:
            a.switch.stop_peer_by_id(bid, "relink")
            b.switch.stop_peer_by_id(a.node_key.id(), "relink")
            try:
                _link(a, b)
            except P2PError:
                return True  # refused: the ban seam closed the loop
            except Exception:  # noqa: BLE001 - teardown still in flight
                pass
        time.sleep(0.05)
    return stop()


def test_flood_smoke_single_node_flooding_peer_banned_no_stall(tmp_path):
    """ISSUE 5 satellite 5, the quick-tier flood smoke: a 1-power
    validator floods its 10-power peer through the nemesis flood action
    (every outbound message amplified with seeded corrupted copies —
    invalid-signature votes and unparseable junk). The victim must score
    the flooder to a ban, refuse its redials, and keep committing.

    Runs under the lock-order witness (utils/lockwitness.py): the flood
    drives the scoreboard/shed/rate-limit locks hard against the p2p and
    consensus locks, and the exit assert proves the acquisition order
    stays acyclic even on the overload paths."""
    genesis, privs = _mk_weighted_genesis([10, 1])
    with lockwitness.witness() as w:
        nodes = [_mk_node(tmp_path, i, genesis, privs[i]) for i in range(2)]
        ids = [n.node_key.id() for n in nodes]
        desc = f"link={ids[1]}>*:flood~8"
        _run_flood_smoke(nodes, ids, desc)
    assert w.acquires > 0 and len(w.edges) > 0


def _run_flood_smoke(nodes, ids, desc):
    try:
        with repro("flood smoke", desc):
            for n in nodes:
                n.start()
            _link(nodes[0], nodes[1])
            assert _wait(lambda: nodes[0].block_store.height >= 2, 30, 0.1), \
                "no initial progress"

            nemesis.add_link(f"{ids[1]}>*:flood~8")
            board = nodes[0].switch.scoreboard
            assert _relink_until(nodes[0], nodes[1],
                                 lambda: board.is_banned(ids[1]), 60), \
                f"flooder never banned; board={board.describe()}"
            assert board.is_banned(ids[1])
            # the drain attributed at least part of the flood to invalid
            # signatures out of the batched bitmap
            offenses = board.describe()["offenses"]
            assert any(k.startswith(f"{ids[1]}:") for k in offenses), offenses

            # redial refused at the switch seam without touching a socket
            assert nodes[0].switch.dial_peer(f"{ids[1]}@127.0.0.1:1") is None
            # ...and the in-process accept seam refuses a fresh link
            from tendermint_tpu.p2p.switch import P2PError

            with pytest.raises(P2PError, match="banned"):
                sa, sb = _socket.socketpair()
                try:
                    nodes[0].switch._add_peer(
                        _PlainConn(sa), nodes[1].transport.node_info,
                        outbound=False)
                finally:
                    sb.close()

            # no commit stall: the 10/11-power node keeps deciding alone
            h = nodes[0].block_store.height
            assert _wait(lambda: nodes[0].block_store.height >= h + 2,
                         30, 0.1), "victim stalled after banning the flooder"
    finally:
        _stop_all(nodes)


@pytest.mark.slow
def test_four_node_mesh_flooder_banned_majority_live(tmp_path):
    """Acceptance scenario: 4-node mesh, node3 floods invalid-signature
    votes (nemesis flood action) and oversized txs (its max_tx_bytes
    exceeds the honest nodes'); the flooder is banned on the honest nodes
    (ban metric increments, redial refused, post-ban traffic never
    reaches the drain) while the honest 3/4 majority keeps committing
    within the liveness bound. Deterministic under TMTPU_FAULT_SEED."""
    def tweak(cfg, i):
        # honest nodes reject txs over 256B; the flooder accepts (and
        # gossips) bigger ones — its tx gossip is oversized BY CONFIG at
        # every honest receiver, the second scoring feed of the scenario
        cfg.mempool.max_tx_bytes = 4096 if i == 3 else 256

    genesis, privs = _mk_weighted_genesis([10, 10, 10, 10])
    nodes = [_mk_node(tmp_path, i, genesis, privs[i], metrics=(i == 0),
                      tweak=tweak) for i in range(4)]
    ids = [n.node_key.id() for n in nodes]
    desc = f"link={ids[3]}>*:flood~8#{VOTE_CH:#x}"
    try:
        with repro("4-node flood ban", desc):
            for n in nodes:
                n.start()
            for i in range(4):
                for j in range(i):
                    _link(nodes[i], nodes[j])
            assert _wait(lambda: min(n.block_store.height
                                     for n in nodes) >= 2, 60, 0.1), \
                "no initial progress"

            # the flood: node3's VOTE-channel traffic is amplified with
            # corrupted copies (scoped with #0x22 so the scenario pins the
            # drain-bitmap attribution path, not the easier unparseable-
            # junk teardowns); plus a legitimately-submitted oversized tx
            # that every honest mempool rejects as too large
            nemesis.add_link(f"{ids[3]}>*:flood~8#{VOTE_CH:#x}")
            nodes[3].mempool.check_tx(b"oversized=" + b"x" * 1000)

            boards = [nodes[i].switch.scoreboard for i in range(3)]
            for i in range(3):
                assert _relink_until(nodes[i], nodes[3],
                                     lambda i=i: boards[i].is_banned(ids[3]),
                                     90), \
                    f"node{i} never banned the flooder: {boards[i].describe()}"
            # invalid-signature lanes out of the batched drain bitmap were
            # attributed to the flooder on at least one honest node
            assert any(
                b.describe()["offenses"].get(f"{ids[3]}:invalid_signature", 0)
                > 0 for b in boards), [b.describe()["offenses"]
                                       for b in boards]

            # post-ban: the flooder is torn down everywhere and its redial
            # is refused — its traffic can never reach the drain again
            from tendermint_tpu.p2p.switch import P2PError

            for i in range(3):
                assert ids[3] not in nodes[i].switch.peers
                assert nodes[i].switch.dial_peer(
                    f"{ids[3]}@127.0.0.1:1") is None
            with pytest.raises(P2PError, match="banned"):
                sa, sb = _socket.socketpair()
                try:
                    nodes[0].switch._add_peer(
                        _PlainConn(sa), nodes[3].transport.node_info,
                        outbound=False)
                finally:
                    sb.close()

            # the honest 3/4 keep committing within the liveness bound
            h = max(n.block_store.height for n in nodes[:3])
            assert _wait(lambda: min(n.block_store.height
                                     for n in nodes[:3]) >= h + 2, 60, 0.1), \
                ("honest majority stalled after banning the flooder: "
                 f"{[n.block_store.height for n in nodes]}")

            # ban metric incremented on node0's /metrics (sampler tick)
            def banned_metric():
                url = f"http://{nodes[0].metrics_server.addr}/metrics"
                body = urllib.request.urlopen(url, timeout=5).read().decode()
                line = next(l for l in body.splitlines()
                            if l.startswith("tendermint_p2p_peers_banned_total"))
                return float(line.rsplit(" ", 1)[1])
            assert _wait(lambda: banned_metric() >= 1.0, 15, 0.3), \
                "peers_banned_total never incremented on /metrics"
    finally:
        _stop_all(nodes)
