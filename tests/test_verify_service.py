"""ISSUE 11: the continuous-batching verify service (crypto/verify_service).

Coalescing CORRECTNESS is the whole game: N threads dispatching
overlapping ed25519/sr25519/mixed batches concurrently must get bitmaps
bit-identical to serial dispatch, with tampered lanes attributed to the
right caller; a breaker trip mid-coalesce must fall back to host without
losing or double-resolving a single waiter; and the PendingVerify
semantics (has_device_output / resolve idempotence / prefetch) must be
unchanged so every existing caller rides the service transparently.

A generous TMTPU_VERIFY_WINDOW_US makes the concurrent tests'
coalescing deterministic: all threads submit well inside one window, so
the executor provably shares one launch (asserted via service stats)."""

import threading

import pytest

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.crypto import ed25519, sr25519, verify_service

CHAIN = b"svc-test"


@pytest.fixture(autouse=True)
def _fresh_service(monkeypatch):
    # force-all mode: on this host the C verifier absorbs sub-crossover
    # batches with no floor, so adaptive routing would keep these small
    # test batches off the service; =1 pins them on (exactly what the
    # concurrent bench and graft stage do)
    monkeypatch.setenv("TMTPU_VERIFY_SERVICE", "1")
    monkeypatch.setenv("TMTPU_VERIFY_WINDOW_US", "50000")
    verify_service.reset()
    yield
    verify_service.reset()


def _tamper(sig: bytes) -> bytes:
    return sig[:-1] + bytes([sig[-1] ^ 1])


def _ed_items(n, seed, tampered=()):
    out = []
    for i in range(n):
        priv = ed25519.gen_priv_key(bytes([seed]) * 16 + i.to_bytes(16, "big"))
        msg = CHAIN + b"-ed-%d-%d" % (seed, i)
        sig = ed25519.sign(priv.data, msg)
        out.append((priv.pub_key(), msg, _tamper(sig) if i in tampered else sig))
    return out


def _sr_items(n, seed, tampered=()):
    out = []
    for i in range(n):
        priv = sr25519.gen_priv_key(bytes([seed]) * 16 + i.to_bytes(16, "big"))
        msg = CHAIN + b"-sr-%d-%d" % (seed, i)
        sig = priv.sign(msg)
        out.append((priv.pub_key(), msg, _tamper(sig) if i in tampered else sig))
    return out


def _dispatch(key_type, items):
    v = cbatch.create_batch_verifier(key_type)
    for pk, m, s in items:
        v.add(pk, m, s)
    return v.dispatch()


def _run(key_type, items):
    return _dispatch(key_type, items).resolve()


def _serial_truth(items):
    return [pk.verify_signature(m, s) for (pk, m, s) in items]


def _concurrent(workloads):
    """Run each (key_type, items) on its own thread; all submissions land
    inside one coalescing window. Returns results parallel to workloads."""
    results = [None] * len(workloads)
    errors = []

    def worker(k, key_type, items):
        try:
            results[k] = _run(key_type, items)
        except Exception as e:  # noqa: BLE001 - surfaced in the test body
            errors.append((k, e))

    threads = [threading.Thread(target=worker, args=(k, kt, its))
               for k, (kt, its) in enumerate(workloads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    return results


def test_concurrent_overlapping_batches_bit_identical_to_serial():
    """N threads, overlapping ed/sr/mixed batches (shared keys between the
    two ed callers), one coalescing window: every caller's (all_ok, bitmap)
    equals both serial dispatch (service off) and the scalar ground truth,
    and tampered lanes land on the right caller at the right index."""
    ed_a = _ed_items(40, seed=1, tampered={5})
    # overlaps ed_a's keys: same seed, shifted tamper — exercises the
    # unique-key-set reuse inside one coalesced generation
    ed_b = _ed_items(40, seed=1, tampered={17})
    sr_a = _sr_items(9, seed=2, tampered={2})
    mixed = ed_a[:6] + sr_a[:3] + ed_a[6:12]
    workloads = [("ed25519", ed_a), ("ed25519", ed_b),
                 ("sr25519", sr_a), (None, mixed)]

    got = _concurrent(workloads)
    svc = verify_service.get()
    assert svc.requests >= 4
    assert svc.max_coalesced >= 2, (
        "concurrent dispatches inside one window did not coalesce: "
        f"launches={svc.launches} requests={svc.requests}")

    import os
    os.environ["TMTPU_VERIFY_SERVICE"] = "0"
    try:
        serial = [_run(kt, its) for (kt, its) in workloads]
    finally:
        del os.environ["TMTPU_VERIFY_SERVICE"]

    for k, (kt, its) in enumerate(workloads):
        truth = _serial_truth(its)
        assert got[k] == serial[k], f"caller {k} ({kt}): service != serial"
        assert got[k] == (all(truth), truth), f"caller {k}: != ground truth"
    # attribution spot checks: each tampered lane fails for ITS caller only
    assert got[0][1][5] is False and got[1][1][5] is True
    assert got[1][1][17] is False and got[0][1][17] is True
    assert got[2][1][2] is False


def test_breaker_trip_mid_coalesce_resolves_every_waiter_once(monkeypatch):
    """TMTPU_FAULTS device failure while several callers share one
    generation: the injected raise at the coalesced ops dispatch opens the
    circuit, the generation degrades to the host fallback, and EVERY
    waiter resolves exactly once with the correct bitmap."""
    import os

    from tendermint_tpu.ops import ed25519_batch
    from tendermint_tpu.utils import faults

    monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "0")  # pin the device route
    monkeypatch.setenv("TM_TPU_BREAKER_COOLDOWN_S", "3600")  # no re-probe
    monkeypatch.setenv("TMTPU_FAULTS", "ops.ed25519.device:raise")
    faults.install_from_env()
    workloads = [("ed25519", _ed_items(34, seed=k, tampered={k}))
                 for k in range(3)]
    try:
        got = _concurrent(workloads)
    finally:
        monkeypatch.setenv("TMTPU_FAULTS", "")
        faults.install_from_env()
        ed25519_batch.BREAKER.reset()
    assert ed25519_batch.BREAKER.failures >= 1, "the fault never fired"
    for k, (kt, its) in enumerate(workloads):
        truth = _serial_truth(its)
        assert got[k] == (all(truth), truth), f"caller {k} wrong after trip"
        assert got[k][1][k] is False
    assert os.environ.get("TMTPU_FAULTS") == ""


def test_executor_dispatch_crash_falls_back_without_losing_waiters(monkeypatch):
    """A failure that escapes even the breaker (ops.dispatch_batch itself
    raising, e.g. a prep bug) resolves every waiter through the scalar
    floor — the service must never deadlock a caller."""
    from tendermint_tpu.ops import ed25519_batch

    def boom(items, force_device=False):
        raise RuntimeError("injected dispatch crash")

    monkeypatch.setattr(ed25519_batch, "dispatch_batch", boom)
    workloads = [("ed25519", _ed_items(33, seed=7, tampered={1})),
                 ("ed25519", _ed_items(33, seed=8))]
    got = _concurrent(workloads)
    svc = verify_service.get()
    assert svc.fallbacks >= 1
    for k, (_, its) in enumerate(workloads):
        truth = _serial_truth(its)
        assert got[k] == (all(truth), truth)


def test_service_pending_semantics_and_prefetch():
    """ServicePending honors the PendingVerify contract: in-flight handles
    report has_device_output() (async callers stash them), resolve() is
    idempotent, and prefetch/resolve_all over service-backed handles just
    works."""
    pendings = [_dispatch("ed25519", _ed_items(33, seed=11)),
                _dispatch("ed25519", _ed_items(33, seed=12, tampered={3}))]
    assert all(isinstance(p, cbatch.ServicePending) for p in pendings)
    results = cbatch.resolve_all(pendings)
    assert results[0][0] is True
    assert results[1][0] is False and results[1][1][3] is False
    for p in pendings:
        assert not p.has_device_output()
        assert p.resolve() is p.resolve()  # cached, idempotent


def test_vote_drain_stash_engages_through_mixed_router(monkeypatch):
    """The consensus drain's overlap test-point: a mixed-registry dispatch
    whose sub-batches ride the service must report has_device_output()
    while the shared launch is in flight (the drain stashes and keeps
    draining), and resolve to the exact serial decision afterwards."""
    # a 2 s window (vs the fixture's 50 ms) makes the in-flight assertion
    # robust to CI scheduler stalls between dispatch and the check
    monkeypatch.setenv("TMTPU_VERIFY_WINDOW_US", "2000000")
    verify_service.reset()
    items = _ed_items(36, seed=21, tampered={9})
    p = _dispatch(None, items)
    # the coalescing window is still open: the launch cannot have completed
    assert p.has_device_output(), (
        "mixed handle hides the in-flight service launch — the vote drain "
        "would lose its dispatch/drain overlap")
    ok, bitmap = p.resolve()
    truth = _serial_truth(items)
    assert (ok, bitmap) == (all(truth), truth)


def test_service_off_restores_direct_dispatch(monkeypatch):
    monkeypatch.setenv("TMTPU_VERIFY_SERVICE", "0")
    p = _dispatch("ed25519", _ed_items(33, seed=31))
    assert not isinstance(p, cbatch.ServicePending)
    ok, bitmap = p.resolve()
    assert ok and all(bitmap)


def test_keyset_unique_set_lru_survives_interleaving(monkeypatch):
    """The device-resident comb-table LRU keyed by key-set content: a novel
    interleaving of already-known keys (the normal shape of a coalesced
    generation) must reuse the cached KeySet, not rebuild tables."""
    from tendermint_tpu.ops import ed25519_batch as edb

    builds = {"n": 0}
    orig = edb._build_comb_tables_tiled

    def counting(a_neg):
        builds["n"] += 1
        return orig(a_neg)

    monkeypatch.setattr(edb, "_build_comb_tables_tiled", counting)
    pubs = [it[0].bytes() for it in _ed_items(6, seed=41)]
    seq_a = [pubs[0], pubs[1], pubs[2], pubs[0]]
    seq_b = [pubs[2], pubs[0], pubs[1], pubs[2], pubs[1]]  # same SET, new order
    ks_a, idx_a, ok_a = edb.get_keyset(seq_a)
    ks_b, idx_b, ok_b = edb.get_keyset(seq_b)
    assert builds["n"] == 1, "novel interleaving rebuilt the comb tables"
    assert ks_a is ks_b
    assert ok_a.all() and ok_b.all()
    # the remap must still point every item at its own key's row
    row = {p: idx_a[i] for i, p in enumerate(seq_a)}
    for i, p in enumerate(seq_b):
        assert idx_b[i] == row[p], "interleaved key_idx maps to wrong row"
    # exact-sequence (level 1) hit returns the same mapping
    ks_a2, idx_a2, _ = edb.get_keyset(seq_a)
    assert ks_a2 is ks_a and (idx_a2 == idx_a).all()
    assert builds["n"] == 1
