"""WAL corruption tolerance — the analogue of the reference's
consensus/wal_fuzz.go + wal corrupt-tail handling (consensus/wal.go:231).

The recovery property: whatever bytes end up on disk after a crash or
corruption, replay (a) never raises and (b) yields a PREFIX of the
messages that were written, in order."""

import os
import random
import struct

from tendermint_tpu.consensus.wal import (
    WAL,
    EndHeightMessage,
    WALMessageBlob,
)


def _write_wal(path, n=20):
    wal = WAL(path)
    msgs = []
    for i in range(n):
        if i % 5 == 4:
            m = EndHeightMessage(height=i // 5 + 1)
        else:
            m = WALMessageBlob(kind="vote", payload=b"payload-%d" % i * 3,
                               peer_id="peer%d" % (i % 3))
        wal.write_sync(m, time_ns=1_700_000_000_000_000_000 + i)
        msgs.append(m)
    wal.close()
    return msgs


def _head_file(path):
    names = [n for n in os.listdir(path)]
    assert names
    return os.path.join(path, sorted(names)[-1])


def _replayed(path):
    return [tm.msg for tm, _ in WAL(path).iter_messages()]


def _is_prefix(got, wrote):
    return len(got) <= len(wrote) and got == wrote[: len(got)]


def test_truncation_at_every_byte_is_a_prefix(tmp_path):
    """Crash mid-write: cut the head file at every possible byte offset;
    replay must never raise and always yield a prefix."""
    base = _write_wal(str(tmp_path / "wal"), n=8)
    head = _head_file(str(tmp_path / "wal"))
    full = open(head, "rb").read()
    for cut in range(len(full) + 1):
        d = str(tmp_path / ("cut%d" % cut))
        os.makedirs(d)
        with open(os.path.join(d, os.path.basename(head)), "wb") as f:
            f.write(full[:cut])
        got = _replayed(d)
        assert _is_prefix(got, base), cut
    # the untouched file replays everything
    assert _replayed(str(tmp_path / "wal")) == base


def test_random_bit_flips_yield_prefix(tmp_path):
    """Flip random bytes anywhere in the log; replay stops at (or before)
    the first damaged frame, never raises, never yields altered/reordered
    messages for frames whose CRC still matches."""
    rng = random.Random(0xDEAD)
    base = _write_wal(str(tmp_path / "wal"), n=20)
    head = _head_file(str(tmp_path / "wal"))
    full = bytearray(open(head, "rb").read())
    for trial in range(60):
        data = bytearray(full)
        for _ in range(rng.randint(1, 4)):
            i = rng.randrange(len(data))
            data[i] ^= 1 << rng.randrange(8)
        d = str(tmp_path / ("flip%d" % trial))
        os.makedirs(d)
        with open(os.path.join(d, os.path.basename(head)), "wb") as f:
            f.write(bytes(data))
        got = _replayed(d)
        assert _is_prefix(got, base), trial


def test_giant_length_field_stops_replay(tmp_path):
    """A corrupted length field larger than MAX_MSG_SIZE must terminate
    replay instead of attempting a giant allocation."""
    base = _write_wal(str(tmp_path / "wal"), n=6)
    head = _head_file(str(tmp_path / "wal"))
    data = bytearray(open(head, "rb").read())
    # frame 0 is intact; overwrite frame 1's length with 512 MiB
    _, l0 = struct.unpack_from(">II", data, 0)
    struct.pack_into(">I", data, 8 + l0 + 4, 512 * 1024 * 1024)
    with open(head, "wb") as f:
        f.write(bytes(data))
    got = _replayed(str(tmp_path / "wal"))
    assert got == base[:1]


def test_search_for_end_height_on_corrupt_tail(tmp_path):
    """EndHeight found before the damage still anchors recovery; an
    EndHeight after the damage is unreachable and reports not-found."""
    _write_wal(str(tmp_path / "wal"), n=20)  # EndHeights 1..4
    head = _head_file(str(tmp_path / "wal"))
    data = bytearray(open(head, "rb").read())
    frames = []
    pos = 0
    while pos + 8 <= len(data):
        _, ln = struct.unpack_from(">II", data, pos)
        frames.append(pos)
        pos += 8 + ln
    # damage the 13th frame: EndHeight(2) at frame index 9 stays readable,
    # EndHeight(3) at frame 14 becomes unreachable
    data[frames[12] + 8] ^= 0xFF
    with open(head, "wb") as f:
        f.write(bytes(data))
    wal = WAL(str(tmp_path / "wal"))
    after = wal.search_for_end_height(2)
    assert after is not None and len(after) == 2  # frames 10,11 survive
    assert wal.search_for_end_height(3) is None


def test_append_after_corrupt_tail_recovers_new_writes(tmp_path):
    """Reopening a WAL with a torn tail must truncate the garbage before
    appending (consensus/wal.py _repair; reference:
    consensus/replay.go:73 repairWalFile) — otherwise the new frames land
    after the tear and replay never reaches them."""
    base = _write_wal(str(tmp_path / "wal"), n=5)
    head = _head_file(str(tmp_path / "wal"))
    with open(head, "ab") as f:
        f.write(b"\x00\x01\x02")  # torn partial frame
    wal = WAL(str(tmp_path / "wal"))  # repair on open
    extra = WALMessageBlob(kind="vote", payload=b"post-crash", peer_id="p")
    wal.write_sync(extra, time_ns=1)
    wal.close()
    # old prefix AND the post-crash write both replay
    assert _replayed(str(tmp_path / "wal")) == base + [extra]
    # the damaged original is kept aside for forensics
    assert any(".corrupted." in n for n in os.listdir(str(tmp_path / "wal")))


def test_repair_mid_file_corruption_truncates_to_valid_prefix(tmp_path):
    """Damage in the middle: repair keeps the valid prefix, drops the
    damaged frame AND everything after it (those frames were unreachable
    by replay anyway), and subsequent writes append cleanly."""
    base = _write_wal(str(tmp_path / "wal"), n=8)
    head = _head_file(str(tmp_path / "wal"))
    data = bytearray(open(head, "rb").read())
    data[8] ^= 0xFF  # corrupt frame 0's body -> whole file unreachable
    with open(head, "wb") as f:
        f.write(bytes(data))
    wal = WAL(str(tmp_path / "wal"))
    extra = WALMessageBlob(kind="vote", payload=b"fresh", peer_id="q")
    wal.write_sync(extra, time_ns=2)
    wal.close()
    assert _replayed(str(tmp_path / "wal")) == [extra]
    assert base  # (original messages preserved only in the .corrupted copy)


def test_clean_wal_reopen_does_not_rewrite(tmp_path):
    """Repair must be a no-op on a clean log: no .corrupted files, all
    messages intact after reopen + append."""
    base = _write_wal(str(tmp_path / "wal"), n=5)
    wal = WAL(str(tmp_path / "wal"))
    extra = WALMessageBlob(kind="vote", payload=b"more", peer_id="r")
    wal.write_sync(extra, time_ns=3)
    wal.close()
    assert _replayed(str(tmp_path / "wal")) == base + [extra]
    assert not any(".corrupted." in n
                   for n in os.listdir(str(tmp_path / "wal")))


def test_tear_in_rotated_chunk_repairs_and_retires_later_chunks(tmp_path):
    """Rotation: a tear in an EARLIER (non-head) chunk used to orphan every
    later chunk and all post-crash writes (repair only looked at the head).
    Repair must truncate the torn chunk, retire later chunks (ordering
    across the gap is broken), and make new writes reachable."""
    d = str(tmp_path / "wal")
    wal = WAL(d, head_size_limit=64)  # force rotation every frame or two
    msgs = []
    for i in range(10):
        m = WALMessageBlob(kind="vote", payload=b"chunked-%d" % i * 4,
                           peer_id="p")
        wal.write_sync(m, time_ns=i)
        msgs.append(m)
    wal.close()
    chunks = sorted(n for n in os.listdir(d) if ".corrupted." not in n)
    assert len(chunks) >= 3, chunks  # rotation actually happened
    # tear the tail of the FIRST chunk
    first = os.path.join(d, chunks[0])
    with open(first, "ab") as f:
        f.write(b"\x00\x01")
    wal2 = WAL(d, head_size_limit=64)
    extra = WALMessageBlob(kind="vote", payload=b"post-tear", peer_id="q")
    wal2.write_sync(extra, time_ns=99)
    wal2.close()
    got = [tm.msg for tm, _ in WAL(d, head_size_limit=64).iter_messages()]
    # the first chunk's valid frames survive, later chunks are retired,
    # and the post-tear write is REACHABLE
    assert got and got[-1] == extra
    assert _is_prefix(got[:-1], msgs)
    assert any(".corrupted." in n for n in os.listdir(d))
