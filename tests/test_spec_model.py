"""Exhaustive small-scope safety checking of the consensus voting rules
(spec/model.py) — the executable analogue of the reference's Ivy proofs
(spec/ivy-proofs/accountable_safety_1.ivy)."""

import importlib.util
import os
import sys

import pytest

_path = os.path.join(os.path.dirname(__file__), "..", "spec", "model.py")
_spec = importlib.util.spec_from_file_location("specmodel", _path)
model = importlib.util.module_from_spec(_spec)
sys.modules["specmodel"] = model
_spec.loader.exec_module(model)


def test_agreement_exhaustive_f_lt_third():
    """Over EVERY reachable interleaving at 3 honest + 1 byzantine-flooding
    validator (rounds 0..1, two values): no two honest validators decide
    differently, and no round ever carries two conflicting polkas
    (spec/consensus.md Theorem + Lemma 1)."""
    res = model.explore(model.Config())
    assert res.violation is None, res.violation
    assert res.lemma1_violation is None, res.lemma1_violation
    # the scope is not vacuous: both values are decidable, and the space
    # is the full product, not a truncated walk
    assert res.decisions_seen == {"A", "B"}
    assert res.states > 100_000


def test_teeth_removing_lock_rule_forks():
    """The invariant is not vacuous: with the lock/POL rules disabled
    (R4/R5 gone — validators prevote any proposal), the same explorer
    FINDS a disagreement trace with only f < N/3 byzantine power. The
    fork is NOT accountable: fewer than f+1 validators hold contradictory
    signatures, which is exactly why the lock rule (and not just vote
    dedup) is what buys accountable safety."""
    cfg = model.Config(lock_rule=False)
    res = model.explore(cfg, stop_at_violation=True)
    assert res.violation is not None
    trace, honest = res.violation
    decided = {s.decided for s in honest if s.decided != model.NIL}
    assert decided == {"A", "B"}
    blamed = model.fork_blame(cfg, trace, honest)
    f = cfg.n // 3
    assert blamed <= set(range(cfg.n_honest, cfg.n))  # honest never blamed
    assert len(blamed) < f + 1  # ...and blame does NOT reach f+1


def test_fork_at_f_geq_third_is_accountable():
    """With f >= N/3 (2 of 4 byzantine) forks exist — and in EVERY
    violating reachable state at this scope, blame localizes to >= f+1
    validators, none of them honest (the accountable-safety claim of
    accountable_safety_1.ivy, checked over the full enumeration rather
    than one witness)."""
    cfg = model.Config(n_honest=2, n_byz=2)
    res = model.explore(cfg)
    assert res.violations
    f = cfg.n // 3
    for trace, honest in res.violations:
        blamed = model.fork_blame(cfg, trace, honest)
        assert len(blamed) >= f + 1, (blamed, trace)
        assert blamed & set(range(cfg.n_honest)) == set(), (blamed, trace)


def test_quorum_below_two_thirds_breaks_agreement():
    """A 1/2 quorum (instead of >2/3) is unsafe even against a single
    byzantine validator — two quorums can intersect in the byzantine
    validator alone, and the explorer finds that fork. Pins the constant
    itself, not just the rules."""
    assert model.Config().quorum == 3  # >2/3 of 4
    res = model.explore(model.Config(quorum=2), stop_at_violation=True)
    assert res.violation is not None


def test_honest_only_scope_decides_and_agrees():
    """Degenerate scope sanity: with zero byzantine validators the model
    still reaches decisions and never forks."""
    res = model.explore(model.Config(n_honest=3, n_byz=0, max_round=1))
    assert res.violation is None
    assert res.decisions_seen  # proposals for both values exist; some decide


def test_agreement_exhaustive_three_rounds():
    """r5 scope increase (VERDICT r4 item 5): rounds 0..2 — deep enough for
    the lock/unlock interactions that only materialize across three rounds
    (see the amnesia test) — explored EXHAUSTIVELY with honest-permutation
    symmetry reduction and the decide-free fork predicate (both reductions
    proven sound: _canon merges true automorphism orbits only; conflicting
    precommit quorums are equivalent to divergent decisions because the
    soup is monotone and DECIDE sends nothing). No fork, no double polka."""
    cfg = model.Config(max_round=2, decide_actions=False)
    res = model.explore(cfg, max_states=4_000_000, symmetry_reduce=True)
    assert res.violation is None, res.violation
    assert res.lemma1_violation is None
    assert res.decisions_seen == {"A", "B"}
    assert res.states > 400_000


def test_amnesia_prevote_weakening_forks_only_at_three_rounds():
    """The amnesia regression: v0.34 UNLOCKS on a nil polka
    (reference consensus/state.go:1367-1383), and that is safe ONLY
    because a locked validator always prevotes its locked block
    (defaultDoPrevote, state.go:1256). Weaken that one guard — a locked
    validator may time out and prevote nil — and the explorer finds a
    fork: lock on v at round 0, amnesiac nil polka at round 1 releases
    the locks, a conflicting polka commits the other value at round 2.
    The fork NEEDS three rounds; at max_round=1 the weakened rule is
    still safe, which is exactly what the r5 scope increase buys."""
    forked = model.explore(
        model.Config(lock_rule="amnesia", max_round=2, decide_actions=False),
        stop_at_violation=True, max_states=4_000_000, symmetry_reduce=True)
    assert forked.violation is not None
    safe = model.explore(
        model.Config(lock_rule="amnesia", max_round=1, decide_actions=False))
    assert safe.violation is None


def test_weighted_voting_power():
    """Weighted powers: agreement holds while byzantine power < 1/3 of
    total even with unequal honest weights, and flips to accountable forks
    the moment one byzantine validator alone carries >= 1/3."""
    safe = model.Config(n_honest=3, n_byz=1, powers=(3, 2, 1, 2))
    assert safe.quorum == (2 * 8) // 3 + 1
    res = model.explore(safe)
    assert res.violation is None and res.lemma1_violation is None
    # one byzantine validator carrying half the power: its equivocation
    # alone splits a round into two quorums (byz+h0 for A, byz+h1 for B)
    unsafe = model.Config(n_honest=2, n_byz=1, powers=(1, 1, 2))
    res = model.explore(unsafe)
    assert res.violations
    for trace, honest in res.violations:
        blamed = model.fork_blame(unsafe, trace, honest)
        assert blamed == {2}, (blamed, trace)  # exactly the heavy byz
        assert sum(unsafe.power(b) for b in blamed) * 3 >= unsafe.total_power
    assert unsafe.byz_power * 3 >= unsafe.total_power


def test_bounded_liveness_under_synchrony():
    """Post-GST bounded termination: with full delivery and a correct
    proposer every honest validator decides in round 0; with the round-0
    proposer faulty (proposal withheld) they time out, move to round 1,
    and decide there. The explorer checks safety; this pins progress."""
    rounds, _soup = model.synchronous_run(model.Config(max_round=2))
    assert rounds == 0
    rounds, _soup = model.synchronous_run(model.Config(max_round=2),
                                          withhold_round0=True)
    assert rounds == 1


def test_symmetry_reduction_is_sound():
    """The symmetry-reduced exploration reaches the same verdicts as the
    full one at the round-2 scope (orbit merging must not hide states):
    same violation-freeness AND the same set of reachable decisions."""
    full = model.explore(model.Config())
    red = model.explore(model.Config(), symmetry_reduce=True)
    assert (full.violation is None) == (red.violation is None)
    assert full.decisions_seen == red.decisions_seen
    assert red.states < full.states  # the reduction actually reduces


@pytest.mark.parametrize("n_honest,n_byz", [(3, 1), (2, 2)])
def test_byzantine_flood_is_complete(n_honest, n_byz):
    """The flood contains every vote a byzantine validator can cast —
    adversary choice is fully subsumed (model soundness guard)."""
    cfg = model.Config(n_honest=n_honest, n_byz=n_byz)
    soup = model.byzantine_soup(cfg)
    expect = (n_byz * (cfg.max_round + 1) * 2 * 3)
    assert len(soup) == expect
