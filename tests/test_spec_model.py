"""Exhaustive small-scope safety checking of the consensus voting rules
(spec/model.py) — the executable analogue of the reference's Ivy proofs
(spec/ivy-proofs/accountable_safety_1.ivy)."""

import importlib.util
import os
import sys

import pytest

_path = os.path.join(os.path.dirname(__file__), "..", "spec", "model.py")
_spec = importlib.util.spec_from_file_location("specmodel", _path)
model = importlib.util.module_from_spec(_spec)
sys.modules["specmodel"] = model
_spec.loader.exec_module(model)


def test_agreement_exhaustive_f_lt_third():
    """Over EVERY reachable interleaving at 3 honest + 1 byzantine-flooding
    validator (rounds 0..1, two values): no two honest validators decide
    differently, and no round ever carries two conflicting polkas
    (spec/consensus.md Theorem + Lemma 1)."""
    res = model.explore(model.Config())
    assert res.violation is None, res.violation
    assert res.lemma1_violation is None, res.lemma1_violation
    # the scope is not vacuous: both values are decidable, and the space
    # is the full product, not a truncated walk
    assert res.decisions_seen == {"A", "B"}
    assert res.states > 100_000


def test_teeth_removing_lock_rule_forks():
    """The invariant is not vacuous: with the lock/POL rules disabled
    (R4/R5 gone — validators prevote any proposal), the same explorer
    FINDS a disagreement trace with only f < N/3 byzantine power. The
    fork is NOT accountable: fewer than f+1 validators hold contradictory
    signatures, which is exactly why the lock rule (and not just vote
    dedup) is what buys accountable safety."""
    cfg = model.Config(lock_rule=False)
    res = model.explore(cfg, stop_at_violation=True)
    assert res.violation is not None
    trace, honest = res.violation
    decided = {s.decided for s in honest if s.decided != model.NIL}
    assert decided == {"A", "B"}
    blamed = model.fork_blame(cfg, trace, honest)
    f = cfg.n // 3
    assert blamed <= set(range(cfg.n_honest, cfg.n))  # honest never blamed
    assert len(blamed) < f + 1  # ...and blame does NOT reach f+1


def test_fork_at_f_geq_third_is_accountable():
    """With f >= N/3 (2 of 4 byzantine) forks exist — and in EVERY
    violating reachable state at this scope, blame localizes to >= f+1
    validators, none of them honest (the accountable-safety claim of
    accountable_safety_1.ivy, checked over the full enumeration rather
    than one witness)."""
    cfg = model.Config(n_honest=2, n_byz=2)
    res = model.explore(cfg)
    assert res.violations
    f = cfg.n // 3
    for trace, honest in res.violations:
        blamed = model.fork_blame(cfg, trace, honest)
        assert len(blamed) >= f + 1, (blamed, trace)
        assert blamed & set(range(cfg.n_honest)) == set(), (blamed, trace)


def test_quorum_below_two_thirds_breaks_agreement():
    """A 1/2 quorum (instead of >2/3) is unsafe even against a single
    byzantine validator — two quorums can intersect in the byzantine
    validator alone, and the explorer finds that fork. Pins the constant
    itself, not just the rules."""
    assert model.Config().quorum == 3  # >2/3 of 4
    res = model.explore(model.Config(quorum=2), stop_at_violation=True)
    assert res.violation is not None


def test_honest_only_scope_decides_and_agrees():
    """Degenerate scope sanity: with zero byzantine validators the model
    still reaches decisions and never forks."""
    res = model.explore(model.Config(n_honest=3, n_byz=0, max_round=1))
    assert res.violation is None
    assert res.decisions_seen  # proposals for both values exist; some decide


@pytest.mark.parametrize("n_honest,n_byz", [(3, 1), (2, 2)])
def test_byzantine_flood_is_complete(n_honest, n_byz):
    """The flood contains every vote a byzantine validator can cast —
    adversary choice is fully subsumed (model soundness guard)."""
    cfg = model.Config(n_honest=n_honest, n_byz=n_byz)
    soup = model.byzantine_soup(cfg)
    expect = (n_byz * (cfg.max_round + 1) * 2 * 3)
    assert len(soup) == expect
