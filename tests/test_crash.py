"""Crash-storm adversary plane (docs/SOAK.md crash cookbook): power-loss
hard kills, WAL-tail tearing, reboot-from-home recovery, and per-node
clock skew.

Quick tier: the tear_wal_tail fault unit against real WAL repair, the
hard-kill/reboot round trip on a durable 4-node fabric (torn tail + skew
composed), ONE canonical finalize crash site, the clock plumbing units,
and the skewed-clock evidence-pool no-false-expiry unit.

Slow tier: the full matrix — a mid-transition freeze + hard kill at EVERY
``consensus.finalize.*`` canonical crash site on a 5-node durable fabric,
each rebooting and converging fork-free onto the fault-free app hash
(exactly-once tx application: a double-applied block would fork the app
hash, which full-prefix agreement then catches).
"""

import os
import time

import pytest

from test_nemesis import _wait, repro  # noqa: F401 (shared harness)

from tendermint_tpu.consensus import wal as cwal
from tendermint_tpu.consensus.state_machine import ConsensusState
from tendermint_tpu.e2e import fabric
from tendermint_tpu.utils import clock as tmclock
from tendermint_tpu.utils import faults, nemesis

SEED = 2026

FINALIZE_SITES = (
    "consensus.finalize.save_block",
    "consensus.finalize.end_height",
    "consensus.finalize.apply_block",
    "consensus.finalize.prune",
    "consensus.finalize.done",
)


@pytest.fixture(autouse=True)
def _clean_chaos():
    faults.configure([], seed=SEED)
    nemesis.clear()
    yield
    nemesis.clear()
    nemesis.PLANE.on_heal.clear()
    faults.clear()
    faults.REGISTRY.crash_fn = lambda: os._exit(1)


def _tweak(cfg, idx):
    # hard-kill scenarios freeze consensus threads on purpose: keep the
    # stall watchdog from "recovering" the corpse before the kill lands
    cfg.consensus.watchdog_stall_s = lambda: 60.0


# ---------------------------------------------------------------------------
# Clock plumbing units (quick)
# ---------------------------------------------------------------------------


def test_clock_skew_and_rate():
    c = tmclock.Clock()
    base = c.now_ns()
    c.set_skew(120.0)
    assert c.skew_s == 120.0
    assert c.now_ns() - base >= int(119.0 * 1e9)
    c.set_skew(-60.0)
    assert c.now_ns() - base <= int(-59.0 * 1e9)
    assert tmclock.Clock(rate=4.0).timer_duration(2.0) == 0.5
    # independent instances: skewing one never moves another
    a, b = tmclock.Clock(), tmclock.Clock()
    a.set_skew(500.0)
    assert abs(b.now_ns() - tmclock.now_ns()) < int(5e9)


def test_ticker_honors_clock_rate():
    from tendermint_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker

    fired = []
    t = TimeoutTicker(fired.append, clock=tmclock.Clock(rate=50.0))
    t.schedule_timeout(TimeoutInfo(duration_s=2.0, height=1, round=0, step=1))
    assert _wait(lambda: fired, 1.0), "rate-50 clock must fire a 2s timeout fast"
    t.stop()


# ---------------------------------------------------------------------------
# tear_wal_tail against real WAL repair (quick)
# ---------------------------------------------------------------------------


def _write_wal(path: str, n: int = 6) -> list:
    w = cwal.WAL(path)
    for h in range(1, n + 1):
        w.write_sync(cwal.EndHeightMessage(h), h * 1000)
    w.close()
    return [tm.msg for tm, _ in cwal.WAL(path).iter_messages()]


@pytest.mark.parametrize("mode", ["torn", "partial"])
def test_tear_wal_tail_then_repair(tmp_path, mode):
    """tear_wal_tail models the crash the in-process abort can't produce
    (bytes the OS never flushed): the last frame is cut mid-body (torn)
    or mid-header (partial), and WAL repair-on-open must trim exactly
    back to the valid prefix."""
    path = str(tmp_path / "cs.wal")
    msgs = _write_wal(path)
    assert len(msgs) == 6
    removed = faults.tear_wal_tail(path, mode=mode, seed=7)
    assert removed > 0
    got = [tm.msg for tm, _ in cwal.WAL(path).iter_messages()]
    assert got == msgs[:-1], "repair must trim exactly the torn frame"
    # deterministic: same seed cuts the same bytes
    path2 = str(tmp_path / "cs2.wal")
    _write_wal(path2)
    assert faults.tear_wal_tail(path2, mode=mode, seed=7) == removed


def test_tear_wal_tail_idempotent_on_damaged_tail(tmp_path):
    path = str(tmp_path / "cs.wal")
    _write_wal(path)
    assert faults.tear_wal_tail(path, seed=3) > 0
    # already-torn tail: a second tear is a no-op, not double damage
    assert faults.tear_wal_tail(path, seed=3) == 0
    with pytest.raises(faults.FaultError):
        faults.tear_wal_tail(path, mode="confetti")


# ---------------------------------------------------------------------------
# Hard-kill / reboot round trip (quick)
# ---------------------------------------------------------------------------


def test_hard_kill_requires_durable_homes(tmp_path):
    cluster = fabric.Cluster(str(tmp_path), 3, topology="full")
    cluster.start()
    try:
        with pytest.raises(RuntimeError, match="durable"):
            cluster.hard_kill(1)
        with pytest.raises(KeyError):
            cluster.reboot(1)  # never crashed
    finally:
        cluster.stop()


def test_hard_kill_torn_tail_reboot_converges(tmp_path):
    """The tentpole round trip: power-loss kill mid-traffic with a torn
    WAL tail on the abandoned home, a skewed survivor, survivors keep
    committing, reboot re-joins the SAME identity from the home, and the
    cluster converges with full-prefix agreement, strictly monotone BFT
    header time, and no false evidence expiry."""
    cluster = fabric.Cluster(str(tmp_path), 4, topology="full",
                             durable=True, tweak=_tweak)
    cluster.start()
    try:
        with repro("hard-kill torn-tail reboot"):
            assert _wait(lambda: cluster.min_height() >= 2, 60, 0.1), \
                f"no initial progress: {cluster.heights()}"
            cluster.set_skew(3, 120.0)  # one skewed survivor, composed in
            cluster.hard_kill(2, tear="torn", seed=SEED)
            assert 2 not in cluster.nodes
            assert all(2 not in fn.links for fn in cluster.nodes.values())
            tip = cluster.max_height()
            assert _wait(lambda: cluster.min_height() >= tip + 2, 60, 0.1), \
                f"survivors stalled after kill: {cluster.heights()}"
            cluster.reboot(2)
            assert 2 in cluster.nodes
            target = cluster.max_height() + 2
            assert _wait(lambda: cluster.min_height() >= target, 90, 0.1), \
                f"rebooted node never caught up: {cluster.heights()}"
            audited = cluster.audit_agreement()
            assert audited >= target
            # BFT time strictly monotone along the agreed prefix even
            # with the +120s skewed survivor (weighted-median header time)
            times = [cluster.block_time(2, h) for h in range(1, audited + 1)]
            assert all(b > a for a, b in zip(times, times[1:]))
            for fn in cluster.nodes.values():
                for e in fn.node.evidence_pool.expired_log:
                    assert e["age_blocks"] > e["max_age_num_blocks"]
    finally:
        cluster.stop()


def test_hard_kill_is_not_graceful_stop(tmp_path):
    """A hard kill must leave the durable home exactly as the crash left
    it: the consensus WAL is NOT closed/flushed by the kill, so the home
    may legitimately hold a shorter WAL than a graceful stop would leave
    — and reboot() recovers from whatever is there."""
    cluster = fabric.Cluster(str(tmp_path), 4, topology="full",
                             durable=True, tweak=_tweak)
    cluster.start()
    try:
        with repro("hard-kill abandons home"):
            assert _wait(lambda: cluster.min_height() >= 2, 60, 0.1)
            home = cluster.nodes[1].home
            gen0 = cluster.nodes[1].generation
            cluster.hard_kill(1)
            # the durable home survives the kill, object gone from the map
            assert os.path.isdir(os.path.join(home, "cs.wal"))
            cluster.reboot(1)
            assert cluster.nodes[1].generation > gen0  # new incarnation
            assert cluster.nodes[1].home == home       # same durable home
            target = cluster.max_height() + 1
            assert _wait(lambda: cluster.min_height() >= target, 90, 0.1), \
                f"reboot from abandoned home failed: {cluster.heights()}"
            cluster.audit_agreement()
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# Canonical finalize crash sites (one quick, full matrix slow)
# ---------------------------------------------------------------------------


def _freeze_victim_crash_fn(victim: dict):
    """A crash_fn that simulates power loss INSIDE _finalize_commit: walk
    to the ConsensusState frame that hit the site, freeze its receive
    routine mid-transition (the drainer exits at the next _running check,
    leaving the height half-finalized), and record it for the harness to
    hard-kill. Returning lets the registry raise FaultInjected, which the
    consensus crash shields swallow — the freeze is what persists."""
    import sys

    def crash_fn():
        f = sys._getframe(1)
        while f is not None:
            cs = f.f_locals.get("self")
            if isinstance(cs, ConsensusState):
                cs._running = False
                victim["cs"] = cs
                break
            f = f.f_back
        return True

    return crash_fn


def _run_crash_site(tmp_path, site: str, nodes: int = 4):
    victim: dict = {}
    faults.REGISTRY.crash_fn = _freeze_victim_crash_fn(victim)
    faults.configure([f"{site}:crash@1"], seed=SEED)
    cluster = fabric.Cluster(str(tmp_path), nodes, topology="full",
                             durable=True, tweak=_tweak)
    cluster.start()
    try:
        with repro(f"crash site {site}"):
            assert _wait(lambda: "cs" in victim, 60, 0.05), \
                f"site {site} never hit: {cluster.heights()}"
            idx = next(i for i, fn in cluster.nodes.items()
                       if fn.node.consensus is victim["cs"])
            cluster.hard_kill(idx, seed=SEED)
            tip = cluster.max_height()
            assert _wait(lambda: cluster.min_height() >= tip + 2, 60, 0.1), \
                f"survivors stalled after {site} crash: {cluster.heights()}"
            cluster.reboot(idx)
            target = cluster.max_height() + 2
            assert _wait(lambda: cluster.min_height() >= target, 90, 0.1), (
                f"reboot after {site} crash never converged: "
                f"{cluster.heights()}")
            # fork-free full prefix ON the fault-free app-hash chain:
            # exactly-once application (a replayed/skipped block at the
            # crash point would diverge the app hash and fork here)
            assert cluster.audit_agreement() >= target
            metas = [cluster.nodes[i].node.block_store.load_block_meta(target)
                     for i in sorted(cluster.nodes)]
            hashes = {m.header.app_hash for m in metas}
            assert len(hashes) == 1, f"app hash diverged at {target}: {hashes}"
    finally:
        cluster.stop()


def test_crash_site_finalize_save_block(tmp_path):
    """Quick canary for the matrix: power loss at the first finalize
    crash site (before the block persists) recovers exactly-once."""
    _run_crash_site(tmp_path, "consensus.finalize.save_block")


@pytest.mark.slow
@pytest.mark.parametrize("site", FINALIZE_SITES)
def test_crash_site_matrix(tmp_path, site):
    """Hard kill at EVERY canonical finalize crash site on a 5-node
    durable fabric: each incarnation reboots from its abandoned home and
    converges fork-free onto the fault-free app hash."""
    _run_crash_site(tmp_path, site, nodes=5)


# ---------------------------------------------------------------------------
# Skewed-clock evidence pool: no false expiry (quick)
# ---------------------------------------------------------------------------


def test_skewed_clock_never_falsely_expires_evidence():
    """Expiry demands BOTH bounds (height AND duration): evidence young
    in blocks survives even when the duration bound reads as blown —
    which is exactly what a skewed clock or skewed BFT time produces.
    The dual-bound expiry logs into expired_log, and only dual-bound."""
    from tendermint_tpu.evidence.pool import EvidencePool, _pending_key
    from tendermint_tpu.state.state import State
    from tendermint_tpu.store.db import MemDB
    from tendermint_tpu.store import envelope
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence
    from tendermint_tpu.types.params import ConsensusParams, EvidenceParams
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.types.vote import Vote, PRECOMMIT_TYPE

    def ev_at(height, n=0):
        return DuplicateVoteEvidence(
            vote_a=Vote(height=height, round=0, type=PRECOMMIT_TYPE,
                        validator_address=bytes([0x11 + n]) * 20,
                        signature=b"\x22" * 64),
            vote_b=Vote(height=height, round=0, type=PRECOMMIT_TYPE,
                        validator_address=bytes([0x11 + n]) * 20,
                        signature=b"\x33" * 64),
            total_voting_power=30, validator_power=10,
            timestamp=Time(1_700_000_000, 0))

    params = ConsensusParams(evidence=EvidenceParams(
        max_age_num_blocks=100, max_age_duration_ns=int(60e9)))
    skewed = tmclock.Clock(skew_s=3600.0)  # +1h node clock
    pool = EvidencePool(MemDB(), None, None, clock=skewed)
    young = ev_at(150)   # 50 blocks old: inside the height bound
    old = ev_at(1, n=1)  # 199 blocks AND hours past: truly expired
    for e in (young, old):
        pool._db.set(_pending_key(e), envelope.wrap(e.bytes()))
    state = State(chain_id="t", last_block_height=200,
                  last_block_time=Time(1_700_009_000, 0),
                  consensus_params=params)
    pool.update(state, [])
    assert pool.is_pending(young), \
        "evidence young in blocks must survive a blown duration bound"
    assert not pool.is_pending(old)
    assert len(pool.expired_log) == 1
    e = pool.expired_log[0]
    assert e["height"] == 1 and e["age_blocks"] > e["max_age_num_blocks"]


def test_node_clock_is_per_node(tmp_path):
    """Each fabric node owns an independent Clock: set_skew moves one
    node's time source and nobody else's, and a rebooted incarnation
    comes back unskewed (a real machine's RTC outlives the power cut,
    but the injected skew rode the dead process)."""
    cluster = fabric.Cluster(str(tmp_path), 3, topology="full",
                             durable=True, tweak=_tweak)
    cluster.start()
    try:
        cluster.set_skew(1, 300.0)
        assert cluster.nodes[1].node.clock.skew_s == 300.0
        assert cluster.nodes[0].node.clock.skew_s == 0.0
        assert _wait(lambda: cluster.min_height() >= 1, 60, 0.1)
        cluster.hard_kill(1)
        cluster.reboot(1)
        assert cluster.nodes[1].node.clock.skew_s == 0.0
    finally:
        cluster.stop()
