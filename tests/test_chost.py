"""Differential tests: C host verifier (ops/chost) vs the pure-Python
scalar references (crypto/ed25519.verify, crypto/sr25519.verify).

The C path is the CPU half of the adaptive kernel/scalar crossover; its
contract is byte-identical accept/reject with the scalar reference
(reference semantics: crypto/ed25519/ed25519.go:148,
crypto/sr25519/pubkey.go:10).  Every case runs through BOTH C modes:
serial (mode 0) and RLC-batch (mode 1, Pippenger with serial fallback),
so a batch-equation bug can never hide behind the fallback."""

from __future__ import annotations

import hashlib
import random

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as ref
from tendermint_tpu.crypto import sr25519 as srref
from tendermint_tpu.ops import chost

# ensure_available: build inline -- the non-blocking available() would
# background the build and wrongly skip this whole module on a fresh tree.
pytestmark = pytest.mark.skipif(
    not chost.ensure_available(), reason="C host verifier unavailable (no g++?)")

rng = random.Random(0xC405)


def _keypair(i):
    priv = ref.gen_priv_key(bytes([i + 1]) * 32)
    return priv, priv.pub_key()


def _prep_ed(items):
    n = len(items)
    pubs = np.zeros((n, 32), np.uint8)
    r32 = np.zeros((n, 32), np.uint8)
    s32 = np.zeros((n, 32), np.uint8)
    h32 = np.zeros((n, 32), np.uint8)
    valid = np.zeros((n,), bool)
    for i, (pub, msg, sig) in enumerate(items):
        if len(pub) != 32 or len(sig) != 64:
            continue  # valid stays False, like prepare_scalars' size mask
        valid[i] = True
        pubs[i] = np.frombuffer(pub, np.uint8)
        r32[i] = np.frombuffer(sig[:32], np.uint8)
        s32[i] = np.frombuffer(sig[32:], np.uint8)
        h = int.from_bytes(
            hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % ref.L
        h32[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
    return pubs, h32, s32, r32, valid


def _check_ed(items):
    expect = np.array([ref.verify(p, m, s) for (p, m, s) in items])
    args = _prep_ed(items)
    for mode in (0, 1, 2):
        got = chost.ed25519_verify(*args, mode=mode)
        assert (got == expect).all(), (
            f"mode={mode} C={got.tolist()} python={expect.tolist()}")


def test_valid_signatures():
    items = []
    for i in range(20):
        priv, pub = _keypair(i)
        msg = b"msg-%d" % i
        items.append((pub.data, msg, ref.sign(priv.data, msg)))
    _check_ed(items)


def test_mixed_corruptions():
    items = []
    for i in range(24):
        priv, pub = _keypair(i % 6)
        msg = b"payload-%d" % i
        sig = bytearray(ref.sign(priv.data, msg))
        if i % 4 == 1:
            sig[rng.randrange(64)] ^= 1 << rng.randrange(8)
        elif i % 4 == 2:
            msg = msg + b"?"
        elif i % 4 == 3:
            sig = bytearray(rng.randbytes(64))
        items.append((pub.data, bytes(msg), bytes(sig)))
    _check_ed(items)


def test_adversarial_encodings():
    """Same vector set as test_ed25519_batch.test_adversarial_encodings."""
    priv, pub = _keypair(7)
    msg = b"edge"
    sig = ref.sign(priv.data, msg)
    s_int = int.from_bytes(sig[32:], "little")
    items = [
        (pub.data, msg, sig[:32] + (s_int + ref.L).to_bytes(32, "little")),
        (pub.data, msg, sig[:32] + ref.L.to_bytes(32, "little")),
        (ref.P.to_bytes(32, "little"), msg, sig),
        ((1).to_bytes(32, "little"), msg, sig),
        ((5).to_bytes(32, "little"), msg, sig),
        ((1 | (1 << 255)).to_bytes(32, "little"), msg, sig),
        (pub.data, msg, ref.P.to_bytes(32, "little") + sig[32:]),
        (pub.data, msg, bytes([sig[0], *sig[1:31], sig[31] ^ 0x80]) + sig[32:]),
        (pub.data[:-1], msg, sig),
        (pub.data, msg, sig[:-1]),
        (b"\x00" * 32, b"", b"\x00" * 64),
        (pub.data, msg, sig),
    ]
    _check_ed(items)


def test_small_order_pubkey_signatures():
    small = (ref.P - 1).to_bytes(32, "little")
    items = []
    for i in range(8):
        r = rng.randbytes(32)
        s = rng.randrange(ref.L).to_bytes(32, "little")
        items.append((small, b"m%d" % i, r + s))
    items.append((small, b"x", (1).to_bytes(32, "little") + b"\x00" * 32))
    _check_ed(items)


def test_forged_sig_under_invalid_pubkey():
    bad_pubs = [
        (5).to_bytes(32, "little"),
        ref.P.to_bytes(32, "little"),
        (1 | (1 << 255)).to_bytes(32, "little"),
    ]
    items = []
    for i, bad in enumerate(bad_pubs):
        s = (i + 2) * 12345 % ref.L
        r_bytes = ref._compress(ref._scalarmult(s, ref.BASE))
        forged = r_bytes + s.to_bytes(32, "little")
        items.append((bad, b"any %d" % i, forged))
    expect = np.array([ref.verify(p, m, s) for (p, m, s) in items])
    assert not expect.any()
    _check_ed(items)


def test_single_bad_item_in_large_batch_attributed():
    """RLC must fail then fall back to serial, attributing exactly the one
    corrupt item (reference per-vote error attribution, types/vote_set.go:205)."""
    items = []
    for i in range(40):
        priv, pub = _keypair(i % 5)
        msg = b"n%d" % i
        sig = ref.sign(priv.data, msg)
        if i == 23:
            sig = sig[:40] + bytes([sig[40] ^ 4]) + sig[41:]
        items.append((pub.data, msg, sig))
    expect = np.array([i != 23 for i in range(40)])
    args = _prep_ed(items)
    for mode in (0, 1):
        got = chost.ed25519_verify(*args, mode=mode)
        assert (got == expect).all()


def test_torsion_component_batch_consistency():
    """Keys/R with torsion components: the mod-8L reduction in the batch
    equation must keep batch-accept == serial-accept (the reason scalars on
    A are reduced mod 8L, not mod L)."""
    # build a mixed-order pubkey: A = [a]B + T where T has order 2
    a = 987654321 % ref.L
    t_pt = ref._decompress((ref.P - 1).to_bytes(32, "little"))
    assert t_pt is not None
    mixed = ref._add(ref._scalarmult(a, ref.BASE), t_pt)
    pub = ref._compress(mixed)
    items = []
    for i in range(12):
        # craft sigs that the serial path accepts: R' = [s]B - [h]A computed
        # with the actual mixed-order A
        s = (a * (i + 3) + 77) % ref.L
        r_guess = ref._compress(ref._scalarmult(s, ref.BASE))
        sig0 = r_guess + s.to_bytes(32, "little")
        msg = b"tors%d" % i
        h = int.from_bytes(
            hashlib.sha512(sig0[:32] + pub + msg).digest(), "little") % ref.L
        negA = (ref.P - mixed[0], mixed[1], mixed[2], (ref.P - mixed[3]) % ref.P)
        rp = ref._add(ref._scalarmult(s, ref.BASE), ref._scalarmult(h, negA))
        # R must be guessed before h; instead use the real construction:
        # pick random r scalar, R = [r]B + torsion sometimes
        items.append((pub, msg, sig0))
        items.append((pub, msg, ref._compress(rp) + s.to_bytes(32, "little")))
    _check_ed(items)


# --- sr25519 -----------------------------------------------------------------


def _prep_sr(items):
    from tendermint_tpu.ops import sr25519_batch as srb

    n = len(items)
    pubs = np.zeros((n, 32), np.uint8)
    r32 = np.zeros((n, 32), np.uint8)
    s32 = np.zeros((n, 32), np.uint8)
    valid = np.zeros((n,), bool)
    for i, (pub, msg, sig) in enumerate(items):
        if len(pub) != 32 or len(sig) != 64:
            continue
        pubs[i] = np.frombuffer(pub, np.uint8)
        r32[i] = np.frombuffer(sig[:32], np.uint8)
        s32[i] = np.frombuffer(sig[32:], np.uint8)
        # schnorrkel v1 marker bit (crypto/sr25519.py verify:358)
        valid[i] = bool(s32[i, 31] & 128)
        s32[i, 31] &= 127
    c32 = srb.challenges([it[1] for it in items], pubs, r32)
    return pubs, c32, s32, r32, valid


def _check_sr(items):
    expect = np.array([srref.verify(p, m, s) for (p, m, s) in items])
    args = _prep_sr(items)
    for mode in (0, 1, 2):
        got = chost.sr25519_verify(*args, mode=mode)
        assert (got == expect).all(), (
            f"mode={mode} C={got.tolist()} python={expect.tolist()}")


def test_sr25519_differential():
    privs = [srref.gen_priv_key(bytes([i + 1])) for i in range(10)]
    items = []
    for i, p in enumerate(privs):
        msg = b"sr-%d" % i
        items.append((p.pub_key().data, msg, p.sign(msg)))
    # corruptions: sig byte, msg, stripped marker bit, bad pub, bad sizes
    items[2] = (items[2][0], items[2][1],
                items[2][2][:40] + b"\x00" + items[2][2][41:])
    items[4] = (items[4][0], items[4][1] + b"!", items[4][2])
    stripped = bytearray(items[6][2])
    stripped[63] &= 127
    items[6] = (items[6][0], items[6][1], bytes(stripped))
    items.append((b"\x01" * 32, b"m", items[0][2]))
    items.append((items[0][0][:-1], b"m", items[0][2]))
    items.append((items[0][0], b"m", items[0][2][:-1]))
    # non-canonical s (>= L with marker bit)
    sbad = bytearray(items[1][2])
    sbad[32:64] = (ref.L + 7).to_bytes(32, "little")
    sbad[63] |= 128
    items.append((items[1][0], b"sr-1", bytes(sbad)))
    _check_sr(items)


def test_routing_host_below_crossover(monkeypatch):
    """ops dispatch routes sub-crossover batches to the host verifier (no
    device work: device_out is None) with bitmaps identical to the kernel."""
    from tendermint_tpu.ops import ed25519_batch as edb

    monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "512")
    items = []
    for i in range(20):
        priv, pub = _keypair(i % 4)
        msg = b"route-%d" % i
        sig = ref.sign(priv.data, msg)
        if i == 13:
            sig = sig[:5] + bytes([sig[5] ^ 1]) + sig[6:]
        items.append((pub.data, msg, sig))
    dev, finish = edb.dispatch_batch(items)
    assert dev is None, "sub-crossover batch must not touch the device"
    got = finish(None)
    expect = np.array([ref.verify(p, m, s) for (p, m, s) in items])
    assert (np.asarray(got) == expect).all()
    # force_device bypasses the host route (kernel warmup / kernel tests)
    got_dev = edb.verify_batch(items, force_device=True)
    assert (np.asarray(got_dev) == expect).all()


def test_verify_signature_fast_path_matches_reference():
    priv, pub = _keypair(3)
    msg = b"single"
    sig = ref.sign(priv.data, msg)
    assert pub.verify_signature(msg, sig)
    assert not pub.verify_signature(msg + b"x", sig)
    assert not pub.verify_signature(msg, sig[:32] + bytes(32))
    sp = srref.gen_priv_key(b"\x11")
    ssig = sp.sign(b"m")
    assert sp.pub_key().verify_signature(b"m", ssig)
    assert not sp.pub_key().verify_signature(b"n", ssig)


def test_sr25519_bad_item_attribution():
    privs = [srref.gen_priv_key(bytes([i + 40])) for i in range(12)]
    items = []
    for i, p in enumerate(privs):
        msg = b"batch-%d" % i
        sig = p.sign(msg)
        if i == 5:
            sig = sig[:12] + bytes([sig[12] ^ 2]) + sig[13:]
        items.append((p.pub_key().data, msg, sig))
    _check_sr(items)
