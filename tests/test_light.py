"""Light client: verifier semantics (port of light/verifier_test.go cases),
client sequential/skipping verification, witness detector, trusted store,
and the batched header-range verify (BASELINE config 3)."""

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.light import verifier as lv
from tendermint_tpu.light.client import Client, TrustOptions, SEQUENTIAL, SKIPPING
from tendermint_tpu.light.detector import ErrConflictingHeaders
from tendermint_tpu.light.provider import (
    ErrHeightTooHigh,
    ErrLightBlockNotFound,
    MockProvider,
)
from tendermint_tpu.light.range_verify import RangeVerifyError, verify_header_range
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.types.block import Commit, CommitSig, Header
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote

CHAIN_ID = "light-test-chain"
TRUST_PERIOD = 3 * 3600.0
DRIFT = 10.0
T0 = 1_700_000_000


def t(sec):
    return Time(T0 + sec, 0)


def _mk_keys(n, power=10, seed=0):
    """power: one int for all validators, or a per-validator list."""
    powers = power if isinstance(power, (list, tuple)) else [power] * n
    pairs = []
    for i in range(n):
        priv = ed25519.gen_priv_key(bytes([(seed * 37 + i + 1) % 256]) * 32)
        pairs.append((priv, Validator.new(priv.pub_key(), powers[i])))
    vs = ValidatorSet([v for _, v in pairs])
    by_addr = {v.address: p for p, v in pairs}
    privs = [by_addr[v.address] for v in vs.validators]
    return privs, vs


def _sign_commit(header, vals, privs, *, skip=(), bad_sig=()):
    bid = BlockID(hash=header.hash(),
                  part_set_header=PartSetHeader(total=1, hash=b"\xcd" * 32))
    sigs = []
    for i, (priv, val) in enumerate(zip(privs, vals.validators)):
        if i in skip:
            sigs.append(CommitSig.new_absent())
            continue
        ts = Time(header.time.seconds, 0)
        vote = Vote(type=PRECOMMIT_TYPE, height=header.height, round=1,
                    block_id=bid, timestamp=ts,
                    validator_address=val.address, validator_index=i)
        sig = priv.sign(vote.sign_bytes(CHAIN_ID))
        if i in bad_sig:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address, ts, sig))
    return Commit(height=header.height, round=1, block_id=bid, signatures=sigs)


def _mk_header(height, time_s, vals, next_vals, last_bid=None):
    return Header(
        chain_id=CHAIN_ID, height=height, time=t(time_s),
        last_block_id=last_bid or BlockID(),
        validators_hash=vals.hash(), next_validators_hash=next_vals.hash(),
        proposer_address=vals.validators[0].address,
    )


def gen_chain(n, privs, vs, start_time=0, step_s=10):
    """n adjacent light blocks (heights 1..n) under one validator set."""
    out = []
    last_bid = BlockID()
    for h in range(1, n + 1):
        header = _mk_header(h, start_time + h * step_s, vs, vs, last_bid)
        commit = _sign_commit(header, vs, privs)
        out.append(LightBlock(signed_header=SignedHeader(header, commit),
                              validator_set=vs.copy()))
        last_bid = commit.block_id
    return out


@pytest.fixture(scope="module")
def keys():
    return _mk_keys(4)


@pytest.fixture(scope="module")
def chain(keys):
    privs, vs = keys
    return gen_chain(12, privs, vs)


# --- verifier (reference: light/verifier_test.go) --------------------------

def test_verify_adjacent_happy(chain):
    lv.verify_adjacent(chain[0].signed_header, chain[1].signed_header,
                       chain[1].validator_set, TRUST_PERIOD, t(100), DRIFT)


def test_verify_adjacent_expired_trusted(chain):
    with pytest.raises(lv.ErrOldHeaderExpired):
        lv.verify_adjacent(chain[0].signed_header, chain[1].signed_header,
                           chain[1].validator_set, 1.0, t(1000), DRIFT)


def test_verify_adjacent_future_time(chain):
    # New header time is beyond now + drift.
    with pytest.raises(lv.ErrInvalidHeader):
        lv.verify_adjacent(chain[0].signed_header, chain[1].signed_header,
                           chain[1].validator_set, TRUST_PERIOD, t(5), DRIFT)


def test_verify_adjacent_vals_hash_mismatch(chain, keys):
    privs, vs = keys
    other_privs, other_vs = _mk_keys(4, seed=9)
    header = _mk_header(2, 20, other_vs, other_vs)
    commit = _sign_commit(header, other_vs, other_privs)
    sh = SignedHeader(header, commit)
    with pytest.raises(lv.LightClientError):
        lv.verify_adjacent(chain[0].signed_header, sh, other_vs,
                           TRUST_PERIOD, t(100), DRIFT)


def test_verify_adjacent_insufficient_power(keys):
    privs, vs = keys
    c = gen_chain(2, privs, vs)
    # Re-sign height 2's commit with 3 of 4 absent: 10 of 40 power < 2/3.
    header = c[1].signed_header.header
    commit = _sign_commit(header, vs, privs, skip=(1, 2, 3))
    sh = SignedHeader(header, commit)
    with pytest.raises(lv.ErrInvalidHeader):
        lv.verify_adjacent(c[0].signed_header, sh, vs, TRUST_PERIOD, t(100), DRIFT)


def test_verify_non_adjacent_happy(chain):
    # Skip straight from height 1 to height 8; same valset so 1/3 trust holds.
    lv.verify_non_adjacent(chain[0].signed_header, chain[0].validator_set,
                           chain[7].signed_header, chain[7].validator_set,
                           TRUST_PERIOD, t(200), DRIFT)


def test_verify_non_adjacent_untrusted_valset():
    privs, vs = _mk_keys(4)
    c = gen_chain(1, privs, vs)
    # Entirely new validator set at height 5: 0 of trusted power signed.
    new_privs, new_vs = _mk_keys(4, seed=5)
    header = _mk_header(5, 50, new_vs, new_vs)
    commit = _sign_commit(header, new_vs, new_privs)
    sh = SignedHeader(header, commit)
    with pytest.raises(lv.ErrNewValSetCantBeTrusted):
        lv.verify_non_adjacent(c[0].signed_header, c[0].validator_set,
                               sh, new_vs, TRUST_PERIOD, t(200), DRIFT)


def test_validate_trust_level():
    for num, den in ((1, 3), (1, 2), (2, 3), (1, 1)):
        lv.validate_trust_level((num, den))
    for num, den in ((0, 1), (1, 4), (2, 1), (1, 0)):
        with pytest.raises(lv.LightClientError):
            lv.validate_trust_level((num, den))


def test_verify_backwards(chain):
    lv.verify_backwards(chain[1].signed_header.header,
                        chain[2].signed_header.header)
    # Wrong linkage: height 1 is not the parent of height 3.
    with pytest.raises(lv.ErrInvalidHeader):
        lv.verify_backwards(chain[0].signed_header.header,
                            chain[2].signed_header.header)


# --- trusted store ---------------------------------------------------------

def test_store_roundtrip_and_prune(chain):
    store = DBStore(MemDB())
    for lb in chain[:5]:
        store.save_light_block(lb)
    assert store.size() == 5
    assert store.latest_light_block().height == 5
    assert store.first_light_block_height() == 1
    assert store.light_block_before(4).height == 3
    store.prune(2)
    assert store.size() == 2
    assert store.first_light_block_height() == 4
    got = store.light_block(5)
    assert got.signed_header.header.hash() == chain[4].hash()


# --- client ----------------------------------------------------------------

def _client(chain, mode, witnesses=(), store=None, height=1):
    primary = MockProvider(CHAIN_ID, {lb.height: lb for lb in chain})
    return Client(
        CHAIN_ID,
        TrustOptions(period_s=TRUST_PERIOD, height=height,
                     hash=chain[height - 1].hash()),
        primary, list(witnesses), store or DBStore(MemDB()),
        verification_mode=mode,
    ), primary


def test_client_sequential_catchup(chain):
    client, _ = _client(chain, SEQUENTIAL)
    lb = client.verify_light_block_at_height(10, t(500))
    assert lb.height == 10
    # All intermediate headers were persisted.
    assert client.trusted_store.light_block(5) is not None
    assert client.latest_trusted.height == 10


def test_client_skipping_catchup(chain):
    client, _ = _client(chain, SKIPPING)
    lb = client.verify_light_block_at_height(12, t(500))
    assert lb.height == 12
    assert client.latest_trusted.height == 12


def test_client_update(chain):
    client, _ = _client(chain, SKIPPING)
    lb = client.update(t(500))
    assert lb is not None and lb.height == 12
    assert client.update(t(501)) is None  # already at tip


def test_client_historical_and_backwards(chain):
    client, _ = _client(chain, SEQUENTIAL, height=5)
    client.verify_light_block_at_height(9, t(500))
    # Height 3 < first trusted (5): backwards hash-linked walk.
    lb = client.verify_light_block_at_height(3, t(500))
    assert lb.height == 3


def test_client_trust_anchor_mismatch(chain):
    primary = MockProvider(CHAIN_ID, {lb.height: lb for lb in chain})
    with pytest.raises(lv.LightClientError):
        Client(CHAIN_ID,
               TrustOptions(period_s=TRUST_PERIOD, height=1, hash=b"\x11" * 32),
               primary, [], DBStore(MemDB()))


def test_client_detector_conflicting_witness(chain, keys):
    privs, vs = keys
    # A forked chain: same heights, different app state (different time step).
    fork = gen_chain(12, privs, vs, start_time=1, step_s=10)
    assert fork[5].hash() != chain[5].hash()
    # Witness agrees on the trust anchor (height 1) but forks afterwards.
    witness_blocks = {lb.height: lb for lb in fork}
    witness_blocks[1] = chain[0]
    witness = MockProvider(CHAIN_ID, witness_blocks)
    client, primary = _client(chain, SEQUENTIAL, witnesses=[witness])
    with pytest.raises(ErrConflictingHeaders):
        client.verify_light_block_at_height(6, t(500))
    # Evidence was reported to both sides and the witness was dropped.
    assert witness.evidences and primary.evidences
    assert client.witnesses == []
    # A client that HAD witnesses refuses to continue without any.
    from tendermint_tpu.light.detector import ErrNoWitnesses
    with pytest.raises(ErrNoWitnesses):
        client.verify_light_block_at_height(8, t(500))


def test_mock_provider_errors(chain):
    p = MockProvider(CHAIN_ID, {lb.height: lb for lb in chain[:3]})
    with pytest.raises(ErrHeightTooHigh):
        p.light_block(99)
    p.remove(2)
    with pytest.raises(ErrLightBlockNotFound):
        p.light_block(2)


# --- batched range verify (BASELINE config 3 shape) ------------------------

def test_range_verify_happy(keys):
    privs, vs = keys
    c = gen_chain(60, privs, vs)
    store = DBStore(MemDB())
    verify_header_range(c[0], c[1:], TRUST_PERIOD, t(900), DRIFT, store=store)
    assert store.size() == 59


def test_range_verify_matches_sequential_failure(keys):
    privs, vs = keys
    c = gen_chain(20, privs, vs)
    # Corrupt one signature inside the serial 2/3 prefix at height 9.
    bad_header = c[8].signed_header.header
    c[8].signed_header.commit = _sign_commit(bad_header, vs, privs, bad_sig=(0,))
    with pytest.raises(RangeVerifyError) as ei:
        verify_header_range(c[0], c[1:], TRUST_PERIOD, t(900), DRIFT)
    assert ei.value.height == 9


def test_range_verify_broken_linkage(keys):
    privs, vs = keys
    c = gen_chain(5, privs, vs)
    with pytest.raises(RangeVerifyError):
        verify_header_range(c[0], [c[1], c[3]], TRUST_PERIOD, t(900), DRIFT)


def test_light_proxy_serves_verified_data(tmp_path):
    """LightProxy: commit/validators/light_block come from verified light
    blocks; raw blocks are accepted only when they hash to the verified
    header (reference: light/proxy/proxy.go)."""
    import json
    import os
    import time as _time
    import urllib.request

    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.light.proxy import LightProxy
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import MockPV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.light_block import LightBlock

    priv = ed25519.gen_priv_key(b"\x53" * 32)
    genesis = GenesisDoc(chain_id="lp-chain", genesis_time=Time(1700003000, 0),
                         validators=[GenesisValidator(b"", priv.pub_key(), 10)])
    cfg = test_config()
    cfg.set_root(str(tmp_path / "node"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = ""
    node = Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x54" * 32)))
    node.start()
    proxy = None
    try:
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline and node.block_store.height < 4:
            _time.sleep(0.1)
        base = "http://" + node.rpc_server.laddr.split("://", 1)[1]
        from tendermint_tpu.light import Client, DBStore, HTTPProvider, TrustOptions
        from tendermint_tpu.store.db import MemDB

        primary = HTTPProvider("lp-chain", base)
        anchor = primary.light_block(1)
        client = Client("lp-chain",
                        TrustOptions(period_s=10 * 365 * 24 * 3600.0, height=1,
                                     hash=anchor.hash()),
                        primary, [], DBStore(MemDB()), max_clock_drift_s=120.0)
        proxy = LightProxy(client, base)
        proxy.start()

        def rpc(method, params=None):
            body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                               "params": params or {}}).encode()
            addr = "http://" + proxy.laddr.split("://", 1)[1]
            with urllib.request.urlopen(urllib.request.Request(
                    addr, data=body,
                    headers={"Content-Type": "application/json"}), timeout=10) as r:
                doc = json.loads(r.read())
            if doc.get("error"):
                raise RuntimeError(doc["error"])
            return doc["result"]

        assert rpc("health") == {}
        st = rpc("status")
        assert st["node_info"]["network"] == "lp-chain"

        c = rpc("commit", {"height": 3})
        assert c["verified"] and c["signed_header"]["height"] == "3"

        v = rpc("validators", {"height": 3})
        assert v["verified"] and v["total"] == "1"

        lb_doc = rpc("light_block", {"height": 3})
        lb = LightBlock.unmarshal(bytes.fromhex(lb_doc["light_block"]))
        lb.validate_basic("lp-chain")

        b = rpc("block", {"height": 3})
        assert b["verified"]
        assert b["block"]["header"]["height"] == "3"

        # URI-style GET works like the node RPC
        addr = "http://" + proxy.laddr.split("://", 1)[1]
        with urllib.request.urlopen(f"{addr}/status", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["result"]["node_info"]["network"] == "lp-chain"

        # a primary lying about block content is caught: tamper with the
        # forwarded block and run the binding check directly
        lb3 = client.trusted_store.light_block(3)
        tampered = json.loads(json.dumps(b))
        tampered["block"]["data"]["txs"] = [
            __import__("base64").b64encode(b"forged=tx").decode()]
        try:
            proxy._check_block_against_header(tampered, lb3)
            raise AssertionError("tampered txs accepted")
        except ValueError as e:
            assert "merkle" in str(e)
        tampered2 = json.loads(json.dumps(b))
        tampered2["block"]["header"]["app_hash"] = "AB" * 32
        try:
            proxy._check_block_against_header(tampered2, lb3)
            raise AssertionError("tampered app_hash accepted")
        except ValueError as e:
            assert "app_hash" in str(e)

        # the proxy's trusted store grew through these verifications
        assert client.trusted_store.light_block(3) is not None
    finally:
        if proxy is not None:
            proxy.stop()
        node.stop()


def test_exhaustive_threshold_boundaries():
    """Enumerate EVERY signer subset at several set sizes/powers and pin
    the exact acceptance boundaries of the two light-client verifies:
    verify_commit_light needs voting power > 2/3 of the set
    (types/validator_set.go:722), verify_commit_light_trusting at level
    (1,3) needs > 1/3 of the TRUSTED set's power (:772-830). The batched
    kernel path must agree with pure arithmetic on all 2^n subsets."""
    import itertools

    from tendermint_tpu.types.validator_set import ErrNotEnoughVotingPowerSigned

    for seed, powers in enumerate(
            ([10, 10, 10, 10], [1, 2, 3, 10], [5, 5, 5, 5, 5])):
        n = len(powers)
        privs, vals = _mk_keys(n, power=powers, seed=seed + 9)
        header = _mk_header(7, 800, vals, vals)
        total = vals.total_voting_power()
        for mask in itertools.product([0, 1], repeat=n):
            absent = tuple(i for i, m in enumerate(mask) if not m)
            commit = _sign_commit(header, vals, privs, skip=absent)
            signed = sum(v.voting_power
                         for v, m in zip(vals.validators, mask) if m)

            def expect(ok_fn, needed_gt):
                try:
                    ok_fn()
                    accepted = True
                except ErrNotEnoughVotingPowerSigned:
                    accepted = False
                want = signed * 3 > needed_gt  # strict >
                assert accepted == want, (powers, mask, signed)

            expect(lambda: vals.verify_commit_light(
                CHAIN_ID, commit.block_id, 7, commit), 2 * total)
            expect(lambda: vals.verify_commit_light_trusting(
                CHAIN_ID, commit, (1, 3)), total)
