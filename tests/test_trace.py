"""ISSUE 10: the consensus flight recorder (utils/trace.py,
docs/OBSERVABILITY.md).

Four layers:

1. Tracer units: instance isolation (no cross-node interleaving), causal
   parent/child linkage + height inheritance, ring bounds, thread safety.
2. THE disabled-cost gate: with tracing off, instrumented paths must not
   touch the ring, and the hot-site guard (one attribute load) must stay
   ~free — this is what lets the spans live on per-message paths.
3. Timeline semantics: lifecycle census, causal-order verdict, phase
   aggregation, last_phase.
4. A 3-node fabric mesh smoke: a committed height's timeline contains
   every lifecycle phase exactly once, served over the unsafe_timeline
   RPC route.
"""

import json
import threading
import time
import urllib.request

import pytest

from tendermint_tpu.utils import trace

pytestmark = pytest.mark.quick


@pytest.fixture
def tracer():
    t = trace.Tracer("t-unit", cap=256, enabled=True)
    yield t
    t.disable()


# ---------------------------------------------------------------------------
# 1. tracer units
# ---------------------------------------------------------------------------


def test_instance_isolation_no_interleaving():
    """Two tracers (two fabric nodes) never see each other's spans, and
    neither pollutes the process DEFAULT ring."""
    before_default = len(trace.DEFAULT.dump())
    a = trace.Tracer("nodeA", enabled=True)
    b = trace.Tracer("nodeB", enabled=True)
    try:
        a.mark("consensus.commit", height=1)
        b.mark("consensus.proposal", height=2)
        with a.activate():
            trace.mark("consensus.precommit", height=1)
        assert [s.name for s in a.dump()] == ["consensus.commit",
                                              "consensus.precommit"]
        assert [s.name for s in b.dump()] == ["consensus.proposal"]
        assert len(trace.DEFAULT.dump()) == before_default
    finally:
        a.disable()
        b.disable()


def test_causal_parent_child_and_height_inheritance(tracer):
    with tracer.span("consensus.vote_drain", height=9, votes=3) as outer:
        with tracer.span("verify.host_prep", n=64) as inner:
            pass
        tracer.record("verify.queue", 0.002)
        tracer.mark("consensus.precommit")
        assert tracer.current_height() == 9
    assert tracer.current_height() is None
    by_name = {s.name: s for s in tracer.dump()}
    drain = by_name["consensus.vote_drain"]
    assert drain.span_id == outer and drain.parent_id == 0
    assert by_name["verify.host_prep"].span_id == inner
    # causality: children link the enclosing span and inherit its height
    for child in ("verify.host_prep", "verify.queue", "consensus.precommit"):
        assert by_name[child].parent_id == drain.span_id, child
        assert by_name[child].tags["height"] == 9, child
    # explicit height beats inheritance
    with tracer.span("fastsync.dispatch", height=5):
        tracer.mark("fastsync.apply", height=6)
    assert {s.tags["height"] for s in tracer.dump()
            if s.name == "fastsync.apply"} == {6}


def test_ring_bound_evicts_oldest():
    t = trace.Tracer("ring", cap=16, enabled=True)
    try:
        for i in range(100):
            t.mark("consensus.commit", height=i)
        spans = t.dump()
        assert len(spans) == 16 and t.size() == 16
        assert [s.tags["height"] for s in spans] == list(range(84, 100))
    finally:
        t.disable()


def test_trace_cap_env_knob(monkeypatch):
    monkeypatch.setenv("TMTPU_TRACE_CAP", "32")
    assert trace.Tracer("capped").cap == 32
    monkeypatch.setenv("TMTPU_TRACE_CAP", "bogus")
    assert trace.Tracer("fallback").cap == trace.DEFAULT_CAP


def test_thread_safety_concurrent_recording():
    t = trace.Tracer("mt", cap=8192, enabled=True)
    errs = []

    def worker(tid):
        try:
            for i in range(200):
                with t.span("consensus.vote_drain", height=tid):
                    t.mark("consensus.commit")
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    t.disable()
    assert not errs
    spans = t.dump()
    assert len(spans) == 8 * 200 * 2
    # per-thread parent stacks never crossed: every mark's parent is a
    # drain span carrying the SAME thread's height tag
    drains = {s.span_id: s for s in spans
              if s.name == "consensus.vote_drain"}
    for s in spans:
        if s.name == "consensus.commit":
            assert s.parent_id in drains
            assert drains[s.parent_id].tags["height"] == s.tags["height"]


# ---------------------------------------------------------------------------
# 2. the disabled-cost quick gate
# ---------------------------------------------------------------------------


def test_disabled_path_records_nothing_and_stays_cheap():
    """ISSUE 10 acceptance: disabled tracing costs one attribute load at
    the hot sites. Structural half: nothing touches the ring. Timing
    half: the guard pattern stays within an order of magnitude of a bare
    loop (generous bound — this catches an accidental lock/allocation on
    the disabled path, not micro-regressions)."""
    t = trace.Tracer("gate")  # disabled
    with t.span("consensus.vote_drain", height=1):
        pass
    t.mark("consensus.commit")
    t.record("verify.queue", 0.1)
    assert t.dump() == [] and not t.enabled

    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        if t.enabled:  # the documented hot-site guard
            raise AssertionError
    guard_s = time.perf_counter() - t0
    assert guard_s / n < 2e-6, f"{guard_s / n * 1e9:.0f} ns/guard"


def test_enabled_refcount_maintains_module_guard():
    base = trace.ENABLED
    a = trace.Tracer("ra")
    b = trace.Tracer("rb")
    a.enable()
    b.enable()
    assert trace.ENABLED
    a.disable()
    assert trace.ENABLED  # b still on
    a.disable()  # idempotent: must not underflow the refcount
    assert trace.ENABLED
    b.disable()
    assert trace.ENABLED == base


# ---------------------------------------------------------------------------
# 3. timeline / last_phase / metrics mirror
# ---------------------------------------------------------------------------


def test_timeline_lifecycle_census_and_causal_order(tracer):
    for name in trace.LIFECYCLE:
        tracer.mark(name, height=7, round=0)
    tracer.mark("consensus.proposal", height=8)  # other height: filtered
    tl = tracer.timeline(7)
    assert tl["lifecycle_complete"] and tl["causal_ok"]
    assert all(n == 1 for n in tl["lifecycle"].values())
    assert all(s["tags"]["height"] == 7 for s in tl["spans"])

    # out-of-order lifecycle (commit observed before proposal) is flagged
    t2 = trace.Tracer("ooo", enabled=True)
    try:
        t2.mark("consensus.commit", height=3)
        t2.mark("consensus.proposal", height=3)
        tl2 = t2.timeline(3)
        assert not tl2["causal_ok"] and not tl2["lifecycle_complete"]
    finally:
        t2.disable()


def test_timeline_phase_aggregation(tracer):
    with tracer.span("consensus.vote_drain", height=4):
        tracer.record("verify.queue", 0.25)
        tracer.record("verify.queue", 0.25)
    ph = tracer.timeline(4)["phases"]
    assert ph["verify.queue"]["count"] == 2
    assert ph["verify.queue"]["total_s"] == pytest.approx(0.5)


def test_last_phase_names_most_recent_completion(tracer):
    assert tracer.last_phase() is None
    tracer.mark("consensus.precommit", height=12, round=1)
    lp = tracer.last_phase()
    assert lp["name"] == "consensus.precommit"
    assert lp["height"] == 12 and lp["round"] == 1
    assert lp["age_s"] >= 0.0


def test_metrics_mirror_phase_and_step_histograms(tracer):
    from tendermint_tpu.utils import metrics as tmmetrics

    m = tmmetrics.NodeMetrics()
    text = m.registry.expose()
    # pre-seeded: every mirrored phase scrapes explicit zeros, with the
    # full histogram exposition (satellite 2)
    for phase in trace.MIRRORED_SPANS:
        assert (f'tendermint_trace_phase_seconds_count{{phase="{phase}"}} 0'
                in text), phase
    assert ('tendermint_trace_phase_seconds_bucket{phase="verify.readback"'
            ',le="+Inf"} 0') in text
    assert ('tendermint_trace_phase_seconds_sum{phase="verify.readback"} 0.0'
            in text)
    assert ('tendermint_consensus_step_duration_seconds_count'
            '{step="RoundStepPropose"} 0') in text
    tmmetrics.GLOBAL_NODE_METRICS = m
    try:
        tracer.record("verify.readback", 0.02, height=1)
        tracer.record("consensus.step", 0.01, step="RoundStepPropose")
        text = m.registry.expose()
        assert ('tendermint_trace_phase_seconds_count'
                '{phase="verify.readback"} 1') in text
        assert ('tendermint_consensus_step_duration_seconds_count'
                '{step="RoundStepPropose"} 1') in text
    finally:
        tmmetrics.GLOBAL_NODE_METRICS = None


def test_pending_verify_spans_via_production_dispatch(tracer):
    """The crypto-layer phases fire through the real dispatch()/resolve()
    contract and inherit the drain height captured at dispatch time."""
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.crypto import ed25519

    priv = ed25519.gen_priv_key(b"\x77" * 32)
    pub = priv.pub_key()
    items = [(pub, b"m%d" % i, ed25519.sign(priv.data, b"m%d" % i))
             for i in range(64)]
    with tracer.activate():
        with tracer.span("consensus.vote_drain", height=21, votes=64):
            v = crypto_batch.create_batch_verifier("ed25519")
            for p, msg, sig in items:
                v.add(p, msg, sig)
            pending = v.dispatch()
        ok, bitmap = pending.resolve()
    assert ok and all(bitmap)
    agg = tracer.summarize()
    assert agg.get("verify.host_prep", {}).get("count") == 1
    # queue wait recorded between dispatch and resolve, on the height
    # captured at dispatch
    queue_spans = [s for s in tracer.dump() if s.name == "verify.queue"]
    assert queue_spans and queue_spans[0].tags.get("height") == 21


# ---------------------------------------------------------------------------
# 4. 3-node mesh smoke: the committed-height timeline end to end
# ---------------------------------------------------------------------------


def _rpc(base: str, method: str, params: dict):
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    with urllib.request.urlopen(urllib.request.Request(
            base, data=body, headers={"Content-Type": "application/json"}),
            timeout=10) as r:
        doc = json.loads(r.read())
    assert "error" not in doc, doc
    return doc["result"]


def test_three_node_mesh_timeline_smoke(tmp_path):
    """Satellite 4 + acceptance: a committed height's timeline contains
    every lifecycle phase exactly once, in causal order, on every node —
    and the unsafe_timeline/unsafe_trace RPC routes serve it."""
    from tendermint_tpu.e2e.fabric import Cluster

    cluster = Cluster(str(tmp_path), 3, topology="full", rpc_node=0,
                      trace=True)
    cluster.start()
    try:
        assert cluster.wait_min_height(4, timeout=120), cluster.heights()
        floor = cluster.min_height()
        # scan recent fully-committed heights (newest first: ring-eviction
        # safe) for one every node saw in a single round
        found = None
        for h in range(floor - 1, 1, -1):
            tls = [cluster.nodes[i].node.tracer.timeline(h) for i in (0, 1, 2)]
            if all(tl["lifecycle_complete"] and tl["causal_ok"]
                   and all(n == 1 for n in tl["lifecycle"].values())
                   for tl in tls):
                found = h
                break
        assert found is not None, {
            i: cluster.nodes[i].node.tracer.timeline(floor - 1)["lifecycle"]
            for i in (0, 1, 2)}

        # the RPC surface: unsafe_timeline serves the same structure
        rpc = cluster.nodes[0].node.rpc_server
        base = "http://" + rpc.laddr.split("://", 1)[1]
        tl = _rpc(base, "unsafe_timeline", {"height": found})
        assert tl["height"] == found and tl["lifecycle_complete"]
        assert tl["causal_ok"] and tl["spans"]
        # unsafe_trace: state + aggregation, and live disable/enable
        view = _rpc(base, "unsafe_trace", {})
        assert view["enabled"] and view["spans"] > 0
        assert "consensus.step" in view["summary"]
        view = _rpc(base, "unsafe_trace", {"enable": False})
        assert not view["enabled"]
        view = _rpc(base, "unsafe_trace", {"enable": True})
        assert view["enabled"]
    finally:
        cluster.stop()
