"""RPC client library (rpc/client.py) against a live node — the analogue of
the reference's rpc/client tests driving both HTTP and Local clients over
one behavior table (rpc/client/rpc_test.go)."""

import os
import time

import pytest

from tendermint_tpu.config.config import test_config as _test_config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.rpc.client import HTTPClient, LocalClient, RPCClientError
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.tx import tx_hash


@pytest.fixture(scope="module")
def live_node(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("rpc_client")
    priv = ed25519.gen_priv_key(b"\x51" * 32)
    genesis = GenesisDoc(
        chain_id="client-chain", genesis_time=Time(1700005000, 0),
        validators=[GenesisValidator(b"", priv.pub_key(), 10)],
    )
    cfg = _test_config()
    cfg.set_root(str(tmp_path / "node"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = ""
    node = Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x52" * 32)))
    node.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and node.block_store.height < 2:
        time.sleep(0.1)
    assert node.block_store.height >= 2
    yield node
    node.stop()


@pytest.fixture(params=["http", "local"])
def client(request, live_node):
    if request.param == "http":
        return HTTPClient(live_node.rpc_server.laddr)
    return LocalClient(live_node)


def test_status_and_info_methods(client):
    st = client.status()
    assert st["node_info"]["network"] == "client-chain"
    assert int(st["sync_info"]["latest_block_height"]) >= 2
    assert client.health() == {}
    assert client.abci_info()["response"]
    ni = client.net_info()
    assert "n_peers" in ni


def test_block_family(client):
    b = client.block(height=1)
    assert int(b["block"]["header"]["height"]) == 1
    h = client.header(height=1)
    assert h["header"] == b["block"]["header"]
    c = client.commit(height=1)
    assert int(c["signed_header"]["header"]["height"]) == 1
    vals = client.validators(height=1)
    assert int(vals["total"]) == 1
    bc = client.blockchain(minHeight=1, maxHeight=2)
    assert len(bc["block_metas"]) == 2
    cp = client.consensus_params(height=1)
    assert int(cp["consensus_params"]["block"]["max_bytes"]) > 0
    g = client.genesis()
    assert g["genesis"]["chain_id"] == "client-chain"


def test_broadcast_and_tx_lookup(client, live_node):
    tx = b"client-tx-%s" % type(client).__name__.encode()
    res = client.broadcast_tx_sync(tx)
    assert res["code"] == 0
    h = tx_hash(tx)
    deadline = time.monotonic() + 30
    doc = None
    while time.monotonic() < deadline and doc is None:
        try:
            doc = client.tx(h)
        except RPCClientError:
            time.sleep(0.1)
    assert doc is not None and doc["hash"] == h.hex().upper()
    found = client.tx_search(query=f"tx.height={doc['height']}")
    assert int(found["total_count"]) >= 1
    proved = client.tx(h, prove=True)
    assert proved["proof"]["root_hash"]


def test_abci_query_roundtrip(client):
    tx = b"queryk=queryv"
    client.broadcast_tx_sync(tx)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        r = client.abci_query("/key", b"queryk")["response"]
        if r.get("value"):
            import base64

            assert base64.b64decode(r["value"]) == b"queryv"
            return
        time.sleep(0.1)
    raise AssertionError("abci_query never saw the committed key")


def test_error_surface(client):
    with pytest.raises(RPCClientError) as ei:
        client.block(height=10_000_000)
    assert ei.value.code == -32603
    with pytest.raises(RPCClientError):
        client._call("no_such_method", {})


def test_unconfirmed_and_check_tx(client):
    res = client.check_tx(b"check-only=1")
    assert res["code"] == 0
    n = client.num_unconfirmed_txs()
    assert "total" in n or "n_txs" in n


def test_subscribe_streams_new_blocks(client):
    gen = client.subscribe("tm.event='NewBlock'", timeout=30)
    try:
        ev = next(gen)
        assert ev["query"] == "tm.event='NewBlock'"
        assert "block" in ev["data"]["value"] or ev["data"]
    finally:
        gen.close()


def test_unsafe_routes_refused_by_default(client):
    """reference: rpc/core/routes.go:51 AddUnsafeRoutes — control routes
    are unreachable unless rpc.unsafe is configured."""
    for call in (lambda: client._call("unsafe_flush_mempool", {}),
                 lambda: client._call("dial_seeds", {"seeds": ["x@1.2.3.4:1"]}),
                 lambda: client._call("dial_peers", {"peers": ["x@1.2.3.4:1"]})):
        with pytest.raises(RPCClientError, match="unsafe"):
            call()


def test_unsafe_flush_mempool_when_enabled(live_node):
    live_node.config.rpc.unsafe = True
    try:
        c = LocalClient(live_node)
        c.broadcast_tx_sync(b"flushme=1")
        # tx may commit quickly; flush must succeed and empty the pool
        assert c._call("unsafe_flush_mempool", {}) == {}
        assert live_node.mempool.size() == 0
        with pytest.raises(RPCClientError, match="no seeds"):
            c._call("dial_seeds", {"seeds": []})
    finally:
        live_node.config.rpc.unsafe = False


def test_unsafe_dial_validation(live_node):
    """Addresses validate up front (reference: net.go parses before
    dialing); unsupported flags error instead of silently no-oping."""
    live_node.config.rpc.unsafe = True
    try:
        c = LocalClient(live_node)
        with pytest.raises(RPCClientError, match="invalid"):
            c._call("dial_peers", {"peers": ["not-an-address"]})
        with pytest.raises(RPCClientError, match="non-empty list"):
            c._call("dial_seeds", {"seeds": "id@1.2.3.4:1"})  # string, not list
        with pytest.raises(RPCClientError, match="not supported"):
            c._call("dial_peers", {"peers": ["a" * 40 + "@1.2.3.4:1"],
                                   "private": True})
    finally:
        live_node.config.rpc.unsafe = False
