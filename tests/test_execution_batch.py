"""ISSUE 17: the batched execution plane (docs/EXECUTION.md).

Batched-vs-serial DeliverTx equivalence (order alignment, results_hash,
app hashes over a full chain), the DeliverTxBatch wire/transport seam
with its structural-probe fallback, the serial-equivalence contract
(fault injection degrades pre-dispatch; real batch errors propagate),
the commit->apply overlap handle with its stale-input discard, the
post-commit worker's FIFO ordering and crash shield, and the plane's
spans/metrics.
"""

from __future__ import annotations

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.state.execution import (
    BlockExecutor,
    PostCommitWorker,
    deliver_block_txs,
)
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote
from tendermint_tpu.utils import faults


class LedgerApp(abci.Application):
    """Appends every delivered tx to a ledger; rejects b'bad*'. The batch
    override rides the base-class serial shim, so `delivered` is the
    per-tx observation sequence either way — any double-apply or
    reordering shows up as a ledger mismatch."""

    def __init__(self):
        self.delivered: list[bytes] = []
        self.batch_calls = 0

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        self.delivered.append(bytes(req.tx))
        if req.tx.startswith(b"bad"):
            return abci.ResponseDeliverTx(code=1, log="rejected")
        return abci.ResponseDeliverTx(code=0, data=bytes(req.tx[::-1]))

    def deliver_tx_batch(self, req: abci.RequestDeliverTxBatch) -> abci.ResponseDeliverTxBatch:
        self.batch_calls += 1
        return super().deliver_tx_batch(req)


class SerialOnlyApp:
    """Duck-typed app WITHOUT deliver_tx_batch (pre-batch stubs)."""

    def __init__(self):
        self.delivered: list[bytes] = []

    def deliver_tx(self, req):
        self.delivered.append(bytes(req.tx))
        return abci.ResponseDeliverTx(code=0, data=bytes(req.tx))


MIX = [b"a-ok", b"bad-1", b"", b"c-ok", b"bad-2", b"d" * 40]


# ---------------------------------------------------------------------------
# deliver_block_txs == the serial loop
# ---------------------------------------------------------------------------


def test_deliver_block_txs_matches_serial():
    batched_app, serial_app = LedgerApp(), LedgerApp()
    batched = deliver_block_txs(batched_app, MIX)
    serial = [serial_app.deliver_tx(abci.RequestDeliverTx(tx=t)) for t in MIX]
    assert batched == serial  # order-aligned, field-identical
    assert batched_app.delivered == serial_app.delivered == MIX
    assert batched_app.batch_calls == 1
    # the deterministic subset feeding LastResultsHash is bit-identical
    assert abci.results_hash(batched) == abci.results_hash(serial)


def test_deliver_block_txs_chunks_at_max_batch(monkeypatch):
    monkeypatch.setenv("TMTPU_DELIVER_MAX_BATCH", "2")
    app = LedgerApp()
    out = deliver_block_txs(app, MIX)
    assert app.batch_calls == 3  # 6 txs / cap 2
    assert [r.code for r in out] == [0, 1, 0, 0, 1, 0]
    assert app.delivered == MIX


def test_deliver_disabled_env_restores_serial(monkeypatch):
    monkeypatch.setenv("TMTPU_DELIVER", "0")
    app = LedgerApp()
    out = deliver_block_txs(app, MIX)
    assert app.batch_calls == 0
    assert [r.code for r in out] == [0, 1, 0, 0, 1, 0]


def test_deliver_block_txs_serial_for_batchless_app():
    app = SerialOnlyApp()
    out = deliver_block_txs(app, [b"x", b"y"])
    assert app.delivered == [b"x", b"y"]
    assert [r.data for r in out] == [b"x", b"y"]


def test_deliver_block_txs_empty_is_empty():
    app = LedgerApp()
    assert deliver_block_txs(app, []) == []
    assert app.batch_calls == 0  # no dispatch, no probe


# ---------------------------------------------------------------------------
# the serial-equivalence contract (docs/EXECUTION.md)
# ---------------------------------------------------------------------------


def test_fault_injection_degrades_chunk_to_serial(monkeypatch):
    """`abci.deliver_batch` fires BEFORE dispatch: the hit chunk runs the
    serial loop — each tx applied exactly once, responses unchanged."""
    monkeypatch.setenv("TMTPU_DELIVER_MAX_BATCH", "2")
    faults.configure(["abci.deliver_batch:raise@2"], seed=7)
    try:
        app = LedgerApp()
        out = deliver_block_txs(app, MIX)
    finally:
        faults.clear()
    assert app.delivered == MIX  # exactly once each, in order
    assert app.batch_calls == 2  # chunk 2 of 3 went serial
    ref = [LedgerApp().deliver_tx(abci.RequestDeliverTx(tx=t)) for t in MIX]
    assert out == ref


def test_fault_injection_every_chunk_still_serial_equivalent():
    faults.configure(["abci.deliver_batch:raise"], seed=7)
    try:
        app = LedgerApp()
        out = deliver_block_txs(app, MIX)
    finally:
        faults.clear()
    assert app.batch_calls == 0
    assert [r.code for r in out] == [0, 1, 0, 0, 1, 0]


def test_app_exception_mid_batch_propagates_not_redone():
    """A genuine app error during a real batch must PROPAGATE with the
    prefix applied — the serial loop's failure shape — never be silently
    redone serially (that would double-apply the prefix)."""

    class BlowsUpAt3(LedgerApp):
        def deliver_tx(self, req):
            if len(self.delivered) == 2:
                raise RuntimeError("app blew up")
            return super().deliver_tx(req)

    app = BlowsUpAt3()
    with pytest.raises(RuntimeError, match="app blew up"):
        deliver_block_txs(app, MIX)
    assert app.delivered == MIX[:2]  # prefix ran once; nothing redone


# ---------------------------------------------------------------------------
# ABCI transport seam: wire codec, socket probe, local client
# ---------------------------------------------------------------------------


def test_wire_codec_deliver_tx_batch_round_trip():
    from tendermint_tpu.abci import wire

    req = abci.RequestDeliverTxBatch(txs=[b"a", b"", b"ccc"])
    kind, back = wire.decode_request(wire.encode_request("deliver_tx_batch", req))
    assert kind == "deliver_tx_batch" and back == req
    # the empty support probe must survive the round trip too
    kind, back = wire.decode_request(
        wire.encode_request("deliver_tx_batch", abci.RequestDeliverTxBatch()))
    assert kind == "deliver_tx_batch" and back == abci.RequestDeliverTxBatch()
    resp = abci.ResponseDeliverTxBatch(responses=[
        abci.ResponseDeliverTx(code=0, data=b"d", gas_used=3),
        abci.ResponseDeliverTx(code=9, log="no", codespace="app"),
    ])
    kind, back = wire.decode_response(wire.encode_response("deliver_tx_batch", resp))
    assert kind == "deliver_tx_batch" and back == resp
    kind, back = wire.decode_response(
        wire.encode_response("deliver_tx_batch", abci.ResponseDeliverTxBatch()))
    assert back == abci.ResponseDeliverTxBatch()


def test_socket_transport_deliver_batch_and_fallback():
    from tendermint_tpu.abci.client import ABCISocketClient
    from tendermint_tpu.abci.server import ABCIServer

    app = LedgerApp()
    server = ABCIServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        cli = ABCISocketClient(server.addr)
        assert cli._batch_delivertx is None  # unprobed
        out = cli.deliver_tx_batch(abci.RequestDeliverTxBatch(
            txs=[b"ok-1", b"bad-x", b"ok-2"]))
        assert cli._batch_delivertx is True
        assert app.batch_calls == 2  # empty probe + the real batch
        assert [r.code for r in out.responses] == [0, 1, 0]
        assert app.delivered == [b"ok-1", b"bad-x", b"ok-2"]
        # pre-batch-server degradation: serial per-tx loop, same responses
        cli._batch_delivertx = False
        out2 = cli.deliver_tx_batch(abci.RequestDeliverTxBatch(
            txs=[b"ok-3", b"bad-y"]))
        assert [r.code for r in out2.responses] == [0, 1]
        assert app.batch_calls == 2  # untouched
        cli.close()
    finally:
        server.stop()


def test_socket_app_exception_does_not_disable_deliver_batching():
    """An app blow-up during a REAL batch is an exception response: it
    must propagate (the prefix executed — exactly the serial failure
    shape) WITHOUT pinning the client to the serial loop, and without
    any serial redo of the failed chunk."""
    from tendermint_tpu.abci.client import ABCISocketClient
    from tendermint_tpu.abci.server import ABCIServer
    from tendermint_tpu.abci.wire import ABCIRemoteError

    class FlakyApp(LedgerApp):
        def __init__(self):
            super().__init__()
            self.fail_once = True

        def deliver_tx_batch(self, req):
            # req.txs guard: the client's empty support probe must not
            # count as the transient failure under test
            if req.txs and self.fail_once:
                self.fail_once = False
                self.delivered.append(bytes(req.txs[0]))  # prefix ran
                raise RuntimeError("transient app failure")
            return super().deliver_tx_batch(req)

    app = FlakyApp()
    server = ABCIServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        cli = ABCISocketClient(server.addr)
        with pytest.raises(ABCIRemoteError, match="transient"):
            cli.deliver_tx_batch(abci.RequestDeliverTxBatch(txs=[b"ok-1"]))
        assert cli._batch_delivertx  # one blip must not cost batching
        assert app.delivered == [b"ok-1"]  # prefix applied ONCE, no redo
        out = cli.deliver_tx_batch(abci.RequestDeliverTxBatch(txs=[b"ok-2"]))
        assert [r.code for r in out.responses] == [0]
        cli.close()
    finally:
        server.stop()


def test_local_client_exposes_deliver_tx_batch():
    from tendermint_tpu.abci.proxy import local_app_conns

    conns = local_app_conns(LedgerApp())
    out = conns.consensus.deliver_tx_batch(abci.RequestDeliverTxBatch(
        txs=[b"ok-l", b"bad-l"]))
    assert [r.code for r in out.responses] == [0, 1]


# ---------------------------------------------------------------------------
# full-chain equivalence through BlockExecutor + the overlap handle
# ---------------------------------------------------------------------------


def _genesis(n_vals=2, chain_id="exec-batch-chain"):
    privs = [ed25519.gen_priv_key(bytes([60 + i]) * 32) for i in range(n_vals)]
    gvals = [GenesisValidator(b"", p.pub_key(), 10) for p in privs]
    gd = GenesisDoc(chain_id=chain_id, validators=gvals,
                    genesis_time=Time(1700000000, 0))
    gd.validate_and_complete()
    return gd, privs


def _commit_for(state, block, privs):
    bid = BlockID(hash=block.hash(),
                  part_set_header=PartSet.from_data(block.marshal()).header())
    sigs = []
    by_addr = {p.pub_key().address(): p for p in privs}
    for val in state.validators.validators:
        priv = by_addr[val.address]
        v = Vote(type=PRECOMMIT_TYPE, height=block.header.height, round=0,
                 block_id=bid, timestamp=block.header.time.add_ns(1_000_000),
                 validator_address=val.address,
                 validator_index=state.validators.get_by_address(val.address)[0])
        v.signature = priv.sign(v.sign_bytes(state.chain_id))
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address, v.timestamp,
                              v.signature))
    return bid, Commit(height=block.header.height, round=0, block_id=bid,
                       signatures=sigs)


def _run_chain(privs, gd, n_blocks=3, speculate=False):
    """Drive n blocks through BlockExecutor + kvstore; returns the
    per-height (app_hash, last_results_hash) trail."""
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    store = StateStore(MemDB())
    store.save(state)
    bx = BlockExecutor(store, app)
    trail = []
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    for h in range(1, n_blocks + 1):
        txs = [b"k%d-%d=v%d" % (h, i, i) for i in range(4 * h)]
        proposer = state.validators.get_proposer()
        block = state.make_block(h, txs, last_commit, [], proposer.address)
        bid, commit = _commit_for(state, block, privs)
        cp = bx.dispatch_commit_verify(state, block) if speculate else None
        state, _ = bx.apply_block(state, bid, block, commit_pending=cp)
        trail.append((state.app_hash, state.last_results_hash))
        last_commit = commit
    return trail


def test_chain_batched_equals_serial_app_hashes(monkeypatch):
    gd, privs = _genesis()
    batched = _run_chain(privs, gd)
    monkeypatch.setenv("TMTPU_DELIVER", "0")
    serial = _run_chain(privs, gd)
    assert batched == serial  # app_hash AND LastResultsHash per height


def test_chain_with_overlap_handle_equals_plain(monkeypatch):
    """dispatch_commit_verify threaded through apply_block resolves to
    the same accept decisions and hashes as the synchronous verify."""
    gd, privs = _genesis()
    plain = _run_chain(privs, gd)
    overlapped = _run_chain(privs, gd, speculate=True)
    assert overlapped == plain


def test_chain_batched_equals_serial_under_fault_injection(monkeypatch):
    gd, privs = _genesis()
    serial_ref = _run_chain(privs, gd)
    faults.configure(["abci.deliver_batch:raise%0.5"], seed=11)
    try:
        injected = _run_chain(privs, gd)
    finally:
        faults.clear()
    assert injected == serial_ref


def test_stale_overlap_handle_is_discarded():
    """A handle whose dispatch-time inputs drifted must NOT be consumed:
    fresh_for returns None and the apply falls back to the sync verify."""
    gd, privs = _genesis()
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    store = StateStore(MemDB())
    store.save(state)
    bx = BlockExecutor(store, app)
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    block1 = state.make_block(1, [b"a=1"], last_commit,
                              [], state.validators.get_proposer().address)
    bid1, commit1 = _commit_for(state, block1, privs)
    assert bx.dispatch_commit_verify(state, block1) is None  # initial height
    state, _ = bx.apply_block(state, bid1, block1)

    block2 = state.make_block(2, [b"b=2"], commit1,
                              [], state.validators.get_proposer().address)
    cp = bx.dispatch_commit_verify(state, block2)
    assert cp is not None
    assert cp.fresh_for(state, block2) is cp.pending
    # height drift and valset drift both kill the handle
    assert cp.fresh_for(state, block1) is None
    stale = type(cp)(pending=cp.pending, height=cp.height,
                     last_block_id=cp.last_block_id, vals_hash=b"\x00" * 32)
    assert stale.fresh_for(state, block2) is None
    # the apply still succeeds with a stale handle (sync fallback)
    bid2, _ = _commit_for(state, block2, privs)
    state, _ = bx.apply_block(state, bid2, block2, commit_pending=stale)
    assert state.last_block_height == 2


# ---------------------------------------------------------------------------
# the post-commit worker
# ---------------------------------------------------------------------------


class _RecordingBus:
    """Event-bus duck type recording publish order across heights."""

    def __init__(self):
        self.events: list[tuple[str, int]] = []

    def publish_event_new_block(self, ev):
        self.events.append(("block", ev.block.header.height))

    def publish_event_new_block_header(self, ev):
        self.events.append(("header", ev.header.height))

    def publish_event_new_evidence(self, ev):
        self.events.append(("evidence", ev.height))

    def publish_event_tx(self, ev):
        self.events.append(("tx", ev.height))

    def publish_event_validator_set_updates(self, ev):
        self.events.append(("valset", -1))


def test_post_commit_events_fifo_across_heights():
    """apply_block returns once state is saved; events still publish in
    height order (h fully before h+1) and flush_post_commit drains."""
    gd, privs = _genesis()
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    store = StateStore(MemDB())
    store.save(state)
    bus = _RecordingBus()
    bx = BlockExecutor(store, app, event_bus=bus)
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    for h in (1, 2, 3):
        block = state.make_block(h, [b"k%d=v" % h, b"j%d=w" % h], last_commit,
                                 [], state.validators.get_proposer().address)
        bid, last_commit = _commit_for(state, block, privs)
        state, _ = bx.apply_block(state, bid, block)
    assert bx.flush_post_commit(timeout_s=10.0)
    heights = [h for _, h in bus.events if h > 0]
    assert heights == sorted(heights)  # h's events strictly before h+1's
    per_height = [h for kind, h in bus.events if kind == "tx"]
    assert per_height == [1, 1, 2, 2, 3, 3]
    bx.stop()


def test_post_commit_worker_crash_shield_and_restart():
    ran = []
    w = PostCommitWorker()
    w.submit(lambda: 1 / 0)  # must not kill the worker
    w.submit(lambda: ran.append("a"))
    assert w.flush(timeout_s=5.0)
    assert ran == ["a"]
    w.stop()
    w.submit(lambda: ran.append("b"))  # restarts after stop
    assert w.flush(timeout_s=5.0)
    assert ran == ["a", "b"]
    w.stop()


def test_flush_without_any_submit_is_immediate():
    assert PostCommitWorker().flush(timeout_s=0.1)


# ---------------------------------------------------------------------------
# headless replay + handshake replay ride the same engine
# ---------------------------------------------------------------------------


def test_replay_ctx_batched_equals_serial_app_hash(monkeypatch):
    from tendermint_tpu.blockchain.pipeline import VerifyAheadPipeline
    from tendermint_tpu.blockchain.replay import ReplayCtx, make_chain
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet

    privs = [ed25519.gen_priv_key(bytes([70 + i]) * 32) for i in range(2)]
    vals = ValidatorSet(validators=[Validator.new(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    privs = [by_addr[v.address] for v in vals.validators]  # signer order
    chain = make_chain("replay-chain", 5, vals, privs,
                       txs_for=lambda h: [b"r%d-%d=v" % (h, i) for i in range(3)])

    def run():
        ctx = ReplayCtx(vals, "replay-chain", app=KVStoreApplication())
        for b in chain:
            ctx.pool.add_block("good", b)
        pipe = VerifyAheadPipeline()
        while pipe.process_next(ctx):
            pass
        return ctx.applied, ctx.app_hash

    batched_applied, batched = run()
    assert batched_applied == [1, 2, 3, 4]  # n-1: last block has no child
    monkeypatch.setenv("TMTPU_DELIVER", "0")
    serial_applied, serial = run()
    assert serial_applied == batched_applied
    assert batched == serial


# ---------------------------------------------------------------------------
# satellites: txs_hash chash route, spans, metrics
# ---------------------------------------------------------------------------


def test_txs_hash_chash_route_matches_reference():
    from tendermint_tpu.crypto import merkle, tmhash
    from tendermint_tpu.types.tx import txs_hash

    txs = [b"tx-%d" % i * (i + 1) for i in range(9)] + [b""]
    ref = merkle.hash_from_byte_slices([tmhash.sum(t) for t in txs])
    assert txs_hash(txs) == ref  # chash route (when up) is bit-identical
    assert txs_hash(txs[:1]) == merkle.hash_from_byte_slices(
        [tmhash.sum(txs[0])])


def test_deliver_spans_are_canonical_and_recorded():
    from tendermint_tpu.utils import trace as tmtrace

    for name in ("abci.deliver_txs", "abci.deliver_batch", "apply.post_commit"):
        assert name in tmtrace.CANONICAL_SPANS
        assert name in tmtrace.MIRRORED_SPANS
    tracer = tmtrace.Tracer(name="deliver-test", enabled=True)
    try:
        with tracer.activate():
            deliver_block_txs(LedgerApp(), MIX)
    finally:
        tracer.disable()
    names = {s.name for s in tracer.dump()}
    assert {"abci.deliver_txs", "abci.deliver_batch"} <= names


def test_deliver_metrics_preseeded_and_counted():
    from tendermint_tpu.utils import metrics as tmmetrics

    nm = tmmetrics.NodeMetrics()
    text = nm.registry.expose()
    assert "tendermint_abci_deliver_batch_size_count 0" in text
    assert "tendermint_abci_deliver_tx_invalid_total 0.0" in text
    prev = tmmetrics.GLOBAL_NODE_METRICS
    tmmetrics.GLOBAL_NODE_METRICS = nm
    try:
        gd, privs = _genesis()
        state = make_genesis_state(gd)
        app = KVStoreApplication()
        store = StateStore(MemDB())
        store.save(state)
        bx = BlockExecutor(store, app)
        last_commit = Commit(height=0, round=0, block_id=BlockID(),
                             signatures=[])
        # two malformed validator txs: rejected by the app (code=1), so the
        # once-dead invalid accumulator now lands on the counter
        block = state.make_block(
            1, [b"ok=1", b"val:not-base64!x", b"val:also-bad"], last_commit,
            [], state.validators.get_proposer().address)
        bid, _ = _commit_for(state, block, privs)
        bx.apply_block(state, bid, block)
        nm2 = nm.registry.expose()
    finally:
        tmmetrics.GLOBAL_NODE_METRICS = prev
    assert "tendermint_abci_deliver_tx_invalid_total 2.0" in nm2
    assert "tendermint_abci_deliver_batch_size_count 0" not in nm2
