"""Remote signer privval: protocol round-trips, double-sign refusal over the
wire, reconnect/retry, and the harness criterion -- a validator committing
blocks while signing over a socket (reference: privval/signer_client.go:16,
signer_listener_endpoint.go, signer_server.go)."""

import os
import time

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.privval.file_pv import FilePV, MockPV
from tendermint_tpu.privval.signer import (
    RemoteSignerError,
    RetrySignerClient,
    SignerClient,
    SignerListenerEndpoint,
    SignerServer,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote

CHAIN_ID = "signer-chain"


def _bid():
    return BlockID(hash=b"\xaa" * 32,
                   part_set_header=PartSetHeader(total=1, hash=b"\xbb" * 32))


def _endpoint_pair(pv):
    ep = SignerListenerEndpoint("tcp://127.0.0.1:0", accept_timeout_s=10.0)
    server = SignerServer(pv, ep.laddr)
    server.start()
    return ep, server


def test_signer_roundtrip_and_double_sign_guard(tmp_path):
    pv = FilePV.generate(str(tmp_path / "key.json"), str(tmp_path / "state.json"),
                         seed=b"\x81" * 32)
    ep, server = _endpoint_pair(pv)
    try:
        client = SignerClient(ep, CHAIN_ID)
        assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()
        assert client.get_address() == pv.get_address()
        assert client.ping()

        vote = Vote(type=PREVOTE_TYPE, height=5, round=0, block_id=_bid(),
                    timestamp=Time(1700000100, 0),
                    validator_address=pv.get_address(), validator_index=0)
        client.sign_vote(CHAIN_ID, vote)
        assert vote.signature
        vote.verify(CHAIN_ID, pv.get_pub_key())  # raises if invalid

        # Same HRS with different block: the FilePV double-sign guard fires
        # remotely and surfaces as RemoteSignerError (never silently signs).
        conflicting = Vote(type=PREVOTE_TYPE, height=5, round=0,
                           block_id=BlockID(hash=b"\xcc" * 32,
                                            part_set_header=PartSetHeader(1, b"\xdd" * 32)),
                           timestamp=Time(1700000101, 0),
                           validator_address=pv.get_address(), validator_index=0)
        with pytest.raises(RemoteSignerError):
            client.sign_vote(CHAIN_ID, conflicting)

        prop = Proposal(height=6, round=0, pol_round=-1, block_id=_bid(),
                        timestamp=Time(1700000102, 0))
        client.sign_proposal(CHAIN_ID, prop)
        assert prop.signature
        sb = prop.sign_bytes(CHAIN_ID)
        assert pv.get_pub_key().verify_signature(sb, prop.signature)
    finally:
        server.stop()
        ep.close()


def test_signer_reconnect_and_retry():
    pv = MockPV(ed25519.gen_priv_key(b"\x82" * 32))
    ep, server = _endpoint_pair(pv)
    try:
        client = RetrySignerClient(SignerClient(ep, CHAIN_ID),
                                   retries=20, interval_s=0.1)
        assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()
        # Drop the connection out from under the client: SignerServer
        # re-dials, RetrySignerClient re-sends.
        ep._drop_connection()
        vote = Vote(type=PRECOMMIT_TYPE, height=9, round=1, block_id=_bid(),
                    timestamp=Time(1700000200, 0),
                    validator_address=pv.get_address(), validator_index=0)
        client.sign_vote(CHAIN_ID, vote)
        vote.verify(CHAIN_ID, pv.get_pub_key())
    finally:
        server.stop()
        ep.close()


def test_consensus_with_remote_signer(tmp_path):
    """The VERDICT criterion: harness passes with the validator signing over
    a socket (reference: node/node.go:753)."""
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    signer_pv = FilePV.generate(str(tmp_path / "signer_key.json"),
                                str(tmp_path / "signer_state.json"),
                                seed=b"\x83" * 32)
    genesis = GenesisDoc(
        chain_id=CHAIN_ID, genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", signer_pv.get_pub_key(), 10)],
    )
    # Operators know the privval address up front: the signer starts FIRST
    # and retries dialing until the node is listening (the node blocks on the
    # signer connection during construction, like the reference).
    import socket as _socket

    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    privval_addr = f"tcp://127.0.0.1:{probe.getsockname()[1]}"
    probe.close()

    cfg = test_config()
    cfg.set_root(str(tmp_path / "node"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.base.priv_validator_laddr = privval_addr
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = ""

    server = SignerServer(signer_pv, privval_addr)
    server.start()
    node = Node(cfg, genesis=genesis, priv_validator=None,
                node_key=NodeKey(ed25519.gen_priv_key(b"\x84" * 32)))
    node.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and node.block_store.height < 3:
            time.sleep(0.1)
        assert node.block_store.height >= 3
        # every commit signature came from the remote key
        commit = node.block_store.load_seen_commit(2)
        assert commit is not None
        commit.get_vote(0).verify(CHAIN_ID, signer_pv.get_pub_key())
    finally:
        node.stop()
        server.stop()


def test_signer_harness_validates_deployment(tmp_path):
    """The operator harness (privval/harness.py; reference
    tools/tm-signer-harness): a well-behaved FilePV-backed remote signer
    passes every check with exit 0; a signer holding a DIFFERENT key than
    priv_validator_key.json exits with the key-mismatch code."""
    import json
    import os
    import shutil
    import threading

    from tendermint_tpu.privval import harness as hn

    home = tmp_path / "home"
    (home / "config").mkdir(parents=True)
    pv = FilePV.generate(str(home / "config" / "priv_validator_key.json"),
                         str(home / "config" / "priv_validator_state.json"),
                         seed=b"\x91" * 32)

    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    logs = []
    laddr = f"tcp://127.0.0.1:{free_port()}"
    server = SignerServer(pv, laddr)
    server.start()
    try:
        code = hn.run_harness(laddr, CHAIN_ID, home=str(home),
                              accept_timeout_s=20.0, log=logs.append)
        assert code == hn.EXIT_OK, logs
        doc = json.loads(hn.summary_json(code))
        assert doc == {"exit_code": 0, "result": "ok"}
    finally:
        server.stop()

    # a signer with the WRONG key: key-mismatch exit code
    wrong = FilePV.generate(str(tmp_path / "other_key.json"),
                            str(tmp_path / "other_state.json"),
                            seed=b"\x92" * 32)
    logs = []
    laddr = f"tcp://127.0.0.1:{free_port()}"
    server = SignerServer(wrong, laddr)
    server.start()
    try:
        code = hn.run_harness(laddr, CHAIN_ID, home=str(home),
                              accept_timeout_s=20.0, log=logs.append)
        assert code == hn.EXIT_KEY_MISMATCH, logs
    finally:
        server.stop()
