"""Lunatic light-client attack end to end: a lying primary is caught by the
light client's witness cross-check, the evidence it ships names the
byzantine validators, a full node's evidence pool re-derives and
cross-checks them, the block executor hands them to ABCI, and the kvstore
app slashes them to zero power (reference: light/detector.go:120-200,
evidence/verify.go:113-160, types/evidence.go:233 GetByzantineValidators,
abci/example/kvstore/persistent_kvstore.go:140-170)."""

import dataclasses

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.light.client import SKIPPING, Client, TrustOptions
from tendermint_tpu.light.detector import ErrConflictingHeaders
from tendermint_tpu.light.provider import MockProvider
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import (
    BLOCK_ID_FLAG_COMMIT,
    PRECOMMIT_TYPE,
    Vote,
)

CHAIN_ID = "attack-chain"


def _commit_for(state, block, privs, signers=None, round_=0):
    bid = BlockID(hash=block.hash(),
                  part_set_header=PartSet.from_data(block.marshal()).header())
    by_addr = {p.pub_key().address(): p for p in privs}
    sigs = []
    vals = signers if signers is not None else state.validators
    for i, val in enumerate(vals.validators):
        priv = by_addr[val.address]
        v = Vote(type=PRECOMMIT_TYPE, height=block.header.height, round=round_,
                 block_id=bid, timestamp=block.header.time.add_ns(1_000_000),
                 validator_address=val.address, validator_index=i)
        v.signature = priv.sign(v.sign_bytes(CHAIN_ID))
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address,
                              v.timestamp, v.signature))
    return bid, Commit(height=block.header.height, round=round_, block_id=bid,
                       signatures=sigs)


def test_lunatic_attack_detector_to_slash():
    # --- 1. the honest full node: real stores, real executed chain --------
    privs = [ed25519.gen_priv_key(bytes([70 + i]) * 32) for i in range(4)]
    gd = GenesisDoc(
        chain_id=CHAIN_ID, genesis_time=Time(1_700_000_000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs])
    gd.validate_and_complete()
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    from tendermint_tpu.abci import types as abci

    app.init_chain(abci.RequestInitChain(
        chain_id=CHAIN_ID,
        validators=[abci.ValidatorUpdate("ed25519", p.pub_key().bytes(), 10)
                    for p in privs]))
    state_store = StateStore(MemDB())
    state_store.save(state)
    block_store = BlockStore(MemDB())
    evpool = EvidencePool(MemDB(), state_store, block_store)
    bx = BlockExecutor(state_store, app, mempool=Mempool(app),
                       evidence_pool=evpool, block_store=block_store)

    # realign privs to the sorted validator order
    by_addr = {p.pub_key().address(): p for p in privs}
    privs = [by_addr[v.address] for v in state.validators.validators]

    commits = {}
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    for h in range(1, 7):
        proposer = state.validators.get_proposer()
        block = bx.create_proposal_block(h, state, last_commit, proposer.address)
        bid, commit = _commit_for(state, block, privs)
        block_store.save_block(block, PartSet.from_data(block.marshal()), commit)
        state, _ = bx.apply_block(state, bid, block)
        commits[h] = commit
        last_commit = commit
    assert state.last_block_height == 6

    # --- 2. light-chain view of the honest chain --------------------------
    honest = {}
    for h in range(1, 7):
        blk = block_store.load_block(h)
        vals = state_store.load_validators(h)
        honest[h] = LightBlock(
            signed_header=SignedHeader(blk.header, commits[h]),
            validator_set=vals)

    # --- 3. the lunatic block: two validators (2/4 power = 1/2 >= 1/3 of
    # the common set) fabricate state at height 5 under a claimed 2-member
    # validator set they fully control ----------------------------------
    attackers = privs[:2]
    claimed = ValidatorSet([Validator.new(p.pub_key(), 10) for p in attackers])
    fake_header = dataclasses.replace(
        honest[5].signed_header.header,
        app_hash=b"\xde\xad" * 16,
        validators_hash=claimed.hash(),
        next_validators_hash=claimed.hash(),
    )

    class _FakeBlock:
        def __init__(self, header):
            self.header = header

        def hash(self):
            return self.header.hash()

        def marshal(self):
            return self.header.marshal()

    _, fake_commit = _commit_for(state, _FakeBlock(fake_header), attackers,
                                 signers=claimed)
    fake_lb = LightBlock(signed_header=SignedHeader(fake_header, fake_commit),
                         validator_set=claimed)
    assert fake_lb.hash() != honest[5].hash()

    # --- 4. light client with a lying primary and an honest witness -------
    lying = dict(honest)
    lying[5] = fake_lb
    primary = MockProvider(CHAIN_ID, lying)
    witness = MockProvider(CHAIN_ID, dict(honest))
    client = Client(
        CHAIN_ID,
        TrustOptions(period_s=3 * 3600.0, height=1, hash=honest[1].hash()),
        primary, [witness], DBStore(MemDB()),
        verification_mode=SKIPPING,
    )
    now = Time(honest[6].signed_header.header.time.seconds + 5, 0)
    try:
        client.verify_light_block_at_height(5, now)
        raise AssertionError("lying primary accepted without conflict")
    except ErrConflictingHeaders:
        pass

    # the honest witness received evidence AGAINST THE PRIMARY naming the
    # two attackers (lunatic extraction from the common set)
    assert witness.evidences, "no evidence reported to the honest provider"
    ev = witness.evidences[-1]
    assert ev.conflicting_block.hash() == fake_lb.hash()
    byz_addrs = {v.address for v in ev.byzantine_validators}
    assert byz_addrs == {p.pub_key().address() for p in attackers}

    # --- 5. the full node's pool verifies it (byzantine set re-derived and
    # cross-checked against what the evidence carries) ---------------------
    evpool.add_evidence(ev)
    assert evpool.is_pending(ev), "evidence did not verify into the pool"

    # --- 6. the evidence is proposed, ABCI sees ByzantineValidators, the
    # kvstore slashes, and the valset drops the attackers two heights on --
    attacker_pubs = {p.pub_key().bytes() for p in attackers}
    assert attacker_pubs <= set(app.validators)
    for h in (7, 8):
        proposer = state.validators.get_proposer()
        block = bx.create_proposal_block(h, state, last_commit, proposer.address)
        if h == 7:
            assert block.evidence, "pending evidence not included in proposal"
        bid, commit = _commit_for(state, block, privs)
        block_store.save_block(block, PartSet.from_data(block.marshal()), commit)
        state, _ = bx.apply_block(state, bid, block)
        last_commit = commit
    # app slashed immediately at height 7's BeginBlock
    assert not (attacker_pubs & set(app.validators)), "attackers not slashed"
    # consensus valset applies the update at H+2 = 9
    next_addrs = {v.address for v in state.next_validators.validators}
    assert not ({p.pub_key().address() for p in attackers} & next_addrs)
    assert evpool.is_committed(ev)
