"""Scenario fabric (tendermint_tpu/e2e/fabric.py, docs/SOAK.md): topology
construction, the per-node thread/fd resource budget, validator churn
(statesync join -> fast-sync catchup -> consensus participation, ABCI
voting-power changes, evidence mid-churn), and the 50-node smoke.

Quick tier: topology/budget units, a 4-node cluster round trip, the churn
scenario, the validator_updates unit, and the bounded 50-node smoke — the
scale path can never silently rot back to 4-node-only coverage.

Every scenario failure prints a TMTPU_* repro line (test_nemesis.repro).
"""

import os
import threading
import time

import pytest

from test_nemesis import _stop_all, _wait, repro  # noqa: F401 (shared harness)

from tendermint_tpu.e2e import fabric
from tendermint_tpu.utils import faults, nemesis

SEED = 2026


@pytest.fixture(autouse=True)
def _clean_chaos():
    faults.configure([], seed=SEED)
    nemesis.clear()
    yield
    nemesis.clear()
    nemesis.PLANE.on_heal.clear()
    faults.clear()


# ---------------------------------------------------------------------------
# Topology units (quick)
# ---------------------------------------------------------------------------


def test_topology_grammar():
    assert len(fabric.topology_edges("full", 6)) == 15
    assert len(fabric.topology_edges("hub-spoke:2", 10)) == 1 + 8 * 2
    edges = fabric.topology_edges("k-regular:4", 20)
    deg = {}
    for a, b in edges:
        deg[a] = deg.get(a, 0) + 1
        deg[b] = deg.get(b, 0) + 1
    assert set(deg.values()) == {4}
    with pytest.raises(ValueError):
        fabric.topology_edges("torus", 9)


def test_k_regular_deterministic_connected():
    e1 = fabric.k_regular_edges(50, 6, seed=0)
    assert e1 == fabric.k_regular_edges(50, 6, seed=0)
    assert e1 != fabric.k_regular_edges(50, 6, seed=1)
    # connected: every node reachable from 0 (the ring guarantees it, but
    # prove it on the generated graph, chords included)
    adj: dict[int, set[int]] = {i: set() for i in range(50)}
    for a, b in e1:
        adj[a].add(b)
        adj[b].add(a)
    seen, queue = {0}, [0]
    while queue:
        u = queue.pop()
        for v in adj[u]:
            if v not in seen:
                seen.add(v)
                queue.append(v)
    assert len(seen) == 50
    # every node within one of the target degree
    assert all(5 <= len(adj[i]) <= 7 for i in range(50))


def test_hub_spoke_shape():
    edges = fabric.hub_spoke_edges(12, 3)
    hubs = {0, 1, 2}
    for a, b in edges:
        assert a in hubs or b in hubs  # no spoke-to-spoke links
    spokes = set(range(3, 12))
    for s in spokes:
        assert sum(1 for a, b in edges if s in (a, b)) == 3


# ---------------------------------------------------------------------------
# Resource budget (quick) — the fabric-level regression tripwire
# ---------------------------------------------------------------------------


def test_budget_formula_arithmetic(tmp_path):
    c = fabric.Cluster(str(tmp_path), 4, topology="full")
    # unstarted cluster: formula-only check against hand arithmetic
    class _FN:
        def __init__(self, links):
            self.links = set(links)

    c.nodes = {0: _FN([1, 2, 3]), 1: _FN([0, 2, 3]),
               2: _FN([0, 1, 3]), 3: _FN([0, 1, 2])}
    per_peer = fabric.PER_PEER_THREADS + fabric.PER_PEER_THREADS_MEMPOOL
    per_node = fabric.NODE_BASE_THREADS + 1 + fabric.NODE_THREADS_INGEST
    assert c.expected_thread_budget() == 4 * per_node + 12 * per_peer
    assert c.expected_fd_budget() == 6 * fabric.FDS_PER_LINK + 4 * fabric.FDS_PER_NODE + 16
    c.mempool_broadcast = False
    assert c.expected_thread_budget() == (
        4 * (fabric.NODE_BASE_THREADS + fabric.NODE_THREADS_INGEST)
        + 12 * fabric.PER_PEER_THREADS)


def test_small_cluster_commits_within_budget(tmp_path):
    """A 3-node full-mesh cluster commits, holds the fork audit, and stays
    inside the declared thread/fd budget — the budget assertion fails HERE,
    at 3 nodes in the quick tier, when a reactor grows a per-peer thread,
    instead of melting a 100-node soak."""
    cluster = fabric.Cluster(str(tmp_path), 3, topology="full")
    cluster.start()
    try:
        with repro("3-node fabric budget"):
            assert _wait(lambda: cluster.min_height() >= 2, 60, 0.1), \
                f"no progress: {cluster.heights()}"
            r = cluster.assert_resource_budget()
            assert r["links"] == 3 and r["threads"] > 0
            # a deliberately impossible budget must fail loudly
            old = fabric.NODE_BASE_THREADS
            try:
                fabric.NODE_BASE_THREADS = -100
                with pytest.raises(AssertionError, match="thread budget"):
                    cluster.assert_resource_budget()
            finally:
                fabric.NODE_BASE_THREADS = old
            assert cluster.audit_agreement() >= 1
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# Churn: join -> catchup -> consensus, power change, evidence (quick)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_churn_statesync_join_power_change_evidence(tmp_path):
    """The churn acceptance scenario: a fresh node statesync-joins a LIVE
    4-validator cluster (snapshot bootstrap through node0's RPC), fast-syncs
    to the tip, is promoted into the validator set via the ABCI
    validator_updates path while an equivocator submits evidence mid-churn,
    and ends up PARTICIPATING in consensus — its signature in a commit —
    with the whole cluster converging on one agreed prefix. Slow tier: the
    ~1 height/s pacing the joiner needs makes this a ~70 s scenario; the
    quick tier carries the mini-soak (join + promote) and the 50-node
    smoke instead."""
    def tweak(cfg, i):
        # pace the chain at ~1 height/s: a joiner bootstrapping + catching
        # up against a test-config-speed chain (~6 heights/s on this host)
        # would chase the tip unboundedly
        cfg.consensus.timeout_commit_s = 0.8
        cfg.consensus.skip_timeout_commit = False

    cluster = fabric.Cluster(str(tmp_path), 4, topology="full",
                             snapshot_interval=2, rpc_node=0, tweak=tweak)
    cluster.start()
    try:
        with repro("statesync churn scenario"):
            # past the trust anchor (h2) and first snapshot (h2/h4)
            assert _wait(lambda: cluster.min_height() >= 5, 90, 0.1), \
                f"no initial progress: {cluster.heights()}"

            joiner = cluster.join_node(statesync=True)
            # evidence mid-churn: node 3 equivocates while the joiner syncs
            cluster.install_misbehavior(3, "double_prevote")

            # statesync bootstrap + fast-sync catchup to the live tip
            assert _wait(
                lambda: cluster.nodes[joiner].height
                >= cluster.max_height() - 2, 120, 0.2), \
                f"joiner never caught up: {cluster.heights()}"
            # the joiner bootstrapped from a snapshot, not from genesis
            base = cluster.nodes[joiner].node.block_store.base
            assert base > 1, f"joiner replayed from genesis (base {base})"

            # voting-power change through state/execution.py: the joiner
            # becomes a validator two heights after the val tx commits
            cluster.promote(joiner, 10)
            assert _wait(lambda: cluster.validator_power(joiner) == 10,
                         90, 0.2), "power change never reached the validator set"

            # the changed validator's votes must VERIFY through the batch
            # path on every node: its signature lands non-absent in a commit
            joiner_addr = cluster.nodes[joiner].priv.pub_key().address()

            def joiner_signed():
                n0 = cluster.nodes[0].node
                for h in range(max(2, n0.block_store.height - 3),
                               n0.block_store.height + 1):
                    commit = n0.block_store.load_block_commit(h)
                    vals = n0.state_store.load_validators(h)
                    if commit is None or vals is None:
                        continue
                    for i, v in enumerate(vals.validators):
                        if (v.address == joiner_addr
                                and i < len(commit.signatures)
                                and not commit.signatures[i].absent()):
                            return True
                return False
            assert _wait(joiner_signed, 120, 0.3), \
                "joined validator never signed a commit"

            # evidence submitted mid-churn commits (and the app slashes)
            def evidence_committed():
                n0 = cluster.nodes[0].node
                for h in range(2, n0.block_store.height + 1):
                    b = n0.block_store.load_block(h)
                    if b is not None and b.evidence:
                        return True
                return False
            assert _wait(evidence_committed, 120, 0.3), \
                "DuplicateVoteEvidence never committed mid-churn"

            # one agreed prefix across the whole churned cluster
            assert cluster.audit_agreement() >= 3
    finally:
        cluster.stop()


def test_remove_node_mid_height_chain_stays_live(tmp_path):
    """Node removal mid-height is O(degree) and non-fatal: the remaining
    supermajority keeps committing and the fork audit still holds."""
    cluster = fabric.Cluster(str(tmp_path), 4, topology="full")
    cluster.start()
    try:
        with repro("mid-height node removal"):
            assert _wait(lambda: cluster.min_height() >= 2, 60, 0.1), \
                f"no initial progress: {cluster.heights()}"
            cluster.remove_node(3)
            assert 3 not in cluster.nodes
            assert all(3 not in fn.links for fn in cluster.nodes.values())
            tip = cluster.max_height()
            assert _wait(lambda: cluster.min_height() >= tip + 2, 60, 0.1), \
                f"chain stalled after removal: {cluster.heights()}"
            cluster.audit_agreement()
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# The 50-node smoke (quick, bounded wall-clock)
# ---------------------------------------------------------------------------


def test_fifty_node_smoke(tmp_path):
    """50 in-process nodes, 50-validator set, hub-spoke topology (diameter
    2 — at n=50 on one core, per-message Python cost times link count is
    the wall, so the 97-link hub-spoke wins over 150-link k-regular),
    continuous auditor attached: >= 5 heights commit cluster-wide with
    zero agreement/liveness violations, inside the thread/fd budget. This
    is ROADMAP item 5's proof shape — the scenario every scale PR reports
    into — bounded for the quick tier: no tx load, no mempool gossip
    threads, one topology. The stall watchdog stays ARMED (a boot-race
    laggard is rescued through the fast-sync hand-back, which is the
    production path for exactly that shape) with a window sized well above
    the observed ~5 s/height steady state."""
    from tendermint_tpu.e2e.soak import ContinuousAuditor

    def tweak(cfg, i):
        # propagation headroom over the 3-node defaults: on one core the
        # proposal + 100 votes serialize through ~2k Python threads
        cfg.consensus.timeout_propose_s = 2.5
        cfg.consensus.timeout_prevote_s = 1.0
        cfg.consensus.timeout_precommit_s = 1.0
        cfg.consensus.peer_gossip_sleep_duration_s = 0.25
        cfg.consensus.watchdog_stall_s = lambda: 30.0

    cluster = fabric.Cluster(str(tmp_path), 50, topology="hub-spoke:2",
                             mempool_broadcast=False, tweak=tweak)
    auditor = None
    try:
        with repro("50-node smoke"):
            t0 = time.monotonic()
            cluster.start()
            boot_s = time.monotonic() - t0
            assert boot_s < 60, f"50-node boot took {boot_s:.0f}s"
            auditor = ContinuousAuditor(cluster, liveness_budget_s=120.0)
            auditor.start()
            assert _wait(lambda: cluster.min_height() >= 5, 300, 0.5), (
                f"50-node cluster below 5 heights after bound "
                f"(boot {boot_s:.0f}s): min {cluster.min_height()} "
                f"max {cluster.max_height()}")
            r = cluster.assert_resource_budget()
            auditor.stop()
            auditor.sweep()
            assert not auditor.violations, (
                f"continuous audit violations: "
                f"{[str(v) for v in auditor.violations[:5]]}")
            assert auditor.heights_audited >= 5
            assert cluster.audit_agreement() >= 5
            # the budget held at scale: record the real numbers in the
            # failure message domain for future tuning
            assert r["threads"] <= r["thread_budget"]
    finally:
        if auditor is not None:
            auditor.stop()
        cluster.stop()


# ---------------------------------------------------------------------------
# e2e runner/generator satellites (quick units — the subprocess e2e tests
# live in the slow tier; these pin the new churn plumbing shapes)
# ---------------------------------------------------------------------------


def test_runner_load_targets_include_post_start_joiners():
    """The load round-robin universe is every registered RPC address, not
    `range(validators)`: a statesync joiner registered after start must
    receive client traffic too (ISSUE 9 satellite — the old
    `attempt % validators` cursor silently starved it)."""
    from tendermint_tpu.e2e.runner import Manifest, Runner

    r = Runner.__new__(Runner)
    r.m = Manifest(validators=3, starting_port=23000)
    r.rpc_addrs = {0: "a", 1: "b", 2: "c"}
    assert r._load_targets() == [0, 1, 2]
    r.rpc_addrs[3] = "d"  # join_statesync_node registers the new slot
    assert r._load_targets() == [0, 1, 2, 3]


def test_generator_samples_churn_dimensions():
    """Generated manifests exercise the churn paths: nemesis partitions,
    validator power changes, and statesync joiners all appear across a
    seeded batch, deterministically, and survive the JSON round trip."""
    import json
    from dataclasses import asdict

    from tendermint_tpu.e2e import generator
    from tendermint_tpu.e2e.runner import Manifest

    ms = generator.generate(5, count=40)
    assert ms == generator.generate(5, count=40)  # deterministic
    assert any(m.power_changes for m in ms)
    assert any(p.action == "partition" and p.groups
               for m in ms for p in m.perturbations)
    assert any(m.statesync_joiner for m in ms)
    for m in ms:
        for p in m.perturbations:
            if p.action == "partition":
                named = {i for g in p.groups for i in g}
                assert named == set(range(m.validators))
        for pc in m.power_changes:
            assert 0 <= pc.node < m.validators
            # never drop a validator from a sub-4 set: quorum would die
            assert pc.power > 0 or m.validators >= 4
    # JSON round trip through Manifest.from_file (the nightly-matrix path)
    doc = json.dumps(asdict(next(m for m in ms if m.power_changes)))
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        f.write(doc)
        path = f.name
    m2 = Manifest.from_file(path)
    assert m2 in ms


# The ABCI validator_updates churn unit (power change propagating through
# state/execution.py into the next-but-one ValidatorSet, with the changed
# validator verifying through the batched vote path) lives in
# tests/test_storage_execution.py next to the BlockExecutor harness it
# reuses: test_validator_power_change_propagates_and_batch_verifies.
