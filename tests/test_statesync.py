"""State sync: snapshot pool / chunk queue units, kvstore snapshot
round-trip, syncer state machine, and the full e2e bootstrap: a fresh node
joins a running chain via snapshot over real sockets, verifies the restored
app hash through the light client, then fast-syncs to the tip."""

import os
import time

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.statesync.chunks import ChunkQueue
from tendermint_tpu.statesync.snapshots import Snapshot, SnapshotPool
from tendermint_tpu.statesync.syncer import (
    ErrRejectSnapshot,
    ErrVerifyFailed,
    Syncer,
)


def test_snapshot_pool_ranking_and_rejection():
    pool = SnapshotPool()
    s1 = Snapshot(height=10, format=1, chunks=2, hash=b"\x01" * 32)
    s2 = Snapshot(height=20, format=1, chunks=2, hash=b"\x02" * 32)
    s3 = Snapshot(height=20, format=2, chunks=2, hash=b"\x03" * 32)
    assert pool.add("a", s1)
    assert pool.add("a", s2)
    assert not pool.add("b", s2)  # known snapshot, new peer
    assert pool.add("b", s3)
    assert pool.best() == s3  # same height, newer format wins
    assert set(pool.peers_of(s2)) == {"a", "b"}

    pool.reject_format(2)
    assert pool.best() == s2
    assert not pool.add("c", s3)  # rejected format never comes back

    pool.reject(s2)
    assert pool.best() == s1
    pool.reject_peer("a")
    assert pool.best() is None  # s1 only known via banned peer


def test_chunk_queue_ordering_and_retry():
    q = ChunkQueue(3)
    assert q.add(1, b"one", "p1")
    assert not q.add(1, b"dup", "p1")
    assert q.add(0, b"zero", "p2")
    got = q.next(1.0)
    assert got == (0, b"zero", "p2")
    assert q.next(1.0) == (1, b"one", "p1")
    # allocate hands out the only missing index
    assert q.allocate(now=0.0, timeout=10.0) == 2
    assert q.allocate(now=1.0, timeout=10.0) is None  # recently requested
    assert q.allocate(now=20.0, timeout=10.0) == 2  # timed out -> re-request
    assert q.add(2, b"two", "p1")
    assert q.next(1.0)[1] == b"two"
    assert q.done()
    # retry reopens an applied index
    q.retry(1)
    assert not q.done()
    assert q.add(1, b"one-again", "p3")
    assert q.next(1.0) == (1, b"one-again", "p3")
    assert q.done()


def _fill_app(app, n_txs, commits):
    txi = 0
    for _ in range(commits):
        app.begin_block(abci.RequestBeginBlock())
        for _ in range(n_txs):
            app.deliver_tx(abci.RequestDeliverTx(tx=b"k%d=v%d" % (txi, txi)))
            txi += 1
        app.end_block(abci.RequestEndBlock())
        app.commit()


def test_kvstore_snapshot_roundtrip():
    src = KVStoreApplication(snapshot_interval=2)
    _fill_app(src, 5, 4)  # heights 1..4, snapshots at 2 and 4
    snaps = src.list_snapshots(abci.RequestListSnapshots()).snapshots
    assert [s.height for s in snaps] == [2, 4]
    snap = snaps[-1]

    dst = KVStoreApplication()
    offer = dst.offer_snapshot(abci.RequestOfferSnapshot(
        snapshot=snap, app_hash=src.app_hash))
    assert offer.result == abci.OFFER_SNAPSHOT_ACCEPT
    for i in range(snap.chunks):
        chunk = src.load_snapshot_chunk(abci.RequestLoadSnapshotChunk(
            height=snap.height, format=snap.format, chunk=i)).chunk
        r = dst.apply_snapshot_chunk(abci.RequestApplySnapshotChunk(
            index=i, chunk=chunk, sender="src"))
        assert r.result == abci.APPLY_CHUNK_ACCEPT
    assert dst.height == snap.height == 4
    assert dst.size == src.size == 20
    assert dst.app_hash == src.app_hash
    q = dst.query(abci.RequestQuery(path="", data=b"k7"))
    assert q.value == b"v7"

    # wrong format is rejected
    bad = abci.Snapshot(height=4, format=9, chunks=1, hash=b"\x00" * 32)
    assert dst.offer_snapshot(abci.RequestOfferSnapshot(snapshot=bad)).result \
        == abci.OFFER_SNAPSHOT_REJECT_FORMAT


class _StubStateProvider:
    def __init__(self, app_hash):
        self._app_hash = app_hash
        self.banned = []

    def app_hash(self, height):
        return self._app_hash

    def state(self, height):
        from tendermint_tpu.state.state import State
        return State(chain_id="stub", last_block_height=height)

    def commit(self, height):
        return f"commit@{height}"


def _wire_syncer(src_app, dst_app, provider, *, corrupt=False):
    syncer = Syncer(dst_app, provider, chunk_request_timeout_s=2.0,
                    chunk_fetchers=2)

    def request_chunk(peer_id, height, fmt, index):
        chunk = src_app.load_snapshot_chunk(abci.RequestLoadSnapshotChunk(
            height=height, format=fmt, chunk=index)).chunk
        if corrupt and index == 0:
            chunk = b"\x00" * len(chunk)
        syncer.add_chunk(index, chunk, peer_id)

    syncer.request_chunk = request_chunk
    return syncer


def test_syncer_restores_and_verifies():
    src = KVStoreApplication(snapshot_interval=3)
    _fill_app(src, 50, 3)  # snapshot at height 3, >1 chunk of data
    snap = src.list_snapshots(abci.RequestListSnapshots()).snapshots[-1]
    dst = KVStoreApplication()
    syncer = _wire_syncer(src, dst, _StubStateProvider(src.app_hash))
    syncer.add_snapshot("peer1", Snapshot(
        height=snap.height, format=snap.format, chunks=snap.chunks,
        hash=snap.hash))
    state, commit = syncer.sync_any(discovery_time_s=0.1, give_up_after_s=30)
    assert state.last_block_height == snap.height
    assert commit == f"commit@{snap.height}"
    assert dst.app_hash == src.app_hash and dst.size == src.size


def test_syncer_rejects_mismatched_app_hash():
    src = KVStoreApplication(snapshot_interval=2)
    _fill_app(src, 5, 2)
    snap = src.list_snapshots(abci.RequestListSnapshots()).snapshots[-1]
    dst = KVStoreApplication()
    syncer = _wire_syncer(src, dst, _StubStateProvider(b"\xde\xad" * 16))
    syncer.add_snapshot("peer1", Snapshot(
        height=snap.height, format=snap.format, chunks=snap.chunks,
        hash=snap.hash))
    with pytest.raises(ErrVerifyFailed):
        syncer.sync(Snapshot(height=snap.height, format=snap.format,
                             chunks=snap.chunks, hash=snap.hash))


def test_syncer_corrupt_chunk_rejected():
    """A tampered chunk fails the app's whole-snapshot hash and the snapshot
    is rejected (RETRY_SNAPSHOT -> ErrRejectSnapshot in sync())."""
    src = KVStoreApplication(snapshot_interval=2)
    _fill_app(src, 5, 2)
    snap = src.list_snapshots(abci.RequestListSnapshots()).snapshots[-1]
    dst = KVStoreApplication()
    syncer = _wire_syncer(src, dst, _StubStateProvider(src.app_hash),
                          corrupt=True)
    s = Snapshot(height=snap.height, format=snap.format, chunks=snap.chunks,
                 hash=snap.hash)
    syncer.add_snapshot("peer1", s)
    with pytest.raises(ErrRejectSnapshot):
        syncer.sync(s)


# --- e2e over real sockets --------------------------------------------------

def _mk_server_node(tmp_path):
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import MockPV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    priv = ed25519.gen_priv_key(b"\x61" * 32)
    genesis = GenesisDoc(
        chain_id="ss-chain", genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", priv.pub_key(), 10)],
    )
    cfg = test_config()
    cfg.set_root(str(tmp_path / "server"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = ""
    node = Node(cfg, app=KVStoreApplication(snapshot_interval=4),
                genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x62" * 32)))
    return node, genesis


def test_e2e_state_sync_bootstrap(tmp_path):
    """Fresh node joins via snapshot: discovers over 0x60, fetches chunks
    over 0x61, light-client-verifies the app hash via the server's RPC, then
    fast-syncs to the tip (reference: statesync/syncer.go:145 + node.go:991)."""
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey

    server, genesis = _mk_server_node(tmp_path)
    server.start()
    try:
        # Feed txs so snapshots have real content; wait past snapshot height 8
        # (progress-based: stalls fail, slow-but-advancing chains don't).
        from tendermint_tpu.e2e.runner import wait_progress

        fed = 0

        def feed():
            nonlocal fed
            if fed < 30:
                server.mempool.check_tx(b"ss%d=val%d" % (fed, fed))
                fed += 1

        wait_progress(lambda: server.block_store.height,
                      lambda h: h >= 10, idle_budget_s=30, hard_cap_s=300,
                      what="server chain reaching height 10", tick=feed,
                      poll_s=0.05)

        trust_meta = server.block_store.load_block_meta(2)
        cfg = test_config()
        cfg.set_root(str(tmp_path / "fresh"))
        os.makedirs(cfg.base.root_dir, exist_ok=True)
        cfg.base.fast_sync_mode = True
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.rpc.laddr = ""
        cfg.consensus.wal_path = ""
        cfg.p2p.persistent_peers = server.p2p_addr()
        cfg.statesync.enable = True
        cfg.statesync.rpc_servers = (
            "http://" + server.rpc_server.laddr.split("://", 1)[1],)
        cfg.statesync.trust_height = 2
        cfg.statesync.trust_hash = trust_meta.block_id.hash.hex()
        cfg.statesync.trust_period_s = 10 * 365 * 24 * 3600.0
        cfg.statesync.discovery_time_s = 0.5

        fresh = Node(cfg, app=KVStoreApplication(),
                     genesis=genesis, priv_validator=None,
                     node_key=NodeKey(ed25519.gen_priv_key(b"\x63" * 32)))
        fresh.start()
        try:
            # State sync must land at a snapshot height (>= 4), then fast
            # sync takes it toward the tip.
            wait_progress(lambda: fresh.state_store.load().last_block_height,
                          lambda h: h >= 4, idle_budget_s=45, hard_cap_s=360,
                          what="state sync reaching a snapshot height",
                          poll_s=0.2)
            synced_state = fresh.state_store.load()
            # The node bootstrapped at a snapshot height: block 1 was never
            # fetched, and the first stored block is snapshot_height+1
            # (fast sync may already be advancing state past the snapshot,
            # so assert on the immutable block-store base, not the state).
            assert fresh.block_store.load_block(1) is None

            # Fast sync catches up past the snapshot height.
            target = synced_state.last_block_height + 2
            wait_progress(lambda: fresh.block_store.height,
                          lambda h: h >= target, idle_budget_s=45,
                          hard_cap_s=360,
                          what="fast sync passing the snapshot", poll_s=0.2)
            base = fresh.block_store.base
            assert base > 1 and base % 4 == 1, base  # snapshot_height + 1
            q = fresh.app.query(abci.RequestQuery(path="", data=b"ss3"))
            assert q.value == b"val3"
        finally:
            fresh.stop()
    finally:
        server.stop()
