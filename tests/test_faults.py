"""Tier-1 smoke + unit tests for the deterministic fault-injection layer
(tendermint_tpu/utils/faults.py) and the device circuit breaker
(tendermint_tpu/ops/breaker.py).

Quick-tier by design (ISSUE satellite: the chaos layer must never silently
rot): one injected WAL torn-write and one injected device failure run on
every `-m 'not slow'` pass. The subprocess crash-recovery matrix and the
real-kernel breaker re-probe live in tests/test_fault_matrix.py (slow)."""

import io
import os
import time

import numpy as np
import pytest

from tendermint_tpu.utils import faults


class SimulatedCrash(Exception):
    """Stands in for os._exit so in-process tests observe the crash."""


@pytest.fixture(autouse=True)
def _clean_faults():
    old_crash = faults.REGISTRY.crash_fn
    yield
    faults.clear()
    faults.REGISTRY.crash_fn = old_crash
    # never leak an open circuit into later tests, even on assert failure
    import sys

    for mod in ("tendermint_tpu.ops.ed25519_batch",
                "tendermint_tpu.ops.sr25519_batch"):
        m = sys.modules.get(mod)
        if m is not None:
            m.BREAKER.reset()


def _raise_sim():
    raise SimulatedCrash()


# ---------------------------------------------------------------------------
# Registry: grammar, triggers, determinism
# ---------------------------------------------------------------------------


def test_rule_grammar():
    r = faults.Rule.parse("wal.write:torn@12")
    assert (r.site, r.action, r.nth, r.times) == ("wal.write", "torn", 12, 1)
    r = faults.Rule.parse("ops.ed25519.device:raise%0.5x2")
    assert (r.prob, r.times) == (0.5, 2)
    r = faults.Rule.parse("p2p.send:delay~0.02")
    assert r.param == 0.02 and r.nth is None and r.prob is None
    for bad in ("", "siteonly", "a.site:frobnicate", "a.site:raise@x"):
        with pytest.raises(ValueError):
            faults.Rule.parse(bad)


def test_nth_trigger_fires_exactly_once():
    faults.configure(["a.site:raise@3"], seed=1)
    fired = []
    for _ in range(6):
        try:
            faults.fire("a.site")
            fired.append(False)
        except faults.FaultInjected:
            fired.append(True)
    assert fired == [False, False, True, False, False, False]


def test_times_widens_nth():
    faults.configure(["a.site:raise@2x2"], seed=1)
    fired = []
    for _ in range(5):
        try:
            faults.fire("a.site")
            fired.append(False)
        except faults.FaultInjected:
            fired.append(True)
    assert fired == [False, True, True, False, False]


def test_prob_decisions_replay_from_seed():
    faults.configure(["b.site:drop%0.4"], seed=42)
    seq1 = [faults.maybe_drop("b.site") for _ in range(100)]
    assert any(seq1) and not all(seq1)
    faults.reset(seed=42)
    assert [faults.maybe_drop("b.site") for _ in range(100)] == seq1
    faults.reset(seed=43)
    assert [faults.maybe_drop("b.site") for _ in range(100)] != seq1


def test_per_site_counters_are_interleaving_independent():
    """The decision for hit k of a site depends only on (seed, site, k):
    interleaving another site's hits between them must not change it."""
    faults.configure(["x.site:drop%0.5", "y.site:drop%0.5"], seed=9)
    seq_x = [faults.maybe_drop("x.site") for _ in range(50)]
    faults.reset()
    inter = []
    for _ in range(50):
        faults.maybe_drop("y.site")
        inter.append(faults.maybe_drop("x.site"))
        faults.maybe_drop("y.site")
    assert inter == seq_x


def test_env_install(monkeypatch):
    monkeypatch.setenv("TMTPU_FAULTS", "c.site:raise@1")
    monkeypatch.setenv("TMTPU_FAULT_SEED", "77")
    faults.install_from_env()
    assert faults.REGISTRY.seed == 77
    with pytest.raises(faults.FaultInjected):
        faults.fire("c.site")
    faults.fire("c.site")  # exhausted


def test_disconnect_action_raises_fault_disconnect():
    faults.configure(["p2p.recv:disconnect@1"], seed=0)
    with pytest.raises(faults.FaultDisconnect):
        faults.maybe_drop("p2p.recv")


def test_env_install_keeps_programmatic_rules(monkeypatch):
    """Node.start() reloads the env config; with NOTHING in the env it must
    not wipe a schedule installed in-process via configure()."""
    monkeypatch.delenv("TMTPU_FAULTS", raising=False)
    faults.configure(["wal.fsync:raise@1"], seed=4)
    faults.install_from_env()
    with pytest.raises(faults.FaultInjected):
        faults.fire("wal.fsync")
    # an explicit env spec wins over the programmatic one
    monkeypatch.setenv("TMTPU_FAULTS", "abci.call:raise@1")
    faults.install_from_env()
    faults.fire("wal.fsync")  # old rule gone
    with pytest.raises(faults.FaultInjected):
        faults.fire("abci.call")


def test_p2p_send_disconnect_tears_down_connection():
    """A p2p.send:disconnect rule must behave like a transport error (peer
    teardown via on_error), never an exception into the sending thread."""
    from tendermint_tpu.p2p.connection import ChannelDescriptor, MConnection

    class _Conn:
        closed = False

        def close(self):
            self.closed = True

    errors = []
    conn = _Conn()
    mc = MConnection(conn, [ChannelDescriptor(id=1)],
                     on_receive=lambda *a: None,
                     on_error=errors.append)
    mc._running = True  # armed without spawning the socket threads
    faults.configure(["p2p.send:disconnect@1"], seed=0)
    assert mc.send(1, b"gossip") is False  # no exception escapes
    assert errors and isinstance(errors[0], faults.FaultDisconnect)
    assert conn.closed and not mc._running


def test_canonical_sites_registered():
    assert set(faults.CANONICAL_SITES) <= set(faults.sites())


def test_mismatched_action_fails_loudly():
    """A rule whose action the site cannot apply (torn at an fsync site,
    drop at a call site) must raise, not silently burn its trigger."""
    faults.configure(["wal.fsync:torn@1", "abci.call:drop@1"], seed=0)
    with pytest.raises(faults.FaultError):
        faults.fire("wal.fsync")
    with pytest.raises(faults.FaultError):
        faults.fire("abci.call")
    faults.configure(["p2p.recv:torn@1"], seed=0)
    with pytest.raises(faults.FaultError):
        faults.maybe_drop("p2p.recv")


def test_legacy_fail_index_counter(monkeypatch):
    faults.REGISTRY.crash_fn = _raise_sim
    monkeypatch.setenv("TMTPU_FAIL_INDEX", "2")
    monkeypatch.setattr(faults, "_legacy_counter", 0)
    faults.fail_point()
    faults.fail_point()
    with pytest.raises(SimulatedCrash):
        faults.fail_point()


# ---------------------------------------------------------------------------
# WAL torn-write smoke (the tier-1 injected WAL fault)
# ---------------------------------------------------------------------------


def _write_until_crash(wal_dir, spec, n=10, seed=11):
    from tendermint_tpu.consensus.wal import WAL, WALMessageBlob

    faults.REGISTRY.crash_fn = _raise_sim
    faults.configure([spec], seed=seed)
    w = WAL(wal_dir)
    n_ok = 0
    try:
        for i in range(n):
            w.write_sync(WALMessageBlob(kind="k", payload=b"p%d" % i), time_ns=i)
            n_ok += 1
    except SimulatedCrash:
        pass
    finally:
        w._head.close()  # simulate process death: no flush of buffers
    return n_ok


@pytest.mark.parametrize("action,expect_ok", [("torn", 4), ("partial", 4)])
def test_wal_torn_write_crash_and_repair(tmp_path, action, expect_ok):
    """A torn/partial frame left by a mid-append crash is truncated by the
    reopen repair; replay yields exactly the valid prefix and appends work."""
    from tendermint_tpu.consensus.wal import WAL, EndHeightMessage

    d = str(tmp_path / action)
    n_ok = _write_until_crash(d, f"wal.write:{action}@5")
    assert n_ok == 4
    # the crash left a damaged tail on disk
    chunk = os.path.join(d, "wal.000000")
    size = os.path.getsize(chunk)
    faults.clear()
    w2 = WAL(d)  # repair runs here
    msgs = [tm.msg for tm, _ in w2.iter_messages()]
    assert len(msgs) == expect_ok
    assert os.path.getsize(chunk) <= size  # torn tail truncated away
    w2.write_sync(EndHeightMessage(3), time_ns=99)
    msgs = [tm.msg for tm, _ in w2.iter_messages()]
    assert len(msgs) == expect_ok + 1 and isinstance(msgs[-1], EndHeightMessage)
    w2.close()


def test_wal_torn_cut_point_replays_from_seed(tmp_path):
    faults.REGISTRY.crash_fn = _raise_sim
    cuts = []
    for run in ("a", "b"):
        d = str(tmp_path / run)
        _write_until_crash(d, "wal.write:torn@3", seed=123)
        cuts.append(os.path.getsize(os.path.join(d, "wal.000000")))
    assert cuts[0] == cuts[1]


# ---------------------------------------------------------------------------
# Device-failure smoke (the tier-1 injected device fault + circuit breaker)
# ---------------------------------------------------------------------------


def _ed_items(n_valid=4, n_bad=1):
    from tendermint_tpu.crypto import ed25519 as ref

    priv = ref.gen_priv_key(b"\x11" * 32)
    pub = priv.pub_key().data
    items = [(pub, b"m%d" % i, ref.sign(priv.data, b"m%d" % i))
             for i in range(n_valid)]
    items += [(pub, b"bad%d" % i, b"\x00" * 64) for i in range(n_bad)]
    return items, [True] * n_valid + [False] * n_bad


def test_device_failure_falls_back_and_recloses(monkeypatch):
    """Injected device-dispatch failure: the batch is re-verified on the
    host within the same dispatch, the circuit opens, and after the
    cooldown the background probe re-closes it; the next batch takes the
    device route again (stubbed here -- the real-kernel twin of this test
    is slow-tier, tests/test_fault_matrix.py)."""
    from tendermint_tpu.ops import ed25519_batch as edb

    monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "0")  # force the device route
    monkeypatch.setenv("TM_TPU_BREAKER_COOLDOWN_S", "0.05")
    items, expect = _ed_items()
    edb.BREAKER.reset()
    faults.configure(["ops.ed25519.device:raise@1"], seed=3)

    # same-dispatch fallback: correct bitmap despite the device failure
    assert edb.verify_batch(items).tolist() == expect
    assert edb.BREAKER.is_open and edb.BREAKER.trips >= 1

    # while open: host fallback keeps verifying (the consensus guarantee)
    assert edb.verify_batch(items).tolist() == expect

    # after cooldown the background probe re-closes the circuit
    monkeypatch.setattr(edb.BREAKER, "probe", lambda: True)
    time.sleep(0.1)
    edb.verify_batch(items)  # allow() kicks the probe
    deadline = time.monotonic() + 10
    while edb.BREAKER.is_open and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not edb.BREAKER.is_open

    # closed again: the device route runs (stub proves the route, no XLA)
    calls = []

    def stub(items_, n, multichip):
        calls.append(n)
        return None, lambda _: np.asarray(expect)

    monkeypatch.setattr(edb, "_dispatch_device", stub)
    assert edb.verify_batch(items).tolist() == expect
    assert calls == [len(items)]
    assert not edb.BREAKER.is_open


def test_sr25519_device_failure_falls_back(monkeypatch):
    from tendermint_tpu.crypto import sr25519 as srref
    from tendermint_tpu.ops import sr25519_batch as srb

    monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "0")
    priv = srref.gen_priv_key(b"\x22" * 32)
    pub = priv.pub_key().data
    items = [(pub, b"sr0", srref.sign(priv.data, b"sr0")),
             (pub, b"bad", b"\x00" * 64)]
    srb.BREAKER.reset()
    faults.configure(["ops.sr25519.device:raise@1"], seed=5)
    assert list(srb.verify_batch(items)) == [True, False]
    assert srb.BREAKER.is_open
    srb.BREAKER.reset()


# ---------------------------------------------------------------------------
# Persistent-peer reconnect backoff
# ---------------------------------------------------------------------------


def test_reconnect_backoff_huge_attempt_does_not_overflow():
    """2.0**1024 overflows a float; a peer down for hours must not kill
    the reconnect thread via OverflowError."""
    from tendermint_tpu.p2p import switch as sw

    for k in (1023, 1024, 10_000_000):
        d = sw.reconnect_backoff_s(k)
        assert sw.RECONNECT_MAX_S <= d <= sw.RECONNECT_MAX_S * (
            1.0 + sw.RECONNECT_JITTER)


def test_reconnect_backoff_schedule():
    import random

    from tendermint_tpu.p2p import switch as sw

    rng = random.Random(7)
    prev_base = 0.0
    for k in range(8):
        base = min(sw.RECONNECT_BASE_S * 2.0 ** k, sw.RECONNECT_MAX_S)
        for _ in range(20):
            d = sw.reconnect_backoff_s(k, rng)
            assert base <= d <= base * (1.0 + sw.RECONNECT_JITTER) + 1e-9
        assert base >= prev_base  # monotone until the cap
        prev_base = base
    assert min(sw.RECONNECT_BASE_S * 2.0 ** 10, sw.RECONNECT_MAX_S) \
        == sw.RECONNECT_MAX_S
