"""Crash-recovery matrix over the NAMED fault sites of the deterministic
chaos layer (tendermint_tpu/utils/faults.py), plus the real-kernel circuit
breaker re-probe.

Each matrix case boots a real single-validator node subprocess
(tests/crash_node.py) with TMTPU_FAULTS pinning one fault at one site (fixed
seed -> fully replayable interleaving), asserts the injected fault actually
killed the process, restarts fault-free, and asserts the recovered node
CONVERGES TO THE FAULT-FREE APP HASH: both runs apply the same fixed tx
universe exactly once (the kvstore app hash is the big-endian applied-tx
count, and crash_node's committed-tx scan + the mempool's committed-tx cache
make re-feeding idempotent), so hash equality is an exact end-state check,
not just internal consistency.

The legacy TMTPU_FAIL_INDEX matrix (tests/test_fastsync_recovery.py) keeps
covering the five finalize sites positionally; this matrix exercises the
named-site layer, the WAL torn/partial-frame writer, and the store-write
crash sites it adds."""

import json
import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu.utils import faults

N_TXS = 5
TARGET_H = 6
FAULT_FREE_APP_HASH = (N_TXS).to_bytes(8, "big").hex()

# Crash-class matrix: every site where a hard crash (or a torn write that
# ends in one) must leave a recoverable tree. @N triggers make each run
# deterministic; the seed fixes torn-frame cut points.
CRASH_MATRIX = [
    "wal.write:torn@12",
    "wal.write:partial@12",
    "wal.fsync:crash@6",
    "store.block.save:crash@3",
    "store.state.save:crash@3",
    "consensus.finalize.save_block:crash@3",
    "consensus.finalize.apply_block:crash@3",
]

# Sites whose failure mode is degradation rather than crash-recovery, with
# the test that owns each (see test_every_site_is_covered).
DEGRADE_SITES = {
    "ops.ed25519.device": "test_faults.py breaker smoke + real-kernel test here",
    "ops.sr25519.device": "test_faults.py sr25519 breaker smoke",
    "ops.ed25519.probe": "probe-owned twin site (keeps device-site hit "
                         "indices deterministic); real-kernel test here",
    "ops.sr25519.probe": "sr25519 probe twin",
    "p2p.send": "faults registry drop determinism (chaos knob for e2e)",
    "p2p.recv": "disconnect action unit test (chaos knob for e2e)",
    "p2p.dial": "reconnect backoff schedule test (chaos knob for e2e)",
    "abci.call": "chaos knob for socket-app runs (in-proc apps bypass it)",
    "mempool.ingest": "batched-CheckTx degradation to the serial loop "
                      "(test_ingest.py + __graft_entry__.ingest_stage)",
    "consensus.finalize.end_height": "legacy TMTPU_FAIL_INDEX matrix "
                                     "(test_fastsync_recovery.py)",
    "consensus.finalize.prune": "legacy TMTPU_FAIL_INDEX matrix",
    "consensus.finalize.done": "legacy TMTPU_FAIL_INDEX matrix",
    # the self-healing storage plane (docs/DURABILITY.md): bit-rot at the
    # record-read sites degrades to quarantine + peer-assisted repair, not
    # crash-recovery — owned by the durability matrix
    "store.block.load": "test_durability.py detect/quarantine/repair matrix "
                        "+ __graft_entry__.durability_stage",
    "store.state.load": "test_durability.py state rebuild-from-blockstore",
    "store.evidence.load": "test_durability.py evidence quarantine-is-repair",
    "store.txindex.load": "test_durability.py reindex-from-stores",
}


def _crash_node(root, mode, env_extra, timeout=240):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for k in ("TMTPU_FAULTS", "TMTPU_FAULT_SEED", "TMTPU_FAIL_INDEX"):
        env.pop(k, None)
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "crash_node.py"),
         root, mode, str(TARGET_H), str(N_TXS)],
        env=env, capture_output=True, timeout=timeout)


def _doc(proc):
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _assert_converged(doc):
    assert doc["app_size"] == N_TXS, doc
    assert doc["app_hash"] == FAULT_FREE_APP_HASH, doc
    assert doc["height"] >= TARGET_H, doc
    assert doc["state_height"] == doc["height"], doc
    assert doc["app_height"] == doc["height"], doc
    assert doc["app_hash"] == doc["state_app_hash"], doc


def test_every_site_is_covered():
    """The matrix enumerates every registered fault site: a new site must be
    consciously added to the crash matrix or the degradation list."""
    covered = {s.split(":")[0] for s in CRASH_MATRIX} | set(DEGRADE_SITES)
    assert covered == set(faults.CANONICAL_SITES), (
        covered ^ set(faults.CANONICAL_SITES))


def test_fault_free_baseline(tmp_path):
    """The fault-free run converges to the analytic app hash (tx count);
    every matrix case below must land on the same hash after recovery."""
    r = _crash_node(str(tmp_path / "clean"), "recover", {})
    assert r.returncode == 0, r.stderr[-2000:]
    _assert_converged(_doc(r))


@pytest.mark.parametrize("spec", CRASH_MATRIX)
def test_named_site_crash_recovery(tmp_path, spec):
    root = str(tmp_path / spec.replace(":", "_").replace("@", "_"))
    crash = _crash_node(root, "crash",
                        {"TMTPU_FAULTS": spec, "TMTPU_FAULT_SEED": "1234"})
    assert crash.returncode == 1, (spec, crash.returncode, crash.stderr[-500:])

    recover = _crash_node(root, "recover", {})
    assert recover.returncode == 0, (spec, recover.stderr[-2000:])
    _assert_converged(_doc(recover))


def test_torn_write_plus_dead_device_acceptance(tmp_path):
    """The ISSUE acceptance scenario: with a fixed fault seed, a WAL
    torn-write plus a persistently failing batch-verifier device during a
    multi-height run. The crash run dies at the torn frame; the recovery
    run keeps the device fault active the whole time -- the node must
    recover to the fault-free app hash with the circuit breaker open,
    committing every height via the host fallback."""
    root = str(tmp_path / "combined")
    # batching on (TM_TPU_DISABLE_BATCH=0 preempts crash_node's setdefault),
    # every batch forced toward the device, breaker cooldown longer than the
    # run so no probe closes the circuit mid-test. The device rule has no
    # trigger suffix: EVERY dispatch fails, so nothing ever compiles XLA.
    knobs = {
        "TM_TPU_DISABLE_BATCH": "0",
        "TM_TPU_SKIP_WARMUP": "1",
        "TM_TPU_BATCH_MIN": "1",
        "TM_TPU_HOST_CROSSOVER": "0",
        "TM_TPU_BREAKER_COOLDOWN_S": "300",
        "TMTPU_FAULT_SEED": "1234",
    }
    crash = _crash_node(root, "crash", {
        **knobs, "TMTPU_FAULTS": "wal.write:torn@12,ops.ed25519.device:raise"})
    assert crash.returncode == 1, (crash.returncode, crash.stderr[-500:])

    recover = _crash_node(root, "recover", {
        **knobs, "TMTPU_FAULTS": "ops.ed25519.device:raise"})
    assert recover.returncode == 0, recover.stderr[-2000:]
    doc = _doc(recover)
    _assert_converged(doc)
    # the accelerator was dead the whole run: the breaker tripped and every
    # verified commit went through the host fallback
    assert doc.get("breaker_trips", 0) >= 1, doc
    assert doc.get("breaker_open") is True, doc


def test_device_breaker_recloses_with_real_kernel(monkeypatch):
    """Slow-tier twin of the quick breaker smoke: the background probe runs
    the REAL device route (jnp kernel on the CPU mesh) and re-closes the
    circuit; the next batch verifies on the device again."""
    from tendermint_tpu.crypto import ed25519 as ref
    from tendermint_tpu.ops import ed25519_batch as edb

    monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "0")
    monkeypatch.setenv("TM_TPU_BREAKER_COOLDOWN_S", "0.2")
    priv = ref.gen_priv_key(b"\x33" * 32)
    pub = priv.pub_key().data
    items = [(pub, b"k%d" % i, ref.sign(priv.data, b"k%d" % i))
             for i in range(8)]
    items.append((pub, b"forged", b"\x01" * 64))
    expect = [True] * 8 + [False]

    edb.BREAKER.reset()
    faults.configure(["ops.ed25519.device:raise@1"], seed=99)
    try:
        assert edb.verify_batch(items).tolist() == expect  # host fallback
        assert edb.BREAKER.is_open
        # wait for the real probe (compiles the kernel once) to re-close
        deadline = time.monotonic() + 600
        while edb.BREAKER.is_open and time.monotonic() < deadline:
            edb.verify_batch(items[:1])  # keeps kicking allow()
            time.sleep(0.25)
        assert not edb.BREAKER.is_open, "probe never re-closed the circuit"
        # device route live again, accept/reject still byte-identical
        assert edb.verify_batch(items).tolist() == expect
        assert not edb.BREAKER.is_open and edb.BREAKER.trips == 1
    finally:
        faults.clear()
        edb.BREAKER.reset()
