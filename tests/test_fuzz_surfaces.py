"""Fuzz the externally reachable surfaces of a live node — the analogue of
the reference's go-fuzz targets (test/fuzz/rpc/jsonrpc, test/fuzz/mempool):
whatever bytes arrive, the server answers (or drops the request) and the
node keeps committing."""

import json
import os
import random
import socket
import time
import urllib.error
import urllib.request

from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time


def _mk_node(tmp_path):
    priv = ed25519.gen_priv_key(b"\x61" * 32)
    genesis = GenesisDoc(
        chain_id="fuzz-chain", genesis_time=Time(1700006000, 0),
        validators=[GenesisValidator(b"", priv.pub_key(), 10)],
    )
    cfg = make_test_config()
    cfg.set_root(str(tmp_path / "node"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = ""
    return Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x62" * 32)))


def _post(base, body: bytes, timeout=5):
    try:
        req = urllib.request.Request(
            base, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except (urllib.error.URLError, ConnectionError, socket.timeout) as e:
        raise AssertionError(f"rpc server died on fuzz input: {e}") from e


def test_jsonrpc_server_survives_malformed_input(tmp_path):
    node = _mk_node(tmp_path)
    node.start()
    base = "http://" + node.rpc_server.laddr.split("://", 1)[1]
    try:
        rng = random.Random(0xF022)
        cases = [
            b"",                                # empty body
            b"{",                               # truncated JSON
            b"[]",                              # batch-ish
            b"null",
            b'{"jsonrpc":"2.0"}',               # no method
            b'{"method":5,"id":{}}',            # wrong types
            b'{"method":{},"params":7,"id":[1]}',  # unhashable method
            b'{"method":["x"],"params":null}',
            b"[null,5]",                        # batch of non-objects
            b'{"jsonrpc":"2.0","id":1,"method":"status","params":"notadict"}',
            b'{"jsonrpc":"2.0","id":1,"method":"block","params":{"height":"NaN"}}',
            b'{"jsonrpc":"2.0","id":1,"method":"block","params":{"bogus_param":1}}',
            b'{"jsonrpc":"2.0","id":1,"method":"no_such_method","params":{}}',
            b'{"jsonrpc":"2.0","id":1,"method":"tx","params":{"hash":"!!!"}}',
            b'{"jsonrpc":"2.0","id":' + b"9" * 400 + b',"method":"status"}',
            json.dumps({"jsonrpc": "2.0", "id": 1, "method": "status",
                        "params": {"x": [[[[[["deep"]]]]]]}}).encode(),
        ]
        cases += [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 300)))
                  for _ in range(40)]
        for body in cases:
            status, _ = _post(base, body)
            assert status in (200, 400, 404, 500), (status, body[:40])
        # URI GET with junk query strings must not kill the server either
        for q in ("/status?x=%zz", "/block?height=--", "/abci_query?data='",
                  "/" + "a" * 500, "/tx?hash=%00%00"):
            try:
                with urllib.request.urlopen(base + q, timeout=5) as r:
                    r.read()
            except urllib.error.HTTPError:
                pass
        # empty batch: single Invalid Request object, not an array
        # (JSON-RPC 2.0 §6)
        status, raw = _post(base, b"[]")
        doc = json.loads(raw)
        assert isinstance(doc, dict) and doc["error"]["code"] == -32600

        # hostile Content-Length headers must get a 400, not a dead thread
        host, port = node.rpc_server.laddr.split("://", 1)[1].rsplit(":", 1)
        for cl in ("abc", "-5"):
            s = socket.create_connection((host, int(port)), timeout=5)
            s.sendall((f"POST / HTTP/1.1\r\nHost: {host}\r\n"
                       f"Content-Length: {cl}\r\n\r\n").encode())
            resp = s.recv(1024)
            assert b"400" in resp.split(b"\r\n", 1)[0], (cl, resp[:60])
            s.close()

        # still alive and correct
        body = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "status", "params": {}}).encode()
        status, raw = _post(base, body)
        assert status == 200
        assert json.loads(raw)["result"]["node_info"]["network"] == "fuzz-chain"
    finally:
        node.stop()


def test_mempool_survives_fuzz_txs(tmp_path):
    """Random CheckTx payloads (empty, huge, binary) must never raise out
    of the mempool, oversized txs are rejected, and consensus keeps
    committing under the load (reference: test/fuzz/mempool)."""
    from tendermint_tpu.mempool import mempool as mp

    # The documented rejection surface: typed errors, exactly like the
    # reference's mempool/errors.go (the RPC boundary maps them to non-zero
    # codes). Anything OUTSIDE this set escaping check_tx is a fuzz failure.
    typed = tuple(e for e in (
        getattr(mp, "ErrTxTooLarge", None), getattr(mp, "ErrTxInCache", None),
        getattr(mp, "ErrMempoolIsFull", None), getattr(mp, "ErrPreCheck", None),
    ) if e is not None)

    node = _mk_node(tmp_path)
    node.start()
    try:
        rng = random.Random(0xF00D)
        max_bytes = node.config.mempool.max_tx_bytes
        accepted = 0
        for i in range(120):
            size = rng.choice([0, 1, 7, 100, 1000, max_bytes, max_bytes + 1,
                               max_bytes * 2])
            tx = bytes(rng.randrange(256) for _ in range(size))
            try:
                res = node.mempool.check_tx(tx)
            except typed as e:
                if size > max_bytes:
                    assert isinstance(e, mp.ErrTxTooLarge)
                continue
            assert size <= max_bytes, "oversized tx accepted"
            if res.code == 0:
                accepted += 1
        assert accepted > 0
        assert node.mempool.size_bytes() <= node.config.mempool.max_txs_bytes
        h = node.block_store.height
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and node.block_store.height < h + 2:
            time.sleep(0.1)
        assert node.block_store.height >= h + 2, "consensus stalled under fuzz load"
    finally:
        node.stop()
