"""ISSUE 12: the micro-batched tx ingestion front door (docs/INGEST.md).

Batch-vs-serial admission equivalence (verdicts, mempool contents, v1
priority order, recheck survivors, app state), the ingest coalescer, the
batched gossip receive with its preserved scoring table, the drain-all
gossip send, the ABCI CheckTxBatch wire/transport seam with its
pre-batch-server fallback, fault-injection degradation, and the overload
composition (a flood through the batched front door still sheds at the
gate and bans the flooder).
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.mempool.mempool import (
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
    Mempool,
    MempoolError,
)
from tendermint_tpu.utils import peerscore


class PricedApp(abci.Application):
    """Prices txs by their last byte; rejects b'bad*'; records every
    CheckTx it observes (batch calls ride the base-class loop shim, so
    `checked` is the per-tx observation multiset either way)."""

    def __init__(self, reject_prefix: bytes = b"bad"):
        self.reject_prefix = reject_prefix
        self.checked: list[bytes] = []
        self.batch_calls = 0

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        self.checked.append(bytes(req.tx))
        if req.tx.startswith(self.reject_prefix):
            return abci.ResponseCheckTx(code=1, log="rejected")
        return abci.ResponseCheckTx(code=0, priority=req.tx[-1] if req.tx else 0,
                                    gas_wanted=1)

    def check_tx_batch(self, req: abci.RequestCheckTxBatch) -> abci.ResponseCheckTxBatch:
        self.batch_calls += 1
        return super().check_tx_batch(req)


def _verdict(o) -> str:
    if isinstance(o, Exception):
        return type(o).__name__
    return "ok" if o.is_ok() else f"reject:{o.code}"


def _seeded_universe(n: int, seed: int = 42) -> list[bytes]:
    rng = random.Random(seed)
    universe: list[bytes] = []
    for i in range(n):
        r = rng.random()
        if r < 0.15:
            universe.append(b"bad-%d-" % i + bytes([rng.randrange(1, 256)]))
        elif r < 0.25:
            universe.append(b"L" * 300)  # oversize for max_tx_bytes=256
        elif r < 0.38 and universe:
            universe.append(universe[rng.randrange(len(universe))])
        else:
            universe.append(b"kv-%d=" % i + bytes([rng.randrange(1, 256)]))
    return universe


def _serial_outcomes(mp: Mempool, txs, senders=None) -> list:
    out = []
    for i, tx in enumerate(txs):
        try:
            out.append(mp.check_tx(tx, senders[i] if senders else ""))
        except Exception as e:  # noqa: BLE001 - the outcome under test
            out.append(e)
    return out


# ---------------------------------------------------------------------------
# check_tx_batch == the serial loop
# ---------------------------------------------------------------------------


def test_batch_matches_serial_on_seeded_universe():
    universe = _seeded_universe(90)
    senders = ["p%d" % (i % 3) for i in range(len(universe))]
    a1, a2 = PricedApp(), PricedApp()
    m1 = Mempool(a1, version="v1", max_tx_bytes=256)
    m2 = Mempool(a2, version="v1", max_tx_bytes=256)
    o1 = _serial_outcomes(m1, universe, senders)
    o2 = m2.check_tx_batch(list(universe), list(senders))
    assert [_verdict(x) for x in o1] == [_verdict(x) for x in o2]
    assert [t.tx for t in m1.iter_txs()] == [t.tx for t in m2.iter_txs()]
    assert m1.reap_max_txs(-1) == m2.reap_max_txs(-1)  # priority order
    assert m1.reap_max_bytes_max_gas(10_000, -1) == \
        m2.reap_max_bytes_max_gas(10_000, -1)
    assert sorted(a1.checked) == sorted(a2.checked)  # app state
    # sender attribution landed on the admitted entries
    for t1, t2 in zip(m1.iter_txs(), m2.iter_txs()):
        assert t1.senders == t2.senders


def test_batch_matches_serial_v0_reject_when_full():
    a1, a2 = PricedApp(), PricedApp()
    m1 = Mempool(a1, version="v0", max_txs=3)
    m2 = Mempool(a2, version="v0", max_txs=3)
    txs = [b"f%d=" % i + bytes([i + 1]) for i in range(8)]
    o1 = _serial_outcomes(m1, txs)
    o2 = m2.check_tx_batch(list(txs))
    assert [_verdict(x) for x in o1] == [_verdict(x) for x in o2]
    assert [_verdict(x) for x in o2][3:] == ["ErrMempoolIsFull"] * 5
    assert [t.tx for t in m1.iter_txs()] == [t.tx for t in m2.iter_txs()]
    # full-rejected txs left the cache on both paths: a later retry works
    m1.update(1, txs[:3])
    m2.update(1, txs[:3])
    assert m1.check_tx(txs[5]).is_ok()
    assert not isinstance(m2.check_tx_batch([txs[5]])[0], Exception)


def test_batch_matches_serial_v1_priority_eviction():
    a1, a2 = PricedApp(), PricedApp()
    m1 = Mempool(a1, version="v1", max_txs=3)
    m2 = Mempool(a2, version="v1", max_txs=3)
    txs = [b"e%d=" % i + bytes([p])
           for i, p in enumerate([5, 3, 9, 1, 200, 2, 250])]
    o1 = _serial_outcomes(m1, txs)
    o2 = m2.check_tx_batch(list(txs))
    assert [_verdict(x) for x in o1] == [_verdict(x) for x in o2]
    assert m1.reap_max_txs(-1) == m2.reap_max_txs(-1)
    # the high-priority latecomers evicted the low-priority residents
    assert m2.reap_max_txs(-1)[0][-1] == 250


def test_duplicate_of_invalid_tx_within_one_batch():
    """Serial: an app-rejected tx is dropped from the cache, so its later
    duplicate reaches the app AGAIN. The batch pre-filter marks the dup as
    cache-expected; the replay detects the un-cached earlier copy and
    falls back to a serial app call at the dup's exact serial position."""
    a1, a2 = PricedApp(), PricedApp()
    m1 = Mempool(a1, version="v1")
    m2 = Mempool(a2, version="v1")
    txs = [b"bad-dup\x05", b"ok-1\x07", b"bad-dup\x05", b"ok-1\x07"]
    o1 = _serial_outcomes(m1, txs)
    o2 = m2.check_tx_batch(list(txs))
    assert [_verdict(x) for x in o1] == [_verdict(x) for x in o2] == \
        ["reject:1", "ok", "reject:1", "ErrTxInCache"]
    assert sorted(a1.checked) == sorted(a2.checked)
    assert a1.checked.count(b"bad-dup\x05") == 2  # app saw the dup twice


def test_batch_app_exception_is_the_per_tx_outcome():
    class Boom(PricedApp):
        def check_tx(self, req):
            if req.tx.startswith(b"boom"):
                raise RuntimeError("app crashed")
            return super().check_tx(req)

    m1 = Mempool(Boom(), version="v1")
    m2 = Mempool(Boom(), version="v1")
    txs = [b"ok-a\x01", b"boom-b\x02", b"ok-c\x03"]
    o1 = _serial_outcomes(m1, txs)
    o2 = m2.check_tx_batch(list(txs))
    assert [_verdict(x) for x in o1] == [_verdict(x) for x in o2] == \
        ["ok", "RuntimeError", "ok"]
    assert [t.tx for t in m1.iter_txs()] == [t.tx for t in m2.iter_txs()]


def test_batch_post_check_filter_applies_identically():
    def post(tx, res):
        if res.gas_wanted > 0 and tx.startswith(b"gassy"):
            raise MempoolError("post-check: too much gas")

    a1, a2 = PricedApp(), PricedApp()
    m1 = Mempool(a1, version="v1")
    m2 = Mempool(a2, version="v1")
    m1.post_check = post
    m2.post_check = post
    txs = [b"ok-a\x01", b"gassy-b\x02", b"ok-c\x03"]
    o1 = _serial_outcomes(m1, txs)
    o2 = m2.check_tx_batch(list(txs))
    assert [_verdict(x) for x in o1] == [_verdict(x) for x in o2] == \
        ["ok", "MempoolError", "ok"]
    assert [t.tx for t in m1.iter_txs()] == [t.tx for t in m2.iter_txs()]


def test_recheck_rides_batched_path_with_identical_survivors():
    class FlipApp(PricedApp):
        """Rejects b'flip*' only on RECHECK — the committed block
        invalidated them (the reference's recheck eviction shape)."""

        def check_tx(self, req):
            self.checked.append(bytes(req.tx))
            if (req.type == abci.CHECK_TX_TYPE_RECHECK
                    and req.tx.startswith(b"flip")):
                return abci.ResponseCheckTx(code=2, log="stale")
            return abci.ResponseCheckTx(code=0, priority=1)

    a1, a2 = FlipApp(), FlipApp()
    m1 = Mempool(a1, version="v0")
    m2 = Mempool(a2, version="v0")
    txs = [b"keep-1", b"flip-2", b"keep-3", b"flip-4", b"keep-5"]
    for tx in txs:
        m1.check_tx(tx)
    assert not any(isinstance(o, Exception)
                   for o in m2.check_tx_batch(list(txs)))
    with m1._mtx:
        m1.update(1, [])  # no committed txs: pure recheck
    before_batches = a2.batch_calls
    with m2._mtx:
        m2.update(1, [])
    assert a2.batch_calls == before_batches + 1  # ONE batched recheck
    assert [t.tx for t in m1.iter_txs()] == [t.tx for t in m2.iter_txs()] \
        == [b"keep-1", b"keep-3", b"keep-5"]


def test_batch_dispatch_fault_degrades_to_serial(monkeypatch):
    from tendermint_tpu.utils import faults

    app = PricedApp()
    mp = Mempool(app, version="v1")
    faults.configure(["mempool.ingest:raise"], seed=3)
    try:
        out = mp.check_tx_batch([b"ok-a\x01", b"bad-b\x02", b"ok-c\x03"])
    finally:
        faults.clear()
    assert [_verdict(x) for x in out] == ["ok", "reject:1", "ok"]
    assert app.batch_calls == 0  # the batched dispatch never succeeded
    assert len(app.checked) == 3  # the serial degradation did the work


# ---------------------------------------------------------------------------
# The ingest coalescer
# ---------------------------------------------------------------------------


def test_coalescer_shares_batches_across_concurrent_submitters(monkeypatch):
    monkeypatch.setenv("TMTPU_INGEST_WINDOW_US", "100000")
    app = PricedApp()
    mp = Mempool(app, version="v1")
    results: dict[int, object] = {}
    barrier = threading.Barrier(12)

    def submit(i):
        try:
            barrier.wait()
            results[i] = mp.ingest_tx(b"conc-%d=" % i + bytes([i + 1]))
        except Exception as e:  # noqa: BLE001 - asserted below
            results[i] = e

    threads = [threading.Thread(target=submit, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert all(not isinstance(r, Exception) and r.is_ok()
               for r in results.values())
    assert mp.size() == 12
    co = mp._ingest
    assert co.requests == 12
    assert co.max_coalesced >= 2, "no coalescing observed"
    assert app.batch_calls == co.batches < 12


def test_ingest_disabled_restores_serial_path(monkeypatch):
    monkeypatch.setenv("TMTPU_INGEST", "0")
    app = PricedApp()
    mp = Mempool(app, version="v1")
    res = mp.ingest_tx(b"serial-1\x05")
    assert res.is_ok() and mp.size() == 1
    with pytest.raises(ErrTxInCache):
        mp.ingest_tx(b"serial-1\x05")
    outcomes = mp.ingest_txs([b"serial-2\x06", b"serial-1\x05"])
    assert _verdict(outcomes[0]) == "ok"
    assert isinstance(outcomes[1], ErrTxInCache)
    assert app.batch_calls == 0  # never touched the batch seam
    assert mp._ingest.requests == 0  # nor the coalescer


def test_ingest_tx_raises_exactly_like_check_tx():
    mp = Mempool(PricedApp(), version="v1", max_tx_bytes=16)
    with pytest.raises(ErrTxTooLarge):
        mp.ingest_tx(b"x" * 64)
    assert mp.ingest_tx(b"ok\x05").is_ok()
    with pytest.raises(ErrTxInCache):
        mp.ingest_tx(b"ok\x05")


def test_coalescer_executor_survives_mempool_blowup(monkeypatch):
    mp = Mempool(PricedApp(), version="v1")

    calls = {"n": 0}
    real = mp.check_tx_batch

    def flaky(txs, senders=None, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient ingest blow-up")
        return real(txs, senders, **kw)

    mp.check_tx_batch = flaky
    with pytest.raises(RuntimeError, match="transient"):
        mp.ingest_tx(b"doomed\x01")
    # the executor shielded the crash: the next submission still works
    assert mp.ingest_tx(b"fine\x02").is_ok()


def test_coalescer_stop_releases_thread_and_restarts_on_submit():
    mp = Mempool(PricedApp(), version="v1")
    assert mp.ingest_tx(b"pre-stop\x05").is_ok()
    co = mp._ingest
    th = co._thread
    assert th is not None and th.is_alive()
    co.stop()
    th.join(5)
    assert not th.is_alive()  # the node-teardown path: no parked leak
    # a later submission simply restarts the executor
    assert mp.ingest_tx(b"post-stop\x06").is_ok()
    assert co._thread is not th and co._thread.is_alive()


def test_submit_immediately_after_stop_cannot_strand_a_waiter():
    """The stop()/submit() race: a submission racing node teardown must
    land in a FRESH executor generation, never behind the old queue's
    shutdown sentinel (where it would hang its RPC handler forever)."""
    mp = Mempool(PricedApp(), version="v1")
    assert mp.ingest_tx(b"warm\x05").is_ok()
    co = mp._ingest
    for i in range(5):
        co.stop()  # no submit between stop and the next ingest_tx:
        # the very next submission must still resolve promptly
        assert mp.ingest_tx(b"race-%d\x06" % i).is_ok()
    co.stop()
    co.stop()  # idempotent: double-stop must not wedge a later restart
    assert mp.ingest_tx(b"after-double-stop\x07").is_ok()


def test_batched_app_check_chunks_under_byte_cap():
    """A batch whose payload exceeds BATCH_MAX_BYTES must split into
    several RequestCheckTxBatch round trips (never one wire-cap-busting
    message), with responses still order-aligned."""

    class SizedApp(PricedApp):
        def __init__(self):
            super().__init__()
            self.batch_sizes = []

        def check_tx_batch(self, req):
            self.batch_sizes.append(sum(len(t) for t in req.txs))
            return super().check_tx_batch(req)

    app = SizedApp()
    mp = Mempool(app, version="v1", max_tx_bytes=1 << 20,
                 max_txs_bytes=1 << 30)
    mp.BATCH_MAX_BYTES = 4096  # instance override: keep the test tiny
    txs = [b"C" * 1500 + b"-%d\x05" % i for i in range(8)]
    out = mp.check_tx_batch(list(txs))
    assert all(not isinstance(o, Exception) and o.is_ok() for o in out)
    assert len(app.batch_sizes) > 1  # it chunked
    assert all(s <= 4096 for s in app.batch_sizes)
    assert mp.size() == 8


# ---------------------------------------------------------------------------
# Gossip receive: batched admission, serial scoring table
# ---------------------------------------------------------------------------


class _FakeSwitchWithBoard:
    def __init__(self):
        self.scoreboard = peerscore.PeerScoreBoard()


class _FakePeer:
    def __init__(self, pid):
        self.id = pid
        self.sent = []

    def try_send(self, ch_id, msg):
        self.sent.append((ch_id, msg))
        return True


def _mixed_gossip_universe():
    """One multi-tx message exercising every scoring row: oversize,
    app-reject, in-cache re-delivery (never scored), and admits."""
    return [b"ok-1\x05", b"x" * 100, b"bad-2\x01", b"ok-1\x05", b"ok-3\x07"]


def _offenses(mp_factory, monkeypatch, ingest_on):
    from tendermint_tpu.mempool.reactor import MempoolReactor, msg_txs

    if ingest_on:
        monkeypatch.delenv("TMTPU_INGEST", raising=False)
    else:
        monkeypatch.setenv("TMTPU_INGEST", "0")
    mp = mp_factory()
    r = MempoolReactor(mp, broadcast=False)
    r.switch = _FakeSwitchWithBoard()
    peer = _FakePeer("gossiper01")
    r.receive(0x30, peer, msg_txs(_mixed_gossip_universe()))
    # and a second delivery: everything now in-cache -> no new offenses
    r.receive(0x30, peer, msg_txs([b"ok-1\x05", b"ok-3\x07"]))
    return dict(r.switch.scoreboard.describe()["offenses"]), mp


def test_gossip_receive_batched_scoring_equals_serial(monkeypatch):
    def factory():
        return Mempool(PricedApp(), version="v1", max_tx_bytes=64)

    off_batched, mp_b = _offenses(factory, monkeypatch, ingest_on=True)
    off_serial, mp_s = _offenses(factory, monkeypatch, ingest_on=False)
    assert off_batched == off_serial
    assert off_batched["gossiper01:tx_too_large"] == 1
    assert off_batched["gossiper01:checktx_reject"] == 1
    assert "gossiper01:mempool_full" not in off_batched
    assert [t.tx for t in mp_b.iter_txs()] == [t.tx for t in mp_s.iter_txs()]
    # ErrTxInCache was never scored, but the sender was recorded for
    # gossip suppression on both paths
    for m in mp_b.iter_txs():
        assert "gossiper01" in m.senders


def test_gossip_receive_full_pool_scores_mempool_full_batched(monkeypatch):
    from tendermint_tpu.mempool.reactor import MempoolReactor, msg_txs

    monkeypatch.delenv("TMTPU_INGEST", raising=False)
    mp = Mempool(PricedApp(), version="v0", max_txs=1, max_tx_bytes=64)
    r = MempoolReactor(mp, broadcast=False)
    r.switch = _FakeSwitchWithBoard()
    peer = _FakePeer("flooder01")
    r.receive(0x30, peer, msg_txs([b"tx-one\x05"]))
    assert mp.size() == 1
    # a flood of fresh txs into the full pool, all in ONE message
    r.receive(0x30, peer, msg_txs([b"tx-flood-%d\x01" % i for i in range(30)]))
    board = r.switch.scoreboard
    assert board.describe()["offenses"]["flooder01:mempool_full"] == 30
    # app blow-up mid-batch: swallowed, unscored, recv thread alive
    mp.flush()
    before = board.score("flooder01")

    def boom(req):
        raise RuntimeError("app crashed")

    mp.app.check_tx = boom
    r.receive(0x30, peer, msg_txs([b"tx-late\x01"]))
    assert board.score("flooder01") <= before
    assert "flooder01:checktx_reject" not in board.describe()["offenses"]


def test_flood_through_batched_front_door_bans_flooder(monkeypatch):
    """Overload composition (docs/OVERLOAD.md): sustained garbage through
    the batched gossip path crosses the ban threshold exactly as the
    serial path did — shed/gate behavior unchanged under batching."""
    from tendermint_tpu.mempool.reactor import MempoolReactor, msg_txs

    monkeypatch.delenv("TMTPU_INGEST", raising=False)
    mp = Mempool(PricedApp(), version="v0", max_txs=1, max_tx_bytes=64)
    r = MempoolReactor(mp, broadcast=False)
    r.switch = _FakeSwitchWithBoard()
    mp.check_tx(b"resident\x05")
    peer = _FakePeer("flooder02")
    board = r.switch.scoreboard
    # the PR 5 flood shape: oversized txs (tx_too_large, full-size points)
    # mixed with full-pool garbage, all through batched messages
    for wave in range(40):
        r.receive(0x30, peer, msg_txs(
            [b"X" * 100 for _ in range(8)]
            + [b"flood-%d-%d\x01" % (wave, i) for i in range(8)]))
        if "flooder02" in board.describe()["banned"]:
            break
    assert "flooder02" in board.describe()["banned"]
    assert board.is_banned("flooder02")
    # ...while the honest pool resident is untouched
    assert [m.tx for m in mp.iter_txs()] == [b"resident\x05"]


def test_rpc_gate_sheds_flood_through_batched_front_door():
    """The admission gate holds one slot per batch-member: a flood beyond
    the inflight limit is refused with the typed overload error while the
    inflight members complete through the coalesced path."""
    import base64

    from tendermint_tpu.rpc import core as rpc_core

    release = threading.Event()

    class SlowApp(PricedApp):
        def check_tx(self, req):
            release.wait(10)
            return super().check_tx(req)

    class _Cfg:
        class rpc:
            unsafe = True
            max_broadcast_tx_inflight = 2

    class _Node:
        config = _Cfg()
        mempool = Mempool(SlowApp(), version="v1")
        switch = None

    class _Env:
        node = _Node()

        def __init__(self):
            self.event_bus = None

    env = _Env()
    results = []

    def tx(s):
        return base64.b64encode(s).decode()

    threads = [threading.Thread(
        target=lambda i=i: results.append(
            rpc_core.broadcast_tx_sync(env, tx(b"held-%d\x05" % i))),
        daemon=True) for i in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        gate = getattr(env.node, "_rpc_tx_gate", None)
        if gate is not None and gate._inflight >= 2:
            break
        time.sleep(0.005)
    # both slots held inside the coalesced CheckTx: the flood is SHED
    with pytest.raises(rpc_core.ErrOverloaded):
        rpc_core.broadcast_tx_sync(env, tx(b"flood\x05"))
    release.set()
    for t in threads:
        t.join(10)
    assert len(results) == 2 and all(r["code"] == 0 for r in results)


# ---------------------------------------------------------------------------
# Gossip send: drain-all batching
# ---------------------------------------------------------------------------


def test_gossip_send_drains_all_eligible_txs_into_one_message():
    from tendermint_tpu.encoding import proto
    from tendermint_tpu.mempool.reactor import MempoolReactor

    mp = Mempool(PricedApp(), version="v0")
    for i in range(7):
        mp.check_tx(b"g%d=v\x05" % i)
    # tx 3 came FROM the peer: suppressed, but must not block the rest
    list(mp.iter_txs())[3].senders.add("peer-x")
    r = MempoolReactor(mp, broadcast=False)
    peer = _FakePeer("peer-x")
    batch, sent_seq, last_seq, progressed = r._eligible_batch(peer, 0)
    assert batch == [b"g%d=v\x05" % i for i in (0, 1, 2, 4, 5, 6)]
    assert last_seq == 7 and not progressed
    # decode the wire message: ONE Txs message carrying the whole batch
    from tendermint_tpu.mempool.reactor import msg_txs

    f = proto.fields(msg_txs(batch))
    inner = proto.fields(f[1][-1])
    assert list(inner.get(1, [])) == batch
    # nothing eligible left once the cursor lands at last_seq
    batch2, s2, l2, p2 = r._eligible_batch(peer, last_seq)
    assert batch2 == [] and not p2


def test_gossip_send_leading_known_txs_advance_without_send():
    from tendermint_tpu.mempool.reactor import MempoolReactor

    mp = Mempool(PricedApp(), version="v0")
    mp.check_tx(b"from-peer-1\x05")
    mp.check_tx(b"from-peer-2\x05")
    for m in mp.iter_txs():
        m.senders.add("peer-y")
    r = MempoolReactor(mp, broadcast=False)
    batch, sent_seq, last_seq, progressed = r._eligible_batch(
        _FakePeer("peer-y"), 0)
    assert batch == [] and progressed and sent_seq == 2


def test_gossip_send_respects_byte_cap():
    from tendermint_tpu.mempool import reactor as reactor_mod

    mp = Mempool(PricedApp(), version="v0", max_txs_bytes=1 << 30)
    big = b"B" * (reactor_mod.GOSSIP_DRAIN_MAX_BYTES // 2 - 16)
    for i in range(4):
        mp.check_tx(big + b"-%d\x05" % i)
    r = reactor_mod.MempoolReactor(mp, broadcast=False)
    batch, _, last_seq, _ = r._eligible_batch(_FakePeer("peer-z"), 0)
    assert len(batch) == 2  # capped; the rest go out next tick
    batch2, _, _, _ = r._eligible_batch(_FakePeer("peer-z"), last_seq)
    assert len(batch2) == 2


def test_gossip_routine_thread_sends_batched_message():
    from tendermint_tpu.mempool.reactor import MEMPOOL_CHANNEL, MempoolReactor

    mp = Mempool(PricedApp(), version="v0")
    for i in range(5):
        mp.check_tx(b"thread-%d\x05" % i)
    r = MempoolReactor(mp, broadcast=True)
    r.switch = _FakeSwitchWithBoard()
    peer = _FakePeer("peer-t")
    r.add_peer(peer)
    deadline = time.monotonic() + 5
    while not peer.sent and time.monotonic() < deadline:
        time.sleep(0.005)
    r.remove_peer(peer, None)
    assert peer.sent, "gossip routine never sent"
    ch, msg = peer.sent[0]
    assert ch == MEMPOOL_CHANNEL
    from tendermint_tpu.encoding import proto

    inner = proto.fields(proto.fields(msg)[1][-1])
    assert list(inner.get(1, [])) == [b"thread-%d\x05" % i for i in range(5)]


# ---------------------------------------------------------------------------
# ABCI transport seam
# ---------------------------------------------------------------------------


def test_wire_codec_check_tx_batch_round_trip():
    from tendermint_tpu.abci import wire

    req = abci.RequestCheckTxBatch(txs=[b"a", b"bb", b""], type=1)
    kind, back = wire.decode_request(wire.encode_request("check_tx_batch", req))
    assert kind == "check_tx_batch" and back == req
    resp = abci.ResponseCheckTxBatch(responses=[
        abci.ResponseCheckTx(code=0, priority=7, sender="s", gas_wanted=2),
        abci.ResponseCheckTx(code=5, log="no", codespace="mempool"),
    ])
    kind, back = wire.decode_response(wire.encode_response("check_tx_batch", resp))
    assert kind == "check_tx_batch" and back == resp
    kind, back = wire.decode_response(
        wire.encode_response("check_tx_batch", abci.ResponseCheckTxBatch()))
    assert back == abci.ResponseCheckTxBatch()


def test_socket_transport_batch_round_trip_and_fallback():
    from tendermint_tpu.abci.client import ABCISocketClient
    from tendermint_tpu.abci.server import ABCIServer

    app = PricedApp()
    server = ABCIServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        cli = ABCISocketClient(server.addr)
        assert cli._batch_checktx is None  # unprobed
        out = cli.check_tx_batch(abci.RequestCheckTxBatch(
            txs=[b"ok\x07", b"bad\x01", b"x\x09"]))
        assert cli._batch_checktx is True  # the empty probe succeeded
        assert app.batch_calls == 2  # probe + the real batch
        assert [r.code for r in out.responses] == [0, 1, 0]
        assert [r.priority for r in out.responses] == [7, 0, 9]
        # the pre-batch-server degradation: serial loop, same responses
        cli._batch_checktx = False
        out2 = cli.check_tx_batch(abci.RequestCheckTxBatch(
            txs=[b"ok\x07", b"bad\x01"]))
        assert [r.code for r in out2.responses] == [0, 1]
        cli.close()
    finally:
        server.stop()


def test_socket_app_exception_does_not_disable_batching():
    """An app blow-up during a batch is an exception RESPONSE, not a
    pre-batch server: it must propagate (the mempool layer serial-falls-
    back that one call) WITHOUT pinning the client to the serial loop."""
    from tendermint_tpu.abci.client import ABCISocketClient
    from tendermint_tpu.abci.server import ABCIServer
    from tendermint_tpu.abci.wire import ABCIRemoteError

    class FlakyApp(PricedApp):
        def __init__(self):
            super().__init__()
            self.fail_once = True

        def check_tx_batch(self, req):
            # req.txs guard: the client's empty support-probe must not
            # count as the transient failure under test
            if req.txs and self.fail_once:
                self.fail_once = False
                raise RuntimeError("transient app failure")
            return super().check_tx_batch(req)

    server = ABCIServer(FlakyApp(), "tcp://127.0.0.1:0")
    server.start()
    try:
        cli = ABCISocketClient(server.addr)
        with pytest.raises(ABCIRemoteError, match="transient"):
            cli.check_tx_batch(abci.RequestCheckTxBatch(txs=[b"ok\x01"]))
        assert cli._batch_checktx  # one blip must not cost batching forever
        out = cli.check_tx_batch(abci.RequestCheckTxBatch(txs=[b"ok\x03"]))
        assert [r.code for r in out.responses] == [0]
        cli.close()
    finally:
        server.stop()


def test_local_client_exposes_check_tx_batch():
    from tendermint_tpu.abci.proxy import local_app_conns

    conns = local_app_conns(PricedApp())
    out = conns.mempool.check_tx_batch(abci.RequestCheckTxBatch(
        txs=[b"ok\x04", b"bad\x01"]))
    assert [r.code for r in out.responses] == [0, 1]


def test_application_shim_preserves_recheck_type():
    seen = []

    class TypedApp(abci.Application):
        def check_tx(self, req):
            seen.append(req.type)
            return abci.ResponseCheckTx(code=0)

    TypedApp().check_tx_batch(abci.RequestCheckTxBatch(
        txs=[b"a", b"b"], type=abci.CHECK_TX_TYPE_RECHECK))
    assert seen == [abci.CHECK_TX_TYPE_RECHECK] * 2


# ---------------------------------------------------------------------------
# Observability
# ---------------------------------------------------------------------------


def test_ingest_spans_are_canonical_and_recorded(monkeypatch):
    from tendermint_tpu.utils import trace as tmtrace

    for name in ("mempool.ingest_batch", "mempool.ingest_coalesce",
                 "mempool.ingest_wait"):
        assert name in tmtrace.CANONICAL_SPANS
    assert "mempool.ingest_batch" in tmtrace.MIRRORED_SPANS
    monkeypatch.setenv("TMTPU_INGEST_WINDOW_US", "20000")
    mp = Mempool(PricedApp(), version="v1")
    tracer = tmtrace.Tracer(name="ingest-test", enabled=True)
    mp.tracer = tracer
    try:
        assert mp.ingest_tx(b"traced\x05").is_ok()
    finally:
        tracer.disable()
    names = {s.name for s in tracer.dump()}
    assert {"mempool.ingest_batch", "mempool.ingest_coalesce",
            "mempool.ingest_wait"} <= names


def test_ingest_metrics_preseeded_and_counted():
    from tendermint_tpu.utils import metrics as tmmetrics

    nm = tmmetrics.NodeMetrics()
    text = nm.registry.expose()
    assert 'tendermint_mempool_ingest_txs_total{result="ok"} 0.0' in text
    assert 'tendermint_mempool_ingest_txs_total{result="reject"} 0.0' in text
    assert 'tendermint_mempool_ingest_txs_total{result="shed"} 0.0' in text
    assert "tendermint_mempool_ingest_coalesced_total 0.0" in text
    assert "tendermint_mempool_ingest_batch_size_count 0" in text
    prev = tmmetrics.GLOBAL_NODE_METRICS
    tmmetrics.GLOBAL_NODE_METRICS = nm
    try:
        mp = Mempool(PricedApp(), version="v1")
        mp.check_tx_batch([b"m-ok\x05", b"bad-m\x01"])
    finally:
        tmmetrics.GLOBAL_NODE_METRICS = prev
    text = nm.registry.expose()
    assert 'tendermint_mempool_ingest_txs_total{result="ok"} 1.0' in text
    assert 'tendermint_mempool_ingest_txs_total{result="reject"} 1.0' in text
    assert "tendermint_mempool_ingest_batch_size_count 1" in text
