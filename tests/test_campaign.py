"""Campaign runner + repro minimization (tendermint_tpu/e2e/campaign.py,
docs/SOAK.md §campaigns).

Quick tier: the ddmin minimizer against synthetic failure predicates
(injected run_fn — no clusters), violation-signature parsing, coverage
gap-fill determinism, and artifact schema arithmetic on a stubbed phase
runner.

Slow tier: a real two-phase generated campaign over a durable fabric
(zero violations, full vocabulary coverage census) and the forced-failure
path — an intentionally unhealed quorum crash whose five-entry schedule
auto-minimizes to exactly the two quorum-cutting crash entries.
"""

import json

import pytest

from tendermint_tpu.e2e import campaign
from tendermint_tpu.e2e.soak import SoakAction, SoakSchedule
from tendermint_tpu.utils import faults, nemesis

SEED = 2026


@pytest.fixture(autouse=True)
def _clean_chaos():
    faults.configure([], seed=SEED)
    nemesis.clear()
    yield
    nemesis.clear()
    nemesis.PLANE.on_heal.clear()
    faults.clear()


# ---------------------------------------------------------------------------
# ddmin minimizer units (quick, synthetic run_fn)
# ---------------------------------------------------------------------------


def test_minimize_finds_interacting_pair():
    calls = []

    def run_fn(sub):
        calls.append(list(sub))
        return "b" in sub and "e" in sub

    sub, runs = campaign.minimize(list("abcdefgh"), run_fn, max_runs=40)
    assert sorted(sub) == ["b", "e"]
    assert runs == len(calls) <= 40
    # every probe the minimizer accepted still reproduces: the returned
    # subset is FAILING by construction, never a guess
    assert run_fn(sub)


def test_minimize_single_culprit_and_order_preserved():
    sub, _ = campaign.minimize(list("abcdef"), lambda s: "d" in s,
                               max_runs=40)
    assert sub == ["d"]
    # order of surviving entries is schedule order, not ddmin visit order
    sub, _ = campaign.minimize(
        list("abcdef"), lambda s: "b" in s and "e" in s, max_runs=40)
    assert sub == ["b", "e"]


def test_minimize_run_cap_returns_failing_superset():
    """A cap hit must return a subset that STILL fails (best-so-far),
    never a half-reduced guess that might pass."""
    entries = list("abcdefghij")

    def run_fn(sub):
        return "a" in sub

    sub, runs = campaign.minimize(entries, run_fn, max_runs=2)
    assert runs <= 2
    assert run_fn(sub), "cap-hit result must still reproduce"


def test_minimize_degenerate_inputs():
    assert campaign.minimize(["x"], lambda s: True, max_runs=5)[0] == ["x"]
    assert campaign.minimize([], lambda s: True, max_runs=5)[0] == []


def test_violation_kind_parsing():
    assert campaign._violation_kind("[liveness @12.3s] no commit") == "liveness"
    assert campaign._violation_kind("[false-expiry @1s] x") == "false-expiry"
    assert campaign._violation_kind("[bft-time @0.5s] y") == "bft-time"
    assert campaign._violation_kind("garbage") == "unknown"


def test_last_phase_attribution_parsing():
    v = ("[liveness @12.3s] no node committed a block for 8.0s "
         "[lagging: node 1@h4 last_phase=consensus.precommit(h4), "
         "node 2@h0 last_phase=?]")
    assert campaign._last_phases(v) == {
        "1": "consensus.precommit(h4)", "2": "?"}
    assert campaign._last_phases("[liveness @1s] bare detail") == {}


# ---------------------------------------------------------------------------
# Coverage gap-fill (quick)
# ---------------------------------------------------------------------------


def test_gap_actions_speak_the_schedule_grammar():
    """Every injectable gap action must round-trip through the soak
    grammar — a gap-filled schedule IS a repro line."""
    for kind in campaign.VOCABULARY:
        a = campaign._gap_action(kind, 5.0, 3)
        assert a is not None, kind
        assert SoakAction.parse(a.describe()).kind == kind


def test_fill_gaps_targets_uncovered_vocabulary():
    base = SoakSchedule([SoakAction(2.0, "partition", "1|rest", 1.0)])
    filled = campaign.fill_gaps(base, {"crash": 1}, 20.0, seed=7, nodes=5)
    kinds = [a.kind for a in filled.actions]
    assert "partition" in kinds
    # injected kinds come from the uncovered vocabulary only
    injected = [k for k in kinds if k != "partition"]
    assert injected and all(k not in ("partition", "crash")
                            for k in injected)
    assert len(injected) <= 3
    # deterministic in (seed, covered): replay re-derives the same fill
    again = campaign.fill_gaps(base, {"crash": 1}, 20.0, seed=7, nodes=5)
    assert again.describe() == filled.describe()
    # nothing missing -> untouched schedule
    full = {k: 1 for k in campaign.VOCABULARY}
    assert campaign.fill_gaps(base, full, 20.0, 7, 5).describe() == \
        base.describe()


def test_injected_crash_always_tears_the_wal_tail():
    a = campaign._gap_action("crash", 5.0, 2)
    assert a.arg.endswith(":torn"), \
        "campaign gap-fill guarantees torn-tail coverage"


# ---------------------------------------------------------------------------
# Real campaigns (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_campaign_two_phases_clean_with_coverage(tmp_path):
    art = campaign.run_campaign(str(tmp_path), seed=3, budget_s=40.0,
                                phase_s=16.0, nodes=5,
                                liveness_budget_s=25.0,
                                out=str(tmp_path / "SOAK.json"))
    assert art["violations"] == [], art["violations"]
    assert art["version"] == campaign.SCHEMA_VERSION
    assert len(art["phases"]) >= 2
    assert len(art["coverage"]) >= 6, art["coverage"]
    assert art["stats"]["heights_audited"] > 0
    assert art["stats"]["max_height"] >= 2
    on_disk = json.loads((tmp_path / "SOAK.json").read_text())
    assert on_disk == art


@pytest.mark.slow
def test_campaign_minimizes_unhealed_quorum_crash(tmp_path):
    """The forced-failure path end to end: three noise entries plus two
    never-rebooted crashes that cut quorum on a 4-node cluster. The
    campaign must record a liveness violation and ddmin the schedule
    down to EXACTLY the two crash entries — a replayable repro line."""
    spec = ("@2:linkfault~1:*>1:drop%0.3;@3:power:2:15;@4:skew~3:3:60;"
            "@6:crash~-1:1;@6.5:crash~-1:2")
    art = campaign.run_campaign(str(tmp_path), seed=9, budget_s=30.0,
                                phase_s=18.0, nodes=4,
                                liveness_budget_s=7.0,
                                phase_specs=[spec], max_minimize_runs=8)
    assert art["violations"]
    assert art["violations"][0]["kind"] == "liveness"
    assert art["violations"][0]["phase"] == 0
    mini = art["minimized_repro"]
    assert mini.startswith("TMTPU_SOAK_REPRO:")
    assert "TMTPU_SOAK_DURABLE=1" in mini
    sched = mini.split("TMTPU_SOAK_SCHEDULE='")[1].rstrip("'")
    assert sched == "@6:crash~-1:1;@6.5:crash~-1:2", mini
