"""L0 utility libs: BitArray set ops + wire round-trip, flowrate monitor
and limiter, autofile group rotation (reference: libs/bits, libs/flowrate,
libs/autofile)."""

import random
import time

from tendermint_tpu.utils.autofile import Group
from tendermint_tpu.utils.bits import BitArray
from tendermint_tpu.utils.flowrate import Monitor


def test_bitarray_basics_and_setops():
    ba = BitArray(70)
    assert len(ba) == 70 and ba.is_empty() and not ba.is_full()
    ba[3] = True
    ba[69] = True
    assert ba[3] and ba[69] and not ba[4]
    assert ba.sum() == 2
    assert ba[-1] is True
    assert ba[0:5] == [False, False, False, True, False]
    assert str(ba).count("x") == 2

    other = BitArray(70)
    other[3] = True
    other[10] = True
    assert ba.or_(other).sum() == 3
    assert ba.and_(other).sum() == 1
    assert ba.sub(other).sum() == 1  # only 69 survives
    assert ba.not_().sum() == 68

    ba.update(other)
    assert ba.sum() == 3

    full = BitArray.from_bools([True] * 8)
    assert full.is_full()
    idx, ok = ba.pick_random(random.Random(1))
    assert ok and ba[idx]
    assert BitArray(0).pick_random() == (0, False)


def test_bitarray_wire_roundtrip():
    for n in (0, 1, 63, 64, 65, 130):
        ba = BitArray(n)
        for i in range(0, n, 3):
            ba[i] = True
        got = BitArray.unmarshal(ba.marshal())
        assert got == ba, n
    # interop with list-of-bools comparison
    assert BitArray.from_bools([True, False, True]) == [True, False, True]


def test_flowrate_monitor_and_limit():
    m = Monitor(sample_period_s=0.01, ewma_window_s=0.05)
    for _ in range(20):
        m.update(1000)
        time.sleep(0.005)
    st = m.status()
    assert st.bytes_total == 20_000
    assert st.avg_rate > 0 and st.cur_rate > 0
    assert st.peak_rate >= st.cur_rate * 0.5

    # limiter: at 10KB/s, moving 30KB must take ~3s -- prove it throttles by
    # checking a tight loop is slowed (use a small amount to keep tests fast)
    m2 = Monitor(sample_period_s=0.01)
    t0 = time.monotonic()
    moved = 0
    while moved < 3000:
        n = m2.limit(1000, rate=10_000, block=True)
        moved += m2.update(n)
    elapsed = time.monotonic() - t0
    assert elapsed >= 0.2, elapsed  # 3KB at 10KB/s >= ~0.3s theoretical
    # unlimited rate never blocks
    assert m2.limit(10**9, rate=0) == 10**9


def test_autofile_group_rotation_and_read(tmp_path):
    head = str(tmp_path / "wal" / "log")
    g = Group(head, head_size_limit=100, total_size_limit=350)
    for i in range(10):
        g.write(b"%02d" % i * 30)  # 60 bytes each -> rotate every 2 writes
    g.flush(fsync=True)
    idxs = g.chunk_indexes()
    assert idxs, "rotation never happened"
    # total size enforcement dropped the oldest chunks
    total = sum(len(c) for c in g.read_all())
    assert total <= 350 + 120  # limit + one head chunk of slack
    # data is readable oldest-first and contiguous per chunk
    blobs = list(g.read_all())
    assert all(isinstance(b, bytes) for b in blobs)
    g.close()

    # reopening appends to the same head
    g2 = Group(head, head_size_limit=100)
    g2.write(b"reopened")
    g2.flush()
    assert b"reopened" in list(g2.read_all())[-1]
    g2.close()


def test_trust_metric_rises_and_falls():
    from tendermint_tpu.p2p.trust import TrustMetric, TrustMetricStore

    m = TrustMetric(interval_s=0.02)
    for _ in range(50):
        m.good_events()
    assert m.trust_score() >= 90
    time.sleep(0.05)
    for _ in range(80):
        m.bad_events()
    assert m.trust_value() < 0.5
    # recovery is slower than decay (negative-trend damping)
    time.sleep(0.05)
    for _ in range(10):
        m.good_events()
    assert m.trust_value() < 1.0

    store = TrustMetricStore(interval_s=0.02)
    a = store.get_peer_trust_metric("peerA")
    assert store.get_peer_trust_metric("peerA") is a
    assert store.size() == 1
    store.peer_disconnected("peerA")
    assert store.size() == 0


def test_fuzzed_connection_faults():
    from tendermint_tpu.p2p.fuzz import FuzzedConnection

    class FakeConn:
        def __init__(self):
            self.written = []
        def write(self, b):
            self.written.append(b)
            return len(b)
        def read(self, n):
            return b"y" * n
        def close(self):
            self.closed = True

    raw = FakeConn()
    # 100% drop: writes vanish, reads look like EOF
    fc = FuzzedConnection(raw, prob_drop_rw=1.0, seed=1)
    assert fc.write(b"x") == 1 and raw.written == []
    assert fc.read(4) == b""
    # 0% drop passes through
    fc2 = FuzzedConnection(FakeConn(), prob_drop_rw=0.0, seed=1)
    assert fc2.read(3) == b"yyy"
    # dead connection raises after the deadline
    fc3 = FuzzedConnection(FakeConn(), die_after_s=0.01, seed=1)
    time.sleep(0.02)
    import pytest
    with pytest.raises(ConnectionError):
        fc3.write(b"x")


def test_trace_spans_and_summary():
    """Module-level span()/dump() are thin delegates to the process
    DEFAULT tracer (ISSUE 10 satellite 1) — but the assertions run on an
    INSTANCE tracer, so they no longer depend on global reset order."""
    from tendermint_tpu.utils import trace

    t = trace.Tracer("libs-unit")
    with t.span("noop"):
        pass
    assert t.dump(clear=True) == []

    t.enable()
    try:
        with t.span("verify", batch=64):
            time.sleep(0.01)
        t.record("kernel", 0.005, chunk=0)
        spans = t.dump()
        names = [s.name for s in spans]
        assert "verify" in names and "kernel" in names
        v = next(s for s in spans if s.name == "verify")
        assert v.duration_s >= 0.01 and v.tags == {"batch": 64}
        agg = t.summarize()
        assert agg["verify"]["count"] == 1
        assert agg["kernel"]["total_s"] >= 0.005
    finally:
        t.disable()

    # the module surface still delegates: enable() flips DEFAULT, span()
    # records into the thread's current tracer (DEFAULT when none active)
    trace.enable()
    try:
        with trace.span("module_delegate"):
            pass
        assert any(s.name == "module_delegate" for s in trace.dump())
    finally:
        trace.disable()
        trace.dump(clear=True)


def test_trace_consensus_steps(tmp_path, monkeypatch):
    """TMTPU_TRACE=1 gives the node an ENABLED instance tracer that
    captures step transitions and a complete per-height lifecycle —
    without touching any process-global ring."""
    import os
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import MockPV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.utils import trace

    monkeypatch.setenv("TMTPU_TRACE", "1")
    priv = ed25519.gen_priv_key(b"\x43" * 32)
    genesis = GenesisDoc(chain_id="trace-chain", genesis_time=Time(1700003000, 0),
                         validators=[GenesisValidator(b"", priv.pub_key(), 10)])
    cfg = test_config()
    cfg.set_root(str(tmp_path / "n"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = ""
    cfg.p2p.pex = False
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = ""
    node = Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x44" * 32)))
    assert node.tracer.enabled  # TMTPU_TRACE=1 wired it on
    node.start()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and node.block_store.height < 3:
            time.sleep(0.1)
        assert node.block_store.height >= 3
    finally:
        node.stop()
        node.tracer.disable()
    agg = node.tracer.summarize()
    assert agg.get("consensus.step", {}).get("count", 0) >= 5
    # the DEFAULT ring stayed out of it: per-node spans are instance-scoped
    assert not any(s.name == "consensus.step" for s in trace.DEFAULT.dump())
    # a committed height carries the full lifecycle in causal order
    tl = node.tracer.timeline(2)
    assert tl["lifecycle_complete"] and tl["causal_ok"], tl["lifecycle"]
    assert all(n == 1 for n in tl["lifecycle"].values()), tl["lifecycle"]


def test_behaviour_reporter():
    from tendermint_tpu.p2p.behaviour import (
        MockReporter,
        SwitchReporter,
        bad_message,
        consensus_vote,
    )
    from tendermint_tpu.p2p.trust import TrustMetricStore

    mock = MockReporter()
    mock.report(consensus_vote("p1"))
    mock.report(bad_message("p1", "garbage"))
    bs = mock.get_behaviours("p1")
    assert [b.kind for b in bs] == ["consensus_vote", "bad_message"]
    assert not bs[1].is_good() and bs[0].is_good()

    # SwitchReporter: bad behaviour stops the peer, good credits trust
    class FakeSwitch:
        def __init__(self):
            self.stopped = []
        def stop_peer_by_id(self, peer_id, reason):
            self.stopped.append(reason)
            return True

    sw = FakeSwitch()
    store = TrustMetricStore(interval_s=10)
    rep = SwitchReporter(sw, trust_store=store)
    rep.report(consensus_vote("p2"))
    assert sw.stopped == []
    rep.report(bad_message("p2", "evil"))
    assert sw.stopped and "bad_message" in sw.stopped[0]
    assert store.get_peer_trust_metric("p2").trust_value() < 1.0
