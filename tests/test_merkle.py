"""RFC 6962 merkle vectors (reference: crypto/merkle/rfc6962_test.go,
crypto/merkle/tree_test.go)."""

import hashlib

from tendermint_tpu.crypto import merkle


def test_empty_hash():
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    assert (
        merkle.empty_hash().hex()
        == "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"
    )


def test_rfc6962_leaf_hash():
    # RFC 6962 test: leaf hash of empty leaf = SHA-256(0x00)
    assert (
        merkle.leaf_hash(b"").hex()
        == "6e340b9cffb37a989ca544e6bb780a2c78901d3fb33738768511a30617afa01d"
    )
    # leaf "L123456"
    assert (
        merkle.leaf_hash(b"L123456").hex()
        == "395aa064aa4c29f7010acfe3f25db9485bbd4b91897b6ad7ad547639252b4d56"
    )


def test_rfc6962_inner_hash():
    assert (
        merkle.inner_hash(b"N123", b"N456").hex()
        == "aa217fe888e47007fa15edab33c2b492a722cb106c64667fc2b044444de66bbb"
    )


def test_split_point():
    assert merkle.split_point(2) == 1
    assert merkle.split_point(3) == 2
    assert merkle.split_point(4) == 2
    assert merkle.split_point(5) == 4
    assert merkle.split_point(8) == 4
    assert merkle.split_point(9) == 8


def test_tree_structure():
    items = [bytes([i]) * 3 for i in range(5)]
    # 5 leaves: split 4|1
    left = merkle.hash_from_byte_slices(items[:4])
    right = merkle.hash_from_byte_slices(items[4:])
    assert merkle.hash_from_byte_slices(items) == merkle.inner_hash(left, right)


def test_proofs_roundtrip():
    for n in [1, 2, 3, 5, 8, 13]:
        items = [b"item%d" % i for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, proof in enumerate(proofs):
            proof.verify(root, items[i])
            assert proof.total == n and proof.index == i


def test_proof_rejects_wrong_leaf():
    items = [b"a", b"b", b"c"]
    root, proofs = merkle.proofs_from_byte_slices(items)
    try:
        proofs[0].verify(root, b"x")
        assert False, "expected failure"
    except ValueError:
        pass


def test_batched_matches_recursive():
    # The level-order batched path (chash-backed) must produce the same root
    # as the recursive reference shape for every size straddling the
    # threshold, including odd/power-of-two/one-off sizes.
    import tendermint_tpu.crypto.merkle as m

    for n in list(range(1, 20)) + [63, 64, 65, 127, 128, 129, 1000]:
        items = [b"item-%d" % i for i in range(n)]
        batched = m._hash_from_byte_slices_batched(items)
        recursive = _recursive_root(m, items)
        assert batched == recursive, n


def _recursive_root(m, items):
    n = len(items)
    if n == 1:
        return m.leaf_hash(items[0])
    k = m.split_point(n)
    return m.inner_hash(_recursive_root(m, items[:k]), _recursive_root(m, items[k:]))


def test_batched_without_c_lib(monkeypatch):
    # hashlib fallback inside chash must give identical results.
    from tendermint_tpu.ops import chash

    monkeypatch.setattr(chash, "_lib", None)
    monkeypatch.setattr(chash, "_tried", True)
    import tendermint_tpu.crypto.merkle as m

    items = [b"x%d" % i for i in range(100)]
    assert m._hash_from_byte_slices_batched(items) == _recursive_root(m, items)


def test_hash_trees_fixed_matches_scalar():
    import tendermint_tpu.crypto.merkle as m

    for arity in (1, 2, 3, 7, 14, 16):
        trees = [[b"t%d-i%d" % (t, i) for i in range(arity)]
                 for t in range(9)]
        roots = m.hash_trees_fixed(trees)
        assert roots == [m.hash_from_byte_slices(tr) for tr in trees]
    assert m.hash_trees_fixed([]) == []
    assert m.hash_trees_fixed([[], []]) == [m.empty_hash()] * 2


def test_hash_trees_fixed_rejects_ragged():
    import pytest

    import tendermint_tpu.crypto.merkle as m

    with pytest.raises(ValueError, match="same-arity"):
        m.hash_trees_fixed([[b"a"], [b"a", b"b"]])


def test_precompute_header_hashes_differential():
    from tendermint_tpu.types.block import Header, precompute_header_hashes
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.ttime import Time

    headers = [
        Header(chain_id="c%d" % (i % 3), height=i + 1,
               time=Time(1700000000 + i, i * 7),
               last_block_id=BlockID(),
               validators_hash=bytes([i % 251 + 1]) * 32,
               next_validators_hash=b"\x02" * 32,
               app_hash=b"" if i % 2 else b"\x03" * 32,
               proposer_address=bytes([i % 200]) * 20)
        for i in range(25)
    ]
    scalar = [h.hash() for h in headers]  # cache is empty: scalar path
    incomplete = Header(chain_id="c", height=99)  # no validators_hash
    precompute_header_hashes(headers + [incomplete])
    assert [h.hash() for h in headers] == scalar
    assert all(h._hash_cache is not None for h in headers)
    assert incomplete._hash_cache is None and incomplete.hash() is None
