"""Storage, privval, mempool, and block execution: unit + end-to-end apply."""

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.mempool.mempool import (
    ErrMempoolIsFull,
    ErrTxInCache,
    Mempool,
)
from tendermint_tpu.privval.file_pv import DoubleSignError, FilePV, MockPV
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.state.store import ABCIResponses, StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.db import MemDB, SQLiteDB
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import (
    BLOCK_ID_FLAG_COMMIT,
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    Vote,
)


def test_db_backends(tmp_path):
    for db in (MemDB(), SQLiteDB(str(tmp_path / "kv.db"))):
        db.set(b"a", b"1")
        db.set(b"b", b"2")
        db.set(b"c", b"3")
        db.delete(b"b")
        assert db.get(b"a") == b"1" and db.get(b"b") is None
        assert [k for k, _ in db.iterator(b"a", b"c")] == [b"a"]
        assert [k for k, _ in db.iterator()] == [b"a", b"c"]
        assert [k for k, _ in db.reverse_iterator()] == [b"c", b"a"]
        db.close()


def _genesis(n_vals=1, chain_id="exec-chain"):
    privs = [ed25519.gen_priv_key(bytes([40 + i]) * 32) for i in range(n_vals)]
    gvals = [GenesisValidator(b"", p.pub_key(), 10) for p in privs]
    gd = GenesisDoc(chain_id=chain_id, validators=gvals,
                    genesis_time=Time(1700000000, 0))
    gd.validate_and_complete()
    return gd, privs


def _commit_for(state, block, privs, round_=0):
    bid = BlockID(hash=block.hash(),
                  part_set_header=PartSet.from_data(block.marshal()).header())
    sigs = []
    by_addr = {p.pub_key().address(): p for p in privs}
    for val in state.validators.validators:
        priv = by_addr[val.address]
        v = Vote(type=PRECOMMIT_TYPE, height=block.header.height, round=round_,
                 block_id=bid, timestamp=block.header.time.add_ns(1_000_000),
                 validator_address=val.address,
                 validator_index=state.validators.get_by_address(val.address)[0])
        v.signature = priv.sign(v.sign_bytes(state.chain_id))
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address, v.timestamp, v.signature))
    return bid, Commit(height=block.header.height, round=round_, block_id=bid,
                       signatures=sigs)


def test_block_executor_applies_chain():
    """Drive three blocks through BlockExecutor + kvstore end to end."""
    gd, privs = _genesis(3)
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    store = StateStore(MemDB())
    store.save(state)
    mp = Mempool(app)
    bx = BlockExecutor(store, app, mempool=mp)

    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    for h in range(1, 4):
        mp.check_tx(b"k%d=v%d" % (h, h))
        proposer = state.validators.get_proposer()
        block = bx.create_proposal_block(h, state, last_commit, proposer.address)
        bid, commit = _commit_for(state, block, privs)
        state, _ = bx.apply_block(state, bid, block)
        assert state.last_block_height == h
        assert mp.size() == 0  # committed tx removed
        last_commit = commit

    assert app.size == 3
    assert state.app_hash == (3).to_bytes(8, "big")
    # validator history is queryable per height
    assert store.load_validators(2).hash() == store.load_validators(3).hash()
    resp = store.load_abci_responses(2)
    assert len(resp.deliver_txs) == 1 and resp.deliver_txs[0].code == 0
    # reload state from disk
    assert store.load().last_block_height == 3


def test_validator_power_change_propagates_and_batch_verifies():
    """ISSUE 9 satellite: a voting-power change submitted as the kvstore
    ``val:`` tx flows EndBlock validator_updates -> state/execution.py
    update_state -> the height+2 ValidatorSet, and the changed validator's
    votes then verify through the batched vote path (VoteSet.add_votes)
    with the NEW power tallied — the unit-level shape of the fabric's
    churn scenario (docs/SOAK.md)."""
    from tendermint_tpu.types.vote_set import VoteSet

    gd, privs = _genesis(3)
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    store = StateStore(MemDB())
    store.save(state)
    bx = BlockExecutor(store, app)

    # height 1: a plain tx
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    proposer = state.validators.get_proposer()
    block1 = state.make_block(1, [b"k=v"], last_commit, [], proposer.address)
    bid1, commit1 = _commit_for(state, block1, privs)
    state, _ = bx.apply_block(state, bid1, block1)

    # height 2 carries the power change: validator 0's power 10 -> 33
    target = privs[0].pub_key()
    tx = KVStoreApplication.make_val_tx(target.bytes(), 33)
    block2 = state.make_block(
        2, [tx], commit1, [], state.validators.get_proposer().address)
    bid2, commit2 = _commit_for(state, block2, privs)
    state, _ = bx.apply_block(state, bid2, block2)

    # scheduled, not immediate: validators(h+1) still carry 10, the
    # h+2 set carries 33 (reference: state/execution.go updateState)
    cur = {v.pub_key.bytes(): v.voting_power for v in state.validators.validators}
    nxt = {v.pub_key.bytes(): v.voting_power
           for v in state.next_validators.validators}
    assert cur[target.bytes()] == 10
    assert nxt[target.bytes()] == 33
    assert state.last_height_validators_changed == 4

    # height 3 commits -> the 33-power set is the CURRENT set for height 4
    block3 = state.make_block(
        3, [], commit2, [], state.validators.get_proposer().address)
    bid3, _commit3 = _commit_for(state, block3, privs)
    state, _ = bx.apply_block(state, bid3, block3)
    vals4 = state.next_validators
    assert {v.pub_key.bytes(): v.voting_power
            for v in vals4.validators}[target.bytes()] == 33
    # and the per-height store agrees
    assert store.load_validators(4).hash() == vals4.hash()

    # the changed validator's votes verify through the BATCH path
    # (VoteSet.add_votes: one dispatch()/resolve for the whole slice) and
    # its NEW power is what tips the 2/3 tally
    vs = VoteSet(state.chain_id, 4, 0, PRECOMMIT_TYPE, vals4)
    votes = []
    for p in privs:
        idx, _val = vals4.get_by_address(p.pub_key().address())
        v = Vote(type=PRECOMMIT_TYPE, height=4, round=0, block_id=bid3,
                 timestamp=Time(1700000500, 0),
                 validator_address=p.pub_key().address(),
                 validator_index=idx)
        v.signature = p.sign(v.sign_bytes(state.chain_id))
        votes.append(v)
    # validator 0 alone: 33 of 53 total is under 2/3 — no majority yet
    res0 = vs.add_votes(votes[:1])
    assert res0[0][0] and res0[0][1] is None
    assert vs.two_thirds_majority()[1] is False
    # +validator 1 (10): 43/53 > 2/3 — the new power is what tipped it
    # (old powers 10+10=20/33 would NOT have)
    res1 = vs.add_votes(votes[1:2])
    assert res1[0][0] and res1[0][1] is None
    maj, ok = vs.two_thirds_majority()
    assert ok and maj == bid3
    # a tampered signature from the changed validator is still rejected
    bad = Vote(type=PRECOMMIT_TYPE, height=4, round=0, block_id=bid3,
               timestamp=Time(1700000501, 0),
               validator_address=privs[2].pub_key().address(),
               validator_index=vals4.get_by_address(
                   privs[2].pub_key().address())[0])
    bad.signature = bytes(64)
    res_bad = vs.add_votes([bad])
    assert not res_bad[0][0] and res_bad[0][1] is not None


def test_block_store_roundtrip():
    gd, privs = _genesis(1)
    state = make_genesis_state(gd)
    app = KVStoreApplication()
    ss = StateStore(MemDB())
    ss.save(state)
    bx = BlockExecutor(ss, app, mempool=Mempool(app))
    bs = BlockStore(MemDB())

    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    block = bx.create_proposal_block(1, state, last_commit,
                                     state.validators.get_proposer().address,
                                     block_time=Time(1700000100, 0))
    ps = PartSet.from_data(block.marshal())
    bid, commit = _commit_for(state, block, privs)
    bs.save_block(block, ps, commit)

    assert bs.height == 1 and bs.base == 1
    loaded = bs.load_block(1)
    assert loaded.hash() == block.hash()
    assert bs.load_block_by_hash(block.hash()).header.height == 1
    assert bs.load_seen_commit(1).block_id == bid
    meta = bs.load_block_meta(1)
    assert meta.block_id.hash == block.hash()
    part = bs.load_block_part(1, 0)
    assert part.bytes_ == ps.get_part(0).bytes_


def test_file_pv_double_sign_protection(tmp_path):
    kf, sf = str(tmp_path / "key.json"), str(tmp_path / "state.json")
    pv = FilePV.generate(kf, sf, seed=b"\x21" * 32)
    bid = BlockID(hash=b"\xcc" * 32)
    from tendermint_tpu.types.block_id import PartSetHeader

    bid = BlockID(hash=b"\xcc" * 32, part_set_header=PartSetHeader(1, b"\xdd" * 32))

    v = Vote(type=PREVOTE_TYPE, height=5, round=0, block_id=bid,
             timestamp=Time(1700000000, 0), validator_address=pv.get_address(),
             validator_index=0)
    pv.sign_vote("pv-chain", v)
    sig1 = v.signature

    # same vote, later timestamp -> reuses previous timestamp + signature
    v2 = Vote(type=PREVOTE_TYPE, height=5, round=0, block_id=bid,
              timestamp=Time(1700000009, 0), validator_address=pv.get_address(),
              validator_index=0)
    pv.sign_vote("pv-chain", v2)
    assert v2.signature == sig1 and v2.timestamp == Time(1700000000, 0)

    # DIFFERENT block at same HRS -> refuses
    v3 = Vote(type=PREVOTE_TYPE, height=5, round=0, block_id=BlockID(),
              timestamp=Time(1700000000, 0), validator_address=pv.get_address(),
              validator_index=0)
    with pytest.raises(DoubleSignError):
        pv.sign_vote("pv-chain", v3)

    # height regression after reload -> refuses
    pv2 = FilePV.load(kf, sf)
    v4 = Vote(type=PREVOTE_TYPE, height=4, round=0, block_id=bid,
              timestamp=Time(1700000000, 0), validator_address=pv.get_address(),
              validator_index=0)
    with pytest.raises(DoubleSignError):
        pv2.sign_vote("pv-chain", v4)


def test_mempool_fifo_and_cache():
    app = KVStoreApplication()
    mp = Mempool(app, max_txs=3)
    mp.check_tx(b"a=1")
    mp.check_tx(b"b=2")
    with pytest.raises(ErrTxInCache):
        mp.check_tx(b"a=1")
    assert mp.size() == 2
    assert mp.reap_max_bytes_max_gas(1000, -1) == [b"a=1", b"b=2"]
    # max_bytes limits the reap
    assert len(mp.reap_max_bytes_max_gas(6, -1)) == 1
    mp.lock()
    mp.update(1, [b"a=1"], [abci.ResponseDeliverTx(code=0)])
    mp.unlock()
    assert mp.size() == 1
    # committed tx stays cached -> rejected on re-add
    with pytest.raises(ErrTxInCache):
        mp.check_tx(b"a=1")


def test_mempool_priority_ordering():
    class PrioApp(KVStoreApplication):
        def check_tx(self, req):
            return abci.ResponseCheckTx(code=0, priority=len(req.tx))

    mp = Mempool(PrioApp(), version="v1")
    mp.check_tx(b"s")
    mp.check_tx(b"looooong")
    mp.check_tx(b"mid")
    assert mp.reap_max_txs(-1) == [b"looooong", b"mid", b"s"]
    # gossip iteration stays insertion-ordered
    assert [m.tx for m in mp.iter_txs()] == [b"s", b"looooong", b"mid"]


def test_state_store_abci_responses_roundtrip():
    ss = StateStore(MemDB())
    rs = ABCIResponses(deliver_txs=[
        abci.ResponseDeliverTx(code=0, data=b"ok", gas_wanted=5),
        abci.ResponseDeliverTx(code=7, log="fail"),
    ])
    ss.save_abci_responses(9, rs)
    out = ss.load_abci_responses(9)
    assert out.deliver_txs[0].data == b"ok"
    assert out.deliver_txs[1].code == 7


def test_mempool_ttl_num_blocks_eviction():
    """ttl-num-blocks: a tx older than N blocks is purged on update and
    leaves the cache so it can be resubmitted (reference:
    mempool/v1/mempool.go purgeExpiredTxs)."""
    app = KVStoreApplication()
    mp = Mempool(app, ttl_num_blocks=2)
    mp.check_tx(b"old=1")  # enters at height 0
    mp.lock(); mp.update(1, []); mp.unlock()
    mp.lock(); mp.update(2, []); mp.unlock()
    assert mp.size() == 1  # age exactly 2: strict > keeps it one more block
    mp.check_tx(b"young=1")  # enters at height 2
    mp.lock(); mp.update(3, []); mp.unlock()
    assert [m.tx for m in mp.iter_txs()] == [b"young=1"]  # old age 3 > 2
    # expired tx left the cache: resubmission is accepted, not ErrTxInCache
    assert mp.check_tx(b"old=1").is_ok()
    assert mp.size() == 2


def test_mempool_ttl_duration_eviction(monkeypatch):
    import time as _time

    from tendermint_tpu.mempool import mempool as mpmod

    app = KVStoreApplication()
    mp = Mempool(app, ttl_duration_s=10.0)
    t0 = _time.monotonic()
    monkeypatch.setattr(mpmod.time, "monotonic", lambda: t0)
    mp.check_tx(b"aging=1")
    mp.check_tx(b"fresh=1")
    # first tx is now 11s old (> 10), second only 5s (re-stamped younger)
    mp._txs[mpmod.tx_key(b"fresh=1")].time = t0 + 6
    monkeypatch.setattr(mpmod.time, "monotonic", lambda: t0 + 11)
    mp.lock(); mp.update(1, []); mp.unlock()
    assert [m.tx for m in mp.iter_txs()] == [b"fresh=1"]


def test_mempool_ttl_disabled_by_default():
    app = KVStoreApplication()
    mp = Mempool(app)
    mp.check_tx(b"keep=1")
    for h in range(1, 8):
        mp.lock(); mp.update(h, []); mp.unlock()
    assert mp.size() == 1


def test_mempool_v1_priority_eviction_when_full():
    """v1 full-pool admission (reference: mempool/v1/mempool.go:505-577):
    a higher-priority arrival evicts the lowest-priority txs (ties: newest
    first); an arrival no better than everything resident is rejected and
    un-cached so it can be retried later. v0 keeps reject-when-full."""
    class PrioApp(KVStoreApplication):
        def check_tx(self, req):
            # priority = numeric suffix after '~'
            return abci.ResponseCheckTx(code=0,
                                        priority=int(req.tx.split(b"~")[1]))

    mp = Mempool(PrioApp(), version="v1", max_txs=3)
    mp.check_tx(b"a~5")
    mp.check_tx(b"b~1")
    mp.check_tx(b"c~3")
    # full; priority 4 > {1,3}: evicts the single lowest (b~1)
    assert mp.check_tx(b"d~4").is_ok()
    assert sorted(m.tx for m in mp.iter_txs()) == [b"a~5", b"c~3", b"d~4"]
    # evicted tx left the cache: immediate retry is not ErrTxInCache
    # (still full, and priority 1 beats nothing -> full again)
    with pytest.raises(ErrMempoolIsFull):
        mp.check_tx(b"b~1")
    with pytest.raises(ErrMempoolIsFull):
        mp.check_tx(b"b~1")  # NOT ErrTxInCache: reject removed it from cache
    # another arrival evicts the current lowest priority (c~3)
    assert mp.check_tx(b"e~9").is_ok()
    assert sorted(m.tx for m in mp.iter_txs()) == [b"a~5", b"d~4", b"e~9"]

    # v0: reject-when-full regardless of priority
    mp0 = Mempool(PrioApp(), version="v0", max_txs=1)
    mp0.check_tx(b"x~1")
    with pytest.raises(ErrMempoolIsFull):
        mp0.check_tx(b"y~9")


def test_tx_filters_from_consensus_state():
    """state/tx_filter.py: pre-check bounds tx size to the block data
    budget, post-check bounds gas to block.max_gas; both typed as
    ErrPreCheck and un-cached so a retry isn't a cache hit (reference:
    state/tx_filter.go, mempool/mempool.go:111-141)."""
    from dataclasses import replace as dc_replace

    from tendermint_tpu.mempool.mempool import ErrPreCheck
    from tendermint_tpu.state.tx_filter import tx_post_check, tx_pre_check
    from tendermint_tpu.types.params import BlockParams

    gd, _ = _genesis(1)
    state = make_genesis_state(gd)

    class GasApp(KVStoreApplication):
        def check_tx(self, req):
            return abci.ResponseCheckTx(code=0, gas_wanted=len(req.tx))

    # post-check: max_gas=5 rejects a 6-byte (gas 6) tx, accepts gas 5
    state5 = dc_replace(
        state, consensus_params=dc_replace(
            state.consensus_params, block=BlockParams(max_gas=5)))
    mp = Mempool(GasApp())
    mp.post_check = tx_post_check(state5)
    assert mp.check_tx(b"five!").is_ok()
    with pytest.raises(ErrPreCheck, match="max gas"):
        mp.check_tx(b"sixsix")
    assert mp.check_tx(b"5char").is_ok()  # gas exactly at the bound passes
    # rejected tx is NOT cached: same bytes later raise the same filter
    # error, not ErrTxInCache
    with pytest.raises(ErrPreCheck, match="max gas"):
        mp.check_tx(b"sixsix")

    # pre-check: a tiny block budget rejects big txs before the app runs
    tiny = dc_replace(
        state, consensus_params=dc_replace(
            state.consensus_params, block=BlockParams(max_bytes=1000)))
    mp2 = Mempool(KVStoreApplication())
    mp2.pre_check = tx_pre_check(tiny)
    assert mp2.check_tx(b"ok=1").is_ok()
    with pytest.raises(ErrPreCheck, match="too big"):
        mp2.check_tx(b"z" * 900)

    # recheck applies post-check: tightening max_gas evicts resident txs
    mp3 = Mempool(GasApp())
    mp3.check_tx(b"sevennn")  # gas 7, admitted (no filter yet)
    mp3.lock()
    mp3.update(1, [], pre_check=tx_pre_check(state5),
               post_check=tx_post_check(state5))
    mp3.unlock()
    assert mp3.size() == 0  # gas 7 > 5: evicted on recheck
