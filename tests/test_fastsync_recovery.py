"""Fast sync proven over real sockets + the crash-recovery fail-point matrix
(reference: blockchain/v0/reactor.go:309-419, consensus/replay_test.go,
libs/fail/fail.go:10-38)."""

import json
import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time


def _wait(cond, timeout, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _mk_node(tmp_path, name, genesis, priv=None, fast_sync=False,
             persistent_peers=""):
    cfg = make_test_config()
    cfg.set_root(str(tmp_path / name))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = fast_sync
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.pex = False
    cfg.p2p.persistent_peers = persistent_peers
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = ""
    return Node(cfg, genesis=genesis,
                priv_validator=MockPV(priv) if priv else None,
                node_key=NodeKey(ed25519.gen_priv_key(
                    bytes([sum(name.encode()) % 200 + 1]) * 32)))


def test_cold_node_fast_syncs_50_heights(tmp_path):
    """The VERDICT criterion: a cold node with fast_sync_mode=True syncs 50+
    heights over real sockets, then switches to consensus and keeps up."""
    privs = [ed25519.gen_priv_key(bytes([50 + i]) * 32) for i in range(2)]
    genesis = GenesisDoc(
        chain_id="fs-chain", genesis_time=Time(1700002000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    n0 = _mk_node(tmp_path, "v0", genesis, privs[0])
    n1 = _mk_node(tmp_path, "v1", genesis, privs[1])
    n0.start()
    n1.start()
    late = None
    try:
        assert n1.switch.dial_peer(n0.p2p_addr()) is not None
        # build 50+ heights of history
        assert _wait(lambda: n0.block_store.height >= 52, 120), n0.block_store.height

        late = _mk_node(tmp_path, "late", genesis, priv=None, fast_sync=True,
                        persistent_peers=",".join([n0.p2p_addr(), n1.p2p_addr()]))
        t0 = time.monotonic()
        late.start()
        assert _wait(lambda: late.block_store.height >= 50, 90), late.block_store.height
        sync_time = time.monotonic() - t0
        # the synced chain is byte-identical to the source
        for h in (1, 25, 50):
            assert late.block_store.load_block(h).hash() == \
                n0.block_store.load_block(h).hash()
        # switched to consensus: keeps committing new heights live
        assert _wait(late.bc_reactor._synced.is_set, 60)
        tip = n0.block_store.height
        assert _wait(lambda: late.block_store.height >= tip + 2, 60), (
            late.block_store.height, n0.block_store.height)
        # sanity: syncing 50 blocks must be much faster than consensus made them
        assert sync_time < 60, sync_time
    finally:
        if late is not None:
            late.stop()
        n0.stop()
        n1.stop()


def test_no_peer_bailout_waits_when_peers_configured(tmp_path):
    """A cold node with persistent peers configured must NOT silently skip
    fast sync after 3s (blockchain/reactor.py bailout guard)."""
    privs = [ed25519.gen_priv_key(bytes([60 + i]) * 32) for i in range(2)]
    genesis = GenesisDoc(
        chain_id="fs2-chain", genesis_time=Time(1700002000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    # peer address that is not up yet
    lone = _mk_node(tmp_path, "lone", genesis, priv=None, fast_sync=True,
                    persistent_peers="deadbeef@127.0.0.1:1")
    lone.start()
    try:
        time.sleep(4.0)
        assert not lone.bc_reactor._synced.is_set()  # still waiting, not bailed
    finally:
        lone.stop()


FAIL_SITES = [10, 11, 12, 13, 14]  # 5 sites in the THIRD block's finalize


@pytest.mark.parametrize("fail_index", FAIL_SITES)
def test_crash_recovery_matrix(tmp_path, fail_index):
    """Kill the node at each commit fail site, restart, and assert the
    replayed state is consistent: block store, state store, and the
    handshake-replayed app all agree (reference: consensus/replay_test.go).
    This also exercises the mock-app replay branch and WAL catchup."""
    root = str(tmp_path / f"crash{fail_index}")
    env = {**os.environ, "TMTPU_FAIL_INDEX": str(fail_index),
           "JAX_PLATFORMS": "cpu"}
    crash = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "crash_node.py"),
         root, "crash", "0"],
        env=env, capture_output=True, timeout=120)
    assert crash.returncode == 1, (crash.returncode, crash.stderr[-500:])

    env.pop("TMTPU_FAIL_INDEX")
    recover = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "crash_node.py"),
         root, "recover", "6"],
        env=env, capture_output=True, timeout=180)
    assert recover.returncode == 0, recover.stderr[-2000:]
    doc = json.loads(recover.stdout.strip().splitlines()[-1])
    # all three state surfaces agree after recovery + catch-up
    assert doc["height"] >= 6
    assert doc["state_height"] == doc["height"]
    assert doc["app_height"] == doc["height"]
    assert doc["app_hash"] == doc["state_app_hash"]


def test_fastsync_v1_cold_node_catches_up(tmp_path):
    """The event-driven v1 FSM syncs a cold node over real sockets and hands
    off to consensus (reference: blockchain/v1/reactor_fsm.go)."""
    privs = [ed25519.gen_priv_key(bytes([55 + i]) * 32) for i in range(2)]
    genesis = GenesisDoc(
        chain_id="fsv1-chain", genesis_time=Time(1700002000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    n0 = _mk_node(tmp_path, "w0", genesis, privs[0])
    n1 = _mk_node(tmp_path, "w1", genesis, privs[1])
    n0.start()
    n1.start()
    late = None
    try:
        assert n1.switch.dial_peer(n0.p2p_addr()) is not None
        assert _wait(lambda: n0.block_store.height >= 22, 90), n0.block_store.height

        cfg = make_test_config()
        cfg.set_root(str(tmp_path / "late-v1"))
        os.makedirs(cfg.base.root_dir, exist_ok=True)
        cfg.base.fast_sync_mode = True
        cfg.fastsync.version = "v1"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.pex = False
        cfg.p2p.persistent_peers = ",".join([n0.p2p_addr(), n1.p2p_addr()])
        cfg.rpc.laddr = ""
        cfg.consensus.wal_path = ""
        late = Node(cfg, genesis=genesis, priv_validator=None,
                    node_key=NodeKey(ed25519.gen_priv_key(b"\x59" * 32)))
        from tendermint_tpu.blockchain.v1 import BlockchainReactorV1
        assert isinstance(late.bc_reactor, BlockchainReactorV1)
        late.start()
        assert _wait(lambda: late.block_store.height >= 20, 90), late.block_store.height
        assert late.block_store.load_block(10).hash() == \
            n0.block_store.load_block(10).hash()
        # FSM finished and handed off to consensus; keeps up live
        assert _wait(late.bc_reactor._synced.is_set, 60)
        tip = n0.block_store.height
        assert _wait(lambda: late.block_store.height >= tip + 2, 60)
    finally:
        if late is not None:
            late.stop()
        n0.stop()
        n1.stop()


def test_fastsync_v2_cold_node_catches_up(tmp_path):
    """The routine-based v2 scheduler/processor syncs a cold node over real
    sockets and hands off to consensus (reference: blockchain/v2/
    scheduler.go, processor.go)."""
    privs = [ed25519.gen_priv_key(bytes([65 + i]) * 32) for i in range(2)]
    genesis = GenesisDoc(
        chain_id="fsv2-chain", genesis_time=Time(1700002000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    n0 = _mk_node(tmp_path, "x0", genesis, privs[0])
    n1 = _mk_node(tmp_path, "x1", genesis, privs[1])
    n0.start()
    n1.start()
    late = None
    try:
        assert n1.switch.dial_peer(n0.p2p_addr()) is not None
        assert _wait(lambda: n0.block_store.height >= 22, 90), n0.block_store.height

        cfg = make_test_config()
        cfg.set_root(str(tmp_path / "late-v2"))
        os.makedirs(cfg.base.root_dir, exist_ok=True)
        cfg.base.fast_sync_mode = True
        cfg.fastsync.version = "v2"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.pex = False
        cfg.p2p.persistent_peers = ",".join([n0.p2p_addr(), n1.p2p_addr()])
        cfg.rpc.laddr = ""
        cfg.consensus.wal_path = ""
        late = Node(cfg, genesis=genesis, priv_validator=None,
                    node_key=NodeKey(ed25519.gen_priv_key(b"\x69" * 32)))
        from tendermint_tpu.blockchain.v2 import BlockchainReactorV2
        assert isinstance(late.bc_reactor, BlockchainReactorV2)
        late.start()
        assert _wait(lambda: late.block_store.height >= 20, 90), late.block_store.height
        assert late.block_store.load_block(12).hash() == \
            n0.block_store.load_block(12).hash()
        assert _wait(late.bc_reactor._synced.is_set, 60)
        tip = n0.block_store.height
        assert _wait(lambda: late.block_store.height >= tip + 2, 60)
    finally:
        if late is not None:
            late.stop()
        n0.stop()
        n1.stop()


def test_fastsync_v2_scheduler_unit():
    """Scheduler planning: request fan-out, timeout retry, invalid-block
    peer drop, finish detection (reference: scheduler_test.go shapes)."""
    from tendermint_tpu.blockchain.v2 import (
        EvBlockInvalid,
        EvBlockProcessed,
        EvBlockResponse,
        EvRemovePeer,
        EvStatus,
        EvTick,
        Scheduler,
    )

    s = Scheduler(initial_height=5)
    acts = s.handle(EvStatus("pA", 1, 10))
    reqs = [a for a in acts if a[0] == "request"]
    assert reqs and all(5 <= a[2] <= 10 for a in reqs)
    assert all(a[1] == "pA" for a in reqs)

    # a second peer shares the load for new heights
    s.handle(EvStatus("pB", 1, 12))
    class _B:  # minimal block stand-in
        def __init__(self, h):
            self.header = type("H", (), {"height": h})()
    s.handle(EvBlockResponse("pA", _B(5)))
    assert 5 in s.received and 5 not in s.pending

    # processed advances the window
    acts = s.handle(EvBlockProcessed(5))
    assert s.height == 6 and not any(a[0] == "finished" for a in acts)

    # invalid block drops the peer
    acts = s.handle(EvBlockInvalid(6, "pA"))
    assert ("drop_peer", "pA", "invalid block") in acts
    s.handle(EvRemovePeer("pA"))
    assert "pA" not in s.peers

    # timeout requeues: pretend a pending request is ancient
    h, (p, _) = next(iter(s.pending.items()))
    s.pending[h] = (p, 0.0)
    s.handle(EvTick())
    assert h in s.pending  # re-scheduled (possibly to the same surviving peer)

    # finishing: processed past every peer's top
    s.peers = {"pB": (1, 6)}
    s.pending.clear()
    s.received.clear()
    acts = s.handle(EvBlockProcessed(6))
    assert ("finished",) in acts
