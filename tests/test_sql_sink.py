"""SQL event sink (state/sql_sink.py) vs the reference psql sink semantics
(state/indexer/sink/psql/{psql.go,backport.go})."""

import json
import sqlite3

import pytest

from tendermint_tpu.abci.types import Event, EventAttribute, ResponseDeliverTx
from tendermint_tpu.state.sql_sink import SqlEventSink, connect
from tendermint_tpu.types.tx import tx_hash

def _sink():
    return SqlEventSink(sqlite3.connect(":memory:"), "test-chain")


def _ev(etype, **attrs):
    return Event(type=etype, attributes=[
        EventAttribute(key=k.encode(), value=v.encode(), index=True)
        for k, v in attrs.items()])


def test_block_events_rows_and_views():
    s = _sink()
    s.index_block_events(5, [_ev("begin", phase="b")], [_ev("end", phase="e")])
    cur = s._conn.cursor()
    cur.execute("SELECT height, chain_id FROM blocks")
    assert cur.fetchall() == [(5, "test-chain")]
    # block_events view: the block.height meta-event plus both app events,
    # all with tx_id NULL (psql.go:161-171).
    cur.execute("SELECT type, composite_key, value FROM block_events")
    rows = set(cur.fetchall())
    assert ("block", "block.height", "5") in rows
    assert ("begin", "begin.phase", "b") in rows
    assert ("end", "end.phase", "e") in rows


def test_duplicate_block_quietly_succeeds():
    s = _sink()
    s.index_block_events(5, [], [])
    s.index_block_events(5, [_ev("x", a="1")], [])  # duplicate: no-op
    cur = s._conn.cursor()
    cur.execute("SELECT COUNT(*) FROM blocks")
    assert cur.fetchone()[0] == 1
    cur.execute("SELECT COUNT(*) FROM events")
    assert cur.fetchone()[0] == 1  # only the first insert's meta-event


def test_tx_events_and_meta_rows():
    s = _sink()
    s.index_block_events(7, [], [])
    res = ResponseDeliverTx(code=0, events=[_ev("transfer", amount="10")])
    s.index_tx(7, 0, b"tx-bytes", res)
    cur = s._conn.cursor()
    cur.execute("SELECT tx_hash, tx_result FROM tx_results")
    h, raw = cur.fetchone()
    assert h == tx_hash(b"tx-bytes").hex().upper()
    doc = json.loads(raw)
    assert doc["height"] == "7"
    assert doc["tx_result"]["events"][0]["type"] == "transfer"
    # tx_events view carries the hash/height meta-events + app event
    # (psql.go:214-222).
    cur.execute("SELECT composite_key, value FROM tx_events")
    rows = set(cur.fetchall())
    assert ("tx.hash", h) in rows
    assert ("tx.height", "7") in rows
    assert ("transfer.amount", "10") in rows


def test_tx_before_block_errors():
    s = _sink()
    with pytest.raises(ValueError, match="must be indexed before"):
        s.index_tx(3, 0, b"t", ResponseDeliverTx())


def test_duplicate_tx_quietly_succeeds():
    s = _sink()
    s.index_block_events(7, [], [])
    s.index_tx(7, 0, b"t", ResponseDeliverTx())
    s.index_tx(7, 0, b"t", ResponseDeliverTx())
    cur = s._conn.cursor()
    cur.execute("SELECT COUNT(*) FROM tx_results")
    assert cur.fetchone()[0] == 1


def test_unindexed_attributes_and_empty_types_skipped():
    s = _sink()
    ev = Event(type="t", attributes=[
        EventAttribute(key=b"k", value=b"v", index=False)])
    s.index_block_events(1, [ev, Event(type="")], [])
    cur = s._conn.cursor()
    cur.execute("SELECT COUNT(*) FROM attributes WHERE composite_key='t.k'")
    assert cur.fetchone()[0] == 0
    cur.execute("SELECT COUNT(*) FROM events WHERE type=''")
    assert cur.fetchone()[0] == 0


def test_backport_adapters_write_only():
    s = _sink()
    txi, bli = s.tx_indexer(), s.block_indexer()
    s.index_block_events(2, [], [])
    txi.index(2, 0, b"via-adapter", ResponseDeliverTx())
    bli.index(2, [], [])  # duplicate block: quiet no-op through the adapter
    for fn in (lambda: txi.get(b"\x00"), lambda: txi.search("tx.height=2"),
               lambda: bli.has(2), lambda: bli.search("block.height=2")):
        with pytest.raises(ValueError, match="not supported"):
            fn()


def test_connect_sqlite_scheme(tmp_path):
    conn = connect(f"sqlite:{tmp_path}/sink.db")
    s = SqlEventSink(conn, "c")
    s.index_block_events(1, [], [])
    s.stop()
    # reopen: schema + row persisted
    conn2 = connect(f"sqlite:{tmp_path}/sink.db")
    s2 = SqlEventSink(conn2, "c")
    cur = s2._conn.cursor()
    cur.execute("SELECT height FROM blocks")
    assert cur.fetchone() == (1,)
    s2.stop()


def _psql_node(tmp_path, conn_str):
    import os

    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import MockPV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    priv = ed25519.gen_priv_key(b"\x93" * 32)
    genesis = GenesisDoc(
        chain_id="sink-chain", genesis_time=Time(1700004000, 0),
        validators=[GenesisValidator(b"", priv.pub_key(), 10)],
    )
    cfg = test_config()
    cfg.set_root(str(tmp_path / "node"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = ""
    cfg.tx_index.indexer = "psql"
    cfg.tx_index.psql_conn = conn_str
    return Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x94" * 32))), priv


def test_node_with_sql_sink(tmp_path):
    """A live node on the psql indexer writes blocks+txs to the SQL store
    and serves 'not supported' for search RPCs (reference:
    node/node.go:282-299 + backport.go)."""
    import time as _time

    db_path = tmp_path / "sink.db"
    node, _ = _psql_node(tmp_path, f"sqlite:{db_path}")
    node.start()
    try:
        node.mempool.check_tx(b"sunk=yes")
        h = tx_hash(b"sunk=yes").hex().upper()
        reader = sqlite3.connect(db_path)
        deadline = _time.monotonic() + 60
        row = None
        while _time.monotonic() < deadline and row is None:
            row = reader.execute(
                "SELECT tx_hash FROM tx_results WHERE tx_hash=?",
                (h,)).fetchone()
            _time.sleep(0.1)
        assert row == (h,)
        assert reader.execute("SELECT COUNT(*) FROM blocks").fetchone()[0] >= 1
        metas = set(reader.execute(
            "SELECT composite_key FROM tx_events").fetchall())
        assert ("tx.hash",) in metas and ("tx.height",) in metas
        with pytest.raises(ValueError, match="not supported"):
            node.tx_indexer.search("tx.height>0")
    finally:
        node.stop()


def test_node_wiring_requires_conn_string(tmp_path):
    """reference: node/node.go:284 errors when PsqlConn is empty."""
    with pytest.raises(ValueError, match="psql_conn"):
        _psql_node(tmp_path, "")


# ---------------------------------------------------------------------------
# Postgres dialect (r4 verdict missing #3): a fake psycopg-shaped driver
# pins the psycopg2 code path — %s placeholders, BIGSERIAL/BYTEA DDL,
# CREATE OR REPLACE VIEW — without a postgres server. Statements are
# captured for shape assertions, then translated to sqlite to prove the
# emitted SQL is internally consistent end to end.
# ---------------------------------------------------------------------------


class _FakePgCursor:
    def __init__(self, cur, log):
        self._cur = cur
        self._log = log
        self._returned = None

    @staticmethod
    def _translate(q):
        return (q.replace("%s", "?")
                 .replace("BIGSERIAL PRIMARY KEY",
                          "INTEGER PRIMARY KEY AUTOINCREMENT")
                 .replace("BYTEA", "BLOB")
                 .replace("CREATE OR REPLACE VIEW",
                          "CREATE VIEW IF NOT EXISTS"))

    def execute(self, q, params=()):
        self._log.append(q)
        self._returned = None
        t = self._translate(q)
        if " RETURNING rowid" in t:
            # A real postgres serves RETURNING natively; the sqlite backing
            # this fake may predate 3.35, so emulate it from lastrowid
            # (None when ON CONFLICT DO NOTHING swallowed a duplicate).
            self._cur.execute(t.replace(" RETURNING rowid", ""), params)
            if self._cur.rowcount != 0:
                self._returned = (self._cur.lastrowid,)
            return self._cur
        return self._cur.execute(t, params)

    def fetchone(self):
        if self._returned is not None:
            row, self._returned = self._returned, None
            return row
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()


class _FakePgConnection:
    """type(conn).__module__ starts with 'psycopg' via __class__ rebinding
    below — exactly the property SqlEventSink dispatches the dialect on."""

    def __init__(self):
        self._db = sqlite3.connect(":memory:")
        self.statements = []

    def cursor(self):
        return _FakePgCursor(self._db.cursor(), self.statements)

    def commit(self):
        self._db.commit()

    def rollback(self):
        self._db.rollback()


# rebind the class into a psycopg-looking module namespace
_FakePgConnection.__module__ = "psycopg2_fake"


def test_postgres_dialect_shapes():
    conn = _FakePgConnection()
    sink = SqlEventSink(conn, "pg-chain")
    assert sink._pg and sink._ph == "%s"

    sink.index_block_events(9, [_ev("begin", foo="1")], [])
    res = ResponseDeliverTx(code=0, events=[_ev("transfer", sender="bob")])
    sink.index_tx(9, 0, b"pgtx", res)

    ddl = "\n".join(conn.statements[:20])
    assert "BIGSERIAL PRIMARY KEY" in ddl
    assert "BYTEA" in ddl
    assert "CREATE OR REPLACE VIEW" in ddl
    assert "AUTOINCREMENT" not in ddl
    dml = [q for q in conn.statements if q.lstrip().startswith(("INSERT",
                                                                "SELECT"))]
    assert dml, "no DML captured"
    for q in dml:
        assert "?" not in q, f"sqlite placeholder leaked into pg SQL: {q}"
    assert any("%s" in q for q in dml)

    # the emitted SQL is consistent end to end: rows landed via translation
    cur = conn.cursor()
    cur.execute("SELECT height, chain_id FROM blocks")
    assert cur.fetchall() == [(9, "pg-chain")]
    cur.execute("SELECT type FROM events ORDER BY rowid")
    types = [r[0] for r in cur.fetchall()]
    assert "begin" in types and "transfer" in types


# ---------------------------------------------------------------------------
# Per-height transaction batching (SqlEventSink.height_txn + the kv
# TxIndexer analogue the IndexerService drives through the same seam).
# ---------------------------------------------------------------------------


class _CountingConnection:
    """sqlite3 connection wrapper counting commit/rollback round-trips."""

    def __init__(self):
        self._db = sqlite3.connect(":memory:")
        self.commits = 0
        self.rollbacks = 0

    def cursor(self):
        return self._db.cursor()

    def commit(self):
        self.commits += 1
        self._db.commit()

    def rollback(self):
        self.rollbacks += 1
        self._db.rollback()


def test_height_txn_commits_once_per_height():
    conn = _CountingConnection()
    s = SqlEventSink(conn, "batch-chain")
    base = conn.commits  # schema setup
    res = ResponseDeliverTx(code=0, events=[_ev("transfer", n="1")])
    with s.height_txn():
        s.index_block_events(3, [_ev("begin", p="b")], [])
        s.index_tx(3, 0, b"t0", res)
        s.index_tx(3, 1, b"t1", res)
        assert conn.commits == base, "postings must not commit mid-height"
    assert conn.commits == base + 1  # ONE commit for the whole height
    cur = conn.cursor()
    cur.execute("SELECT COUNT(*) FROM tx_results")
    assert cur.fetchone()[0] == 2


def test_height_txn_duplicate_keeps_earlier_postings():
    """The quiet-duplicate early return must unwind only its own savepoint,
    not the height's earlier staged rows."""
    s = _sink()
    s.index_block_events(4, [], [])  # pre-existing height
    with s.height_txn():
        s.index_block_events(5, [_ev("begin", p="b")], [])
        s.index_block_events(4, [_ev("dup", a="1")], [])  # duplicate: no-op
        s.index_tx(5, 0, b"tx5", ResponseDeliverTx(code=0, events=[]))
    cur = s._conn.cursor()
    cur.execute("SELECT COUNT(*) FROM blocks")
    assert cur.fetchone()[0] == 2
    cur.execute("SELECT COUNT(*) FROM tx_results")
    assert cur.fetchone()[0] == 1
    cur.execute("SELECT COUNT(*) FROM events WHERE type = 'dup'")
    assert cur.fetchone()[0] == 0


def test_height_txn_failed_call_unwinds_only_itself():
    s = _sink()
    with s.height_txn():
        s.index_block_events(6, [_ev("begin", p="b")], [])
        with pytest.raises(ValueError):
            # no block row at height 99 -> the call fails and its
            # savepoint rolls back; height 6's rows stay staged
            s.index_tx(99, 0, b"orphan", None)
        s.index_tx(6, 0, b"ok", ResponseDeliverTx(code=0, events=[]))
    cur = s._conn.cursor()
    cur.execute("SELECT COUNT(*) FROM blocks")
    assert cur.fetchone()[0] == 1
    cur.execute("SELECT COUNT(*) FROM tx_results")
    assert cur.fetchone()[0] == 1


def test_height_txn_escaping_exception_discards_height():
    conn = _CountingConnection()
    s = SqlEventSink(conn, "rb-chain")
    with pytest.raises(RuntimeError):
        with s.height_txn():
            s.index_block_events(7, [], [])
            raise RuntimeError("boom")
    cur = conn.cursor()
    cur.execute("SELECT COUNT(*) FROM blocks")
    assert cur.fetchone()[0] == 0
    assert conn.rollbacks >= 1


def test_height_txn_is_reentrant_across_backport_adapters():
    """IndexerService enters the seam via BOTH backport adapters of one
    sink; the commit must happen exactly once, at the outermost exit."""
    conn = _CountingConnection()
    s = SqlEventSink(conn, "reent-chain")
    base = conn.commits
    with s.block_indexer().height_txn():
        with s.tx_indexer().height_txn():
            s.index_block_events(8, [], [])
            assert conn.commits == base
        assert conn.commits == base, "inner exit must not commit"
    assert conn.commits == base + 1


def test_kv_tx_indexer_height_txn_batches_store_writes():
    from tendermint_tpu.state.txindex import TxIndexer
    from tendermint_tpu.store.db import MemDB

    db = MemDB()
    calls = []
    orig = db.write_batch

    def counting(sets):
        calls.append(len(list(sets)))
        return orig(sets)

    db.write_batch = counting
    ti = TxIndexer(db)
    res = ResponseDeliverTx(code=0, events=[_ev("transfer", n="1")])
    with ti.height_txn():
        ti.index(9, 0, b"a", res)
        ti.index(9, 1, b"b", res)
        assert calls == [], "staged postings must not hit the store yet"
    assert len(calls) == 1, "one write_batch per height"
    assert ti.get(tx_hash(b"a")) is not None
    assert ti.get(tx_hash(b"b")) is not None
    # outside the context, per-tx writes are unchanged
    ti.index(10, 0, b"c", res)
    assert len(calls) == 2
