"""Nemesis layer: peer-scoped link faults, partitions, the consensus stall
watchdog, and the partition scenario matrix (reference: the perturbation
dimension of test/e2e/ — but cutting LINKS, not processes).

Quick tier (every `-m 'not slow'` run): grammar/plane units, MConnection
integration, watchdog unit, reconnect-backoff reset, and one 3-node
in-process partition/heal round — the chaos plane can never silently rot.

Slow tier: the scenario matrix on 4 in-process nodes — even 2|2 split
(safety: zero forks, no commits while split; liveness after heal),
minority partition (the isolated node's watchdog hands it back to
fast-sync catchup, no process restart), and an equivocator inside the
minority side of a partition (buffered DuplicateVoteEvidence still
commits after heal).

Every scenario failure prints the exact TMTPU_FAULTS / TMTPU_FAULT_SEED /
TMTPU_NEMESIS repro line.
"""

import contextlib
import os
import time
import urllib.request

import pytest

from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.utils import faults, lockwitness, nemesis

SEED = 2026

STATE_CH, DATA_CH, VOTE_CH = 0x20, 0x21, 0x22


@pytest.fixture(autouse=True)
def _clean_chaos():
    faults.configure([], seed=SEED)
    nemesis.clear()
    yield
    nemesis.clear()
    # stopped switches deregister themselves; anything left is a dead
    # listener from a failed teardown and must not leak across tests
    nemesis.PLANE.on_heal.clear()
    faults.clear()


@contextlib.contextmanager
def repro(scenario: str, nemesis_desc: str = ""):
    """On any scenario failure, print the exact env repro line."""
    try:
        yield
    except BaseException as e:
        line = (f"repro: TMTPU_FAULT_SEED={faults.REGISTRY.seed} "
                f"TMTPU_FAULTS={os.environ.get('TMTPU_FAULTS', '')!r} "
                f"TMTPU_NEMESIS={nemesis_desc or os.environ.get('TMTPU_NEMESIS', '')!r}")
        raise AssertionError(f"[{scenario}] {e}\n{line}") from e


# ---------------------------------------------------------------------------
# Grammar + plane units (quick)
# ---------------------------------------------------------------------------


def test_link_rule_grammar():
    r = nemesis.LinkRule.parse("ab>*:drop%0.5")
    assert (r.src, r.dst, r.action, r.prob) == ("ab", "*", "drop", 0.5)
    r = nemesis.LinkRule.parse("*>cd:delay~0.05")
    assert r.action == "delay" and r.param == 0.05 and r.ch is None
    r = nemesis.LinkRule.parse("a>b:drop#0x22")
    assert r.ch == 0x22
    r = nemesis.LinkRule.parse("a>b:drop%0.5#34")
    assert r.ch == 34 and r.prob == 0.5
    for bad in ("", "a:drop", "a>b:frobnicate", "a>:drop", ">b:drop"):
        with pytest.raises(ValueError):
            nemesis.LinkRule.parse(bad)


def test_env_grammar_statements():
    nemesis.configure("partition=aa/bb|cc,link=aa>cc:drop%0.5,link=*>*:delay~0.01")
    d = nemesis.PLANE.describe()
    assert d["active"] and d["partition"] == [["aa", "bb"], ["cc"]]
    assert "aa>cc:drop%0.5" in d["links"]
    with pytest.raises(ValueError):
        nemesis.configure("frob=1")


def test_env_install_keeps_programmatic_plane(monkeypatch):
    """Node.start() reloads env config; with nothing in the env it must
    not wipe a plane installed in-process (the in-process test harness)."""
    monkeypatch.delenv("TMTPU_NEMESIS", raising=False)
    nemesis.partition([["aa"], ["bb"]])
    nemesis.install_from_env()
    with pytest.raises(faults.FaultDisconnect):
        nemesis.outcome("p2p.send", "aa1", "bb2")
    monkeypatch.setenv("TMTPU_NEMESIS", "link=aa>bb:dup")
    nemesis.install_from_env()  # explicit env spec wins
    assert nemesis.outcome("p2p.send", "aa1", "bb2") == "dup"


def test_partition_cut_heal_and_listeners():
    nemesis.partition([["aa", "bb"], ["cc"]])
    # a partition SEVERS crossing links (teardown, not silent loss — silent
    # drops would poison gossip has-vote bookkeeping past the heal)
    with pytest.raises(faults.FaultDisconnect):
        nemesis.outcome("p2p.send", "aaXX", "ccYY")
    with pytest.raises(faults.FaultDisconnect):
        nemesis.outcome("p2p.recv", "ccYY", "aaXX")
    assert nemesis.outcome("p2p.send", "aaXX", "bbZZ") == "pass"
    assert nemesis.outcome("p2p.send", "aaXX", "dd00") == "pass"  # unlisted
    with pytest.raises(faults.FaultInjected):
        nemesis.outcome("p2p.dial", "aa11", "cc22")  # dial refused
    healed = []
    nemesis.PLANE.on_heal.append(lambda: healed.append(1))
    try:
        nemesis.heal()
    finally:
        nemesis.PLANE.on_heal.clear()
    assert healed == [1]
    assert nemesis.outcome("p2p.send", "aaXX", "ccYY") == "pass"


def test_heal_timer_from_env_grammar():
    nemesis.configure("partition=aa|bb,heal@0.15")
    with pytest.raises(faults.FaultDisconnect):
        nemesis.outcome("p2p.send", "aa1", "bb1")
    deadline = time.monotonic() + 5
    while nemesis.PLANE.active and time.monotonic() < deadline:
        time.sleep(0.01)
    assert nemesis.outcome("p2p.send", "aa1", "bb1") == "pass"


def test_link_rule_direction_asymmetry_and_channel_scope():
    # asymmetric: only n1 -> n2 messages drop
    nemesis.add_link("n1>n2:drop")
    assert nemesis.outcome("p2p.send", "n1", "n2") == "drop"
    assert nemesis.outcome("p2p.recv", "n2", "n1") == "drop"  # delivered at n2
    assert nemesis.outcome("p2p.send", "n2", "n1") == "pass"  # reverse flows
    assert nemesis.outcome("p2p.recv", "n1", "n2") == "pass"
    nemesis.clear()
    # channel-scoped: only the vote channel starves
    nemesis.add_link(f"*>n3:drop#{VOTE_CH:#x}")
    assert nemesis.outcome("p2p.recv", "n3", "n0", channel=VOTE_CH) == "drop"
    assert nemesis.outcome("p2p.recv", "n3", "n0", channel=0x40) == "pass"
    assert nemesis.outcome("p2p.dial", "n0", "n3") == "pass"  # no channel


def test_prob_link_decisions_replay_from_seed():
    faults.configure([], seed=42)
    nemesis.add_link("*>*:drop%0.4")
    seq1 = [nemesis.outcome("p2p.send", "n1", "n2") for _ in range(100)]
    assert "drop" in seq1 and "pass" in seq1
    nemesis.PLANE.reset_counters()
    assert [nemesis.outcome("p2p.send", "n1", "n2") for _ in range(100)] == seq1
    # decisions are per-link: another link's traffic can't perturb them
    nemesis.PLANE.reset_counters()
    inter = []
    for _ in range(100):
        nemesis.outcome("p2p.send", "n9", "n2")
        inter.append(nemesis.outcome("p2p.send", "n1", "n2"))
    assert inter == seq1
    # a different seed gives a different schedule
    faults.configure([], seed=43)
    nemesis.PLANE.reset_counters()
    assert [nemesis.outcome("p2p.send", "n1", "n2") for _ in range(100)] != seq1


def test_remove_link_removes_exactly_one_rule():
    """The soak driver expires scheduled faults by removing the exact rule
    it installed: overlapping faults keep theirs, a standing partition
    keeps the plane active, and removal is idempotent."""
    r1 = nemesis.add_link("a>b:drop")
    r2 = nemesis.add_link("a>b:dup")
    nemesis.remove_link(r1)
    assert nemesis.outcome("p2p.send", "a", "b") == "dup"  # r2 untouched
    nemesis.partition([["a"], ["b"]])
    nemesis.remove_link(r2)
    assert nemesis.PLANE.active  # the partition still holds the plane on
    nemesis.remove_link(r2)  # idempotent
    nemesis.heal()
    assert not nemesis.PLANE.active


def test_dup_at_dial_fails_loudly():
    nemesis.add_link("*>*:dup")
    with pytest.raises(faults.FaultError):
        nemesis.outcome("p2p.dial", "a", "b")


def test_fire_with_peer_context_consults_plane():
    nemesis.partition([["aa"], ["bb"]])
    with pytest.raises(faults.FaultInjected):
        faults.fire("p2p.dial", local="aa1", remote="bb1")
    faults.fire("p2p.dial", local="aa1", remote="aa2")  # same side: fine
    faults.fire("p2p.dial")  # no context: plane not consulted


# ---------------------------------------------------------------------------
# unsafe_nemesis RPC route (quick) — the e2e runner's partition/heal driver
# ---------------------------------------------------------------------------


def test_unsafe_nemesis_rpc_route():
    from tendermint_tpu.rpc import core as rpc_core

    class _Cfg:
        class rpc:
            unsafe = True

    class _Node:
        config = _Cfg()

    class _Env:
        node = _Node()

    env = _Env()
    out = rpc_core.unsafe_nemesis(env, partition=[["aa"], ["bb"]])
    assert out["active"] and out["partition"] == [["aa"], ["bb"]]
    with pytest.raises(faults.FaultDisconnect):
        nemesis.outcome("p2p.send", "aa1", "bb1")
    out = rpc_core.unsafe_nemesis(env, heal=True,
                                  links=["aa>bb:delay~0.001"])
    assert out["partition"] == [] and out["links"] == ["aa>bb:delay~0.001"]
    assert nemesis.outcome("p2p.send", "aa1", "bb1") == "pass"  # delay only
    with pytest.raises(ValueError):
        rpc_core.unsafe_nemesis(env, partition=["not-a-group"])
    with pytest.raises(ValueError):
        rpc_core.unsafe_nemesis(env, links="not-a-list")
    env.node.config.rpc.unsafe = False
    with pytest.raises(ValueError, match="unsafe"):
        rpc_core.unsafe_nemesis(env, heal=True)


# ---------------------------------------------------------------------------
# MConnection integration (quick)
# ---------------------------------------------------------------------------


class _FakeConn:
    closed = False

    def close(self):
        self.closed = True


def _mk_mconn(local, remote, received=None, errors=None):
    from tendermint_tpu.p2p.connection import ChannelDescriptor, MConnection

    mc = MConnection(
        _FakeConn(), [ChannelDescriptor(id=1)],
        on_receive=(lambda ch, msg: received.append((ch, msg)))
        if received is not None else (lambda *a: None),
        on_error=errors.append if errors is not None else None,
        local_id=local, remote_id=remote)
    mc._running = True  # armed without spawning the socket threads
    return mc


def test_mconnection_send_partition_severs_and_dup():
    errors = []
    nemesis.partition([["aaa"], ["bbb"]])
    mc = _mk_mconn("aaa1", "bbb1", errors=errors)
    assert mc.send(1, b"x") is False  # crossing message severs the link
    assert errors and isinstance(errors[0], faults.FaultDisconnect)
    assert mc._conn.closed and not mc._running
    nemesis.clear()
    nemesis.add_link("aaa>bbb:drop")  # a plain drop RULE stays silent loss
    mc2 = _mk_mconn("aaa1", "bbb1")
    assert mc2.send(1, b"y") is True
    assert mc2._channels[1].send_queue.empty()
    nemesis.clear()
    nemesis.add_link("aaa>bbb:dup")
    mc3 = _mk_mconn("aaa1", "bbb1")
    assert mc3.send(1, b"z") is True
    assert mc3._channels[1].send_queue.qsize() == 2  # duplicated on the wire


def test_mconnection_disconnect_rule_tears_down():
    errors = []
    nemesis.add_link("aaa>bbb:disconnect")
    mc = _mk_mconn("aaa1", "bbb1", errors=errors)
    assert mc.send(1, b"gossip") is False  # no exception into the sender
    assert errors and isinstance(errors[0], faults.FaultDisconnect)
    assert mc._conn.closed and not mc._running


# ---------------------------------------------------------------------------
# Watchdog unit (quick)
# ---------------------------------------------------------------------------


class _WDHarness:
    """Stub node surface for ConsensusWatchdog."""

    class _Store:
        height = 5

    class _CR:
        wait_sync = False
        _peer_states = {}

    class _Pool:
        def __init__(self):
            self.h = 0

        def max_peer_height(self):
            return self.h

    class _BCR:
        def __init__(self):
            self.pool = _WDHarness._Pool()
            self.switch = None

    def __init__(self, stall_s=0.2):
        from tendermint_tpu.config.config import ConsensusConfig

        self.config = ConsensusConfig(watchdog_stall_multiple=1.0)
        self._stall_s = stall_s
        self.config.watchdog_stall_s = lambda: self._stall_s
        self.store = self._Store()
        self.cr = self._CR()
        self.bcr = self._BCR()
        self.recovered = []

    def watchdog(self, **kw):
        from tendermint_tpu.consensus.watchdog import ConsensusWatchdog

        return ConsensusWatchdog(
            self.config, self.store, self.cr, self.bcr,
            lambda: self.recovered.append(self.store.height),
            check_interval_s=0.02, **kw)


def _wait(cond, timeout, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_watchdog_fires_only_with_stall_and_peer_lead():
    h = _WDHarness(stall_s=0.1)
    wd = h.watchdog()
    wd.start()
    try:
        # stalled but no peer lead: never recovers, reports stalled
        assert not _wait(lambda: h.recovered, 0.5)
        assert wd.stalled
        # peers pull ahead: recovery fires
        h.bcr.pool.h = h.store.height + 2
        assert _wait(lambda: h.recovered, 5.0)
        assert wd.recoveries == 1
    finally:
        wd.stop()


def test_watchdog_quiet_while_progressing_or_syncing():
    h = _WDHarness(stall_s=0.1)
    h.bcr.pool.h = 100  # peers far ahead the whole time
    wd = h.watchdog()
    wd.start()
    try:
        # steady progress: no recovery
        for _ in range(10):
            h.store.height += 1
            time.sleep(0.04)
        assert not h.recovered
        # stalled but already in a sync (wait_sync): the sync owns recovery
        h.cr.wait_sync = True
        assert not _wait(lambda: h.recovered, 0.4)
    finally:
        wd.stop()


def test_watchdog_disabled_by_zero_multiple():
    h = _WDHarness()
    h.config.watchdog_stall_multiple = 0.0
    wd = h.watchdog()
    wd.start()
    assert wd._thread is None  # never armed


# ---------------------------------------------------------------------------
# Reconnect backoff state (quick) — the healed-link redial bugfix
# ---------------------------------------------------------------------------


def test_reconnect_backoff_resets_on_success_and_heal_kick():
    """A persistent peer redialed throughout a long partition accumulates
    the clamped max backoff; kick_reconnect (wired to nemesis heal) must
    wipe it so the healed link redials on the next pass, and a SUCCESSFUL
    dial must zero the attempt counter so the next outage starts from the
    fast end of the schedule."""
    from tendermint_tpu.p2p import switch as sw

    class _T:
        class node_info:
            node_id = "meme"

    from tendermint_tpu.utils import peerscore

    s = sw.Switch.__new__(sw.Switch)  # no sockets: just the backoff state
    s.transport = _T()
    s.peers = {}
    s.logger = None
    s.scoreboard = peerscore.PeerScoreBoard()  # consulted by the pass
    s._persistent_addrs = ["peer1@127.0.0.1:1"]
    s._reconnect_attempts = {}
    s._reconnect_next_try = {}
    dials = {"ok": False}
    s.dial_peer = lambda addr, persistent=False: (object() if dials["ok"]
                                                  else None)

    # partition: every pass fails, backoff climbs to the clamp
    for _ in range(12):
        s._reconnect_next_try.clear()  # force the pass to actually dial
        s._reconnect_pass(s._reconnect_attempts, s._reconnect_next_try)
    addr = s._persistent_addrs[0]
    assert s._reconnect_attempts[addr] == 12
    s._reconnect_pass(s._reconnect_attempts, s._reconnect_next_try)
    assert s._reconnect_attempts[addr] == 12  # next_try gate held it back

    # heal kick: backoff state forgotten, next pass dials immediately
    s.kick_reconnect()
    assert not s._reconnect_attempts and not s._reconnect_next_try
    dials["ok"] = True
    s._reconnect_pass(s._reconnect_attempts, s._reconnect_next_try)
    # success resets the counter: nothing accumulated for the next outage
    assert addr not in s._reconnect_attempts


def test_switch_start_registers_heal_listener(tmp_path):
    from tendermint_tpu.p2p.switch import Switch, Transport
    from tendermint_tpu.p2p.node_info import NodeInfo

    nk = NodeKey(ed25519.gen_priv_key(b"\x55" * 32))
    t = Transport(nk, NodeInfo(node_id=nk.id(), network="x", moniker="m"))
    s = Switch(t)
    s.start()
    try:
        assert s.kick_reconnect in nemesis.PLANE.on_heal
    finally:
        s.stop()
    assert s.kick_reconnect not in nemesis.PLANE.on_heal


# ---------------------------------------------------------------------------
# In-process testnets
# ---------------------------------------------------------------------------


def _mk_genesis(n):
    privs = [ed25519.gen_priv_key(bytes([70 + i]) * 32) for i in range(n)]
    genesis = GenesisDoc(
        chain_id="nemesis-chain",
        genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    return genesis, privs


def _mk_node(tmp_path, i, genesis, priv, metrics=False):
    from tendermint_tpu.node.node import Node

    cfg = make_test_config()
    cfg.set_root(str(tmp_path / f"node{i}"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = ""  # peered via plain socketpairs (no `cryptography`)
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = os.path.join(cfg.base.root_dir, "cs.wal")
    if metrics:
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
    node_key = NodeKey(ed25519.gen_priv_key(bytes([110 + i]) * 32))
    return Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=node_key)


# The socketpair stitching lives in the scenario fabric now
# (tendermint_tpu/e2e/fabric.py) — one mesh harness for the 3-node smokes
# here, the flood scenarios in test_overload.py, and 50+ node clusters.
from tendermint_tpu.e2e.fabric import PlainConn as _PlainConn  # noqa: E402
from tendermint_tpu.e2e.fabric import link_nodes as _link  # noqa: E402


def _start_mesh(tmp_path, n, metrics_node=-1):
    genesis, privs = _mk_genesis(n)
    nodes = [_mk_node(tmp_path, i, genesis, privs[i], metrics=(i == metrics_node))
             for i in range(n)]
    for node in nodes:
        node.start()
    for i in range(n):
        for j in range(i):
            _link(nodes[i], nodes[j])
    return nodes


def _relink_mesh(nodes, timeout=15):
    """Re-establish severed links after a heal. A real deployment's
    persistent-peer redial does this (Switch._reconnect_loop, kicked by
    the heal listener — the e2e subprocess tests exercise that path); the
    socketpair harness has no transport to dial through, so the relink is
    explicit here."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        missing = []
        for i in range(len(nodes)):
            for j in range(i):
                if (nodes[j].node_key.id() not in nodes[i].switch.peers
                        or nodes[i].node_key.id() not in nodes[j].switch.peers):
                    missing.append((i, j))
        if not missing:
            return
        for i, j in missing:
            # clear any half-torn remnant, then link fresh
            nodes[i].switch.stop_peer_by_id(nodes[j].node_key.id(), "relink")
            nodes[j].switch.stop_peer_by_id(nodes[i].node_key.id(), "relink")
            try:
                _link(nodes[i], nodes[j])
            except Exception:  # noqa: BLE001 - teardown still in flight
                pass
        time.sleep(0.1)
    raise AssertionError("mesh relink failed after heal")


def _stop_all(nodes):
    for n in nodes:
        try:
            n.stop()
        except Exception:  # noqa: BLE001 - teardown best effort
            pass


def _heights(nodes):
    return [n.block_store.height for n in nodes]


def _audit_agreement(nodes):
    """Zero-fork audit over EVERY committed height on every node."""
    audited = 0
    for h in range(1, max(_heights(nodes)) + 1):
        hashes = {}
        for i, n in enumerate(nodes):
            b = n.block_store.load_block(h)
            if b is not None:
                hashes[i] = b.hash()
        if len(hashes) >= 2:
            audited += 1
            assert len(set(hashes.values())) == 1, (
                f"fork at height {h}: "
                f"{ {i: v.hex()[:16] for i, v in hashes.items()} }")
    return audited


# --- quick-tier smoke: 3 nodes, one partition/heal round -------------------


def test_three_node_partition_heal_smoke(tmp_path):
    """The quick-tier nemesis smoke: 3 in-process validators over real TCP,
    one partition/heal round. With 1|2 split neither side holds >2/3 power,
    so the split freezes the chain (safety: no commits, no forks); heal
    restores liveness. Tiny timeouts — one `-m 'not slow'` pass covers the
    whole plane end to end.

    Runs under the lock-order witness (TMTPU_LOCKWITNESS semantics,
    utils/lockwitness.py): every Lock/RLock the 3 nodes create is
    instrumented, and exiting the context asserts the observed
    acquisition-order graph is acyclic with bounded witness overhead —
    the dynamic half of tmlint's lock-order rule, run where the real
    cross-node interleavings are."""
    with lockwitness.witness() as w:
        nodes = _start_mesh(tmp_path, 3)
        ids = [n.node_key.id() for n in nodes]
        desc = f"partition={ids[0]}|{ids[1]}/{ids[2]}"
        try:
            with repro("3-node partition/heal smoke", desc):
                assert _wait(lambda: min(_heights(nodes)) >= 2, 30, 0.1), \
                    f"no initial progress: {_heights(nodes)}"

                nemesis.partition([[ids[0]], [ids[1], ids[2]]])
                time.sleep(0.3)  # let in-flight commits land
                split_h = _heights(nodes)
                time.sleep(1.2)
                frozen_h = _heights(nodes)
                # no commits while split (≤1 height of in-flight slack)
                assert all(f <= s + 1 for s, f in zip(split_h, frozen_h)), \
                    f"commits during 1|2 split: {split_h} -> {frozen_h}"
                _audit_agreement(nodes)

                nemesis.heal()
                _relink_mesh(nodes)
                target = max(frozen_h) + 2
                assert _wait(lambda: min(_heights(nodes)) >= target, 60, 0.1), \
                    f"no liveness after heal: {_heights(nodes)} < {target}"
                assert _audit_agreement(nodes) >= target - 1
        finally:
            _stop_all(nodes)
    # the witness actually saw the mesh run (not a silently-disabled no-op)
    assert w.acquires > 0 and len(w.edges) > 0


# --- slow-tier scenario matrix ---------------------------------------------


@pytest.mark.slow
def test_even_split_no_forks_and_live_after_heal(tmp_path):
    """Even 2|2 split: neither side holds >2/3, so the partition must
    freeze the chain with ZERO forks (the BFT safety property the verify
    pipeline exists to protect), and after heal all 4 nodes converge to
    within 2 heights of the tip inside the liveness bound. Deterministic:
    the full cut has no probabilistic rules; one TMTPU_FAULT_SEED replays
    the schedule."""
    nodes = _start_mesh(tmp_path, 4)
    ids = [n.node_key.id() for n in nodes]
    desc = f"partition={ids[0]}/{ids[1]}|{ids[2]}/{ids[3]}"
    try:
        with repro("even 2|2 split", desc):
            assert _wait(lambda: min(_heights(nodes)) >= 3, 60, 0.1), \
                f"no initial progress: {_heights(nodes)}"

            nemesis.partition([[ids[0], ids[1]], [ids[2], ids[3]]])
            time.sleep(0.3)
            split_h = _heights(nodes)
            time.sleep(2.0)
            frozen_h = _heights(nodes)
            assert all(f <= s + 1 for s, f in zip(split_h, frozen_h)), \
                f"commits during 2|2 split: {split_h} -> {frozen_h}"
            _audit_agreement(nodes)  # zero forks while split

            nemesis.heal()
            _relink_mesh(nodes)
            target = max(frozen_h) + 3
            assert _wait(lambda: min(_heights(nodes)) >= target, 90, 0.1), \
                f"no liveness after heal: {_heights(nodes)} < {target}"
            # liveness bound: all nodes within 2 heights of the max
            assert _wait(
                lambda: max(_heights(nodes)) - min(_heights(nodes)) <= 2,
                30, 0.1), f"nodes spread after heal: {_heights(nodes)}"
            assert _audit_agreement(nodes) >= target - 1  # zero forks, ever
    finally:
        _stop_all(nodes)


@pytest.mark.slow
def test_minority_partition_watchdog_recovers(tmp_path, monkeypatch):
    """Minority partition: node3 is isolated while the 3/4 majority keeps
    committing. After the heal, node3 is vote-starved (channel-scoped drop
    on its consensus DATA/VOTE channels — a peer that is reachable but
    starved of votes models a saturated peer, and pins THIS test on the
    watchdog path instead of racing consensus catchup gossip). The
    watchdog must detect the stall, probe peer heights over the blockchain
    channel, hand the node back to fast-sync catchup, and converge it to
    the majority app hash WITHOUT a process restart —
    watchdog_recoveries_total ≥ 1 visible on its /metrics endpoint."""
    monkeypatch.delenv("TMTPU_WATCHDOG_STALL_S", raising=False)
    nodes = _start_mesh(tmp_path, 4, metrics_node=3)
    ids = [n.node_key.id() for n in nodes]
    n3 = nodes[3]
    desc = (f"partition={ids[3]}|{ids[0]}/{ids[1]}/{ids[2]} then "
            f"link=*>{ids[3]}:drop#0x21,link=*>{ids[3]}:drop#0x22")
    try:
        with repro("minority partition watchdog recovery", desc):
            assert _wait(lambda: min(_heights(nodes)) >= 2, 60, 0.1), \
                f"no initial progress: {_heights(nodes)}"

            nemesis.partition([[ids[3]], [ids[0], ids[1], ids[2]]])
            # shrink the stall window only now: armed from boot it would
            # thrash every node through its first-commit lag (the config
            # helper reads the env live, so this applies immediately)
            monkeypatch.setenv("TMTPU_WATCHDOG_STALL_S", "1.0")
            h3_stall = n3.block_store.height
            # the majority must keep committing through the partition
            assert _wait(
                lambda: nodes[0].block_store.height >= h3_stall + 6, 60, 0.1), \
                f"majority stalled during minority partition: {_heights(nodes)}"
            assert n3.block_store.height <= h3_stall + 1
            time.sleep(1.2)  # let node3's stall clock pass the window

            # heal into the vote-starved configuration
            nemesis.add_link(f"*>{ids[3]}:drop#{DATA_CH:#x}")
            nemesis.add_link(f"*>{ids[3]}:drop#{VOTE_CH:#x}")
            nemesis.heal()
            _relink_mesh(nodes)

            # watchdog: stall + probed peer lead -> fast-sync hand-back
            assert _wait(lambda: n3.watchdog.recoveries >= 1, 30, 0.1), \
                "watchdog never recovered the stalled node"
            assert _wait(
                lambda: n3.block_store.height
                >= nodes[0].block_store.height - 2, 60, 0.1), \
                f"fast-sync catchup never converged: {_heights(nodes)}"

            # full heal: node3 rejoins consensus and the chain stays live
            nemesis.clear()
            tip = max(_heights(nodes))
            assert _wait(lambda: min(_heights(nodes)) >= tip + 2, 60, 0.1), \
                f"no liveness after full heal: {_heights(nodes)}"

            # converged to the majority app hash at a common height
            h = min(_heights(nodes)) - 1
            apps = {b.header.app_hash
                    for b in (n.block_store.load_block(h) for n in nodes)
                    if b is not None}
            assert len(apps) == 1, f"app hash divergence at {h}: {apps}"
            _audit_agreement(nodes)

            # the recovery is visible on the /metrics route
            url = f"http://{n3.metrics_server.addr}/metrics"
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            line = next(l for l in body.splitlines()
                        if l.startswith(
                            "tendermint_consensus_watchdog_recoveries_total"))
            assert float(line.rsplit(" ", 1)[1]) >= 1.0, line
    finally:
        _stop_all(nodes)


@pytest.mark.slow
def test_equivocator_inside_minority_partition(tmp_path):
    """An equivocator (double_prevote) trapped in the minority side of a
    2|2 split: honest node2 shares the minority with byzantine node3,
    observes the conflicting prevotes while partitioned, and buffers
    DuplicateVoteEvidence it cannot yet gossip across the cut. After the
    heal the evidence must still gossip out and COMMIT in a block — a
    partition must not launder equivocation."""
    from tendermint_tpu.consensus.misbehavior import double_prevote

    nodes = _start_mesh(tmp_path, 4)
    ids = [n.node_key.id() for n in nodes]
    desc = f"partition={ids[0]}/{ids[1]}|{ids[2]}/{ids[3]} + byz node3"
    nodes[3].consensus.misbehaviors["prevote"] = double_prevote(nodes[3].switch)
    try:
        with repro("equivocator inside minority partition", desc):
            assert _wait(lambda: min(_heights(nodes)) >= 2, 60, 0.1), \
                f"no initial progress: {_heights(nodes)}"

            nemesis.partition([[ids[0], ids[1]], [ids[2], ids[3]]])
            time.sleep(0.3)
            split_h = _heights(nodes)

            # node2 must observe the equivocation inside the partition
            def minority_buffered():
                evs, _ = nodes[2].evidence_pool.pending_evidence(1 << 20)
                return (bool(evs)
                        or bool(nodes[2].evidence_pool._consensus_buffer))
            assert _wait(minority_buffered, 30, 0.1), \
                "no conflicting votes buffered on the minority honest node"
            frozen_h = _heights(nodes)
            assert all(f <= s + 1 for s, f in zip(split_h, frozen_h)), \
                f"commits during 2|2 split: {split_h} -> {frozen_h}"

            nemesis.heal()
            _relink_mesh(nodes)

            # after heal: the buffered evidence gossips and COMMITS
            def evidence_committed():
                for n in (nodes[0], nodes[1]):
                    for h in range(2, n.block_store.height + 1):
                        b = n.block_store.load_block(h)
                        if b is not None and b.evidence:
                            return True
                return False
            assert _wait(evidence_committed, 90, 0.2), \
                "DuplicateVoteEvidence never committed after heal"
            _audit_agreement(nodes[:3])  # honest nodes: zero forks
    finally:
        _stop_all(nodes)
