"""UPnP against a fake in-process IGD: SSDP discovery, description parsing,
GetExternalIPAddress, Add/DeletePortMapping SOAP round-trips (reference:
p2p/upnp/upnp.go)."""

import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tendermint_tpu.p2p import upnp

DESC_XML = """<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
 <device>
  <deviceType>urn:schemas-upnp-org:device:InternetGatewayDevice:1</deviceType>
  <serviceList>
   <service>
    <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
    <controlURL>/ctl</controlURL>
   </service>
  </serviceList>
 </device>
</root>"""


class _FakeIGD:
    def __init__(self):
        self.actions = []

        fake = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                body = DESC_XML.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode()
                action = self.headers.get("SOAPAction", "").split("#")[-1].strip('"')
                fake.actions.append((action, body))
                if action == "GetExternalIPAddress":
                    resp = ("<r><NewExternalIPAddress>203.0.113.7"
                            "</NewExternalIPAddress></r>")
                else:
                    resp = "<r/>"
                out = resp.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.http_port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

        # SSDP responder on loopback UDP
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind(("127.0.0.1", 0))
        self.udp_port = self.udp.getsockname()[1]

        def ssdp():
            while True:
                try:
                    data, addr = self.udp.recvfrom(4096)
                except OSError:
                    return
                if b"M-SEARCH" in data:
                    resp = (f"HTTP/1.1 200 OK\r\n"
                            f"LOCATION: http://127.0.0.1:{self.http_port}/desc.xml\r\n"
                            f"ST: {upnp.SEARCH_TARGET}\r\n\r\n").encode()
                    self.udp.sendto(resp, addr)

        threading.Thread(target=ssdp, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.udp.close()


def test_upnp_against_fake_igd():
    fake = _FakeIGD()
    try:
        igd = upnp.discover(timeout_s=3.0, ssdp_addr="127.0.0.1",
                            ssdp_port=fake.udp_port)
        assert igd.control_url == f"http://127.0.0.1:{fake.http_port}/ctl"
        assert igd.service_type.endswith("WANIPConnection:1")

        assert upnp.get_external_ip(igd) == "203.0.113.7"

        upnp.add_port_mapping(igd, 26656, 26656, description="test-map")
        upnp.delete_port_mapping(igd, 26656)
        names = [a for a, _ in fake.actions]
        assert names == ["GetExternalIPAddress", "AddPortMapping",
                         "DeletePortMapping"]
        add_body = fake.actions[1][1]
        assert "<NewExternalPort>26656</NewExternalPort>" in add_body
        assert "<NewProtocol>TCP</NewProtocol>" in add_body
        assert "test-map" in add_body
    finally:
        fake.close()


def test_upnp_discover_timeout():
    with pytest.raises(upnp.UPnPError):
        # a bound-but-silent port: nothing answers the M-SEARCH
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("127.0.0.1", 0))
        try:
            upnp.discover(timeout_s=0.3, ssdp_addr="127.0.0.1",
                          ssdp_port=s.getsockname()[1])
        finally:
            s.close()
