"""Device SHA-512 (ops/sha512_jax) vs hashlib — differential across
message lengths, padding boundaries, and the packing helpers."""

import hashlib

import numpy as np
import pytest

from tendermint_tpu.ops import sha512_jax as sj


def _rand(n, seed=7):
    return np.random.default_rng(seed).integers(0, 256, size=n, dtype=np.uint8)


@pytest.mark.parametrize("lens", [
    # straddle the 1-block/2-block padding boundary for the 64-byte prefix:
    # 64 + 47 + 17 == 128 exactly; 48 tips into block 2
    [0, 1, 46, 47, 48, 63],
    # canonical-vote sizes and multi-block
    [110, 122, 126, 174, 175, 176],
    [300, 500, 900],
])
def test_differential_vs_hashlib(lens):
    msgs = [_rand(l, seed=l + 1).tobytes() for l in lens]
    n = len(msgs)
    r32 = np.ascontiguousarray(_rand((n, 32), seed=2))
    pubs = np.ascontiguousarray(_rand((n, 32), seed=3))
    out = sj.sha512_rab_device(r32, pubs, msgs, lanes=n + 3)
    got = np.asarray(out).T
    for i, m in enumerate(msgs):
        want = hashlib.sha512(r32[i].tobytes() + pubs[i].tobytes() + m).digest()
        assert got[i].tobytes() == want, lens[i]


def test_block_count_and_bucketing():
    assert sj.n_blocks(0) == 1          # 64 + 17 <= 128
    assert sj.n_blocks(47) == 1         # exactly one block
    assert sj.n_blocks(48) == 2
    assert sj.n_blocks(122) == 2        # canonical vote
    assert sj.n_blocks(128 * 7) == 8
    assert sj.bucket_blocks(1) == 2
    assert sj.bucket_blocks(3) == 4
    assert sj.bucket_blocks(8) == 8
    with pytest.raises(ValueError):
        sj.bucket_blocks(9)


def test_too_long_message_falls_back():
    r32 = np.zeros((1, 32), np.uint8)
    assert sj.sha512_rab_device(r32, r32, [b"x" * 2000], 1) is None


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("TM_TPU_DEVICE_SHA", raising=False)
    assert not sj.enabled()
    monkeypatch.setenv("TM_TPU_DEVICE_SHA", "1")
    assert sj.enabled()


def test_pipelined_dispatch_with_device_sha(monkeypatch):
    """The env-gated integration: force the pallas pipelined path onto the
    CPU interpreter-free jnp kernels is not possible, but the prep split
    (hash=False returning pubs32, no h64) must hold and verify_batch must
    stay correct with the flag on (CPU routes through the jnp path, which
    never consults the flag — this pins the flag from breaking it)."""
    from tendermint_tpu.crypto import ed25519 as ref
    from tendermint_tpu.ops import ed25519_batch as eb

    monkeypatch.setenv("TM_TPU_DEVICE_SHA", "1")
    items = []
    for i in range(8):
        priv = ref.gen_priv_key(bytes([i + 1]) * 32)
        msg = b"dev-sha-%d" % i
        items.append((priv.pub_key().data, msg, ref.sign(priv.data, msg)))
    out = eb.verify_batch(items)
    assert out.all()
    s = eb.prepare_scalars(items, np.ones(8, bool), windows=False,
                           reduce=False, host_hash=False)
    assert "h64" not in s and s["pubs32"].shape == (8, 32)
