"""Byzantine adversary plane units (docs/BYZANTINE.md, ISSUE 14):
behavior-spec grammar, the FilePV double-sign guard vs the maverick's
unguarded signer, batched duplicate-vote evidence verification,
light-client-attack byzantine attribution (lunatic / equivocation /
amnesia), evidence-reactor hardening (scored rejects + the
evidence_rejected_total counter), the soak `byz` grammar/generator
invariants, and the auditor's evidence-lifecycle convergence logic."""

import dataclasses

import pytest

from tendermint_tpu.consensus import misbehavior as mb
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.e2e import soak
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.evidence.reactor import EvidenceReactor, msg_evidence_list
from tendermint_tpu.privval.file_pv import DoubleSignError, FilePV, MockPV
from tendermint_tpu.types.block import Commit, CommitSig, Header
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    EvidenceError,
    LightClientAttackEvidence,
)
from tendermint_tpu.types.light_block import LightBlock, SignedHeader
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import (
    BLOCK_ID_FLAG_COMMIT,
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    Vote,
)

CHAIN_ID = "byz-chain"


# ---------------------------------------------------------------------------
# behavior-spec grammar
# ---------------------------------------------------------------------------


def test_spec_grammar_roundtrip():
    for spec in ("double_prevote", "absent~4", "equivocate~3-5",
                 "lunatic~7-", "amnesia~-9",
                 "double_prevote~3-5+lunatic~7-", "double_precommit"):
        windows = mb.parse_spec(spec)
        assert mb.describe_spec(windows) == spec
        again = mb.parse_spec(mb.describe_spec(windows))
        assert again == windows


def test_spec_grammar_windows():
    (w,) = mb.parse_spec("equivocate~3-5")
    assert not w.active(2) and w.active(3) and w.active(5) and not w.active(6)
    (w,) = mb.parse_spec("lunatic~7-")
    assert not w.active(6) and w.active(7) and w.active(10_000)
    (w,) = mb.parse_spec("absent~4")
    assert [h for h in range(1, 8) if w.active(h)] == [4]
    (w,) = mb.parse_spec("double_prevote")
    assert w.active(1) and w.active(999)


def test_spec_grammar_rejects_unknown():
    with pytest.raises(ValueError):
        mb.parse_spec("nonsense")
    with pytest.raises(ValueError):
        mb.parse_spec("")


# ---------------------------------------------------------------------------
# FilePV double-sign guard (the safety property misbehavior.py's docstring
# promises: a guarded signer REFUSES the equivocating second signature)
# ---------------------------------------------------------------------------


def _prevote(height, round_, block_hash):
    return Vote(type=PREVOTE_TYPE, height=height, round=round_,
                block_id=BlockID(hash=block_hash,
                                 part_set_header=PartSetHeader()),
                timestamp=Time(1_700_000_100, 0),
                validator_address=b"\x01" * 20, validator_index=0)


def test_filepv_refuses_equivocating_signature(tmp_path):
    pv = FilePV.generate(str(tmp_path / "key.json"),
                         str(tmp_path / "state.json"), seed=b"\x42" * 32)
    vote_a = _prevote(5, 0, b"\xaa" * 32)
    pv.sign_vote(CHAIN_ID, vote_a)
    assert vote_a.signature
    # the conflicting second prevote at the SAME H/R/S must be refused
    vote_b = _prevote(5, 0, b"")
    with pytest.raises(DoubleSignError):
        pv.sign_vote(CHAIN_ID, vote_b)
    # ...and the guard survives a process restart (state file is fsync'd)
    pv2 = FilePV.load(str(tmp_path / "key.json"), str(tmp_path / "state.json"))
    with pytest.raises(DoubleSignError):
        pv2.sign_vote(CHAIN_ID, _prevote(5, 0, b"\xbb" * 32))


def test_mockpv_maverick_signs_conflicting_votes(tmp_path):
    """The byzantine install swaps FilePV for a MockPV with the SAME key —
    which happily signs the equivocating pair FilePV refuses."""
    pv = FilePV.generate(str(tmp_path / "key.json"),
                         str(tmp_path / "state.json"), seed=b"\x43" * 32)
    unguarded = MockPV(pv.priv_key)
    assert unguarded.get_address() == pv.get_address()
    vote_a = _prevote(5, 0, b"\xaa" * 32)
    vote_b = _prevote(5, 0, b"")
    unguarded.sign_vote(CHAIN_ID, vote_a)
    unguarded.sign_vote(CHAIN_ID, vote_b)
    assert vote_a.signature and vote_b.signature
    pub = pv.get_pub_key()
    assert pub.verify_signature(vote_a.sign_bytes(CHAIN_ID), vote_a.signature)
    assert pub.verify_signature(vote_b.sign_bytes(CHAIN_ID), vote_b.signature)


# ---------------------------------------------------------------------------
# batched duplicate-vote verification (evidence/pool.py through the
# BatchVerifier registry: one 2-sig batch, serial error order preserved)
# ---------------------------------------------------------------------------


def _duplicate_vote_pair(priv, height=3, round_=0):
    addr = priv.pub_key().address()
    votes = []
    for bh in (b"\xaa" * 32, b"\xcc" * 32):
        v = Vote(type=PRECOMMIT_TYPE, height=height, round=round_,
                 block_id=BlockID(hash=bh, part_set_header=PartSetHeader()),
                 timestamp=Time(1_700_000_200, 0),
                 validator_address=addr, validator_index=0)
        v.signature = priv.sign(v.sign_bytes(CHAIN_ID))
        votes.append(v)
    votes.sort(key=lambda v: v.block_id.key())
    return votes


def test_verify_duplicate_vote_batched_accepts_valid_pair():
    priv = ed25519.gen_priv_key(b"\x51" * 32)
    val_set = ValidatorSet([Validator.new(priv.pub_key(), 10)])
    va, vb = _duplicate_vote_pair(priv)
    ev = DuplicateVoteEvidence(vote_a=va, vote_b=vb)
    EvidencePool.verify_duplicate_vote(ev, CHAIN_ID, val_set)  # no raise


def test_verify_duplicate_vote_batched_serial_error_order():
    priv = ed25519.gen_priv_key(b"\x52" * 32)
    val_set = ValidatorSet([Validator.new(priv.pub_key(), 10)])
    for tamper_idx, want in ((0, "vote A"), (1, "vote B")):
        va, vb = _duplicate_vote_pair(priv)
        votes = [va, vb]
        sig = votes[tamper_idx].signature
        votes[tamper_idx].signature = sig[:-1] + bytes([sig[-1] ^ 1])
        ev = DuplicateVoteEvidence(vote_a=votes[0], vote_b=votes[1])
        with pytest.raises(EvidenceError) as ei:
            EvidencePool.verify_duplicate_vote(ev, CHAIN_ID, val_set)
        assert want in str(ei.value)
        assert ei.value.reason == "bad_sig"
    # both bad: the serial path reports vote A first
    va, vb = _duplicate_vote_pair(priv)
    va.signature = b"\x00" * 64
    vb.signature = b"\x00" * 64
    with pytest.raises(EvidenceError) as ei:
        EvidencePool.verify_duplicate_vote(
            DuplicateVoteEvidence(vote_a=va, vote_b=vb), CHAIN_ID, val_set)
    assert "vote A" in str(ei.value)


def test_verify_duplicate_vote_unknown_validator_reason():
    priv = ed25519.gen_priv_key(b"\x53" * 32)
    other = ed25519.gen_priv_key(b"\x54" * 32)
    val_set = ValidatorSet([Validator.new(other.pub_key(), 10)])
    va, vb = _duplicate_vote_pair(priv)
    with pytest.raises(EvidenceError) as ei:
        EvidencePool.verify_duplicate_vote(
            DuplicateVoteEvidence(vote_a=va, vote_b=vb), CHAIN_ID, val_set)
    assert ei.value.reason == "unknown_validator"


# ---------------------------------------------------------------------------
# light-client-attack byzantine attribution (types/evidence.py
# get_byzantine_validators: lunatic / equivocation / amnesia)
# ---------------------------------------------------------------------------


def _attribution_fixture():
    privs = [ed25519.gen_priv_key(bytes([60 + i]) * 32) for i in range(4)]
    common = ValidatorSet([Validator.new(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    privs = [by_addr[v.address] for v in common.validators]  # sorted order
    trusted_header = Header(
        chain_id=CHAIN_ID, height=5, time=Time(1_700_000_500, 0),
        validators_hash=common.hash(), next_validators_hash=common.hash(),
        consensus_hash=b"\x11" * 32, app_hash=b"\x22" * 32,
        last_results_hash=b"\x33" * 32, data_hash=b"\x44" * 32,
        proposer_address=common.validators[0].address)
    return privs, common, trusted_header


def _commit(header, signers, round_=0, absent=()):
    bid = BlockID(hash=header.hash(), part_set_header=PartSetHeader())
    sigs = []
    for i, val in enumerate(signers):
        if i in absent:
            sigs.append(CommitSig.new_absent())
        else:
            sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address,
                                  header.time, b"\x77" * 64))
    return Commit(height=header.height, round=round_, block_id=bid,
                  signatures=sigs)


def test_attribution_lunatic_names_common_set_signers():
    privs, common, trusted_header = _attribution_fixture()
    attackers = common.validators[:2]
    claimed = ValidatorSet([Validator.new(privs[i].pub_key(), 10)
                            for i in range(2)])
    fake_header = dataclasses.replace(
        trusted_header, app_hash=b"\xde\xad" * 16,
        validators_hash=claimed.hash(), next_validators_hash=claimed.hash())
    ev = LightClientAttackEvidence(
        conflicting_block=LightBlock(
            SignedHeader(fake_header, _commit(fake_header, claimed.validators)),
            claimed),
        common_height=1)
    trusted_sh = SignedHeader(trusted_header,
                              _commit(trusted_header, common.validators))
    byz = ev.get_byzantine_validators(common, trusted_sh)
    assert {v.address for v in byz} == {v.address for v in attackers}
    # attribution pulls the COMMON-set validator entries (old powers)
    assert all(v.voting_power == 10 for v in byz)


def test_attribution_equivocation_names_double_signers():
    privs, common, trusted_header = _attribution_fixture()
    # derived header (every state field matches), different data hash
    conf_header = dataclasses.replace(trusted_header, data_hash=b"\x55" * 32)
    # validators 0 and 1 signed BOTH commits; 2 and 3 absent on the fork
    conf_commit = _commit(conf_header, common.validators, round_=0,
                          absent=(2, 3))
    trusted_commit = _commit(trusted_header, common.validators, round_=0)
    ev = LightClientAttackEvidence(
        conflicting_block=LightBlock(SignedHeader(conf_header, conf_commit),
                                     common),
        common_height=1)
    byz = ev.get_byzantine_validators(
        common, SignedHeader(trusted_header, trusted_commit))
    assert {v.address for v in byz} == {common.validators[0].address,
                                        common.validators[1].address}


def test_attribution_amnesia_attributes_nobody():
    """Different round + derived header: not attributable from the two
    commits alone (the amnesia case) -> empty."""
    privs, common, trusted_header = _attribution_fixture()
    conf_header = dataclasses.replace(trusted_header, data_hash=b"\x55" * 32)
    conf_commit = _commit(conf_header, common.validators, round_=1)
    trusted_commit = _commit(trusted_header, common.validators, round_=0)
    ev = LightClientAttackEvidence(
        conflicting_block=LightBlock(SignedHeader(conf_header, conf_commit),
                                     common),
        common_height=1)
    assert ev.get_byzantine_validators(
        common, SignedHeader(trusted_header, trusted_commit)) == []


# ---------------------------------------------------------------------------
# evidence reactor hardening: unverifiable evidence is SCORED and counted,
# our-limitation rejections stay unscored
# ---------------------------------------------------------------------------


class _StubPool:
    version = 0

    def __init__(self, exc=None):
        self.exc = exc
        self.added = []

    def add_evidence(self, ev):
        if self.exc is not None:
            raise self.exc
        self.added.append(ev)


class _StubPeer:
    def __init__(self, pid="peer-evil"):
        self.id = pid


class _StubSwitch:
    def __init__(self, board):
        self.scoreboard = board
        self.logger = None


def _some_evidence():
    priv = ed25519.gen_priv_key(b"\x55" * 32)
    va, vb = _duplicate_vote_pair(priv)
    return DuplicateVoteEvidence(vote_a=va, vote_b=vb,
                                 total_voting_power=10, validator_power=10,
                                 timestamp=Time(1_700_000_200, 0))


@pytest.fixture
def _metrics(monkeypatch):
    from tendermint_tpu.utils import metrics as tmmetrics

    nm = tmmetrics.NodeMetrics()
    monkeypatch.setattr(tmmetrics, "GLOBAL_NODE_METRICS", nm)
    return nm


def _rejected_count(nm, reason):
    return nm.evidence_rejected._values.get((reason,), 0)


def test_reactor_scores_unverifiable_evidence(_metrics):
    from tendermint_tpu.utils.peerscore import PeerScoreBoard

    board = PeerScoreBoard()
    for exc, reason in (
            (EvidenceError("bogus sig", reason="bad_sig"), "bad_sig"),
            (EvidenceError("too old", reason="expired"), "expired"),
            (EvidenceError("power mismatch", reason="meta_mismatch"),
             "meta_mismatch")):
        reactor = EvidenceReactor(_StubPool(exc=exc))
        reactor.switch = _StubSwitch(board)
        peer = _StubPeer()
        before = board.score(peer.id)
        reactor.receive(0x38, peer, msg_evidence_list([_some_evidence()]))
        assert board.score(peer.id) > before, reason
        assert _rejected_count(_metrics, reason) == 1, reason


def test_reactor_scores_malformed_bytes(_metrics):
    from tendermint_tpu.encoding import proto
    from tendermint_tpu.utils.peerscore import PeerScoreBoard

    board = PeerScoreBoard()
    reactor = EvidenceReactor(_StubPool())
    reactor.switch = _StubSwitch(board)
    peer = _StubPeer()
    garbage = proto.Writer().message(1, b"\xff\xff\xff\xff",
                                     always=True).out()
    reactor.receive(0x38, peer, garbage)
    assert board.score(peer.id) > 0
    assert _rejected_count(_metrics, "malformed") == 1


def test_reactor_our_limitations_stay_unscored(_metrics):
    from tendermint_tpu.state.store import StateStoreError
    from tendermint_tpu.store.envelope import CorruptedStoreError
    from tendermint_tpu.utils.peerscore import PeerScoreBoard

    for exc in (StateStoreError("no state yet"),
                CorruptedStoreError("block", b"k", "crc")):
        board = PeerScoreBoard()
        reactor = EvidenceReactor(_StubPool(exc=exc))
        reactor.switch = _StubSwitch(board)
        peer = _StubPeer()
        reactor.receive(0x38, peer, msg_evidence_list([_some_evidence()]))
        assert board.score(peer.id) == 0.0, type(exc).__name__
    for reason in EvidenceError.REASONS:
        assert _rejected_count(_metrics, reason) == 0


def test_reactor_valid_evidence_unscored(_metrics):
    from tendermint_tpu.utils.peerscore import PeerScoreBoard

    board = PeerScoreBoard()
    pool = _StubPool()
    reactor = EvidenceReactor(pool)
    reactor.switch = _StubSwitch(board)
    peer = _StubPeer()
    reactor.receive(0x38, peer, msg_evidence_list([_some_evidence()]))
    assert len(pool.added) == 1
    assert board.score(peer.id) == 0.0


# ---------------------------------------------------------------------------
# soak grammar + generator invariants
# ---------------------------------------------------------------------------


def test_soak_byz_grammar_roundtrip():
    for entry in ("@3:byz:5:double_precommit", "@4:byz:0:equivocate~8-12",
                  "@5:byz:1:double_prevote~3-5+lunatic~7-",
                  "@24:evidence:3"):
        a = soak.SoakAction.parse(entry)
        assert a.describe() == entry


def test_soak_generator_byzantine_below_one_third():
    """Generated schedules never put >= 1/3 of the (equal-power) nodes
    under adversary control, and every byz arg parses as a behavior spec."""
    for seed in range(12):
        for nodes in (4, 7, 9):
            sch = soak.SoakSchedule.generate(seed, 90.0, nodes)
            assert soak.SoakSchedule.parse(sch.describe()).describe() == \
                sch.describe()
            byz_targets = set()
            for a in sch.actions:
                if a.kind == "byz":
                    idx_s, _, spec = a.arg.partition(":")
                    byz_targets.add(int(idx_s))
                    assert mb.parse_spec(spec)
                elif a.kind == "evidence":
                    byz_targets.add(int(a.arg))
            assert 3 * len(byz_targets) < nodes or not byz_targets, (
                seed, nodes, sorted(byz_targets))


# ---------------------------------------------------------------------------
# auditor evidence-lifecycle convergence (stub cluster: pure logic)
# ---------------------------------------------------------------------------


class _StubBlock:
    def __init__(self, evidence):
        self.evidence = evidence


class _StubStore:
    base = 1

    def __init__(self, blocks):
        self.blocks = blocks  # height -> _StubBlock

    def load_block(self, h):
        return self.blocks.get(h)


class _StubNode:
    def __init__(self, blocks):
        self.block_store = _StubStore(blocks)


class _StubFab:
    _gen = iter(range(1, 10_000))

    def __init__(self, blocks):
        self.node = _StubNode(blocks)
        self.generation = next(self._gen)

    @property
    def height(self):
        return max(self.node.block_store.blocks, default=0)


class _StubCluster:
    def __init__(self, per_node_blocks, byzantine=()):
        self.nodes = {i: _StubFab(b) for i, b in per_node_blocks.items()}
        self.byzantine = set(byzantine)

    def block_hash(self, i, h):
        return b"\x00" * 32 if h <= self.nodes[i].height else None


def _blocks(tip, evidence_at):
    return {h: _StubBlock(list(evidence_at.get(h, ())))
            for h in range(1, tip + 1)}


def test_auditor_evidence_converged_is_clean():
    ev = _some_evidence()
    blocks = {i: _blocks(12, {4: [ev]}) for i in range(3)}
    auditor = soak.ContinuousAuditor(_StubCluster(blocks), evidence_bound=5)
    auditor.sweep()
    assert not auditor.violations
    assert auditor.evidence_audited == 1


def test_auditor_flags_missing_convergence_within_bound():
    ev = _some_evidence()
    blocks = {0: _blocks(12, {4: [ev]}),
              1: _blocks(12, {4: [ev]}),
              2: _blocks(12, {})}  # node 2 is past 4+5 and still lacks it
    auditor = soak.ContinuousAuditor(_StubCluster(blocks), evidence_bound=5)
    auditor.sweep()
    kinds = [v.kind for v in auditor.violations]
    assert kinds == ["evidence"], auditor.violations
    assert "missing on node 2" in auditor.violations[0].detail
    # flagged once, not re-reported every sweep
    auditor.sweep()
    assert len(auditor.violations) == 1


def test_auditor_gives_laggards_the_height_bound():
    ev = _some_evidence()
    blocks = {0: _blocks(12, {4: [ev]}),
              1: _blocks(12, {4: [ev]}),
              2: _blocks(3, {})}  # tip 3 < 4+5: still inside the bound
    auditor = soak.ContinuousAuditor(_StubCluster(blocks), evidence_bound=5)
    auditor.sweep()
    assert not auditor.violations
    # the laggard catches up WITH the evidence in its height-4 block (the
    # same chain every honest node commits): converged, still clean
    blocks[2].update({h: _StubBlock([ev] if h == 4 else [])
                      for h in range(4, 13)})
    auditor.sweep()
    assert not auditor.violations


def test_auditor_flags_exactly_once_violation():
    ev = _some_evidence()
    blocks = {0: _blocks(12, {4: [ev], 9: [ev]}),   # committed twice!
              1: _blocks(12, {4: [ev]}),
              2: _blocks(12, {4: [ev]})}
    auditor = soak.ContinuousAuditor(_StubCluster(blocks), evidence_bound=5)
    auditor.sweep()
    assert [v.kind for v in auditor.violations] == ["evidence"]
    assert "TWICE" in auditor.violations[0].detail


def test_auditor_restart_rescan_is_not_double_commit():
    """A restarted honest node re-scans its full prefix (new generation
    key); re-reading the SAME carrying block must not read as the
    evidence being committed twice."""
    ev = _some_evidence()
    blocks = {i: _blocks(12, {4: [ev]}) for i in range(3)}
    cluster = _StubCluster(blocks)
    auditor = soak.ContinuousAuditor(cluster, evidence_bound=5)
    auditor.sweep()
    assert not auditor.violations
    # simulate a restart: same chain, fresh node object + generation
    cluster.nodes[1] = _StubFab(blocks[1])
    auditor.sweep()
    assert not auditor.violations, auditor.violations
    # a REAL re-admission (same evidence at a second, NEWLY COMMITTED
    # height past the incremental scan pointer) still flags
    blocks[2][13] = _StubBlock([ev])
    auditor.sweep()
    assert [v.kind for v in auditor.violations] == ["evidence"]
    assert "TWICE" in auditor.violations[0].detail


class _StubConsensus:
    def __init__(self):
        self.on_new_round_step = []
        self.misbehaviors = {}
        self.priv_validator = None
        self.priv_validator_pub_key = None


class _StubByzNode:
    def __init__(self):
        self.consensus = _StubConsensus()
        self.priv_validator = MockPV(ed25519.gen_priv_key(b"\x61" * 32))
        self.switch = None
        self.block_store = _StubStore({})
        self.block_store.height = 0

    class genesis:
        chain_id = CHAIN_ID


def test_install_cycling_unhooks_lunatic_fabricator():
    """Behavior cycling replaces the whole map: a node cycled away from
    lunatic must stop forging light blocks (the on_new_round_step
    fabricator is unhooked, not leaked)."""
    node = _StubByzNode()
    mb.install(node, "lunatic~2-4")
    assert len(node.consensus.on_new_round_step) == 1
    assert node._byz_on_step == node.consensus.on_new_round_step
    # re-install lunatic: replaced, not stacked
    mb.install(node, "lunatic~2-4")
    assert len(node.consensus.on_new_round_step) == 1
    # cycle to a non-lunatic behavior: fabricator unhooked
    mb.install(node, "absent")
    assert node.consensus.on_new_round_step == []
    assert node._byz_on_step == []
    assert "prevote" in node.consensus.misbehaviors
    assert "propose" not in node.consensus.misbehaviors


def test_soak_behaviors_derive_from_catalog():
    """The soak/generator behavior tables stay in lockstep with the
    authoritative misbehavior catalog."""
    from tendermint_tpu.e2e import generator

    assert set(soak._BYZ_BEHAVIORS) == set(mb.BEHAVIORS) - {"absent_prevote"}
    assert set(generator._BYZ_BEHAVIORS) == set(mb.BEHAVIORS) - {"absent"}


def test_auditor_fork_audit_skips_byzantine_nodes():
    class _ForkyCluster(_StubCluster):
        def block_hash(self, i, h):
            if h > self.nodes[i].height:
                return None
            return (b"\xff" * 32 if i == 9 else b"\x00" * 32)

    blocks = {0: _blocks(5, {}), 1: _blocks(5, {}), 9: _blocks(5, {})}
    # byzantine node 9's divergent store is NOT a fork violation...
    auditor = soak.ContinuousAuditor(_ForkyCluster(blocks, byzantine={9}))
    auditor.sweep()
    assert not [v for v in auditor.violations if v.kind == "fork"]
    # ...but an honest node diverging still is
    auditor2 = soak.ContinuousAuditor(_ForkyCluster(blocks, byzantine=set()))
    auditor2.sweep()
    assert [v for v in auditor2.violations if v.kind == "fork"]
