"""Multi-chip shard path under pytest: the multi-device virtual CPU mesh
from conftest drives the shard_map verify + psum tally (VERDICT round-2: the
sharded path had only smoke coverage, no pytest).

The mesh is derived from whatever conftest provides (8 devices today, but
nothing here assumes that); below 2 devices every test skips."""

import jax
import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519 as ref
from tendermint_tpu.ops import ed25519_batch
from tendermint_tpu.parallel import batch_shard


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("multi-chip tests need >= 2 devices "
                    "(conftest provides the virtual CPU mesh)")
    return batch_shard.make_mesh(devices)


def _ndev(mesh):
    return mesh.devices.size


def _batch(n, tamper=()):
    items = []
    for i in range(n):
        priv = ref.gen_priv_key(bytes([i % 251 + 1]) * 32)
        msg = b"mc-%d" % i
        sig = ref.sign(priv.data, msg)
        if i in tamper:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((priv.pub_key().data, msg, sig))
    args, _ = ed25519_batch.prepare(items)
    return items, args


def _tally_batch(mesh, n, tamper=()):
    """prepare() pads to a power-of-2 bucket; the tally step needs the
    padded axis divisible by the mesh."""
    _, args = _batch(n, tamper=tamper)
    if args["valid"].shape[0] % _ndev(mesh) != 0:
        pytest.skip(f"padded bucket {args['valid'].shape[0]} not divisible "
                    f"by {_ndev(mesh)} devices")
    return args


def test_sharded_verify_tally_all_valid(mesh):
    n = 64
    args = _tally_batch(mesh, n)
    power = np.full((args["valid"].shape[0],), 3, dtype=np.int32)
    for_block = args["valid"].copy()
    step = batch_shard.sharded_verify_tally(mesh)
    placed = batch_shard.shard_args(mesh, args, power, for_block)
    ok, tally, all_ok = step(
        placed["tab"], placed["h_win"], placed["s_win"], placed["r_y"],
        placed["r_sign"], placed["valid"], placed["power"], placed["for_block"])
    ok = np.asarray(ok)
    assert ok[:n].all()
    assert int(tally) == 3 * n  # psum across all shards
    assert bool(all_ok)
    # result bitmap is actually sharded over the mesh
    assert len(ok) % _ndev(mesh) == 0


def test_sharded_verify_tally_detects_bad_sigs(mesh):
    n = 64
    tampered = {5, 23, 60}
    args = _tally_batch(mesh, n, tamper=tampered)
    power = np.ones((args["valid"].shape[0],), dtype=np.int32)
    for_block = args["valid"].copy()
    step = batch_shard.sharded_verify_tally(mesh)
    placed = batch_shard.shard_args(mesh, args, power, for_block)
    ok, tally, all_ok = step(
        placed["tab"], placed["h_win"], placed["s_win"], placed["r_y"],
        placed["r_sign"], placed["valid"], placed["power"], placed["for_block"])
    ok = np.asarray(ok)
    for i in range(n):
        assert ok[i] == (i not in tampered), i
    assert int(tally) == n - len(tampered)
    assert not bool(all_ok)


def test_production_verify_batch_routes_through_shard(mesh, monkeypatch):
    """The PRODUCTION entry (ops.ed25519_batch.verify_batch, what
    Ed25519BatchVerifier calls) must itself shard on a multi-device mesh and
    agree bit-for-bit with the single-device path (VERDICT r3: batch_shard
    was reachable only from the dryrun/tests, never from production)."""
    n = _ndev(mesh) * ed25519_batch.JNP_TILE  # one full sharded chunk
    tampered = {3, n // 2, n - 1}
    items = []
    for i in range(n):
        priv = ref.gen_priv_key(bytes([i % 61 + 1]) * 32)  # 61 unique keys
        msg = b"prod-%d" % i
        sig = ref.sign(priv.data, msg)
        if i in tampered:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((priv.pub_key().data, msg, sig))

    assert batch_shard.should_shard(n)
    sharded = ed25519_batch.verify_batch(items)
    monkeypatch.setenv("TM_TPU_SHARD", "0")
    assert not batch_shard.should_shard(n)
    single = ed25519_batch.verify_batch(items)
    assert (sharded == single).all()
    for i in range(n):
        assert sharded[i] == (i not in tampered), i


@pytest.mark.parametrize("extra", [1, 7])
def test_sharded_uneven_batch_pads_and_masks(mesh, monkeypatch, extra):
    """N not divisible by the device count: the shard driver pads the
    signature axis up to a device multiple with valid=False lanes and key
    index 0; the returned bitmap must have exactly N entries and be
    bit-identical to the single-device route (padding lanes can never leak
    in as accepted)."""
    ndev = _ndev(mesh)
    n = batch_shard.shard_threshold(ndev) + extra
    if n % ndev == 0:  # a device count that divides `extra`: not uneven
        n += 1
    tampered = {0, n - 1}
    items = []
    for i in range(n):
        priv = ref.gen_priv_key(bytes([i % 13 + 1]) * 32)
        msg = b"uneven-%d" % i
        sig = ref.sign(priv.data, msg)
        if i in tampered:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((priv.pub_key().data, msg, sig))

    assert batch_shard.should_shard(n)
    sharded = ed25519_batch.verify_batch(items)
    assert sharded.shape == (n,)
    monkeypatch.setenv("TM_TPU_SHARD", "0")
    single = ed25519_batch.verify_batch(items)
    assert (sharded == single).all()
    for i in range(n):
        assert sharded[i] == (i not in tampered), i


def test_sharded_matches_single_device(mesh):
    """The sharded decision bitmap must be byte-identical to the single-chip
    jnp kernel over the same prepared batch."""
    n = 32
    args = _tally_batch(mesh, n, tamper={7})
    single = np.asarray(ed25519_batch._jnp_kernel(
        args["tab"], args["h_win"], args["s_win"], args["r_y"],
        args["r_sign"], args["valid"]))
    power = np.ones((args["valid"].shape[0],), dtype=np.int32)
    step = batch_shard.sharded_verify_tally(mesh)
    placed = batch_shard.shard_args(mesh, args, power, args["valid"].copy())
    ok, _, _ = step(
        placed["tab"], placed["h_win"], placed["s_win"], placed["r_y"],
        placed["r_sign"], placed["valid"], placed["power"], placed["for_block"])
    assert (np.asarray(ok) == single).all()
