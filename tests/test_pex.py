"""PEX + addrbook: bucket mechanics, promotion, persistence, message codec,
and the bootstrap criterion -- a 5-node net self-assembles from one seed
(reference: p2p/pex/addrbook.go:120, p2p/pex/pex_reactor.go)."""

import os
import time

from tendermint_tpu.p2p.addrbook import AddrBook, NetAddress
from tendermint_tpu.p2p.pex_reactor import (
    _parse_addrs,
    msg_pex_addrs,
    msg_pex_request,
)
from tendermint_tpu.encoding import proto


def _na(i, port=26656, host=None):
    return NetAddress(node_id=f"{i:040x}", host=host or f"10.0.{i}.1", port=port)


def test_addrbook_add_pick_promote():
    book = AddrBook(strict=False)  # 10.x test addresses are non-routable
    src = _na(99)
    for i in range(1, 21):
        assert book.add_address(_na(i), src)
    assert book.size() == 20
    picked = book.pick_address()
    assert picked is not None and book.has_address(picked)

    # promotion to old bucket
    book.mark_good(_na(5).node_id)
    ka = book._addrs[_na(5).node_id]
    assert ka.is_old() and len(ka.buckets) == 1
    # gossip can't re-demote an old address
    assert not book.add_address(_na(5), src)

    # mark_bad removes entirely
    book.mark_bad(_na(6).node_id)
    assert not book.has_address(_na(6))
    assert book.size() == 19


def test_addrbook_strict_rejects_local():
    book = AddrBook(strict=True)
    assert not book.add_address(_na(1, host="127.0.0.1"), _na(2))
    assert not book.add_address(_na(1, host="192.168.1.5"), _na(2))
    lax = AddrBook(strict=False)
    assert lax.add_address(_na(1, host="127.0.0.1"), _na(2))


def test_addrbook_our_address_never_added():
    book = AddrBook(strict=False)
    us = _na(7)
    book.add_our_address(us)
    assert not book.add_address(us, _na(8))
    assert book.our_address(us)


def test_addrbook_persistence(tmp_path):
    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path, strict=False)
    src = _na(99)
    for i in range(1, 11):
        book.add_address(_na(i), src)
    book.mark_good(_na(3).node_id)
    book.save()

    book2 = AddrBook(path, strict=False)
    assert book2.size() == 10
    assert book2._addrs[_na(3).node_id].is_old()
    assert book2.has_address(_na(7))


def test_addrbook_selection_size():
    book = AddrBook(strict=False)
    src = _na(99)
    for i in range(1, 101):
        book.add_address(_na(i), src)
    sel = book.get_selection()
    assert 23 <= len(sel) <= 100


def test_pex_message_codec():
    addrs = [_na(1), _na(2, port=1234)]
    buf = msg_pex_addrs(addrs)
    f = proto.fields(buf)
    assert 2 in f
    parsed = _parse_addrs(f[2][-1])
    assert [str(a) for a in parsed] == [str(a) for a in addrs]
    req = msg_pex_request()
    assert 1 in proto.fields(req)


def _mk_p2p_node(tmp_path, name, seed_addr="", seed_mode=False):
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    val_priv = ed25519.gen_priv_key(b"\x99" * 32)  # nobody holds this key
    genesis = GenesisDoc(
        chain_id="pex-chain", genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", val_priv.pub_key(), 10)],
    )
    cfg = test_config()
    cfg.set_root(str(tmp_path / name))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.pex = True
    cfg.p2p.addr_book_strict = False  # loopback net (reference tests do this)
    cfg.p2p.seed_mode = seed_mode
    cfg.p2p.seeds = seed_addr
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = ""
    return Node(cfg, genesis=genesis, priv_validator=None,
                node_key=NodeKey(ed25519.gen_priv_key(bytes([hash(name) % 200 + 1]) * 32)))


def test_five_node_net_bootstraps_from_one_seed(tmp_path):
    """The VERDICT criterion: nodes know only the seed; PEX must assemble the
    mesh."""
    seed = _mk_p2p_node(tmp_path, "seed", seed_mode=True)
    seed.start()
    nodes = []
    try:
        seed_addr = seed.p2p_addr()
        for i in range(4):
            n = _mk_p2p_node(tmp_path, f"n{i}", seed_addr=seed_addr)
            n.start()
            nodes.append(n)

        deadline = time.monotonic() + 45
        def mesh_degree():
            return [len([p for p in n.switch.peers.values()
                         if p.id != seed.node_key.id()]) for n in nodes]
        while time.monotonic() < deadline:
            if all(d >= 2 for d in mesh_degree()):
                break
            time.sleep(0.3)
        assert all(d >= 2 for d in mesh_degree()), mesh_degree()
        # every node's book learned addresses beyond the seed
        for n in nodes:
            assert n.addr_book.size() >= 2
    finally:
        for n in nodes:
            n.stop()
        seed.stop()
