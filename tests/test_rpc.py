"""RPC surface: JSON-RPC over HTTP + URI GET + WebSocket subscription."""

import base64
import json
import os
import time
import urllib.request

from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time


def _mk_node(tmp_path):
    priv = ed25519.gen_priv_key(b"\x41" * 32)
    genesis = GenesisDoc(
        chain_id="rpc-chain", genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", priv.pub_key(), 10)],
    )
    cfg = make_test_config()
    cfg.set_root(str(tmp_path / "node"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = ""
    return Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x42" * 32)))


def _rpc(base, method, params=None):
    req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                      "params": params or {}}).encode()
    with urllib.request.urlopen(urllib.request.Request(
            base, data=req, headers={"Content-Type": "application/json"}),
            timeout=10) as r:
        doc = json.loads(r.read())
    if "error" in doc:
        raise RuntimeError(doc["error"])
    return doc["result"]


def test_rpc_surface(tmp_path):
    node = _mk_node(tmp_path)
    node.start()
    base = "http://" + node.rpc_server.laddr.split("://", 1)[1]
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and node.block_store.height < 2:
            time.sleep(0.1)
        assert node.block_store.height >= 2

        assert _rpc(base, "health") == {}
        st = _rpc(base, "status")
        assert int(st["sync_info"]["latest_block_height"]) >= 2
        assert st["node_info"]["network"] == "rpc-chain"

        b = _rpc(base, "block", {"height": 1})
        assert b["block"]["header"]["height"] == "1"
        bh = _rpc(base, "block_by_hash", {"hash": b["block_id"]["hash"]})
        assert bh["block"]["header"]["height"] == "1"

        vals = _rpc(base, "validators")
        assert vals["total"] == "1"

        ci = _rpc(base, "commit", {"height": 1})
        assert ci["signed_header"]["commit"]["height"] == "1"

        bc = _rpc(base, "blockchain")
        assert int(bc["last_height"]) >= 2

        gen = _rpc(base, "genesis")
        assert gen["genesis"]["chain_id"] == "rpc-chain"

        # header / header_by_hash (reference: rpc/core/blocks.go:95-112)
        hd = _rpc(base, "header", {"height": 1})
        assert hd["header"]["height"] == "1"
        hdh = _rpc(base, "header_by_hash", {"hash": b["block_id"]["hash"]})
        assert hdh["header"]["height"] == "1"

        # broadcast_tx_commit waits for the block
        tx = base64.b64encode(b"rpc=tx").decode()
        res = _rpc(base, "broadcast_tx_commit", {"tx": tx})
        assert res["deliver_tx"]["code"] == 0
        assert int(res["height"]) > 0
        tx_height = int(res["height"])

        # tx with prove=true returns a Merkle inclusion proof that verifies
        # against the block's data_hash (reference: rpc/core/tx.go:47)
        from tendermint_tpu.crypto.merkle import Proof
        from tendermint_tpu.types.tx import tx_hash

        txr = _rpc(base, "tx", {"hash": base64.b64encode(
            tx_hash(b"rpc=tx")).decode(), "prove": True})
        proof_doc = txr["proof"]["proof"]
        p = Proof(total=int(proof_doc["total"]), index=int(proof_doc["index"]),
                  leaf_hash=base64.b64decode(proof_doc["leaf_hash"]),
                  aunts=[base64.b64decode(a) for a in proof_doc["aunts"]])
        blk_doc = _rpc(base, "block", {"height": tx_height})
        root = bytes.fromhex(txr["proof"]["root_hash"].lower())
        assert p.compute_root_hash() == root
        assert blk_doc["block"]["header"]["data_hash"].lower() == root.hex()

        # block_search over the block indexer with a height-range query
        # (the indexer drains the event bus asynchronously: retry briefly)
        bs = {"total_count": "0"}
        bs_deadline = time.monotonic() + 10
        while time.monotonic() < bs_deadline and int(bs["total_count"]) < 1:
            bs = _rpc(base, "block_search",
                      {"query": f"block.height>{tx_height - 1} AND "
                                f"block.height<={tx_height}"})
            time.sleep(0.2)
        assert int(bs["total_count"]) >= 1
        assert bs["blocks"][0]["block"]["header"]["height"] == str(tx_height)

        # tx_search with a comparison operator
        ts = _rpc(base, "tx_search", {"query": f"tx.height>={tx_height}"})
        assert int(ts["total_count"]) >= 1

        # abci_query sees it after commit
        q = _rpc(base, "abci_query", {"path": "", "data": b"rpc".hex()})
        assert base64.b64decode(q["response"]["value"]) == b"tx"

        # URI-style GET
        with urllib.request.urlopen(f"{base}/status", timeout=10) as r:
            doc = json.loads(r.read())
        assert int(doc["result"]["sync_info"]["latest_block_height"]) >= 1

        cs = _rpc(base, "consensus_state")
        assert "round_state" in cs
        ni = _rpc(base, "net_info")
        assert ni["n_peers"] == "0"

        # light_block route + HTTPProvider wire round-trip
        from tendermint_tpu.light.provider import (
            ErrHeightTooHigh,
            HTTPProvider,
        )

        lb_res = _rpc(base, "light_block", {"height": 1})
        provider = HTTPProvider("rpc-chain", base)
        lb = provider.light_block(1)
        assert lb.height == 1 and lb.marshal().hex() == lb_res["light_block"]
        lb.validate_basic("rpc-chain")
        latest = provider.light_block(0)
        assert latest.height >= 1
        try:
            provider.light_block(10_000)
            raise AssertionError("expected ErrHeightTooHigh")
        except ErrHeightTooHigh:
            pass

        # gateway routes: verified-or-refused plane over the same store
        g1 = _rpc(base, "gateway_light_block", {"height": 1})
        assert g1["light_block"] == lb_res["light_block"]
        assert g1["verdict"] in ("fresh", "cached")
        assert _rpc(base, "gateway_light_block", {"height": 1})["verdict"] == "cached"
        # height=0 (latest): the test chain is timestamped at genesis_time
        # (2023) which is past the trust period by real wall clock, so the
        # gateway must REFUSE with a typed degradation rather than serve.
        try:
            _rpc(base, "gateway_light_block", {"height": 0})
            raise AssertionError("expected gateway degraded refusal")
        except RuntimeError as e:
            assert "gateway degraded" in str(e)
        try:
            _rpc(base, "gateway_light_block", {"height": 10_000})
            raise AssertionError("expected height-too-high error")
        except RuntimeError as e:
            assert "must be less" in str(e)
        gs = _rpc(base, "gateway_status")
        assert gs["primary"] == "local"
        assert gs["counters"]["queries"] >= 3
        assert gs["counters"]["cache_hits"] >= 1
    finally:
        node.stop()


def test_websocket_subscription(tmp_path):
    import hashlib
    import socket
    import struct

    node = _mk_node(tmp_path)
    node.start()
    try:
        host, port = node.rpc_server.laddr.split("://", 1)[1].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        key = base64.b64encode(b"0123456789abcdef").decode()
        s.sendall((f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
                   f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                   f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
                   ).encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            resp += s.recv(4096)
        assert b"101" in resp.split(b"\r\n")[0]

        def ws_send(payload: bytes):
            mask = b"\x01\x02\x03\x04"
            masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
            hdr = bytes([0x81, 0x80 | len(payload)]) if len(payload) < 126 else None
            s.sendall(hdr + mask + masked)

        def ws_recv():
            hdr = s.recv(2)
            ln = hdr[1] & 0x7F
            if ln == 126:
                (ln,) = struct.unpack(">H", s.recv(2))
            buf = b""
            while len(buf) < ln:
                buf += s.recv(ln - len(buf))
            return buf

        sub = json.dumps({"jsonrpc": "2.0", "id": 7, "method": "subscribe",
                          "params": {"query": "tm.event='NewBlock'"}}).encode()
        ws_send(sub)
        # first reply: subscription confirmation; then block events
        got_block = False
        s.settimeout(30)
        for _ in range(5):
            doc = json.loads(ws_recv())
            result = doc.get("result", {})
            if result and result.get("data", {}).get("type") == "tendermint/event/NewBlock":
                got_block = True
                break
        assert got_block
        s.close()
    finally:
        node.stop()


def test_grpc_broadcast_api(tmp_path):
    """gRPC BroadcastAPI: Ping + BroadcastTx commit round-trip (reference:
    rpc/grpc/api.go)."""
    from tendermint_tpu.rpc.grpc_server import BroadcastAPIClient

    node = _mk_node(tmp_path)
    node.config.rpc.grpc_laddr = "tcp://127.0.0.1:0"
    node.start()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and node.block_store.height < 1:
            time.sleep(0.1)
        client = BroadcastAPIClient(node.grpc_server.laddr)
        assert client.ping()
        res = client.broadcast_tx(b"grpc=yes")
        assert res["check_tx"]["code"] == 0
        assert res["deliver_tx"]["code"] == 0
        # the tx actually landed in the app
        q = node.app.query(__import__("tendermint_tpu.abci.types", fromlist=["x"]).RequestQuery(
            path="", data=b"grpc"))
        assert q.value == b"yes"
        client.close()
    finally:
        node.stop()
