"""Types layer: sign-bytes parity vectors + structural invariants.

The golden byte vectors are the reference's own published test vectors
(reference: types/vote_test.go:60-131 TestVoteSignBytesTestVectors), proving
wire-level parity of CanonicalVote sign-bytes with the Go implementation."""

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.types.block import Block, Commit, CommitSig, Data, Header
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import (
    ErrNotEnoughVotingPowerSigned,
    ErrWrongSignature,
    ValidatorSet,
)
from tendermint_tpu.types.vote import (
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    ErrVoteConflictingVotes,
    Vote,
)
from tendermint_tpu.types.vote_set import VoteSet


def test_vote_sign_bytes_golden_vectors():
    """reference: types/vote_test.go:60-131."""
    cases = [
        ("", Vote(type=0, height=0, round=0),
         bytes([0xD, 0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1])),
        ("", Vote(type=PRECOMMIT_TYPE, height=1, round=1),
         bytes([0x21, 0x8, 0x2, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
                0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1])),
        ("", Vote(type=PREVOTE_TYPE, height=1, round=1),
         bytes([0x21, 0x8, 0x1, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
                0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1])),
        ("", Vote(type=0, height=1, round=1),
         bytes([0x1F, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
                0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1])),
        ("test_chain_id", Vote(type=0, height=1, round=1),
         bytes([0x2E, 0x11, 1, 0, 0, 0, 0, 0, 0, 0, 0x19, 1, 0, 0, 0, 0, 0, 0, 0,
                0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1,
                0x32, 0xD]) + b"test_chain_id"),
    ]
    for i, (chain_id, vote, want) in enumerate(cases):
        got = vote.sign_bytes(chain_id)
        assert got == want, f"case {i}: {got.hex()} != {want.hex()}"


def _mk_validators(n, power=10):
    out = []
    for i in range(n):
        priv = ed25519.gen_priv_key(bytes([i + 1]) * 32)
        out.append((priv, Validator.new(priv.pub_key(), power)))
    return out


def _block_id():
    return BlockID(hash=b"\xaa" * 32,
                   part_set_header=PartSetHeader(total=1, hash=b"\xbb" * 32))


def _mk_commit(chain_id, height, round_, block_id, vals, privs, *, skip=(), nil=(),
               bad_sig=()):
    sigs = []
    for i, (priv, val) in enumerate(zip(privs, vals)):
        if i in skip:
            sigs.append(CommitSig.new_absent())
            continue
        flag = BLOCK_ID_FLAG_NIL if i in nil else BLOCK_ID_FLAG_COMMIT
        ts = Time(1700000000 + i, 500)
        vote = Vote(
            type=PRECOMMIT_TYPE, height=height, round=round_,
            block_id=BlockID() if i in nil else block_id,
            timestamp=ts, validator_address=val.address, validator_index=i,
        )
        sig = priv.sign(vote.sign_bytes(chain_id))
        if i in bad_sig:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        sigs.append(CommitSig(flag, val.address, ts, sig))
    return Commit(height=height, round=round_, block_id=block_id, signatures=sigs)


def test_verify_commit_happy_and_sad():
    chain_id = "test-chain"
    pairs = _mk_validators(7)
    privs = [p for p, _ in pairs]
    vals = [v for _, v in pairs]
    vs = ValidatorSet(vals)
    # ValidatorSet sorts by power desc then address: rebuild privs in set order
    order = {v.address: privs[i] for i, (_, v) in enumerate(pairs)}
    sorted_privs = [order[v.address] for v in vs.validators]

    bid = _block_id()
    commit = _mk_commit(chain_id, 5, 2, bid, vs.validators, sorted_privs)
    vs.verify_commit(chain_id, bid, 5, commit)
    vs.verify_commit_light(chain_id, bid, 5, commit)
    vs.verify_commit_light_trusting(chain_id, commit, (1, 3))

    # two absent + one nil still passes (5 of 7 > 2/3... 4.66)
    commit2 = _mk_commit(chain_id, 5, 2, bid, vs.validators, sorted_privs, skip=(0,), nil=(1,))
    vs.verify_commit(chain_id, bid, 5, commit2)

    # bad signature fails VerifyCommit with exact index attribution
    commit3 = _mk_commit(chain_id, 5, 2, bid, vs.validators, sorted_privs, bad_sig=(3,))
    with pytest.raises(ErrWrongSignature) as ei:
        vs.verify_commit(chain_id, bid, 5, commit3)
    assert ei.value.index == 3

    # ...but VerifyCommitLight never looks at index 3 if threshold crossed by 5
    # (7 validators x10 power: need >46, first 5 give 50)
    vs.verify_commit_light(chain_id, bid, 5, _mk_commit(
        chain_id, 5, 2, bid, vs.validators, sorted_privs, bad_sig=(6,)))

    # insufficient power
    commit4 = _mk_commit(chain_id, 5, 2, bid, vs.validators, sorted_privs,
                         skip=(0, 1, 2), nil=(3,))
    with pytest.raises(ErrNotEnoughVotingPowerSigned):
        vs.verify_commit(chain_id, bid, 5, commit4)


def test_vote_set_maj23_and_commit():
    chain_id = "vs-chain"
    pairs = _mk_validators(4)
    vs = ValidatorSet([v for _, v in pairs])
    order = {v.address: p for p, v in pairs}
    sorted_privs = [order[v.address] for v in vs.validators]
    bid = _block_id()

    votes = VoteSet(chain_id, 3, 0, PRECOMMIT_TYPE, vs)
    assert not votes.has_two_thirds_majority()
    for i in range(3):
        v = Vote(type=PRECOMMIT_TYPE, height=3, round=0, block_id=bid,
                 timestamp=Time(1700000100 + i, 0),
                 validator_address=vs.validators[i].address, validator_index=i)
        v.signature = sorted_privs[i].sign(v.sign_bytes(chain_id))
        assert votes.add_vote(v)
    maj, ok = votes.two_thirds_majority()
    assert ok and maj == bid
    commit = votes.make_commit()
    assert commit.signatures[3].absent()
    vs.verify_commit_light(chain_id, bid, 3, commit)

    # duplicate add returns False
    v0 = votes.get_by_index(0)
    assert votes.add_vote(v0) is False


def test_vote_set_conflicting_vote():
    chain_id = "vs-chain"
    pairs = _mk_validators(4)
    vs = ValidatorSet([v for _, v in pairs])
    order = {v.address: p for p, v in pairs}
    sorted_privs = [order[v.address] for v in vs.validators]
    votes = VoteSet(chain_id, 3, 0, PREVOTE_TYPE, vs)

    v1 = Vote(type=PREVOTE_TYPE, height=3, round=0, block_id=_block_id(),
              timestamp=Time(1700000100, 0),
              validator_address=vs.validators[0].address, validator_index=0)
    v1.signature = sorted_privs[0].sign(v1.sign_bytes(chain_id))
    assert votes.add_vote(v1)

    v2 = Vote(type=PREVOTE_TYPE, height=3, round=0, block_id=BlockID(),
              timestamp=Time(1700000101, 0),
              validator_address=vs.validators[0].address, validator_index=0)
    v2.signature = sorted_privs[0].sign(v2.sign_bytes(chain_id))
    with pytest.raises(ErrVoteConflictingVotes) as ei:
        votes.add_vote(v2)
    assert ei.value.vote_a == v1


def test_batched_add_votes_matches_serial():
    chain_id = "batch-chain"
    pairs = _mk_validators(8)
    vs = ValidatorSet([v for _, v in pairs])
    order = {v.address: p for p, v in pairs}
    sorted_privs = [order[v.address] for v in vs.validators]
    bid = _block_id()

    def mk_votes():
        out = []
        for i in range(8):
            v = Vote(type=PREVOTE_TYPE, height=3, round=0, block_id=bid,
                     timestamp=Time(1700000100 + i, 0),
                     validator_address=vs.validators[i].address, validator_index=i)
            v.signature = sorted_privs[i].sign(v.sign_bytes(chain_id))
            if i == 5:  # corrupt one signature
                v.signature = v.signature[:-1] + bytes([v.signature[-1] ^ 1])
            out.append(v)
        return out

    serial = VoteSet(chain_id, 3, 0, PREVOTE_TYPE, vs)
    serial_results = []
    for v in mk_votes():
        try:
            serial_results.append((serial.add_vote(v), None))
        except Exception as e:  # noqa: BLE001
            serial_results.append((False, type(e).__name__))

    batched = VoteSet(chain_id, 3, 0, PREVOTE_TYPE, vs)
    batch_results = [
        (added, type(e).__name__ if e else None)
        for added, e in batched.add_votes(mk_votes())
    ]
    assert serial_results == batch_results
    assert serial.maj23 == batched.maj23
    assert serial.sum == batched.sum


def test_header_hash_changes_with_fields():
    h = Header(chain_id="c", height=3, validators_hash=b"\x01" * 32,
               proposer_address=b"\x02" * 20, time=Time(1700000000, 1))
    base = h.hash()
    assert base is not None and len(base) == 32
    h2 = Header(chain_id="c", height=4, validators_hash=b"\x01" * 32,
                proposer_address=b"\x02" * 20, time=Time(1700000000, 1))
    assert h2.hash() != base
    h3 = Header(chain_id="c", height=3, validators_hash=b"",
                proposer_address=b"\x02" * 20)
    assert h3.hash() is None


def test_part_set_roundtrip():
    data = bytes(range(256)) * 700  # ~180kB -> 3 parts
    ps = PartSet.from_data(data)
    assert ps.header().total == 3
    ps2 = PartSet.from_header(ps.header())
    assert not ps2.is_complete()
    for i in [2, 0, 1]:
        part = ps.get_part(i)
        blob = part.marshal()
        from tendermint_tpu.types.part_set import Part

        assert ps2.add_part(Part.unmarshal(blob))
    assert ps2.is_complete()
    assert ps2.assemble() == data
    # duplicate add -> False
    assert ps2.add_part(ps.get_part(0)) is False


def test_block_roundtrip_and_hash():
    pairs = _mk_validators(4)
    vs = ValidatorSet([v for _, v in pairs])
    bid = _block_id()
    commit = Commit(height=2, round=0, block_id=bid,
                    signatures=[CommitSig.new_absent() for _ in range(4)])
    b = Block(
        header=Header(chain_id="c", height=3, validators_hash=vs.hash(),
                      next_validators_hash=vs.hash(),
                      proposer_address=vs.validators[0].address,
                      time=Time(1700000000, 0)),
        data=Data(txs=[b"tx1", b"tx2"]),
        last_commit=commit,
    )
    h = b.hash()
    assert h is not None
    blob = b.marshal()
    b2 = Block.unmarshal(blob)
    assert b2.hash() == h
    assert b2.data.txs == [b"tx1", b"tx2"]
    assert b2.last_commit.block_id == bid
    b2.validate_basic()


def test_proposal_sign_roundtrip():
    priv = ed25519.gen_priv_key(b"\x07" * 32)
    p = Proposal(height=4, round=2, pol_round=-1, block_id=_block_id(),
                 timestamp=Time(1700000000, 42))
    p.signature = priv.sign(p.sign_bytes("pchain"))
    assert priv.pub_key().verify_signature(p.sign_bytes("pchain"), p.signature)
    p2 = Proposal.unmarshal(p.marshal())
    assert p2 == p


def test_validator_set_proposer_rotation():
    pairs = _mk_validators(3, power=1)
    vs = ValidatorSet([v for _, v in pairs])
    seen = []
    for _ in range(6):
        seen.append(vs.get_proposer().address)
        vs.increment_proposer_priority(1)
    # equal power: perfect round-robin over 3 validators
    assert seen[:3] == seen[3:6]
    assert len(set(seen[:3])) == 3


def test_validator_set_update_and_hash():
    pairs = _mk_validators(3, power=10)
    vs = ValidatorSet([v for _, v in pairs])
    h0 = vs.hash()
    newp = ed25519.gen_priv_key(b"\x99" * 32)
    vs.update_with_change_set([Validator.new(newp.pub_key(), 5)])
    assert vs.size() == 4
    assert vs.hash() != h0
    # new validator got the -1.125*total penalty => should not be proposer now
    assert vs.get_proposer().address != newp.pub_key().address()
    # removal via power 0
    vs.update_with_change_set([Validator.new(newp.pub_key(), 0)])
    assert vs.size() == 3


def test_commit_vote_sign_bytes_template_differential():
    """The templated Commit.vote_sign_bytes must equal building each Vote
    (types/block.py vote_sign_bytes fast path)."""
    from tendermint_tpu.types.block import Commit, CommitSig
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.ttime import Time
    from tendermint_tpu.types.vote import (
        BLOCK_ID_FLAG_ABSENT,
        BLOCK_ID_FLAG_COMMIT,
        BLOCK_ID_FLAG_NIL,
    )

    bid = BlockID(hash=b"\x11" * 32,
                  part_set_header=PartSetHeader(total=3, hash=b"\x22" * 32))
    sigs = [
        CommitSig(BLOCK_ID_FLAG_COMMIT, b"\x01" * 20, Time(1700000001, 7), b"s" * 64),
        CommitSig(BLOCK_ID_FLAG_NIL, b"\x02" * 20, Time(1700000002, 0), b"t" * 64),
        CommitSig(BLOCK_ID_FLAG_COMMIT, b"\x03" * 20, Time(0, 0), b"u" * 64),
        CommitSig(BLOCK_ID_FLAG_ABSENT, b"", Time(0, 0), b""),
    ]
    c = Commit(height=300, round=2, block_id=bid, signatures=sigs)
    for chain_id in ("chain-x", "other"):  # second id must drop the template
        for i in range(len(sigs)):
            assert (c.vote_sign_bytes(chain_id, i)
                    == c.get_vote(i).sign_bytes(chain_id)), (chain_id, i)


def test_canonical_vote_bytes_template_cache_differential():
    """canonical_vote_bytes' template cache must be invisible: byte-equal
    to a fresh construction across types, rounds, nil block ids, many
    timestamps, and cache eviction (types/vote.py)."""
    from tendermint_tpu.encoding import proto
    from tendermint_tpu.types import vote as vmod
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.ttime import Time

    def fresh(chain_id, vtype, height, round_, bid, ts):
        w = proto.Writer()
        w.varint(1, vtype)
        w.sfixed64(2, height)
        w.sfixed64(3, round_)
        cbid = vmod.canonical_block_id_bytes(bid)
        if cbid is not None:
            w.message(4, cbid, always=True)
        w.message(5, ts.marshal(), always=True)
        w.string(6, chain_id)
        return proto.delimited(w.out())

    bids = [BlockID(),
            BlockID(hash=b"\x07" * 32,
                    part_set_header=PartSetHeader(total=2, hash=b"\x08" * 32))]
    cases = []
    for h in (1, 77, 300):
        for r in (0, 5):
            for vt in (vmod.PREVOTE_TYPE, vmod.PRECOMMIT_TYPE):
                for bid in bids:
                    for ts in (Time(0, 0), Time(1_700_000_000, 999)):
                        cases.append(("chain-%d" % (h % 2), vt, h, r, bid, ts))
    vmod._CV_TEMPLATES.clear()
    for case in cases * 2:  # second pass hits the cache
        assert vmod.canonical_vote_bytes(*case) == fresh(*case), case
    # force eviction mid-stream and keep verifying
    vmod._CV_TEMPLATES.clear()
    for case in cases:
        assert vmod.canonical_vote_bytes(*case) == fresh(*case)
