"""Differential tests for the batched sr25519 verifier.

The batched path (ops/sr25519_batch: C merlin transcripts + device ristretto
decode + Edwards comb kernel) must be byte-identical in accept/reject with
the spec-faithful pure-Python crypto/sr25519.verify — the same contract the
ed25519 kernel holds against its scalar path (reference analogue:
crypto/sr25519/pubkey.go:10 go-schnorrkel wrapping)."""

import numpy as np
import pytest

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.crypto import sr25519 as sr
from tendermint_tpu.ops import chash
from tendermint_tpu.ops import sr25519_batch as srb


@pytest.fixture(scope="module")
def signed_items():
    rng = np.random.default_rng(7)
    privs = [sr.gen_priv_key(bytes([i]) * 4) for i in range(6)]
    items = []
    for i in range(18):
        p = privs[i % len(privs)]
        msg = b"vote-%d|" % i + bytes(
            rng.integers(0, 256, size=int(rng.integers(0, 120)), dtype=np.uint8))
        sig = sr.sign(p.data, msg, rng_seed=bytes([i + 1]) * 32)
        items.append((p.pub_key().data, msg, sig))
    return items


def test_challenges_match_pure_python(signed_items):
    """The C STROBE/merlin stack produces the exact transcript challenge the
    pure-Python Transcript does, for varied message lengths."""
    if not chash.available():
        pytest.skip("C hash library unavailable")
    n = len(signed_items)
    pubs = np.frombuffer(
        b"".join(it[0] for it in signed_items), dtype=np.uint8).reshape(n, 32)
    rs = np.frombuffer(
        b"".join(it[2][:32] for it in signed_items), dtype=np.uint8).reshape(n, 32)
    got = srb.challenges([it[1] for it in signed_items], pubs, rs)
    for i, (pub, msg, sig) in enumerate(signed_items):
        t = sr._signing_context(msg)
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", pub)
        t.append_message(b"sign:R", sig[:32])
        want = t.challenge_scalar(b"sign:c")
        assert int.from_bytes(got[i].tobytes(), "little") == want


def test_batch_matches_scalar_verify(signed_items):
    """Valid + systematically corrupted signatures: the batch bitmap equals
    the scalar path decision for every item."""
    pub, msg, sig = signed_items[0]
    bad = [
        (pub, msg + b"!", sig),                        # wrong message
        (pub, msg, sig[:32] + bytes(31) + b"\x80"),    # forged s=0
        (pub, msg, bytes(sig[:63]) + bytes([sig[63] & 0x7F])),  # marker clear
        (pub, msg, bytes([sig[0] ^ 1]) + sig[1:]),     # R parity flip
        (pub, msg, sig[:20] + b"\x01" + sig[21:]),     # R tweak
        (b"\xff" * 32, msg, sig),                      # undecodable pub
        (pub, msg, sig[:12]),                          # truncated sig
        (signed_items[1][0], msg, sig),                # wrong pubkey
        # non-canonical s: add L to a small s (stays < 2^255 with marker)
        (pub, msg, sig[:32]
         + ((int.from_bytes(sig[32:], "little") & ((1 << 255) - 1)) % sr.L + sr.L
            ).to_bytes(32, "little")[:31]
         + bytes([(((int.from_bytes(sig[32:], "little") & ((1 << 255) - 1)) % sr.L
                    + sr.L) >> 248 | 0x80) & 0xFF])),
    ]
    allitems = list(signed_items) + bad
    got = srb.verify_batch(allitems)
    want = np.array([sr.verify(p, m, s) for (p, m, s) in allitems])
    assert (got == want).all()
    assert got[: len(signed_items)].all()
    assert not got[len(signed_items):].any()


def test_registered_batch_verifier(signed_items, monkeypatch):
    """sr25519 now routes through the batched verifier (VERDICT r3: it used
    to fall to the serial scalar loop inside MixedBatchVerifier)."""
    monkeypatch.setenv("TM_TPU_BATCH_MIN", "1")
    assert cbatch.supports_batch("sr25519")
    v = cbatch.create_batch_verifier("sr25519")
    assert isinstance(v, cbatch.Sr25519BatchVerifier)
    for pub, msg, sig in signed_items[:4]:
        v.add(sr.PubKey(pub), msg, sig)
    ok, bitmap = v.verify()
    assert ok and bitmap == [True] * 4

    mixed = cbatch.create_batch_verifier()
    from tendermint_tpu.crypto import ed25519 as ed

    epriv = ed.gen_priv_key(b"\x05" * 32)
    mixed.add(epriv.pub_key(), b"m0", ed.sign(epriv.data, b"m0"))
    pub, msg, sig = signed_items[0]
    mixed.add(sr.PubKey(pub), msg, sig)
    mixed.add(epriv.pub_key(), b"m1", ed.sign(epriv.data, b"mX"))  # bad
    ok, bitmap = mixed.verify()
    assert not ok and bitmap == [True, True, False]
