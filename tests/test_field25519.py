"""Differential tests: limb field arithmetic vs Python bignum ground truth."""

import random

import numpy as np
import jax.numpy as jnp

from tendermint_tpu.ops import field25519 as fe

P = fe.P
rng = random.Random(1234)


def _rand_ints(n):
    vals = [rng.randrange(P) for _ in range(n - 4)]
    vals += [0, 1, P - 1, 2**255 - 20]  # edge values
    return vals


def _to_dev(vals):
    return jnp.asarray(np.stack([fe.from_int(v) for v in vals]))


def test_roundtrip():
    vals = _rand_ints(16)
    arr = _to_dev(vals)
    for i, v in enumerate(vals):
        assert fe.to_int(np.asarray(arr[i])) == v % P


def test_mul_add_sub():
    a_vals = _rand_ints(32)
    b_vals = _rand_ints(32)
    a, b = _to_dev(a_vals), _to_dev(b_vals)
    m = np.asarray(fe.to_canonical(fe.mul(a, b)))
    s = np.asarray(fe.to_canonical(fe.add(a, b)))
    d = np.asarray(fe.to_canonical(fe.sub(a, b)))
    for i in range(32):
        assert fe.to_int(m[i]) == a_vals[i] * b_vals[i] % P
        assert fe.to_int(s[i]) == (a_vals[i] + b_vals[i]) % P
        assert fe.to_int(d[i]) == (a_vals[i] - b_vals[i]) % P


def test_chained_ops_stay_bounded():
    """Long chains of add/sub/mul keep limbs inside the NORM bound and remain
    exact -- catches int32 overflow in the bound analysis."""
    a_vals = _rand_ints(8)
    b_vals = _rand_ints(8)
    a, b = _to_dev(a_vals), _to_dev(b_vals)
    ga, gb = list(a_vals), list(b_vals)
    for step in range(30):
        if step % 3 == 0:
            a = fe.mul(fe.add(a, b), fe.sub(a, b))
            ga = [(x + y) * (x - y) % P for x, y in zip(ga, gb)]
        elif step % 3 == 1:
            b = fe.add(fe.mul(b, b), a)
            gb = [(y * y + x) % P for x, y in zip(ga, gb)]
        else:
            a = fe.sub(fe.mul_small(a, 2), b)
            ga = [(2 * x - y) % P for x, y in zip(ga, gb)]
        assert int(jnp.max(a)) < 9500 and int(jnp.max(b)) < 9500
        assert int(jnp.min(a)) >= 0 and int(jnp.min(b)) >= 0
    am = np.asarray(fe.to_canonical(a))
    bm = np.asarray(fe.to_canonical(b))
    for i in range(8):
        assert fe.to_int(am[i]) == ga[i]
        assert fe.to_int(bm[i]) == gb[i]


def test_sub_with_max_top_limb():
    """Regression: b's top limb can legitimately reach 8191 (loose NORM);
    the fat-limb bias in sub must cover it (every bias limb >= 9500)."""
    assert int(fe.PSUB_LIMBS.min()) >= 9500
    # craft b with all limbs at the max a carry pass can emit (8191) and a=0
    b_limbs = np.full((1, fe.NLIMB), 8191, dtype=np.int32)
    b_int = fe.to_int(b_limbs[0])
    a = jnp.zeros((1, fe.NLIMB), dtype=jnp.int32)
    d = fe.sub(a, jnp.asarray(b_limbs))
    assert int(jnp.min(d)) >= 0 and int(jnp.max(d)) < 9500
    assert fe.to_int(np.asarray(fe.to_canonical(d))[0]) == (-b_int) % fe.P


def test_inv():
    vals = [v for v in _rand_ints(16) if v != 0]
    a = _to_dev(vals)
    iv = np.asarray(fe.to_canonical(fe.inv(a)))
    for i, v in enumerate(vals):
        assert fe.to_int(iv[i]) == pow(v, P - 2, P)


def test_canonical_reduces_below_p():
    vals = [P - 1, 0, 1, 2**255 - 20, 2**255 - 19]
    a = _to_dev(vals)
    c = np.asarray(fe.to_canonical(a))
    for i, v in enumerate(vals):
        got = fe.to_int(c[i])
        assert got == v % P
        assert got < P
