"""The verified-signature cache (crypto/sigcache): gossip delivers the same
vote from several peers, and a bounded LRU of known-good (pub, msg, sig)
digests lets the repeat copies skip the kernel/scalar verify and go straight
to the serial accept-replay (ISSUE 4 second prong)."""

import pytest

from tendermint_tpu.crypto import batch as cbatch
from tendermint_tpu.crypto import ed25519, sigcache
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import PREVOTE_TYPE, Vote
from tendermint_tpu.types.vote_set import VoteSet

CHAIN_ID = "sigcache-chain"


@pytest.fixture(autouse=True)
def fresh_cache():
    sigcache.reset()
    yield
    sigcache.reset()


def _net(n):
    privs = [ed25519.gen_priv_key((i + 1).to_bytes(2, "big") * 16)
             for i in range(n)]
    vals = ValidatorSet(
        [Validator(p.pub_key().address(), p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return [by_addr[v.address] for v in vals.validators], vals


def _votes(privs, vals, tamper=()):
    bid = BlockID(hash=b"\x31" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x32" * 32))
    out = []
    for i, p in enumerate(privs):
        v = Vote(type=PREVOTE_TYPE, height=1, round=0, block_id=bid,
                 timestamp=Time(1_700_002_000, 0),
                 validator_address=vals.validators[i].address,
                 validator_index=i)
        sig = p.sign(v.sign_bytes(CHAIN_ID))
        if i in tamper:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        v.signature = sig
        out.append(v)
    return out


class _DispatchSpy:
    """Counts how many items each registry dispatch actually verifies."""

    def __init__(self, monkeypatch):
        self.batches: list[int] = []
        orig = cbatch._KernelBatchVerifier.dispatch
        spy = self

        def counted(vself, force_device=False):
            spy.batches.append(len(vself._items))
            return orig(vself, force_device=force_device)

        monkeypatch.setattr(cbatch._KernelBatchVerifier, "dispatch", counted)

    @property
    def items(self):
        return sum(self.batches)


def test_lru_eviction_at_cap():
    c = sigcache.SigCache(cap=3)
    keys = [sigcache.cache_key(b"p%d" % i, b"m", b"s") for i in range(4)]
    for k in keys[:3]:
        c.add(k)
    assert c.hit(keys[0])          # refresh 0: now 1 is LRU
    c.add(keys[3])                 # evicts 1
    assert len(c) == 3
    assert c.hit(keys[0]) and c.hit(keys[2]) and c.hit(keys[3])
    assert not c.hit(keys[1])
    assert c.hits == 4 and c.misses == 1


def test_cache_key_framing():
    """Length framing: shifting bytes between pub and msg must not collide."""
    assert (sigcache.cache_key(b"ab", b"c", b"s")
            != sigcache.cache_key(b"a", b"bc", b"s"))


def test_hit_skips_device_dispatch(monkeypatch):
    """The fetch-spy gate: a second delivery of the same votes (fresh
    VoteSet, same height/round -- the gossip re-delivery shape) must verify
    ZERO items through the registry dispatch; every triple comes from the
    cache and goes straight to the accept-replay."""
    privs, vals = _net(6)
    votes = _votes(privs, vals)
    spy = _DispatchSpy(monkeypatch)

    vs1 = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    res1 = vs1.add_votes(votes)
    assert all(ok for ok, _ in res1)
    first_items = spy.items
    assert first_items == len(votes)

    vs2 = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    res2 = vs2.add_votes(votes)
    assert all(ok for ok, _ in res2)
    assert spy.items == first_items, (
        "cache hit still paid a verify: second delivery dispatched "
        f"{spy.items - first_items} items")
    c = sigcache.get()
    assert c is not None and c.hits == len(votes)


def test_tampered_sig_never_caches_as_valid(monkeypatch):
    privs, vals = _net(5)
    votes = _votes(privs, vals, tamper={2})
    spy = _DispatchSpy(monkeypatch)

    vs1 = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    res1 = vs1.add_votes(votes)
    assert [ok for ok, _ in res1] == [True, True, False, True, True]
    assert "invalid signature" in str(res1[2][1])

    # Second delivery: the four good votes hit the cache; the tampered one
    # MUST miss, re-verify, and be rejected again.
    vs2 = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    before = spy.items
    res2 = vs2.add_votes(votes)
    assert [ok for ok, _ in res2] == [True, True, False, True, True]
    assert "invalid signature" in str(res2[2][1])
    assert spy.items - before == 1  # only the tampered lane re-verified
    assert len(sigcache.get()) == 4


def test_disabled_by_env(monkeypatch):
    monkeypatch.setenv("TM_TPU_SIGCACHE", "0")
    assert sigcache.get() is None
    privs, vals = _net(3)
    votes = _votes(privs, vals)
    spy = _DispatchSpy(monkeypatch)
    for _ in range(2):
        vs = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
        assert all(ok for ok, _ in vs.add_votes(votes))
    assert spy.items == 2 * len(votes)  # both deliveries paid full verify


def test_cap_env_knob(monkeypatch):
    monkeypatch.setenv("TM_TPU_SIGCACHE_CAP", "2")
    privs, vals = _net(5)
    votes = _votes(privs, vals)
    vs = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    assert all(ok for ok, _ in vs.add_votes(votes))
    assert len(sigcache.get()) == 2  # LRU held at the cap


def test_device_fault_does_not_poison_cache(monkeypatch):
    """TMTPU_FAULTS device-failure interaction: with the ed25519 device
    route raising, the breaker degrades the flush to the host fallback
    WITHIN the same dispatch -- the resolved bitmap is still correct, so
    good votes may cache, but the tampered lane must stay uncached and
    rejected. A flush whose resolve RAISES outright caches nothing."""
    from tendermint_tpu.ops import ed25519_batch as edb
    from tendermint_tpu.utils import faults

    privs, vals = _net(4)
    votes = _votes(privs, vals, tamper={1})
    # Pin the kernel route (no host crossover absorb) so the injected
    # device fault actually fires, and drop batch_min so 4 votes dispatch.
    monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "0")
    monkeypatch.setenv("TM_TPU_BATCH_MIN", "1")
    faults.configure(["ops.ed25519.device:raise"], seed=7)
    edb.BREAKER.reset()
    try:
        vs = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
        res = vs.add_votes(votes)
        assert [ok for ok, _ in res] == [True, False, True, True]
        assert edb.BREAKER.failures >= 1  # the fault really fired
        c = sigcache.get()
        assert len(c) == 3  # only the host-reverified good lanes
        bad = votes[1]
        ck = sigcache.cache_key(
            vals.validators[1].pub_key.bytes(),
            bad.sign_bytes(CHAIN_ID), bad.signature)
        assert not c.hit(ck)
    finally:
        faults.clear()
        edb.BREAKER.reset()

    # Resolve-raises-outright: nothing may enter the cache.
    sigcache.reset()

    def broken_dispatch(vself, force_device=False):
        vself._items = []

        def boom(_fetched):
            raise RuntimeError("device died at fetch")

        return cbatch.PendingVerify([object()], boom)

    monkeypatch.setattr(cbatch._KernelBatchVerifier, "dispatch",
                        broken_dispatch)
    vs = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    with pytest.raises(RuntimeError):
        vs.add_votes(votes)
    assert len(sigcache.get()) == 0
