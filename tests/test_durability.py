"""The self-healing storage plane (docs/DURABILITY.md): record-level
integrity envelopes, bit-rot fault injection, quarantine, and repair —
peer-assisted block re-fetch (batch-verified before rewrite), state
rebuild-from-blockstore, index re-derivation — plus the BlockStore pruning
coverage (BH:/part rows actually deleted; a pruned gap scrubs healthy)."""

import os
import sqlite3
import time

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.state import store as ss_mod
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.state.store import ErrNoValSetForHeight, StateStore
from tendermint_tpu.state.txindex import BlockIndexer, TxIndexer
from tendermint_tpu.store import block_store as bs_mod
from tendermint_tpu.store import envelope
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.db import MemDB, SQLiteDB, prefix_end
from tendermint_tpu.store.repair import (
    StoreRepairer,
    rebuild_state_from_blockstore,
    recover_state,
)
from tendermint_tpu.store.scrub import Scrubber
from tendermint_tpu.types.block import Commit, CommitSig
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.vote import BLOCK_ID_FLAG_COMMIT, PRECOMMIT_TYPE, Vote
from tendermint_tpu.utils import faults


# --- chain-building helpers (the test_storage_execution.py idiom) ------------


def _genesis(n_vals=1, chain_id="dur-chain"):
    privs = [ed25519.gen_priv_key(bytes([60 + i]) * 32) for i in range(n_vals)]
    gvals = [GenesisValidator(b"", p.pub_key(), 10) for p in privs]
    gd = GenesisDoc(chain_id=chain_id, validators=gvals,
                    genesis_time=Time(1700000000, 0))
    gd.validate_and_complete()
    return gd, privs


def _commit_for(state, block, privs, round_=0):
    bid = BlockID(hash=block.hash(),
                  part_set_header=PartSet.from_data(block.marshal()).header())
    sigs = []
    by_addr = {p.pub_key().address(): p for p in privs}
    for val in state.validators.validators:
        priv = by_addr[val.address]
        v = Vote(type=PRECOMMIT_TYPE, height=block.header.height, round=round_,
                 block_id=bid, timestamp=block.header.time.add_ns(1_000_000),
                 validator_address=val.address,
                 validator_index=state.validators.get_by_address(val.address)[0])
        v.signature = priv.sign(v.sign_bytes(state.chain_id))
        sigs.append(CommitSig(BLOCK_ID_FLAG_COMMIT, val.address, v.timestamp,
                              v.signature))
    return bid, Commit(height=block.header.height, round=round_, block_id=bid,
                       signatures=sigs)


def _build_chain(heights=4, n_vals=2):
    """A real committed chain in real stores: BlockExecutor + kvstore apply
    per height, every block saved with its parts and seen commit."""
    from tendermint_tpu.mempool.mempool import Mempool

    gd, privs = _genesis(n_vals)
    state = make_genesis_state(gd)
    block_store = BlockStore(MemDB())
    state_store = StateStore(MemDB())
    state_store.save(state)
    app = KVStoreApplication()
    mp = Mempool(app)
    bx = BlockExecutor(state_store, app, mempool=mp,
                       block_store=block_store)
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    for h in range(1, heights + 1):
        mp.check_tx(b"dur%d=v%d" % (h, h))
        proposer = state.validators.get_proposer()
        block = bx.create_proposal_block(h, state, last_commit,
                                         proposer.address)
        bid, commit = _commit_for(state, block, privs)
        block_store.save_block(block, PartSet.from_data(block.marshal()),
                               commit)
        state, _ = bx.apply_block(state, bid, block)
        last_commit = commit
    return block_store, state_store, gd, privs, state


# --- envelope ----------------------------------------------------------------


def test_envelope_roundtrip_and_detection():
    w = envelope.wrap(b"payload")
    assert envelope.is_framed(w)
    assert envelope.unwrap(w, "block", b"k") == b"payload"
    # every single-bit flip anywhere in the CRC or payload is detected
    for pos in range(2, len(w)):
        bad = w[:pos] + bytes([w[pos] ^ 1]) + w[pos + 1:]
        with pytest.raises(envelope.CorruptedStoreError) as ei:
            envelope.unwrap(bad, "block", b"thekey")
        assert ei.value.key == b"thekey" and ei.value.store == "block"
    # truncation inside the header, and to nothing
    with pytest.raises(envelope.CorruptedStoreError):
        envelope.unwrap(w[:4], "block", b"k")
    with pytest.raises(envelope.CorruptedStoreError):
        envelope.unwrap(b"", "block", b"k")
    # unframed (legacy) rows pass through untouched
    assert envelope.unwrap(b"\x0a\x04abcd", "block", b"k") == b"\x0a\x04abcd"


def test_decode_converts_bare_errors_to_typed():
    def boom(_):
        raise ValueError("not a proto")

    with pytest.raises(envelope.CorruptedStoreError) as ei:
        envelope.decode(b"legacy-garbage", "state", b"vk", boom)
    assert "decode failed" in ei.value.reason
    hook_calls = []
    with pytest.raises(envelope.CorruptedStoreError):
        envelope.decode(envelope.wrap(b"x")[:-1] + b"\x00", "state", b"vk",
                        lambda b: b, on_corruption=hook_calls.append)
    assert len(hook_calls) == 1 and hook_calls[0].key == b"vk"


def test_quarantine_moves_record_out_of_live_keyspace():
    db = MemDB()
    db.set(b"k1", b"rotten")
    err = envelope.CorruptedStoreError("block", b"k1", "test", b"rotten")
    envelope.quarantine(db, err)
    assert db.get(b"k1") is None
    assert db.get(b"Q:k1") == b"rotten"
    assert envelope.quarantined_keys(db) == [b"k1"]
    envelope.quarantine(db, err)  # idempotent
    assert db.get(b"Q:k1") == b"rotten"


# --- block store -------------------------------------------------------------


def test_block_store_loads_are_checked_and_hook_fires():
    bs, _ss, _gd, _privs, _state = _build_chain(3)
    detected = []
    bs.on_corruption = detected.append
    pkey = bs_mod._part_key(2, 0)
    orig = bs._db.get(pkey)
    assert envelope.is_framed(orig)
    faults.corrupt_db(bs._db, pkey, mode="bitrot", seed=11)
    with pytest.raises(envelope.CorruptedStoreError) as ei:
        bs.load_block_part(2, 0)
    assert ei.value.key == pkey and ei.value.store == "block"
    assert detected and detected[0].key == pkey
    # intact heights unaffected
    assert bs.load_block(3) is not None


def test_block_store_legacy_unframed_rows_read_compatibly():
    bs, _ss, _gd, _privs, _state = _build_chain(2)
    meta = bs.load_block_meta(2)
    # rewrite the row UNFRAMED, as a pre-envelope store would have left it
    bs._db.set(bs_mod._meta_key(2), meta.marshal())
    again = bs.load_block_meta(2)
    assert again.block_id.hash == meta.block_id.hash
    assert bs.load_block(2) is not None


def test_block_store_state_row_self_heals():
    bs, _ss, _gd, _privs, _state = _build_chain(3)
    db = bs._db
    faults.corrupt_db(db, b"blockStore", mode="truncate", seed=3)
    healed = BlockStore(db)  # constructor rederives {base, height}
    assert (healed.base, healed.height) == (1, 3)
    assert envelope.unwrap(db.get(b"blockStore"), "block", b"blockStore")


def test_bitrot_fault_site_rules_are_deterministic():
    bs, _ss, _gd, _privs, _state = _build_chain(2)
    faults.configure(["store.block.load:bitrot@1"], seed=77)
    try:
        with pytest.raises(envelope.CorruptedStoreError):
            bs.load_block_meta(1)
        # rule exhausted (@1 fires once): the UNDERLYING row is untouched
        assert bs.load_block_meta(1) is not None
        faults.reset()
        with pytest.raises(envelope.CorruptedStoreError):
            bs.load_block_meta(1)
    finally:
        faults.clear()


def test_drop_rule_reads_as_missing_and_truncate_detected():
    bs, _ss, _gd, _privs, _state = _build_chain(2)
    faults.configure(["store.block.load:drop@1"], seed=5)
    try:
        assert bs.load_block_meta(1) is None  # lost, not corrupt
        assert bs.load_block_meta(1) is not None
        faults.configure(["store.block.load:truncate@1"], seed=5)
        with pytest.raises(envelope.CorruptedStoreError):
            bs.load_block_meta(1)
    finally:
        faults.clear()


def test_value_actions_rejected_at_message_sites():
    faults.configure(["p2p.send:bitrot"], seed=1)
    try:
        with pytest.raises(faults.FaultError):
            faults.fire("p2p.send")
    finally:
        faults.clear()


def test_corrupt_db_is_deterministic_per_seed():
    a, b = MemDB(), MemDB()
    for db in (a, b):
        db.set(b"k", envelope.wrap(b"some-payload-bytes"))
    oa = faults.corrupt_db(a, b"k", mode="bitrot", seed=9)
    ob = faults.corrupt_db(b, b"k", mode="bitrot", seed=9)
    assert oa == ob and a.get(b"k") == b.get(b"k") != oa
    with pytest.raises(faults.FaultError):
        faults.corrupt_db(a, b"absent", mode="bitrot")
    with pytest.raises(faults.FaultError):
        faults.corrupt_db(a, b"k", mode="melt")


# --- pruning (satellite: BH:/part rows really deleted; gap scrubs healthy) ---


def test_pruning_deletes_bh_and_part_rows_and_gap_scrubs_healthy():
    bs, ss, _gd, _privs, _state = _build_chain(5)
    db = bs._db
    hashes = {h: bs.load_block_meta(h).block_id.hash for h in range(1, 6)}
    assert bs.prune_blocks(4) == 3  # heights 1..3 go, 4..5 stay
    for h in range(1, 4):
        assert db.get(bs_mod._meta_key(h)) is None
        assert db.get(bs_mod._hash_key(hashes[h])) is None, h
        pp = b"P:%020d:" % h
        assert not list(db.iterator(pp, prefix_end(pp))), h
        assert db.get(bs_mod._seen_commit_key(h)) is None
    for h in (4, 5):
        assert bs.load_block(h) is not None
        assert db.get(bs_mod._hash_key(hashes[h])) is not None
    assert (bs.base, bs.height) == (4, 5)
    report = Scrubber(block_store=bs, state_store=ss).scrub()
    assert report.ok, report.as_dict()  # a pruned gap is NOT corruption
    assert report.pruned_gap_heights == 3


def test_pruning_survives_corrupt_meta_via_prefix_scan():
    bs, _ss, _gd, _privs, _state = _build_chain(4)
    db = bs._db
    h2_hash = bs.load_block_meta(2).block_id.hash
    faults.corrupt_db(db, bs_mod._meta_key(2), mode="bitrot", seed=13)
    assert bs.prune_blocks(3) == 2
    assert db.get(bs_mod._meta_key(2)) is None
    assert db.get(bs_mod._hash_key(h2_hash)) is None  # found by BH scan
    pp = b"P:%020d:" % 2
    assert not list(db.iterator(pp, prefix_end(pp)))
    assert Scrubber(block_store=bs).scrub().ok


# --- scrubber: offline matrix ------------------------------------------------


@pytest.mark.parametrize("mode", ["bitrot", "truncate"])
def test_scrubber_detects_every_block_row_class(mode):
    bs, _ss, _gd, _privs, _state = _build_chain(3)
    keys = [bs_mod._meta_key(2), bs_mod._part_key(2, 0),
            bs_mod._commit_key(2), bs_mod._seen_commit_key(2)]
    for k in keys:
        assert bs._db.get(k) is not None, k
        faults.corrupt_db(bs._db, k, mode=mode, seed=21)
    report = Scrubber(block_store=bs).scrub()
    found = {c.key for c in report.corruptions}
    assert set(keys) <= found, set(keys) - found
    # quarantined: nothing corrupt is ever served again
    assert bs.load_block(2) is None
    assert bs.load_block(3) is not None
    for k in keys:
        assert bs._db.get(k) is None


@pytest.mark.parametrize("mode", ["bitrot", "truncate"])
def test_scrubber_detects_state_rows(mode):
    _bs, ss, _gd, _privs, _state = _build_chain(3)
    keys = [b"stateKey", ss_mod._val_key(2), ss_mod._params_key(2),
            ss_mod._abci_key(2)]
    for k in keys:
        assert ss._db.get(k) is not None, k
        faults.corrupt_db(ss._db, k, mode=mode, seed=22)
    report = Scrubber(state_store=ss).scrub()
    found = {c.key for c in report.corruptions}
    assert set(keys) <= found, set(keys) - found


def test_scrubber_flags_dangling_bh_row():
    bs, _ss, _gd, _privs, _state = _build_chain(2)
    bs._db.set(bs_mod._hash_key(b"\xaa" * 32), envelope.wrap(b"2"))
    report = Scrubber(block_store=bs).scrub()
    assert any(b"BH:" in c.key and "dangling" in c.reason
               for c in report.corruptions), report.as_dict()


# --- state repair ------------------------------------------------------------


def test_recover_state_rebuilds_from_blockstore():
    bs, ss, _gd, _privs, state = _build_chain(4)
    tip_meta = bs.load_block_meta(4)
    faults.corrupt_db(ss._db, b"stateKey", mode="bitrot", seed=31)
    rebuilt = recover_state(ss, bs)
    assert rebuilt.last_block_height == 3
    assert rebuilt.app_hash == tip_meta.header.app_hash
    assert rebuilt.chain_id == state.chain_id
    assert rebuilt.validators.hash() == state.validators.hash()
    # ...and the rewritten row reads back clean
    assert ss.load().last_block_height == 3


def test_recover_state_falls_back_to_bootstrap_when_unrebuildable():
    ss = StateStore(MemDB())
    bs = BlockStore(MemDB())
    ss._set(b"stateKey", b"\xde\xad\xbe\xef")  # framed garbage payload
    faults.corrupt_db(ss._db, b"stateKey", mode="bitrot", seed=1)
    st = recover_state(ss, bs)
    assert st.is_empty()  # routes into statesync/fast-sync bootstrap


def test_repairer_state_task_sets_needs_statesync_verdict():
    ss = StateStore(MemDB())
    bs = BlockStore(MemDB())
    rep = StoreRepairer(block_store=bs, state_store=ss)
    assert rep.repair_state() is True  # empty store: bootstrap's problem
    assert rep.needs_statesync


def test_validators_row_repair_tip_window_and_pointer():
    bs, ss, _gd, _privs, state = _build_chain(4)
    rep = StoreRepairer(block_store=bs, state_store=ss, chain_id="dur-chain")
    tip = state.last_block_height
    # tip-window row: rewritten FULL from the live state row
    vkey = ss_mod._val_key(tip + 1)
    faults.corrupt_db(ss._db, vkey, mode="truncate", seed=41)
    with pytest.raises(envelope.CorruptedStoreError):
        ss.load_validators(tip + 1)
    assert rep._repair_validators_row(tip + 1)
    assert ss.load_validators(tip + 1).hash() == state.validators.hash()
    # mid-chain pointer row: re-derived from the NEXT row's back-pointer
    # (validators never changed, so rows 2..N point at last_changed=1)
    nxt = ss.validators_last_changed(3)
    assert nxt is not None and nxt < 2
    envelope.quarantine(ss._db, envelope.CorruptedStoreError(
        "state", ss_mod._val_key(2), "test"))
    assert rep._repair_validators_row(2)
    assert ss.load_validators(2).hash() == state.validators.hash()


# --- evidence + txindex ------------------------------------------------------


def _fake_evidence(n=0):
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence

    return DuplicateVoteEvidence(
        vote_a=Vote(height=2, round=0, type=PRECOMMIT_TYPE,
                    validator_address=bytes([0x11 + n]) * 20,
                    signature=b"\x22" * 64),
        vote_b=Vote(height=2, round=0, type=PRECOMMIT_TYPE,
                    validator_address=bytes([0x11 + n]) * 20,
                    signature=b"\x33" * 64),
        total_voting_power=30, validator_power=10,
        timestamp=Time(1700000000, 0))


def test_evidence_pool_quarantines_corrupt_rows_and_keeps_serving():
    from tendermint_tpu.evidence.pool import EvidencePool, _pending_key

    pool = EvidencePool(MemDB(), None, None)
    good, bad = _fake_evidence(0), _fake_evidence(1)
    pool._db.set(_pending_key(good), envelope.wrap(good.bytes()))
    pool._db.set(_pending_key(bad), envelope.wrap(bad.bytes()))
    faults.corrupt_db(pool._db, _pending_key(bad), mode="bitrot", seed=51)
    evs, _sz = pool.pending_evidence(-1)
    assert [e.hash() for e in evs] == [good.hash()]  # rot never gossiped
    assert pool._db.get(_pending_key(bad)) is None   # quarantined
    evs2, _ = pool.pending_evidence(-1)
    assert [e.hash() for e in evs2] == [good.hash()]


def test_txindexer_detects_and_repairer_reindexes():
    bs, ss, _gd, _privs, _state = _build_chain(3)
    idb = MemDB()
    txi, bli = TxIndexer(idb), BlockIndexer(idb)
    block2 = bs.load_block(2)
    assert block2.data.txs
    resp = ss.load_abci_responses(2)
    for i, tx in enumerate(block2.data.txs):
        txi.index(2, i, tx, resp.deliver_txs[i] if resp.deliver_txs else None)
    from tendermint_tpu.types.tx import tx_hash

    h0 = tx_hash(block2.data.txs[0])
    assert txi.get(h0) is not None
    # corrupt the document row: read raises typed, quarantines
    faults.corrupt_db(idb, b"txr/" + h0, mode="bitrot", seed=61)
    with pytest.raises(envelope.CorruptedStoreError):
        txi.get(h0)
    assert idb.get(b"txr/" + h0) is None
    # corrupt a posting row carrying the height: the repairer re-derives
    # the whole height from the block + ABCI-responses stores
    pkeys = [k for k, _ in idb.iterator(b"txe/", prefix_end(b"txe/"))]
    assert pkeys
    faults.corrupt_db(idb, pkeys[0], mode="truncate", seed=62)
    rep = StoreRepairer(block_store=bs, state_store=ss, tx_indexer=txi,
                        block_indexer=bli)
    report = Scrubber(txindex_db=idb).scrub(repairer=rep)
    assert report.corruptions
    assert not rep.pending()
    assert txi.get(h0) is not None  # the reindex restored the doc row too
    assert txi.search("tx.height=2")


# --- SQLite durability knob --------------------------------------------------


def test_sqlite_db_sync_knob(tmp_path, monkeypatch):
    db = SQLiteDB(str(tmp_path / "n.db"))
    assert db._conn.execute("PRAGMA synchronous").fetchone()[0] == 1  # NORMAL
    db.close()
    monkeypatch.setenv("TMTPU_DB_SYNC", "full")
    db = SQLiteDB(str(tmp_path / "f.db"))
    assert db._conn.execute("PRAGMA synchronous").fetchone()[0] == 2  # FULL
    db.set(b"k", b"v")
    db.close()  # fsync-on-close folds the WAL; DB must reopen clean
    monkeypatch.setenv("TMTPU_DB_SYNC", "normal")
    db = SQLiteDB(str(tmp_path / "f.db"))
    assert db.get(b"k") == b"v"
    db.close()
    monkeypatch.setenv("TMTPU_DB_SYNC", "paranoid")
    with pytest.raises(ValueError):
        SQLiteDB(str(tmp_path / "x.db"))


def test_sqlite_close_truncates_wal(tmp_path):
    path = str(tmp_path / "w.db")
    db = SQLiteDB(path)
    for i in range(32):
        db.set(b"k%d" % i, envelope.wrap(b"v" * 128))
    db.close()
    wal = path + "-wal"
    assert not os.path.exists(wal) or os.path.getsize(wal) == 0


# --- soak grammar ------------------------------------------------------------


def test_soak_bitrot_action_grammar_roundtrip():
    from tendermint_tpu.e2e.soak import SoakAction, SoakSchedule

    a = SoakAction.parse("@7:bitrot:2:state:truncate")
    assert (a.kind, a.arg) == ("bitrot", "2:state:truncate")
    assert a.describe() == "@7:bitrot:2:state:truncate"
    # generated schedules can carry the perturbation (seeded determinism)
    for seed in range(30):
        sched = SoakSchedule.generate(seed, 60.0, 8)
        again = SoakSchedule.parse(sched.describe())
        assert again.describe() == sched.describe()
        if any(x.kind == "bitrot" for x in sched.actions):
            break
    else:
        pytest.fail("no seed in 0..29 generated a bitrot perturbation")


# --- the fabric acceptance scenario ------------------------------------------


def test_fabric_bitrot_detect_and_peer_repair(tmp_path):
    """ISSUE acceptance: inject bit-rot into one node's blockstore and
    statestore mid-run; the node detects on read, never serves a corrupt
    part, repairs blocks from peers (batch-verified before rewrite), and
    the cluster converges with full-prefix agreement."""
    from tendermint_tpu.e2e.fabric import Cluster
    from tendermint_tpu.rpc import core as rpc_core

    def tweak(cfg, idx):
        cfg.rpc.unsafe = True  # exercise the unsafe_scrub route in-process

    cluster = Cluster(str(tmp_path), 3, tweak=tweak)
    cluster.start()
    try:
        assert cluster.wait_min_height(3, 60.0), cluster.heights()
        node = cluster.nodes[0].node
        bs = node.block_store
        h = 2
        originals = {k: bs._db.get(k)
                     for k in (bs_mod._meta_key(h), bs_mod._part_key(h, 0))}
        for k in originals:
            faults.corrupt_db(bs._db, k, mode="bitrot", seed=71)
        # a peer asking for the block hits the corrupt rows: the serving
        # path must answer no-block (detection -> quarantine), never rot
        peer_block = None
        try:
            peer_block = bs.load_block(h)
        except envelope.CorruptedStoreError:
            pass
        assert peer_block is None
        assert bs.load_block(h) is None  # quarantined now

        # on-demand scrub + repair over the unsafe RPC surface
        env = rpc_core.Environment(node)
        out = rpc_core.unsafe_scrub(env, repair=True, timeout=10.0)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and node.store_repairer.pending():
            node.store_repairer.repair_pending(timeout_s=5.0)
        assert not node.store_repairer.pending(), out
        for k, orig in originals.items():
            assert bs._db.get(k) == orig, k  # byte-identical, peer-verified
        assert bs.load_block(h) is not None

        # statestore rot: a tip-window validators row heals locally
        tip = node.state_store.load().last_block_height
        vkey = ss_mod._val_key(tip + 1)
        if node.state_store._db.get(vkey) is not None:
            faults.corrupt_db(node.state_store._db, vkey, mode="truncate",
                              seed=72)
            node.scrubber().scrub(repairer=node.store_repairer,
                                  repair_timeout_s=5.0)
            assert node.state_store.load_validators(tip + 1) is not None

        # convergence: commits continue, zero forks anywhere in the prefix
        resume = cluster.max_height() + 2
        assert cluster.wait_min_height(resume, 60.0), cluster.heights()
        cluster.audit_agreement()
    finally:
        cluster.stop()
        faults.clear()


def test_node_startup_scrub_quarantines_damage(tmp_path):
    """A node booting over a damaged durable store quarantines at scrub
    time — before any peer can request the rotten block."""
    from tendermint_tpu.e2e.fabric import Cluster

    cluster = Cluster(str(tmp_path), 2, durable=True)
    cluster.start()
    try:
        assert cluster.wait_min_height(2, 60.0), cluster.heights()
        idx = 1
        # rot a row the app-replay handshake does NOT need (the seen
        # commit), so boot proceeds and the scrub+repair plane heals it;
        # rot in a replay-required block fails the handshake TYPED instead
        # (consensus/replay.py) — that path needs statesync/operator help
        key = bs_mod._seen_commit_key(1)
        db = cluster.nodes[idx].node.block_store._db
        assert db.get(key) is not None
        faults.corrupt_db(db, key, mode="bitrot", seed=81)
        # restart over the damaged durable home: the boot scrub must
        # quarantine before any peer can be served the rotten row
        cluster.restart_node(idx)
        node = cluster.nodes[idx].node
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            try:
                sc = node.block_store.load_seen_commit(1)
            except envelope.CorruptedStoreError:
                sc = None
            if sc is not None:
                break  # repaired from the peer
            time.sleep(0.2)
        sc = node.block_store.load_seen_commit(1)
        assert sc is not None and sc.height == 1
        cluster.audit_agreement()
    finally:
        cluster.stop()
        faults.clear()


# --- post-review regression coverage -----------------------------------------


def test_decimal_height_strict():
    assert envelope.decimal_height(b"42") == 42
    assert envelope.decimal_height(b"007") == 7
    # bare int(b.decode()) would accept every one of these
    for bad in (b" 2", b"2\n", b"1_0", b"+3", b"-1", b"", b"0x10"):
        with pytest.raises(ValueError):
            envelope.decimal_height(bad)


def test_v1_no_block_only_drops_solicited_peer():
    """An honest peer answering NoBlock to a request it was never pooled
    for (the store repairer broadcasts its own BlockRequests) must not be
    torn down; a pool-solicited NoBlock still is."""
    from tendermint_tpu.blockchain.v1 import BlockchainReactorV1, Ev, S_WAIT_FOR_BLOCK

    bs, _ss, _gd, _privs, _state = _build_chain(2)
    r = BlockchainReactorV1(None, None, bs, fast_sync=False)
    dropped = []
    r.drop_peer = lambda pid, reason: dropped.append(pid)
    r.fsm.state = S_WAIT_FOR_BLOCK
    r.fsm.handle(Ev("no_block", peer_id="p1", height=9))
    assert dropped == []  # unsolicited: ignored, not punished
    r.pool.requested[9] = "p1"
    r.fsm.handle(Ev("no_block", peer_id="p1", height=9))
    assert dropped == ["p1"]  # we asked p1 for 9 and it refused: drop


def test_blk_posting_quarantine_is_final_and_not_counted_repaired():
    """blk/ block-event postings are not re-derivable (ABCIResponses only
    persists DeliverTx results): quarantine must stand, and neither the
    detection-time read path nor the repairer may claim a repair."""
    from tendermint_tpu.abci.types import Event, EventAttribute
    from tendermint_tpu.store.repair import _task_key
    from tendermint_tpu.utils import metrics as tmmetrics

    assert _task_key("txindex", b"blk/k/v/5") == ("txindex_row", b"blk/k/v/5")
    assert _task_key("txindex", b"blkh/5") == ("txindex", 5)
    assert _task_key("txindex", b"txe/k/v/5/0") == ("txindex", 5)

    bs, ss, _gd, _privs, _state = _build_chain(3)
    idb = MemDB()
    bli = BlockIndexer(idb)
    ev = Event("reward", [EventAttribute(b"to", b"alice", True)])
    bli.index(2, [ev], [])
    assert bli.search("reward.to=alice") == [2]
    pkey = b"blk/reward.to/alice/2"
    assert idb.get(pkey) is not None
    faults.corrupt_db(idb, pkey, mode="bitrot", seed=71)

    nm = tmmetrics.NodeMetrics()
    prev = tmmetrics.GLOBAL_NODE_METRICS
    tmmetrics.GLOBAL_NODE_METRICS = nm
    try:
        rep = StoreRepairer(block_store=bs, state_store=ss,
                            tx_indexer=TxIndexer(idb), block_indexer=bli)
        report = Scrubber(txindex_db=idb).scrub(repairer=rep)
    finally:
        tmmetrics.GLOBAL_NODE_METRICS = prev
    assert report.corruptions and not rep.pending()
    assert idb.get(pkey) is None            # quarantined, never resurrected
    assert bli.search("reward.to=alice") == []
    text = nm.registry.expose()
    assert 'store_corruption_detected_total{store="txindex"} 1.0' in text
    assert 'store_corruption_repaired_total{store="txindex"} 0.0' in text


def test_txe_reindex_counts_exactly_one_repair():
    """One corrupt-but-rederivable posting: detected once, repaired once —
    the detection-time count_repair double-count is gone."""
    from tendermint_tpu.utils import metrics as tmmetrics

    bs, ss, _gd, _privs, _state = _build_chain(3)
    idb = MemDB()
    txi = TxIndexer(idb)
    block2 = bs.load_block(2)
    resp = ss.load_abci_responses(2)
    for i, tx in enumerate(block2.data.txs):
        txi.index(2, i, tx, resp.deliver_txs[i] if resp.deliver_txs else None)
    pkeys = [k for k, _ in idb.iterator(b"txe/", prefix_end(b"txe/"))]
    faults.corrupt_db(idb, pkeys[0], mode="truncate", seed=72)

    nm = tmmetrics.NodeMetrics()
    prev = tmmetrics.GLOBAL_NODE_METRICS
    tmmetrics.GLOBAL_NODE_METRICS = nm
    try:
        rep = StoreRepairer(block_store=bs, state_store=ss, tx_indexer=txi)
        report = Scrubber(txindex_db=idb).scrub(repairer=rep)
    finally:
        tmmetrics.GLOBAL_NODE_METRICS = prev
    assert report.corruptions and not rep.pending()
    assert txi.search("tx.height=2")  # the reindex actually landed
    text = nm.registry.expose()
    assert 'store_corruption_detected_total{store="txindex"} 1.0' in text
    assert 'store_corruption_repaired_total{store="txindex"} 1.0' in text


def test_consensus_boot_survives_both_commit_rows_corrupt():
    """SC:<h> AND C:<h> both rotten: ConsensusState construction must fail
    with the typed ConsensusError (seen commit not found), never leak the
    bare CorruptedStoreError out of the fallback load."""
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.consensus.state_machine import ConsensusError, ConsensusState
    from tendermint_tpu.mempool.mempool import Mempool

    bs, ss, _gd, _privs, state = _build_chain(3)
    h = state.last_block_height
    # the canonical C:<tip> row normally arrives with block tip+1; lay one
    # down so the fallback has a row to find rotten
    bs._db.set(bs_mod._commit_key(h),
               envelope.wrap(bs.load_seen_commit(h).marshal()))
    faults.corrupt_db(bs._db, bs_mod._seen_commit_key(h), mode="bitrot", seed=73)
    faults.corrupt_db(bs._db, bs_mod._commit_key(h), mode="bitrot", seed=74)
    app = KVStoreApplication()
    bx = BlockExecutor(ss, app, mempool=Mempool(app), block_store=bs)
    with pytest.raises(ConsensusError):
        ConsensusState(test_config().consensus, state, bx, bs,
                       mempool=Mempool(app))


def test_prune_blocks_single_bh_scan_for_many_corrupt_metas(monkeypatch):
    """K corrupt metas in one prune range must cost ONE BH: keyspace scan
    (it runs under the store mutex), and still delete every row."""
    bs, _ss, _gd, _privs, _state = _build_chain(5)
    for h in (1, 2, 3):
        faults.corrupt_db(bs._db, bs_mod._meta_key(h), mode="bitrot",
                          seed=80 + h)
    scans = []
    orig = BlockStore._bh_rows_by_height
    monkeypatch.setattr(BlockStore, "_bh_rows_by_height",
                        lambda self: scans.append(1) or orig(self))
    assert bs.prune_blocks(4) == 3
    assert len(scans) == 1
    assert bs.base == 4
    for h in (1, 2, 3):
        assert bs._db.get(bs_mod._meta_key(h)) is None
        pp = b"P:%020d:" % h
        assert not list(bs._db.iterator(pp, prefix_end(pp)))
    # no BH rows for pruned heights survive
    for _k, v in bs._db.iterator(b"BH:", prefix_end(b"BH:")):
        assert int(envelope.unwrap(v, "block", b"?")) >= 4


def test_committed_evidence_marker_restored_not_orphaned():
    """is_committed only tests key presence, so quarantining a rotten
    c:<hash> marker would re-open a double-commit window — the repairer
    must rewrite the canonical marker."""
    from tendermint_tpu.evidence.pool import EvidencePool, _committed_key

    pool = EvidencePool(MemDB(), None, None)
    ev = _fake_evidence(0)
    key = _committed_key(ev)
    pool._db.set(key, envelope.wrap(b"\x01"))
    assert pool.is_committed(ev)
    faults.corrupt_db(pool._db, key, mode="bitrot", seed=90)
    rep = StoreRepairer(evidence_db=pool._db)
    report = Scrubber(evidence_db=pool._db).scrub(repairer=rep)
    assert report.corruptions and not rep.pending()
    assert pool.is_committed(ev)  # marker restored, not orphaned
    assert pool._db.get(key) == envelope.wrap(b"\x01")


def test_txr_doc_reindexed_via_tx_height_posting():
    """A rotten txr/ doc row's height is recovered from the surviving
    tx.height posting and the doc is rebuilt from the stores."""
    from tendermint_tpu.types.tx import tx_hash

    bs, ss, _gd, _privs, _state = _build_chain(3)
    idb = MemDB()
    txi = TxIndexer(idb)
    block2 = bs.load_block(2)
    resp = ss.load_abci_responses(2)
    for i, tx in enumerate(block2.data.txs):
        txi.index(2, i, tx, resp.deliver_txs[i] if resp.deliver_txs else None)
    h0 = tx_hash(block2.data.txs[0])
    faults.corrupt_db(idb, b"txr/" + h0, mode="bitrot", seed=91)
    rep = StoreRepairer(block_store=bs, state_store=ss, tx_indexer=txi)
    report = Scrubber(txindex_db=idb).scrub(repairer=rep)
    assert report.corruptions and not rep.pending()
    doc = txi.get(h0)
    assert doc is not None and doc["height"] == "2"


def test_recover_state_refuses_pruned_unrebuildable_without_statesync():
    """Unrebuildable state row + PRUNED block store: genesis replay can't
    cover heights below base, so boot must fail typed unless statesync
    can re-bootstrap."""
    bs, ss, _gd, _privs, _state = _build_chain(4)
    bs.prune_blocks(3)  # base=3: blocks 1..2 gone
    # make the rebuild impossible too: corrupt the validator history the
    # tip-1 reconstruction needs, then the state row itself
    for k, _ in list(ss._db.iterator(b"validatorsKey:",
                                     prefix_end(b"validatorsKey:"))):
        faults.corrupt_db(ss._db, k, mode="truncate", seed=92)
    faults.corrupt_db(ss._db, b"stateKey", mode="bitrot", seed=93)
    with pytest.raises(envelope.CorruptedStoreError):
        recover_state(ss, bs, statesync_enabled=False)
    # the refusal must NOT quarantine: a retry boot has to fail typed too,
    # not see *missing* and silently take the genesis path
    assert ss._db.get(b"stateKey") is not None
    with pytest.raises(envelope.CorruptedStoreError):
        recover_state(ss, bs, statesync_enabled=False)
    # with statesync available the empty state routes into re-bootstrap
    st = recover_state(ss, bs, statesync_enabled=True)
    assert st.is_empty()


def test_unsafe_scrub_report_only_still_schedules_repairs():
    """scrub(drain=False) must quarantine AND queue every finding — a
    report-only pass that dropped the repair would orphan the row."""
    bs, ss, _gd, _privs, _state = _build_chain(3)
    faults.corrupt_db(bs._db, bs_mod._seen_commit_key(2), mode="bitrot",
                      seed=95)
    rep = StoreRepairer(block_store=bs, state_store=ss,
                        chain_id="dur-chain")
    report = Scrubber(block_store=bs).scrub(repairer=rep, drain=False)
    assert report.corruptions
    # scheduled, not dropped: the woken background worker (or a manual
    # drain) restores SC: from the canonical commit row
    deadline = time.monotonic() + 10.0
    sc = None
    while time.monotonic() < deadline and sc is None:
        rep.repair_pending()
        sc = bs.load_seen_commit(2)  # quarantined -> None until repaired
        if sc is None:
            time.sleep(0.05)
    assert sc is not None and sc.height == 2

# --- post-review regressions: rebuild hash, repair liveness, prune race ------


def test_rebuilt_state_carries_tip_results_hash():
    """State at target height carries results(target), which the TIP header
    commits — using the previous header's last_results_hash (results of
    target-1) would fail validate_block when the handshake replays the tip.
    The echo app makes every height's results hash distinct, so the
    off-by-one cannot hide (kvstore's identical-per-height results would)."""
    from dataclasses import replace

    from tendermint_tpu.mempool.mempool import Mempool

    class _EchoApp(KVStoreApplication):
        def deliver_tx(self, req):
            return replace(super().deliver_tx(req), data=bytes(req.tx))

    gd, privs = _genesis(2)
    state = make_genesis_state(gd)
    bs, ss = BlockStore(MemDB()), StateStore(MemDB())
    ss.save(state)
    app = _EchoApp()
    mp = Mempool(app)
    bx = BlockExecutor(ss, app, mempool=mp, block_store=bs)
    last_commit = Commit(height=0, round=0, block_id=BlockID(), signatures=[])
    for h in range(1, 5):
        mp.check_tx(b"res%d=v%d" % (h, h))
        block = bx.create_proposal_block(
            h, state, last_commit, state.validators.get_proposer().address)
        bid, last_commit = _commit_for(state, block, privs)
        bs.save_block(block, PartSet.from_data(block.marshal()), last_commit)
        state, _ = bx.apply_block(state, bid, block)
    tip_meta = bs.load_block_meta(4)
    prev_meta = bs.load_block_meta(3)
    assert (tip_meta.header.last_results_hash
            != prev_meta.header.last_results_hash)  # guard: test has teeth
    rebuilt = rebuild_state_from_blockstore(ss, bs)
    assert rebuilt.last_block_height == 3
    assert rebuilt.last_results_hash == tip_meta.header.last_results_hash


class _FakeSwitch:
    """Just enough Switch for the repairer's peer snapshot + broadcast."""

    def __init__(self, peers=()):
        import threading as _threading

        self._peers_mtx = _threading.RLock()
        self.peers = {p.id: p for p in peers}


def test_block_repair_attempt_not_burned_without_peers():
    """A corruption detected before any peer handshake (the boot-scrub
    window) must not exhaust its MAX_ATTEMPTS budget against an empty
    switch: the quarantined row would otherwise be abandoned for the whole
    run while honest peers were seconds away."""
    from tendermint_tpu.store.repair import MAX_ATTEMPTS

    bs, ss, _gd, _privs, _state = _build_chain(3)
    faults.corrupt_db(bs._db, bs_mod._part_key(2, 0), mode="bitrot", seed=97)
    rep = StoreRepairer(block_store=bs, state_store=ss, chain_id="dur-chain")
    rep.switch = _FakeSwitch()  # p2p wired, zero peers connected
    rep.note(envelope.CorruptedStoreError("block", bs_mod._part_key(2, 0),
                                          "test"), spawn=False)
    task = rep.pending()[0]
    for _ in range(MAX_ATTEMPTS + 1):
        done, _failed = rep.repair_pending(timeout_s=0.05)
        assert not done
    assert rep.pending() == [task]          # still queued...
    assert rep._pending[task] == 0          # ...with zero attempts burned


def test_garbage_fastest_responder_does_not_defeat_repair():
    """Repair verifies every response landing in the fetch window — a
    malicious peer winning the race with garbage bytes must not crowd out
    the honest copy arriving right behind it."""
    bs, ss, _gd, _privs, _state = _build_chain(3)
    honest = bs.load_block(2)
    garbage_bs, _, _, _, _ = _build_chain(3, n_vals=1)  # different valset
    garbage = garbage_bs.load_block(2)                  # => different hash
    assert garbage.hash() != honest.hash()

    rep = StoreRepairer(block_store=bs, state_store=ss, chain_id="dur-chain")

    class _Peer:
        id = "p0"

        def try_send(self, _chan, _msg):
            # both responses land inside the window, garbage FIRST
            rep.offer_block("evil", garbage)
            rep.offer_block("honest", honest)
            return True

    rep.switch = _FakeSwitch([_Peer()])
    pkey = bs_mod._part_key(2, 0)
    orig = bs._db.get(pkey)
    faults.corrupt_db(bs._db, pkey, mode="bitrot", seed=98)
    assert rep.repair_block_height(2, timeout_s=1.0) is True
    assert bs._db.get(pkey) == orig         # honest bytes, byte-identical
    assert bs.load_block(2).hash() == honest.hash()


def test_rewrite_block_refuses_pruned_height():
    """A repair racing prune_blocks must not re-lay rows below base —
    pruning never revisits them, so they would leak forever and every
    future scrub would flag the resurrected BH row."""
    bs, _ss, _gd, _privs, _state = _build_chain(4)
    block = bs.load_block(2)
    commit = bs.load_seen_commit(2)
    bhash = block.hash()
    bs.prune_blocks(3)  # base -> 3; height 2's rows are gone
    assert bs.rewrite_block(block, PartSet.from_data(block.marshal()),
                            commit) is False
    assert bs._db.get(bs_mod._meta_key(2)) is None
    assert bs._db.get(bs_mod._hash_key(bhash)) is None
    assert bs._db.get(bs_mod._part_key(2, 0)) is None
    # the repairer treats the vanished height as healed, not failed
    rep = StoreRepairer(block_store=bs, chain_id="dur-chain")
    assert rep.repair_block_height(2) is True


def test_evidence_drop_rule_is_transient_not_destructive():
    """`drop` at store.evidence.load must read as a transient miss like
    every other store's drop rule — NOT quarantine the intact on-disk row
    (which destroyed real pending evidence and inflated repaired_total)."""
    from tendermint_tpu.evidence.pool import EvidencePool, _pending_key

    pool = EvidencePool(MemDB(), None, None)
    ev = _fake_evidence(7)
    key = _pending_key(ev)
    pool._db.set(key, envelope.wrap(ev.bytes()))
    faults.configure(["store.evidence.load:drop@1"], seed=5)
    try:
        out, _sz = pool.pending_evidence(-1)
        assert out == []                            # this read missed...
        assert pool._db.get(key) is not None        # ...but the row SURVIVES
        out2, _sz = pool.pending_evidence(-1)
        assert len(out2) == 1                       # next read serves it
    finally:
        faults.clear()


class _StaleSnapshotStore:
    """Presents a stale base/height on the FIRST read of each attribute
    (the scrub's snapshot line) and the live store's value afterwards —
    emulating a chain that grew or pruned between snapshot and sweep."""

    def __init__(self, bs, stale_base=None, stale_height=None):
        self._bs = bs
        self._stale = {"base": stale_base, "height": stale_height}

    def _bound(self, name):
        stale = self._stale.get(name)
        if stale is not None:
            self._stale[name] = None
            return stale
        return getattr(self._bs, name)

    @property
    def base(self):
        return self._bound("base")

    @property
    def height(self):
        return self._bound("height")

    def __getattr__(self, name):
        return getattr(self._bs, name)


def test_live_scrub_tolerates_growth_after_snapshot():
    """Blocks committed after the scrub's base/height snapshot are healthy
    growth: their BH rows must not be flagged (and quarantined!) as
    'unknown height' by the dangling sweep."""
    bs, _ss, _gd, _privs, _state = _build_chain(4)
    grown = _StaleSnapshotStore(bs, stale_height=3)  # walk sees tip=3
    report = Scrubber(block_store=grown).scrub()
    assert report.ok, report.as_dict()
    h4 = bs.load_block_meta(4)
    assert bs._db.get(bs_mod._hash_key(h4.block_id.hash)) is not None


def test_live_scrub_tolerates_prune_after_snapshot():
    """Heights pruned after the scrub's snapshot are a healthy gap, not a
    trail of 'missing meta row' corruptions."""
    bs, _ss, _gd, _privs, _state = _build_chain(4)
    bs.prune_blocks(3)                                # base -> 3
    pruned = _StaleSnapshotStore(bs, stale_base=1)    # walk starts at 1
    report = Scrubber(block_store=pruned).scrub()
    assert report.ok, report.as_dict()


def test_repairerless_scrub_restores_committed_marker():
    """A repairer-less scrub must not leave a rotten c:<hash> marker
    quarantined — is_committed tests key presence only, so the loss would
    re-open a double-commit window. The value is constant: restore inline."""
    from tendermint_tpu.evidence.pool import EvidencePool, _committed_key

    edb = MemDB()
    ev = _fake_evidence(9)
    ckey = _committed_key(ev)
    edb.set(ckey, envelope.wrap(b"\x01"))
    faults.corrupt_db(edb, ckey, mode="bitrot", seed=77)
    report = Scrubber(evidence_db=edb).scrub()   # NO repairer
    assert any(c.key == ckey for c in report.corruptions)
    assert edb.get(ckey) == envelope.wrap(b"\x01")   # restored, not orphaned
    assert report.repaired
    pool = EvidencePool(edb, None, None)
    assert pool.is_committed(ev)                 # double-commit window shut
