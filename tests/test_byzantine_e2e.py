"""Byzantine acceptance scenario (ISSUE 14, docs/BYZANTINE.md): a 7-node
fabric with 2 byzantine nodes cycling through the whole maverick behavior
catalog under a seeded soak schedule — honest nodes stay fork-free and
live, every provoked misbehavior converges to identical committed evidence
on all honest nodes within the height bound, and a live light-client
attack (posterior-corruption lunatic as byzantine primary, honest witness,
client OUTSIDE the cluster over real RPC) is detected, its evidence
committed cluster-wide, and the voting-power slash applied at h+2."""

import time

import pytest

from tendermint_tpu.e2e.fabric import Cluster
from tendermint_tpu.e2e.soak import SoakDriver, SoakSchedule
from tendermint_tpu.light.client import SKIPPING, Client, TrustOptions
from tendermint_tpu.light.detector import ErrConflictingHeaders
from tendermint_tpu.light.provider import HTTPProvider
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.types.evidence import (
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
)
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.utils import faults, nemesis

SEED = 14
HONEST = (2, 3, 4, 5, 6)

# the two byzantine nodes cycle through every behavior in the catalog:
# node 0 (the demoted posterior-corruption lunatic) also equivocates as a
# proposer; node 1 walks the vote-level behaviors
CYCLE_SCHEDULE = (
    "@0.5:byz:0:lunatic~2-4;"
    "@2:byz:1:double_prevote;"
    "@5:byz:1:double_precommit;"
    "@7:byz:0:equivocate+lunatic~2-4;"
    "@9:byz:1:amnesia;"
    "@12:byz:1:absent;"
    "@13:flood~1:4>3"
)


@pytest.fixture(autouse=True)
def _clean_chaos():
    faults.configure([], seed=SEED)
    nemesis.clear()
    yield
    nemesis.clear()
    faults.clear()


def _wait(cond, timeout, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _committed_evidence(node):
    out = {}
    for h in range(1, node.block_store.height + 1):
        block = node.block_store.load_block(h)
        for ev in (block.evidence if block else ()):
            out.setdefault(type(ev).__name__, []).append((h, ev.hash()))
    return out


def test_byzantine_acceptance_seven_nodes(tmp_path):
    cluster = Cluster(str(tmp_path), 7,
                      powers=[30, 4, 10, 10, 10, 10, 10],
                      topology="full", rpc_nodes=(0, 2), trace=True)
    cluster.start()
    try:
        # --- phase 1: honest warm-up, then demote the future lunatic so
        # live byzantine power stays < 1/3 when it turns (the attack is
        # staged by POSTERIOR CORRUPTION: the key held 30/84 >= 1/3 at the
        # heights it will forge) -----------------------------------------
        assert cluster.wait_min_height(3, 90.0), cluster.heights()
        cluster.promote(0, 10)
        assert _wait(lambda: cluster.validator_power(0) == 10, 60.0), (
            cluster.validator_powers())

        # --- phase 2: seeded soak cycling both byzantine nodes through
        # the behavior catalog under tx load, with the continuous
        # safety/liveness/evidence auditor attached ----------------------
        schedule = SoakSchedule.parse(CYCLE_SCHEDULE)
        assert schedule.describe() == CYCLE_SCHEDULE  # repro-line contract
        driver = SoakDriver(cluster, schedule, SEED, duration_s=15.0,
                            liveness_budget_s=60.0)
        report = driver.run()
        assert report.ok, (report.violations, report.repro)
        assert report.byzantine == [0, 1]
        byz_power, total = cluster.byzantine_power_fraction()
        assert 3 * byz_power < total, (byz_power, total)
        # the vote-level behaviors provoked committed DuplicateVoteEvidence
        assert report.evidence_audited >= 1, report

        # --- phase 3: the live light-client attack from OUTSIDE ---------
        fakes = cluster.nodes[0].node.byzantine_light_blocks
        assert 3 in fakes, sorted(fakes)
        primary = HTTPProvider(cluster.chain_id, cluster.rpc_url(0))
        witness = HTTPProvider(cluster.chain_id, cluster.rpc_url(2))
        anchor = witness.light_block(1)
        client = Client(
            cluster.chain_id,
            TrustOptions(period_s=1e9, height=1, hash=anchor.hash()),
            primary, [witness], DBStore(MemDB()),
            verification_mode=SKIPPING)
        with pytest.raises(ErrConflictingHeaders):
            client.verify_light_block_at_height(3, Time.now())
        assert client.divergences
        attack_ev = client.divergences[-1].evidence_against_primary
        assert isinstance(attack_ev, LightClientAttackEvidence)
        # attribution names the lunatic with its power AT THE COMMON HEIGHT
        byz_vals = {v.address: v.voting_power
                    for v in attack_ev.byzantine_validators}
        lunatic_addr = cluster.nodes[0].priv.pub_key().address()
        assert byz_vals == {lunatic_addr: 30}

        # --- convergence: BOTH evidence kinds committed on EVERY honest
        # node, exactly once each, within the auditor's height bound -----
        def all_converged():
            driver.auditor.sweep()  # keep the evidence ledger advancing
            per_node = {i: _committed_evidence(cluster.nodes[i].node)
                        for i in HONEST}
            kinds_ok = all(
                {"DuplicateVoteEvidence", "LightClientAttackEvidence"}
                <= set(per_node[i]) for i in HONEST)
            tracked = driver.auditor._ev_first
            converged = driver.auditor._ev_converged
            return kinds_ok and tracked and set(tracked) <= converged

        assert _wait(all_converged, 120.0), {
            i: sorted(_committed_evidence(cluster.nodes[i].node))
            for i in HONEST}
        assert not driver.auditor.violations, driver.auditor.violations
        # identical evidence everywhere: same hash set on every honest node
        hash_sets = []
        for i in HONEST:
            evs = _committed_evidence(cluster.nodes[i].node)
            hash_sets.append({h for entries in evs.values()
                              for _, h in entries})
        assert all(s == hash_sets[0] for s in hash_sets[1:])

        # --- slash at h+2: both byzantine validators at power 0 on every
        # honest node's CURRENT set, and the honest majority stays live --
        assert _wait(lambda: all(
            cluster.validator_power(0, at=i) == 0
            and cluster.validator_power(1, at=i) == 0
            for i in HONEST), 90.0), cluster.validator_powers(at=2)
        resume = cluster.max_height() + 2
        assert cluster.wait_min_height(resume, 90.0, among=list(HONEST)), (
            cluster.heights())
        cluster.audit_agreement()  # honest prefix, full re-check
    finally:
        cluster.stop()
