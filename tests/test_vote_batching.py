"""The batched addVote hot loop (BASELINE config 5): gossiped votes drained
and verified in one BatchVerifier flush, with per-vote side effects applied in
arrival order (reference serial path: consensus/state.go:1995 addVote ->
types/vote_set.go:205 vote.Verify, one scalar verify per vote)."""

import time

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE, Vote, VoteError
from tendermint_tpu.types.vote_set import VoteSet

CHAIN_ID = "batch-chain"
N_VALS = 1024


def _net(n):
    privs = [
        ed25519.gen_priv_key((i + 1).to_bytes(2, "big") * 16) for i in range(n)
    ]
    vals = ValidatorSet(
        [Validator(p.pub_key().address(), p.pub_key(), 10) for p in privs]
    )
    # ValidatorSet orders by (power desc, address asc); realign priv keys.
    by_addr = {p.pub_key().address(): p for p in privs}
    privs = [by_addr[v.address] for v in vals.validators]
    return privs, vals


def _signed_vote(priv, vals, vtype, block_id, i=None):
    addr = priv.pub_key().address()
    idx, _ = vals.get_by_address(addr)
    v = Vote(
        type=vtype, height=1, round=0, block_id=block_id,
        timestamp=Time(1700001000, 0), validator_address=addr,
        validator_index=idx,
    )
    v.signature = priv.sign(v.sign_bytes(CHAIN_ID))
    return v


@pytest.fixture(scope="module")
def big_net():
    return _net(N_VALS)


def test_add_votes_1024_validators_maj23(big_net):
    """1024 prevotes through ONE batched flush; maj23 must be found and every
    vote individually accepted."""
    privs, vals = big_net
    bid = BlockID(hash=b"\x11" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32))
    votes = [_signed_vote(p, vals, PREVOTE_TYPE, bid) for p in privs]

    vs = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    t0 = time.monotonic()
    results = vs.add_votes(votes)
    dt = time.monotonic() - t0
    assert all(added for added, err in results), [e for _, e in results if e][:3]
    maj, ok = vs.two_thirds_majority()
    assert ok and maj == bid
    # throughput telemetry (not an assert: CI hosts vary; the serial scalar
    # path at ~2ms/verify would take ~2s for 1024 votes)
    print(f"\nadd_votes: {len(votes)} votes in {dt*1e3:.1f} ms "
          f"({len(votes)/dt:.0f} votes/s)")


def test_add_votes_per_vote_error_attribution(big_net):
    """One corrupted signature in the batch: only that vote errors; order and
    acceptance of the rest are unchanged (the reference's per-vote error
    semantics, types/vote_set.go:209-217)."""
    privs, vals = big_net
    bid = BlockID(hash=b"\x33" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x44" * 32))
    votes = [_signed_vote(p, vals, PREVOTE_TYPE, bid) for p in privs[:200]]
    bad_i = 77
    votes[bad_i].signature = bytes([votes[bad_i].signature[0] ^ 1]) + \
        votes[bad_i].signature[1:]

    vs = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    results = vs.add_votes(votes)
    for i, (added, err) in enumerate(results):
        if i == bad_i:
            assert not added and isinstance(err, VoteError)
        else:
            assert added and err is None, (i, err)


def test_add_votes_duplicate_within_batch(big_net):
    privs, vals = big_net
    bid = BlockID(hash=b"\x55" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x66" * 32))
    v = _signed_vote(privs[0], vals, PREVOTE_TYPE, bid)
    vs = VoteSet(CHAIN_ID, 1, 0, PREVOTE_TYPE, vals)
    results = vs.add_votes([v, v, v])
    assert results[0] == (True, None)
    assert results[1][0] is False and results[1][1] is None  # duplicate
    assert results[2][0] is False and results[2][1] is None


def test_consensus_drain_applies_batch(big_net):
    """The state machine's _handle_vote_batch: a pile of gossiped precommits
    is flushed through one batch verify and applied in order (with one bad
    signature dropped), without touching the scalar per-vote path."""
    privs, vals = big_net
    from tendermint_tpu.consensus import cstypes
    from tendermint_tpu.consensus.state_machine import (
        ConsensusState, MsgInfo, VoteMessage,
    )
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.state.state import make_genesis_state
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    genesis = GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time=Time(1700001000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs[:64]],
    )
    state = make_genesis_state(genesis)
    cs = ConsensusState(test_config().consensus, state, None, None)
    vals64 = cs.rs.votes.val_set

    bid = BlockID(hash=b"\x77" * 32,
                  part_set_header=PartSetHeader(total=1, hash=b"\x88" * 32))
    msgs = []
    # only validators present in the 64-member set can vote here
    members = [p for p in privs if vals64.has_address(p.pub_key().address())]
    assert len(members) == 64
    for p in members:
        v = _signed_vote(p, vals64, PREVOTE_TYPE, bid)
        msgs.append(MsgInfo(VoteMessage(v), "peerX"))
    # corrupt one
    bad = msgs[10].msg.vote
    bad.signature = bytes([bad.signature[0] ^ 1]) + bad.signature[1:]

    cs.rs.step = cstypes.STEP_PREVOTE
    cs._handle_vote_batch(msgs)
    # With the continuous-batching verify service, the flush is genuinely
    # in flight when _handle_vote_batch returns (has_device_output() sees
    # the shared launch) and the drain stashes it; the production loop
    # applies it before any later state transition via _flush_pending_votes
    # — drive that exact step here.
    cs._flush_pending_votes()
    prevotes = cs.rs.votes.prevotes(0)
    assert sum(prevotes.bit_array()) == 63  # all but the corrupted one
    maj, ok = prevotes.two_thirds_majority()
    assert ok and maj == bid
