"""Subprocess driver for the crash-recovery fail-point matrix
(tests/test_fastsync_recovery.py). Runs a single-validator node on durable
stores; with TMTPU_FAIL_INDEX set the node os._exit()s mid-commit at the
chosen fail site, simulating a hard crash. In recovery mode it replays
WAL + block store through the app and prints a JSON state summary.

Usage: python tests/crash_node.py <root_dir> <mode:crash|recover> <target_height>
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TM_TPU_DISABLE_BATCH", "1")  # no kernel warmup needed here

from tendermint_tpu.config.config import test_config  # noqa: E402
from tendermint_tpu.crypto import ed25519  # noqa: E402
from tendermint_tpu.node.node import Node  # noqa: E402
from tendermint_tpu.p2p.key import NodeKey  # noqa: E402
from tendermint_tpu.privval.file_pv import FilePV  # noqa: E402
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator  # noqa: E402
from tendermint_tpu.types.ttime import Time  # noqa: E402


def main() -> int:
    root, mode, target_height = sys.argv[1], sys.argv[2], int(sys.argv[3])
    os.makedirs(root, exist_ok=True)

    pv = FilePV.load_or_generate(os.path.join(root, "pv_key.json"),
                                 os.path.join(root, "pv_state.json"))
    genesis = GenesisDoc(
        chain_id="crash-chain", genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", pv.get_pub_key(), 10)],
    )
    cfg = test_config()
    cfg.set_root(root)
    os.makedirs(os.path.join(root, "data"), exist_ok=True)
    cfg.base.db_backend = "sqlite"  # durable across the crash
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = ""
    cfg.p2p.pex = False
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = os.path.join(root, "data", "cs.wal")

    node = Node(cfg, genesis=genesis, priv_validator=pv,
                node_key=NodeKey(ed25519.gen_priv_key(b"\x55" * 32)))
    node.start()

    # feed a tx per block so the app state actually advances
    deadline = time.monotonic() + 120
    fed = 0
    while time.monotonic() < deadline:
        h = node.block_store.height
        if fed <= h:
            try:
                node.mempool.check_tx(b"%s%d=v%d" % (mode.encode(), fed, fed))
            except Exception:  # noqa: BLE001 - dupes after replay are expected
                pass
            fed += 1
        if mode == "recover" and h >= target_height:
            break
        time.sleep(0.05)
        # In crash mode the process never reaches here past the fail site:
        # os._exit fires inside finalize_commit on the consensus thread.
    node.stop()

    app = node.app  # in-proc kvstore
    st = node.state_store.load()
    print(json.dumps({
        "height": node.block_store.height,
        "state_height": st.last_block_height,
        "state_app_hash": st.app_hash.hex(),
        "app_height": app.height,
        "app_hash": app.app_hash.hex(),
        "app_size": app.size,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
