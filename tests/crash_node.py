"""Subprocess driver for the crash-recovery fault matrix
(tests/test_fastsync_recovery.py, tests/test_fault_matrix.py). Runs a
single-validator node on durable stores; with TMTPU_FAIL_INDEX set the node
os._exit()s mid-commit at the chosen legacy fail site, and with
TMTPU_FAULTS/TMTPU_FAULT_SEED set the named-site chaos layer
(tendermint_tpu/utils/faults.py) drives torn WAL writes, store-write
crashes, etc. In recovery mode it replays WAL + block store through the app
and prints a JSON state summary.

Usage: python tests/crash_node.py <root_dir> <mode:crash|recover> \
           <target_height> [n_txs]

With ``n_txs`` the node feeds the fixed tx universe t0..t{n-1} ("t<i>=v<i>"),
skipping any tx already committed in the block store -- so a crash+recover
pair applies each tx exactly once and converges to the same app hash as a
fault-free run (the kvstore app hash is the big-endian applied-tx count).
Without it, the legacy mode-prefixed feeding is kept for the
TMTPU_FAIL_INDEX matrix.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TM_TPU_DISABLE_BATCH", "1")  # no kernel warmup needed here

from tendermint_tpu.config.config import test_config  # noqa: E402
from tendermint_tpu.crypto import ed25519  # noqa: E402
from tendermint_tpu.node.node import Node  # noqa: E402
from tendermint_tpu.p2p.key import NodeKey  # noqa: E402
from tendermint_tpu.privval.file_pv import FilePV  # noqa: E402
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator  # noqa: E402
from tendermint_tpu.types.ttime import Time  # noqa: E402


def _committed_txs(node) -> set:
    """Every tx already in a committed block (the recovery scan that makes
    deterministic re-feeding idempotent)."""
    out = set()
    for h in range(1, node.block_store.height + 1):
        b = node.block_store.load_block(h)
        if b is not None:
            out.update(b.data.txs)
    return out


def _wait_app_settled(app, seconds: float = 1.5, budget: float = 20.0) -> None:
    """Wait until no new txs have been applied for `seconds`: WAL replay /
    in-flight block application must finish before the committed-tx scan,
    or a pre-crash tx could be double-fed."""
    stable, t_stable = app.size, time.monotonic()
    deadline = time.monotonic() + budget
    while time.monotonic() - t_stable < seconds and time.monotonic() < deadline:
        if app.size != stable:
            stable, t_stable = app.size, time.monotonic()
        time.sleep(0.05)


def main() -> int:
    root, mode, target_height = sys.argv[1], sys.argv[2], int(sys.argv[3])
    n_txs = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    os.makedirs(root, exist_ok=True)

    pv = FilePV.load_or_generate(os.path.join(root, "pv_key.json"),
                                 os.path.join(root, "pv_state.json"))
    genesis = GenesisDoc(
        chain_id="crash-chain", genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", pv.get_pub_key(), 10)],
    )
    cfg = test_config()
    cfg.set_root(root)
    os.makedirs(os.path.join(root, "data"), exist_ok=True)
    cfg.base.db_backend = "sqlite"  # durable across the crash
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = ""
    cfg.p2p.pex = False
    cfg.rpc.laddr = ""
    cfg.consensus.wal_path = os.path.join(root, "data", "cs.wal")

    node = Node(cfg, genesis=genesis, priv_validator=pv,
                node_key=NodeKey(ed25519.gen_priv_key(b"\x55" * 32)))
    node.start()
    app = node.app  # in-proc kvstore

    if n_txs:
        universe = [b"t%d=v%d" % (i, i) for i in range(n_txs)]
        _wait_app_settled(app)
        remaining = [tx for tx in universe if tx not in _committed_txs(node)]
    else:
        remaining = []

    # feed a tx per block so the app state actually advances
    deadline = time.monotonic() + 120
    fed = 0
    while time.monotonic() < deadline:
        h = node.block_store.height
        if n_txs:
            if fed < len(remaining) and fed <= h:
                try:
                    node.mempool.check_tx(remaining[fed])
                except Exception:  # noqa: BLE001
                    pass
                fed += 1
            if mode == "recover" and h >= target_height and app.size >= n_txs:
                break
            if mode == "crash" and h >= target_height + 8:
                break  # the injected fault never fired; exit 0 so the
                # caller's returncode assertion fails fast, not at timeout
        else:
            if fed <= h:
                try:
                    node.mempool.check_tx(b"%s%d=v%d" % (mode.encode(), fed, fed))
                except Exception:  # noqa: BLE001 - dupes after replay are expected
                    pass
                fed += 1
            if mode == "recover" and h >= target_height:
                break
        time.sleep(0.05)
        # In crash mode the process never reaches here past the fail site:
        # os._exit fires at the injected fault on the consensus thread.
    node.stop()

    st = node.state_store.load()
    summary = {
        "height": node.block_store.height,
        "state_height": st.last_block_height,
        "state_app_hash": st.app_hash.hex(),
        "app_height": app.height,
        "app_hash": app.app_hash.hex(),
        "app_size": app.size,
    }
    edb = sys.modules.get("tendermint_tpu.ops.ed25519_batch")
    if edb is not None:
        summary["breaker_trips"] = edb.BREAKER.trips
        summary["breaker_open"] = edb.BREAKER.is_open
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
