"""Manifest-driven e2e runner: 4 real node PROCESSES over TCP with a
kill + pause perturbation schedule, load, liveness, and fork check
(reference: test/e2e/runner, networks/ci.toml shape)."""

import json

import pytest

from tendermint_tpu.e2e import Manifest, Perturbation, Runner


def test_e2e_testnet_with_perturbations(tmp_path):
    m = Manifest(
        validators=4,
        chain_id="e2e-ci",
        target_height=8,
        load_txs=8,
        perturbations=[
            # kill -9 one validator mid-chain; it must recover from disk
            Perturbation(node=3, action="kill", at_height=3, revive_after_s=1.0),
            # freeze another briefly; 3 of 4 keep committing
            Perturbation(node=2, action="pause", at_height=5, revive_after_s=2.0),
        ],
    )
    r = Runner(m, str(tmp_path / "net"))
    r.setup()
    r.start()
    try:
        r.load()
        r.perturb_and_wait(timeout_s=240)
        assert r.max_height() >= m.target_height
        r.assert_consistent(m.target_height - 2)
    finally:
        r.stop()


def test_e2e_statesync_join(tmp_path):
    """A brand-new node process joins the running net via SNAPSHOT state
    sync (light-client trust over node0's RPC), then fast-syncs to the tip
    without ever replaying from genesis (reference: test/e2e state-sync
    nodes)."""
    m = Manifest(validators=4, chain_id="e2e-ss", target_height=9, load_txs=6)
    r = Runner(m, str(tmp_path / "net"))
    r.setup()
    r.start()
    try:
        r.load()
        r.perturb_and_wait(timeout_s=180)
        idx = r.join_statesync_node(timeout_s=150)
        st = r._rpc(idx, "status", {})
        # bootstrapped mid-chain: no genesis replay
        assert int(st["sync_info"]["earliest_block_height"]) > 1
        # agrees with the net
        r.assert_consistent(m.target_height - 1)
    finally:
        r.stop()


def test_manifest_from_file(tmp_path):
    path = tmp_path / "manifest.json"
    path.write_text(json.dumps({
        "validators": 5, "target_height": 20, "load_txs": 3,
        "perturbations": [{"node": 1, "action": "restart", "at_height": 4}],
    }))
    m = Manifest.from_file(str(path))
    assert m.validators == 5
    assert m.perturbations[0].action == "restart"
    assert m.perturbations[0].revive_after_s == 1.0
