"""Manifest-driven e2e runner: 4 real node PROCESSES over TCP with a
kill + pause perturbation schedule, load, liveness, and fork check
(reference: test/e2e/runner, networks/ci.toml shape)."""

import json

import pytest

from tendermint_tpu.e2e import Manifest, Perturbation, Runner


def test_e2e_testnet_with_perturbations(tmp_path):
    m = Manifest(
        validators=4,
        chain_id="e2e-ci",
        target_height=8,
        load_txs=8,
        perturbations=[
            # kill -9 one validator mid-chain; it must recover from disk
            Perturbation(node=3, action="kill", at_height=3, revive_after_s=1.0),
            # freeze another briefly; 3 of 4 keep committing
            Perturbation(node=2, action="pause", at_height=5, revive_after_s=2.0),
        ],
    )
    r = Runner(m, str(tmp_path / "net"))
    r.setup()
    r.start()
    try:
        r.load()
        r.perturb_and_wait(timeout_s=240)
        assert r.max_height() >= m.target_height
        # full-prefix audit: fork detection at EVERY committed height, so
        # the crash matrix can't miss a fork below the sampled height
        assert r.audit_agreement() >= m.target_height - 2
    finally:
        r.stop()


def test_e2e_statesync_join(tmp_path):
    """A brand-new node process joins the running net via SNAPSHOT state
    sync (light-client trust over node0's RPC), then fast-syncs to the tip
    without ever replaying from genesis (reference: test/e2e state-sync
    nodes)."""
    m = Manifest(validators=4, chain_id="e2e-ss", target_height=9, load_txs=6)
    r = Runner(m, str(tmp_path / "net"))
    r.setup()
    r.start()
    try:
        r.load()
        r.perturb_and_wait(timeout_s=240)
        # generous: the joiner subprocess pays a cold JAX import on the
        # 1-core CI host, and any concurrent load stretches it (this
        # deadline only matters when the host is contended)
        idx = r.join_statesync_node(timeout_s=300)
        st = r._rpc(idx, "status", {})
        # bootstrapped mid-chain: no genesis replay
        assert int(st["sync_info"]["earliest_block_height"]) > 1
        # agrees with the net at every height it serves
        r.audit_agreement()
    finally:
        r.stop()


def test_e2e_byzantine_node_and_load_report(tmp_path):
    """Manifest-marked byzantine PROCESS (TMTPU_MISBEHAVIOR=double_prevote,
    reference: maverick nodes in e2e manifests): the equivocator pushes
    conflicting prevotes to every peer; the honest 3/4 must keep committing,
    stay fork-free, and commit DuplicateVoteEvidence against it. Also runs
    the timed load stage and checks the throughput report shape (reference:
    test/loadtime, docs/qa/v034 block-rate tables)."""
    m = Manifest(validators=4, chain_id="e2e-byz", target_height=8,
                 load_txs=6, byzantine_node=3,
                 misbehavior="double_prevote")
    r = Runner(m, str(tmp_path / "net"))
    r.setup()
    r.start()
    try:
        r.load()
        r.perturb_and_wait(timeout_s=240)
        assert r.max_height() >= m.target_height
        r.audit_agreement()
        report = r.load_report(window_s=10.0)
        assert report["blocks"] >= 1 and report["blocks_per_min"] > 0
        assert report["txs_committed"] >= 1
        # the equivocation must surface as committed evidence on-chain
        found = False
        for h in range(2, r.max_height() + 1):
            try:
                b = r._rpc(0, "block", {"height": str(h)})
            except Exception:  # noqa: BLE001
                continue
            if b["block"]["evidence"]["evidence"]:
                found = True
                break
        assert found, "DuplicateVoteEvidence never committed"
    finally:
        r.stop()


def test_generator_deterministic_and_bounded():
    """generator.generate is seed-deterministic and every rolled manifest
    respects the topology constraints (reference: e2e generator)."""
    from tendermint_tpu.e2e.generator import generate

    a = generate(seed=7, count=12)
    b = generate(seed=7, count=12)
    assert a == b
    assert a != generate(seed=8, count=12)
    for m in a:
        assert 2 <= m.validators <= 5
        assert m.fastsync_version in ("v0", "v1", "v2")
        if m.byzantine_node >= 0:
            assert m.validators >= 4 and m.byzantine_node < m.validators
        for p in m.perturbations:
            assert p.node < m.validators
            assert p.action in ("kill", "restart", "pause", "partition")
            if p.action == "partition":
                assert p.groups and all(p.groups)
        assert m.light_clients in (0, 4, 8, 16)
    # the light-serving dimension does get rolled somewhere in the matrix
    assert any(m.light_clients for m in generate(seed=7, count=40))


def test_e2e_generated_manifest_runs(tmp_path):
    """One deterministic generated topology runs end to end through
    run_manifest (the matrix-in-CI entry: same path the full generated
    matrix would take nightly)."""
    from tendermint_tpu.e2e.generator import generate_one
    import random

    # seed chosen for a small, fast topology (2-3 validators, no joiner)
    rng = random.Random(21)
    m = generate_one(rng, 0)
    m.statesync_joiner = False  # keep the CI tier fast; joiner covered above
    m.target_height = min(m.target_height, 8)
    from tendermint_tpu.e2e.runner import run_manifest

    run_manifest(m, str(tmp_path / "net"))


def test_manifest_from_file(tmp_path):
    path = tmp_path / "manifest.json"
    path.write_text(json.dumps({
        "validators": 5, "target_height": 20, "load_txs": 3,
        "perturbations": [{"node": 1, "action": "restart", "at_height": 4}],
    }))
    m = Manifest.from_file(str(path))
    assert m.validators == 5
    assert m.perturbations[0].action == "restart"
    assert m.perturbations[0].revive_after_s == 1.0
