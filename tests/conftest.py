"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths (jax.sharding.Mesh + shard_map) are exercised without TPU
hardware. Must run before jax is imported anywhere."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
