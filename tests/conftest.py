"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths (jax.sharding.Mesh + shard_map) are exercised without TPU
hardware.

The runtime image pre-imports jax at interpreter startup (axon sitecustomize
via PALLAS_AXON_POOL_IPS) and pins JAX_PLATFORMS=axon, so env vars alone are
too late; the backend is re-targeted via jax.config before any JAX op runs."""

import os

# TM_TPU_TEST_BACKEND=tpu keeps the session on the real chip (for the
# on-chip tests like test_pallas_tpu.py); default is the CPU mesh.
_KEEP_TPU = os.environ.get("TM_TPU_TEST_BACKEND") == "tpu"

# The in-process jax.config updates below are what take effect for THIS
# process; the env vars exist so child processes tests spawn (e2e runner,
# node subprocesses) inherit the same CPU-mesh setup.
if not _KEEP_TPU:
    # Short-lived test processes must not race a background XLA warmup
    # compile at interpreter exit (C++ teardown abort); see crypto/batch.py.
    os.environ.setdefault("TM_TPU_SKIP_WARMUP", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
if not _KEEP_TPU and (
        "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", "")):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# TMTPU_LOCKWITNESS=1 runs the WHOLE session under the lock-order witness
# (utils/lockwitness.py): every Lock/RLock created from here on records
# runtime acquisition-order edges. The two mesh scenario tests always run
# under it via lockwitness.witness(); this hook is the opt-in for full-
# suite sweeps.
from tendermint_tpu.utils import lockwitness  # noqa: E402

lockwitness.install_from_env()

# Tier split (VERDICT r3: the full suite crossed 7 min, dominated by
# subprocess e2e tests each paying a cold JAX import on one core).
# `-m quick` runs the fast tier (<3 min); `-m slow` the process-heavy rest.
_SLOW_MODULES = {
    # subprocess / multi-node e2e
    "test_e2e_runner", "test_fastsync_recovery", "test_statesync",
    "test_observability", "test_p2p_node", "test_consensus",
    "test_remote_signer", "test_pallas_tpu", "test_adversarial",
    # kernel-bound: wide batches / fresh XLA shapes on the 1-core CPU mesh
    "test_multichip", "test_perf_gate", "test_sr25519_batch",
    "test_ed25519_batch",
    # exhaustive state-space exploration (spec/model.py)
    "test_spec_model",
    # subprocess crash-recovery matrix + real-kernel breaker re-probe
    "test_fault_matrix",
}


def pytest_configure(config):
    config.addinivalue_line("markers", "quick: fast in-process tier (<3 min)")
    config.addinivalue_line("markers", "slow: subprocess/e2e tier")
    config.addinivalue_line(
        "markers",
        "soak: long-running seeded soak scenarios (docs/SOAK.md); always "
        "implies slow, so tier-1's `-m 'not slow'` never picks one up")


def pytest_sessionfinish(session, exitstatus):
    # The session-wide witness sweep must actually VERDICT: any lock-order
    # cycle observed anywhere in the run fails the whole session.
    if lockwitness.WITNESS.enabled:
        cycles = lockwitness.WITNESS.cycles()
        if cycles or lockwitness.WITNESS.truncated:
            print("\nLOCKWITNESS: "
                  + (f"acquisition-order cycle {' -> '.join(cycles[0])}"
                     if cycles else
                     f"edge graph truncated at {lockwitness.MAX_EDGES}"),
                  f"(edges={len(lockwitness.WITNESS.edges)}, "
                  f"acquires={lockwitness.WITNESS.acquires})")
            session.exitstatus = 1


# Modules whose point is exercising the DEVICE kernels: pin the host/kernel
# crossover to 0 there so the C host verifier (ops/chost) cannot absorb the
# batches they mean to run through the kernel. Everything else keeps the
# production adaptive routing.
_KERNEL_PATH_MODULES = {
    "test_ed25519_batch", "test_sr25519_batch", "test_multichip",
    "test_pallas_tpu", "test_sha512_device", "test_perf_gate",
}


@pytest.fixture(autouse=True)
def _pin_kernel_path(request, monkeypatch):
    mod = request.module.__name__.rsplit(".", 1)[-1]
    if mod in _KERNEL_PATH_MODULES:
        monkeypatch.setenv("TM_TPU_HOST_CROSSOVER", "0")


def pytest_collection_modifyitems(config, items):
    for item in items:
        # A soak-marked test is always slow-tier, whatever its module says.
        if item.get_closest_marker("soak"):
            if not item.get_closest_marker("slow"):
                item.add_marker(pytest.mark.slow)
            continue
        # An explicit @pytest.mark.quick/slow on the test wins over the
        # module default (a no-kernel gate in a kernel-heavy module can
        # opt into the quick tier).
        if item.get_closest_marker("quick") or item.get_closest_marker("slow"):
            continue
        mod = item.module.__name__.rsplit(".", 1)[-1]
        item.add_marker(pytest.mark.slow if mod in _SLOW_MODULES
                        else pytest.mark.quick)

if not _KEEP_TPU:
    if _xb.backends_are_initialized():
        # Some earlier import already ran a JAX op; start over in-process.
        try:
            import jax.extend.backend as _jeb

            _jeb.clear_backends()
        except (ImportError, AttributeError):
            jax.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # Older jax (e.g. 0.4.x) has no jax_num_cpu_devices; the
        # xla_force_host_platform_device_count XLA flag set above provides
        # the same 8-device virtual CPU mesh there.
        pass
    assert jax.default_backend() == "cpu" and len(jax.devices()) == 8
