"""On-chip differential test for the fused Pallas verifier. SKIPPED on CPU
backends (the suite forces CPU; run explicitly on the TPU env:
`JAX_PLATFORMS=axon python -m pytest tests/test_pallas_tpu.py`)."""

import numpy as np
import pytest

import jax

pytestmark = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="pallas TPU kernel requires a TPU backend",
)


def test_pallas_differential_vs_scalar():
    from tendermint_tpu.crypto import ed25519 as ref
    from tendermint_tpu.ops import ed25519_batch as edb

    assert edb._use_pallas()
    rng = np.random.default_rng(5)
    privs = [ref.gen_priv_key(bytes([i % 250 + 1]) * 32) for i in range(200)]
    items = []
    expect = []
    for i in range(4500):
        p = privs[i % 200]
        msg = b"pl%d" % i + rng.bytes(30)
        sig = ref.sign(p.data, msg)
        bad = i % 11 == 0
        if bad:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((p.pub_key().data, msg, sig))
        expect.append(not bad)
    # adversarial: S >= L, truncated sig, off-curve pubkey
    items.append((privs[0].pub_key().data, b"x", b"\xff" * 64)); expect.append(False)
    items.append((privs[0].pub_key().data, b"x", b"\x00" * 63)); expect.append(False)
    items.append((b"\x01" * 32, b"x", ref.sign(privs[0].data, b"x"))); expect.append(False)

    out = edb.verify_batch(items)
    assert (out == np.array(expect)).all()
    # scalar differential on a sample
    sample = list(range(0, len(items), 131))
    scal = np.array([ref.verify(*items[i]) for i in sample])
    assert (out[sample] == scal).all()


def test_pipelined_device_sha_matches_default(monkeypatch):
    """TM_TPU_DEVICE_SHA=1 routes digests through ops/sha512_jax and the
    on-device column slicing; verdicts must equal the default C-hash path
    bit for bit, including corruptions and mixed message lengths."""
    from tendermint_tpu.crypto import ed25519 as ref
    from tendermint_tpu.ops import ed25519_batch as edb

    rng = np.random.default_rng(9)
    privs = [ref.gen_priv_key(bytes([i % 250 + 1]) * 32) for i in range(64)]
    items = []
    for i in range(4200):  # > CHUNK so the slicing spans two chunks
        p = privs[i % 64]
        msg = b"ds%d" % i + rng.bytes(i % 200)  # mixed lengths, 1-2 blocks
        sig = ref.sign(p.data, msg)
        if i % 13 == 0:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        items.append((p.pub_key().data, msg, sig))

    monkeypatch.setenv("TM_TPU_DEVICE_SHA", "1")
    dev = edb.verify_batch(items)
    monkeypatch.setenv("TM_TPU_DEVICE_SHA", "0")
    host = edb.verify_batch(items)
    assert (dev == host).all()
    assert not dev[0] and dev.sum() == sum(1 for i in range(4200) if i % 13)

    # an over-long message must fall back to the C path with a warning,
    # not degrade silently
    import warnings

    items.append((privs[0].pub_key().data, b"L" * 2000,
                  ref.sign(privs[0].data, b"L" * 2000)))
    monkeypatch.setenv("TM_TPU_DEVICE_SHA", "1")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = edb.verify_batch(items)
    assert out[-1] and (out[:-1] == dev).all()
    assert any("C host hash" in str(x.message) for x in w)
