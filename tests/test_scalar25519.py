"""Direct unit tests for the numeric host-prep code: reduce_mod_l against
exact integer arithmetic at boundary values, lt_l at the L fence,
comb_windows bit-exact reconstruction, and the C hash library differential
against hashlib at padding boundaries (VERDICT round-2 weak #6)."""

import hashlib

import numpy as np

from tendermint_tpu.crypto.ed25519 import L
from tendermint_tpu.ops import chash
from tendermint_tpu.ops import scalar25519 as sc


def _le(v: int, nbytes: int) -> bytes:
    return v.to_bytes(nbytes, "little")


def test_reduce_mod_l_boundaries_and_random():
    cases = [
        0, 1, 2, L - 1, L, L + 1, 2 * L, 2 * L - 1,
        2**252, 2**252 - 1, 2**255 - 19, 2**256 - 1,
        2**511, 2**512 - 1,
        # largest multiple of L that fits in 512 bits, and its neighbors
        ((2**512 - 1) // L) * L, ((2**512 - 1) // L) * L - 1,
        # values whose high part stresses every fold stage
        (L - 1) << 252, ((L - 1) << 252) + L - 1,
    ]
    rng = np.random.default_rng(11)
    cases += [int.from_bytes(rng.bytes(64), "little") for _ in range(500)]

    vals = np.frombuffer(
        b"".join(_le(v, 64) for v in cases), dtype=np.uint8
    ).reshape(len(cases), 64)
    got = sc.reduce_mod_l(np.ascontiguousarray(vals))
    for i, v in enumerate(cases):
        want = v % L
        assert int.from_bytes(bytes(got[i]), "little") == want, hex(v)


def test_lt_l_fence():
    cases = {
        0: True, 1: True, L - 1: True, L: False, L + 1: False,
        2**252: True,  # 2^252 < L
        2**253: False, 2**256 - 1: False,
    }
    arr = np.frombuffer(
        b"".join(_le(v, 32) for v in cases), dtype=np.uint8
    ).reshape(len(cases), 32)
    got = sc.lt_l(np.ascontiguousarray(arr))
    for (v, want), g in zip(cases.items(), got):
        assert bool(g) == want, hex(v)


def test_comb_windows_reconstruct():
    rng = np.random.default_rng(7)
    scalars = [0, 1, L - 1, 2**256 - 1] + [
        int.from_bytes(rng.bytes(32), "little") for _ in range(100)
    ]
    arr = np.frombuffer(
        b"".join(_le(v, 32) for v in scalars), dtype=np.uint8
    ).reshape(len(scalars), 32)
    win = sc.comb_windows(np.ascontiguousarray(arr))
    assert win.shape == (len(scalars), 64) and win.max() <= 15
    for i, v in enumerate(scalars):
        # processing order: output column 0 is bit-column 63
        rec = 0
        for out_col in range(64):
            j = 63 - out_col
            w = int(win[i, out_col])
            for t in range(4):
                if w >> t & 1:
                    rec |= 1 << (j + 64 * t)
        assert rec == v, hex(v)


def test_chash_differential_vs_hashlib():
    # message lengths straddling SHA-512 (128B block, 112B pad fence) and
    # SHA-256 (64B block, 56B pad fence) boundaries
    lengths = [0, 1, 55, 56, 57, 63, 64, 65, 111, 112, 113, 127, 128, 129,
               255, 256, 1000]
    msgs = [bytes([i % 256]) * n for i, n in enumerate(lengths)]

    got512 = chash.sha512_many(msgs)
    for i, m in enumerate(msgs):
        assert bytes(got512[i]) == hashlib.sha512(m).digest(), len(m)

    got256 = chash.sha256_many(msgs)
    for i, m in enumerate(msgs):
        assert bytes(got256[i]) == hashlib.sha256(m).digest(), len(m)

    n = len(msgs)
    r32 = np.frombuffer(bytes(range(32)) * n, dtype=np.uint8).reshape(n, 32)
    a32 = np.frombuffer(bytes(range(32, 64)) * n, dtype=np.uint8).reshape(n, 32)
    got = chash.sha512_rab(np.ascontiguousarray(r32),
                           np.ascontiguousarray(a32), msgs)
    for i, m in enumerate(msgs):
        want = hashlib.sha512(bytes(r32[i]) + bytes(a32[i]) + m).digest()
        assert bytes(got[i]) == want, len(m)


def test_device_mod_l_reduction_matches_host():
    """The device-side radix-2^12 mod-L reducer + window extractor
    (ops/ed25519_pallas) must be bit-identical to the host numpy path
    (scalar25519.reduce_mod_l / comb_windows) -- it feeds the comb kernel."""
    import jax.numpy as jnp

    from tendermint_tpu.ops import ed25519_pallas as edp
    from tendermint_tpu.ops import scalar25519 as sc

    rng = np.random.default_rng(3)
    cases = [rng.integers(0, 256, size=(64,), dtype=np.uint8) for _ in range(64)]
    L = sc.L
    for v in [0, 1, L - 1, L, L + 1, 2**252, 2**252 - 1,
              (2**512 - 1) // L * L, (2**512 - 1) // L * L - 1, 2**512 - 1,
              L * 2**259, L * 2**259 + 5]:
        cases.append(np.frombuffer(
            int(v % 2**512).to_bytes(64, "little"), dtype=np.uint8).copy())
    arr = np.stack(cases)
    host = sc.reduce_mod_l(arr)
    dev = np.asarray(edp._reduce_mod_l_device(jnp.asarray(arr.T)))
    for i in range(len(cases)):
        want = int.from_bytes(host[i].tobytes(), "little")
        got = sum(int(dev[j, i]) << (12 * j) for j in range(22))
        assert got == want, i
        assert all(0 <= dev[j, i] < 4096 for j in range(22)), i
    hw_host = sc.comb_windows(host)
    hw_dev = np.asarray(edp._windows_from_limbs12(jnp.asarray(dev)))
    assert (hw_host == hw_dev.T).all()


def test_sha512_rab_uniform_lengths_cross_padding_boundaries():
    """Regression (round-5 review): the 4-way AVX2 SHA-512 lanes only fire
    for quads of EQUAL message length, so every length must be tested
    uniformly — and (64 + mlen) % 128 == 112 (mlen = 48 mod 128) is the
    exact padding boundary where the 0x80 byte needs a whole extra block
    (the original nblk formula overwrote the length field instead)."""
    import hashlib
    import random

    import numpy as np

    from tendermint_tpu.ops import chash

    rng = random.Random(11)
    for L in (0, 1, 47, 48, 49, 63, 64, 111, 112, 113, 127, 128,
              175, 176, 177, 304, 432, 944):
        n = 8
        r32 = np.frombuffer(rng.randbytes(32 * n), np.uint8).reshape(n, 32)
        a32 = np.frombuffer(rng.randbytes(32 * n), np.uint8).reshape(n, 32)
        msgs = [rng.randbytes(L) for _ in range(n)]
        got = chash.sha512_rab(np.ascontiguousarray(r32),
                               np.ascontiguousarray(a32), msgs)
        for i in range(n):
            exp = hashlib.sha512(bytes(r32[i]) + bytes(a32[i]) + msgs[i]).digest()
            assert bytes(got[i]) == exp, f"L={L} lane {i}"
