"""Full-node integration: real TCP p2p (secret connection + mconnection +
reactors), multi-node consensus over sockets, tx gossip, fast sync catch-up."""

import os
import time

import pytest

from tendermint_tpu.config.config import test_config as make_test_config
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.privval.file_pv import MockPV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.ttime import Time


def _mk_genesis(n):
    privs = [ed25519.gen_priv_key(bytes([70 + i]) * 32) for i in range(n)]
    genesis = GenesisDoc(
        chain_id="tcp-chain",
        genesis_time=Time(1700002000, 0),
        validators=[GenesisValidator(b"", p.pub_key(), 10) for p in privs],
    )
    return genesis, privs


def _mk_node(tmp_path, i, genesis, priv, fast_sync=False):
    cfg = make_test_config()
    cfg.set_root(str(tmp_path / f"node{i}"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = fast_sync
    cfg.p2p.laddr = "tcp://127.0.0.1:0"  # ephemeral port
    cfg.rpc.laddr = ""  # no RPC in this test
    cfg.consensus.wal_path = os.path.join(cfg.base.root_dir, "cs.wal")
    node_key = NodeKey(ed25519.gen_priv_key(bytes([90 + i]) * 32))
    return Node(cfg, genesis=genesis, priv_validator=MockPV(priv), node_key=node_key)


def _wait(cond, timeout, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_two_nodes_over_tcp_commit_blocks(tmp_path):
    genesis, privs = _mk_genesis(2)
    n0 = _mk_node(tmp_path, 0, genesis, privs[0])
    n1 = _mk_node(tmp_path, 1, genesis, privs[1])
    n0.start()
    n1.start()
    try:
        # n1 dials n0
        addr = n0.p2p_addr()
        assert n1.switch.dial_peer(addr) is not None
        assert _wait(lambda: len(n0.switch.peers) == 1, 10)

        # consensus must commit blocks over real sockets
        assert _wait(lambda: n0.block_store.height >= 2 and n1.block_store.height >= 2,
                     60), (n0.block_store.height, n1.block_store.height)
        assert (n0.block_store.load_block(1).hash()
                == n1.block_store.load_block(1).hash())

        # tx gossip: submit on n1, must land in a block on n0
        n1.mempool.check_tx(b"gossip=works")
        def tx_committed():
            for h in range(1, n0.block_store.height + 1):
                b = n0.block_store.load_block(h)
                if b and b"gossip=works" in b.data.txs:
                    return True
            return False
        assert _wait(tx_committed, 30)
    finally:
        n0.stop()
        n1.stop()


def test_fast_sync_catches_up(tmp_path):
    """A fresh node fast-syncs a chain from an up-to-date peer, then switches
    to consensus."""
    genesis, privs = _mk_genesis(3)
    nodes = [_mk_node(tmp_path, i, genesis, privs[i]) for i in range(2)]
    for n in nodes:
        n.start()
    try:
        assert nodes[1].switch.dial_peer(nodes[0].p2p_addr()) is not None
        # 2 of 3 validators = 2/3... power 20 of 30 is NOT > 2/3(=20); need 3rd
        late = _mk_node(tmp_path, 2, genesis, privs[2])
        late.start()
        try:
            late.switch.dial_peer(nodes[0].p2p_addr())
            late.switch.dial_peer(nodes[1].p2p_addr())
            assert _wait(lambda: all(n.block_store.height >= 4 for n in nodes), 90), (
                [n.block_store.height for n in nodes]
            )
            # stop the late node, let the chain advance, restart-like catchup
            h_before = late.block_store.height
            assert _wait(lambda: late.block_store.height >= 4, 60), late.block_store.height
            _ = h_before
        finally:
            late.stop()
    finally:
        for n in nodes:
            n.stop()
