"""Counter example app vs reference abci/example/counter/counter.go."""

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.counter import (
    CODE_TYPE_BAD_NONCE,
    CODE_TYPE_ENCODING_ERROR,
    CounterApp,
)


def _tx(n: int) -> bytes:
    return n.to_bytes((n.bit_length() + 7) // 8 or 1, "big")


def test_non_serial_accepts_anything():
    app = CounterApp()
    assert app.check_tx(abci.RequestCheckTx(tx=b"\x00" * 20)).is_ok()
    assert app.deliver_tx(abci.RequestDeliverTx(tx=b"whatever")).is_ok()
    assert app.tx_count == 1


def test_serial_nonce_rules():
    app = CounterApp(serial=True)
    # CheckTx: >= count passes, < count is a bad nonce (counter.go:66-82)
    assert app.check_tx(abci.RequestCheckTx(tx=_tx(0))).is_ok()
    assert app.check_tx(abci.RequestCheckTx(tx=_tx(5))).is_ok()
    # DeliverTx: must equal the count exactly (counter.go:45-62)
    assert app.deliver_tx(abci.RequestDeliverTx(tx=_tx(0))).is_ok()
    r = app.deliver_tx(abci.RequestDeliverTx(tx=_tx(0)))
    assert r.code == CODE_TYPE_BAD_NONCE and "Expected 1" in r.log
    assert app.deliver_tx(abci.RequestDeliverTx(tx=_tx(1))).is_ok()
    r = app.check_tx(abci.RequestCheckTx(tx=_tx(1)))
    assert r.code == CODE_TYPE_BAD_NONCE
    # oversize tx
    r = app.deliver_tx(abci.RequestDeliverTx(tx=b"\x01" * 9))
    assert r.code == CODE_TYPE_ENCODING_ERROR


def test_commit_hash_and_query():
    app = CounterApp()
    assert app.commit().data == b""  # no txs yet: empty hash (counter.go:87)
    app.deliver_tx(abci.RequestDeliverTx(tx=b"\x00"))
    # tx_count is 1 after one deliver; the hash is its 8-byte BE encoding
    assert app.commit().data == (1).to_bytes(8, "big")
    assert app.query(abci.RequestQuery(path="hash")).value == b"2"
    assert app.query(abci.RequestQuery(path="tx")).value == b"1"
    assert "Invalid query path" in app.query(abci.RequestQuery(path="x")).log


def test_set_option_enables_serial():
    app = CounterApp()
    app.set_option("serial", "on")
    app.deliver_tx(abci.RequestDeliverTx(tx=_tx(0)))
    assert app.deliver_tx(abci.RequestDeliverTx(tx=_tx(7))).code == CODE_TYPE_BAD_NONCE


def test_counter_in_node_selection():
    from tendermint_tpu.node.node import default_app

    assert isinstance(default_app("counter"), CounterApp)
    assert default_app("counter_serial").serial is True
