"""Differential tests: batched TPU-path ed25519 verify vs the scalar reference.

The contract under test is SURVEY.md's hard requirement: byte-identical
accept/reject decisions between tendermint_tpu.ops.ed25519_batch.verify_batch
and tendermint_tpu.crypto.ed25519.verify for every input class, including
malformed and adversarial ones (reference semantics:
crypto/ed25519/ed25519.go:148)."""

import random

import numpy as np

from tendermint_tpu.crypto import ed25519 as ref
from tendermint_tpu.ops import ed25519_batch as batch

rng = random.Random(99)


def _keypair(i):
    seed = bytes([i % 256] * 31 + [(i * 7 + 3) % 256])
    priv = ref.gen_priv_key(seed)
    return priv, priv.pub_key()


def _check(items):
    got = batch.verify_batch(items)
    want = np.array([ref.verify(p, m, s) for (p, m, s) in items])
    assert got.shape == want.shape
    mism = np.nonzero(got != want)[0]
    assert mism.size == 0, f"mismatch at {mism[:10]}: got {got[mism[:10]]}"


def test_valid_signatures():
    items = []
    for i in range(20):
        priv, pub = _keypair(i)
        msg = bytes([i]) * (i + 1)
        items.append((pub.data, msg, ref.sign(priv.data, msg)))
    got = batch.verify_batch(items)
    assert got.all()
    _check(items)


def test_mixed_corruptions():
    items = []
    for i in range(48):
        priv, pub = _keypair(i)
        msg = b"vote-" + bytes([i])
        sig = bytearray(ref.sign(priv.data, msg))
        kind = i % 6
        if kind == 1:  # flip a bit in R
            sig[rng.randrange(32)] ^= 1 << rng.randrange(8)
        elif kind == 2:  # flip a bit in S
            sig[32 + rng.randrange(32)] ^= 1 << rng.randrange(8)
        elif kind == 3:  # wrong message
            msg = msg + b"!"
        elif kind == 4:  # wrong key
            pub = _keypair(i + 1)[1]
        elif kind == 5:  # random garbage sig
            sig = bytearray(rng.randbytes(64))
        items.append((pub.data, bytes(msg), bytes(sig)))
    _check(items)


def test_adversarial_encodings():
    priv, pub = _keypair(7)
    msg = b"edge"
    sig = ref.sign(priv.data, msg)
    s_int = int.from_bytes(sig[32:], "little")
    items = [
        # s >= L (add L to a valid s: same sig equation, must reject)
        (pub.data, msg, sig[:32] + (s_int + ref.L).to_bytes(32, "little")),
        # s = L exactly
        (pub.data, msg, sig[:32] + ref.L.to_bytes(32, "little")),
        # non-canonical pubkey: y = p (encodes like 0 but >= p)
        (ref.P.to_bytes(32, "little"), msg, sig),
        # pubkey = identity encoding (y=1, valid small-order point)
        ((1).to_bytes(32, "little"), msg, sig),
        # pubkey y not on curve
        ((5).to_bytes(32, "little"), msg, sig),
        # x=0 with sign bit set (invalid per RFC 8032)
        ((1 | (1 << 255)).to_bytes(32, "little"), msg, sig),
        # non-canonical R: y_R >= p
        (pub.data, msg, ref.P.to_bytes(32, "little") + sig[32:]),
        # R with sign bit flipped
        (pub.data, msg, bytes([sig[0], *sig[1:31], sig[31] ^ 0x80]) + sig[32:]),
        # wrong sizes
        (pub.data[:-1], msg, sig),
        (pub.data, msg, sig[:-1]),
        # zero everything
        (b"\x00" * 32, b"", b"\x00" * 64),
        # valid control
        (pub.data, msg, sig),
    ]
    _check(items)


def test_small_order_pubkey_signatures():
    """Signatures under small-order keys: both paths must agree (h is reduced
    mod L in both, so torsion components behave identically)."""
    # y = -1 point (order 2): encoding of y = p-1
    small = (ref.P - 1).to_bytes(32, "little")
    items = []
    for i in range(8):
        r = rng.randbytes(32)
        s = rng.randrange(ref.L).to_bytes(32, "little")
        items.append((small, b"m%d" % i, r + s))
    # forged sig with s=0, R=identity-encoding under small-order key
    items.append((small, b"x", (1).to_bytes(32, "little") + b"\x00" * 32))
    _check(items)


def test_forged_sig_under_invalid_pubkey():
    """Regression (round-3 advisor finding): a non-decompressable pubkey gets
    an identity comb table, so R' = [s]B; a crafted sig with R = compress([s]B)
    would verify under ANY off-curve key unless key validity is folded into
    the item mask. The scalar path rejects at _decompress(pub) is None."""
    bad_pubs = [
        (5).to_bytes(32, "little"),            # y not on curve
        ref.P.to_bytes(32, "little"),          # y >= p
        (1 | (1 << 255)).to_bytes(32, "little"),  # x=0 with sign bit
    ]
    items = []
    for i, bad in enumerate(bad_pubs):
        s = (i + 2) * 12345 % ref.L
        r_bytes = ref._compress(ref._scalarmult(s, ref.BASE))
        forged = r_bytes + s.to_bytes(32, "little")
        for msg in (b"", b"any message %d" % i):
            items.append((bad, msg, forged))
    got = batch.verify_batch(items)
    assert not got.any(), "forged sig accepted under invalid pubkey"
    _check(items)


def test_large_batch_with_padding():
    """Crosses a bucket boundary (70 -> padded 128)."""
    items = []
    for i in range(70):
        priv, pub = _keypair(i % 9)
        msg = b"batch-%d" % i
        sig = ref.sign(priv.data, msg)
        if i % 7 == 0:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        items.append((pub.data, msg, sig))
    _check(items)


def test_empty_batch():
    assert batch.verify_batch([]).shape == (0,)
