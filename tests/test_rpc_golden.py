"""RPC JSON golden-shape vectors (r4 verdict missing #2).

The skeletons below were extracted from the REFERENCE's own API contract
(/root/reference/rpc/openapi/openapi.yaml components.schemas, $ref/allOf
resolved) and frozen here. For each route the test asserts that our
hand-built JSON (rpc/core.py) is a SUPERSET of the reference shape: every
key a reference client would read exists and carries the same JSON type
(string-typed int64s stay strings, int32s stay numbers, and so on). That
is what "a reference client can parse our responses" means concretely.

Arrays check their first element when non-empty. "any" skips (the openapi
schema itself leaves those open). Extra keys on our side are fine —
clients ignore unknown fields."""

from __future__ import annotations

import time

import pytest

from tests.test_rpc import _mk_node, _rpc  # noqa: F401  (same tier helpers)

# openapi.yaml components.schemas, $ref/allOf resolved; see module docstring
GOLDEN = {
    "status": {
        "node_info": {
            "protocol_version": {"p2p": "string", "block": "string",
                                 "app": "string"},
            "id": "string", "listen_addr": "string", "network": "string",
            "version": "string", "channels": "string", "moniker": "string",
            "other": {"tx_index": "string", "rpc_address": "string"},
        },
        "sync_info": {
            "latest_block_hash": "string", "latest_app_hash": "string",
            "latest_block_height": "string", "latest_block_time": "string",
            "earliest_block_hash": "string", "earliest_app_hash": "string",
            "earliest_block_height": "string",
            "earliest_block_time": "string", "catching_up": "boolean",
        },
        "validator_info": {
            "address": "string",
            "pub_key": {"type": "string", "value": "string"},
            "voting_power": "string",
        },
    },
    "block": {
        "block_id": "any",
        "block": {
            "header": {
                "version": {"block": "string"},
                "chain_id": "string", "height": "string", "time": "string",
                "last_block_id": "any", "last_commit_hash": "string",
                "data_hash": "string", "validators_hash": "string",
                "next_validators_hash": "string", "consensus_hash": "string",
                "app_hash": "string", "last_results_hash": "string",
                "evidence_hash": "string", "proposer_address": "string",
            },
            "last_commit": {
                "height": "any", "round": "integer", "block_id": "any",
                "signatures": ["any"],
            },
        },
    },
    "abci_info": {
        "response": {"data": "string", "version": "string",
                     "app_version": "string"},
    },
    "commit": {
        "signed_header": {
            "header": {
                "chain_id": "string", "height": "string", "time": "string",
                "validators_hash": "string", "next_validators_hash": "string",
                "app_hash": "string", "proposer_address": "string",
            },
            "commit": {
                "height": "string", "round": "integer", "block_id": "any",
                "signatures": [{
                    "block_id_flag": "integer",
                    "validator_address": "string",
                    "timestamp": "string", "signature": "string",
                }],
            },
        },
        "canonical": "boolean",
    },
    "validators": {
        "block_height": "string",
        "validators": [{
            "address": "string",
            "pub_key": {"type": "string", "value": "string"},
            "voting_power": "string", "proposer_priority": "string",
        }],
        "count": "string", "total": "string",
    },
    "block_results": {
        "height": "string",
    },
    "net_info": {
        "listening": "boolean", "listeners": ["string"], "n_peers": "string",
        "peers": ["any"],
    },
    "genesis": {
        "genesis": {
            "genesis_time": "string", "chain_id": "string",
            "consensus_params": "any",
            "validators": [{
                "address": "string",
                "pub_key": {"type": "string", "value": "string"},
                "power": "string", "name": "string",
            }],
            "app_hash": "string",
        },
    },
    "num_unconfirmed_txs": {
        "n_txs": "string", "total": "string", "total_bytes": "string",
    },
}

_JSON_TYPES = {
    "string": str,
    "integer": (int,),
    "boolean": bool,
    "number": (int, float),
}


def _assert_shape(golden, got, path):
    if golden == "any":
        return
    if isinstance(golden, dict):
        assert isinstance(got, dict), f"{path}: expected object, got {type(got).__name__}"
        for k, sub in golden.items():
            assert k in got, f"{path}.{k}: missing (reference clients read it)"
            _assert_shape(sub, got[k], f"{path}.{k}")
        return
    if isinstance(golden, list):
        assert isinstance(got, list), f"{path}: expected array, got {type(got).__name__}"
        if got:
            _assert_shape(golden[0], got[0], f"{path}[0]")
        return
    want = _JSON_TYPES[golden]
    # JSON bool is an int subclass in Python: keep the check exact
    if golden == "integer":
        ok = isinstance(got, int) and not isinstance(got, bool)
    elif golden == "boolean":
        ok = isinstance(got, bool)
    else:
        ok = isinstance(got, want) and not isinstance(got, bool)
    assert ok, f"{path}: expected {golden}, got {type(got).__name__} ({got!r})"


@pytest.fixture(scope="module")
def live_node(tmp_path_factory):
    node = _mk_node(tmp_path_factory.mktemp("golden"))
    node.start()
    try:
        deadline = time.monotonic() + 40
        while time.monotonic() < deadline and node.block_store.height < 2:
            time.sleep(0.1)
        assert node.block_store.height >= 2
        yield "http://" + node.rpc_server.laddr.split("://", 1)[1]
    finally:
        node.stop()


@pytest.mark.parametrize("route", sorted(GOLDEN))
def test_rpc_shape_matches_reference(route, live_node):
    params = {"height": 2} if route in ("block", "commit",
                                        "block_results") else {}
    result = _rpc(live_node, route, params)
    _assert_shape(GOLDEN[route], result, route)
