"""Operator CLI: init a home dir, run a node from it, then drive the
maintenance commands (replay, reindex-event, compact, debug, light --once)
against the produced chain (reference: cmd/tendermint/commands/)."""

import json
import os
import time

from tendermint_tpu.cli.main import main as cli


def _wait(cond, timeout, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_cli_lifecycle(tmp_path, capsys):
    home = str(tmp_path / "home")
    assert cli(["--home", home, "init", "--chain-id", "cli-chain"]) == 0
    assert os.path.exists(f"{home}/config/genesis.json")
    assert cli(["--home", home, "show-node-id"]) == 0
    assert cli(["--home", home, "show-validator"]) == 0
    assert cli(["--home", home, "version"]) == 0
    capsys.readouterr()

    # run a real node from the CLI home (in-process; `start` blocks, so wire
    # the Node directly like cmd_start does)
    from tendermint_tpu.cli.main import _load_config
    from tendermint_tpu.node.node import Node

    cfg = _load_config(home)
    cfg.base.db_backend = "sqlite"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = os.path.join(home, "data", "cs.wal")
    node = Node(cfg)
    node.start()
    try:
        node.mempool.check_tx(b"cli=works")
        assert _wait(lambda: node.block_store.height >= 3, 60)
        rpc_addr = node.rpc_server.laddr

        # light --once against the running node
        meta = node.block_store.load_block_meta(1)
        assert cli(["--home", str(tmp_path / "lighthome"), "light", "cli-chain",
                    "--primary", "http://" + rpc_addr.split("://", 1)[1],
                    "--trusted-height", "1",
                    "--trusted-hash", meta.block_id.hash.hex(),
                    "--trust-period", str(10 * 365 * 24 * 3600.0),
                    "--once"]) == 0
        out = capsys.readouterr().out
        assert "verified height" in out or "Light client running" in out

        # debug against the running node
        assert cli(["--home", home, "debug", "--rpc-laddr", rpc_addr,
                    "--output", str(tmp_path / "dbg")]) == 0
        doc = json.load(open(tmp_path / "dbg" / "dump.json"))
        assert int(doc["status"]["sync_info"]["latest_block_height"]) >= 1
        assert doc["block_store"]["height"] >= 1
    finally:
        node.stop()
    time.sleep(0.3)  # let sqlite handles settle

    # offline maintenance on the same home
    assert cli(["--home", home, "replay"]) == 0
    out = capsys.readouterr().out
    assert "Replayed to height" in out

    assert cli(["--home", home, "reindex-event"]) == 0
    out = capsys.readouterr().out
    assert "Reindexed heights" in out

    assert cli(["--home", home, "compact"]) == 0
    out = capsys.readouterr().out
    assert "compacted" in out

    assert cli(["--home", home, "rollback"]) == 0
    out = capsys.readouterr().out
    assert "Rolled back state to height" in out
