"""Observability: structured logger, Prometheus registry/exposition,
tx/block indexer, and the localnet criterion -- metrics scrapeable and
tx_search returning an indexed tx (reference: libs/log, consensus/metrics.go,
state/txindex/indexer_service.go)."""

import io
import json
import os
import time
import urllib.request

from tendermint_tpu.abci import types as abci
from tendermint_tpu.state.txindex import BlockIndexer, TxIndexer
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.types.tx import tx_hash
from tendermint_tpu.utils.log import NopLogger, new_logger
from tendermint_tpu.utils.metrics import Counter, Gauge, Histogram, Registry


def test_logger_plain_and_json_and_levels():
    sink = io.StringIO()
    lg = new_logger(level="info", fmt="plain", sink=sink)
    lg.debug("invisible", x=1)
    lg.info("hello", height=5, hash=b"\xab\xcd")
    lg.error("bad", err=ValueError("boom"))
    out = sink.getvalue()
    assert "invisible" not in out
    assert "INF" in out and "hello" in out and "height=5" in out
    assert "abcd" in out  # bytes rendered as hex
    assert "ERR" in out and "ValueError: boom" in out

    sink2 = io.StringIO()
    jlg = new_logger(level="debug", fmt="json", sink=sink2).with_(module="consensus")
    jlg.debug("visible", round=2)
    doc = json.loads(sink2.getvalue())
    assert doc["module"] == "consensus" and doc["round"] == 2
    assert doc["level"] == "DBG" and doc["msg"] == "visible"

    # binding is immutable
    base = new_logger(sink=io.StringIO())
    bound = base.with_(module="p2p")
    assert bound._bound == {"module": "p2p"} and base._bound == {}

    NopLogger().with_(x=1).info("goes nowhere")


def test_metrics_registry_exposition():
    r = Registry(namespace="tm")
    c = r.counter("consensus", "txs_total", "Total txs.")
    g = r.gauge("p2p", "peers", "Peers.", labels=("dir",))
    h = r.histogram("state", "apply_seconds", "Apply time.", buckets=(0.1, 1.0))
    c.add(3)
    g.set(4, dir="out")
    g.set(2, dir="in")
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = r.expose()
    assert "# TYPE tm_consensus_txs_total counter" in text
    assert "tm_consensus_txs_total 3.0" in text
    assert 'tm_p2p_peers{dir="out"} 4.0' in text
    assert 'tm_p2p_peers{dir="in"} 2.0' in text
    assert 'tm_state_apply_seconds_bucket{le="0.1"} 1' in text
    assert 'tm_state_apply_seconds_bucket{le="1.0"} 2' in text
    assert 'tm_state_apply_seconds_bucket{le="+Inf"} 3' in text
    assert "tm_state_apply_seconds_count 3" in text
    assert "tm_state_apply_seconds_sum 5.55" in text


def test_sigcache_and_sharded_verify_metrics_exposed():
    """ISSUE 4 metrics satellite: the signature-cache hit/miss counters and
    the sharded-dispatch counter flow through NodeMetrics into the same
    exposition the /metrics route serves."""
    from tendermint_tpu.crypto import sigcache
    from tendermint_tpu.utils import metrics as tmmetrics

    m = tmmetrics.NodeMetrics()
    text = m.registry.expose()
    # pre-seeded at 0 so a healthy node scrapes explicit zeros
    assert "tendermint_crypto_sigcache_hits_total 0.0" in text
    assert "tendermint_crypto_sigcache_misses_total 0.0" in text

    tmmetrics.GLOBAL_NODE_METRICS = m
    try:
        sigcache.reset()
        c = sigcache.get()
        k = sigcache.cache_key(b"p", b"m", b"s")
        c.hit(k)   # miss
        c.add(k)
        c.hit(k)   # hit
        m.verify_sharded.add(devices=8)
        text = m.registry.expose()
        assert "tendermint_crypto_sigcache_hits_total 1.0" in text
        assert "tendermint_crypto_sigcache_misses_total 1.0" in text
        assert ('tendermint_consensus_verify_sharded_total{devices="8"} 1.0'
                in text)
    finally:
        tmmetrics.GLOBAL_NODE_METRICS = None
        sigcache.reset()


import pytest


@pytest.mark.quick
def test_trace_phase_histogram_preseeded_and_mirrored():
    """ISSUE 10 satellite 2: the flight-recorder phase histogram is
    pre-seeded for the whole MIRRORED_SPANS label universe (explicit
    `_bucket`/`_sum`/`_count` zeros — dashboards alert on absence), the
    step_duration histogram for the 8 step names, and a recorded span
    flows through the mirror into the same exposition."""
    from tendermint_tpu.utils import metrics as tmmetrics
    from tendermint_tpu.utils import trace as tmtrace

    m = tmmetrics.NodeMetrics()
    text = m.registry.expose()
    for phase in tmtrace.MIRRORED_SPANS:
        assert (f'tendermint_trace_phase_seconds_count{{phase="{phase}"}} 0'
                in text), phase
        assert (f'tendermint_trace_phase_seconds_sum{{phase="{phase}"}} 0.0'
                in text), phase
    assert ('tendermint_trace_phase_seconds_bucket{phase="verify.readback",'
            'le="+Inf"} 0') in text
    assert ('tendermint_trace_phase_seconds_bucket{phase="verify.readback",'
            'le="0.001"} 0') in text
    assert ('tendermint_consensus_step_duration_seconds_count'
            '{step="RoundStepCommit"} 0') in text

    tmmetrics.GLOBAL_NODE_METRICS = m
    t = tmtrace.Tracer("obs-mirror", enabled=True)
    try:
        t.record("verify.host_prep", 0.003, height=1)
        with t.span("mempool.check_tx", bytes=10):
            pass
        text = m.registry.expose()
        assert ('tendermint_trace_phase_seconds_count'
                '{phase="verify.host_prep"} 1') in text
        assert ('tendermint_trace_phase_seconds_count'
                '{phase="mempool.check_tx"} 1') in text
        assert ('tendermint_trace_phase_seconds_bucket'
                '{phase="verify.host_prep",le="0.005"} 1') in text
    finally:
        t.disable()
        tmmetrics.GLOBAL_NODE_METRICS = None


@pytest.mark.quick
def test_overload_counters_preseeded_in_exposition():
    """ISSUE 5 satellite 5: the overload-resilience counters (docs/
    OVERLOAD.md) are pre-seeded at 0 so a healthy node scrapes explicit
    zeros — dashboards alert on absence."""
    from tendermint_tpu.utils import metrics as tmmetrics

    text = tmmetrics.NodeMetrics().registry.expose()
    assert "tendermint_p2p_peers_banned_total 0.0" in text
    for ch in ("vote", "proposal", "block_part", "rpc_tx"):
        assert f'tendermint_p2p_shed_total{{channel="{ch}"}} 0.0' in text
    assert ('tendermint_p2p_rate_limited_total{peer="",channel=""} 0.0'
            in text)
    assert "# TYPE tendermint_p2p_peer_score gauge" in text


@pytest.mark.quick
def test_overload_counters_flow_through_node_sampler_shapes():
    """The scoreboard snapshot() contract the node sampler pumps: bans as
    a counter delta, sheds/rate-limits keyed for the labeled counters,
    scores as live gauges."""
    from tendermint_tpu.utils import peerscore

    b = peerscore.PeerScoreBoard()
    b.record("noisy01", "invalid_signature")
    b.ban("evil02", 60)
    b.count_shed("vote", 3)
    b.count_rate_limited("noisy01", "0x22")
    s = b.snapshot()
    assert s["scores"]["noisy01"] > 0
    assert s["bans_total"] == 1
    assert s["shed"] == {"vote": 3}
    assert s["rate_limited"] == {("noisy01", "0x22"): 1}


def _mk_result(events=None, code=0):
    return abci.ResponseDeliverTx(code=code, data=b"ok", gas_wanted=1,
                                  events=events or [])


def test_tx_indexer_index_get_search():
    idx = TxIndexer(MemDB())
    ev = [abci.Event(type="transfer", attributes=[
        abci.EventAttribute(key=b"sender", value=b"alice", index=True),
        abci.EventAttribute(key=b"memo", value=b"secret", index=False),
    ])]
    idx.index(7, 0, b"tx-one", _mk_result(ev))
    idx.index(7, 1, b"tx-two", _mk_result())
    idx.index(9, 0, b"tx-three", _mk_result(ev))

    doc = idx.get(tx_hash(b"tx-one"))
    assert doc["height"] == "7" and doc["index"] == 0
    assert doc["tx_result"]["events"][0]["type"] == "transfer"

    by_height = idx.search("tx.height=7")
    assert [d["index"] for d in by_height] == [0, 1]
    by_event = idx.search("transfer.sender=alice")
    assert len(by_event) == 2
    both = idx.search("transfer.sender=alice AND tx.height=9")
    assert len(both) == 1 and both[0]["height"] == "9"
    # unindexed attributes are not searchable
    assert idx.search("transfer.memo=secret") == []
    assert idx.search("transfer.sender=bob") == []


def test_block_indexer_search():
    idx = BlockIndexer(MemDB())
    ev = [abci.Event(type="rewards", attributes=[
        abci.EventAttribute(key=b"epoch", value=b"4", index=True)])]
    idx.index(3, ev, [])
    idx.index(5, [], ev)
    assert idx.has(3) and idx.has(5) and not idx.has(4)
    assert idx.search("rewards.epoch=4") == [3, 5]
    assert idx.search("rewards.epoch=4 AND block.height=5") == [5]


def test_query_time_date_operands():
    """TIME/DATE operands (reference libs/pubsub/query/query.go
    DateLayout + TimeLayout; r4 verdict missing #1): temporal comparison of
    RFC3339 event values, date operands spanning whole days, parse errors
    rejected at Query construction."""
    import pytest

    from tendermint_tpu.types.events import Query

    q = Query("tx.time >= TIME 2013-05-03T14:45:00Z")
    assert q.matches({"tx.time": ["2013-05-03T14:45:00Z"]})
    assert q.matches({"tx.time": ["2014-01-01T00:00:00Z"]})
    assert not q.matches({"tx.time": ["2013-05-03T14:44:59Z"]})
    assert not q.matches({"tx.time": ["garbage"]})
    q = Query("tx.date = DATE 2013-05-03")
    assert q.matches({"tx.date": ["2013-05-03"]})
    assert not q.matches({"tx.date": ["2013-05-04"]})
    # event value in RFC3339 vs DATE operand (midnight UTC, ref matchValue)
    q = Query("tx.time > DATE 2013-05-03")
    assert q.matches({"tx.time": ["2013-05-03T00:00:01Z"]})
    assert not q.matches({"tx.time": ["2013-05-02T23:59:59Z"]})
    # offsets compare as instants
    q = Query("tx.time < TIME 2013-05-03T10:00:00+02:00")
    assert q.matches({"tx.time": ["2013-05-03T07:59:00Z"]})
    assert not q.matches({"tx.time": ["2013-05-03T08:01:00Z"]})
    with pytest.raises(ValueError):
        Query("tx.time > TIME not-a-time")
    with pytest.raises(ValueError):
        Query("tx.date = DATE 2013-13-90")


def test_query_language_operators():
    """The reference grammar's comparison operators (libs/pubsub/query/
    query.go): <, <=, >, >=, CONTAINS, EXISTS — in the pubsub matcher and
    in both kv indexers (VERDICT r3 missing #6)."""
    from tendermint_tpu.types.events import Query

    q = Query("tx.height>5 AND app.key='x'")
    assert q.matches({"tx.height": ["9"], "app.key": ["x"]})
    assert not q.matches({"tx.height": ["5"], "app.key": ["x"]})
    assert not q.matches({"tx.height": ["9"], "app.key": ["y"]})
    assert Query("a.b CONTAINS 'ell'").matches({"a.b": ["hello"]})
    assert not Query("a.b CONTAINS 'z'").matches({"a.b": ["hello"]})
    assert Query("a.b EXISTS").matches({"a.b": ["1"]})
    assert not Query("a.b EXISTS").matches({"c.d": ["1"]})
    assert Query("x.n<=3 AND x.n>=3").matches({"x.n": ["3"]})

    # tx indexer: ranges + CONTAINS + EXISTS over postings
    idx = TxIndexer(MemDB())
    ev = [abci.Event(type="transfer", attributes=[
        abci.EventAttribute(key=b"sender", value=b"alice", index=True)])]
    for h, i, tx in ((5, 0, b"q-a"), (7, 0, b"q-b"), (9, 0, b"q-c")):
        idx.index(h, i, tx, _mk_result(ev if h != 7 else None))
    assert [d["height"] for d in idx.search("tx.height>5")] == ["7", "9"]
    assert [d["height"] for d in idx.search("tx.height>5 AND tx.height<9")] == ["7"]
    assert [d["height"] for d in
            idx.search("tx.height>=5 AND transfer.sender='alice'")] == ["5", "9"]
    assert [d["height"] for d in
            idx.search("transfer.sender CONTAINS 'lic' AND tx.height<6")] == ["5"]
    assert [d["height"] for d in
            idx.search("transfer.sender EXISTS AND tx.height>8")] == ["9"]

    # block indexer: height ranges + event-value ranges
    bidx = BlockIndexer(MemDB())
    for h, epoch in ((3, b"4"), (5, b"4"), (8, b"6")):
        bidx.index(h, [abci.Event(type="rewards", attributes=[
            abci.EventAttribute(key=b"epoch", value=epoch, index=True)])], [])
    assert bidx.search("block.height>3") == [5, 8]
    assert bidx.search("block.height>=3 AND block.height<8") == [3, 5]
    assert bidx.search("rewards.epoch>4") == [8]
    assert bidx.search("rewards.epoch EXISTS AND block.height<=5") == [3, 5]


def test_localnet_metrics_and_tx_search(tmp_path):
    """The VERDICT criterion: metrics scrapeable; tx_search returns an
    indexed tx."""
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.privval.file_pv import MockPV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.ttime import Time

    priv = ed25519.gen_priv_key(b"\x91" * 32)
    genesis = GenesisDoc(
        chain_id="obs-chain", genesis_time=Time(1700003000, 0),
        validators=[GenesisValidator(b"", priv.pub_key(), 10)],
    )
    cfg = test_config()
    cfg.set_root(str(tmp_path / "node"))
    os.makedirs(cfg.base.root_dir, exist_ok=True)
    cfg.base.fast_sync_mode = False
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.consensus.wal_path = ""
    cfg.tx_index.indexer = "kv"
    cfg.instrumentation.prometheus = True
    cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
    node = Node(cfg, genesis=genesis, priv_validator=MockPV(priv),
                node_key=NodeKey(ed25519.gen_priv_key(b"\x92" * 32)))
    node.start()
    try:
        node.mempool.check_tx(b"observed=yes")
        deadline = time.monotonic() + 60
        h = tx_hash(b"observed=yes")
        while time.monotonic() < deadline and node.tx_indexer.get(h) is None:
            time.sleep(0.1)
        doc = node.tx_indexer.get(h)
        assert doc is not None and doc["tx_result"]["code"] == 0

        # tx_search over RPC
        base = "http://" + node.rpc_server.laddr.split("://", 1)[1]
        body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": "tx_search",
                           "params": {"query": f"tx.height={doc['height']}"}}).encode()
        with urllib.request.urlopen(urllib.request.Request(
                base, data=body, headers={"Content-Type": "application/json"}),
                timeout=10) as r:
            res = json.loads(r.read())["result"]
        assert int(res["total_count"]) >= 1
        assert any(t["hash"] == h.hex().upper() for t in res["txs"])
        # tx route by hash
        body = json.dumps({"jsonrpc": "2.0", "id": 2, "method": "tx",
                           "params": {"hash": __import__("base64").b64encode(h).decode()}}).encode()
        with urllib.request.urlopen(urllib.request.Request(
                base, data=body, headers={"Content-Type": "application/json"}),
                timeout=10) as r:
            res = json.loads(r.read())["result"]
        assert res["height"] == doc["height"]

        # block events from kvstore's DeliverTx (creator attr) are indexed
        assert node.tx_indexer.search("app.creator=kvstore")

        # Prometheus scrape (poll: gauges update on a 0.25s sampler tick)
        def scrape():
            with urllib.request.urlopen(
                    f"http://{node.metrics_server.addr}/metrics", timeout=10) as r:
                return r.read().decode()

        text = scrape()
        while time.monotonic() < deadline:
            hval = [ln for ln in text.splitlines()
                    if ln.startswith("tendermint_consensus_height ")]
            if hval and float(hval[0].split()[-1]) >= 1:
                break
            time.sleep(0.2)
            text = scrape()
        assert hval and float(hval[0].split()[-1]) >= 1
        assert "tendermint_mempool_size" in text
        assert "tendermint_state_block_processing_time_count" in text
        # ISSUE 4: sigcache counters ride the same scrape (pre-seeded 0)
        assert "tendermint_crypto_sigcache_hits_total" in text
        assert "tendermint_crypto_sigcache_misses_total" in text
        # ISSUE 5: overload-resilience counters ride it too (pre-seeded 0)
        assert "tendermint_p2p_peers_banned_total" in text
        assert 'tendermint_p2p_shed_total{channel="vote"}' in text
        assert "tendermint_p2p_rate_limited_total" in text
        # ISSUE 10: the flight-recorder phase histogram rides the same
        # scrape, pre-seeded for the whole mirrored-span label universe
        assert ('tendermint_trace_phase_seconds_count'
                '{phase="verify.readback"}') in text
        assert ('tendermint_trace_phase_seconds_bucket'
                '{phase="consensus.abci_apply",le="+Inf"}') in text
    finally:
        node.stop()
        from tendermint_tpu.utils import metrics as tmmetrics

        tmmetrics.GLOBAL_NODE_METRICS = None
