"""Perf breakdown for the batched verifier on the current backend.

Phases timed independently at N=BENCH_N_SIGS (default 20480):
  keyset   get_keyset cache hit
  prep     host scalar prep (SHA-512, reduce mod L, validity)
  stage    padding + per-chunk transposes (host)
  device   kernel wall time with pre-staged device inputs (block_until_ready)
  e2e      full verify_batch

Run: python tools/perf_breakdown.py
"""

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

N = int(os.environ.get("BENCH_N_SIGS", 20480))


def t(fn, iters=5):
    out = []
    for _ in range(iters):
        t0 = time.monotonic()
        fn()
        out.append((time.monotonic() - t0) * 1000)
    return statistics.median(out)


def main():
    import jax

    from tendermint_tpu.crypto import ed25519 as ref
    from tendermint_tpu.ops import ed25519_batch as edb
    from tendermint_tpu.ops import ed25519_pallas as edp

    print("backend:", jax.default_backend(), "chunk:", edp.CHUNK)
    n_vals = N // 2
    privs = [ref.gen_priv_key(i.to_bytes(4, "big") * 8) for i in range(n_vals)]
    items = []
    for r in range(2):
        for i in range(n_vals):
            msg = b"breakdown" + r.to_bytes(2, "big") + i.to_bytes(4, "big") + bytes(80)
            items.append((privs[i].pub_key().data, msg, ref.sign(privs[i].data, msg)))

    # end-to-end warm
    assert edb.verify_batch(items).all()
    print("e2e      %8.1f ms" % t(lambda: edb.verify_batch(items)))

    pubs = [it[0] for it in items]
    print("keyset   %8.1f ms" % t(lambda: edb.get_keyset(pubs)))

    ks, key_idx, pub_ok = edb.get_keyset(pubs)
    pub_ok = pub_ok & ks.valid[key_idx]
    print("prep     %8.1f ms" % t(lambda: edb.prepare_scalars(items, pub_ok, windows=False)))

    s = edb.prepare_scalars(items, pub_ok, windows=False)
    n = len(items)
    nb = -(-n // edp.CHUNK) * edp.CHUNK
    idx = np.zeros((nb,), dtype=np.int32)
    idx[:n] = key_idx

    def stage():
        h32 = np.zeros((nb, 32), np.uint8); h32[:n] = s["h32"]
        s32 = np.zeros((nb, 32), np.uint8); s32[:n] = s["s32"]
        r32 = np.zeros((nb, 32), np.uint8); r32[:n] = s["r32"]
        v = np.zeros((nb, 1), np.uint8); v[:n, 0] = s["valid"]
        out = []
        for off in range(0, nb, edp.CHUNK):
            sl = slice(off, off + edp.CHUNK)
            out.append((np.ascontiguousarray(h32[sl].T), np.ascontiguousarray(s32[sl].T),
                        np.ascontiguousarray(r32[sl].T), np.ascontiguousarray(v[sl].T)))
        return out

    print("stage    %8.1f ms" % t(stage))

    staged = stage()
    tabs = [ks.gathered_lane(idx[off:off + edp.CHUNK])
            for off in range(0, nb, edp.CHUNK)]
    import jax.numpy as jnp

    dev = [tuple(jnp.asarray(x) for x in ch) for ch in staged]
    for tab in tabs:
        tab.block_until_ready()

    def device_only():
        outs = [edp._verify_chunk(tab, *ch) for tab, ch in zip(tabs, dev)]
        for o in outs:
            o.block_until_ready()

    device_only()
    print("device   %8.1f ms" % t(device_only))

    def upload():
        return [tuple(jnp.asarray(x) for x in ch) for ch in staged]

    ups = upload()
    for ch in ups:
        for x in ch:
            x.block_until_ready()

    def upload_timed():
        for ch in upload():
            for x in ch:
                x.block_until_ready()

    print("upload   %8.1f ms" % t(upload_timed))


if __name__ == "__main__":
    main()
