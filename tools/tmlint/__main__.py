"""CLI: ``python -m tools.tmlint [paths...] [options]``.

Exit 0 when every finding is baselined (or none), 1 otherwise, 2 on
usage errors. Output is one ``path:line RULE message`` per finding,
byte-deterministic across runs.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from tools.tmlint import checks  # noqa: F401  (registers rules)
from tools.tmlint import core

# The package, the tooling, the tests (registry/parity rules cover them;
# the concurrency rules scope themselves to tendermint_tpu/), and the two
# top-level entry scripts — shared with lint_gate() and the tier-1 gate.
DEFAULT_PATHS = core.DEFAULT_PATHS


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.tmlint",
        description="project-invariant static analysis for tendermint-tpu")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to scan (default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--rule", action="append", dest="rules", metavar="RULE",
                    help="run only this rule (repeatable)")
    ap.add_argument("--changed", action="store_true",
                    help="report only findings in git-changed files "
                         "(full tree still scanned so cross-file rules see "
                         "the whole graph)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default tools/tmlint/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the summary line")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(core.RULES):
            print(f"{name:24s} {core.RULES[name][1]}")
        return 0

    root = repo_root()
    paths = args.paths or DEFAULT_PATHS
    for p in paths:
        if not os.path.exists(os.path.join(root, p)):
            print(f"tmlint: no such path: {p}", file=sys.stderr)
            return 2

    t0 = time.monotonic()
    try:
        project = core.Project(root, core.collect_files(root, paths))
        findings = core.run_rules(project, args.rules)
    except ValueError as e:
        print(f"tmlint: {e}", file=sys.stderr)
        return 2

    if args.changed:
        changed = core.changed_paths(root)
        findings = [f for f in findings if f.path in changed]

    if args.write_baseline:
        if args.changed or args.paths or args.rules:
            # a filtered run would TRUNCATE the baseline to the filtered
            # findings, silently dropping grandfathered entries elsewhere
            print("tmlint: --write-baseline requires a full default-scope "
                  "all-rules run (drop --changed/--rule and explicit "
                  "paths)", file=sys.stderr)
            return 2
        core.write_baseline(findings, args.baseline)
        print(f"tmlint: wrote {len(findings)} finding(s) to baseline")
        return 0

    baseline = set() if args.no_baseline else core.load_baseline(args.baseline)
    new, old = core.split_baselined(findings, baseline)
    for f in new:
        print(f.render())
    if not args.quiet:
        dt = time.monotonic() - t0
        print(f"tmlint: {len(new)} finding(s), {len(old)} baselined, "
              f"{len(project.files)} files, {dt:.2f}s", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
