"""The tmlint rule set: 9 project invariants as AST checks.

Each rule is a pure function Project -> [Finding], registered under the
name used in output, pragmas, and --rule. The concurrency rules share one
whole-project lock/function model (built once per run) so the lock-order
graph can follow calls across modules.

Rules (docs/LINT.md has the full table with the motivating PR trail):

  lock-held-call          no blocking/callback calls under a held lock
  lock-order              static lock-acquisition graph must be acyclic
  device-sync-choke-point jax.device_get & friends only at audited sites
  thread-crash-surface    thread targets need a broad try/except shield
  daemon-or-joined        every Thread is daemonized or tracked for join
  metrics-discipline      labeled counters/gauges pre-seeded or removal-
                          disciplined (bounded exposition)
  fault-site-registry     faults.fire(...) literals canonical + documented
  trace-span-discipline   trace span(...) names canonical + documented
  config-knob-parity      TM_TPU_*/TMTPU_* knobs <-> docs/CONFIG.md
"""

from __future__ import annotations

import ast
import os
import re

from tools.tmlint.core import Finding, Project, rule

# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------


def dotted(node) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal(node) -> str | None:
    """Last segment of a call target ('c' for a.b.c(...))."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


_LOCK_SEG = re.compile(r"(?:^|_)(?:lock|mtx|mu|cv|cond)\d*$")


def _lockish_name(name: str) -> bool:
    return bool(_LOCK_SEG.search(name))


def _short_module(path: str) -> str:
    """tendermint_tpu/p2p/switch.py -> p2p.switch (message-sized keys)."""
    p = path[:-3] if path.endswith(".py") else path
    parts = p.split("/")
    if parts and parts[0] == "tendermint_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or p


_LOCK_CTORS = {"Lock", "RLock", "Condition"}


# ---------------------------------------------------------------------------
# Whole-project lock / function model
# ---------------------------------------------------------------------------


class FuncInfo:
    def __init__(self, key, module, cls, node, path):
        self.key = key          # "p2p.switch:Switch.dial_peer"
        self.module = module
        self.cls = cls          # enclosing class name or None
        self.node = node
        self.path = path
        self.acquires: list = []       # (lockkey|None, rawtext, line)
        self.edges: list = []          # (lockA, lockB, path, line)
        self.calls_under: list = []    # (ref, heldkeys, innermost_raw, line)
        self.calls_all: list = []      # refs
        self.blocking: list = []       # (callname, lockraw, line)
        self.thread_spawns: list = []  # ast.Call nodes of threading.Thread(...)


class LockModel:
    """Pass 1 collects classes/functions/imports/lock attributes; pass 2
    scans every function body resolving lock identities and call refs."""

    def __init__(self, project: Project):
        self.project = project
        self.class_locks: dict = {}    # (mod, cls) -> {attr: kind}
        self.module_locks: dict = {}   # mod -> {name: kind}
        self.methods: dict = {}        # (mod, cls) -> {name: funckey}
        self.module_funcs: dict = {}   # mod -> {name: funckey}
        self.imports: dict = {}        # mod -> {alias: target mod (short)}
        self.from_funcs: dict = {}     # mod -> {alias: (target mod, name)}
        self.funcs: dict = {}          # funckey -> FuncInfo
        self._attr_owner: dict = {}    # lock attr -> set of (mod, cls)
        self._method_owner: dict = {}  # method name -> set of funckey
        self._build()
        self._scan_all()
        self.may_acquire = self._closure()

    # -- pass 1 -------------------------------------------------------------

    def _build(self) -> None:
        for sf in self.project.prod_files():
            mod = _short_module(sf.path)
            self.imports.setdefault(mod, {})
            self.from_funcs.setdefault(mod, {})
            self.module_locks.setdefault(mod, {})
            self.module_funcs.setdefault(mod, {})
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name.startswith("tendermint_tpu"):
                            short = ".".join(a.name.split(".")[1:]) or a.name
                            self.imports[mod][a.asname or a.name.split(".")[-1]] = short
                elif isinstance(node, ast.ImportFrom):
                    if node.module and node.module.startswith("tendermint_tpu"):
                        base = ".".join(node.module.split(".")[1:])
                        for a in node.names:
                            # `from tendermint_tpu.utils import faults` makes
                            # faults a module alias; `from ..utils.faults
                            # import fire` a function alias. Record both ways;
                            # resolution tries module first.
                            tgt = f"{base}.{a.name}" if base else a.name
                            self.imports[mod].setdefault(a.asname or a.name, tgt)
                            if base:
                                self.from_funcs[mod].setdefault(
                                    a.asname or a.name, (base, a.name))
            self._collect_defs(sf, mod)

    def _collect_defs(self, sf, mod: str) -> None:
        def walk(body, cls, prefix):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    self.methods.setdefault((mod, node.name), {})
                    self.class_locks.setdefault((mod, node.name), {})
                    walk(node.body, node.name, prefix + node.name + ".")
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    key = f"{mod}:{prefix}{node.name}"
                    info = FuncInfo(key, mod, cls, node, sf.path)
                    self.funcs[key] = info
                    if cls is not None and prefix == cls + ".":
                        self.methods[(mod, cls)][node.name] = key
                        self._method_owner.setdefault(node.name, set()).add(key)
                    elif cls is None and not prefix:
                        self.module_funcs[mod][node.name] = key
                    # nested defs get their own FuncInfo (thread targets)
                    walk(node.body, cls, prefix + node.name + ".")
                else:
                    if isinstance(node, ast.Assign) and not prefix:
                        self._note_lock_assign(node, mod, None)
                    # defs directly under module-level if/try blocks
                    walk([c for c in ast.iter_child_nodes(node)
                          if isinstance(c, (ast.ClassDef, ast.FunctionDef,
                                            ast.AsyncFunctionDef))],
                         cls, prefix)

        walk(sf.tree.body, None, "")
        # method bodies: lock attribute assignments + `with self.X` usage
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        self._note_lock_assign(sub, mod, node.name)
                    elif isinstance(sub, ast.With):
                        for item in sub.items:
                            d = dotted(item.context_expr)
                            if (d and d.startswith("self.")
                                    and d.count(".") == 1
                                    and _lockish_name(d.split(".")[1])):
                                self.class_locks.setdefault(
                                    (mod, node.name), {}).setdefault(
                                    d.split(".")[1], "?")

        for (m, c), attrs in self.class_locks.items():
            for a in attrs:
                self._attr_owner.setdefault(a, set()).add((m, c))

    def _note_lock_assign(self, node: ast.Assign, mod, cls) -> None:
        if not isinstance(node.value, ast.Call):
            return
        t = terminal(node.value.func)
        d = dotted(node.value.func) or ""
        if t not in _LOCK_CTORS or not (d.startswith("threading.") or d == t):
            return
        for tgt in node.targets:
            td = dotted(tgt)
            if td is None:
                continue
            if td.startswith("self.") and td.count(".") == 1 and cls:
                self.class_locks.setdefault((mod, cls), {})[td[5:]] = t
            elif "." not in td and cls is None:
                self.module_locks.setdefault(mod, {})[td] = t

    # -- lock identity ------------------------------------------------------

    def lock_key(self, expr, mod: str, cls: str | None) -> str | None:
        """Stable identity for a lock expression, or None when the owner
        cannot be pinned (region still tracked, no order edges)."""
        d = dotted(expr)
        if d is None:
            return None
        seg = d.split(".")[-1]
        if d.startswith("self.") and d.count(".") == 1 and cls is not None:
            if _lockish_name(seg) or seg in self.class_locks.get((mod, cls), {}):
                self.class_locks.setdefault((mod, cls), {}).setdefault(seg, "?")
                return f"{mod}.{cls}.{seg}"
            return None
        if "." not in d:
            if d in self.module_locks.get(mod, {}):
                return f"{mod}.{d}"
            return None  # local variable: instance unknowable statically
        # obj.X / self.a.X: resolvable iff exactly one class owns lock X
        owners = self._attr_owner.get(seg)
        if owners and len(owners) == 1:
            (m, c), = owners
            return f"{m}.{c}.{seg}"
        return None

    def lock_kind(self, key: str) -> str:
        mod_cls, _, attr = key.rpartition(".")
        mod, _, cls = mod_cls.rpartition(".")
        for (m, c), attrs in self.class_locks.items():
            if f"{m}.{c}" == mod_cls:
                return attrs.get(attr, "?")
        return self.module_locks.get(mod_cls, {}).get(attr, "?")

    def _is_lockish_expr(self, expr, mod, cls) -> bool:
        d = dotted(expr)
        if d is None:
            return False
        seg = d.split(".")[-1]
        if _lockish_name(seg):
            return True
        if d.startswith("self.") and d.count(".") == 1 and cls is not None:
            return seg in self.class_locks.get((mod, cls), {})
        return seg in self._attr_owner

    # -- pass 2: function body scan -----------------------------------------

    def _scan_all(self) -> None:
        for info in self.funcs.values():
            self._scan(info)

    def _scan(self, info: FuncInfo) -> None:
        model = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.held: list = []  # (key|None, raw, line)

            def visit_With(self, node: ast.With):
                pushed = 0
                for item in node.items:
                    expr = item.context_expr
                    if model._is_lockish_expr(expr, info.module, info.cls):
                        raw = dotted(expr) or "<lock>"
                        key = model.lock_key(expr, info.module, info.cls)
                        info.acquires.append((key, raw, node.lineno))
                        if key is not None:
                            for hk, _, _ in self.held:
                                if hk is not None and hk != key:
                                    info.edges.append(
                                        (hk, key, info.path, node.lineno))
                        self.held.append((key, raw, node.lineno))
                        pushed += 1
                for stmt in node.body:
                    self.visit(stmt)
                for _ in range(pushed):
                    self.held.pop()

            visit_AsyncWith = visit_With

            def visit_Call(self, node: ast.Call):
                ref = model._call_ref(node, info)
                if ref is not None:
                    info.calls_all.append(ref)
                    if self.held:
                        heldkeys = tuple(hk for hk, _, _ in self.held
                                         if hk is not None)
                        info.calls_under.append(
                            (ref, heldkeys, self.held[-1][1], node.lineno))
                if self.held:
                    name = dotted(node.func) or terminal(node.func) or "?"
                    if _is_blocking_call(node):
                        info.blocking.append(
                            (name, self.held[-1][1], node.lineno))
                t = terminal(node.func)
                d = dotted(node.func) or ""
                if t == "Thread" and (d == "threading.Thread" or d == "Thread"):
                    info.thread_spawns.append(node)
                self.generic_visit(node)

            # a nested def's body is NOT executed under the enclosing
            # lock; it is scanned as its own FuncInfo.
            def visit_FunctionDef(self, node):
                pass

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_Lambda = visit_FunctionDef

            def visit_ClassDef(self, node):
                pass

        v = V()
        for stmt in info.node.body:
            v.visit(stmt)

    def _call_ref(self, node: ast.Call, info: FuncInfo):
        d = dotted(node.func)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            return ("bare", parts[0])
        if parts[0] == "self" and len(parts) == 2:
            return ("self", parts[1])
        if len(parts) == 2 and parts[0] in self.imports.get(info.module, {}):
            return ("mod", parts[0], parts[1])
        return ("attr", parts[-1])

    def resolve_ref(self, ref, info: FuncInfo) -> str | None:
        kind = ref[0]
        if kind == "self" and info.cls is not None:
            return self.methods.get((info.module, info.cls), {}).get(ref[1])
        if kind == "bare":
            fk = self.module_funcs.get(info.module, {}).get(ref[1])
            if fk:
                return fk
            tgt = self.from_funcs.get(info.module, {}).get(ref[1])
            if tgt:
                return self.module_funcs.get(tgt[0], {}).get(tgt[1])
            return None
        if kind == "mod":
            tgt = self.imports.get(info.module, {}).get(ref[1])
            if tgt is not None:
                return self.module_funcs.get(tgt, {}).get(ref[2])
            return None
        if kind == "attr":
            owners = self._method_owner.get(ref[1])
            if owners and len(owners) == 1:
                return next(iter(owners))
        return None

    # -- transitive may-acquire sets ----------------------------------------

    def _closure(self) -> dict:
        may: dict = {k: {a for a, _, _ in f.acquires if a is not None}
                     for k, f in self.funcs.items()}
        changed = True
        guard = 0
        while changed and guard < 64:
            changed = False
            guard += 1
            for key, f in self.funcs.items():
                cur = may[key]
                before = len(cur)
                for ref in f.calls_all:
                    callee = self.resolve_ref(ref, f)
                    if callee is not None and callee != key:
                        cur |= may.get(callee, set())
                if len(cur) != before:
                    changed = True
        return may


def _model(project: Project) -> LockModel:
    m = getattr(project, "_tmlint_lock_model", None)
    if m is None:
        m = LockModel(project)
        project._tmlint_lock_model = m
    return m


# ---------------------------------------------------------------------------
# Rule: lock-held-call
# ---------------------------------------------------------------------------

# Blocking or callback-invoking terminals that must never run under a held
# lock. `wait`/`notify` are excluded: Condition.wait under its own lock is
# the correct idiom. Thread.join is matched only on thread-shaped targets
# (str.join is everywhere).
_BLOCKING_TERMINALS = {
    "sleep", "sendall", "recv", "recv_into", "accept", "connect",
    "create_connection", "getaddrinfo", "device_get", "block_until_ready",
    "send", "try_send", "broadcast", "dial", "dial_peer",
    "stop_peer_for_error", "stop_peer_by_id",
}
_CALLBACK_BARE_NAMES = {"cb", "callback", "fn", "handler", "listener", "hook"}


def _is_blocking_call(node: ast.Call) -> bool:
    t = terminal(node.func)
    if t is None:
        return False
    if t in _BLOCKING_TERMINALS:
        return True
    if t.startswith("on_"):
        return True
    if isinstance(node.func, ast.Name) and t in _CALLBACK_BARE_NAMES:
        return True
    if t == "join" and isinstance(node.func, ast.Attribute):
        v = dotted(node.func.value) or ""
        if "thread" in v.lower():
            return True
    return False


@rule("lock-held-call",
      "no blocking or callback-invoking calls while holding a lock")
def check_lock_held_call(project: Project) -> list[Finding]:
    model = _model(project)
    out = []
    for info in model.funcs.values():
        for name, lockraw, line in info.blocking:
            out.append(Finding(
                info.path, line, "lock-held-call",
                f"call to {name}() inside `with {lockraw}:` — blocking/"
                f"callback work must move outside the lock"))
    return out


# ---------------------------------------------------------------------------
# Rule: lock-order
# ---------------------------------------------------------------------------


@rule("lock-order",
      "the cross-module static lock-acquisition graph must be acyclic")
def check_lock_order(project: Project) -> list[Finding]:
    model = _model(project)
    edges: dict = {}   # (A, B) -> (path, line, note)
    selfdead: list = []
    for info in model.funcs.values():
        for a, b, path, line in info.edges:
            edges.setdefault((a, b), (path, line, "nested with"))
        for ref, held, _, line in info.calls_under:
            if not held:
                continue
            callee = model.resolve_ref(ref, info)
            if callee is None:
                continue
            for lk in sorted(model.may_acquire.get(callee, ())):
                for hk in held:
                    if hk == lk:
                        # same key via a self-call chain on a non-reentrant
                        # lock: guaranteed self-deadlock
                        if (ref[0] == "self"
                                and model.lock_kind(lk) == "Lock"
                                and lk in {a for a, _, _ in
                                           model.funcs[callee].acquires}):
                            selfdead.append((info.path, line, lk, callee))
                        continue
                    edges.setdefault(
                        (hk, lk),
                        (info.path, line, f"via {callee.split(':')[-1]}()"))
    out = []
    for path, line, lk, callee in selfdead:
        out.append(Finding(
            path, line, "lock-order",
            f"non-reentrant lock {lk} re-acquired via self-call "
            f"{callee.split(':')[-1]}() while already held "
            f"(guaranteed deadlock)"))
    # Tarjan SCC over the edge set
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    sccs = _tarjan(graph)
    for scc in sccs:
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        cyc_edges = sorted((a, b) for (a, b) in edges
                           if a in scc and b in scc)
        # no line numbers in the MESSAGE: it is the baseline identity and
        # must survive unrelated line drift (the finding's own line field
        # carries the location)
        detail = "; ".join(
            f"{a}->{b} in {edges[(a, b)][0]} ({edges[(a, b)][2]})"
            for a, b in cyc_edges)
        path, line, _ = edges[cyc_edges[0]]
        out.append(Finding(
            path, line, "lock-order",
            f"lock-order cycle among {{{', '.join(cyc)}}}: {detail}"))
    return out


def _tarjan(graph: dict) -> list[set]:
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


# ---------------------------------------------------------------------------
# Rule: device-sync-choke-point
# ---------------------------------------------------------------------------

# Where host<->device syncs are ALLOWED: the kernel modules (finishers,
# probes, warmup), the shard driver, and the two audited choke FUNCTIONS —
# crypto/batch._device_get (every PendingVerify/prefetch readback) and
# crypto/verify_service._readback (the continuous-batching service's
# single blocking fetch, itself routed through _device_get). Everything
# else must go through PendingVerify/resolve_all or the service; a stray
# device_get/block_until_ready anywhere else re-introduces an unshared
# ~104 ms sync floor the ROADMAP-1 campaign just removed.
_DEVICE_ALLOW_DIRS = ("tendermint_tpu/ops/", "tendermint_tpu/parallel/")
_DEVICE_CHOKE_FUNCS = (
    ("tendermint_tpu/crypto/batch.py", "_device_get"),
    ("tendermint_tpu/crypto/verify_service.py", "_readback"),
)


@rule("device-sync-choke-point",
      "jax.device_get/block_until_ready/np.asarray only at audited sites")
def check_device_sync(project: Project) -> list[Finding]:
    out = []
    choke_by_file: dict = {}
    for path, func in _DEVICE_CHOKE_FUNCS:
        choke_by_file.setdefault(path, set()).add(func)
    for sf in project.prod_files():
        if sf.path.startswith(_DEVICE_ALLOW_DIRS):
            continue
        choke_ranges = []
        for func in choke_by_file.get(sf.path, ()):
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name == func):
                    choke_ranges.append(
                        (node.lineno, max(getattr(n, "end_lineno", node.lineno)
                                          for n in ast.walk(node))))
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            t = terminal(node.func)
            d = dotted(node.func) or ""
            hit = None
            if t == "device_get":
                hit = d or "device_get"
            elif t == "block_until_ready":
                hit = f"{d}()" if d else "block_until_ready"
            elif d in ("np.asarray", "numpy.asarray"):
                hit = d
            if hit is None:
                continue
            if any(lo <= node.lineno <= hi for lo, hi in choke_ranges):
                continue
            out.append(Finding(
                sf.path, node.lineno, "device-sync-choke-point",
                f"{hit} outside the audited sync sites — route through "
                f"crypto/batch._device_get (PendingVerify/resolve_all) or "
                f"the verify service's _readback so the ~104 ms sync floor "
                f"stays at the audited choke points"))
    return out


# ---------------------------------------------------------------------------
# Rules: thread-crash-surface, daemon-or-joined
# ---------------------------------------------------------------------------


def _broad_try(stmt) -> bool:
    if not isinstance(stmt, ast.Try):
        return False
    for h in stmt.handlers:
        if h.type is None:
            return True
        names = []
        if isinstance(h.type, ast.Tuple):
            names = [terminal(e) for e in h.type.elts]
        else:
            names = [terminal(h.type)]
        if any(n in ("Exception", "BaseException") for n in names):
            return True
    return False


def _body_after_docstring(fd):
    body = list(fd.body)
    if (body and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)):
        body = body[1:]
    return body


def _is_crash_shielded(model: LockModel, fd, depth: int = 0) -> bool:
    """A thread target survives anything if a broad try/except wraps its
    work: a top-level Try, a Try at the top of a top-level loop, or full
    delegation to a function that is itself shielded."""
    if fd is None or depth > 3:
        return False
    body = _body_after_docstring(fd.node if isinstance(fd, FuncInfo) else fd)
    node = fd.node if isinstance(fd, FuncInfo) else fd
    for stmt in body:
        if _broad_try(stmt):
            return True
        # ...or at the top of a top-level loop / with region (shield inside
        # the drain loop, or under a build lock) — same guarantee
        if isinstance(stmt, (ast.While, ast.For, ast.With)):
            if any(_broad_try(s) for s in stmt.body):
                return True
    # delegation: def run(): self._real_run()
    if len(body) == 1:
        inner = body[0]
        call = None
        if isinstance(inner, ast.Expr) and isinstance(inner.value, ast.Call):
            call = inner.value
        elif isinstance(inner, ast.Return) and isinstance(inner.value, ast.Call):
            call = inner.value
        if call is not None and isinstance(fd, FuncInfo):
            ref = model._call_ref(call, fd)
            if ref is not None:
                callee = model.resolve_ref(ref, fd)
                if callee is not None:
                    return _is_crash_shielded(model, model.funcs[callee],
                                              depth + 1)
    return False


def _resolve_thread_target(model: LockModel, info: FuncInfo, expr):
    """Map a Thread(target=...) expression to a FuncInfo, or None when the
    target is library code (e.g. httpd.serve_forever) we cannot see."""
    if isinstance(expr, ast.Lambda):
        if isinstance(expr.body, ast.Call):
            return _resolve_thread_target(model, info, expr.body.func)
        return None
    if isinstance(expr, ast.Call):  # functools.partial(f, ...)
        if terminal(expr.func) == "partial" and expr.args:
            return _resolve_thread_target(model, info, expr.args[0])
        return None
    d = dotted(expr)
    if d is None:
        return None
    parts = d.split(".")
    if len(parts) == 1:
        # nested def in the same function, then module-level
        nested = model.funcs.get(f"{info.key}.{parts[0]}")
        if nested is not None:
            return nested
        fk = model.module_funcs.get(info.module, {}).get(parts[0])
        return model.funcs.get(fk) if fk else None
    if parts[0] == "self" and len(parts) == 2 and info.cls is not None:
        fk = model.methods.get((info.module, info.cls), {}).get(parts[1])
        return model.funcs.get(fk) if fk else None
    return None


@rule("thread-crash-surface",
      "every in-tree Thread target needs a top-level broad try/except")
def check_thread_crash_surface(project: Project) -> list[Finding]:
    model = _model(project)
    out = []
    for info in model.funcs.values():
        for call in info.thread_spawns:
            tgt = _kwarg(call, "target")
            if tgt is None:
                continue
            target = _resolve_thread_target(model, info, tgt)
            if target is None:
                continue  # library target; nothing to inspect
            if not _is_crash_shielded(model, target):
                out.append(Finding(
                    info.path, call.lineno, "thread-crash-surface",
                    f"Thread target {target.key.split(':')[-1]}() has no "
                    f"top-level try/except Exception — a stray exception "
                    f"kills the routine silently"))
    return out


@rule("daemon-or-joined",
      "every Thread is daemonized or tracked for join")
def check_daemon_or_joined(project: Project) -> list[Finding]:
    model = _model(project)
    # joined attr/name terminals per module, e.g. self._thread.join()
    joined: dict = {}
    for sf in project.prod_files():
        mod = _short_module(sf.path)
        names = joined.setdefault(mod, set())
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and terminal(node.func) == "join"
                    and isinstance(node.func, ast.Attribute)):
                base = terminal(node.func.value)
                if base:
                    names.add(base)
    out = []
    for info in model.funcs.values():
        # daemon flags set in this function: `t.daemon = True`
        daemoned = set()
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "daemon"):
                        base = terminal(tgt.value)
                        if base:
                            daemoned.add(base)
        # map call node -> assignment target terminal
        assigned: dict = {}
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                base = terminal(node.targets[0])
                if base:
                    assigned[id(node.value)] = base
        for call in info.thread_spawns:
            if _kwarg(call, "daemon") is not None:
                continue
            base = assigned.get(id(call))
            if base is not None:
                if base in daemoned:
                    continue
                if base in joined.get(info.module, set()):
                    continue
            out.append(Finding(
                info.path, call.lineno, "daemon-or-joined",
                "Thread is neither daemon=True nor joined anywhere in its "
                "module — it can outlive stop() and hang teardown"))
    return out


# ---------------------------------------------------------------------------
# Rule: metrics-discipline
# ---------------------------------------------------------------------------


@rule("metrics-discipline",
      "labeled counters/gauges pre-seeded or removal-disciplined")
def check_metrics_discipline(project: Project) -> list[Finding]:
    out = []
    # Seeds/removals are collected project-wide: a metric created in
    # utils/metrics.py may be removal-disciplined by the node sampler
    # (Gauge.remove on peer departure) in node/node.py.
    seeded: set = set()
    removed: set = set()
    for sf in project.prod_files():
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            metric = terminal(node.func.value)
            if metric is None:
                continue
            if node.func.attr in ("add", "set") and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and a0.value in (0, 0.0):
                    seeded.add(metric)
            elif node.func.attr == "remove":
                removed.add(metric)
    for sf in project.prod_files():
        # creations: self.NAME = r.counter/gauge(..., labels=(...))
        created = []  # (attrname, kind, line)
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            t = terminal(node.value.func)
            if t not in ("counter", "gauge"):
                continue
            labels = _kwarg(node.value, "labels")
            if labels is None and len(node.value.args) >= 4:
                labels = node.value.args[3]
            if labels is None:
                continue
            if (isinstance(labels, (ast.Tuple, ast.List))
                    and not labels.elts):
                continue
            tgt = dotted(node.targets[0]) if node.targets else None
            if not tgt:
                continue
            created.append((tgt.split(".")[-1], t, node.value.lineno))
        for name, kind, line in created:
            if name in seeded or name in removed:
                continue
            out.append(Finding(
                sf.path, line, "metrics-discipline",
                f"labeled {kind} '{name}' is never pre-seeded (add/set 0) "
                f"nor removal-disciplined — absent series break dashboards, "
                f"unbounded label values leak exposition lines"))
    return out


# ---------------------------------------------------------------------------
# Rule: fault-site-registry
# ---------------------------------------------------------------------------

_FAULTS_FILE = "tendermint_tpu/utils/faults.py"
_FAULTS_DOC = "docs/FAULTS.md"
_FIRE_FAMILY = {"fire", "maybe_drop", "link_outcome", "torn_write",
                "crash_point", "fail_point", "check", "mutate_value"}
_SITE_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")


def _canonical_sites(project: Project) -> dict[str, int]:
    """site -> declaration line, parsed from the CANONICAL_SITES dict
    literal (no project import: the linter stays jax-free)."""
    sf = project.file(_FAULTS_FILE)
    sites: dict[str, int] = {}
    if sf is None or sf.tree is None:
        text = project.read_side_file(_FAULTS_FILE)
        if text is None:
            return sites
        try:
            sf_tree = ast.parse(text)
        except SyntaxError:
            # unparsable faults.py: degrade to the rule's own
            # "not found/parsable" finding (plus parse-error) instead of
            # aborting the whole lint run with a traceback
            return sites
    else:
        sf_tree = sf.tree
    for node in ast.walk(sf_tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if (targets
                and any(isinstance(t, ast.Name) and t.id == "CANONICAL_SITES"
                        for t in targets)
                and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    sites[k.value] = k.lineno
    return sites


@rule("fault-site-registry",
      "faults.fire(...) site literals must be canonical and documented")
def check_fault_sites(project: Project) -> list[Finding]:
    sites = _canonical_sites(project)
    out = []
    if not sites:
        return [Finding(_FAULTS_FILE, 1, "fault-site-registry",
                        "CANONICAL_SITES dict not found/parsable")]
    namespaces = {s.split(".")[0] for s in sites}
    for sf in project.prod_files():
        if sf.path == _FAULTS_FILE:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and terminal(node.func) in _FIRE_FAMILY
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            lit = node.args[0].value
            if not _SITE_RE.match(lit):
                continue
            if lit not in sites:
                out.append(Finding(
                    sf.path, node.lineno, "fault-site-registry",
                    f"fault site '{lit}' is not declared in "
                    f"utils/faults.py CANONICAL_SITES"))
    # docs cross-check
    doc = project.read_side_file(_FAULTS_DOC)
    if doc is None:
        out.append(Finding(_FAULTS_DOC, 1, "fault-site-registry",
                           "docs/FAULTS.md missing"))
        return out
    for site in sorted(sites):
        # abbreviated table rows (`a.b.{x} … y / z`) count via last segment
        if site not in doc and site.split(".")[-1] not in doc:
            out.append(Finding(
                _FAULTS_FILE, sites[site], "fault-site-registry",
                f"canonical site '{site}' is not documented in "
                f"docs/FAULTS.md"))
    for i, line in enumerate(doc.splitlines(), start=1):
        for tok in re.findall(r"`([^`]+)`", line):
            if (_SITE_RE.match(tok) and tok not in sites
                    and tok.split(".")[0] in namespaces):
                out.append(Finding(
                    _FAULTS_DOC, i, "fault-site-registry",
                    f"docs/FAULTS.md names site '{tok}' which is not in "
                    f"CANONICAL_SITES (stale or undeclared)"))
    return out


# ---------------------------------------------------------------------------
# Rule: trace-span-discipline
# ---------------------------------------------------------------------------

_TRACE_FILE = "tendermint_tpu/utils/trace.py"
_TRACE_DOC = "docs/OBSERVABILITY.md"
# The flight-recorder recording surface (utils/trace.py): dotted-name
# string literals passed to these terminals are span names. Non-dotted
# first args (peerscore offences, dict keys) never match _SITE_RE, so the
# family can stay broad without false positives.
_SPAN_FAMILY = {"span", "mark"}
_SPAN_RECORD = "record"


def _canonical_spans(project: Project) -> dict[str, int]:
    """span name -> declaration line, parsed from the CANONICAL_SPANS dict
    literal (no project import: the linter stays jax-free) — the exact
    pattern of fault-site-registry's CANONICAL_SITES."""
    sf = project.file(_TRACE_FILE)
    spans: dict[str, int] = {}
    if sf is None or sf.tree is None:
        text = project.read_side_file(_TRACE_FILE)
        if text is None:
            return spans
        try:
            tree = ast.parse(text)
        except SyntaxError:
            return spans
    else:
        tree = sf.tree
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if (targets
                and any(isinstance(t, ast.Name) and t.id == "CANONICAL_SPANS"
                        for t in targets)
                and isinstance(node.value, ast.Dict)):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    spans[k.value] = k.lineno
    return spans


@rule("trace-span-discipline",
      "trace.span/mark/record name literals must be canonical + documented")
def check_trace_spans(project: Project) -> list[Finding]:
    spans = _canonical_spans(project)
    out = []
    if not spans:
        return [Finding(_TRACE_FILE, 1, "trace-span-discipline",
                        "CANONICAL_SPANS dict not found/parsable")]
    namespaces = {s.split(".")[0] for s in spans}
    for sf in project.prod_files():
        if sf.path == _TRACE_FILE:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            t = terminal(node.func)
            if t not in _SPAN_FAMILY and t != _SPAN_RECORD:
                continue
            lit = node.args[0].value
            if not _SITE_RE.match(lit):
                continue
            if t == _SPAN_RECORD and not isinstance(node.func, ast.Attribute):
                continue  # a bare record() is some other module's function
            if lit not in spans:
                out.append(Finding(
                    sf.path, node.lineno, "trace-span-discipline",
                    f"trace span '{lit}' is not declared in "
                    f"utils/trace.py CANONICAL_SPANS — ad-hoc span names "
                    f"drift from docs/OBSERVABILITY.md"))
    doc = project.read_side_file(_TRACE_DOC)
    if doc is None:
        out.append(Finding(_TRACE_DOC, 1, "trace-span-discipline",
                           "docs/OBSERVABILITY.md missing"))
        return out
    for span_name in sorted(spans):
        if span_name not in doc:
            out.append(Finding(
                _TRACE_FILE, spans[span_name], "trace-span-discipline",
                f"canonical span '{span_name}' is not documented in "
                f"docs/OBSERVABILITY.md"))
    for i, line in enumerate(doc.splitlines(), start=1):
        for tok in re.findall(r"`([^`]+)`", line):
            if (_SITE_RE.match(tok) and tok not in spans
                    and tok.split(".")[0] in namespaces
                    and "." in tok):
                out.append(Finding(
                    _TRACE_DOC, i, "trace-span-discipline",
                    f"docs/OBSERVABILITY.md names span '{tok}' which is "
                    f"not in CANONICAL_SPANS (stale or undeclared)"))
    return out


# ---------------------------------------------------------------------------
# Rule: config-knob-parity
# ---------------------------------------------------------------------------

_CONFIG_DOC = "docs/CONFIG.md"
_KNOB_RE = re.compile(r"\bTM_TPU_[A-Z0-9][A-Z0-9_]*\b|\bTMTPU_[A-Z0-9][A-Z0-9_]*\b")


def _knob_tokens_in_tree(tree) -> dict[str, int]:
    toks: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for tok in _KNOB_RE.findall(node.value):
                toks.setdefault(tok, node.lineno)
    return toks


def _scan_covers_default_scope(project: Project) -> bool:
    """True when every DEFAULT_PATHS entry that exists on disk is in the
    scanned set. The doc->code ("stale doc") direction is only sound
    then: a subset scan (`tmlint tendermint_tpu tests`) simply cannot see
    a knob read only in bench.py and must not call its doc entry stale."""
    from tools.tmlint.core import DEFAULT_PATHS

    for p in DEFAULT_PATHS:
        if not os.path.exists(os.path.join(project.root, p)):
            continue
        covered = any(sf.path == p or sf.path.startswith(p + "/")
                      for sf in project.files)
        if not covered:
            return False
    return True


@rule("config-knob-parity",
      "every TM_TPU_*/TMTPU_* env knob in code <-> docs/CONFIG.md")
def check_knob_parity(project: Project) -> list[Finding]:
    code: dict[str, tuple[str, int]] = {}
    for sf in project.files:
        if sf.tree is None or sf.path.startswith("tools/tmlint/"):
            continue
        for tok, line in sorted(_knob_tokens_in_tree(sf.tree).items()):
            code.setdefault(tok, (sf.path, line))
    doc = project.read_side_file(_CONFIG_DOC)
    if doc is None:
        return [Finding(_CONFIG_DOC, 1, "config-knob-parity",
                        "docs/CONFIG.md missing")]
    doc_toks: dict[str, int] = {}
    for i, line in enumerate(doc.splitlines(), start=1):
        for tok in _KNOB_RE.findall(line):
            doc_toks.setdefault(tok, i)
    out = []
    for tok in sorted(set(code) - set(doc_toks)):
        path, line = code[tok]
        out.append(Finding(
            path, line, "config-knob-parity",
            f"env knob {tok} is used in code but undocumented in "
            f"docs/CONFIG.md"))
    if _scan_covers_default_scope(project):
        for tok in sorted(set(doc_toks) - set(code)):
            out.append(Finding(
                _CONFIG_DOC, doc_toks[tok], "config-knob-parity",
                f"docs/CONFIG.md documents {tok} but nothing in the tree "
                f"reads it (stale doc)"))
    return out
