"""tmlint core: file model, rule registry, pragmas, baseline, driver.

Everything here is deliberately boring: parse each file once with `ast`,
hand the whole-project view to every registered rule, subtract pragma'd
and baselined findings, emit `path:line RULE message` sorted. Rules are
pure functions of the Project, so two runs over the same tree produce
byte-identical output (tests/test_lint.py pins that).
"""

from __future__ import annotations

import ast
import io
import os
import re
import subprocess
import tokenize
from dataclasses import dataclass

# Directories never scanned (caches, VCS innards).
_SKIP_DIRS = {"__pycache__", ".git", ".claude", ".pytest_cache"}

# The ONE default scan set (CLI, __graft_entry__.lint_gate, the tier-1
# gate in tests/test_lint.py all import this — hand-copied lists drift).
DEFAULT_PATHS = ["tendermint_tpu", "tools", "tests",
                 "bench.py", "__graft_entry__.py"]

# Paths (relative, '/'-separated) treated as *production* code: the
# concurrency/device rules apply here. Tests may spawn bare threads and
# poke device arrays on purpose; the registry/parity rules still scan them.
_PROD_PREFIX = "tendermint_tpu/"

_PRAGMA_RE = re.compile(
    r"#\s*tmlint:\s*(?P<kind>disable-file|disable)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True, order=True)
class Finding:
    path: str      # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line numbers drift, (rule, path, message)
        pins the finding."""
        return (self.rule, self.path, self.message)


class SourceFile:
    """One parsed file: AST + raw lines + its tmlint pragmas."""

    def __init__(self, root: str, relpath: str):
        self.path = relpath.replace(os.sep, "/")
        with open(os.path.join(root, relpath), "r", encoding="utf-8",
                  errors="replace") as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(self.text, filename=self.path)
        except SyntaxError as e:  # surfaced as its own finding by run_rules
            self.parse_error = e
        # pragma maps: line -> set of rule names (or {"*"}), plus file-wide.
        # Only real COMMENT tokens count — a pragma-shaped string literal
        # (a lint test fixture, a doc snippet) must never register a live
        # suppression.
        self._line_pragmas: dict[int, set[str]] = {}
        self._file_pragmas: set[str] = set()
        if "tmlint:" not in self.text:
            return  # cheap pre-filter: tokenizing ~200 pragma-free files
            # would double the scan time for nothing
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, SyntaxError, ValueError,
                IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            if m.group("kind") == "disable-file":
                self._file_pragmas |= rules
            else:
                self._line_pragmas[tok.start[0]] = rules

    def suppressed(self, line: int, rule: str) -> bool:
        """A pragma suppresses findings on its own line or the line below
        (so it can sit above a long statement)."""
        if rule in self._file_pragmas or "*" in self._file_pragmas:
            return True
        for at in (line, line - 1):
            rules = self._line_pragmas.get(at)
            if rules and (rule in rules or "*" in rules):
                return True
        return False


class Project:
    """The whole scanned tree, plus the repo root for side files
    (docs/CONFIG.md, docs/FAULTS.md) rules cross-check against."""

    def __init__(self, root: str, files: list[SourceFile]):
        self.root = root
        self.files = sorted(files, key=lambda f: f.path)
        self._by_path = {f.path: f for f in self.files}

    def file(self, path: str) -> SourceFile | None:
        return self._by_path.get(path)

    def prod_files(self) -> list[SourceFile]:
        return [f for f in self.files
                if f.path.startswith(_PROD_PREFIX) and f.tree is not None]

    def read_side_file(self, relpath: str) -> str | None:
        try:
            with open(os.path.join(self.root, relpath), "r",
                      encoding="utf-8", errors="replace") as fh:
                return fh.read()
        except OSError:
            return None


def collect_files(root: str, paths: list[str]) -> list[SourceFile]:
    out: list[SourceFile] = []
    seen: set[str] = set()
    for p in paths:
        abspath = os.path.join(root, p)
        if os.path.isfile(abspath):
            if p.endswith(".py") and p not in seen:
                seen.add(p)
                out.append(SourceFile(root, p))
            continue
        for dirpath, dirnames, filenames in os.walk(abspath):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if rel not in seen:
                    seen.add(rel)
                    out.append(SourceFile(root, rel))
    return out


# --- rule registry ----------------------------------------------------------

# name -> (fn(project) -> list[Finding], one-line doc)
RULES: dict[str, tuple] = {}


def rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = (fn, doc)
        return fn
    return deco


def run_rules(project: Project, rules: list[str] | None = None) -> list[Finding]:
    """All findings, pragma-filtered, deduped, sorted. Parse failures are
    findings too (rule ``parse-error``): a file the analyzer cannot see is
    a hole in every invariant."""
    selected = sorted(rules) if rules else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)} "
                         f"(have: {', '.join(sorted(RULES))})")
    findings: list[Finding] = []
    for f in project.files:
        if f.parse_error is not None:
            findings.append(Finding(
                f.path, f.parse_error.lineno or 1, "parse-error",
                f"file does not parse: {f.parse_error.msg}"))
    for name in selected:
        findings.extend(RULES[name][0](project))
    out = []
    for fd in findings:
        sf = project.file(fd.path)
        if sf is not None and sf.suppressed(fd.line, fd.rule):
            continue
        out.append(fd)
    return sorted(set(out))


# --- baseline ---------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.txt")


def load_baseline(path: str | None = None) -> set[tuple[str, str, str]]:
    """Baseline grammar: one finding per line, TAB-separated
    ``rule<TAB>path<TAB>message`` (no line numbers — they drift). Blank
    lines and ``#`` comments ignored."""
    entries: set[tuple[str, str, str]] = set()
    path = path or BASELINE_PATH
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if not line.strip() or line.lstrip().startswith("#"):
                    continue
                parts = line.split("\t", 2)
                if len(parts) == 3:
                    entries.add((parts[0], parts[1], parts[2]))
    except OSError:
        pass
    return entries


def write_baseline(findings: list[Finding], path: str | None = None) -> None:
    path = path or BASELINE_PATH
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# tmlint baseline: grandfathered findings "
                 "(rule<TAB>path<TAB>message). Keep ~empty.\n")
        for fd in sorted(set(findings)):
            fh.write(f"{fd.rule}\t{fd.path}\t{fd.message}\n")


def split_baselined(findings: list[Finding],
                    baseline: set[tuple[str, str, str]]):
    new, old = [], []
    for fd in findings:
        (old if fd.key() in baseline else new).append(fd)
    return new, old


# --- git scoping (--changed) ------------------------------------------------

def changed_paths(root: str) -> set[str]:
    """Repo-relative paths touched in the working tree (staged, unstaged,
    untracked) — the fast pre-commit scope."""
    try:
        proc = subprocess.run(
            ["git", "-C", root, "status", "--porcelain", "-uall"],
            capture_output=True, text=True, timeout=30, check=True)
    except (OSError, subprocess.SubprocessError):
        return set()
    out: set[str] = set()
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: take the new side
            path = path.split(" -> ", 1)[1]
        out.add(path.strip().strip('"'))
    return out
