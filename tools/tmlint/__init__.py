"""tmlint — project-invariant static analysis for tendermint-tpu.

A stdlib-`ast` analyzer encoding the invariants this repo enforces by hand
in review (docs/LINT.md has the rule table and the rationale trail):

* no blocking or callback-invoking calls under a held lock,
* a cross-module lock-acquisition graph free of order cycles,
* `jax.device_get`-class syncs only at the audited choke points,
* every spawned thread crash-shielded and daemonized-or-joined,
* labeled metrics pre-seeded, fault-site literals canonical + documented,
* `TM_TPU_*`/`TMTPU_*` env knobs in parity with docs/CONFIG.md.

Usage::

    python -m tools.tmlint                  # whole tree, default rule set
    python -m tools.tmlint --changed        # git-diff-scoped (pre-commit)
    python -m tools.tmlint --rule lock-order tendermint_tpu

Pure AST + text: no project imports, no jax, runs in seconds. Pragmas
(`# tmlint: disable=RULE`) silence one line; `tools/tmlint/baseline.txt`
grandfathers accepted findings (kept ~empty — fix, don't grandfather).
"""

from tools.tmlint.core import (  # noqa: F401
    Finding,
    Project,
    load_baseline,
    run_rules,
)
from tools.tmlint import checks  # noqa: F401  (registers the rule set)
