"""BlockExecutor: validates blocks, drives the ABCI app, applies validator
updates (reference: state/execution.go:94,117,131,211,259,403).

This module also owns the batched execution plane (docs/EXECUTION.md):
`deliver_block_txs` is the ONE deliver engine every DeliverTx loop in the
tree goes through (block apply, handshake replay, bench, entry gates), so
the batched and serial paths cannot drift; `PostCommitWorker` moves event
publish off the apply critical path; `dispatch_commit_verify` is the
commit→apply overlap seam that lets a block's LastCommit verification ride
the device while host-side work (store save, WAL fsync) proceeds.
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, replace

from tendermint_tpu.abci import types as abci
from tendermint_tpu.crypto import keys as crypto_keys
from tendermint_tpu.state.state import State
from tendermint_tpu.state.store import ABCIResponses, StateStore
from tendermint_tpu.state.validation import validate_block
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.utils import faults
from tendermint_tpu.utils import trace as _trace


class BlockExecutionError(Exception):
    pass


# --- the batched deliver engine (docs/EXECUTION.md) -------------------------


def deliver_enabled() -> bool:
    """`TMTPU_DELIVER=0` restores the serial per-tx DeliverTx loop. Read
    per call so tests and the chain_throughput bench flip it live."""
    return os.environ.get("TMTPU_DELIVER") != "0"


def deliver_max_batch(default: int = 1024) -> int:
    """Tx cap per batched DeliverTx round trip (`TMTPU_DELIVER_MAX_BATCH`):
    bounds one wire message's size and the app's worst-case batched call."""
    try:
        v = int(os.environ.get("TMTPU_DELIVER_MAX_BATCH", default))
    except ValueError:
        return default
    return max(1, v)


def deliver_block_txs(app, txs) -> list[abci.ResponseDeliverTx]:
    """Execute a block's txs against the app: one ABCI round trip per
    `deliver_max_batch()`-sized chunk (wire extension fields 21/22), with
    per-tx responses order-aligned and bit-identical to the serial loop's.

    Degradation to the serial loop happens ONLY when provably no app code
    ran for the chunk: the `abci.deliver_batch` fault site fires BEFORE
    dispatch, apps without the batch method never get called, and the
    transports fall back only on structural probe / UNIMPLEMENTED
    evidence. A genuine app or transport error during a real batch
    PROPAGATES — the chunk's prefix has already mutated app state, which
    is exactly the serial loop's failure shape, and a silent redo would
    double-apply it.
    """
    txs = list(txs)
    if not txs:
        return []
    batch_fn = getattr(app, "deliver_tx_batch", None)
    if batch_fn is None or not deliver_enabled():
        return [app.deliver_tx(abci.RequestDeliverTx(tx=tx)) for tx in txs]
    out: list[abci.ResponseDeliverTx] = []
    cap = deliver_max_batch()
    with _trace.current().span("abci.deliver_txs", n=len(txs)):
        for start in range(0, len(txs), cap):
            chunk = txs[start:start + cap]
            try:
                faults.fire("abci.deliver_batch")
            except Exception:  # noqa: BLE001 - injected pre-dispatch: no
                # app code has run for this chunk, so the serial loop is
                # safe (cannot double-apply)
                out.extend(app.deliver_tx(abci.RequestDeliverTx(tx=tx))
                           for tx in chunk)
                continue
            with _trace.current().span("abci.deliver_batch", n=len(chunk)):
                rs = batch_fn(abci.RequestDeliverTxBatch(txs=chunk)).responses
            if len(rs) != len(chunk):
                raise BlockExecutionError(
                    f"batched DeliverTx returned {len(rs)} responses "
                    f"for {len(chunk)} txs")
            _observe_deliver_batch(len(chunk))
            out.extend(rs)
    return out


def _observe_deliver_batch(n: int) -> None:
    from tendermint_tpu.utils import metrics as tmmetrics

    m = tmmetrics.GLOBAL_NODE_METRICS
    if m is None:
        return
    try:
        m.deliver_batch_size.observe(float(n))
    except Exception:  # noqa: BLE001 - observability never fails the apply
        pass


def _observe_invalid_txs(n: int) -> None:
    from tendermint_tpu.utils import metrics as tmmetrics

    m = tmmetrics.GLOBAL_NODE_METRICS
    if m is None or n == 0:
        return
    try:
        m.abci_deliver_tx_invalid_total.add(float(n))
    except Exception:  # noqa: BLE001 - observability never fails the apply
        pass


# --- post-commit worker (docs/EXECUTION.md) ---------------------------------


class PostCommitWorker:
    """Single FIFO daemon thread for post-commit work (event publish →
    tx index, RPC subscribers) so `apply_block` returns as soon as state
    is durably saved. One queue, one thread: work for height h runs
    before work for h+1, the ordering subscribers rely on. Crash-shielded:
    a failing task is dropped and later heights still publish."""

    _STOP = object()

    def __init__(self, logger=None):
        self._logger = logger
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread: threading.Thread | None = None
        self._mtx = threading.Lock()

    def submit(self, fn) -> None:
        with self._mtx:
            t = self._thread
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._run, name="post-commit",
                                     daemon=True)
                self._thread = t
                t.start()
        self._q.put(fn)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until everything submitted so far has run (tests,
        Node.stop). Returns False on timeout."""
        with self._mtx:
            t = self._thread
        if t is None or not t.is_alive():
            return True
        done = threading.Event()
        self._q.put(done.set)
        return done.wait(timeout_s)

    def stop(self, timeout_s: float = 5.0) -> None:
        with self._mtx:
            t = self._thread
            self._thread = None
        if t is None or not t.is_alive():
            return
        self._q.put(self._STOP)
        t.join(timeout_s)

    def _run(self) -> None:
        try:
            while True:
                fn = self._q.get()
                if fn is PostCommitWorker._STOP:
                    return
                try:
                    fn()
                except Exception:  # noqa: BLE001 - post-commit work must
                    # never kill the worker; later heights still publish
                    if self._logger is not None:
                        try:
                            self._logger.error("post-commit task failed")
                        except Exception:  # noqa: BLE001
                            pass
        except Exception:  # noqa: BLE001 - crash shield (docs/LINT.md)
            pass


# --- commit→apply overlap seam (docs/EXECUTION.md) --------------------------


@dataclass
class SpeculativeCommitVerify:
    """A block's LastCommit verification dispatched on-device ahead of the
    apply, plus the dispatch-time inputs that make it safe to consume:
    the handle is used only if height / last_block_id / validator-set
    hash still match at resolve time, otherwise it is silently discarded
    and the apply falls back to the synchronous verify (the PIPELINE.md
    stale-input discipline)."""

    pending: object  # types.validator_set.PendingCommitVerify
    height: int
    last_block_id: BlockID
    vals_hash: bytes

    def fresh_for(self, state: State, block: Block):
        """The inner pending handle iff dispatch-time inputs still hold."""
        if (self.height == block.header.height
                and self.last_block_id == state.last_block_id
                and self.vals_hash == state.last_validators.hash()):
            return self.pending
        return None


def validator_updates_from_abci(updates: list[abci.ValidatorUpdate]) -> list[Validator]:
    """reference: types/protobuf.go PB2TM.ValidatorUpdates."""
    out = []
    for vu in updates:
        pub = crypto_keys.pubkey_from_type_bytes(vu.pub_key_type, vu.pub_key_bytes)
        out.append(Validator.new(pub, vu.power))
    return out


def validate_validator_updates(updates: list[abci.ValidatorUpdate],
                               params: ConsensusParams) -> None:
    """reference: state/execution.go:379-401."""
    for vu in updates:
        if vu.power < 0:
            raise BlockExecutionError(f"voting power can't be negative {vu}")
        if vu.power == 0:
            continue
        if vu.pub_key_type not in params.validator.pub_key_types:
            raise BlockExecutionError(
                f"validator {vu} is using pubkey {vu.pub_key_type}, which is unsupported for consensus"
            )


class BlockExecutor:
    """reference: state/execution.go:34-92."""

    def __init__(self, state_store: StateStore, app, mempool=None, evidence_pool=None,
                 event_bus=None, block_store=None, logger=None, metrics=None):
        self.store = state_store
        self.app = app  # proxy.AppConnConsensus-like (direct Application ok)
        self.mempool = mempool
        self.evidence_pool = evidence_pool
        self.event_bus = event_bus
        self.block_store = block_store
        self.logger = logger
        self.metrics = metrics
        # lazy: no thread until the first post-commit submission
        self._post_commit = PostCommitWorker(logger)

    # --- proposal creation (reference: state/execution.go:94-129) ----------

    def create_proposal_block(self, height: int, state: State, last_commit,
                              proposer_address: bytes,
                              block_time: Time | None = None) -> Block:
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence = []
        ev_size = 0
        if self.evidence_pool is not None:
            evidence, ev_size = self.evidence_pool.pending_evidence(
                state.consensus_params.evidence.max_bytes
            )
        max_data = max_data_bytes(max_bytes, ev_size, state.validators.size())
        txs = self.mempool.reap_max_bytes_max_gas(max_data, max_gas) if self.mempool else []
        return state.make_block(height, txs, last_commit, evidence, proposer_address,
                                block_time)

    def validate_block(self, state: State, block: Block,
                       commit_pending: SpeculativeCommitVerify | None = None) -> None:
        inner = commit_pending.fresh_for(state, block) if commit_pending else None
        validate_block(state, block, self.block_store, commit_pending=inner)
        if self.evidence_pool is not None:
            self.evidence_pool.check_evidence(state, block.evidence)

    def dispatch_commit_verify(self, state: State,
                               block: Block) -> SpeculativeCommitVerify | None:
        """Dispatch `block.last_commit`'s verification on-device NOW and
        return a stale-guarded handle that `validate_block`/`apply_block`
        resolve later — the commit→apply overlap seam: the device round
        trip rides under host-side work (structural checks, store save,
        WAL fsync) instead of serializing with it. `resolve()` replays the
        exact serial accept/reject decision and is idempotent, so passing
        one handle through both the pre-save validate and the apply costs
        one verification total. Returns None when there is nothing to
        verify (the initial block)."""
        if block.header.height == state.initial_height:
            return None
        pending = state.last_validators.verify_commit_async(
            state.chain_id, state.last_block_id,
            block.header.height - 1, block.last_commit)
        return SpeculativeCommitVerify(
            pending=pending, height=block.header.height,
            last_block_id=state.last_block_id,
            vals_hash=state.last_validators.hash())

    def flush_post_commit(self, timeout_s: float = 10.0) -> bool:
        """Wait for all queued post-commit work (event publish) to run."""
        return self._post_commit.flush(timeout_s)

    def stop(self) -> None:
        self._post_commit.stop()

    # --- applying a decided block (reference: state/execution.go:131-209) --

    def apply_block(self, state: State, block_id: BlockID, block: Block,
                    commit_pending: SpeculativeCommitVerify | None = None,
                    ) -> tuple[State, int]:
        import time as _t

        from tendermint_tpu.utils import metrics as tmmetrics

        _started = _t.monotonic()
        self.validate_block(state, block, commit_pending=commit_pending)

        abci_responses = self._exec_block_on_app(state, block)
        self.store.save_abci_responses(block.header.height, abci_responses)

        end = abci_responses.end_block
        validate_validator_updates(end.validator_updates, state.consensus_params)
        validator_updates = validator_updates_from_abci(end.validator_updates)

        new_state = update_state(state, block_id, block, abci_responses, validator_updates)

        # Lock mempool, commit app state, update mempool (reference:
        # state/execution.go:211-257).
        app_hash, retain_height = self._commit(new_state, block, abci_responses)
        if self.evidence_pool is not None:
            self.evidence_pool.update(new_state, block.evidence)

        new_state = replace(new_state, app_hash=app_hash)
        self.store.save(new_state)

        # Post-commit work is off the critical path: apply_block returns
        # as soon as state is durably saved; the single FIFO worker keeps
        # height h's events ahead of h+1's for every subscriber.
        if self.event_bus is not None:
            self._post_commit.submit(
                lambda: self._fire_events(block, block_id, abci_responses,
                                          validator_updates))
        if tmmetrics.GLOBAL_NODE_METRICS is not None:
            tmmetrics.GLOBAL_NODE_METRICS.block_processing_time.observe(
                _t.monotonic() - _started)
        return new_state, retain_height

    def _exec_block_on_app(self, state: State, block: Block) -> ABCIResponses:
        """BeginBlock / DeliverTx* / EndBlock (reference:
        state/execution.go:259-377)."""
        commit_info = get_begin_block_validator_info(block, self.store, state.initial_height)
        byz_vals = []
        for ev in block.evidence:
            byz_vals.extend(abci_evidence(ev, state))

        begin_res = self.app.begin_block(abci.RequestBeginBlock(
            hash=block.hash() or b"",
            header=block.header,
            last_commit_info=commit_info,
            byzantine_validators=byz_vals,
        ))
        deliver_txs = deliver_block_txs(self.app, block.data.txs)
        _observe_invalid_txs(sum(1 for r in deliver_txs if not r.is_ok()))
        end_res = self.app.end_block(abci.RequestEndBlock(height=block.header.height))
        return ABCIResponses(deliver_txs=deliver_txs, end_block=end_res, begin_block=begin_res)

    def _commit(self, state: State, block: Block, abci_responses: ABCIResponses):
        """reference: state/execution.go:211-257: flush mempool, app Commit,
        mempool Update (with admission filters rebuilt from the new state)."""
        if self.mempool is not None:
            self.mempool.lock()
        try:
            res = self.app.commit()
            if self.mempool is not None:
                from tendermint_tpu.state.tx_filter import (
                    tx_post_check,
                    tx_pre_check,
                )

                self.mempool.update(
                    block.header.height, block.data.txs, abci_responses.deliver_txs,
                    pre_check=tx_pre_check(state),
                    post_check=tx_post_check(state),
                )
        finally:
            if self.mempool is not None:
                self.mempool.unlock()
        return res.data, res.retain_height

    def _fire_events(self, block: Block, block_id: BlockID,
                     abci_responses: ABCIResponses, validator_updates) -> None:
        """reference: state/execution.go:471-552."""
        if self.event_bus is None:
            return
        from tendermint_tpu.types import events

        with _trace.current().span("apply.post_commit",
                                   height=block.header.height):
            self._publish_events(block, block_id, abci_responses,
                                 validator_updates, events)

    def _publish_events(self, block, block_id, abci_responses,
                        validator_updates, events) -> None:
        self.event_bus.publish_event_new_block(
            events.EventDataNewBlock(block=block, block_id=block_id,
                                     result_begin_block=abci_responses.begin_block,
                                     result_end_block=abci_responses.end_block))
        self.event_bus.publish_event_new_block_header(
            events.EventDataNewBlockHeader(header=block.header,
                                           num_txs=len(block.data.txs),
                                           result_begin_block=abci_responses.begin_block,
                                           result_end_block=abci_responses.end_block))
        for ev in block.evidence:
            self.event_bus.publish_event_new_evidence(
                events.EventDataNewEvidence(evidence=ev, height=block.header.height))
        for i, tx in enumerate(block.data.txs):
            self.event_bus.publish_event_tx(events.EventDataTx(
                height=block.header.height, tx=tx, index=i,
                result=abci_responses.deliver_txs[i]))
        if validator_updates:
            self.event_bus.publish_event_validator_set_updates(
                events.EventDataValidatorSetUpdates(validator_updates=validator_updates))


def update_state(state: State, block_id: BlockID, block: Block,
                 abci_responses: ABCIResponses, validator_updates) -> State:
    """reference: state/execution.go:403-469."""
    n_val_set = state.next_validators.copy()
    last_height_vals_changed = state.last_height_validators_changed
    if validator_updates:
        n_val_set.update_with_change_set(validator_updates)
        last_height_vals_changed = block.header.height + 1 + 1

    n_val_set.increment_proposer_priority(1)

    next_params = state.consensus_params
    last_height_params_changed = state.last_height_consensus_params_changed
    if abci_responses.end_block is not None and abci_responses.end_block.consensus_param_updates is not None:
        next_params = abci_responses.end_block.consensus_param_updates
        next_params.validate_basic()
        last_height_params_changed = block.header.height + 1

    from tendermint_tpu.abci.types import results_hash

    return State(
        version=state.version,
        chain_id=state.chain_id,
        initial_height=state.initial_height,
        last_block_height=block.header.height,
        last_block_id=block_id,
        last_block_time=block.header.time,
        next_validators=n_val_set,
        validators=state.next_validators.copy(),
        last_validators=state.validators.copy(),
        last_height_validators_changed=last_height_vals_changed,
        consensus_params=next_params,
        last_height_consensus_params_changed=last_height_params_changed,
        last_results_hash=results_hash(abci_responses.deliver_txs),
        app_hash=b"",  # set after Commit
    )


def get_begin_block_validator_info(block: Block, store: StateStore,
                                   initial_height: int) -> abci.LastCommitInfo:
    """reference: state/execution.go:307-352."""
    vote_infos = []
    if block.header.height > initial_height:
        last_val_set = store.load_validators(block.header.height - 1)
        commit_size = block.last_commit.size()
        vals_size = last_val_set.size()
        if commit_size != vals_size:
            raise BlockExecutionError(
                f"commit size ({commit_size}) doesn't match valset length ({vals_size}) "
                f"at height {block.header.height}"
            )
        for i, val in enumerate(last_val_set.validators):
            cs = block.last_commit.signatures[i]
            vote_infos.append(abci.VoteInfo(
                validator=abci.ABCIValidator(address=val.address, power=val.voting_power),
                signed_last_block=not cs.absent(),
            ))
    round_ = block.last_commit.round if block.last_commit else 0
    return abci.LastCommitInfo(round=round_, votes=vote_infos)


def abci_evidence(ev, state: State) -> list[abci.ABCIEvidence]:
    """types.Evidence.ABCI() equivalents (reference: types/evidence.go:76,203)."""
    from tendermint_tpu.types.evidence import DuplicateVoteEvidence, LightClientAttackEvidence

    if isinstance(ev, DuplicateVoteEvidence):
        return [abci.ABCIEvidence(
            type=abci.EVIDENCE_TYPE_DUPLICATE_VOTE,
            validator=abci.ABCIValidator(address=ev.vote_a.validator_address,
                                         power=ev.validator_power),
            height=ev.vote_a.height,
            time_seconds=ev.timestamp.seconds,
            time_nanos=ev.timestamp.nanos,
            total_voting_power=ev.total_voting_power,
        )]
    if isinstance(ev, LightClientAttackEvidence):
        out = []
        for v in ev.byzantine_validators:
            out.append(abci.ABCIEvidence(
                type=abci.EVIDENCE_TYPE_LIGHT_CLIENT_ATTACK,
                validator=abci.ABCIValidator(address=v.address, power=v.voting_power),
                height=ev.height(),
                time_seconds=ev.timestamp.seconds,
                time_nanos=ev.timestamp.nanos,
                total_voting_power=ev.total_voting_power,
            ))
        return out
    return []


def max_data_bytes(max_bytes: int, evidence_bytes: int, num_vals: int) -> int:
    """reference: types/block.go MaxDataBytes."""
    MAX_OVERHEAD_FOR_BLOCK = 11
    MAX_HEADER_BYTES = 626
    MAX_COMMIT_OVERHEAD = 94
    MAX_COMMIT_SIG_BYTES = 109
    max_data = (max_bytes - MAX_OVERHEAD_FOR_BLOCK - MAX_HEADER_BYTES
                - MAX_COMMIT_OVERHEAD - num_vals * MAX_COMMIT_SIG_BYTES
                - evidence_bytes)
    if max_data < 0:
        raise BlockExecutionError(
            f"negative MaxDataBytes. Block.MaxBytes={max_bytes} is too small to accommodate header&lastCommit&evidence"
        )
    return max_data
