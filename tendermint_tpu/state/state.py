"""State: the chain-tip snapshot between blocks (reference: state/state.go:34,
state/state.go:300-354 MakeGenesisState)."""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tendermint_tpu.types.block import BLOCK_PROTOCOL, Consensus
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.genesis import GenesisDoc
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet

INIT_STATE_VERSION = Consensus(block=BLOCK_PROTOCOL, app=0)


@dataclass
class State:
    version: Consensus = field(default_factory=lambda: INIT_STATE_VERSION)
    chain_id: str = ""
    initial_height: int = 1

    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time: Time = field(default_factory=Time.zero)

    # validators at height+1, height, height-1 (reference: state/state.go:60-75)
    next_validators: ValidatorSet | None = None
    validators: ValidatorSet | None = None
    last_validators: ValidatorSet | None = None
    last_height_validators_changed: int = 0

    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    last_height_consensus_params_changed: int = 0

    last_results_hash: bytes = b""
    app_hash: bytes = b""

    def copy(self) -> "State":
        return replace(
            self,
            next_validators=self.next_validators.copy() if self.next_validators else None,
            validators=self.validators.copy() if self.validators else None,
            last_validators=self.last_validators.copy() if self.last_validators else None,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    def equals(self, other: "State") -> bool:
        return (
            self.chain_id == other.chain_id
            and self.last_block_height == other.last_block_height
            and self.app_hash == other.app_hash
            and self.last_block_id == other.last_block_id
        )

    def make_block(self, height: int, txs: list[bytes], last_commit, evidence,
                   proposer_address: bytes, block_time: Time | None = None):
        """reference: state/state.go:230-263 MakeBlock: block time is the
        genesis time for the initial block, else the weighted median of the
        last commit's timestamps (MedianTime)."""
        from tendermint_tpu.types.block import Block, Data, Header

        if block_time is None:
            if height == self.initial_height:
                block_time = self.last_block_time  # genesis time
            else:
                from tendermint_tpu.state.validation import median_time

                block_time = median_time(last_commit, self.last_validators)

        block = Block(
            header=Header(
                version=self.version,
                chain_id=self.chain_id,
                height=height,
                time=block_time,
                last_block_id=self.last_block_id,
                validators_hash=self.validators.hash(),
                next_validators_hash=self.next_validators.hash(),
                consensus_hash=self.consensus_params.hash(),
                app_hash=self.app_hash,
                last_results_hash=self.last_results_hash,
                proposer_address=proposer_address,
            ),
            data=Data(txs=list(txs)),
            evidence=list(evidence),
            last_commit=last_commit,
        )
        block.fill_header()
        return block


def make_genesis_state(genesis: GenesisDoc) -> State:
    """reference: state/state.go:300-354."""
    genesis.validate_and_complete()
    if genesis.validators:
        vals = [Validator.new(v.pub_key, v.power) for v in genesis.validators]
        val_set = ValidatorSet(vals)
        next_vals = val_set.copy_increment_proposer_priority(1)
    else:
        val_set = ValidatorSet()  # awaiting InitChain response
        next_vals = ValidatorSet()
    return State(
        version=Consensus(block=BLOCK_PROTOCOL, app=(genesis.consensus_params or ConsensusParams()).version.app_version),
        chain_id=genesis.chain_id,
        initial_height=genesis.initial_height,
        last_block_height=0,
        last_block_id=BlockID(),
        last_block_time=genesis.genesis_time,
        next_validators=next_vals,
        validators=val_set,
        last_validators=ValidatorSet(),
        last_height_validators_changed=genesis.initial_height,
        consensus_params=genesis.consensus_params or ConsensusParams(),
        last_height_consensus_params_changed=genesis.initial_height,
        app_hash=genesis.app_hash,
    )
