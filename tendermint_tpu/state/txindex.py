"""Tx + block indexers and the service that feeds them from the EventBus
(reference: state/txindex/kv/kv.go, state/indexer/block/kv/kv.go,
state/txindex/indexer_service.go:19).

Index layout (kv backend):
  txr/<hash>                -> JSON TxResult document (served raw over RPC)
  txe/<key>/<value>/<h>/<i> -> hash  (event postings, incl. tx.height)
  blk/<key>/<value>/<h>     -> height (block events from Begin/EndBlock)

Search is the AND of per-condition posting scans: `=` conditions hit exact
posting prefixes; range/CONTAINS/EXISTS conditions scan the key's postings
and filter values (full reference operator grammar,
libs/pubsub/query/query.go). The reference's psql sink
(state/indexer/sink/psql) is mirrored by state/sql_sink.py (write-only,
any DB-API driver; tested on sqlite3).
"""

from __future__ import annotations

import base64
import contextlib
import json
import threading

from tendermint_tpu.store import envelope
from tendermint_tpu.store.db import DB, prefix_end
from tendermint_tpu.utils import faults
from tendermint_tpu.types import events as tmevents
from tendermint_tpu.types.tx import tx_hash


def _esc(s: str) -> str:
    return s.replace("/", "%2F")


LOAD_SITE = "store.txindex.load"


def _checked(db, key: bytes, raw: bytes | None, fn, on_corruption=None):
    """The indexers' checked read path: fault site -> envelope -> guarded
    decode, quarantining on detection. Most index rows are DERIVED data the
    repairer re-creates from the block + ABCI-responses stores (txr/, txe/,
    blkh/); the repaired counter is bumped there, when the reindex actually
    lands — never here at detection time (docs/DURABILITY.md)."""
    raw = faults.mutate_value(LOAD_SITE, raw)
    if raw is None:
        return None
    try:
        return envelope.decode(raw, "txindex", key, fn,
                               on_corruption=on_corruption)
    except envelope.CorruptedStoreError:
        envelope.quarantine(db, envelope.CorruptedStoreError(
            "txindex", key, "quarantined on read", raw))
        raise


def _posting_hash(b: bytes) -> bytes:
    """Strict posting decode: the value IS a 32-byte tx hash. Shape
    validation closes the one envelope blind spot — a bit flip landing in
    the 2-byte magic demotes the row to the legacy path, where an
    identity decode would accept anything (docs/DURABILITY.md)."""
    if len(b) != 32:
        raise ValueError(f"posting value is {len(b)} bytes, want a 32-byte "
                         "tx hash")
    return b


def _height_str(b: bytes) -> int:
    """Strict decimal decode for blk/blkh height rows (same blind-spot
    closure as _posting_hash)."""
    return envelope.decimal_height(b)


class TxIndexer:
    """reference: state/txindex/kv/kv.go:32 TxIndex."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.Lock()
        self._staged: list | None = None
        self.on_corruption = None

    @contextlib.contextmanager
    def height_txn(self):
        """Batch one height's tx postings into a single write_batch (the kv
        analogue of SqlEventSink.height_txn): index() calls stage their rows
        while the context is open and the whole height lands in one batch on
        exit — one store write per height instead of one per tx."""
        with self._mtx:
            if self._staged is not None:
                raise RuntimeError("height_txn does not nest")
            self._staged = []
        try:
            yield self
        except Exception:
            with self._mtx:
                self._staged = None
            raise
        else:
            with self._mtx:
                sets, self._staged = self._staged, None
                if sets:
                    self._db.write_batch(sets)

    def index(self, height: int, idx: int, tx: bytes, result) -> None:
        h = tx_hash(tx)
        doc = {
            "hash": h.hex().upper(),
            "height": str(height),
            "index": idx,
            "tx": base64.b64encode(tx).decode(),
            "tx_result": {
                "code": result.code if result else 0,
                "data": base64.b64encode(result.data if result else b"").decode(),
                "log": result.log if result else "",
                "gas_wanted": str(result.gas_wanted if result else 0),
                "gas_used": str(result.gas_used if result else 0),
                "events": [
                    {"type": e.type, "attributes": [
                        {"key": base64.b64encode(a.key).decode(),
                         "value": base64.b64encode(a.value).decode(),
                         "index": a.index}
                        for a in e.attributes]}
                    for e in (result.events if result else [])
                ],
            },
        }
        sets = [(b"txr/" + h, envelope.wrap(json.dumps(doc).encode()))]
        postings = [("tx.height", str(height))]
        for e in (result.events if result else []):
            for a in e.attributes:
                if not a.index:
                    continue  # only attributes the app marked indexable
                try:
                    postings.append((f"{e.type}.{a.key.decode()}", a.value.decode()))
                except UnicodeDecodeError:
                    continue
        for key, value in postings:
            pk = f"txe/{_esc(key)}/{_esc(value)}/{height}/{idx}".encode()
            sets.append((pk, envelope.wrap(h)))
        with self._mtx:
            if self._staged is not None:
                self._staged.extend(sets)
            else:
                self._db.write_batch(sets)

    def get(self, h: bytes) -> dict | None:
        key = b"txr/" + h
        return _checked(self._db, key, self._db.get(key), json.loads,
                        on_corruption=self.on_corruption)

    def _scan(self, key: str, op: str, value: str | None) -> set[bytes]:
        """Candidate tx hashes for one condition (reference: kv.go:133
        Search + matchRange). `=` hits the exact posting prefix; range /
        CONTAINS / EXISTS conditions scan the key's postings and filter
        the posted values."""
        if op == "=":
            prefix = f"txe/{_esc(key)}/{_esc(value)}/".encode()
            return {h for h in
                    (self._posting(k, v) for k, v in
                     list(self._db.iterator(prefix, prefix_end(prefix))))
                    if h is not None}
        prefix = f"txe/{_esc(key)}/".encode()
        found = set()
        for k, v in list(self._db.iterator(prefix, prefix_end(prefix))):
            posted = k.decode().split("/")[2].replace("%2F", "/")
            if op == "exists" or tmevents.Query._cmp(op, posted, value):
                h = self._posting(k, v)
                if h is not None:
                    found.add(h)
        return found

    def _posting(self, k: bytes, v: bytes) -> bytes | None:
        """One posting row through the checked path; a corrupt posting is
        quarantined and simply drops out of the candidate set."""
        try:
            return _checked(self._db, k, v, _posting_hash,
                            on_corruption=self.on_corruption)
        except envelope.CorruptedStoreError:
            return None

    def search(self, query: str) -> list[dict]:
        """AND of conditions over the event postings; supports the full
        operator grammar (=, <, <=, >, >=, CONTAINS, EXISTS)."""
        q = tmevents.Query(query)
        conditions = [c for c in q.conditions
                      if c[0] != tmevents.EVENT_TYPE_KEY]
        if not conditions:
            return []
        result_hashes: set[bytes] | None = None
        for key, op, value in conditions:
            found = self._scan(key, op, value)
            result_hashes = found if result_hashes is None else (result_hashes & found)
            if not result_hashes:
                return []
        docs = []
        for h in result_hashes:
            try:
                docs.append(self.get(h))
            except envelope.CorruptedStoreError:
                continue  # quarantined; the posting's doc is gone
        docs = [d for d in docs if d is not None]
        docs.sort(key=lambda d: (int(d["height"]), d["index"]))
        return docs


class BlockIndexer:
    """reference: state/indexer/block/kv/kv.go."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.Lock()
        self.on_corruption = None

    def index(self, height: int, begin_block_events, end_block_events) -> None:
        sets = [(f"blkh/{height}".encode(),
                 envelope.wrap(str(height).encode()))]
        for stage, evs in (("begin_block", begin_block_events),
                           ("end_block", end_block_events)):
            for e in evs or []:
                for a in e.attributes:
                    if not a.index:
                        continue
                    try:
                        key = f"{e.type}.{a.key.decode()}"
                        value = a.value.decode()
                    except UnicodeDecodeError:
                        continue
                    pk = f"blk/{_esc(key)}/{_esc(value)}/{height}".encode()
                    sets.append((pk, envelope.wrap(str(height).encode())))
        with self._mtx:
            self._db.write_batch(sets)

    def has(self, height: int) -> bool:
        return self._db.get(f"blkh/{height}".encode()) is not None

    def _height_row(self, k: bytes, v: bytes) -> int | None:
        try:
            return _checked(self._db, k, v, _height_str,
                            on_corruption=self.on_corruption)
        except envelope.CorruptedStoreError:
            return None

    def search(self, query: str) -> list[int]:
        q = tmevents.Query(query)
        conditions = [c for c in q.conditions
                      if c[0] != tmevents.EVENT_TYPE_KEY]
        if not conditions:
            return []
        heights: set[int] | None = None
        for key, op, value in conditions:
            if key == "block.height":
                if op == "=":
                    found = {int(value)} if self.has(int(value)) else set()
                else:
                    prefix = b"blkh/"
                    found = set()
                    for k, v in list(self._db.iterator(prefix, prefix_end(prefix))):
                        h = self._height_row(k, v)
                        if h is not None and (
                                op == "exists"
                                or tmevents.Query._cmp(op, str(h), value)):
                            found.add(h)
            elif op == "=":
                prefix = f"blk/{_esc(key)}/{_esc(value)}/".encode()
                found = {h for h in
                         (self._height_row(k, v) for k, v in
                          list(self._db.iterator(prefix, prefix_end(prefix))))
                         if h is not None}
            else:
                prefix = f"blk/{_esc(key)}/".encode()
                found = set()
                for k, v in list(self._db.iterator(prefix, prefix_end(prefix))):
                    posted = k.decode().split("/")[2].replace("%2F", "/")
                    if op == "exists" or tmevents.Query._cmp(op, posted, value):
                        h = self._height_row(k, v)
                        if h is not None:
                            found.add(h)
            heights = found if heights is None else (heights & found)
            if not heights:
                return []
        return sorted(heights)


class IndexerService:
    """Subscribes to the EventBus and feeds both indexers (reference:
    state/txindex/indexer_service.go:19)."""

    SUBSCRIBER = "IndexerService"

    def __init__(self, tx_indexer: TxIndexer, block_indexer: BlockIndexer,
                 event_bus, logger=None):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self.logger = logger
        self._running = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._tx_sub = self.event_bus.subscribe(
            self.SUBSCRIBER, f"{tmevents.EVENT_TYPE_KEY}={tmevents.EVENT_TX}",
            out_capacity=0)
        self._block_sub = self.event_bus.subscribe(
            self.SUBSCRIBER,
            f"{tmevents.EVENT_TYPE_KEY}={tmevents.EVENT_NEW_BLOCK_HEADER}",
            out_capacity=0)
        self._running = True
        self._thread = threading.Thread(target=self._run, name="indexer",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        try:
            self.event_bus.unsubscribe_all(self.SUBSCRIBER)
        except ValueError:
            pass
        # Join the drain thread so no index write is in flight when callers
        # (e.g. Node.stop) go on to close the sink's DB connection.
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        try:
            self._drain()
        except tmevents.SubscriptionCancelled:
            return  # unsubscribed during stop()
        except Exception as e:  # noqa: BLE001 - indexing is best-effort;
            # a dead drainer must at least say so
            if self.logger:
                self.logger.error("indexer drain crashed", err=e)

    def _drain(self) -> None:
        # Reference ordering (state/txindex/indexer_service.go:59-75): drive
        # off the header subscription; for each header pull exactly num_txs
        # tx events, index the BLOCK first, then its txs — the SQL sink
        # requires the block row to exist before its tx rows.
        while self._running:
            bmsg = self._block_sub.next(timeout=0.1)
            if bmsg is None:
                continue
            d = bmsg.data
            # Batch the height: every posting of this block (header + its
            # num_txs tx results) lands in ONE indexer transaction when the
            # backend offers a height_txn seam (kv batches the store write,
            # the SQL sink commits once instead of 1 + num_txs times).
            with contextlib.ExitStack() as stack:
                for indexer in (self.block_indexer, self.tx_indexer):
                    hx = getattr(indexer, "height_txn", None)
                    if hx is not None:
                        stack.enter_context(hx())
                try:
                    self.block_indexer.index(
                        d.header.height,
                        d.result_begin_block.events
                        if d.result_begin_block else [],
                        d.result_end_block.events
                        if d.result_end_block else [])
                except Exception as e:  # noqa: BLE001
                    if self.logger:
                        self.logger.error("failed to index block", err=e)
                for _ in range(d.num_txs):
                    msg = None
                    while self._running and msg is None:
                        msg = self._tx_sub.next(timeout=0.1)
                    if msg is None:
                        return
                    t = msg.data
                    try:
                        self.tx_indexer.index(t.height, t.index, t.tx,
                                              t.result)
                    except Exception as e:  # noqa: BLE001
                        if self.logger:
                            self.logger.error("failed to index tx", err=e)
