"""Mempool admission filters derived from consensus state
(reference: state/tx_filter.go, mempool/mempool.go:111-141).

`tx_pre_check(state)` bounds a single tx to the block's maximum data size
(MaxDataBytesNoEvidence: the whole block budget minus header/commit
overhead for the current validator count); `tx_post_check(state)` bounds
the gas the app priced a tx at to the block's max_gas (-1 disables). Both
are rebuilt from the NEW state after every applied block, exactly like
the reference's mempool.Update(..., preCheck, postCheck) plumbing.
"""

from __future__ import annotations

from tendermint_tpu.mempool.mempool import ErrPreCheck
from tendermint_tpu.types.tx import total_tx_bytes


def max_data_bytes_no_evidence(max_bytes: int, num_vals: int) -> int:
    """reference: types/block.go:301 MaxDataBytesNoEvidence."""
    from tendermint_tpu.state.execution import max_data_bytes

    return max_data_bytes(max_bytes, 0, num_vals)


def tx_pre_check(state):
    limit = max_data_bytes_no_evidence(
        state.consensus_params.block.max_bytes, state.validators.size())

    def check(tx: bytes) -> None:
        # proto size of Data{txs: [tx]} (reference: types/tx.go:156
        # ComputeProtoSizeForTxs)
        size = total_tx_bytes([tx])
        if size > limit:
            raise ErrPreCheck(f"tx size is too big: {size}, max: {limit}")

    return check


def tx_post_check(state):
    max_gas = state.consensus_params.block.max_gas

    def check(tx: bytes, res) -> None:
        if max_gas == -1:
            return
        if res.gas_wanted < 0:
            raise ErrPreCheck(f"gas wanted {res.gas_wanted} is negative")
        if res.gas_wanted > max_gas:
            raise ErrPreCheck(
                f"gas wanted {res.gas_wanted} is greater than "
                f"max gas {max_gas}")

    return check
