"""State store: persists State + per-height validator/params history + ABCI
responses (reference: state/store.go:100-661).

Layout:
  stateKey                    -> full State
  validatorsKey:<height>      -> ValidatorsInfo {set | last_height_changed}
  consensusParamsKey:<height> -> ConsensusParamsInfo {params | last_height_changed}
  abciResponsesKey:<height>   -> serialized DeliverTx responses + EndBlock

The validator history trick mirrors the reference: heights where nothing
changed store only a back-pointer to last_height_changed
(state/store.go:483-560), so lookups may take one indirection.
"""

from __future__ import annotations

from tendermint_tpu.abci.types import ResponseDeliverTx
from tendermint_tpu.encoding import proto
from tendermint_tpu.state.state import State
from tendermint_tpu.store import envelope
from tendermint_tpu.store.db import DB
from tendermint_tpu.types.block import Consensus
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.ttime import Time
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.utils import faults

_STATE_KEY = b"stateKey"
VALSET_CHECK_INTERVAL = 100000  # reference: state/store.go valSetCheckpointInterval


def _val_key(h: int) -> bytes:
    return b"validatorsKey:%020d" % h


def _params_key(h: int) -> bytes:
    return b"consensusParamsKey:%020d" % h


def _abci_key(h: int) -> bytes:
    return b"abciResponsesKey:%020d" % h


class StateStoreError(Exception):
    pass


class ErrNoValSetForHeight(StateStoreError):
    def __init__(self, height: int):
        super().__init__(f"could not find validator set for height #{height}")


def _marshal_state(s: State) -> bytes:
    w = proto.Writer()
    w.message(1, s.version.marshal(), always=True)
    w.string(2, s.chain_id)
    w.varint(3, s.last_block_height)
    w.message(4, s.last_block_id.marshal(), always=True)
    w.message(5, s.last_block_time.marshal(), always=True)
    w.message(6, s.next_validators.marshal() if s.next_validators else b"", always=True)
    w.message(7, s.validators.marshal() if s.validators else b"", always=True)
    w.message(8, s.last_validators.marshal() if s.last_validators else b"", always=True)
    w.varint(9, s.last_height_validators_changed)
    w.message(10, s.consensus_params.marshal(), always=True)
    w.varint(11, s.last_height_consensus_params_changed)
    w.bytes(12, s.last_results_hash)
    w.bytes(13, s.app_hash)
    w.varint(14, s.initial_height)
    return w.out()


def _unmarshal_state(buf: bytes) -> State:
    f = proto.fields(buf)
    return State(
        version=Consensus.unmarshal(f.get(1, [b""])[-1]),
        chain_id=f.get(2, [b""])[-1].decode() if 2 in f else "",
        last_block_height=proto.as_sint64(f.get(3, [0])[-1]),
        last_block_id=BlockID.unmarshal(f.get(4, [b""])[-1]),
        last_block_time=Time.unmarshal(f.get(5, [b""])[-1]),
        next_validators=ValidatorSet.unmarshal(f.get(6, [b""])[-1]),
        validators=ValidatorSet.unmarshal(f.get(7, [b""])[-1]),
        last_validators=ValidatorSet.unmarshal(f.get(8, [b""])[-1]),
        last_height_validators_changed=proto.as_sint64(f.get(9, [0])[-1]),
        consensus_params=ConsensusParams.unmarshal(f.get(10, [b""])[-1]),
        last_height_consensus_params_changed=proto.as_sint64(f.get(11, [0])[-1]),
        last_results_hash=f.get(12, [b""])[-1],
        app_hash=f.get(13, [b""])[-1],
        initial_height=proto.as_sint64(f.get(14, [1])[-1]) or 1,
    )


class ABCIResponses:
    """reference: state/store.go:60-75 (tmstate.ABCIResponses)."""

    def __init__(self, deliver_txs: list[ResponseDeliverTx] | None = None,
                 end_block=None, begin_block=None):
        self.deliver_txs = deliver_txs or []
        self.end_block = end_block
        self.begin_block = begin_block

    def marshal(self) -> bytes:
        w = proto.Writer()
        for r in self.deliver_txs:
            w.message(1, r.marshal(), always=True)
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "ABCIResponses":
        f = proto.fields(buf)
        return ABCIResponses(
            deliver_txs=[ResponseDeliverTx.unmarshal(b) for b in f.get(1, [])]
        )


LOAD_SITE = "store.state.load"


class StateStore:
    def __init__(self, db: DB):
        self._db = db
        # repair hook: wired by the node to its StoreRepairer so every
        # integrity detection quarantines + schedules (docs/DURABILITY.md)
        self.on_corruption = None

    def _load_checked(self, key: bytes, fn):
        """DB get -> fault site -> envelope unwrap -> guarded decode: the
        checked read path every load below routes through. Corruption
        raises the typed CorruptedStoreError naming the key, never a bare
        proto/struct error."""
        raw = faults.mutate_value(LOAD_SITE, self._db.get(key))
        if raw is None:
            return None
        return envelope.decode(raw, "state", key, fn,
                               on_corruption=self.on_corruption)

    def _set(self, key: bytes, payload: bytes) -> None:
        self._db.set(key, envelope.wrap(payload))

    # --- state -------------------------------------------------------------

    def load(self) -> State:
        st = self._load_checked(_STATE_KEY, _unmarshal_state)
        return State() if st is None else st

    def save(self, state: State) -> None:
        """Persist state + index validator/params history (reference:
        state/store.go:174-205)."""
        next_height = state.last_block_height + 1
        if next_height == 1:
            next_height = state.initial_height
            self._save_validators(next_height, state.last_height_validators_changed,
                                  state.validators)
        self._save_validators(next_height + 1, state.last_height_validators_changed,
                              state.next_validators)
        self._save_params(next_height, state.last_height_consensus_params_changed,
                          state.consensus_params)
        # crash between the history rows above and the state key below is
        # the interesting torn-state case replay must absorb
        faults.fire("store.state.save")
        self._set(_STATE_KEY, _marshal_state(state))

    def bootstrap(self, state: State) -> None:
        """reference: state/store.go:207-241."""
        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height
        if height > 1 and state.last_validators and not state.last_validators.is_nil_or_empty():
            self._save_validators(height - 1, height - 1, state.last_validators)
        self._save_validators(height, height, state.validators)
        self._save_validators(height + 1, height + 1, state.next_validators)
        self._save_params(height, state.last_height_consensus_params_changed,
                          state.consensus_params)
        self._set(_STATE_KEY, _marshal_state(state))

    # --- validator history -------------------------------------------------

    def _save_validators(self, height: int, last_changed: int, vals: ValidatorSet) -> None:
        if vals is None:
            return
        if last_changed == height or height % VALSET_CHECK_INTERVAL == 0:
            body = proto.Writer().message(1, vals.marshal(), always=True).varint(2, last_changed).out()
        else:
            body = proto.Writer().varint(2, last_changed).out()
        self._set(_val_key(height), body)

    def load_validators(self, height: int) -> ValidatorSet:
        """reference: state/store.go:483-530 (with back-pointer chase)."""
        f = self._load_checked(_val_key(height), proto.fields)
        if f is None:
            raise ErrNoValSetForHeight(height)
        if 1 in f:
            return ValidatorSet.unmarshal(f[1][-1])
        last_changed = proto.as_sint64(f.get(2, [0])[-1])
        f2 = self._load_checked(_val_key(last_changed), proto.fields)
        if f2 is None:
            raise ErrNoValSetForHeight(height)
        if 1 not in f2:
            raise StateStoreError(
                f"validator checkpoint at height {last_changed} is itself a pointer"
            )
        return ValidatorSet.unmarshal(f2[1][-1])

    def validators_last_changed(self, height: int) -> int | None:
        """The back-pointer (or self height) of one validator-history row;
        None when the row is missing. The state repairer uses intact
        NEIGHBOR rows to re-derive a quarantined pointer row
        (store/repair.py)."""
        f = self._load_checked(_val_key(height), proto.fields)
        if f is None:
            return None
        return height if 1 in f else proto.as_sint64(f.get(2, [0])[-1])

    def rewrite_validators(self, height: int, last_changed: int,
                           vals: ValidatorSet | None) -> None:
        """Repair-path write: re-lay one validator-history row (a FULL row
        when ``vals`` is given, else a back-pointer to ``last_changed``)."""
        if vals is not None:
            self._save_validators(height, height, vals)
        else:
            self._set(_val_key(height),
                      proto.Writer().varint(2, last_changed).out())

    def params_last_changed(self, height: int) -> int | None:
        """Pointer twin of :meth:`validators_last_changed` for the
        consensus-params history (used by the state repairer)."""
        f = self._load_checked(_params_key(height), proto.fields)
        if f is None:
            return None
        return height if 1 in f else proto.as_sint64(f.get(2, [0])[-1])

    # --- consensus params history ------------------------------------------

    def _save_params(self, height: int, last_changed: int, params: ConsensusParams) -> None:
        if last_changed == height:
            body = proto.Writer().message(1, params.marshal(), always=True).varint(2, last_changed).out()
        else:
            body = proto.Writer().varint(2, last_changed).out()
        self._set(_params_key(height), body)

    def load_consensus_params(self, height: int) -> ConsensusParams:
        f = self._load_checked(_params_key(height), proto.fields)
        if f is None:
            raise StateStoreError(f"could not find consensus params for height #{height}")
        if 1 in f:
            return ConsensusParams.unmarshal(f[1][-1])
        last_changed = proto.as_sint64(f.get(2, [0])[-1])
        f2 = self._load_checked(_params_key(last_changed), proto.fields)
        if f2 is None:
            raise StateStoreError(f"could not find consensus params for height #{height}")
        return ConsensusParams.unmarshal(f2[1][-1])

    # --- ABCI responses ----------------------------------------------------

    def save_abci_responses(self, height: int, responses: ABCIResponses) -> None:
        self._set(_abci_key(height), responses.marshal())

    def load_abci_responses(self, height: int) -> ABCIResponses:
        resp = self._load_checked(_abci_key(height), ABCIResponses.unmarshal)
        if resp is None:
            raise StateStoreError(f"could not find ABCI responses for height #{height}")
        return resp

    # --- pruning -----------------------------------------------------------

    def prune_states(self, base: int, height: int) -> None:
        """Deletes history in [base, height) (reference: state/store.go:243-330).

        Surviving heights may hold back-pointers into the pruned range, so the
        retain boundary `height` is first rewritten as FULL validator/params
        rows (the reference does the same with its keepVals/keepParams sets)."""
        if base <= 0 or height <= base:
            raise StateStoreError(f"invalid range {base}..{height}")
        # Materialize the boundary rows before deleting what they point into.
        boundary_vals = self.load_validators(height)
        self._save_validators(height, height, boundary_vals)
        try:
            boundary_params = self.load_consensus_params(height)
            self._save_params(height, height, boundary_params)
        except StateStoreError:
            pass
        # A pointer one past the boundary (height+1 row saved by save()) may
        # also reference the pruned range.
        try:
            next_vals = self.load_validators(height + 1)
            self._save_validators(height + 1, height + 1, next_vals)
        except ErrNoValSetForHeight:
            pass
        deletes = []
        for h in range(base, height):
            if h % VALSET_CHECK_INTERVAL != 0:
                deletes.append(_val_key(h))
            deletes.append(_params_key(h))
            deletes.append(_abci_key(h))
        self._db.write_batch([], deletes)
