"""Rollback one height (reference: state/rollback.go:112, cmd rollback)."""

from __future__ import annotations

import os
from dataclasses import replace

from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.db import new_db


class RollbackError(Exception):
    pass


def rollback_state(cfg) -> tuple[int, bytes]:
    backend = cfg.base.db_backend
    dbdir = cfg.db_dir()
    block_store = BlockStore(new_db(backend, os.path.join(dbdir, "blockstore.db")))
    state_store = StateStore(new_db(backend, os.path.join(dbdir, "state.db")))
    return rollback(block_store, state_store)


def rollback(block_store: BlockStore, state_store: StateStore) -> tuple[int, bytes]:
    """reference: state/rollback.go Rollback."""
    invalid_state = state_store.load()
    if invalid_state.is_empty():
        raise RollbackError("no state found")

    height = block_store.height
    # state and store out of sync (crash between SaveBlock and state save):
    # the state is already where rollback would put it.
    if height == invalid_state.last_block_height + 1:
        return invalid_state.last_block_height, invalid_state.app_hash
    if height != invalid_state.last_block_height:
        raise RollbackError(
            f"statestore height ({invalid_state.last_block_height}) is not one below or "
            f"equal to blockstore height ({height})"
        )

    rollback_height = invalid_state.last_block_height - 1
    if rollback_height < 1:
        raise RollbackError("can't rollback state at genesis height")
    rolled_back_block = block_store.load_block_meta(rollback_height)
    if rolled_back_block is None:
        raise RollbackError(f"block at height {rollback_height} not found")
    latest_block = block_store.load_block_meta(invalid_state.last_block_height)

    prev_validators = state_store.load_validators(rollback_height)
    curr_validators = state_store.load_validators(rollback_height + 1)
    next_validators = state_store.load_validators(rollback_height + 2)
    params = state_store.load_consensus_params(rollback_height + 1)

    rolled = replace(
        invalid_state,
        last_block_height=rollback_height,
        last_block_id=block_store.load_block_meta(rollback_height).block_id,
        last_block_time=rolled_back_block.header.time,
        validators=curr_validators,
        next_validators=next_validators,
        last_validators=prev_validators,
        consensus_params=params,
        app_hash=latest_block.header.app_hash,
        # results(rollback_height) are committed by the NEXT header — the
        # latest block — not the rolled-back header (rollback.go does the
        # same: LastResultsHash comes from latestBlock)
        last_results_hash=latest_block.header.last_results_hash,
    )
    state_store.save(rolled)
    return rolled.last_block_height, rolled.app_hash
