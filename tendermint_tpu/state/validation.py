"""Block validation against state (reference: state/validation.go:15-151)."""

from __future__ import annotations

from tendermint_tpu.state.state import State
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.ttime import Time


class BlockValidationError(Exception):
    pass


def validate_block(state: State, block: Block, block_store=None,
                   commit_pending=None) -> None:
    """reference: state/validation.go:15. Includes the batched
    LastValidators.VerifyCommit at the same point the reference does (line 93),
    which on TPU is one kernel launch instead of N serial verifies.

    `commit_pending` (a resolvable handle from
    BlockExecutor.dispatch_commit_verify, already stale-checked by the
    caller) replaces the synchronous verify with a resolve of the
    already-dispatched device work — the commit→apply overlap seam
    (docs/EXECUTION.md). Resolution replays the exact serial accept/reject
    decision, so accept/reject and error attribution are unchanged."""
    block.validate_basic()

    h = block.header
    if h.version != state.version:
        raise BlockValidationError(
            f"wrong Block.Header.Version. Expected {state.version}, got {h.version}"
        )
    if h.chain_id != state.chain_id:
        raise BlockValidationError(
            f"wrong Block.Header.ChainID. Expected {state.chain_id}, got {h.chain_id}"
        )
    if state.last_block_height == 0 and h.height != state.initial_height:
        raise BlockValidationError(
            f"wrong Block.Header.Height. Expected {state.initial_height} (initial height), got {h.height}"
        )
    if state.last_block_height > 0 and h.height != state.last_block_height + 1:
        raise BlockValidationError(
            f"wrong Block.Header.Height. Expected {state.last_block_height + 1}, got {h.height}"
        )
    if h.last_block_id != state.last_block_id:
        raise BlockValidationError(
            f"wrong Block.Header.LastBlockID. Expected {state.last_block_id}, got {h.last_block_id}"
        )
    if h.app_hash != state.app_hash:
        raise BlockValidationError(
            f"wrong Block.Header.AppHash. Expected {state.app_hash.hex().upper()}, got {h.app_hash.hex().upper()}"
        )
    if h.consensus_hash != state.consensus_params.hash():
        raise BlockValidationError("wrong Block.Header.ConsensusHash")
    if h.last_results_hash != state.last_results_hash:
        raise BlockValidationError("wrong Block.Header.LastResultsHash")
    if h.validators_hash != state.validators.hash():
        raise BlockValidationError(
            f"wrong Block.Header.ValidatorsHash. Expected {state.validators.hash().hex().upper()}, "
            f"got {h.validators_hash.hex().upper()}"
        )
    if h.next_validators_hash != state.next_validators.hash():
        raise BlockValidationError("wrong Block.Header.NextValidatorsHash")

    # LastCommit
    if block.header.height == state.initial_height:
        if block.last_commit is not None and len(block.last_commit.signatures) != 0:
            raise BlockValidationError("initial block can't have LastCommit signatures")
    elif commit_pending is not None:
        # dispatched earlier (overlapped with store save / WAL fsync);
        # resolve() is idempotent and raises exactly what the
        # synchronous verify would
        commit_pending.resolve()
    else:
        # THE hot call (reference: state/validation.go:93): one batched kernel.
        state.last_validators.verify_commit(
            state.chain_id, state.last_block_id, block.header.height - 1, block.last_commit
        )

    # proposer must be in the current validator set
    if not state.validators.has_address(h.proposer_address):
        raise BlockValidationError(
            f"block.Header.ProposerAddress {h.proposer_address.hex().upper()} is not a validator"
        )

    # time validation (reference: state/validation.go:118-145)
    if block.header.height > state.initial_height:
        if not block.header.time > state.last_block_time:
            raise BlockValidationError(
                f"block time {block.header.time} not greater than last block time {state.last_block_time}"
            )
        if block.last_commit is not None and len(state.last_validators.validators) > 0:
            median = median_time(block.last_commit, state.last_validators)
            if block.header.time != median:
                raise BlockValidationError(
                    f"invalid block time. Expected {median}, got {block.header.time}"
                )
    elif block.header.height == state.initial_height:
        if block.header.time < state.last_block_time:
            raise BlockValidationError("block time is earlier than genesis time")


def median_time(commit, validators) -> Time:
    """Weighted median of commit timestamps (reference: types/validator_set.go
    / state MedianTime via types/time.WeightedMedian)."""
    weighted: list[tuple[Time, int]] = []
    for i, cs in enumerate(commit.signatures):
        if cs.absent():
            continue
        _, val = validators.get_by_address(cs.validator_address)
        if val is not None:
            weighted.append((cs.timestamp, val.voting_power))
    if not weighted:
        return Time.zero()
    weighted.sort(key=lambda tv: (tv[0].seconds, tv[0].nanos))
    total = sum(w for _, w in weighted)
    median = total // 2
    acc = 0
    for t, w in weighted:
        acc += w
        if acc > median:
            return t
    return weighted[-1][0]
