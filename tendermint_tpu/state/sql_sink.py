"""SQL event sink: the analogue of the reference's PostgreSQL indexer sink
(state/indexer/sink/psql/{psql.go,schema.sql,backport.go}).

The sink writes normalized block/tx/event/attribute rows through any DB-API
2.0 connection. The schema is the reference's (blocks, tx_results, events,
attributes + the event_attributes/block_events/tx_events views); only the
auto-increment spelling differs per dialect. This image ships no postgres
driver, so the tested backend is the stdlib ``sqlite3`` (>=3.35 for
RETURNING); a psycopg2 connection works unchanged — the dialect is picked
from the driver module's ``paramstyle``.

Like the reference sink, this is write-only: reads (``get``/``search``/
``has``) are served by the kv indexer, and the backport adapters raise for
them (backport.go:52-61,74-77,86-89). One deviation: ``tx_result`` stores
the JSON document this framework serves over RPC rather than a protobuf
``TxResult`` message (psql.go:182) — this repo's wire analogue for indexed
results is JSON throughout (state/txindex.py).
"""

from __future__ import annotations

import json
import threading
import time

# The reference schema, dialect-parameterized: {PK} is the auto-increment
# primary-key spelling ("BIGSERIAL PRIMARY KEY" on postgres,
# "INTEGER PRIMARY KEY AUTOINCREMENT" on sqlite).
SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      {PK},
  height     BIGINT NOT NULL,
  chain_id   VARCHAR NOT NULL,
  created_at VARCHAR NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid      {PK},
  block_id   BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_index   INTEGER NOT NULL,
  created_at VARCHAR NOT NULL,
  tx_hash    VARCHAR NOT NULL,
  tx_result  {BLOB} NOT NULL,
  UNIQUE (block_id, tx_index)
);
CREATE TABLE IF NOT EXISTS events (
  rowid    {PK},
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_id    BIGINT NULL REFERENCES tx_results(rowid),
  type     VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
  event_id      BIGINT NOT NULL REFERENCES events(rowid),
  key           VARCHAR NOT NULL,
  composite_key VARCHAR NOT NULL,
  value         VARCHAR NULL,
  UNIQUE (event_id, key)
);
CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);
CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key,
         composite_key, value
  FROM blocks JOIN event_attributes
    ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;
CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, tx_index, chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id)
  WHERE event_attributes.tx_id IS NOT NULL;
"""

BLOCK_HEIGHT_KEY = "block.height"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"


def connect(conn_str: str):
    """Open a DB-API connection from a psql_conn-style string. ``sqlite:PATH``
    (or a bare path / ``:memory:``) opens stdlib sqlite3; anything else is
    handed to psycopg2 when available (the reference's driver,
    psql.go:24 driverName)."""
    if conn_str.startswith("sqlite:"):
        conn_str = conn_str[len("sqlite:"):]
    elif "=" in conn_str or conn_str.startswith("postgres"):
        try:
            import psycopg2  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "psql_conn looks like a postgres conn string but no "
                "postgres driver is installed; use 'sqlite:PATH'") from e
        return psycopg2.connect(conn_str)
    import sqlite3

    return sqlite3.connect(conn_str, check_same_thread=False)


class SqlEventSink:
    """reference: state/indexer/sink/psql/psql.go:30 EventSink."""

    def __init__(self, conn, chain_id: str):
        self._conn = conn
        self.chain_id = chain_id
        self._mtx = threading.Lock()
        mod = type(conn).__module__.split(".")[0]
        self._pg = mod.startswith("psycopg")
        self._ph = "%s" if self._pg else "?"
        self.ensure_schema()

    def _sql(self, q: str) -> str:
        return q.replace("$", self._ph)

    def ensure_schema(self) -> None:
        pk = ("BIGSERIAL PRIMARY KEY" if self._pg
              else "INTEGER PRIMARY KEY AUTOINCREMENT")
        blob = "BYTEA" if self._pg else "BLOB"
        ddl = SCHEMA.format(PK=pk, BLOB=blob)
        if self._pg:
            ddl = ddl.replace("CREATE VIEW IF NOT EXISTS",
                              "CREATE OR REPLACE VIEW")
        with self._mtx:
            cur = self._conn.cursor()
            for stmt in ddl.split(";"):
                if stmt.strip():
                    cur.execute(stmt)
            self._conn.commit()

    # -- write paths (psql.go:142 IndexBlockEvents, :177 IndexTxEvents) ------

    def _insert_events(self, cur, block_id: int, tx_id, events) -> None:
        """psql.go:86 insertEvents: one row per event, one per indexed
        attribute; empty event types skipped."""
        for e in events or ():
            etype = e.type
            if not etype:
                continue
            cur.execute(self._sql(
                "INSERT INTO events (block_id, tx_id, type) "
                "VALUES ($, $, $) RETURNING rowid"), (block_id, tx_id, etype))
            eid = cur.fetchone()[0]
            for a in e.attributes or ():
                if not a.index:
                    continue
                key = a.key.decode("utf-8", "replace")
                cur.execute(self._sql(
                    "INSERT INTO attributes (event_id, key, composite_key, "
                    "value) VALUES ($, $, $, $)"),
                    (eid, key, f"{etype}.{key}",
                     a.value.decode("utf-8", "replace")))

    def _meta_event(self, cur, block_id: int, tx_id, composite_key: str,
                    value: str) -> None:
        """psql.go:130 makeIndexedEvent: "type.name" becomes a single-
        attribute event."""
        etype, _, name = composite_key.partition(".")
        cur.execute(self._sql(
            "INSERT INTO events (block_id, tx_id, type) "
            "VALUES ($, $, $) RETURNING rowid"), (block_id, tx_id, etype))
        eid = cur.fetchone()[0]
        if name:
            cur.execute(self._sql(
                "INSERT INTO attributes (event_id, key, composite_key, value) "
                "VALUES ($, $, $, $)"), (eid, name, composite_key, value))

    def _now(self) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    def index_block_events(self, height: int, begin_events, end_events) -> None:
        with self._mtx:
            cur = self._conn.cursor()
            try:
                cur.execute(self._sql(
                    "INSERT INTO blocks (height, chain_id, created_at) "
                    "VALUES ($, $, $) ON CONFLICT DO NOTHING RETURNING rowid"),
                    (height, self.chain_id, self._now()))
                row = cur.fetchone()
                if row is None:  # duplicate: quietly succeed (psql.go:154)
                    self._conn.rollback()
                    return
                block_id = row[0]
                self._meta_event(cur, block_id, None, BLOCK_HEIGHT_KEY,
                                 str(height))
                # Order matters: begin-block before end-block (psql.go:166).
                self._insert_events(cur, block_id, None, begin_events)
                self._insert_events(cur, block_id, None, end_events)
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def index_tx(self, height: int, idx: int, tx: bytes, result) -> None:
        from tendermint_tpu.types.tx import tx_hash

        h = tx_hash(tx).hex().upper()
        doc = _tx_result_doc(height, idx, tx, result, h)
        with self._mtx:
            cur = self._conn.cursor()
            try:
                cur.execute(self._sql(
                    "SELECT rowid FROM blocks WHERE height = $ AND "
                    "chain_id = $"), (height, self.chain_id))
                row = cur.fetchone()
                if row is None:
                    raise ValueError(
                        f"no indexed block at height {height}; the block "
                        "header must be indexed before its transactions")
                block_id = row[0]
                cur.execute(self._sql(
                    "INSERT INTO tx_results (block_id, tx_index, created_at, "
                    "tx_hash, tx_result) VALUES ($, $, $, $, $) "
                    "ON CONFLICT DO NOTHING RETURNING rowid"),
                    (block_id, idx, self._now(), h,
                     json.dumps(doc).encode()))
                row = cur.fetchone()
                if row is None:  # duplicate: quietly succeed (psql.go:207)
                    self._conn.rollback()
                    return
                tx_id = row[0]
                self._meta_event(cur, block_id, tx_id, TX_HASH_KEY, h)
                self._meta_event(cur, block_id, tx_id, TX_HEIGHT_KEY,
                                 str(height))
                self._insert_events(cur, block_id, tx_id,
                                    result.events if result else ())
                self._conn.commit()
            except Exception:
                self._conn.rollback()
                raise

    def stop(self) -> None:
        with self._mtx:  # wait out any in-flight index transaction
            self._conn.close()

    # -- backport adapters (backport.go:32,65) -------------------------------

    def tx_indexer(self) -> "BackportTxIndexer":
        return BackportTxIndexer(self)

    def block_indexer(self) -> "BackportBlockIndexer":
        return BackportBlockIndexer(self)


def _tx_result_doc(height: int, idx: int, tx: bytes, result,
                   hash_hex: str) -> dict:
    """Same JSON document shape the kv indexer stores (state/txindex.py)."""
    import base64

    return {
        "hash": hash_hex,
        "height": str(height),
        "index": idx,
        "tx": base64.b64encode(tx).decode(),
        "tx_result": {
            "code": result.code if result else 0,
            "data": base64.b64encode(result.data if result else b"").decode(),
            "log": result.log if result else "",
            "gas_wanted": str(result.gas_wanted if result else 0),
            "gas_used": str(result.gas_used if result else 0),
            "events": [
                {"type": e.type, "attributes": [
                    {"key": base64.b64encode(a.key).decode(),
                     "value": base64.b64encode(a.value).decode(),
                     "index": a.index}
                    for a in e.attributes]}
                for e in (result.events if result else [])
            ],
        },
    }


class BackportTxIndexer:
    """Bridges the sink to the TxIndexer interface IndexerService drives;
    reads are not supported by this sink (backport.go:38-61)."""

    def __init__(self, sink: SqlEventSink):
        self._sink = sink

    def index(self, height: int, idx: int, tx: bytes, result) -> None:
        self._sink.index_tx(height, idx, tx, result)

    def get(self, h: bytes):
        raise ValueError("the TxIndexer.Get method is not supported by the "
                         "sql event sink")

    def search(self, query: str):
        raise ValueError("the TxIndexer.Search method is not supported by "
                         "the sql event sink")


class BackportBlockIndexer:
    """backport.go:70 BackportBlockIndexer."""

    def __init__(self, sink: SqlEventSink):
        self._sink = sink

    def index(self, height: int, begin_block_events, end_block_events) -> None:
        self._sink.index_block_events(height, begin_block_events,
                                      end_block_events)

    def has(self, height: int) -> bool:
        raise ValueError("the BlockIndexer.Has method is not supported by "
                         "the sql event sink")

    def search(self, query: str):
        raise ValueError("the BlockIndexer.Search method is not supported by "
                         "the sql event sink")
