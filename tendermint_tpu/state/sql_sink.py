"""SQL event sink: the analogue of the reference's PostgreSQL indexer sink
(state/indexer/sink/psql/{psql.go,schema.sql,backport.go}).

The sink writes normalized block/tx/event/attribute rows through any DB-API
2.0 connection. The schema is the reference's (blocks, tx_results, events,
attributes + the event_attributes/block_events/tx_events views); only the
auto-increment spelling differs per dialect. This image ships no postgres
driver, so the tested backend is the stdlib ``sqlite3`` (RETURNING when the
library is >=3.35, a ``cursor.lastrowid``/``INSERT OR IGNORE`` fallback
below that); a psycopg2 connection works unchanged — the dialect is picked
from the driver module's ``paramstyle``.

Write granularity: each ``index_block_events``/``index_tx`` call is its own
transaction by default (the reference's per-call shape), but the post-commit
indexer wraps a whole height in :meth:`SqlEventSink.height_txn` so the block
header and every tx posting of that height commit as ONE sink transaction —
one fsync per height instead of one per posting.

Like the reference sink, this is write-only: reads (``get``/``search``/
``has``) are served by the kv indexer, and the backport adapters raise for
them (backport.go:52-61,74-77,86-89). One deviation: ``tx_result`` stores
the JSON document this framework serves over RPC rather than a protobuf
``TxResult`` message (psql.go:182) — this repo's wire analogue for indexed
results is JSON throughout (state/txindex.py).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time

# The reference schema, dialect-parameterized: {PK} is the auto-increment
# primary-key spelling ("BIGSERIAL PRIMARY KEY" on postgres,
# "INTEGER PRIMARY KEY AUTOINCREMENT" on sqlite).
SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      {PK},
  height     BIGINT NOT NULL,
  chain_id   VARCHAR NOT NULL,
  created_at VARCHAR NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid      {PK},
  block_id   BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_index   INTEGER NOT NULL,
  created_at VARCHAR NOT NULL,
  tx_hash    VARCHAR NOT NULL,
  tx_result  {BLOB} NOT NULL,
  UNIQUE (block_id, tx_index)
);
CREATE TABLE IF NOT EXISTS events (
  rowid    {PK},
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_id    BIGINT NULL REFERENCES tx_results(rowid),
  type     VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
  event_id      BIGINT NOT NULL REFERENCES events(rowid),
  key           VARCHAR NOT NULL,
  composite_key VARCHAR NOT NULL,
  value         VARCHAR NULL,
  UNIQUE (event_id, key)
);
CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);
CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key,
         composite_key, value
  FROM blocks JOIN event_attributes
    ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;
CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, tx_index, chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id)
  WHERE event_attributes.tx_id IS NOT NULL;
"""

BLOCK_HEIGHT_KEY = "block.height"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"


def connect(conn_str: str):
    """Open a DB-API connection from a psql_conn-style string. ``sqlite:PATH``
    (or a bare path / ``:memory:``) opens stdlib sqlite3; anything else is
    handed to psycopg2 when available (the reference's driver,
    psql.go:24 driverName)."""
    if conn_str.startswith("sqlite:"):
        conn_str = conn_str[len("sqlite:"):]
    elif "=" in conn_str or conn_str.startswith("postgres"):
        try:
            import psycopg2  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "psql_conn looks like a postgres conn string but no "
                "postgres driver is installed; use 'sqlite:PATH'") from e
        return psycopg2.connect(conn_str)
    import sqlite3

    return sqlite3.connect(conn_str, check_same_thread=False)


class SqlEventSink:
    """reference: state/indexer/sink/psql/psql.go:30 EventSink."""

    def __init__(self, conn, chain_id: str):
        self._conn = conn
        self.chain_id = chain_id
        self._mtx = threading.Lock()
        self._deferred = 0  # height_txn nesting depth
        mod = type(conn).__module__.split(".")[0]
        self._pg = mod.startswith("psycopg")
        self._ph = "%s" if self._pg else "?"
        if self._pg:
            self._returning = True
        else:
            import sqlite3

            self._returning = sqlite3.sqlite_version_info >= (3, 35)
        self.ensure_schema()

    def _sql(self, q: str) -> str:
        return q.replace("$", self._ph)

    def _insert_row_id(self, cur, q: str, params):
        """Run an ``INSERT ... RETURNING rowid`` and return the new rowid,
        or None when an ON CONFLICT DO NOTHING clause swallowed a duplicate.

        sqlite grew RETURNING in 3.35; on older libraries the same insert
        is issued plain and the rowid read from ``cursor.lastrowid``, with
        ON CONFLICT DO NOTHING respelled as INSERT OR IGNORE so the
        duplicate case is still detectable (rowcount == 0)."""
        if self._returning:
            cur.execute(self._sql(q), params)
            row = cur.fetchone()
            return None if row is None else row[0]
        q = q.replace(" RETURNING rowid", "")
        if " ON CONFLICT DO NOTHING" in q:
            q = q.replace(" ON CONFLICT DO NOTHING", "")
            q = q.replace("INSERT INTO", "INSERT OR IGNORE INTO", 1)
        cur.execute(self._sql(q), params)
        if cur.rowcount == 0:
            return None
        return cur.lastrowid

    # -- per-height transaction batching (ROADMAP item-5 follow-on) ----------

    def _call_begin(self, cur) -> None:
        if self._deferred:
            cur.execute("SAVEPOINT height_call")

    def _call_commit(self, cur) -> None:
        if self._deferred:
            cur.execute("RELEASE SAVEPOINT height_call")
        else:
            self._conn.commit()

    def _call_rollback(self, cur) -> None:
        if self._deferred:
            # Unwind only this call's rows; earlier postings of the batched
            # height stay staged.
            cur.execute("ROLLBACK TO SAVEPOINT height_call")
            cur.execute("RELEASE SAVEPOINT height_call")
        else:
            self._conn.rollback()

    @contextlib.contextmanager
    def height_txn(self):
        """Batch every posting for one height into ONE sink transaction.

        The post-commit indexer wraps a height's block-event and tx-event
        postings in this context so the whole height commits atomically
        (and with one fsync) instead of once per call. Inside the context
        each index call runs under a savepoint instead of its own
        transaction — a failing or duplicate call unwinds just its own
        rows; exiting the context commits the height, an escaping
        exception rolls the whole height back.

        Reentrant: both backport adapters of one sink may be entered for
        the same height (the indexer service does exactly that); the
        commit/rollback happens at the outermost exit."""
        with self._mtx:
            self._deferred += 1
            if self._deferred == 1 and not self._pg:
                # sqlite: a savepoint opened in autocommit mode COMMITS at
                # its RELEASE; pin an explicit transaction for the height
                # so the per-call savepoints nest inside it. (psycopg opens
                # one implicitly on the first statement.)
                self._conn.cursor().execute("BEGIN")
        try:
            yield self
        except Exception:
            with self._mtx:
                self._deferred -= 1
                if self._deferred == 0:
                    self._conn.rollback()
            raise
        else:
            with self._mtx:
                self._deferred -= 1
                if self._deferred == 0:
                    self._conn.commit()

    def ensure_schema(self) -> None:
        pk = ("BIGSERIAL PRIMARY KEY" if self._pg
              else "INTEGER PRIMARY KEY AUTOINCREMENT")
        blob = "BYTEA" if self._pg else "BLOB"
        ddl = SCHEMA.format(PK=pk, BLOB=blob)
        if self._pg:
            ddl = ddl.replace("CREATE VIEW IF NOT EXISTS",
                              "CREATE OR REPLACE VIEW")
        with self._mtx:
            cur = self._conn.cursor()
            for stmt in ddl.split(";"):
                if stmt.strip():
                    cur.execute(stmt)
            self._conn.commit()

    # -- write paths (psql.go:142 IndexBlockEvents, :177 IndexTxEvents) ------

    def _insert_events(self, cur, block_id: int, tx_id, events) -> None:
        """psql.go:86 insertEvents: one row per event, one per indexed
        attribute; empty event types skipped."""
        for e in events or ():
            etype = e.type
            if not etype:
                continue
            eid = self._insert_row_id(cur,
                "INSERT INTO events (block_id, tx_id, type) "
                "VALUES ($, $, $) RETURNING rowid", (block_id, tx_id, etype))
            for a in e.attributes or ():
                if not a.index:
                    continue
                key = a.key.decode("utf-8", "replace")
                cur.execute(self._sql(
                    "INSERT INTO attributes (event_id, key, composite_key, "
                    "value) VALUES ($, $, $, $)"),
                    (eid, key, f"{etype}.{key}",
                     a.value.decode("utf-8", "replace")))

    def _meta_event(self, cur, block_id: int, tx_id, composite_key: str,
                    value: str) -> None:
        """psql.go:130 makeIndexedEvent: "type.name" becomes a single-
        attribute event."""
        etype, _, name = composite_key.partition(".")
        eid = self._insert_row_id(cur,
            "INSERT INTO events (block_id, tx_id, type) "
            "VALUES ($, $, $) RETURNING rowid", (block_id, tx_id, etype))
        if name:
            cur.execute(self._sql(
                "INSERT INTO attributes (event_id, key, composite_key, value) "
                "VALUES ($, $, $, $)"), (eid, name, composite_key, value))

    def _now(self) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    def index_block_events(self, height: int, begin_events, end_events) -> None:
        with self._mtx:
            cur = self._conn.cursor()
            self._call_begin(cur)
            try:
                block_id = self._insert_row_id(cur,
                    "INSERT INTO blocks (height, chain_id, created_at) "
                    "VALUES ($, $, $) ON CONFLICT DO NOTHING RETURNING rowid",
                    (height, self.chain_id, self._now()))
                if block_id is None:  # duplicate: quiet success (psql.go:154)
                    self._call_rollback(cur)
                    return
                self._meta_event(cur, block_id, None, BLOCK_HEIGHT_KEY,
                                 str(height))
                # Order matters: begin-block before end-block (psql.go:166).
                self._insert_events(cur, block_id, None, begin_events)
                self._insert_events(cur, block_id, None, end_events)
                self._call_commit(cur)
            except Exception:
                self._call_rollback(cur)
                raise

    def index_tx(self, height: int, idx: int, tx: bytes, result) -> None:
        from tendermint_tpu.types.tx import tx_hash

        h = tx_hash(tx).hex().upper()
        doc = _tx_result_doc(height, idx, tx, result, h)
        with self._mtx:
            cur = self._conn.cursor()
            self._call_begin(cur)
            try:
                cur.execute(self._sql(
                    "SELECT rowid FROM blocks WHERE height = $ AND "
                    "chain_id = $"), (height, self.chain_id))
                row = cur.fetchone()
                if row is None:
                    raise ValueError(
                        f"no indexed block at height {height}; the block "
                        "header must be indexed before its transactions")
                block_id = row[0]
                tx_id = self._insert_row_id(cur,
                    "INSERT INTO tx_results (block_id, tx_index, created_at, "
                    "tx_hash, tx_result) VALUES ($, $, $, $, $) "
                    "ON CONFLICT DO NOTHING RETURNING rowid",
                    (block_id, idx, self._now(), h,
                     json.dumps(doc).encode()))
                if tx_id is None:  # duplicate: quiet success (psql.go:207)
                    self._call_rollback(cur)
                    return
                self._meta_event(cur, block_id, tx_id, TX_HASH_KEY, h)
                self._meta_event(cur, block_id, tx_id, TX_HEIGHT_KEY,
                                 str(height))
                self._insert_events(cur, block_id, tx_id,
                                    result.events if result else ())
                self._call_commit(cur)
            except Exception:
                self._call_rollback(cur)
                raise

    def stop(self) -> None:
        with self._mtx:  # wait out any in-flight index transaction
            self._conn.close()

    # -- backport adapters (backport.go:32,65) -------------------------------

    def tx_indexer(self) -> "BackportTxIndexer":
        return BackportTxIndexer(self)

    def block_indexer(self) -> "BackportBlockIndexer":
        return BackportBlockIndexer(self)


def _tx_result_doc(height: int, idx: int, tx: bytes, result,
                   hash_hex: str) -> dict:
    """Same JSON document shape the kv indexer stores (state/txindex.py)."""
    import base64

    return {
        "hash": hash_hex,
        "height": str(height),
        "index": idx,
        "tx": base64.b64encode(tx).decode(),
        "tx_result": {
            "code": result.code if result else 0,
            "data": base64.b64encode(result.data if result else b"").decode(),
            "log": result.log if result else "",
            "gas_wanted": str(result.gas_wanted if result else 0),
            "gas_used": str(result.gas_used if result else 0),
            "events": [
                {"type": e.type, "attributes": [
                    {"key": base64.b64encode(a.key).decode(),
                     "value": base64.b64encode(a.value).decode(),
                     "index": a.index}
                    for a in e.attributes]}
                for e in (result.events if result else [])
            ],
        },
    }


class BackportTxIndexer:
    """Bridges the sink to the TxIndexer interface IndexerService drives;
    reads are not supported by this sink (backport.go:38-61)."""

    def __init__(self, sink: SqlEventSink):
        self._sink = sink

    def index(self, height: int, idx: int, tx: bytes, result) -> None:
        self._sink.index_tx(height, idx, tx, result)

    def height_txn(self):
        return self._sink.height_txn()

    def get(self, h: bytes):
        raise ValueError("the TxIndexer.Get method is not supported by the "
                         "sql event sink")

    def search(self, query: str):
        raise ValueError("the TxIndexer.Search method is not supported by "
                         "the sql event sink")


class BackportBlockIndexer:
    """backport.go:70 BackportBlockIndexer."""

    def __init__(self, sink: SqlEventSink):
        self._sink = sink

    def index(self, height: int, begin_block_events, end_block_events) -> None:
        self._sink.index_block_events(height, begin_block_events,
                                      end_block_events)

    def height_txn(self):
        return self._sink.height_txn()

    def has(self, height: int) -> bool:
        raise ValueError("the BlockIndexer.Has method is not supported by "
                         "the sql event sink")

    def search(self, query: str):
        raise ValueError("the BlockIndexer.Search method is not supported by "
                         "the sql event sink")
