"""Go-compatible time values for canonical encoding.

The reference signs over google.protobuf.Timestamp (seconds + nanos), with
Go's zero time (0001-01-01T00:00:00Z, seconds = -62135596800) as the zero
value for absent/nil commit signatures. Nanoseconds-since-epoch cannot
represent that, so Time carries (seconds, nanos) directly.

Reference: gogo StdTimeMarshal usage in types/block.go:445-452,
types/canonical.go:13 (RFC3339Nano string form for display).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from functools import total_ordering

from tendermint_tpu.encoding import proto
from tendermint_tpu.utils import clock as _clock

GO_ZERO_SECONDS = -62135596800  # 0001-01-01T00:00:00Z


@total_ordering
@dataclass(frozen=True)
class Time:
    seconds: int = GO_ZERO_SECONDS
    nanos: int = 0

    @staticmethod
    def zero() -> "Time":
        return Time()

    @staticmethod
    def now() -> "Time":
        # reads through utils/clock so a skewed process (TMTPU_CLOCK_SKEW_S
        # or a nemesis skew action on clock.DEFAULT) timestamps accordingly;
        # per-node components read their own node Clock instead
        return Time.from_unix_ns(_clock.now_ns())

    @staticmethod
    def from_unix_ns(ns: int) -> "Time":
        return Time(ns // 1_000_000_000, ns % 1_000_000_000)

    def is_zero(self) -> bool:
        return self.seconds == GO_ZERO_SECONDS and self.nanos == 0

    def unix_ns(self) -> int:
        return self.seconds * 1_000_000_000 + self.nanos

    def add_ns(self, ns: int) -> "Time":
        return Time.from_unix_ns(self.unix_ns() + ns)

    def __lt__(self, other: "Time") -> bool:
        return (self.seconds, self.nanos) < (other.seconds, other.nanos)

    # --- encoding ----------------------------------------------------------
    def marshal(self) -> bytes:
        """google.protobuf.Timestamp body (field 1 seconds, field 2 nanos)."""
        return proto.Writer().varint(1, self.seconds).varint(2, self.nanos).out()

    @staticmethod
    def unmarshal(buf: bytes) -> "Time":
        seconds, nanos = 0, 0
        for field, _w, v in proto.Reader(buf):
            if field == 1:
                seconds = proto.as_sint64(v)
            elif field == 2:
                nanos = proto.as_sint64(v)
        return Time(seconds, nanos)

    def __str__(self) -> str:
        if self.is_zero():
            return "0001-01-01T00:00:00Z"
        frac = f".{self.nanos:09d}".rstrip("0") if self.nanos else ""
        t = _time.gmtime(self.seconds)
        return (
            f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d}T"
            f"{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}{frac}Z"
        )


def canonical_now(override_ns: int | None = None) -> Time:
    """tmtime.Now truncates to the canonical form (UTC, no monotonic part)."""
    if override_ns is not None:
        return Time.from_unix_ns(override_ns)
    return Time.now()
