"""ConsensusParams (reference: types/params.go,
proto/tendermint/types/params.proto)."""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.encoding import proto

MAX_BLOCK_SIZE_BYTES = 104857600
ABCI_PUBKEY_TYPE_ED25519 = "ed25519"
ABCI_PUBKEY_TYPE_SECP256K1 = "secp256k1"
ABCI_PUBKEY_TYPE_SR25519 = "sr25519"


@dataclass(frozen=True)
class BlockParams:
    max_bytes: int = 22020096  # 21MB
    max_gas: int = -1
    time_iota_ms: int = 1000

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .varint(1, self.max_bytes)
            .varint(2, self.max_gas)
            .varint(3, self.time_iota_ms)
            .out()
        )


@dataclass(frozen=True)
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000  # 48h
    max_bytes: int = 1048576

    def marshal(self) -> bytes:
        dur = (
            proto.Writer()
            .varint(1, self.max_age_duration_ns // 1_000_000_000)
            .varint(2, self.max_age_duration_ns % 1_000_000_000)
            .out()
        )
        return (
            proto.Writer()
            .varint(1, self.max_age_num_blocks)
            .message(2, dur, always=True)
            .varint(3, self.max_bytes)
            .out()
        )


@dataclass(frozen=True)
class ValidatorParams:
    pub_key_types: tuple = (ABCI_PUBKEY_TYPE_ED25519,)

    def marshal(self) -> bytes:
        w = proto.Writer()
        for t in self.pub_key_types:
            w.string(1, t)
        return w.out()


@dataclass(frozen=True)
class VersionParams:
    app_version: int = 0

    def marshal(self) -> bytes:
        return proto.Writer().uvarint(1, self.app_version).out()


@dataclass(frozen=True)
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)

    def hash(self) -> bytes:
        """SHA-256 of HashedParams{BlockMaxBytes, BlockMaxGas} (reference:
        types/params.go:137-155)."""
        hp = proto.Writer().varint(1, self.block.max_bytes).varint(2, self.block.max_gas).out()
        return tmhash.sum(hp)

    def validate_basic(self) -> None:
        if self.block.max_bytes <= 0:
            raise ValueError(f"block.MaxBytes must be greater than 0. Got {self.block.max_bytes}")
        if self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError("block.MaxBytes is too big")
        if self.block.max_gas < -1:
            raise ValueError(f"block.MaxGas must be greater or equal to -1. Got {self.block.max_gas}")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.MaxAgeNumBlocks must be greater than 0")
        if self.evidence.max_age_duration_ns <= 0:
            raise ValueError("evidence.MaxAgeDuration must be greater than 0")
        if self.evidence.max_bytes > self.block.max_bytes:
            raise ValueError("evidence.MaxBytesEvidence is greater than upper bound")
        if self.evidence.max_bytes < 0:
            raise ValueError("evidence.MaxBytes must be non negative")
        if not self.pub_key_types_valid():
            raise ValueError("validator.PubKeyTypes must not be empty / unknown")

    def pub_key_types_valid(self) -> bool:
        known = {ABCI_PUBKEY_TYPE_ED25519, ABCI_PUBKEY_TYPE_SECP256K1, ABCI_PUBKEY_TYPE_SR25519}
        return bool(self.validator.pub_key_types) and all(
            t in known for t in self.validator.pub_key_types
        )

    def update(self, block=None, evidence=None, validator=None, version=None) -> "ConsensusParams":
        """Apply ABCI EndBlock param updates (reference: types/params.go
        UpdateConsensusParams)."""
        out = self
        if block is not None:
            out = replace(out, block=block)
        if evidence is not None:
            out = replace(out, evidence=evidence)
        if validator is not None:
            out = replace(out, validator=validator)
        if version is not None:
            out = replace(out, version=version)
        return out

    def marshal(self) -> bytes:
        return (
            proto.Writer()
            .message(1, self.block.marshal(), always=True)
            .message(2, self.evidence.marshal(), always=True)
            .message(3, self.validator.marshal(), always=True)
            .message(4, self.version.marshal(), always=True)
            .out()
        )

    @staticmethod
    def unmarshal(buf: bytes) -> "ConsensusParams":
        f = proto.fields(buf)
        bf = proto.fields(f.get(1, [b""])[-1])
        block = BlockParams(
            max_bytes=proto.as_sint64(bf.get(1, [0])[-1]),
            max_gas=proto.as_sint64(bf.get(2, [0])[-1]),
            time_iota_ms=proto.as_sint64(bf.get(3, [0])[-1]),
        )
        ef = proto.fields(f.get(2, [b""])[-1])
        durf = proto.fields(ef.get(2, [b""])[-1])
        evidence = EvidenceParams(
            max_age_num_blocks=proto.as_sint64(ef.get(1, [0])[-1]),
            max_age_duration_ns=proto.as_sint64(durf.get(1, [0])[-1]) * 1_000_000_000
            + proto.as_sint64(durf.get(2, [0])[-1]),
            max_bytes=proto.as_sint64(ef.get(3, [0])[-1]),
        )
        vf = proto.fields(f.get(3, [b""])[-1])
        validator = ValidatorParams(
            pub_key_types=tuple(b.decode() for b in vf.get(1, []))
        )
        verf = proto.fields(f.get(4, [b""])[-1])
        version = VersionParams(app_version=verf.get(1, [0])[-1])
        return ConsensusParams(block, evidence, validator, version)


DEFAULT_CONSENSUS_PARAMS = ConsensusParams()
