"""SignedHeader and LightBlock (reference: types/light_block.go,
proto/tendermint/types/types.proto SignedHeader/LightBlock)."""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.encoding import proto
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.validator_set import ValidatorSet


@dataclass
class SignedHeader:
    header: Header | None = None
    commit: Commit | None = None

    def validate_basic(self, chain_id: str) -> None:
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header belongs to another chain {self.header.chain_id!r}, not {chain_id!r}"
            )
        if self.commit.height != self.header.height:
            raise ValueError(
                f"header and commit height mismatch: {self.header.height} vs {self.commit.height}"
            )
        hhash = self.header.hash()
        if self.commit.block_id.hash != hhash:
            raise ValueError(
                f"commit signs block {self.commit.block_id.hash.hex()}, header is block {hhash.hex()}"
            )

    @property
    def height(self) -> int:
        return self.header.height if self.header else 0

    def hash(self) -> bytes | None:
        return self.header.hash() if self.header else None

    def marshal(self) -> bytes:
        w = proto.Writer()
        if self.header is not None:
            w.message(1, self.header.marshal(), always=True)
        if self.commit is not None:
            w.message(2, self.commit.marshal(), always=True)
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "SignedHeader":
        f = proto.fields(buf)
        return SignedHeader(
            header=Header.unmarshal(f[1][-1]) if 1 in f else None,
            commit=Commit.unmarshal(f[2][-1]) if 2 in f else None,
        )


@dataclass
class LightBlock:
    signed_header: SignedHeader | None = None
    validator_set: ValidatorSet | None = None

    def validate_basic(self, chain_id: str) -> None:
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None:
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        vh = self.validator_set.hash()
        if self.signed_header.header.validators_hash != vh:
            raise ValueError(
                f"expected validators hash of light block to match validator set hash "
                f"({self.signed_header.header.validators_hash.hex()} != {vh.hex()})"
            )

    @property
    def height(self) -> int:
        return self.signed_header.height if self.signed_header else 0

    def hash(self) -> bytes | None:
        return self.signed_header.hash() if self.signed_header else None

    def marshal(self) -> bytes:
        w = proto.Writer()
        if self.signed_header is not None:
            w.message(1, self.signed_header.marshal(), always=True)
        if self.validator_set is not None:
            w.message(2, self.validator_set.marshal(), always=True)
        return w.out()

    @staticmethod
    def unmarshal(buf: bytes) -> "LightBlock":
        f = proto.fields(buf)
        return LightBlock(
            signed_header=SignedHeader.unmarshal(f[1][-1]) if 1 in f else None,
            validator_set=ValidatorSet.unmarshal(f[2][-1]) if 2 in f else None,
        )
