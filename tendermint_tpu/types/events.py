"""Event types + EventBus (reference: types/events.go, types/event_bus.go:33,
libs/pubsub).

The pubsub query language supports the subset the reference's RPC subscribe
uses: "tm.event='NewBlock'" style equality conditions joined by AND
(reference: libs/pubsub/query/query.go).
"""

from __future__ import annotations

import fnmatch
import re
import threading
from dataclasses import dataclass, field

# Event type strings (reference: types/events.go:20-60)
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_LOCK = "Lock"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_POLKA = "Polka"
EVENT_RELOCK = "Relock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_UNLOCK = "Unlock"
EVENT_VALID_BLOCK = "ValidBlock"
EVENT_VOTE = "Vote"

EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"


@dataclass
class EventDataNewBlock:
    block: object = None
    block_id: object = None
    result_begin_block: object = None
    result_end_block: object = None


@dataclass
class EventDataNewBlockHeader:
    header: object = None
    num_txs: int = 0
    result_begin_block: object = None
    result_end_block: object = None


@dataclass
class EventDataNewEvidence:
    evidence: object = None
    height: int = 0


@dataclass
class EventDataTx:
    height: int = 0
    tx: bytes = b""
    index: int = 0
    result: object = None


@dataclass
class EventDataRoundState:
    height: int = 0
    round: int = 0
    step: str = ""


@dataclass
class EventDataNewRound:
    height: int = 0
    round: int = 0
    step: str = ""
    proposer_address: bytes = b""
    proposer_index: int = -1


@dataclass
class EventDataCompleteProposal:
    height: int = 0
    round: int = 0
    step: str = ""
    block_id: object = None


@dataclass
class EventDataVote:
    vote: object = None


@dataclass
class EventDataValidatorSetUpdates:
    validator_updates: list = field(default_factory=list)


@dataclass
class EventDataString:
    value: str = ""


# value operand: a quoted string or a single bare token (number, hex hash,
# glob pattern) — anything else is a parse error, as in the reference parser.
# Comparison operands may carry the reference grammar's TIME/DATE keyword
# (libs/pubsub/query/query.go DateLayout/TimeLayout).
_VAL = r"'[^']*'|\"[^\"]*\"|[\w.+\-:*?\[\]]+"
_COND_RE = re.compile(
    r"^(?P<key>[\w.\-/]+)\s*"
    rf"(?:(?P<op><=|>=|=|<|>)\s*(?:(?P<tkind>TIME|DATE)\s+)?(?P<val>{_VAL})"
    rf"|\s(?P<word>CONTAINS)\s+(?P<cval>{_VAL})"
    r"|\s(?P<exists>EXISTS))$"
)


# In-band tag for temporal condition operands. \x00 cannot appear in a
# parsed value token and never legitimately starts a quoted operand, so a
# user string like 'TIME up' can never be mistaken for a temporal operand.
_TEMPORAL_TAG = "\x00"


def _parse_operand_time(v: str):
    """RFC3339 (`TIME ...`) or 2006-01-02 (`DATE ...`) -> aware datetime,
    None when unparseable (the reference errors the match out; we treat it
    as no-match). RFC3339 requires a UTC offset: zone-less values return
    None rather than a naive datetime (which would make later comparisons
    raise instead of not matching)."""
    import datetime as _dt

    try:
        if "T" in v:
            t = _dt.datetime.fromisoformat(v.replace("Z", "+00:00"))
            return t if t.tzinfo is not None else None
        d = _dt.date.fromisoformat(v)
        return _dt.datetime(d.year, d.month, d.day, tzinfo=_dt.timezone.utc)
    except ValueError:
        return None


def _split_and(expr: str) -> list[str]:
    """Split on AND outside quotes (a quoted value may contain ' AND ')."""
    parts, buf, quote = [], [], ""
    i = 0
    while i < len(expr):
        c = expr[i]
        if quote:
            if c == quote:
                quote = ""
            buf.append(c)
        elif c in "'\"":
            quote = c
            buf.append(c)
        elif expr.startswith(" AND ", i):
            parts.append("".join(buf))
            buf = []
            i += 4
        else:
            buf.append(c)
        i += 1
    parts.append("".join(buf))
    return parts


class Query:
    """Pubsub query: AND of conditions over event attributes with the
    reference grammar's operators =, <, <=, >, >=, CONTAINS, EXISTS
    (reference: libs/pubsub/query/query.go). Comparison operators apply
    numerically (heights, amounts); `=` additionally supports glob
    patterns on string values (a superset of the reference's exact match).

    conditions: list of (key, op, value) with op in
    {"=", "<", "<=", ">", ">=", "contains", "exists"}; value is None for
    exists."""

    def __init__(self, expr: str):
        self.expr = expr.strip()
        self.conditions: list[tuple[str, str, str | None]] = []
        if self.expr:
            for part in _split_and(self.expr):
                m = _COND_RE.match(part.strip())
                if not m:
                    raise ValueError(f"bad query condition: {part!r}")
                key = m.group("key")
                if m.group("exists"):
                    self.conditions.append((key, "exists", None))
                elif m.group("word"):
                    self.conditions.append(
                        (key, "contains", m.group("cval").strip().strip("'\"")))
                else:
                    val = m.group("val").strip().strip("'\"")
                    if m.group("tkind"):
                        # tag the operand ("\x00TIME <rfc3339>" /
                        # "\x00DATE <date>") — conditions stay 3-tuples for
                        # every consumer, and _cmp dispatches on the tag
                        if _parse_operand_time(val) is None:
                            raise ValueError(f"bad {m.group('tkind')} "
                                             f"operand: {part!r}")
                        val = f"{_TEMPORAL_TAG}{m.group('tkind')} {val}"
                    self.conditions.append((key, m.group("op"), val))

    @staticmethod
    def _cmp(op: str, x: str, v: str) -> bool:
        if v.startswith(_TEMPORAL_TAG):
            # temporal comparison (reference query.go matchValue time case):
            # the event value parses as RFC3339 when it contains 'T', else
            # as a plain date; unparseable values never match
            operand = _parse_operand_time(v.split(" ", 1)[1])
            xt = _parse_operand_time(x)
            if operand is None or xt is None:
                return False
            return {"=": xt == operand, "<": xt < operand,
                    "<=": xt <= operand, ">": xt > operand,
                    ">=": xt >= operand}[op]
        if op == "=":
            return x == v or fnmatch.fnmatchcase(x, v)
        if op == "contains":
            return v in x
        try:
            xn, vn = float(x), float(v)
        except ValueError:
            return False  # comparison operators are numeric otherwise
        return {"<": xn < vn, "<=": xn <= vn,
                ">": xn > vn, ">=": xn >= vn}[op]

    def matches(self, events: dict[str, list[str]]) -> bool:
        for k, op, v in self.conditions:
            vals = events.get(k)
            if vals is None:
                return False
            if op == "exists":
                continue
            if not any(self._cmp(op, x, v) for x in vals):
                return False
        return True

    def __str__(self) -> str:
        return self.expr

    def __eq__(self, other):
        return isinstance(other, Query) and self.expr == other.expr

    def __hash__(self):
        return hash(self.expr)


class Subscription:
    def __init__(self, query: Query, out_capacity: int = 100):
        import collections

        self.query = query
        self.queue: collections.deque = collections.deque(maxlen=out_capacity if out_capacity else None)
        self.event = threading.Event()
        self.cancelled = False
        self.cancel_reason = ""

    def publish(self, msg) -> None:
        self.queue.append(msg)
        self.event.set()

    def next(self, timeout: float | None = None):
        while True:
            if self.queue:
                msg = self.queue.popleft()
                if not self.queue:
                    self.event.clear()
                return msg
            if self.cancelled:
                raise SubscriptionCancelled(self.cancel_reason)
            if not self.event.wait(timeout):
                return None


class SubscriptionCancelled(Exception):
    pass


@dataclass
class PubSubMessage:
    data: object
    events: dict[str, list[str]]


class EventBus:
    """Typed wrapper over a pubsub server (reference: types/event_bus.go)."""

    def __init__(self) -> None:
        self._subs: dict[tuple[str, str], Subscription] = {}
        self._mtx = threading.RLock()

    def subscribe(self, subscriber: str, query: Query | str,
                  out_capacity: int = 100) -> Subscription:
        if isinstance(query, str):
            query = Query(query)
        with self._mtx:
            key = (subscriber, str(query))
            if key in self._subs:
                raise ValueError("already subscribed")
            sub = Subscription(query, out_capacity)
            self._subs[key] = sub
            return sub

    def unsubscribe(self, subscriber: str, query: Query | str) -> None:
        with self._mtx:
            sub = self._subs.pop((subscriber, str(query)), None)
            if sub is None:
                raise ValueError("subscription not found")
            sub.cancelled = True
            sub.event.set()

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            for key in [k for k in self._subs if k[0] == subscriber]:
                sub = self._subs.pop(key)
                sub.cancelled = True
                sub.event.set()

    def num_clients(self) -> int:
        with self._mtx:
            return len({k[0] for k in self._subs})

    def publish(self, event_type: str, data, extra_events: dict[str, list[str]] | None = None) -> None:
        events = {EVENT_TYPE_KEY: [event_type]}
        if extra_events:
            for k, v in extra_events.items():
                events.setdefault(k, []).extend(v)
        msg = PubSubMessage(data=data, events=events)
        with self._mtx:
            subs = list(self._subs.values())
        for sub in subs:
            if sub.query.matches(events):
                sub.publish(msg)

    # --- typed publishers (reference: types/event_bus.go:80-300) -----------

    def publish_event_new_block(self, data: EventDataNewBlock) -> None:
        extra = _abci_events(data.result_begin_block, data.result_end_block)
        self.publish(EVENT_NEW_BLOCK, data, extra)

    def publish_event_new_block_header(self, data: EventDataNewBlockHeader) -> None:
        extra = _abci_events(data.result_begin_block, data.result_end_block)
        self.publish(EVENT_NEW_BLOCK_HEADER, data, extra)

    def publish_event_new_evidence(self, data: EventDataNewEvidence) -> None:
        self.publish(EVENT_NEW_EVIDENCE, data)

    def publish_event_tx(self, data: EventDataTx) -> None:
        from tendermint_tpu.types.tx import tx_hash

        extra: dict[str, list[str]] = {
            TX_HASH_KEY: [tx_hash(data.tx).hex().upper()],
            TX_HEIGHT_KEY: [str(data.height)],
        }
        if data.result is not None:
            for ev in getattr(data.result, "events", []):
                for attr in ev.attributes:
                    if attr.index:
                        key = f"{ev.type}.{attr.key.decode(errors='replace')}"
                        extra.setdefault(key, []).append(attr.value.decode(errors="replace"))
        self.publish(EVENT_TX, data, extra)

    def publish_event_vote(self, data: EventDataVote) -> None:
        self.publish(EVENT_VOTE, data)

    def publish_event_valid_block(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_VALID_BLOCK, data)

    def publish_event_new_round_step(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_NEW_ROUND_STEP, data)

    def publish_event_timeout_propose(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_TIMEOUT_PROPOSE, data)

    def publish_event_timeout_wait(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_TIMEOUT_WAIT, data)

    def publish_event_new_round(self, data: EventDataNewRound) -> None:
        self.publish(EVENT_NEW_ROUND, data)

    def publish_event_complete_proposal(self, data: EventDataCompleteProposal) -> None:
        self.publish(EVENT_COMPLETE_PROPOSAL, data)

    def publish_event_polka(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_POLKA, data)

    def publish_event_unlock(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_UNLOCK, data)

    def publish_event_relock(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_RELOCK, data)

    def publish_event_lock(self, data: EventDataRoundState) -> None:
        self.publish(EVENT_LOCK, data)

    def publish_event_validator_set_updates(self, data: EventDataValidatorSetUpdates) -> None:
        self.publish(EVENT_VALIDATOR_SET_UPDATES, data)


def _abci_events(begin_block, end_block) -> dict[str, list[str]]:
    extra: dict[str, list[str]] = {}
    for res in (begin_block, end_block):
        if res is None:
            continue
        for ev in getattr(res, "events", []):
            for attr in ev.attributes:
                if attr.index:
                    key = f"{ev.type}.{attr.key.decode(errors='replace')}"
                    extra.setdefault(key, []).append(attr.value.decode(errors="replace"))
    return extra
