"""VoteSet: vote accumulation with 2/3-majority tracking (reference:
types/vote_set.go:78,145-290).

Two verification modes:

* add_vote(vote): the reference's semantics -- one signature verify per call
  (types/vote_set.go:205 -> vote.Verify).
* add_votes(votes): the deferred batched mode the reference lacks (SURVEY.md
  section 7.3): all signatures are verified in ONE BatchVerifier flush (one
  TPU kernel launch), then each vote's side effects (conflict detection,
  maj23 bookkeeping, evidence-triggering errors) are applied in arrival
  order, preserving per-vote error attribution exactly as if add_vote had
  been called serially.
"""

from __future__ import annotations

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.block import Commit, make_commit
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.utils.bits import BitArray
from tendermint_tpu.types.vote import (
    ErrVoteConflictingVotes,
    ErrVoteInvalidSignature,
    Vote,
    VoteError,
    is_vote_type_valid,
)


class VoteSetError(Exception):
    pass


class _BlockVotes:
    """Votes for one BlockID (reference: types/vote_set.go:560-590)."""

    __slots__ = ("peer_maj23", "votes", "sum")

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.votes: list[Vote | None] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.votes[idx] = vote
            self.sum += voting_power

    def get_by_index(self, idx: int) -> Vote | None:
        return self.votes[idx]


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int, signed_msg_type: int,
                 val_set: ValidatorSet):
        if height == 0:
            raise VoteSetError("cannot make VoteSet for height == 0, doesn't make sense")
        if not is_vote_type_valid(signed_msg_type):
            raise VoteSetError(f"invalid vote type {signed_msg_type}")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self.votes_bit_array = BitArray(val_set.size())
        self.votes: list[Vote | None] = [None] * val_set.size()
        self.sum = 0
        self.maj23: BlockID | None = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}

    # --- adding votes ------------------------------------------------------

    def add_vote(self, vote: Vote | None, verified: bool = False) -> bool:
        """Returns True if added (False: duplicate). Raises on invalid
        (reference: types/vote_set.go:145-230).

        verified=True skips the signature check: the caller already verified
        this exact (val_set[index].pub_key, sign_bytes, signature) triple
        through a BatchVerifier flush (the deferred batched mode)."""
        if vote is None:
            raise VoteSetError("nil vote")
        checked = self._precheck(vote)
        if checked is None:
            return False  # exact duplicate
        val = checked
        if not verified and not val.pub_key.verify_signature(
            vote.sign_bytes(self.chain_id), vote.signature
        ):
            raise ErrVoteInvalidSignature(
                f"failed to verify vote with ChainID {self.chain_id} and "
                f"PubKey {val.pub_key.bytes().hex()}: invalid signature"
            )
        added, conflicting = self._apply_verified(vote, val)
        if conflicting is not None:
            err = ErrVoteConflictingVotes(conflicting, vote)
            err.added = added
            raise err
        if not added:
            raise AssertionError("expected to add non-conflicting vote")
        return added

    def add_votes(self, votes: list[Vote]) -> list[tuple[bool, Exception | None]]:
        """Deferred batched mode: one kernel flush for all signatures, then
        in-order application. Result list is parallel to `votes`."""
        from tendermint_tpu.crypto import sigcache

        prechecked: list[tuple[Vote, object] | None] = []
        results: list[tuple[bool, Exception | None]] = [None] * len(votes)  # type: ignore
        dc = sigcache.DrainCache()
        verifier = crypto_batch.create_batch_verifier()
        queued: list[int] = []
        # Gossiped votes at one (height, round, step, block) share identical
        # sign bytes; build each distinct canonical encoding once.
        sb_memo: dict[tuple, bytes] = {}
        for i, vote in enumerate(votes):
            try:
                checked = self._precheck(vote)
            except Exception as e:  # noqa: BLE001 - mirrored per-vote error
                results[i] = (False, e)
                prechecked.append(None)
                continue
            if checked is None:
                results[i] = (False, None)  # duplicate
                prechecked.append(None)
                continue
            prechecked.append((vote, checked))
            sb_key = (vote.height, vote.round, vote.type,
                      vote.block_id.key(), vote.timestamp)
            sb = sb_memo.get(sb_key)
            if sb is None:
                sb = sb_memo[sb_key] = vote.sign_bytes(self.chain_id)
            # A triple already verified in an earlier drain (gossip
            # re-delivery, another round's batch) skips the kernel and goes
            # straight to the accept-replay below.
            if dc.check(i, checked.pub_key.bytes(), sb, vote.signature):
                continue
            verifier.add(checked.pub_key, sb, vote.signature)
            queued.append(i)
        if queued or dc.cached_ok:
            try:
                bitmap = verifier.verify()[1] if queued else []
            except BaseException:
                dc.commit([], [])  # flush metrics deltas; nothing cached
                raise
            ok_by_i = dc.commit(queued, bitmap)
            # queued and the cache hits are each ascending; the merged
            # sorted order is exactly the serial arrival order.
            for i in sorted(ok_by_i):
                vote, val = prechecked[i]  # type: ignore[misc]
                if not ok_by_i[i]:
                    results[i] = (False, ErrVoteInvalidSignature(
                        f"failed to verify vote with ChainID {self.chain_id} and "
                        f"PubKey {val.pub_key.bytes().hex()}: invalid signature"
                    ))
                    continue
                try:
                    # Re-run ONLY the duplicate/conflict check (the rest of
                    # _precheck is state-independent and already passed): an
                    # earlier vote in this same batch may have made this one
                    # a duplicate or a non-deterministic-signature error.
                    existing = self._get_vote(vote.validator_index,
                                              vote.block_id.key())
                    if existing is not None:
                        if existing.signature == vote.signature:
                            results[i] = (False, None)
                        else:
                            results[i] = (False, VoteError(
                                f"existing vote: {existing}; new vote: {vote}: "
                                "non-deterministic signature"))
                        continue
                    added, conflicting = self._apply_verified(vote, val)
                    if conflicting is not None:
                        err = ErrVoteConflictingVotes(conflicting, vote)
                        err.added = added
                        results[i] = (added, err)
                    else:
                        results[i] = (added, None)
                except Exception as e:  # noqa: BLE001
                    results[i] = (False, e)
        return results

    def _precheck(self, vote: Vote):
        """Everything add_vote does before the signature check. Returns the
        validator, or None for an exact duplicate."""
        val_index = vote.validator_index
        val_addr = vote.validator_address
        if not vote.block_id.is_zero():
            vote.block_id.validate_basic()
        if val_index < 0:
            raise VoteSetError("index < 0: invalid validator index")
        if not val_addr:
            raise VoteSetError("empty address: invalid validator address")
        if (vote.height != self.height or vote.round != self.round
                or vote.type != self.signed_msg_type):
            raise VoteSetError(
                f"expected {self.height}/{self.round}/{self.signed_msg_type}, "
                f"but got {vote.height}/{vote.round}/{vote.type}: unexpected step"
            )
        addr, val = self.val_set.get_by_index(val_index)
        if val is None:
            raise VoteSetError(
                f"cannot find validator {val_index} in valSet of size {self.val_set.size()}: "
                "invalid validator index"
            )
        if addr != val_addr:
            raise VoteSetError(
                f"vote.ValidatorAddress ({val_addr.hex()}) does not match address "
                f"({addr.hex()}) for vote.ValidatorIndex ({val_index})"
            )
        existing = self._get_vote(val_index, vote.block_id.key())
        if existing is not None:
            if existing.signature == vote.signature:
                return None  # duplicate
            raise VoteError(
                f"existing vote: {existing}; new vote: {vote}: non-deterministic signature"
            )
        return val

    def _get_vote(self, val_index: int, block_key: bytes) -> Vote | None:
        """reference: types/vote_set.go getVote -- checks the main slot AND
        the per-block tracker (conflicting votes live only in the latter)."""
        existing = self.votes[val_index]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(val_index)
        return None

    def _apply_verified(self, vote: Vote, val) -> tuple[bool, Vote | None]:
        """addVerifiedVote (reference: types/vote_set.go:234-300): conflict
        handling + maj23 bookkeeping. Returns (added, conflicting)."""
        val_index = vote.validator_index
        voting_power = val.voting_power
        block_key = vote.block_id.key()

        existing = self.votes[val_index]
        conflicting: Vote | None = None
        if existing is not None:
            if existing.block_id == vote.block_id:
                raise AssertionError("addVerifiedVote does not expect duplicate votes")
            conflicting = existing
            # Replace the main-slot vote only if this block already has maj23.
            if self.maj23 is not None and self.maj23.key() == block_key:
                self.votes[val_index] = vote
                self.votes_bit_array[val_index] = True
        else:
            self.votes[val_index] = vote
            self.votes_bit_array[val_index] = True
            self.sum += voting_power

        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            if conflicting is not None and not bv.peer_maj23:
                # Conflict and no peer claims this block is special.
                return False, conflicting
        else:
            if conflicting is not None:
                # Not even tracking this block: forget it.
                return False, conflicting
            bv = _BlockVotes(peer_maj23=False, num_validators=self.val_set.size())
            self.votes_by_block[block_key] = bv

        before = bv.sum
        bv.add_verified_vote(vote, voting_power)
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        if before < quorum <= bv.sum and self.maj23 is None:
            self.maj23 = vote.block_id
            # Promote this block's votes into the main tally.
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self.votes[i] = v
        return True, conflicting

    # --- queries (reference: types/vote_set.go:300-520) --------------------

    def get_by_index(self, idx: int) -> Vote | None:
        if idx < 0 or idx >= len(self.votes):
            return None
        return self.votes[idx]

    def get_by_address(self, address: bytes) -> Vote | None:
        idx, _ = self.val_set.get_by_address(address)
        return self.get_by_index(idx) if idx >= 0 else None

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """reference: types/vote_set.go:300-340."""
        block_key = block_id.key()
        existing = self.peer_maj23s.get(peer_id)
        if existing is not None:
            if existing == block_id:
                return
            raise VoteSetError(
                f"setPeerMaj23: Received conflicting blockID from peer {peer_id}: "
                f"{existing} vs {block_id}"
            )
        self.peer_maj23s[peer_id] = block_id
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            bv.peer_maj23 = True
        else:
            self.votes_by_block[block_key] = _BlockVotes(
                peer_maj23=True, num_validators=self.val_set.size()
            )

    def bit_array(self) -> BitArray:
        return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        bv = self.votes_by_block.get(block_id.key())
        if bv is None:
            return None
        return BitArray.from_bools([v is not None for v in bv.votes])

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def two_thirds_majority(self) -> tuple[BlockID | None, bool]:
        if self.maj23 is not None:
            return self.maj23, True
        return None, False

    def has_two_thirds_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_one_third_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() // 3

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def size(self) -> int:
        return self.val_set.size()

    def make_commit(self) -> Commit:
        """reference: types/vote_set.go:590-620."""
        if self.signed_msg_type != 2:
            raise VoteSetError("cannot MakeCommit() unless VoteSet.Type is PrecommitType")
        if self.maj23 is None:
            raise VoteSetError("cannot MakeCommit() unless a blockhash has +2/3")
        return make_commit(self.maj23, self.height, self.round, self.votes)

    def __str__(self) -> str:
        n_present = sum(1 for v in self.votes if v is not None)
        return (
            f"VoteSet{{H:{self.height} R:{self.round} T:{self.signed_msg_type} "
            f"{n_present}/{self.size()} sum={self.sum} maj23={self.maj23}}}"
        )
